"""Packaging for dask_sql_tpu (reference: /root/reference/setup.py console
scripts at :106-111; no jar build step — the planner is native Python/C++)."""
import os

from setuptools import Extension, find_packages, setup

ext_modules = []
# the native lexer builds opportunistically; pure-python fallback otherwise
if os.environ.get("DASK_SQL_TPU_BUILD_NATIVE", "1") == "1":
    ext_modules.append(
        Extension(
            "dask_sql_tpu.native._lexer",
            sources=["native/lexer.cpp"],
            extra_compile_args=["-O2", "-std=c++17"],
            optional=True,
        )
    )

setup(
    name="dask_sql_tpu",
    version="0.1.0",
    description="TPU-native distributed SQL query engine (dask-sql capability parity)",
    packages=find_packages(include=["dask_sql_tpu", "dask_sql_tpu.*"]),
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "numpy",
        "pandas",
    ],
    extras_require={
        "dev": ["pytest"],
        "ml": ["scikit-learn", "joblib"],
        "cli": ["prompt_toolkit", "pygments"],
    },
    entry_points={
        "console_scripts": [
            "dask-sql-tpu = dask_sql_tpu.cmd:main",
            "dask-sql-tpu-server = dask_sql_tpu.server.app:main",
        ]
    },
    ext_modules=ext_modules,
)
