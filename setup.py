"""Packaging for dask_sql_tpu (reference: /root/reference/setup.py console
scripts at :106-111; no jar build step — the planner is native Python/C++)."""
from setuptools import find_packages, setup
from setuptools.dist import Distribution


class _BinaryDistribution(Distribution):
    """The prebuilt native parser makes this a platform wheel."""

    def has_ext_modules(self):
        return True


setup(
    name="dask_sql_tpu",
    version="0.1.0",
    description="TPU-native distributed SQL query engine (dask-sql capability parity)",
    packages=find_packages(include=["dask_sql_tpu", "dask_sql_tpu.*"]),
    package_data={"dask_sql_tpu.native": ["*.so"]},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "numpy",
        "pandas",
    ],
    extras_require={
        "dev": ["pytest"],
        "ml": ["scikit-learn", "joblib"],
        "cli": ["prompt_toolkit", "pygments"],
    },
    entry_points={
        "console_scripts": [
            "dask-sql-tpu = dask_sql_tpu.cmd:main",
            "dask-sql-tpu-server = dask_sql_tpu.server.app:main",
        ]
    },
    distclass=_BinaryDistribution,
)
