#include "json.h"

#include <cinttypes>
#include <cmath>
#include <cstdio>
#include <cstring>

#include "parser.h"  // json_quote

namespace dsql {

namespace {

struct P {
  const char* p;
  const char* end;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  [[noreturn]] void fail(const std::string& m) {
    throw JsonError("json: " + m);
  }
  char peek() {
    if (p >= end) fail("unexpected end");
    return *p;
  }
  void expect(char c) {
    if (p >= end || *p != c) fail(std::string("expected '") + c + "'");
    ++p;
  }
  bool lit(const char* s) {
    size_t n = std::strlen(s);
    if ((size_t)(end - p) >= n && std::memcmp(p, s, n) == 0) {
      p += n;
      return true;
    }
    return false;
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (true) {
      if (p >= end) fail("unterminated string");
      char c = *p++;
      if (c == '"') break;
      if (c == '\\') {
        if (p >= end) fail("bad escape");
        char e = *p++;
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (end - p < 4) fail("bad \\u escape");
            unsigned v = 0;
            for (int k = 0; k < 4; ++k) {
              char h = *p++;
              v <<= 4;
              if (h >= '0' && h <= '9') v |= h - '0';
              else if (h >= 'a' && h <= 'f') v |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') v |= h - 'A' + 10;
              else fail("bad hex digit");
            }
            // encode code point (surrogate pairs for the BMP-external
            // range the Python bridge never emits; kept for completeness)
            unsigned cp = v;
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 &&
                p[0] == '\\' && p[1] == 'u') {
              unsigned lo = 0;
              const char* q = p + 2;
              bool ok = true;
              for (int k = 0; k < 4; ++k) {
                char h = q[k];
                lo <<= 4;
                if (h >= '0' && h <= '9') lo |= h - '0';
                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                else { ok = false; break; }
              }
              if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                p += 6;
              }
            }
            if (cp < 0x80) {
              out += (char)cp;
            } else if (cp < 0x800) {
              out += (char)(0xC0 | (cp >> 6));
              out += (char)(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
              out += (char)(0xE0 | (cp >> 12));
              out += (char)(0x80 | ((cp >> 6) & 0x3F));
              out += (char)(0x80 | (cp & 0x3F));
            } else {
              out += (char)(0xF0 | (cp >> 18));
              out += (char)(0x80 | ((cp >> 12) & 0x3F));
              out += (char)(0x80 | ((cp >> 6) & 0x3F));
              out += (char)(0x80 | (cp & 0x3F));
            }
            break;
          }
          default:
            fail("bad escape char");
        }
      } else {
        out += c;
      }
    }
    return out;
  }

  JVP parse_number() {
    const char* start = p;
    if (peek() == '-') ++p;
    while (p < end && *p >= '0' && *p <= '9') ++p;
    bool integral = true;
    if (p < end && *p == '.') {
      integral = false;
      ++p;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      integral = false;
      ++p;
      if (p < end && (*p == '+' || *p == '-')) ++p;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    std::string tok(start, p - start);
    if (integral) {
      errno = 0;
      char* endp = nullptr;
      long long v = std::strtoll(tok.c_str(), &endp, 10);
      if (errno == 0 && endp && *endp == '\0') return JV::integer(v);
      // out of int64 range: the Python bridge refuses such plans before
      // serializing, so this is parse-of-foreign-input safety only
      return JV::dbl(std::strtod(tok.c_str(), nullptr));
    }
    return JV::dbl(std::strtod(tok.c_str(), nullptr));
  }

  JVP value() {
    ws();
    char c = peek();
    if (c == '{') {
      ++p;
      auto o = JV::object();
      ws();
      if (peek() == '}') { ++p; return o; }
      while (true) {
        ws();
        std::string k = parse_string();
        ws();
        expect(':');
        o->set(k, value());
        ws();
        if (peek() == ',') { ++p; continue; }
        expect('}');
        return o;
      }
    }
    if (c == '[') {
      ++p;
      auto a = JV::array();
      ws();
      if (peek() == ']') { ++p; return a; }
      while (true) {
        a->push(value());
        ws();
        if (peek() == ',') { ++p; continue; }
        expect(']');
        return a;
      }
    }
    if (c == '"') return JV::str(parse_string());
    if (lit("null")) return JV::null();
    if (lit("true")) return JV::boolean(true);
    if (lit("false")) return JV::boolean(false);
    if (c == '-' || (c >= '0' && c <= '9')) return parse_number();
    fail("unexpected character");
  }
};

void emit(const JVP& v, std::string& out) {
  if (!v) { out += "null"; return; }
  switch (v->kind) {
    case JV::NUL: out += "null"; break;
    case JV::BOOL: out += v->b ? "true" : "false"; break;
    case JV::INT: {
      char buf[32];
      std::snprintf(buf, sizeof buf, "%" PRId64, v->i);
      out += buf;
      break;
    }
    case JV::DBL: {
      if (std::isnan(v->d)) { out += "\"__nan__\""; break; }
      if (std::isinf(v->d)) {
        out += v->d > 0 ? "\"__inf__\"" : "\"__-inf__\"";
        break;
      }
      char buf[40];
      std::snprintf(buf, sizeof buf, "%.17g", v->d);
      // ensure a float stays a float on re-parse
      if (!std::strpbrk(buf, ".eE")) std::strcat(buf, ".0");
      out += buf;
      break;
    }
    case JV::STR: out += json_quote(v->s); break;
    case JV::ARR: {
      out += '[';
      for (size_t k = 0; k < v->arr.size(); ++k) {
        if (k) out += ',';
        emit(v->arr[k], out);
      }
      out += ']';
      break;
    }
    case JV::OBJ: {
      out += '{';
      for (size_t k = 0; k < v->obj.size(); ++k) {
        if (k) out += ',';
        out += json_quote(v->obj[k].first);
        out += ':';
        emit(v->obj[k].second, out);
      }
      out += '}';
      break;
    }
  }
}

}  // namespace

JVP json_parse(const std::string& text) {
  P parser{text.c_str(), text.c_str() + text.size()};
  JVP v = parser.value();
  parser.ws();
  if (parser.p != parser.end) throw JsonError("json: trailing data");
  return v;
}

std::string json_emit(const JVP& v) {
  std::string out;
  emit(v, out);
  return out;
}

}  // namespace dsql
