// Native SQL parser: tokens -> AST serialized as JSON.
//
// C++ counterpart of dask_sql_tpu/sql/parser.py, mirroring the reference's
// native planner front-end (Java/Calcite + the custom statement grammar in
// planner/src/main/codegen/includes/{create,model,show,utils}.ftl).  The JSON
// shape is one object per AST node: {"t": "<ClassName>", <field>: <value>...}
// with field names identical to the dataclasses in dask_sql_tpu/sql/ast.py,
// so the Python bridge reconstructs the exact same AST the Python parser
// produces.
#pragma once

#include <string>

namespace dsql {

struct ParseError {
  std::string msg;  // already includes the "(got ...)" suffix
  int line, col, width;
};

// Parse one-or-more ;-separated statements; returns a JSON array of
// statement nodes. Throws ParseError or LexError.
std::string parse_statements_json(const std::string& sql);

// JSON-escape a string, including the surrounding quotes.
std::string json_quote(const std::string& s);

}  // namespace dsql
