#include "plan.h"

#include <cmath>

namespace dsql {

// ---------------------------------------------------------------------------
// Rex constructors
// ---------------------------------------------------------------------------

RexP Rex::input_ref(int64_t idx, const SqlType& t) {
  auto r = std::make_shared<Rex>();
  r->kind = INPUT;
  r->index = idx;
  r->stype = t;
  return r;
}

RexP Rex::literal_bool(bool v, const SqlType& t) {
  auto r = std::make_shared<Rex>();
  r->kind = LIT;
  r->lkind = L_BOOL;
  r->bval = v;
  r->stype = t;
  return r;
}

RexP Rex::literal_int(int64_t v, const SqlType& t) {
  auto r = std::make_shared<Rex>();
  r->kind = LIT;
  r->lkind = L_INT;
  r->ival = v;
  r->stype = t;
  return r;
}

RexP Rex::call(const std::string& op, std::vector<RexP> ops,
               const SqlType& t) {
  auto r = std::make_shared<Rex>();
  r->kind = CALL;
  r->op = op;
  r->operands = std::move(ops);
  r->stype = t;
  return r;
}

RexP Rex::call_info(const std::string& op, std::vector<RexP> ops,
                    const SqlType& t, const SqlType& info) {
  auto r = std::make_shared<Rex>();
  r->kind = CALL;
  r->op = op;
  r->operands = std::move(ops);
  r->stype = t;
  r->has_info = true;
  r->info = info;
  return r;
}

// structural equality mirroring Python dataclass == (stype and info
// participate; subquery rex compares by plan identity like Python's
// default object field equality would only succeed on the same object)
bool rex_equal(const RexP& a, const RexP& b) {
  if (a == b) return true;
  if (!a || !b) return false;
  if (a->kind != b->kind || !(a->stype == b->stype)) return false;
  switch (a->kind) {
    case Rex::INPUT:
      return a->index == b->index;
    case Rex::LIT:
      if (a->lkind != b->lkind) return false;
      switch (a->lkind) {
        case Rex::L_NULL: return true;
        case Rex::L_BOOL: return a->bval == b->bval;
        case Rex::L_INT: return a->ival == b->ival;
        case Rex::L_DBL: return a->dval == b->dval;
        case Rex::L_STR: return a->sval == b->sval;
      }
      return false;
    case Rex::CALL: {
      if (a->op != b->op || a->has_info != b->has_info) return false;
      if (a->has_info && !(a->info == b->info)) return false;
      if (a->operands.size() != b->operands.size()) return false;
      for (size_t i = 0; i < a->operands.size(); ++i)
        if (!rex_equal(a->operands[i], b->operands[i])) return false;
      return true;
    }
    case Rex::SUBQ:
      return a->plan == b->plan;
  }
  return false;
}

// ---------------------------------------------------------------------------
// Rel helpers
// ---------------------------------------------------------------------------

std::vector<RelP> Rel::inputs() const {
  switch (kind) {
    case SCAN:
    case VALUES:
      return {};
    case JOIN:
      return {left, right};
    case UNION:
    case INTERSECT:
    case EXCEPT:
      return set_inputs;
    default:
      return {input};
  }
}

RelP Rel::with_inputs(const std::vector<RelP>& ins) const {
  auto n = std::make_shared<Rel>(*this);
  switch (kind) {
    case SCAN:
    case VALUES:
      break;
    case JOIN:
      n->left = ins.at(0);
      n->right = ins.at(1);
      break;
    case UNION:
    case INTERSECT:
    case EXCEPT:
      n->set_inputs = ins;
      break;
    default:
      n->input = ins.at(0);
      break;
  }
  return n;
}

RelP make_project(RelP in, std::vector<RexP> exprs,
                  std::vector<Field> schema) {
  auto n = std::make_shared<Rel>();
  n->kind = Rel::PROJECT;
  n->input = std::move(in);
  n->exprs = std::move(exprs);
  n->schema = std::move(schema);
  return n;
}

RelP make_filter(RelP in, RexP cond, std::vector<Field> schema) {
  auto n = std::make_shared<Rel>();
  n->kind = Rel::FILTER;
  n->input = std::move(in);
  n->condition = std::move(cond);
  n->schema = std::move(schema);
  return n;
}

RelP make_join(RelP l, RelP r, const std::string& jt, RexP cond,
               std::vector<Field> schema, bool null_aware) {
  auto n = std::make_shared<Rel>();
  n->kind = Rel::JOIN;
  n->left = std::move(l);
  n->right = std::move(r);
  n->join_type = jt;
  n->condition = std::move(cond);
  n->schema = std::move(schema);
  n->null_aware = null_aware;
  return n;
}

RelP make_aggregate(RelP in, std::vector<int64_t> gk,
                    std::vector<AggCall> aggs, std::vector<Field> schema) {
  auto n = std::make_shared<Rel>();
  n->kind = Rel::AGG;
  n->input = std::move(in);
  n->group_keys = std::move(gk);
  n->aggs = std::move(aggs);
  n->schema = std::move(schema);
  return n;
}

// ---------------------------------------------------------------------------
// rex utilities
// ---------------------------------------------------------------------------

void rex_inputs(const RexP& r, std::vector<int64_t>& out) {
  if (!r) return;
  if (r->kind == Rex::INPUT) {
    out.push_back(r->index);
  } else if (r->kind == Rex::CALL) {
    for (const auto& o : r->operands) rex_inputs(o, out);
  }
}

std::vector<int64_t> rex_inputs(const RexP& r) {
  std::vector<int64_t> out;
  rex_inputs(r, out);
  return out;
}

RexP remap_rex(const RexP& r, const std::map<int64_t, int64_t>& mapping) {
  if (r->kind == Rex::INPUT) {
    auto it = mapping.find(r->index);
    if (it == mapping.end()) throw PlanError("remap: unmapped ordinal");
    return Rex::input_ref(it->second, r->stype);
  }
  if (r->kind == Rex::CALL) {
    std::vector<RexP> ops;
    ops.reserve(r->operands.size());
    for (const auto& o : r->operands) ops.push_back(remap_rex(o, mapping));
    auto n = std::make_shared<Rex>(*r);
    n->operands = std::move(ops);
    return n;
  }
  return r;
}

// ---------------------------------------------------------------------------
// wire conversion
// ---------------------------------------------------------------------------

SqlType type_from_json(const JVP& v) {
  if (!v || v->kind != JV::ARR || v->arr.size() != 4)
    throw PlanError("bad SqlType");
  SqlType t;
  t.name = v->arr[0]->as_str();
  if (!v->arr[1]->is_null()) {
    t.has_prec = true;
    t.prec = v->arr[1]->as_int();
  }
  if (!v->arr[2]->is_null()) {
    t.has_scale = true;
    t.scale = v->arr[2]->as_int();
  }
  t.nullable = v->arr[3]->as_bool();
  return t;
}

JVP type_to_json(const SqlType& t) {
  auto a = JV::array();
  a->push(JV::str(t.name));
  a->push(t.has_prec ? JV::integer(t.prec) : JV::null());
  a->push(t.has_scale ? JV::integer(t.scale) : JV::null());
  a->push(JV::boolean(t.nullable));
  return a;
}

static Field field_from_json(const JVP& v) {
  if (!v || v->kind != JV::ARR || v->arr.size() != 2)
    throw PlanError("bad Field");
  return Field{v->arr[0]->as_str(), type_from_json(v->arr[1])};
}

static JVP field_to_json(const Field& f) {
  auto a = JV::array();
  a->push(JV::str(f.name));
  a->push(type_to_json(f.stype));
  return a;
}

static std::vector<Field> schema_from_json(const JVP& v) {
  if (!v || v->kind != JV::ARR) throw PlanError("bad schema");
  std::vector<Field> out;
  out.reserve(v->arr.size());
  for (const auto& f : v->arr) out.push_back(field_from_json(f));
  return out;
}

static JVP schema_to_json(const std::vector<Field>& s) {
  auto a = JV::array();
  for (const auto& f : s) a->push(field_to_json(f));
  return a;
}

RexP rex_from_json(const JVP& v) {
  if (!v || v->kind != JV::ARR || v->arr.empty())
    throw PlanError("bad rex");
  const std::string& tag = v->arr[0]->as_str();
  auto r = std::make_shared<Rex>();
  if (tag == "in") {
    r->kind = Rex::INPUT;
    r->index = v->arr[1]->as_int();
    r->stype = type_from_json(v->arr[2]);
    return r;
  }
  if (tag == "lit") {
    r->kind = Rex::LIT;
    const std::string& lt = v->arr[1]->as_str();
    const JVP& val = v->arr[2];
    if (lt == "n") r->lkind = Rex::L_NULL;
    else if (lt == "b") { r->lkind = Rex::L_BOOL; r->bval = val->as_bool(); }
    else if (lt == "i") { r->lkind = Rex::L_INT; r->ival = val->as_int(); }
    else if (lt == "f") { r->lkind = Rex::L_DBL; r->dval = val->as_double(); }
    else if (lt == "s") { r->lkind = Rex::L_STR; r->sval = val->as_str(); }
    else throw PlanError("bad literal tag");
    r->stype = type_from_json(v->arr[3]);
    return r;
  }
  if (tag == "call") {
    r->kind = Rex::CALL;
    r->op = v->arr[1]->as_str();
    if (v->arr[2]->kind != JV::ARR) throw PlanError("bad call operands");
    for (const auto& o : v->arr[2]->arr) r->operands.push_back(rex_from_json(o));
    r->stype = type_from_json(v->arr[3]);
    if (!v->arr[4]->is_null()) {
      r->has_info = true;
      r->info = type_from_json(v->arr[4]);
    }
    return r;
  }
  if (tag == "subq") {
    r->kind = Rex::SUBQ;
    r->plan = rel_from_json(v->arr[1]);
    r->stype = type_from_json(v->arr[2]);
    return r;
  }
  throw PlanError("unknown rex tag: " + tag);
}

JVP rex_to_json(const RexP& r) {
  auto a = JV::array();
  switch (r->kind) {
    case Rex::INPUT:
      a->push(JV::str("in"));
      a->push(JV::integer(r->index));
      a->push(type_to_json(r->stype));
      break;
    case Rex::LIT: {
      a->push(JV::str("lit"));
      switch (r->lkind) {
        case Rex::L_NULL:
          a->push(JV::str("n"));
          a->push(JV::null());
          break;
        case Rex::L_BOOL:
          a->push(JV::str("b"));
          a->push(JV::boolean(r->bval));
          break;
        case Rex::L_INT:
          a->push(JV::str("i"));
          a->push(JV::integer(r->ival));
          break;
        case Rex::L_DBL:
          a->push(JV::str("f"));
          a->push(JV::dbl(r->dval));
          break;
        case Rex::L_STR:
          a->push(JV::str("s"));
          a->push(JV::str(r->sval));
          break;
      }
      a->push(type_to_json(r->stype));
      break;
    }
    case Rex::CALL: {
      a->push(JV::str("call"));
      a->push(JV::str(r->op));
      auto ops = JV::array();
      for (const auto& o : r->operands) ops->push(rex_to_json(o));
      a->push(ops);
      a->push(type_to_json(r->stype));
      a->push(r->has_info ? type_to_json(r->info) : JV::null());
      break;
    }
    case Rex::SUBQ:
      a->push(JV::str("subq"));
      a->push(rel_to_json(r->plan));
      a->push(type_to_json(r->stype));
      break;
  }
  return a;
}

static SortCollation coll_from_json(const JVP& v) {
  if (!v || v->kind != JV::ARR || v->arr.size() != 3)
    throw PlanError("bad collation");
  SortCollation c;
  c.index = v->arr[0]->as_int();
  c.ascending = v->arr[1]->as_bool();
  c.nulls_first = v->arr[2]->is_null() ? -1 : (v->arr[2]->as_bool() ? 1 : 0);
  return c;
}

static JVP coll_to_json(const SortCollation& c) {
  auto a = JV::array();
  a->push(JV::integer(c.index));
  a->push(JV::boolean(c.ascending));
  a->push(c.nulls_first < 0 ? JV::null() : JV::boolean(c.nulls_first == 1));
  return a;
}

static AggCall agg_from_json(const JVP& v) {
  if (!v || v->kind != JV::ARR || v->arr.size() != 6)
    throw PlanError("bad AggCall");
  AggCall a;
  a.op = v->arr[0]->as_str();
  for (const auto& x : v->arr[1]->arr) a.args.push_back(x->as_int());
  a.distinct = v->arr[2]->as_bool();
  a.stype = type_from_json(v->arr[3]);
  a.name = v->arr[4]->as_str();
  if (!v->arr[5]->is_null()) {
    a.has_filter = true;
    a.filter_arg = v->arr[5]->as_int();
  }
  return a;
}

static JVP agg_to_json(const AggCall& a) {
  auto v = JV::array();
  v->push(JV::str(a.op));
  auto args = JV::array();
  for (int64_t x : a.args) args->push(JV::integer(x));
  v->push(args);
  v->push(JV::boolean(a.distinct));
  v->push(type_to_json(a.stype));
  v->push(JV::str(a.name));
  v->push(a.has_filter ? JV::integer(a.filter_arg) : JV::null());
  return v;
}

static WindowCall wcall_from_json(const JVP& v) {
  if (!v || v->kind != JV::ARR || v->arr.size() != 7)
    throw PlanError("bad WindowCall");
  WindowCall w;
  w.op = v->arr[0]->as_str();
  for (const auto& x : v->arr[1]->arr) w.args.push_back(x->as_int());
  for (const auto& x : v->arr[2]->arr) w.partition.push_back(x->as_int());
  for (const auto& x : v->arr[3]->arr) w.order.push_back(coll_from_json(x));
  w.frame = v->arr[4];  // opaque
  w.stype = type_from_json(v->arr[5]);
  w.name = v->arr[6]->as_str();
  return w;
}

static JVP wcall_to_json(const WindowCall& w) {
  auto v = JV::array();
  v->push(JV::str(w.op));
  auto args = JV::array();
  for (int64_t x : w.args) args->push(JV::integer(x));
  v->push(args);
  auto part = JV::array();
  for (int64_t x : w.partition) part->push(JV::integer(x));
  v->push(part);
  auto ord = JV::array();
  for (const auto& c : w.order) ord->push(coll_to_json(c));
  v->push(ord);
  v->push(w.frame ? w.frame : JV::null());
  v->push(type_to_json(w.stype));
  v->push(JV::str(w.name));
  return v;
}

RelP rel_from_json(const JVP& v) {
  if (!v || v->kind != JV::OBJ) throw PlanError("bad rel");
  const std::string& k = v->at("k")->as_str();
  auto n = std::make_shared<Rel>();
  n->schema = schema_from_json(v->at("schema"));
  if (k == "scan") {
    n->kind = Rel::SCAN;
    n->schema_name = v->at("sn")->as_str();
    n->table_name = v->at("tn")->as_str();
  } else if (k == "proj") {
    n->kind = Rel::PROJECT;
    n->input = rel_from_json(v->at("in"));
    for (const auto& e : v->at("exprs")->arr)
      n->exprs.push_back(rex_from_json(e));
  } else if (k == "filt") {
    n->kind = Rel::FILTER;
    n->input = rel_from_json(v->at("in"));
    n->condition = rex_from_json(v->at("cond"));
  } else if (k == "agg") {
    n->kind = Rel::AGG;
    n->input = rel_from_json(v->at("in"));
    for (const auto& g : v->at("gk")->arr)
      n->group_keys.push_back(g->as_int());
    for (const auto& a : v->at("aggs")->arr)
      n->aggs.push_back(agg_from_json(a));
  } else if (k == "join") {
    n->kind = Rel::JOIN;
    n->left = rel_from_json(v->at("l"));
    n->right = rel_from_json(v->at("r"));
    n->join_type = v->at("jt")->as_str();
    if (!v->at("cond")->is_null())
      n->condition = rex_from_json(v->at("cond"));
    n->null_aware = v->at("na")->as_bool();
  } else if (k == "sort") {
    n->kind = Rel::SORT;
    n->input = rel_from_json(v->at("in"));
    for (const auto& c : v->at("coll")->arr)
      n->collation.push_back(coll_from_json(c));
    if (!v->at("limit")->is_null()) {
      n->has_limit = true;
      n->limit = v->at("limit")->as_int();
    }
    if (!v->at("offset")->is_null()) {
      n->has_offset = true;
      n->offset = v->at("offset")->as_int();
    }
  } else if (k == "union" || k == "intersect" || k == "except") {
    n->kind = k == "union" ? Rel::UNION
              : k == "intersect" ? Rel::INTERSECT : Rel::EXCEPT;
    for (const auto& i : v->at("ins")->arr)
      n->set_inputs.push_back(rel_from_json(i));
    n->all_flag = v->at("all")->as_bool();
  } else if (k == "values") {
    n->kind = Rel::VALUES;
    for (const auto& row : v->at("rows")->arr) {
      std::vector<RexP> r;
      for (const auto& e : row->arr) r.push_back(rex_from_json(e));
      n->rows.push_back(std::move(r));
    }
  } else if (k == "window") {
    n->kind = Rel::WINDOW;
    n->input = rel_from_json(v->at("in"));
    for (const auto& c : v->at("calls")->arr)
      n->calls.push_back(wcall_from_json(c));
  } else if (k == "sample") {
    n->kind = Rel::SAMPLE;
    n->input = rel_from_json(v->at("in"));
    n->method = v->at("method")->as_str();
    n->percentage = v->at("pct")->as_double();
    if (!v->at("seed")->is_null()) {
      n->has_seed = true;
      n->seed = v->at("seed")->as_int();
    }
  } else {
    throw PlanError("unknown rel kind: " + k);
  }
  return n;
}

JVP rel_to_json(const RelP& r) {
  auto o = JV::object();
  switch (r->kind) {
    case Rel::SCAN:
      o->set("k", JV::str("scan"));
      o->set("sn", JV::str(r->schema_name));
      o->set("tn", JV::str(r->table_name));
      break;
    case Rel::PROJECT: {
      o->set("k", JV::str("proj"));
      o->set("in", rel_to_json(r->input));
      auto e = JV::array();
      for (const auto& x : r->exprs) e->push(rex_to_json(x));
      o->set("exprs", e);
      break;
    }
    case Rel::FILTER:
      o->set("k", JV::str("filt"));
      o->set("in", rel_to_json(r->input));
      o->set("cond", rex_to_json(r->condition));
      break;
    case Rel::AGG: {
      o->set("k", JV::str("agg"));
      o->set("in", rel_to_json(r->input));
      auto g = JV::array();
      for (int64_t x : r->group_keys) g->push(JV::integer(x));
      o->set("gk", g);
      auto a = JV::array();
      for (const auto& x : r->aggs) a->push(agg_to_json(x));
      o->set("aggs", a);
      break;
    }
    case Rel::JOIN:
      o->set("k", JV::str("join"));
      o->set("l", rel_to_json(r->left));
      o->set("r", rel_to_json(r->right));
      o->set("jt", JV::str(r->join_type));
      o->set("cond", r->condition ? rex_to_json(r->condition) : JV::null());
      o->set("na", JV::boolean(r->null_aware));
      break;
    case Rel::SORT: {
      o->set("k", JV::str("sort"));
      o->set("in", rel_to_json(r->input));
      auto c = JV::array();
      for (const auto& x : r->collation) c->push(coll_to_json(x));
      o->set("coll", c);
      o->set("limit", r->has_limit ? JV::integer(r->limit) : JV::null());
      o->set("offset", r->has_offset ? JV::integer(r->offset) : JV::null());
      break;
    }
    case Rel::UNION:
    case Rel::INTERSECT:
    case Rel::EXCEPT: {
      o->set("k", JV::str(r->kind == Rel::UNION ? "union"
                          : r->kind == Rel::INTERSECT ? "intersect"
                                                      : "except"));
      auto ins = JV::array();
      for (const auto& i : r->set_inputs) ins->push(rel_to_json(i));
      o->set("ins", ins);
      o->set("all", JV::boolean(r->all_flag));
      break;
    }
    case Rel::VALUES: {
      o->set("k", JV::str("values"));
      auto rows = JV::array();
      for (const auto& row : r->rows) {
        auto jr = JV::array();
        for (const auto& e : row) jr->push(rex_to_json(e));
        rows->push(jr);
      }
      o->set("rows", rows);
      break;
    }
    case Rel::WINDOW: {
      o->set("k", JV::str("window"));
      o->set("in", rel_to_json(r->input));
      auto c = JV::array();
      for (const auto& x : r->calls) c->push(wcall_to_json(x));
      o->set("calls", c);
      break;
    }
    case Rel::SAMPLE:
      o->set("k", JV::str("sample"));
      o->set("in", rel_to_json(r->input));
      o->set("method", JV::str(r->method));
      o->set("pct", JV::dbl(r->percentage));
      o->set("seed", r->has_seed ? JV::integer(r->seed) : JV::null());
      break;
  }
  o->set("schema", schema_to_json(r->schema));
  return o;
}

}  // namespace dsql
