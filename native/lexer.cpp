#include "lexer.h"

#include <cctype>

namespace dsql {

namespace {

const char* kMultiOps[] = {"<>", "!=", ">=", "<=", "||", "::", "=>"};
const std::string kSingleOps = "+-*/%=<>(),.;[]{}?&^|~:";

inline bool is_ident_start(unsigned char c) {
  return std::isalpha(c) || c == '_' || c >= 0x80;  // utf-8 continuation ok
}
inline bool is_ident_char(unsigned char c) {
  return std::isalnum(c) || c == '_' || c == '$' || c >= 0x80;
}

std::string ascii_upper(const std::string& s) {
  std::string out = s;
  for (auto& c : out)
    if (c >= 'a' && c <= 'z') c -= 32;
  return out;
}

}  // namespace

std::vector<Token> tokenize(const std::string& sql) {
  std::vector<Token> tokens;
  size_t i = 0, n = sql.size();
  int line = 1, col = 1;

  auto advance = [&](size_t k) {
    for (size_t j = 0; j < k; ++j) {
      if (i < n && sql[i] == '\n') {
        ++line;
        col = 1;
      } else {
        ++col;
      }
      ++i;
    }
  };
  auto push = [&](Tk kind, std::string text, int l, int c) {
    Token t;
    t.kind = kind;
    t.upper = (kind == Tk::IDENT) ? ascii_upper(text) : "";
    t.text = std::move(text);
    t.line = l;
    t.col = c;
    tokens.push_back(std::move(t));
  };

  while (i < n) {
    char c = sql[i];
    if (c == ' ' || c == '\t' || c == '\r' || c == '\n') {
      advance(1);
      continue;
    }
    if (c == '-' && i + 1 < n && sql[i + 1] == '-') {  // line comment
      while (i < n && sql[i] != '\n') advance(1);
      continue;
    }
    if (c == '/' && i + 1 < n && sql[i + 1] == '*') {  // block comment
      int sl = line, sc = col;
      advance(2);
      while (i < n && !(sql[i] == '*' && i + 1 < n && sql[i + 1] == '/')) advance(1);
      if (i >= n) throw LexError{"Unterminated block comment", sl, sc};
      advance(2);
      continue;
    }
    if (c == '\'') {  // string literal, '' escapes
      int sl = line, sc = col;
      advance(1);
      std::string buf;
      for (;;) {
        if (i >= n) throw LexError{"Unterminated string literal", sl, sc};
        if (sql[i] == '\'') {
          if (i + 1 < n && sql[i + 1] == '\'') {
            buf += '\'';
            advance(2);
            continue;
          }
          advance(1);
          break;
        }
        buf += sql[i];
        advance(1);
      }
      push(Tk::STRING, buf, sl, sc);
      continue;
    }
    if (c == '"' || c == '`') {  // quoted identifier
      char quote = c;
      int sl = line, sc = col;
      advance(1);
      std::string buf;
      for (;;) {
        if (i >= n) throw LexError{"Unterminated quoted identifier", sl, sc};
        if (sql[i] == quote) {
          if (i + 1 < n && sql[i + 1] == quote) {
            buf += quote;
            advance(2);
            continue;
          }
          advance(1);
          break;
        }
        buf += sql[i];
        advance(1);
      }
      push(Tk::QIDENT, buf, sl, sc);
      continue;
    }
    if (std::isdigit((unsigned char)c) ||
        (c == '.' && i + 1 < n && std::isdigit((unsigned char)sql[i + 1]))) {
      int sl = line, sc = col;
      size_t j = i;
      bool seen_dot = false, seen_exp = false;
      while (j < n) {
        char ch = sql[j];
        if (std::isdigit((unsigned char)ch)) {
          ++j;
        } else if (ch == '.' && !seen_dot && !seen_exp) {
          seen_dot = true;
          ++j;
        } else if ((ch == 'e' || ch == 'E') && !seen_exp && j + 1 < n &&
                   (std::isdigit((unsigned char)sql[j + 1]) ||
                    ((sql[j + 1] == '+' || sql[j + 1] == '-') && j + 2 < n &&
                     std::isdigit((unsigned char)sql[j + 2])))) {
          seen_exp = true;
          j += (sql[j + 1] == '+' || sql[j + 1] == '-') ? 2 : 1;
        } else {
          break;
        }
      }
      std::string text = sql.substr(i, j - i);
      advance(j - i);
      push(Tk::NUMBER, text, sl, sc);
      continue;
    }
    if (is_ident_start((unsigned char)c)) {
      int sl = line, sc = col;
      size_t j = i;
      while (j < n && is_ident_char((unsigned char)sql[j])) ++j;
      std::string text = sql.substr(i, j - i);
      advance(j - i);
      push(Tk::IDENT, text, sl, sc);
      continue;
    }
    if (i + 1 < n) {
      std::string two = sql.substr(i, 2);
      bool matched = false;
      for (const char* op : kMultiOps) {
        if (two == op) {
          push(Tk::OP, two, line, col);
          advance(2);
          matched = true;
          break;
        }
      }
      if (matched) continue;
    }
    if (kSingleOps.find(c) != std::string::npos) {
      push(Tk::OP, std::string(1, c), line, col);
      advance(1);
      continue;
    }
    throw LexError{std::string("Unexpected character '") + c + "'", line, col};
  }
  push(Tk::END, "", line, col);
  return tokens;
}

}  // namespace dsql
