// C API for the native SQL planner front-end (loaded from Python via ctypes —
// pybind11 is not available in this environment; the reference exposes its
// native planner to Python through an in-process bridge the same way, via
// JPype: /root/reference/dask_sql/java.py:62-98).
//
// Contract:
//   dsql_parse(sql) -> malloc'd UTF-8 JSON string, either
//     {"ok": <statement array>}  or
//     {"error": {"msg": ..., "line": N, "col": N, "width": N}}
//   The caller must release the result with dsql_free().
//   dsql_optimize(plan_json, enable_pruning) -> malloc'd UTF-8 JSON string,
//     {"ok": <optimized plan>} or {"error": {"msg": ...}} — the native rule
//     optimizer (optimizer.cpp), lockstep with plan/optimizer.py.
#include <cstdlib>
#include <cstring>
#include <string>

#include "json.h"
#include "lexer.h"
#include "parser.h"
#include "plan.h"

namespace {

using dsql::json_quote;

char* dup_string(const std::string& s) {
  char* out = (char*)std::malloc(s.size() + 1);
  if (out) std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

std::string error_json(const std::string& msg, int line, int col, int width) {
  return "{\"error\":{\"msg\":" + json_quote(msg) + ",\"line\":" + std::to_string(line) +
         ",\"col\":" + std::to_string(col) + ",\"width\":" + std::to_string(width) +
         "}}";
}

}  // namespace

extern "C" {

const char* dsql_version() { return "1"; }

char* dsql_parse(const char* sql) {
  try {
    std::string result = dsql::parse_statements_json(sql ? sql : "");
    return dup_string("{\"ok\":" + result + "}");
  } catch (const dsql::ParseError& e) {
    return dup_string(error_json(e.msg, e.line, e.col, e.width));
  } catch (const dsql::LexError& e) {
    return dup_string(error_json(e.msg, e.line, e.col, 1));
  } catch (const std::exception& e) {
    return dup_string(error_json(std::string("internal: ") + e.what(), 1, 1, 1));
  } catch (...) {
    return dup_string(error_json("internal: unknown error", 1, 1, 1));
  }
}

void dsql_free(char* p) { std::free(p); }

char* dsql_optimize(const char* plan_json, int enable_pruning) {
  try {
    dsql::JVP doc = dsql::json_parse(plan_json ? plan_json : "");
    dsql::RelP plan = dsql::rel_from_json(doc);
    dsql::RelP out = dsql::optimize_plan(plan, enable_pruning != 0);
    return dup_string("{\"ok\":" + dsql::json_emit(dsql::rel_to_json(out)) +
                      "}");
  } catch (const std::exception& e) {
    return dup_string(error_json(std::string("optimize: ") + e.what(), 1, 1,
                                 1));
  } catch (...) {
    return dup_string(error_json("optimize: unknown error", 1, 1, 1));
  }
}

}  // extern "C"
