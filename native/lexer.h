// SQL lexer: text -> token stream with line/col positions.
//
// Native counterpart of dask_sql_tpu/sql/lexer.py — the reference keeps its
// whole parser stack native (Java/Calcite, planner/src/main/codegen); here the
// native planner front-end is C++.  Dialect decisions follow the reference's
// DaskSqlDialect (DaskSqlDialect.java:25-26): unquoted identifiers KEEP their
// case, keywords are case-insensitive, quoted identifiers use double quotes or
// backticks, strings use single quotes with '' escaping.
#pragma once

#include <string>
#include <vector>

namespace dsql {

enum class Tk { IDENT, QIDENT, STRING, NUMBER, OP, END };

struct Token {
  Tk kind;
  std::string text;   // raw text (identifier case preserved; string unescaped)
  std::string upper;  // ASCII upper-case of text (for keyword matching)
  int line = 0, col = 0;
};

struct LexError {
  std::string msg;
  int line, col;
};

// Tokenize `sql`; throws LexError on bad input. Appends an END token.
std::vector<Token> tokenize(const std::string& sql);

}  // namespace dsql
