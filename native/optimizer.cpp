// Native rule-based heuristic optimizer — the C++ port of
// dask_sql_tpu/plan/optimizer.py (which reproduces the load-bearing effects
// of the reference's 17-rule HepPlanner program,
// /root/reference/planner/.../RelationalAlgebraGenerator.java:198-224).
//
// Every pass is a faithful, lockstep port of its Python namesake: the
// Python implementation stays as the fallback (plans carrying Python-only
// payloads — UDFs, UDAFs — never reach this library), and
// tests/unit/test_native_optimizer.py asserts explain() equality between
// the two on the full TPC-H + fixture corpus.
#include <algorithm>
#include <cstring>
#include <functional>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "plan.h"

namespace dsql {

namespace {

const SqlType BOOLEAN{"BOOLEAN"};
const SqlType BIGINT{"BIGINT"};

// ---------------------------------------------------------------------------
// generic helpers (optimizer.py:32-60)
// ---------------------------------------------------------------------------

void split_conjuncts(const RexP& rex, std::vector<RexP>& out) {
  if (rex->kind == Rex::CALL && rex->op == "AND") {
    split_conjuncts(rex->operands[0], out);
    split_conjuncts(rex->operands[1], out);
    return;
  }
  out.push_back(rex);
}

std::vector<RexP> split_conjuncts(const RexP& rex) {
  std::vector<RexP> out;
  split_conjuncts(rex, out);
  return out;
}

RexP and_all(const std::vector<RexP>& rexes) {
  if (rexes.empty()) return nullptr;
  RexP out = rexes[0];
  for (size_t i = 1; i < rexes.size(); ++i)
    out = Rex::call("AND", {out, rexes[i]}, BOOLEAN);
  return out;
}

bool is_pure(const RexP& rex) {
  switch (rex->kind) {
    case Rex::INPUT:
    case Rex::LIT:
      return true;
    case Rex::SUBQ:
      return false;
    case Rex::CALL: {
      if (rex->op == "RAND" || rex->op == "RANDOM" ||
          rex->op == "RAND_INTEGER")
        return false;
      for (const auto& o : rex->operands)
        if (!is_pure(o)) return false;
      return true;
    }
  }
  return false;
}

std::map<int64_t, int64_t> identity_shift(const RexP& c, int64_t delta) {
  std::map<int64_t, int64_t> m;
  for (int64_t i : rex_inputs(c)) m[i] = i + delta;
  return m;
}

// ---------------------------------------------------------------------------
// pass: merge_filters (optimizer.py:67-76)
// ---------------------------------------------------------------------------

RelP merge_filters(const RelP& rel0) {
  RelP rel = rel0;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    for (const auto& i : ins) ni.push_back(merge_filters(i));
    rel = rel->with_inputs(ni);
  }
  if (rel->kind == Rel::FILTER) {
    if (rel->condition->is_true_literal()) return rel->input;
    if (rel->input->kind == Rel::FILTER) {
      RexP cond = Rex::call(
          "AND", {rel->input->condition, rel->condition}, BOOLEAN);
      return make_filter(rel->input->input, cond, rel->schema);
    }
  }
  return rel;
}

// ---------------------------------------------------------------------------
// pass: merge_projects (optimizer.py:83-113)
// ---------------------------------------------------------------------------

RexP inline_rex(const RexP& rex, const std::vector<RexP>& exprs) {
  if (rex->kind == Rex::INPUT) return exprs.at(rex->index);
  if (rex->kind == Rex::CALL) {
    std::vector<RexP> ops;
    ops.reserve(rex->operands.size());
    for (const auto& o : rex->operands) ops.push_back(inline_rex(o, exprs));
    auto n = std::make_shared<Rex>(*rex);
    n->operands = std::move(ops);
    return n;
  }
  return rex;
}

int64_t rex_size(const RexP& rex) {
  if (rex->kind == Rex::CALL) {
    int64_t s = 1;
    for (const auto& o : rex->operands) s += rex_size(o);
    return s;
  }
  return 1;
}

RelP merge_projects(const RelP& rel0) {
  RelP rel = rel0;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    for (const auto& i : ins) ni.push_back(merge_projects(i));
    rel = rel->with_inputs(ni);
  }
  if (rel->kind == Rel::PROJECT && rel->input->kind == Rel::PROJECT) {
    const RelP& inner = rel->input;
    bool pure = true;
    for (const auto& e : inner->exprs)
      if (!is_pure(e)) { pure = false; break; }
    if (pure) {
      std::vector<RexP> new_exprs;
      new_exprs.reserve(rel->exprs.size());
      for (const auto& e : rel->exprs)
        new_exprs.push_back(inline_rex(e, inner->exprs));
      int64_t ns = 0, rs = 0, is = 0;
      for (const auto& e : new_exprs) ns += rex_size(e);
      for (const auto& e : rel->exprs) rs += rex_size(e);
      for (const auto& e : inner->exprs) is += rex_size(e);
      if (ns <= 4 * (rs + is))
        return make_project(inner->input, std::move(new_exprs), rel->schema);
    }
  }
  return rel;
}

// ---------------------------------------------------------------------------
// pass: push_filters (optimizer.py:121-233)
// ---------------------------------------------------------------------------

RelP push_filters(const RelP& rel0) {
  RelP rel = rel0;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    for (const auto& i : ins) ni.push_back(push_filters(i));
    rel = rel->with_inputs(ni);
  }
  if (rel->kind != Rel::FILTER) return rel;
  const RelP& child = rel->input;
  std::vector<RexP> conjuncts = split_conjuncts(rel->condition);

  // -- through Project: rewrite refs via inlining (only pure exprs)
  if (child->kind == Rel::PROJECT) {
    bool pure_child = true;
    for (const auto& e : child->exprs)
      if (!is_pure(e)) { pure_child = false; break; }
    if (pure_child) {
      std::vector<RexP> pushable, stay;
      for (const auto& c : conjuncts)
        (is_pure(c) ? pushable : stay).push_back(c);
      if (!pushable.empty()) {
        std::vector<RexP> inlined;
        for (const auto& c : pushable)
          inlined.push_back(inline_rex(c, child->exprs));
        RelP new_input = push_filters(make_filter(
            child->input, and_all(inlined), child->input->schema));
        RelP new_child =
            make_project(new_input, child->exprs, child->schema);
        if (!stay.empty())
          return make_filter(new_child, and_all(stay), rel->schema);
        return new_child;
      }
    }
  }

  // -- into Join sides
  if (child->kind == Rel::JOIN &&
      (child->join_type == "INNER" || child->join_type == "LEFT" ||
       child->join_type == "RIGHT" || child->join_type == "CROSS")) {
    int64_t nl = (int64_t)child->left->schema.size();
    const std::string& jt0 = child->join_type;
    std::vector<RexP> left_side, right_side, into_join, stay;
    for (const auto& c : conjuncts) {
      auto refs = rex_inputs(c);
      bool all_left = true, all_right = true;
      for (int64_t r : refs) {
        if (r >= nl) all_left = false;
        if (r < nl) all_right = false;
      }
      if (!is_pure(c)) {
        stay.push_back(c);
      } else if (all_left &&
                 (jt0 == "INNER" || jt0 == "LEFT" || jt0 == "CROSS")) {
        left_side.push_back(c);
      } else if (all_right &&
                 (jt0 == "INNER" || jt0 == "RIGHT" || jt0 == "CROSS")) {
        right_side.push_back(c);
      } else if (jt0 == "INNER" || jt0 == "CROSS") {
        into_join.push_back(c);
      } else {
        stay.push_back(c);
      }
    }
    if (!left_side.empty() || !right_side.empty() || !into_join.empty()) {
      RelP new_left = child->left, new_right = child->right;
      if (!left_side.empty())
        new_left = push_filters(make_filter(
            child->left, and_all(left_side), child->left->schema));
      if (!right_side.empty()) {
        std::vector<RexP> shifted;
        for (const auto& c : right_side)
          shifted.push_back(remap_rex(c, identity_shift(c, -nl)));
        new_right = push_filters(make_filter(
            child->right, and_all(shifted), child->right->schema));
      }
      RexP cond = child->condition;
      std::string jt = child->join_type;
      if (!into_join.empty()) {
        std::vector<RexP> pieces;
        if (cond && !cond->is_true_literal()) pieces.push_back(cond);
        for (const auto& c : into_join) pieces.push_back(c);
        cond = and_all(pieces);
        jt = "INNER";
      }
      RelP new_join = make_join(new_left, new_right, jt, cond,
                                child->schema, false);
      if (!stay.empty())
        return make_filter(new_join, and_all(stay), rel->schema);
      return new_join;
    }
  }

  // -- through SEMI/ANTI joins (output IS the left input)
  if (child->kind == Rel::JOIN &&
      (child->join_type == "SEMI" || child->join_type == "ANTI")) {
    std::vector<RexP> pushable, stay;
    for (const auto& c : conjuncts)
      (is_pure(c) ? pushable : stay).push_back(c);
    if (!pushable.empty()) {
      RelP new_left = push_filters(make_filter(
          child->left, and_all(pushable), child->left->schema));
      RelP new_join =
          make_join(new_left, child->right, child->join_type,
                    child->condition, child->schema, child->null_aware);
      if (!stay.empty())
        return make_filter(new_join, and_all(stay), rel->schema);
      return new_join;
    }
  }

  // -- through Aggregate: conjuncts that only touch group keys
  if (child->kind == Rel::AGG) {
    int64_t n_keys = (int64_t)child->group_keys.size();
    std::vector<RexP> pushable, stay;
    for (const auto& c : conjuncts) {
      auto refs = rex_inputs(c);
      bool only_keys = true;
      for (int64_t r : refs)
        if (r >= n_keys) { only_keys = false; break; }
      if (is_pure(c) && only_keys)
        pushable.push_back(c);
      else
        stay.push_back(c);
    }
    if (!pushable.empty()) {
      std::map<int64_t, int64_t> mapping;
      for (int64_t i = 0; i < n_keys; ++i) mapping[i] = child->group_keys[i];
      std::vector<RexP> remapped;
      for (const auto& c : pushable)
        remapped.push_back(remap_rex(c, mapping));
      RelP new_input = push_filters(make_filter(
          child->input, and_all(remapped), child->input->schema));
      RelP new_agg = make_aggregate(new_input, child->group_keys,
                                    child->aggs, child->schema);
      if (!stay.empty())
        return make_filter(new_agg, and_all(stay), rel->schema);
      return new_agg;
    }
  }

  return rel;
}

// ---------------------------------------------------------------------------
// pass: reorder_joins (optimizer.py:240-430)
// ---------------------------------------------------------------------------

struct ReorderResult {
  RelP rel;
  std::vector<RexP> leftover;
};

bool reorder_chain(const RelP& root, const std::vector<RexP>& filt_conjuncts,
                   ReorderResult& out) {
  if (root->join_type != "INNER" && root->join_type != "CROSS") return false;
  std::vector<std::pair<int64_t, RelP>> leaves;  // (global offset, leaf)
  std::vector<RexP> pool;                        // global-ordinal conjuncts

  std::function<int64_t(const RelP&, int64_t)> flat =
      [&](const RelP& j, int64_t base) -> int64_t {
    if (j->kind == Rel::JOIN &&
        (j->join_type == "INNER" || j->join_type == "CROSS")) {
      int64_t lw = flat(j->left, base);
      int64_t rw = flat(j->right, base + lw);
      if (j->condition && !j->condition->is_true_literal()) {
        for (const auto& cj : split_conjuncts(j->condition))
          pool.push_back(remap_rex(cj, identity_shift(cj, base)));
      }
      return lw + rw;
    }
    leaves.emplace_back(base, j);
    return (int64_t)j->schema.size();
  };

  int64_t total = flat(root, 0);
  if (leaves.size() < 3) return false;

  std::map<int64_t, int64_t> leaf_of;
  for (size_t li = 0; li < leaves.size(); ++li) {
    int64_t off = leaves[li].first;
    for (int64_t o = off; o < off + (int64_t)leaves[li].second->schema.size();
         ++o)
      leaf_of[o] = (int64_t)li;
  }

  auto leafset = [&](const RexP& c) {
    std::set<int64_t> s;
    for (int64_t r : rex_inputs(c)) s.insert(leaf_of.at(r));
    return s;
  };
  auto is_equi = [](const RexP& c) {
    return c->kind == Rex::CALL && c->op == "=";
  };

  std::vector<RexP> cand = pool;
  for (const auto& c : filt_conjuncts)
    if (is_pure(c)) cand.push_back(c);
  std::vector<std::pair<RexP, std::set<int64_t>>> connectors;
  for (const auto& c : cand) {
    auto ls = leafset(c);
    if (ls.size() >= 2) connectors.emplace_back(c, ls);
  }
  if (connectors.empty()) return false;

  auto is_subset = [](const std::set<int64_t>& a,
                      const std::set<int64_t>& b) {
    for (int64_t x : a)
      if (!b.count(x)) return false;
    return true;
  };

  auto count_stranded = [&](const std::vector<int64_t>& seq) {
    std::set<int64_t> joined{seq[0]};
    int64_t bad = 0;
    for (size_t k = 1; k < seq.size(); ++k) {
      int64_t li = seq[k];
      bool connected = false;
      for (const auto& [c, ls] : connectors) {
        (void)c;
        if (ls.count(li)) {
          std::set<int64_t> rest = ls;
          rest.erase(li);
          if (is_subset(rest, joined)) { connected = true; break; }
        }
      }
      if (!connected) ++bad;
      joined.insert(li);
    }
    return bad;
  };

  // stranded count of the ORIGINAL (possibly bushy) tree
  int64_t leaf_counter = 0;
  std::function<std::pair<std::set<int64_t>, int64_t>(const RelP&)>
      tree_stranded = [&](const RelP& j)
      -> std::pair<std::set<int64_t>, int64_t> {
    if (j->kind == Rel::JOIN &&
        (j->join_type == "INNER" || j->join_type == "CROSS")) {
      auto [lset, lbad] = tree_stranded(j->left);
      auto [rset, rbad] = tree_stranded(j->right);
      std::set<int64_t> here = lset;
      here.insert(rset.begin(), rset.end());
      bool connected = false;
      for (const auto& [c, ls] : connectors) {
        (void)c;
        bool hits_l = false, hits_r = false;
        for (int64_t x : ls) {
          if (lset.count(x)) hits_l = true;
          if (rset.count(x)) hits_r = true;
        }
        if (hits_l && hits_r && is_subset(ls, here)) {
          connected = true;
          break;
        }
      }
      return {here, lbad + rbad + (connected ? 0 : 1)};
    }
    return {{leaf_counter++}, 0};
  };

  int64_t orig_stranded = tree_stranded(root).second;
  if (orig_stranded == 0) return false;

  // greedy order: prefer an equi-connected leaf (FROM order), then any
  // connected leaf, then fall back to a genuine cross step
  std::vector<int64_t> order{0};
  std::set<int64_t> joined{0};
  std::vector<int64_t> remaining;
  for (size_t i = 1; i < leaves.size(); ++i) remaining.push_back((int64_t)i);
  while (!remaining.empty()) {
    int64_t pick = -1;
    for (int want_equi = 1; want_equi >= 0 && pick < 0; --want_equi) {
      for (int64_t li : remaining) {
        for (const auto& [c, ls] : connectors) {
          if (ls.count(li)) {
            std::set<int64_t> rest = ls;
            rest.erase(li);
            if (is_subset(rest, joined) && (is_equi(c) || !want_equi)) {
              pick = li;
              break;
            }
          }
        }
        if (pick >= 0) break;
      }
    }
    if (pick < 0) pick = remaining[0];
    order.push_back(pick);
    joined.insert(pick);
    remaining.erase(std::find(remaining.begin(), remaining.end(), pick));
  }

  if (count_stranded(order) >= orig_stranded) return false;

  // ordinal mapping old-global -> new-global
  std::map<int64_t, int64_t> old_to_new;
  int64_t new_off = 0;
  for (int64_t li : order) {
    int64_t off = leaves[li].first;
    int64_t w = (int64_t)leaves[li].second->schema.size();
    for (int64_t k = 0; k < w; ++k) old_to_new[off + k] = new_off + k;
    new_off += w;
  }

  // left-deep tree, attaching each connector at the first step where all
  // its leaves are available
  std::vector<bool> placed(connectors.size(), false);
  std::vector<RexP> single;
  for (const auto& c : pool)
    if (leafset(c).size() < 2) single.push_back(c);
  RelP acc = leaves[order[0]].second;
  std::set<int64_t> covered{order[0]};
  for (size_t k = 1; k < order.size(); ++k) {
    int64_t li = order[k];
    covered.insert(li);
    std::vector<RexP> conds;
    for (size_t ci = 0; ci < connectors.size(); ++ci) {
      if (!placed[ci] && is_subset(connectors[ci].second, covered)) {
        placed[ci] = true;
        const RexP& c = connectors[ci].first;
        std::map<int64_t, int64_t> m;
        for (int64_t o : rex_inputs(c)) m[o] = old_to_new.at(o);
        conds.push_back(remap_rex(c, m));
      }
    }
    const RelP& leaf = leaves[li].second;
    std::vector<Field> schema = acc->schema;
    schema.insert(schema.end(), leaf->schema.begin(), leaf->schema.end());
    acc = make_join(acc, leaf, conds.empty() ? "CROSS" : "INNER",
                    and_all(conds), schema, false);
  }

  // restore the original column order for the parent
  std::vector<Field> orig_fields;
  for (const auto& [off, leaf] : leaves) {
    (void)off;
    orig_fields.insert(orig_fields.end(), leaf->schema.begin(),
                       leaf->schema.end());
  }
  std::vector<RexP> exprs;
  for (int64_t o = 0; o < total; ++o)
    exprs.push_back(Rex::input_ref(old_to_new.at(o), orig_fields[o].stype));
  RelP proj = make_project(acc, std::move(exprs), orig_fields);

  // leftovers: placed filter connectors disappear; single-leaf
  // join-condition conjuncts rejoin the filter pool
  std::set<const Rex*> used_filter;
  for (size_t ci = 0; ci < connectors.size(); ++ci) {
    if (!placed[ci]) continue;
    for (const auto& fc : filt_conjuncts)
      if (connectors[ci].first.get() == fc.get())
        used_filter.insert(fc.get());
  }
  std::vector<RexP> leftover;
  for (const auto& c : filt_conjuncts)
    if (!used_filter.count(c.get())) leftover.push_back(c);
  leftover.insert(leftover.end(), single.begin(), single.end());
  out.rel = proj;
  out.leftover = std::move(leftover);
  return true;
}

RelP reorder_joins(const RelP& rel0) {
  RelP rel = rel0;
  ReorderResult rr;
  bool matched = false;
  if (rel->kind == Rel::FILTER && rel->input->kind == Rel::JOIN) {
    matched = reorder_chain(rel->input, split_conjuncts(rel->condition), rr);
  } else if (rel->kind == Rel::JOIN) {
    matched = reorder_chain(rel, {}, rr);
  }
  if (matched) {
    RelP nw = rr.rel;
    if (!rr.leftover.empty())
      nw = make_filter(nw, and_all(rr.leftover), nw->schema);
    std::vector<RelP> ni;
    for (const auto& i : nw->inputs()) ni.push_back(reorder_joins(i));
    return nw->with_inputs(ni);
  }
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    for (const auto& i : ins) ni.push_back(reorder_joins(i));
    rel = rel->with_inputs(ni);
  }
  return rel;
}

// ---------------------------------------------------------------------------
// pass: factor_or_predicates (optimizer.py:604-655)
// ---------------------------------------------------------------------------

RexP factor_or(const RexP& rex0) {
  if (rex0->kind != Rex::CALL) return rex0;
  std::vector<RexP> ops;
  for (const auto& o : rex0->operands) ops.push_back(factor_or(o));
  auto rex = std::make_shared<Rex>(*rex0);
  rex->operands = std::move(ops);
  if (rex->op != "OR") return rex;

  std::function<void(const RexP&, std::vector<RexP>&)> branches =
      [&](const RexP& r, std::vector<RexP>& out) {
        if (r->kind == Rex::CALL && r->op == "OR") {
          branches(r->operands[0], out);
          branches(r->operands[1], out);
          return;
        }
        out.push_back(r);
      };
  std::vector<RexP> brs_flat;
  branches(rex, brs_flat);
  std::vector<std::vector<RexP>> brs;
  for (const auto& b : brs_flat) brs.push_back(split_conjuncts(b));

  std::vector<RexP> common;
  for (const auto& c : brs[0]) {
    if (!is_pure(c)) continue;
    bool in_all = true;
    for (size_t bi = 1; bi < brs.size(); ++bi) {
      bool found = false;
      for (const auto& d : brs[bi])
        if (rex_equal(c, d)) { found = true; break; }
      if (!found) { in_all = false; break; }
    }
    if (in_all) common.push_back(c);
  }
  if (common.empty()) return rex;

  std::vector<RexP> rest_branches;
  for (const auto& b : brs) {
    std::vector<RexP> rest;
    for (const auto& c : b) {
      bool is_common = false;
      for (const auto& d : common)
        if (rex_equal(c, d)) { is_common = true; break; }
      if (!is_common) rest.push_back(c);
    }
    RexP anded = and_all(rest);
    rest_branches.push_back(anded ? anded
                                  : Rex::literal_bool(true, BOOLEAN));
  }
  RexP rest_or = rest_branches[0];
  for (size_t k = 1; k < rest_branches.size(); ++k)
    rest_or = Rex::call("OR", {rest_or, rest_branches[k]}, BOOLEAN);
  std::vector<RexP> all = common;
  all.push_back(rest_or);
  return and_all(all);
}

RelP factor_or_predicates(const RelP& rel0) {
  RelP rel = rel0;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    for (const auto& i : ins) ni.push_back(factor_or_predicates(i));
    rel = rel->with_inputs(ni);
  }
  if (rel->kind == Rel::FILTER)
    return make_filter(rel->input, factor_or(rel->condition), rel->schema);
  if (rel->kind == Rel::JOIN && rel->condition)
    return make_join(rel->left, rel->right, rel->join_type,
                     factor_or(rel->condition), rel->schema,
                     rel->null_aware);
  return rel;
}

// ---------------------------------------------------------------------------
// pass: push_join_side_conditions (optimizer.py:665-713)
// ---------------------------------------------------------------------------

RelP push_join_side_conditions(const RelP& rel0) {
  RelP rel = rel0;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    for (const auto& i : ins) ni.push_back(push_join_side_conditions(i));
    rel = rel->with_inputs(ni);
  }
  if (!(rel->kind == Rel::JOIN &&
        (rel->join_type == "INNER" || rel->join_type == "LEFT" ||
         rel->join_type == "RIGHT") &&
        rel->condition))
    return rel;
  int64_t nl = (int64_t)rel->left->schema.size();
  bool left_ok = rel->join_type == "INNER" || rel->join_type == "RIGHT";
  bool right_ok = rel->join_type == "INNER" || rel->join_type == "LEFT";
  std::vector<RexP> stay, to_left, to_right;
  for (const auto& cj : split_conjuncts(rel->condition)) {
    auto refs = rex_inputs(cj);
    bool all_left = true, all_right = true;
    for (int64_t r : refs) {
      if (r >= nl) all_left = false;
      if (r < nl) all_right = false;
    }
    if (!is_pure(cj) || refs.empty())
      stay.push_back(cj);
    else if (all_left && left_ok)
      to_left.push_back(cj);
    else if (all_right && right_ok)
      to_right.push_back(cj);
    else
      stay.push_back(cj);
  }
  if (to_left.empty() && to_right.empty()) return rel;
  RelP new_left = rel->left, new_right = rel->right;
  if (!to_left.empty())
    new_left = make_filter(rel->left, and_all(to_left), rel->left->schema);
  if (!to_right.empty()) {
    std::vector<RexP> shifted;
    for (const auto& cj : to_right)
      shifted.push_back(remap_rex(cj, identity_shift(cj, -nl)));
    new_right =
        make_filter(rel->right, and_all(shifted), rel->right->schema);
  }
  RexP cond = stay.empty() ? nullptr : and_all(stay);
  return make_join(new_left, new_right, rel->join_type, cond, rel->schema,
                   rel->null_aware);
}

// ---------------------------------------------------------------------------
// split_join_condition (optimizer.py:716-745)
// ---------------------------------------------------------------------------

void split_join_condition(const RelP& rel, std::vector<std::pair<int64_t, int64_t>>& equi,
                          std::vector<RexP>& residual) {
  int64_t nl = (int64_t)rel->left->schema.size();
  std::function<void(const RexP&)> visit = [&](const RexP& rex) {
    if (rex->kind == Rex::CALL && rex->op == "AND") {
      visit(rex->operands[0]);
      visit(rex->operands[1]);
      return;
    }
    if (rex->kind == Rex::CALL && rex->op == "=" &&
        rex->operands.size() == 2) {
      const RexP& a = rex->operands[0];
      const RexP& b = rex->operands[1];
      if (a->kind == Rex::INPUT && b->kind == Rex::INPUT) {
        if (a->index < nl && nl <= b->index) {
          equi.emplace_back(a->index, b->index - nl);
          return;
        }
        if (b->index < nl && nl <= a->index) {
          equi.emplace_back(b->index, a->index - nl);
          return;
        }
      }
    }
    if (rex->is_true_literal()) return;
    residual.push_back(rex);
  };
  if (rel->condition) visit(rel->condition);
}

// ---------------------------------------------------------------------------
// pass: rewrite_exist_test_joins (optimizer.py:752-852)
// ---------------------------------------------------------------------------

bool is_exist_test_op(const std::string& op) {
  return op == "<>" || op == "<" || op == "<=" || op == ">" || op == ">=";
}

std::string exist_flip(const std::string& op) {
  if (op == "<") return ">";
  if (op == "<=") return ">=";
  if (op == ">") return "<";
  if (op == ">=") return "<=";
  return "<>";
}

RelP rewrite_exist_test_joins(const RelP& rel0) {
  RelP rel = rel0;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    bool changed = false;
    for (const auto& i : ins) {
      RelP n = rewrite_exist_test_joins(i);
      if (n != i) changed = true;
      ni.push_back(n);
    }
    if (changed) rel = rel->with_inputs(ni);
  }
  if (rel->kind != Rel::JOIN ||
      (rel->join_type != "SEMI" && rel->join_type != "ANTI") ||
      rel->null_aware || !rel->condition)
    return rel;
  std::vector<std::pair<int64_t, int64_t>> equi;
  std::vector<RexP> residual;
  split_join_condition(rel, equi, residual);
  if (equi.empty() || residual.size() != 1) return rel;
  const RexP& r = residual[0];
  int64_t nl = (int64_t)rel->left->schema.size();
  if (!(r->kind == Rex::CALL && is_exist_test_op(r->op) &&
        r->operands.size() == 2 &&
        r->operands[0]->kind == Rex::INPUT &&
        r->operands[1]->kind == Rex::INPUT))
    return rel;
  const RexP& a = r->operands[0];
  const RexP& b = r->operands[1];
  int64_t y_idx, x_idx;
  std::string op;
  if (a->index < nl && nl <= b->index) {
    y_idx = a->index;
    x_idx = b->index - nl;
    op = exist_flip(r->op);
  } else if (b->index < nl && nl <= a->index) {
    y_idx = b->index;
    x_idx = a->index - nl;
    op = r->op;
  } else {
    return rel;
  }

  const RelP& right = rel->right;
  const Field& x_f = right->schema[x_idx];
  const Field& y_f = rel->left->schema[y_idx];
  if (x_f.stype.is_floating() || y_f.stype.is_floating()) return rel;
  std::vector<int64_t> gks;
  for (const auto& [pi, bi] : equi) {
    (void)pi;
    if (std::find(gks.begin(), gks.end(), bi) == gks.end())
      gks.push_back(bi);
  }
  std::vector<Field> key_fields;
  for (int64_t bi : gks)
    key_fields.push_back(
        Field{right->schema[bi].name, right->schema[bi].stype});
  std::vector<AggCall> pre_aggs;
  {
    AggCall cnt{"COUNT", {x_idx}, false, BIGINT, "cnt$"};
    AggCall mn{"MIN", {x_idx}, false, x_f.stype, "mn$"};
    AggCall mx{"MAX", {x_idx}, false, x_f.stype, "mx$"};
    pre_aggs = {cnt, mn, mx};
  }
  std::vector<Field> agg_schema = key_fields;
  agg_schema.push_back(Field{"cnt$", BIGINT});
  agg_schema.push_back(Field{"mn$", x_f.stype});
  agg_schema.push_back(Field{"mx$", x_f.stype});
  RelP agg = make_aggregate(right, gks, pre_aggs, agg_schema);

  std::map<int64_t, int64_t> pos_of;
  for (size_t i = 0; i < gks.size(); ++i) pos_of[gks[i]] = (int64_t)i;
  RexP cond;
  for (const auto& [pi, bi] : equi) {
    RexP eq = Rex::call(
        "=",
        {Rex::input_ref(pi, rel->left->schema[pi].stype),
         Rex::input_ref(nl + pos_of.at(bi), right->schema[bi].stype)},
        BOOLEAN);
    cond = cond ? Rex::call("AND", {cond, eq}, BOOLEAN) : eq;
  }
  int64_t nk = (int64_t)gks.size();
  std::vector<Field> j_schema = rel->left->schema;
  j_schema.insert(j_schema.end(), agg->schema.begin(), agg->schema.end());
  RelP joined =
      make_join(rel->left, agg,
                rel->join_type == "SEMI" ? "INNER" : "LEFT", cond,
                j_schema, false);
  RexP y = Rex::input_ref(y_idx, y_f.stype);
  RexP cnt = Rex::input_ref(nl + nk, BIGINT);
  RexP mn = Rex::input_ref(nl + nk + 1, x_f.stype);
  RexP mx = Rex::input_ref(nl + nk + 2, x_f.stype);
  RexP pred;
  if (op == "<>") {
    pred = Rex::call("OR",
                     {Rex::call("<>", {mn, y}, BOOLEAN),
                      Rex::call("<>", {mx, y}, BOOLEAN)},
                     BOOLEAN);
  } else if (op == "<" || op == "<=") {
    pred = Rex::call(op, {mn, y}, BOOLEAN);
  } else {
    pred = Rex::call(op, {mx, y}, BOOLEAN);
  }
  RexP cnt_pos = Rex::call(
      ">=",
      {Rex::call("COALESCE", {cnt, Rex::literal_int(0, BIGINT)}, BIGINT),
       Rex::literal_int(1, BIGINT)},
      BOOLEAN);
  RexP exists_pred = Rex::call("AND", {cnt_pos, pred}, BOOLEAN);
  RexP keep;
  if (rel->join_type == "SEMI") {
    keep = exists_pred;
  } else {
    keep = Rex::call("OR",
                     {Rex::call("IS_NULL", {y}, BOOLEAN),
                      Rex::call("NOT", {exists_pred}, BOOLEAN)},
                     BOOLEAN);
  }
  RelP filt = make_filter(joined, keep, joined->schema);
  std::vector<RexP> exprs;
  for (size_t i = 0; i < rel->left->schema.size(); ++i)
    exprs.push_back(
        Rex::input_ref((int64_t)i, rel->left->schema[i].stype));
  return make_project(filt, std::move(exprs), rel->schema);
}

// ---------------------------------------------------------------------------
// pass: aggregate_through_join (optimizer.py:858-952)
// ---------------------------------------------------------------------------

bool agg_through_join_op(const std::string& op) {
  return op == "COUNT" || op == "SUM" || op == "$SUM0" || op == "MIN" ||
         op == "MAX";
}

RelP aggregate_through_join(const RelP& rel0) {
  RelP rel = rel0;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    for (const auto& i : ins) ni.push_back(aggregate_through_join(i));
    rel = rel->with_inputs(ni);
  }
  if (rel->kind != Rel::AGG) return rel;
  RelP join = rel->input;
  // look through a bare-ref projection (the binder's pre-projection)
  bool has_remap = false;
  std::vector<int64_t> remap;
  if (join->kind == Rel::PROJECT) {
    bool all_refs = true;
    for (const auto& e : join->exprs)
      if (e->kind != Rex::INPUT) { all_refs = false; break; }
    if (all_refs) {
      has_remap = true;
      for (const auto& e : join->exprs) remap.push_back(e->index);
      join = join->input;
    }
  }
  if (!(join->kind == Rel::JOIN &&
        (join->join_type == "INNER" || join->join_type == "LEFT") &&
        join->condition))
    return rel;

  auto m = [&](int64_t i) { return has_remap ? remap.at(i) : i; };

  std::vector<int64_t> group_keys;
  for (int64_t g : rel->group_keys) group_keys.push_back(m(g));
  std::vector<std::vector<int64_t>> agg_args;
  for (const auto& agg : rel->aggs) {
    std::vector<int64_t> args;
    for (int64_t a : agg.args) args.push_back(m(a));
    agg_args.push_back(std::move(args));
  }
  int64_t nl = (int64_t)join->left->schema.size();
  std::vector<int64_t> lkeys, rkeys;
  for (const auto& cj : split_conjuncts(join->condition)) {
    if (!(cj->kind == Rex::CALL && cj->op == "=" &&
          cj->operands.size() == 2 &&
          cj->operands[0]->kind == Rex::INPUT &&
          cj->operands[1]->kind == Rex::INPUT))
      return rel;
    int64_t a = cj->operands[0]->index, b = cj->operands[1]->index;
    if (a < nl && nl <= b) {
      lkeys.push_back(a);
      rkeys.push_back(b - nl);
    } else if (b < nl && nl <= a) {
      lkeys.push_back(b);
      rkeys.push_back(a - nl);
    } else {
      return rel;
    }
  }
  if (lkeys.empty()) return rel;
  for (int64_t g : group_keys)
    if (g >= nl) return rel;
  for (size_t i = 0; i < rel->aggs.size(); ++i) {
    const AggCall& agg = rel->aggs[i];
    const auto& args = agg_args[i];
    if (!agg_through_join_op(agg.op) || agg.distinct || agg.has_filter ||
        args.empty())
      return rel;
    for (int64_t a : args)
      if (a < nl) return rel;
  }

  // right pre-aggregate: group by the right join keys
  std::vector<Field> pre_fields;
  for (size_t i = 0; i < rkeys.size(); ++i)
    pre_fields.push_back(Field{"$jk" + std::to_string(i),
                               join->right->schema[rkeys[i]].stype});
  std::vector<AggCall> pre_aggs;
  for (size_t i = 0; i < rel->aggs.size(); ++i) {
    const AggCall& agg = rel->aggs[i];
    AggCall pa;
    pa.op = agg.op;
    for (int64_t a : agg_args[i]) pa.args.push_back(a - nl);
    pa.distinct = false;
    pa.stype = agg.stype;
    pa.name = "$pa" + std::to_string(i);
    pre_aggs.push_back(pa);
    pre_fields.push_back(Field{pa.name, agg.stype});
  }
  RelP pre = make_aggregate(join->right, rkeys, pre_aggs, pre_fields);

  RexP cond;
  for (size_t i = 0; i < lkeys.size(); ++i) {
    RexP eq = Rex::call(
        "=",
        {Rex::input_ref(lkeys[i], join->left->schema[lkeys[i]].stype),
         Rex::input_ref(nl + (int64_t)i, pre_fields[i].stype)},
        BOOLEAN);
    cond = cond ? Rex::call("AND", {cond, eq}, BOOLEAN) : eq;
  }
  std::vector<Field> j_schema = join->left->schema;
  j_schema.insert(j_schema.end(), pre_fields.begin(), pre_fields.end());
  RelP j2 = make_join(join->left, pre, join->join_type, cond, j_schema,
                      false);

  std::vector<AggCall> out_aggs;
  for (size_t i = 0; i < rel->aggs.size(); ++i) {
    const AggCall& agg = rel->aggs[i];
    AggCall oa;
    oa.op = agg.op == "COUNT" ? "$SUM0" : agg.op;
    oa.args = {nl + (int64_t)rkeys.size() + (int64_t)i};
    oa.distinct = false;
    oa.stype = agg.stype;
    oa.name = agg.name;
    out_aggs.push_back(oa);
  }
  return make_aggregate(j2, group_keys, out_aggs, rel->schema);
}

// ---------------------------------------------------------------------------
// pass: prune_columns (optimizer.py:442-597)
// ---------------------------------------------------------------------------

struct PruneResult {
  RelP rel;
  std::map<int64_t, int64_t> mapping;
};

PruneResult prune(const RelP& rel, const std::set<int64_t>& needed);

RelP prune_columns(const RelP& rel) {
  std::set<int64_t> all;
  for (size_t i = 0; i < rel->schema.size(); ++i) all.insert((int64_t)i);
  return prune(rel, all).rel;
}

std::map<int64_t, int64_t> identity_map(int64_t n) {
  std::map<int64_t, int64_t> m;
  for (int64_t i = 0; i < n; ++i) m[i] = i;
  return m;
}

PruneResult prune(const RelP& rel, const std::set<int64_t>& needed) {
  if (rel->kind == Rel::SCAN) {
    std::vector<int64_t> keep(needed.begin(), needed.end());
    if (keep.empty() && !rel->schema.empty()) keep = {0};
    std::vector<Field> new_schema;
    std::map<int64_t, int64_t> mapping;
    for (size_t i = 0; i < keep.size(); ++i) {
      new_schema.push_back(rel->schema[keep[i]]);
      mapping[keep[i]] = (int64_t)i;
    }
    auto n = std::make_shared<Rel>();
    n->kind = Rel::SCAN;
    n->schema_name = rel->schema_name;
    n->table_name = rel->table_name;
    n->schema = std::move(new_schema);
    return {n, mapping};
  }

  if (rel->kind == Rel::PROJECT) {
    std::vector<int64_t> keep(needed.begin(), needed.end());
    if (keep.empty() && !rel->exprs.empty()) keep = {0};
    std::set<int64_t> child_needed;
    for (int64_t i : keep)
      for (int64_t r : rex_inputs(rel->exprs[i])) child_needed.insert(r);
    PruneResult cr = prune(rel->input, child_needed);
    std::vector<RexP> new_exprs;
    std::vector<Field> new_schema;
    std::map<int64_t, int64_t> mapping;
    for (size_t i = 0; i < keep.size(); ++i) {
      new_exprs.push_back(remap_rex(rel->exprs[keep[i]], cr.mapping));
      new_schema.push_back(rel->schema[keep[i]]);
      mapping[keep[i]] = (int64_t)i;
    }
    return {make_project(cr.rel, std::move(new_exprs), std::move(new_schema)),
            mapping};
  }

  if (rel->kind == Rel::FILTER) {
    std::set<int64_t> child_needed = needed;
    for (int64_t r : rex_inputs(rel->condition)) child_needed.insert(r);
    PruneResult cr = prune(rel->input, child_needed);
    RexP cond = remap_rex(rel->condition, cr.mapping);
    std::vector<int64_t> keep;
    if (!needed.empty()) {
      keep.assign(needed.begin(), needed.end());
    } else {
      for (const auto& kv : cr.mapping) keep.push_back(kv.first);
    }
    std::vector<Field> new_schema;
    for (int64_t i : keep) new_schema.push_back(rel->schema[i]);
    std::vector<int64_t> cmap_keys;
    for (const auto& kv : cr.mapping) cmap_keys.push_back(kv.first);
    bool identity = cmap_keys == keep;
    if (identity) {
      for (size_t j = 0; j < keep.size(); ++j)
        if (cr.mapping.at(keep[j]) != (int64_t)j) { identity = false; break; }
    }
    std::map<int64_t, int64_t> out_map;
    for (size_t j = 0; j < keep.size(); ++j) out_map[keep[j]] = (int64_t)j;
    if (identity)
      return {make_filter(cr.rel, cond, new_schema), out_map};
    RelP filt = make_filter(cr.rel, cond, cr.rel->schema);
    std::vector<RexP> exprs;
    for (int64_t i : keep)
      exprs.push_back(
          Rex::input_ref(cr.mapping.at(i), rel->schema[i].stype));
    RelP proj = make_project(filt, std::move(exprs), new_schema);
    return {proj, out_map};
  }

  if (rel->kind == Rel::AGG) {
    int64_t n_keys = (int64_t)rel->group_keys.size();
    std::vector<int64_t> used_aggs;
    for (int64_t i : needed)
      if (i >= n_keys) used_aggs.push_back(i - n_keys);
    std::sort(used_aggs.begin(), used_aggs.end());
    std::set<int64_t> child_needed(rel->group_keys.begin(),
                                   rel->group_keys.end());
    for (int64_t ai : used_aggs) {
      for (int64_t a : rel->aggs[ai].args) child_needed.insert(a);
      if (rel->aggs[ai].has_filter)
        child_needed.insert(rel->aggs[ai].filter_arg);
    }
    PruneResult cr = prune(rel->input, child_needed);
    std::vector<int64_t> new_keys;
    for (int64_t k : rel->group_keys) new_keys.push_back(cr.mapping.at(k));
    std::vector<AggCall> new_aggs;
    for (int64_t ai : used_aggs) {
      const AggCall& a = rel->aggs[ai];
      AggCall na = a;
      na.args.clear();
      for (int64_t x : a.args) na.args.push_back(cr.mapping.at(x));
      if (a.has_filter) na.filter_arg = cr.mapping.at(a.filter_arg);
      new_aggs.push_back(na);
    }
    std::vector<Field> new_schema(rel->schema.begin(),
                                  rel->schema.begin() + n_keys);
    for (int64_t ai : used_aggs)
      new_schema.push_back(rel->schema[n_keys + ai]);
    std::map<int64_t, int64_t> mapping;
    for (int64_t i = 0; i < n_keys; ++i) mapping[i] = i;
    for (size_t j = 0; j < used_aggs.size(); ++j)
      mapping[n_keys + used_aggs[j]] = n_keys + (int64_t)j;
    return {make_aggregate(cr.rel, new_keys, new_aggs, new_schema), mapping};
  }

  if (rel->kind == Rel::JOIN) {
    int64_t nl = (int64_t)rel->left->schema.size();
    std::set<int64_t> all_needed = needed;
    if (rel->condition)
      for (int64_t r : rex_inputs(rel->condition)) all_needed.insert(r);
    std::set<int64_t> left_needed, right_needed;
    for (int64_t i : all_needed) {
      if (i < nl)
        left_needed.insert(i);
      else
        right_needed.insert(i - nl);
    }
    PruneResult lr = prune(rel->left, left_needed);
    PruneResult rr = prune(rel->right, right_needed);
    int64_t new_nl = (int64_t)lr.rel->schema.size();
    std::map<int64_t, int64_t> mapping;
    for (const auto& kv : lr.mapping) mapping[kv.first] = kv.second;
    for (const auto& kv : rr.mapping)
      mapping[nl + kv.first] = new_nl + kv.second;
    RexP cond =
        rel->condition ? remap_rex(rel->condition, mapping) : nullptr;
    std::vector<Field> new_schema;
    std::map<int64_t, int64_t> out_mapping;
    if (rel->join_type == "SEMI" || rel->join_type == "ANTI") {
      for (const auto& kv : lr.mapping)
        new_schema.push_back(rel->schema[kv.first]);
      out_mapping = lr.mapping;
    } else {
      for (const auto& kv : lr.mapping)
        new_schema.push_back(rel->schema[kv.first]);
      for (const auto& kv : rr.mapping)
        new_schema.push_back(rel->schema[nl + kv.first]);
      out_mapping = mapping;
    }
    RelP out = make_join(lr.rel, rr.rel, rel->join_type, cond, new_schema,
                         rel->null_aware);
    return {out, out_mapping};
  }

  if (rel->kind == Rel::SORT) {
    std::set<int64_t> child_needed = needed;
    for (const auto& c : rel->collation) child_needed.insert(c.index);
    PruneResult cr = prune(rel->input, child_needed);
    std::vector<SortCollation> coll;
    for (const auto& c : rel->collation) {
      SortCollation nc = c;
      nc.index = cr.mapping.at(c.index);
      coll.push_back(nc);
    }
    std::vector<Field> new_schema;
    for (const auto& kv : cr.mapping) new_schema.push_back(rel->schema[kv.first]);
    auto n = std::make_shared<Rel>(*rel);
    n->input = cr.rel;
    n->collation = std::move(coll);
    n->schema = std::move(new_schema);
    return {n, cr.mapping};
  }

  if (rel->kind == Rel::WINDOW) {
    int64_t n_in = (int64_t)rel->input->schema.size();
    std::vector<int64_t> used_calls;
    for (int64_t i : needed)
      if (i >= n_in) used_calls.push_back(i - n_in);
    std::sort(used_calls.begin(), used_calls.end());
    std::set<int64_t> child_needed;
    for (int64_t i : needed)
      if (i < n_in) child_needed.insert(i);
    for (int64_t ci : used_calls) {
      const WindowCall& c = rel->calls[ci];
      for (int64_t a : c.args) child_needed.insert(a);
      for (int64_t p : c.partition) child_needed.insert(p);
      for (const auto& k : c.order) child_needed.insert(k.index);
    }
    PruneResult cr = prune(rel->input, child_needed);
    std::vector<WindowCall> new_calls;
    for (int64_t ci : used_calls) {
      const WindowCall& c = rel->calls[ci];
      WindowCall nc = c;
      nc.args.clear();
      for (int64_t a : c.args) nc.args.push_back(cr.mapping.at(a));
      nc.partition.clear();
      for (int64_t p : c.partition) nc.partition.push_back(cr.mapping.at(p));
      nc.order.clear();
      for (const auto& k : c.order) {
        SortCollation nk = k;
        nk.index = cr.mapping.at(k.index);
        nc.order.push_back(nk);
      }
      new_calls.push_back(nc);
    }
    std::vector<Field> new_schema = cr.rel->schema;
    for (int64_t ci : used_calls)
      new_schema.push_back(rel->schema[n_in + ci]);
    std::map<int64_t, int64_t> mapping = cr.mapping;
    for (size_t j = 0; j < used_calls.size(); ++j)
      mapping[n_in + used_calls[j]] =
          (int64_t)cr.rel->schema.size() + (int64_t)j;
    auto n = std::make_shared<Rel>(*rel);
    n->input = cr.rel;
    n->calls = std::move(new_calls);
    n->schema = std::move(new_schema);
    return {n, mapping};
  }

  if (rel->kind == Rel::UNION || rel->kind == Rel::INTERSECT ||
      rel->kind == Rel::EXCEPT) {
    std::vector<RelP> new_inputs;
    for (const auto& i : rel->set_inputs) {
      std::set<int64_t> all;
      for (size_t k = 0; k < i->schema.size(); ++k) all.insert((int64_t)k);
      new_inputs.push_back(prune(i, all).rel);
    }
    RelP out = rel->with_inputs(new_inputs);
    return {out, identity_map((int64_t)rel->schema.size())};
  }

  if (rel->kind == Rel::SAMPLE) {
    PruneResult cr = prune(rel->input, needed);
    auto n = std::make_shared<Rel>(*rel);
    n->input = cr.rel;
    n->schema = cr.rel->schema;
    return {n, cr.mapping};
  }

  // default (VALUES): require everything below, identity above
  RelP out = rel;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> new_inputs;
    for (const auto& i : ins) {
      std::set<int64_t> all;
      for (size_t k = 0; k < i->schema.size(); ++k) all.insert((int64_t)k);
      new_inputs.push_back(prune(i, all).rel);
    }
    out = rel->with_inputs(new_inputs);
  }
  return {out, identity_map((int64_t)out->schema.size())};
}

// ---------------------------------------------------------------------------
// optimize_subplans + driver (optimizer.py:955-994)
// ---------------------------------------------------------------------------

RexP optimize_rex_subplans(const RexP& r) {
  if (r->kind == Rex::SUBQ) {
    auto n = std::make_shared<Rex>(*r);
    n->plan = optimize_plan(r->plan, true);
    return n;
  }
  if (r->kind == Rex::CALL) {
    std::vector<RexP> ops;
    bool changed = false;
    for (const auto& o : r->operands) {
      RexP n = optimize_rex_subplans(o);
      if (n != o) changed = true;
      ops.push_back(n);
    }
    if (!changed) return r;
    auto n = std::make_shared<Rex>(*r);
    n->operands = std::move(ops);
    return n;
  }
  return r;
}

RelP optimize_subplans(const RelP& rel0) {
  RelP rel = rel0;
  auto ins = rel->inputs();
  if (!ins.empty()) {
    std::vector<RelP> ni;
    for (const auto& i : ins) ni.push_back(optimize_subplans(i));
    rel = rel->with_inputs(ni);
  }
  if (rel->kind == Rel::PROJECT) {
    std::vector<RexP> exprs;
    bool changed = false;
    for (const auto& e : rel->exprs) {
      RexP n = optimize_rex_subplans(e);
      if (n != e) changed = true;
      exprs.push_back(n);
    }
    if (changed) return make_project(rel->input, std::move(exprs), rel->schema);
  } else if (rel->kind == Rel::FILTER) {
    RexP n = optimize_rex_subplans(rel->condition);
    if (n != rel->condition) return make_filter(rel->input, n, rel->schema);
  } else if (rel->kind == Rel::JOIN && rel->condition) {
    RexP n = optimize_rex_subplans(rel->condition);
    if (n != rel->condition)
      return make_join(rel->left, rel->right, rel->join_type, n,
                       rel->schema, rel->null_aware);
  }
  return rel;
}

}  // namespace

RelP optimize_plan(RelP plan, bool enable_pruning) {
  // PASSES (optimizer.py:955-959)
  plan = merge_filters(plan);
  plan = factor_or_predicates(plan);
  plan = push_filters(plan);
  plan = merge_filters(plan);
  plan = reorder_joins(plan);
  plan = push_filters(plan);
  plan = merge_filters(plan);
  plan = push_join_side_conditions(plan);
  plan = push_filters(plan);
  plan = merge_filters(plan);
  plan = rewrite_exist_test_joins(plan);
  plan = aggregate_through_join(plan);
  plan = merge_projects(plan);
  plan = optimize_subplans(plan);
  if (enable_pruning) {
    plan = prune_columns(plan);
    plan = merge_projects(plan);
  }
  return plan;
}

}  // namespace dsql
