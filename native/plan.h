// Bound logical plans for the native optimizer — the C++ mirror of
// dask_sql_tpu/plan/nodes.py (same node vocabulary, same field meanings).
// Nodes are immutable and shared (shared_ptr); every rewrite builds new
// nodes, mirroring the Python passes' with_inputs/dataclass style.
//
// Wire format (Python bridge: dask_sql_tpu/plan/native_planner.py):
//   SqlType  [name, prec|null, scale|null, nullable]
//   Field    [name, SqlType]
//   Rex      ["in", index, SqlType]
//            ["lit", tag, value, SqlType]     tag: "n" | "b" | "i" | "f" | "s"
//            ["call", op, [Rex...], SqlType, info(SqlType)|null]
//            ["subq", Rel, SqlType]
//   Rel      object with "k" discriminator — see from_json/to_json.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "json.h"

namespace dsql {

struct PlanError : std::runtime_error {
  explicit PlanError(const std::string& m) : std::runtime_error(m) {}
};

struct SqlType {
  std::string name;
  bool has_prec = false;
  int64_t prec = 0;
  bool has_scale = false;
  int64_t scale = 0;
  bool nullable = true;

  bool operator==(const SqlType& o) const {
    return name == o.name && has_prec == o.has_prec && prec == o.prec &&
           has_scale == o.has_scale && scale == o.scale &&
           nullable == o.nullable;
  }
  bool is_floating() const {
    return name == "FLOAT" || name == "DOUBLE" || name == "REAL" ||
           name == "DECIMAL";
  }
};

struct Field {
  std::string name;
  SqlType stype;
};

struct Rel;
using RelP = std::shared_ptr<const Rel>;

struct Rex;
using RexP = std::shared_ptr<const Rex>;

struct Rex {
  enum Kind { INPUT, LIT, CALL, SUBQ } kind = INPUT;
  SqlType stype;

  // INPUT
  int64_t index = 0;

  // LIT
  enum LKind { L_NULL, L_BOOL, L_INT, L_DBL, L_STR } lkind = L_NULL;
  bool bval = false;
  int64_t ival = 0;
  double dval = 0.0;
  std::string sval;

  // CALL
  std::string op;
  std::vector<RexP> operands;
  bool has_info = false;
  SqlType info;

  // SUBQ
  RelP plan;

  static RexP input_ref(int64_t idx, const SqlType& t);
  static RexP literal_bool(bool v, const SqlType& t);
  static RexP literal_int(int64_t v, const SqlType& t);
  static RexP call(const std::string& op, std::vector<RexP> ops,
                   const SqlType& t);
  static RexP call_info(const std::string& op, std::vector<RexP> ops,
                        const SqlType& t, const SqlType& info);

  bool is_true_literal() const {
    return kind == LIT && lkind == L_BOOL && bval;
  }
};

bool rex_equal(const RexP& a, const RexP& b);

struct AggCall {
  std::string op;
  std::vector<int64_t> args;
  bool distinct = false;
  SqlType stype;
  std::string name;
  bool has_filter = false;
  int64_t filter_arg = 0;
};

struct SortCollation {
  int64_t index = 0;
  bool ascending = true;
  int nulls_first = -1;  // -1 = None (postgres default), 0 = false, 1 = true
};

struct WindowCall {
  std::string op;
  std::vector<int64_t> args;
  std::vector<int64_t> partition;
  std::vector<SortCollation> order;
  JVP frame;  // opaque (round-tripped untouched)
  SqlType stype;
  std::string name;
};

struct Rel {
  enum Kind {
    SCAN, PROJECT, FILTER, AGG, JOIN, SORT,
    UNION, INTERSECT, EXCEPT, VALUES, WINDOW, SAMPLE
  } kind = SCAN;
  std::vector<Field> schema;

  // SCAN
  std::string schema_name, table_name;
  // PROJECT
  std::vector<RexP> exprs;
  // FILTER / JOIN condition (null allowed on JOIN)
  RexP condition;
  // AGG
  std::vector<int64_t> group_keys;
  std::vector<AggCall> aggs;
  // JOIN
  RelP left, right;
  std::string join_type = "INNER";
  bool null_aware = false;
  // single-input nodes (PROJECT/FILTER/AGG/SORT/WINDOW/SAMPLE)
  RelP input;
  // SORT
  std::vector<SortCollation> collation;
  bool has_limit = false;
  int64_t limit = 0;
  bool has_offset = false;
  int64_t offset = 0;
  // set ops
  std::vector<RelP> set_inputs;
  bool all_flag = false;
  // VALUES
  std::vector<std::vector<RexP>> rows;
  // WINDOW
  std::vector<WindowCall> calls;
  // SAMPLE
  std::string method = "BERNOULLI";
  double percentage = 100.0;
  bool has_seed = false;
  int64_t seed = 0;

  std::vector<RelP> inputs() const;
  RelP with_inputs(const std::vector<RelP>& ins) const;
};

// construction helpers (mirror the Python dataclass constructors)
RelP make_project(RelP in, std::vector<RexP> exprs, std::vector<Field> schema);
RelP make_filter(RelP in, RexP cond, std::vector<Field> schema);
RelP make_join(RelP l, RelP r, const std::string& jt, RexP cond,
               std::vector<Field> schema, bool null_aware);
RelP make_aggregate(RelP in, std::vector<int64_t> gk, std::vector<AggCall> aggs,
                    std::vector<Field> schema);

// wire conversion
SqlType type_from_json(const JVP& v);
JVP type_to_json(const SqlType& t);
RexP rex_from_json(const JVP& v);
JVP rex_to_json(const RexP& r);
RelP rel_from_json(const JVP& v);
JVP rel_to_json(const RelP& r);

// rex utilities (mirror nodes.py)
void rex_inputs(const RexP& r, std::vector<int64_t>& out);
std::vector<int64_t> rex_inputs(const RexP& r);
RexP remap_rex(const RexP& r, const std::map<int64_t, int64_t>& mapping);

// the optimizer entry (optimizer.cpp)
RelP optimize_plan(RelP plan, bool enable_pruning);

}  // namespace dsql
