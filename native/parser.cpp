#include "parser.h"

#include <cctype>
#include <cmath>
#include <initializer_list>
#include <set>
#include <sstream>
#include <string>
#include <vector>

#include "lexer.h"

namespace dsql {

std::string json_quote(const std::string& s) {
  std::string out = "\"";
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;
        }
    }
  }
  out += '"';
  return out;
}

namespace {

// Words that terminate expressions / cannot be bare identifiers in most spots
// (must stay in lock-step with RESERVED in dask_sql_tpu/sql/parser.py).
const std::set<std::string> kReserved = {
    "SELECT", "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET",
    "UNION", "INTERSECT", "EXCEPT", "JOIN", "INNER", "LEFT", "RIGHT", "FULL",
    "CROSS", "ON", "USING", "AS", "AND", "OR", "NOT", "CASE", "WHEN", "THEN",
    "ELSE", "END", "IS", "NULL", "TRUE", "FALSE", "BETWEEN", "IN", "LIKE",
    "ILIKE", "SIMILAR", "EXISTS", "DISTINCT", "ALL", "ANY", "SOME", "BY",
    "ASC", "DESC", "NULLS", "FIRST", "LAST", "CAST", "INTERVAL", "CREATE",
    "DROP", "SHOW", "DESCRIBE", "ANALYZE", "WITH", "VALUES", "OVER",
    "PARTITION", "TABLESAMPLE", "FETCH", "FILTER", "TO", "FOR",
    "NATURAL",  // else the table-alias rule swallows it before join parsing
};

const std::set<std::string> kComparisons = {"=", "<>", "!=", "<", "<=", ">", ">="};
const std::set<std::string> kJoinTypes = {"INNER", "LEFT", "RIGHT", "FULL", "CROSS"};

// ----------------------------------------------------------------- JSON utils

std::string jstr(const std::string& s) { return json_quote(s); }

// Emit a SQL NUMBER token verbatim as a JSON number.  json.loads applies the
// same int-vs-float rule as the Python parser's _number_value ('.'/'e' =>
// float), so round-tripping the raw text preserves exact semantics, incl.
// arbitrary-precision integers.  "1." / ".5" / "1.e5" are valid SQL but not
// valid JSON; pad with a zero (same numeric value).
std::string jnum(std::string t) {
  if (!t.empty() && t[0] == '.') t = "0" + t;
  size_t d = t.find('.');
  if (d != std::string::npos &&
      (d + 1 == t.size() || !std::isdigit((unsigned char)t[d + 1])))
    t.insert(d + 1, "0");
  return t;
}

bool number_is_float(const std::string& t) {
  return t.find('.') != std::string::npos || t.find('e') != std::string::npos ||
         t.find('E') != std::string::npos;
}

std::string join(const std::vector<std::string>& items, const char* sep = ",") {
  std::string out;
  for (size_t i = 0; i < items.size(); ++i) {
    if (i) out += sep;
    out += items[i];
  }
  return out;
}

std::string jarr(const std::vector<std::string>& items) {
  return "[" + join(items) + "]";
}

std::string jstrarr(const std::vector<std::string>& raw) {
  std::vector<std::string> q;
  q.reserve(raw.size());
  for (const auto& s : raw) q.push_back(jstr(s));
  return jarr(q);
}

// ------------------------------------------------------------------- parser

class Parser {
 public:
  explicit Parser(const std::string& sql) : sql_(sql), tokens_(tokenize(sql)) {}

  std::string parse_statements() {
    std::vector<std::string> stmts;
    while (cur().kind != Tk::END) {
      stmts.push_back(parse_statement());
      while (eat_op(";")) {
      }
    }
    return jarr(stmts);
  }

 private:
  const std::string& sql_;
  std::vector<Token> tokens_;
  size_t i_ = 0;

  // --------------------------------------------------------------- helpers
  // Clamped like peek(): tokenize() always appends an END token, so running
  // past the end must keep returning it, never read out of bounds.
  const Token& cur() const {
    return tokens_[i_ < tokens_.size() ? i_ : tokens_.size() - 1];
  }
  const Token& peek(size_t k = 0) const {
    size_t j = i_ + k;
    if (j >= tokens_.size()) j = tokens_.size() - 1;
    return tokens_[j];
  }
  bool at_kw(std::initializer_list<const char*> words, size_t k = 0) const {
    const Token& t = peek(k);
    if (t.kind != Tk::IDENT) return false;
    for (const char* w : words)
      if (t.upper == w) return true;
    return false;
  }
  bool at_op(std::initializer_list<const char*> ops, size_t k = 0) const {
    const Token& t = peek(k);
    if (t.kind != Tk::OP) return false;
    for (const char* o : ops)
      if (t.text == o) return true;
    return false;
  }
  std::string eat_kw(std::initializer_list<const char*> words) {
    if (at_kw(words)) {
      std::string w = cur().upper;
      ++i_;
      return w;
    }
    return "";
  }
  std::string eat_op(std::initializer_list<const char*> ops) {
    if (at_op(ops)) {
      std::string o = cur().text;
      ++i_;
      return o;
    }
    return "";
  }
  bool eat_op(const char* op) { return !eat_op({op}).empty(); }
  std::string expect_kw(std::initializer_list<const char*> words) {
    std::string w = eat_kw(words);
    if (w.empty()) {
      std::vector<std::string> ws(words.begin(), words.end());
      error("Expected " + join(ws, " or "));
    }
    return w;
  }
  void expect_op(const char* op) {
    if (!eat_op(op)) error(std::string("Expected '") + op + "'");
  }
  [[noreturn]] void error(const std::string& message) const { error(message, cur()); }
  [[noreturn]] void error(const std::string& message, const Token& t) const {
    std::string got = t.kind != Tk::END ? t.text : "end of statement";
    int width = (int)t.text.size();
    throw ParseError{message + " (got '" + got + "')", t.line, t.col,
                     width > 1 ? width : 1};
  }

  std::string identifier(const char* what = "identifier") {
    const Token& t = cur();
    if (t.kind == Tk::QIDENT) {
      ++i_;
      return t.text;
    }
    if (t.kind == Tk::IDENT && !kReserved.count(t.upper)) {
      ++i_;
      return t.text;
    }
    error(std::string("Expected ") + what);
  }
  std::string any_identifier() {
    const Token& t = cur();
    if (t.kind == Tk::IDENT || t.kind == Tk::QIDENT) {
      ++i_;
      return t.text;
    }
    error("Expected identifier");
  }
  std::vector<std::string> compound_identifier() {
    std::vector<std::string> parts{identifier()};
    while (eat_op(".")) parts.push_back(any_identifier());
    return parts;
  }
  std::string pos_of(const Token& t) const {
    return "[" + std::to_string(t.line) + "," + std::to_string(t.col) + "]";
  }
  std::string pos_here() const { return pos_of(cur()); }

  // ------------------------------------------------------------ statements
  std::string parse_statement() {
    const Token& t = cur();
    if (t.kind == Tk::IDENT) {
      const std::string& u = t.upper;
      if (u == "CREATE") return parse_create();
      if (u == "DROP") return parse_drop();
      if (u == "SHOW") return parse_show();
      if (u == "DESCRIBE" || u == "DESC") return parse_describe();
      if (u == "ANALYZE") return parse_analyze();
      if (u == "USE") return parse_use();
      if (u == "EXPORT") return parse_export();
      if (u == "EXPLAIN") {
        std::string pos = pos_of(t);
        ++i_;
        return R"({"t":"ExplainStatement","query":)" + parse_query() +
               ",\"pos\":" + pos + "}";
      }
    }
    if ((t.kind == Tk::IDENT &&
         (t.upper == "SELECT" || t.upper == "WITH" || t.upper == "VALUES")) ||
        at_op({"("}))
      return R"({"t":"QueryStatement","query":)" + parse_query() + "}";
    error("Expected a SQL statement");
  }

  std::string parse_create() {
    std::string pos = pos_here();
    expect_kw({"CREATE"});
    bool or_replace = false;
    if (!eat_kw({"OR"}).empty()) {
      expect_kw({"REPLACE"});
      or_replace = true;
    }
    std::string kind = expect_kw({"TABLE", "VIEW", "MODEL", "SCHEMA", "EXPERIMENT"});
    bool if_not_exists = false;
    if (!eat_kw({"IF"}).empty()) {
      expect_kw({"NOT"});
      expect_kw({"EXISTS"});
      if_not_exists = true;
    }
    auto flags = [&] {
      return std::string(",\"if_not_exists\":") + (if_not_exists ? "true" : "false") +
             ",\"or_replace\":" + (or_replace ? "true" : "false") + ",\"pos\":" + pos;
    };
    if (kind == "SCHEMA") {
      std::string name = identifier("schema name");
      return R"({"t":"CreateSchema","name":)" + jstr(name) + flags() + "}";
    }
    std::string name = jstrarr(compound_identifier());
    if (kind == "MODEL" || kind == "EXPERIMENT") {
      std::string kwargs = "{}";
      if (!eat_kw({"WITH"}).empty()) kwargs = parse_kwargs();
      expect_kw({"AS"});
      std::string query = parse_parenthesized_or_plain_query();
      const char* cls = kind == "MODEL" ? "CreateModel" : "CreateExperiment";
      return std::string("{\"t\":\"") + cls + "\",\"name\":" + name +
             ",\"kwargs\":" + kwargs + ",\"query\":" + query + flags() + "}";
    }
    // TABLE or VIEW
    if (!eat_kw({"WITH"}).empty()) {
      std::string kwargs = parse_kwargs();
      return R"({"t":"CreateTable","name":)" + name + ",\"kwargs\":" + kwargs +
             flags() + "}";
    }
    expect_kw({"AS"});
    std::string query = parse_parenthesized_or_plain_query();
    return R"({"t":"CreateTableAs","name":)" + name + ",\"query\":" + query +
           flags() + ",\"view\":" + (kind == "VIEW" ? "true" : "false") + "}";
  }

  std::string parse_parenthesized_or_plain_query() {
    if (at_op({"("})) {
      expect_op("(");
      std::string q = parse_query();
      expect_op(")");
      return q;
    }
    return parse_query();
  }

  // kwargs dict syntax (reference utils.ftl:1-136): plain JSON object; MAP
  // values become {"__map__": [k,v,k,v...]} (keys may be non-strings).
  std::string parse_kwargs() {
    expect_op("(");
    std::vector<std::string> items;
    if (!at_op({")"})) {
      for (;;) {
        std::string key = any_identifier();
        expect_op("=");
        items.push_back(jstr(key) + ":" + parse_kwarg_value());
        if (!eat_op(",")) break;
      }
    }
    expect_op(")");
    return "{" + join(items) + "}";
  }

  std::string parse_kwarg_value() {
    const Token& t = cur();
    if (at_op({"("})) return parse_kwargs();  // nested dict (MULTISET form)
    if (at_kw({"ARRAY"})) {
      ++i_;
      expect_op("[");
      std::vector<std::string> vals;
      if (!at_op({"]"})) {
        for (;;) {
          vals.push_back(parse_kwarg_value());
          if (!eat_op(",")) break;
        }
      }
      expect_op("]");
      return jarr(vals);
    }
    if (at_kw({"MAP"})) {
      ++i_;
      expect_op("[");
      std::vector<std::string> items;
      if (!at_op({"]"})) {
        for (;;) {
          items.push_back(parse_kwarg_value());
          if (!eat_op(",")) break;
        }
      }
      expect_op("]");
      return R"({"__map__":)" + jarr(items) + "}";
    }
    if (t.kind == Tk::STRING) {
      ++i_;
      return jstr(t.text);
    }
    if (t.kind == Tk::NUMBER) {
      ++i_;
      return jnum(t.text);
    }
    if (eat_op("-")) {
      const Token& t2 = cur();
      if (t2.kind == Tk::NUMBER) {
        ++i_;
        return "-" + jnum(t2.text);
      }
      error("Expected number");
    }
    if (t.kind == Tk::IDENT) {
      std::string u = t.upper;
      ++i_;
      if (u == "TRUE") return "true";
      if (u == "FALSE") return "false";
      if (u == "NULL") return "null";
      return jstr(t.text);  // bare identifier value, e.g. format = csv
    }
    error("Expected kwarg value");
  }

  std::string parse_drop() {
    std::string pos = pos_here();
    expect_kw({"DROP"});
    std::string kind = expect_kw({"TABLE", "MODEL", "SCHEMA", "VIEW"});
    bool if_exists = false;
    if (!eat_kw({"IF"}).empty()) {
      expect_kw({"EXISTS"});
      if_exists = true;
    }
    std::string fl = std::string(",\"if_exists\":") + (if_exists ? "true" : "false") +
                     ",\"pos\":" + pos + "}";
    if (kind == "SCHEMA")
      return R"({"t":"DropSchema","name":)" + jstr(identifier()) + fl;
    std::string name = jstrarr(compound_identifier());
    if (kind == "MODEL") return R"({"t":"DropModel","name":)" + name + fl;
    return R"({"t":"DropTable","name":)" + name + fl;
  }

  std::string parse_show() {
    std::string pos = pos_here();
    expect_kw({"SHOW"});
    std::string kind = expect_kw({"SCHEMAS", "TABLES", "COLUMNS", "MODELS"});
    if (kind == "SCHEMAS") {
      std::string like = "null";
      if (!eat_kw({"LIKE"}).empty()) {
        if (cur().kind != Tk::STRING)
          error("Expected a string literal after LIKE");
        like = jstr(cur().text);
        ++i_;
      }
      return R"({"t":"ShowSchemas","like":)" + like + ",\"pos\":" + pos + "}";
    }
    if (kind == "TABLES") {
      std::string schema = "null";
      if (!eat_kw({"FROM", "IN"}).empty()) schema = jstr(identifier());
      return R"({"t":"ShowTables","schema":)" + schema + ",\"pos\":" + pos + "}";
    }
    if (kind == "COLUMNS") {
      expect_kw({"FROM", "IN"});
      return R"({"t":"ShowColumns","table":)" + jstrarr(compound_identifier()) +
             ",\"pos\":" + pos + "}";
    }
    return R"({"t":"ShowModels","pos":)" + pos + "}";
  }

  std::string parse_describe() {
    std::string pos = pos_here();
    ++i_;  // DESCRIBE / DESC
    if (!eat_kw({"MODEL"}).empty())
      return R"({"t":"DescribeModel","name":)" + jstrarr(compound_identifier()) +
             ",\"pos\":" + pos + "}";
    eat_kw({"TABLE"});
    return R"({"t":"DescribeTable","table":)" + jstrarr(compound_identifier()) +
           ",\"pos\":" + pos + "}";
  }

  std::string parse_analyze() {
    std::string pos = pos_here();
    expect_kw({"ANALYZE"});
    expect_kw({"TABLE"});
    std::string table = jstrarr(compound_identifier());
    std::string columns = "null";
    expect_kw({"COMPUTE"});
    expect_kw({"STATISTICS"});
    if (!eat_kw({"FOR"}).empty()) {
      if (!eat_kw({"ALL"}).empty()) {
        expect_kw({"COLUMNS"});
      } else {
        expect_kw({"COLUMNS"});
        std::vector<std::string> cols{identifier()};
        while (eat_op(",")) cols.push_back(identifier());
        columns = jstrarr(cols);
      }
    }
    return R"({"t":"AnalyzeTable","table":)" + table + ",\"columns\":" + columns +
           ",\"pos\":" + pos + "}";
  }

  std::string parse_use() {
    std::string pos = pos_here();
    expect_kw({"USE"});
    expect_kw({"SCHEMA"});
    return R"({"t":"UseSchema","name":)" + jstr(identifier()) + ",\"pos\":" + pos + "}";
  }

  std::string parse_export() {
    std::string pos = pos_here();
    expect_kw({"EXPORT"});
    expect_kw({"MODEL"});
    std::string name = jstrarr(compound_identifier());
    std::string kwargs = "{}";
    if (!eat_kw({"WITH"}).empty()) kwargs = parse_kwargs();
    return R"({"t":"ExportModel","name":)" + name + ",\"kwargs\":" + kwargs +
           ",\"pos\":" + pos + "}";
  }

  // --------------------------------------------------------------- queries

  // A parsed query body, pre-assembly, so ORDER/LIMIT/OFFSET/CTEs can be
  // merged the same way the python parser mutates the dataclasses in
  // parse_query before the result is consumed.
  struct SelectParts {
    enum Kind { SELECT, SETOP, RAW } kind = RAW;
    // SELECT fields:
    std::string projections, distinct, from_, where, group_by, having, pos;
    std::vector<std::string> ctes;  // serialized [name, query] pairs
    // shared by SELECT and SETOP:
    std::string order_by = "[]", limit = "null", offset = "null";
    // SETOP: JSON prefix lacking order_by/limit/offset and the closing brace.
    std::string raw_prefix;
    // RAW: complete JSON (ValuesQuery)
    std::string raw;
  };

  std::string select_json(const SelectParts& s) {
    return R"({"t":"Select","projections":)" + s.projections +
           ",\"distinct\":" + s.distinct + ",\"from_\":" + s.from_ +
           ",\"where\":" + s.where + ",\"group_by\":" + s.group_by +
           ",\"having\":" + s.having + ",\"order_by\":" + s.order_by +
           ",\"limit\":" + s.limit + ",\"offset\":" + s.offset +
           ",\"ctes\":[" + join(s.ctes) + "],\"pos\":" + s.pos + "}";
  }

  // Serialize a SelectParts as a complete JSON node.
  std::string finish_parts(const SelectParts& p) {
    if (p.kind == SelectParts::SELECT) return select_json(p);
    if (p.kind == SelectParts::SETOP)
      return p.raw_prefix + ",\"order_by\":" + p.order_by + ",\"limit\":" + p.limit +
             ",\"offset\":" + p.offset + "}";
    return p.raw;
  }

  std::string parse_query() { return finish_parts(parse_query_parts()); }

  SelectParts parse_query_parts() {
    std::vector<std::string> ctes;  // [name, query] pairs
    if (at_kw({"WITH"})) {
      ++i_;
      for (;;) {
        std::string name = identifier("CTE name");
        expect_kw({"AS"});
        expect_op("(");
        ctes.push_back("[" + jstr(name) + "," + parse_query() + "]");
        expect_op(")");
        if (!eat_op(",")) break;
      }
    }
    SelectParts body = parse_set_expr();
    std::string order_by, limit, offset;
    parse_order_limit(order_by, limit, offset);

    if (body.kind == SelectParts::SELECT && body.order_by == "[]") {
      body.ctes.insert(body.ctes.begin(), ctes.begin(), ctes.end());
      body.order_by = order_by;
      if (body.limit == "null") body.limit = limit;
      if (body.offset == "null") body.offset = offset;
      return body;
    }
    bool raw_needs_wrap =
        body.kind == SelectParts::RAW &&
        (order_by != "[]" || limit != "null" || offset != "null");
    bool needs_wrap =
        (!ctes.empty() && body.kind != SelectParts::SELECT) || raw_needs_wrap;
    if (body.kind == SelectParts::SETOP && !needs_wrap) {
      body.order_by = order_by;
      body.limit = limit;
      body.offset = offset;
    }
    if (needs_wrap) {
      // wrap in a Select to carry the CTEs and/or outer ORDER BY/LIMIT
      SelectParts sel;
      sel.kind = SelectParts::SELECT;
      sel.projections = R"([[{"t":"Star","table":null,"pos":[0,0]},null]])";
      sel.distinct = "false";
      sel.from_ = R"({"t":"SubqueryRelation","query":)" + finish_parts(body) +
                  R"(,"alias":"__cte_body__","column_aliases":null,"pos":[0,0]})";
      sel.where = "null";
      sel.group_by = "null";
      sel.having = "null";
      sel.pos = "[0,0]";
      sel.ctes = ctes;
      sel.order_by = order_by;
      sel.limit = limit;
      sel.offset = offset;
      return sel;
    }
    return body;
  }

  void parse_order_limit(std::string& order_by, std::string& limit,
                         std::string& offset) {
    std::vector<std::string> keys;
    limit = "null";
    offset = "null";
    if (at_kw({"ORDER"})) {
      ++i_;
      expect_kw({"BY"});
      for (;;) {
        keys.push_back(parse_sort_key());
        if (!eat_op(",")) break;
      }
    }
    if (!eat_kw({"LIMIT"}).empty()) limit = parse_expr();
    if (!eat_kw({"OFFSET"}).empty()) {
      offset = parse_expr();
      eat_kw({"ROWS", "ROW"});
    }
    if (!eat_kw({"FETCH"}).empty()) {
      expect_kw({"FIRST", "NEXT"});
      limit = parse_expr();
      eat_kw({"ROWS", "ROW"});
      expect_kw({"ONLY"});
    }
    order_by = jarr(keys);
  }

  std::string parse_sort_key() {
    std::string e = parse_expr();
    bool asc = true;
    if (!eat_kw({"DESC"}).empty())
      asc = false;
    else
      eat_kw({"ASC"});
    std::string nulls_first = "null";
    if (!eat_kw({"NULLS"}).empty())
      nulls_first = expect_kw({"FIRST", "LAST"}) == "FIRST" ? "true" : "false";
    return R"({"t":"SortKey","expr":)" + e + ",\"ascending\":" +
           (asc ? "true" : "false") + ",\"nulls_first\":" + nulls_first + "}";
  }

  SelectParts parse_set_expr() { return parse_set_tail(parse_select_core()); }

  // chain set ops onto a parsed left-hand side (no-op if none follow)
  SelectParts parse_set_tail(SelectParts left) {
    for (;;) {
      std::string pos = pos_here();
      std::string op = eat_kw({"UNION", "INTERSECT", "EXCEPT", "MINUS"});
      if (op.empty()) return left;
      if (op == "MINUS") op = "EXCEPT";
      bool all = !eat_kw({"ALL"}).empty();
      if (!all) eat_kw({"DISTINCT"});
      SelectParts right = parse_select_core();
      std::string lj = finish_parts(left), rj = finish_parts(right);
      SelectParts so;
      so.kind = SelectParts::SETOP;
      so.raw_prefix = R"({"t":"SetOp","op":)" + jstr(op) + ",\"all\":" +
                      (all ? "true" : "false") + ",\"left\":" + lj +
                      ",\"right\":" + rj + ",\"pos\":" + pos;
      left = std::move(so);
    }
  }

  SelectParts parse_select_core() {
    SelectParts out;
    if (at_op({"("})) {
      expect_op("(");
      std::string q = parse_query();
      expect_op(")");
      out.raw = q;
      return out;
    }
    std::string pos = pos_here();
    if (at_kw({"VALUES"})) {
      ++i_;
      std::vector<std::string> rows;
      for (;;) {
        expect_op("(");
        std::vector<std::string> row{parse_expr()};
        while (eat_op(",")) row.push_back(parse_expr());
        expect_op(")");
        rows.push_back(jarr(row));
        if (!eat_op(",")) break;
      }
      out.raw = R"({"t":"ValuesQuery","rows":)" + jarr(rows) + ",\"pos\":" + pos + "}";
      return out;
    }
    if (at_kw({"WITH"})) {
      out.raw = parse_query();
      return out;
    }
    expect_kw({"SELECT"});
    bool distinct = false;
    if (!eat_kw({"DISTINCT"}).empty())
      distinct = true;
    else
      eat_kw({"ALL"});
    std::vector<std::string> projections;
    for (;;) {
      std::string proj_pos = pos_here();
      if (at_op({"*"})) {
        ++i_;
        projections.push_back(R"([{"t":"Star","table":null,"pos":)" + proj_pos +
                              "},null]");
      } else {
        std::string e = parse_expr();
        std::string alias = "null";
        if (!eat_kw({"AS"}).empty()) {
          alias = jstr(any_identifier());
        } else if (cur().kind == Tk::QIDENT ||
                   (cur().kind == Tk::IDENT && !kReserved.count(cur().upper))) {
          alias = jstr(cur().text);
          ++i_;
        }
        projections.push_back("[" + e + "," + alias + "]");
      }
      if (!eat_op(",")) break;
    }
    out.kind = SelectParts::SELECT;
    out.projections = jarr(projections);
    out.distinct = distinct ? "true" : "false";
    out.pos = pos;
    out.from_ = "null";
    out.where = "null";
    out.group_by = "null";
    out.having = "null";
    if (!eat_kw({"FROM"}).empty()) out.from_ = parse_relation();
    if (!eat_kw({"WHERE"}).empty()) out.where = parse_expr();
    if (at_kw({"GROUP"})) {
      ++i_;
      expect_kw({"BY"});
      std::vector<std::string> gb;
      for (;;) {
        if (eat_op("(")) {
          if (!eat_op(")")) {  // GROUP BY () = empty grouping set
            gb.push_back(parse_expr());
            while (eat_op(",")) gb.push_back(parse_expr());
            expect_op(")");
          }
        } else {
          gb.push_back(parse_expr());
        }
        if (!eat_op(",")) break;
      }
      out.group_by = jarr(gb);
    }
    if (!eat_kw({"HAVING"}).empty()) out.having = parse_expr();
    return out;
  }

  // -------------------------------------------------------------- relations
  std::string parse_relation() {
    std::string left = parse_table_factor();
    for (;;) {
      std::string pos = pos_here();
      if (eat_op(",")) {
        std::string right = parse_table_factor();
        left = R"({"t":"JoinRelation","left":)" + left + ",\"right\":" + right +
               R"(,"join_type":"CROSS","condition":null,"using":null,"pos":)" +
               pos + "}";
        continue;
      }
      std::string jt;
      bool natural = false;
      if (at_kw({"NATURAL"})) {
        ++i_;
        natural = true;
      }
      if (at_kw({"JOIN"})) {
        jt = "INNER";
        ++i_;
      } else if (at_kw({"INNER", "LEFT", "RIGHT", "FULL", "CROSS"})) {
        jt = cur().upper;
        ++i_;
        eat_kw({"OUTER"});
        expect_kw({"JOIN"});
      } else {
        if (natural) error("Expected JOIN after NATURAL");
        return left;
      }
      std::string right = parse_table_factor();
      std::string cond = "null";
      std::string using_ = "null";
      if (jt != "CROSS" && !natural) {
        if (!eat_kw({"ON"}).empty()) {
          cond = parse_expr();
        } else if (!eat_kw({"USING"}).empty()) {
          expect_op("(");
          std::vector<std::string> cols{identifier()};
          while (eat_op(",")) cols.push_back(identifier());
          expect_op(")");
          using_ = jstrarr(cols);
        } else {
          error("Expected ON or USING after JOIN");
        }
      }
      if (natural) using_ = jstr("NATURAL");  // resolved by the binder
      left = R"({"t":"JoinRelation","left":)" + left + ",\"right\":" + right +
             ",\"join_type\":" + jstr(jt) + ",\"condition\":" + cond +
             ",\"using\":" + using_ + ",\"pos\":" + pos + "}";
    }
  }

  std::string parse_table_factor() {
    std::string pos = pos_here();
    if (at_op({"("})) {
      expect_op("(");
      if (at_kw({"SELECT", "WITH", "VALUES"}) || at_op({"("})) {
        std::string q = parse_query();
        expect_op(")");
        std::string alias, cols;
        parse_alias(alias, cols);
        return R"({"t":"SubqueryRelation","query":)" + q + ",\"alias\":" + alias +
               ",\"column_aliases\":" + cols + ",\"pos\":" + pos + "}";
      }
      std::string rel = parse_relation();
      expect_op(")");
      return rel;
    }
    if (at_kw({"PREDICT"})) {
      ++i_;
      expect_op("(");
      expect_kw({"MODEL"});
      std::string model = jstrarr(compound_identifier());
      expect_op(",");
      std::string q = parse_query();
      expect_op(")");
      std::string alias, cols;
      parse_alias(alias, cols);
      return R"({"t":"PredictRelation","model":)" + model + ",\"query\":" + q +
             ",\"alias\":" + alias + ",\"pos\":" + pos + "}";
    }
    std::string parts = jstrarr(compound_identifier());
    std::string sample = "null";
    if (at_kw({"TABLESAMPLE"})) {
      ++i_;
      std::string method = expect_kw({"SYSTEM", "BERNOULLI"});
      expect_op("(");
      const Token& pct = cur();
      if (pct.kind != Tk::NUMBER) error("Expected sample percentage");
      ++i_;
      expect_op(")");
      std::string seed = "null";
      if (!eat_kw({"REPEATABLE"}).empty()) {
        expect_op("(");
        seed = cur().text;  // integer token
        ++i_;
        expect_op(")");
      }
      // pct serialized as float (python: float(text))
      std::string p = jnum(pct.text);
      if (!number_is_float(pct.text)) p += ".0";
      sample = "[" + jstr(method) + "," + p + "," + seed + "]";
    }
    std::string alias, cols;
    parse_alias(alias, cols);
    return R"({"t":"TableRef","parts":)" + parts + ",\"alias\":" + alias +
           ",\"column_aliases\":" + cols + ",\"sample\":" + sample +
           ",\"pos\":" + pos + "}";
  }

  void parse_alias(std::string& alias, std::string& cols) {
    alias = "null";
    cols = "null";
    if (!eat_kw({"AS"}).empty()) {
      alias = jstr(any_identifier());
    } else if (cur().kind == Tk::QIDENT ||
               (cur().kind == Tk::IDENT && !kReserved.count(cur().upper))) {
      alias = jstr(cur().text);
      ++i_;
    }
    if (alias != "null" && at_op({"("})) {
      expect_op("(");
      std::vector<std::string> cs{identifier()};
      while (eat_op(",")) cs.push_back(identifier());
      expect_op(")");
      cols = jstrarr(cs);
    }
  }

  // ------------------------------------------------------------ expressions
  std::string call2(const std::string& op, const std::string& a,
                    const std::string& b, const std::string& pos) {
    return R"({"t":"Call","op":)" + jstr(op) + ",\"args\":[" + a + "," + b +
           R"(],"distinct":false,"filter":null,"over":null,"pos":)" + pos + "}";
  }
  std::string call1(const std::string& op, const std::string& a,
                    const std::string& pos) {
    return R"({"t":"Call","op":)" + jstr(op) + ",\"args\":[" + a +
           R"(],"distinct":false,"filter":null,"over":null,"pos":)" + pos + "}";
  }
  std::string calln(const std::string& op, const std::vector<std::string>& args,
                    const std::string& pos) {
    return R"({"t":"Call","op":)" + jstr(op) + ",\"args\":" + jarr(args) +
           R"(,"distinct":false,"filter":null,"over":null,"pos":)" + pos + "}";
  }
  std::string lit_sym(const std::string& v) {
    return R"({"t":"Literal","value":)" + jstr(v) + R"(,"type_name":"SYMBOL","pos":[0,0]})";
  }

  std::string parse_expr() { return parse_or(); }

  std::string parse_or() {
    std::string left = parse_and();
    while (at_kw({"OR"})) {
      std::string pos = pos_here();
      ++i_;
      left = call2("OR", left, parse_and(), pos);
    }
    return left;
  }

  std::string parse_and() {
    std::string left = parse_not();
    while (at_kw({"AND"})) {
      std::string pos = pos_here();
      ++i_;
      left = call2("AND", left, parse_not(), pos);
    }
    return left;
  }

  std::string parse_not() {
    if (at_kw({"NOT"})) {
      std::string pos = pos_here();
      ++i_;
      return call1("NOT", parse_not(), pos);
    }
    return parse_predicate();
  }

  std::string parse_predicate() {
    std::string left = parse_additive_chain();
    for (;;) {
      std::string pos = pos_here();
      bool negated = false;
      size_t save = i_;
      if (at_kw({"NOT"})) {
        ++i_;
        negated = true;
      }
      const char* neg = negated ? "true" : "false";
      if (at_kw({"BETWEEN"})) {
        ++i_;
        eat_kw({"ASYMMETRIC"});
        bool sym = !eat_kw({"SYMMETRIC"}).empty();
        std::string low = parse_additive_chain();
        expect_kw({"AND"});
        std::string high = parse_additive_chain();
        left = R"({"t":"Between","expr":)" + left + ",\"low\":" + low +
               ",\"high\":" + high + ",\"negated\":" + neg +
               ",\"symmetric\":" + (sym ? "true" : "false") + ",\"pos\":" + pos + "}";
        continue;
      }
      if (at_kw({"IN"})) {
        ++i_;
        expect_op("(");
        if (at_kw({"SELECT", "WITH", "VALUES"})) {
          std::string q = parse_query();
          expect_op(")");
          left = R"({"t":"Subquery","query":)" + q +
                 R"(,"kind":"in","outer":)" + left + ",\"op\":null,\"negated\":" +
                 neg + ",\"pos\":" + pos + "}";
        } else {
          std::vector<std::string> vals{parse_expr()};
          while (eat_op(",")) vals.push_back(parse_expr());
          expect_op(")");
          left = R"({"t":"InList","expr":)" + left + ",\"values\":" + jarr(vals) +
                 ",\"negated\":" + neg + ",\"pos\":" + pos + "}";
        }
        continue;
      }
      if (at_kw({"LIKE", "ILIKE"})) {
        std::string kind = cur().upper;
        ++i_;
        std::string pattern = parse_additive_chain();
        std::string escape = "null";
        if (!eat_kw({"ESCAPE"}).empty()) escape = parse_additive_chain();
        left = R"({"t":"Like","expr":)" + left + ",\"pattern\":" + pattern +
               ",\"escape\":" + escape + ",\"negated\":" + neg +
               ",\"kind\":" + jstr(kind) + ",\"pos\":" + pos + "}";
        continue;
      }
      if (at_kw({"SIMILAR"})) {
        ++i_;
        expect_kw({"TO"});
        std::string pattern = parse_additive_chain();
        std::string escape = "null";
        if (!eat_kw({"ESCAPE"}).empty()) escape = parse_additive_chain();
        left = R"({"t":"Like","expr":)" + left + ",\"pattern\":" + pattern +
               ",\"escape\":" + escape + ",\"negated\":" + neg +
               R"(,"kind":"SIMILAR","pos":)" + pos + "}";
        continue;
      }
      if (negated) {
        i_ = save;
        return left;
      }
      if (at_kw({"IS"})) {
        ++i_;
        bool n2 = !eat_kw({"NOT"}).empty();
        const char* neg2 = n2 ? "true" : "false";
        if (!eat_kw({"NULL"}).empty()) {
          left = R"({"t":"IsNull","expr":)" + left + ",\"negated\":" + neg2 +
                 ",\"pos\":" + pos + "}";
        } else if (!eat_kw({"TRUE"}).empty()) {
          left = R"({"t":"IsBool","expr":)" + left + ",\"value\":true,\"negated\":" +
                 neg2 + ",\"pos\":" + pos + "}";
        } else if (!eat_kw({"FALSE"}).empty()) {
          left = R"({"t":"IsBool","expr":)" + left + ",\"value\":false,\"negated\":" +
                 neg2 + ",\"pos\":" + pos + "}";
        } else if (!eat_kw({"UNKNOWN"}).empty()) {
          left = R"({"t":"IsNull","expr":)" + left + ",\"negated\":" + neg2 +
                 ",\"pos\":" + pos + "}";
        } else if (!eat_kw({"DISTINCT"}).empty()) {
          expect_kw({"FROM"});
          std::string right = parse_additive_chain();
          left = R"({"t":"IsDistinctFrom","left":)" + left + ",\"right\":" + right +
                 ",\"negated\":" + neg2 + ",\"pos\":" + pos + "}";
        } else {
          error("Expected NULL/TRUE/FALSE/DISTINCT after IS");
        }
        continue;
      }
      if (cur().kind == Tk::OP && kComparisons.count(cur().text)) {
        std::string op = cur().text;
        if (op == "!=") op = "<>";
        ++i_;
        if (at_kw({"ANY", "SOME", "ALL"})) {
          std::string quant = cur().upper;
          ++i_;
          expect_op("(");
          std::string q = parse_query();
          expect_op(")");
          left = R"({"t":"Subquery","query":)" + q + ",\"kind\":" +
                 jstr(quant == "ALL" ? "all" : "any") + ",\"outer\":" + left +
                 ",\"op\":" + jstr(op) + ",\"negated\":false,\"pos\":" + pos + "}";
        } else {
          left = call2(op, left, parse_additive_chain(), pos);
        }
        continue;
      }
      return left;
    }
  }

  std::string parse_additive_chain() { return parse_concat(); }

  std::string parse_concat() {
    std::string left = parse_add();
    while (at_op({"||"})) {
      std::string pos = pos_here();
      ++i_;
      left = call2("||", left, parse_add(), pos);
    }
    return left;
  }

  std::string parse_add() {
    std::string left = parse_mul();
    while (at_op({"+", "-"})) {
      std::string pos = pos_here();
      std::string op = cur().text;
      ++i_;
      left = call2(op, left, parse_mul(), pos);
    }
    return left;
  }

  std::string parse_mul() {
    std::string left = parse_unary();
    while (at_op({"*", "/", "%"})) {
      std::string pos = pos_here();
      std::string op = cur().text;
      ++i_;
      left = call2(op, left, parse_unary(), pos);
    }
    return left;
  }

  std::string parse_unary() {
    std::string pos = pos_here();
    if (eat_op("-")) return call1("NEGATE", parse_unary(), pos);
    if (eat_op("+")) return parse_unary();
    return parse_postfix();
  }

  std::string parse_postfix() {
    std::string e = parse_primary();
    while (at_op({"::"})) {
      std::string pos = pos_here();
      ++i_;
      std::string tn, prec, scale;
      parse_type_name(tn, prec, scale);
      e = R"({"t":"Cast","expr":)" + e + ",\"type_name\":" + jstr(tn) +
          ",\"precision\":" + prec + ",\"scale\":" + scale + ",\"pos\":" + pos + "}";
    }
    return e;
  }

  void parse_type_name(std::string& name, std::string& prec, std::string& scale) {
    std::string raw = any_identifier();
    name.clear();
    for (char c : raw) name += (c >= 'a' && c <= 'z') ? char(c - 32) : c;
    if (name == "DOUBLE" && at_kw({"PRECISION"})) ++i_;
    prec = "null";
    scale = "null";
    if (at_op({"("})) {
      ++i_;
      prec = type_param();
      if (eat_op(",")) scale = type_param();
      expect_op(")");
    }
  }

  std::string type_param() {
    if (cur().kind != Tk::NUMBER ||
        cur().text.find_first_not_of("0123456789") != std::string::npos)
      error("Expected an integer type parameter");
    std::string v = cur().text;
    ++i_;
    return v;
  }

  std::string parse_primary() {
    const Token& t = cur();
    std::string pos = pos_of(t);

    if (t.kind == Tk::NUMBER) {
      ++i_;
      const char* tn = number_is_float(t.text) ? "DOUBLE" : "BIGINT";
      return R"({"t":"Literal","value":)" + jnum(t.text) + ",\"type_name\":" +
             jstr(tn) + ",\"pos\":" + pos + "}";
    }
    if (t.kind == Tk::STRING) {
      ++i_;
      return R"({"t":"Literal","value":)" + jstr(t.text) +
             R"(,"type_name":"VARCHAR","pos":)" + pos + "}";
    }
    if (at_op({"?"})) {
      ++i_;
      return R"({"t":"Param","index":0,"pos":)" + pos + "}";
    }
    if (at_op({"("})) {
      ++i_;
      if (at_kw({"SELECT", "WITH", "VALUES"})) {
        std::string q = parse_query();
        expect_op(")");
        return R"({"t":"Subquery","query":)" + q +
               R"(,"kind":"scalar","outer":null,"op":null,"negated":false,"pos":)" +
               pos + "}";
      }
      std::string e = parse_expr();
      if (at_op({","})) {
        std::vector<std::string> items{e};
        while (eat_op(",")) items.push_back(parse_expr());
        expect_op(")");
        return calln("ROW", items, pos);
      }
      expect_op(")");
      return e;
    }

    if (t.kind == Tk::QIDENT) return parse_identifier_expr();
    if (t.kind != Tk::IDENT) error("Expected expression");

    const std::string& u = t.upper;
    if (u == "CASE") return parse_case();
    if (u == "CAST" || u == "TRY_CAST") {
      ++i_;
      expect_op("(");
      std::string e = parse_expr();
      expect_kw({"AS"});
      std::string tn, prec, scale;
      parse_type_name(tn, prec, scale);
      expect_op(")");
      return R"({"t":"Cast","expr":)" + e + ",\"type_name\":" + jstr(tn) +
             ",\"precision\":" + prec + ",\"scale\":" + scale + ",\"pos\":" + pos + "}";
    }
    if (u == "EXISTS") {
      ++i_;
      expect_op("(");
      std::string q = parse_query();
      expect_op(")");
      return R"({"t":"Subquery","query":)" + q +
             R"(,"kind":"exists","outer":null,"op":null,"negated":false,"pos":)" +
             pos + "}";
    }
    if (u == "NOT") {
      ++i_;
      return call1("NOT", parse_not(), pos);
    }
    if (u == "TRUE") {
      ++i_;
      return R"({"t":"Literal","value":true,"type_name":"BOOLEAN","pos":)" + pos + "}";
    }
    if (u == "FALSE") {
      ++i_;
      return R"({"t":"Literal","value":false,"type_name":"BOOLEAN","pos":)" + pos + "}";
    }
    if (u == "NULL") {
      ++i_;
      return R"({"t":"Literal","value":null,"type_name":"NULL","pos":)" + pos + "}";
    }
    if (u == "INTERVAL") return parse_interval();
    if ((u == "DATE" || u == "TIME" || u == "TIMESTAMP") &&
        peek(1).kind == Tk::STRING) {
      ++i_;
      std::string s = cur().text;
      ++i_;
      return R"({"t":"Literal","value":)" + jstr(s) + ",\"type_name\":" + jstr(u) +
             ",\"pos\":" + pos + "}";
    }
    if (u == "EXTRACT" && at_op({"("}, 1)) {
      i_ += 2;
      std::string field = any_identifier();
      for (auto& c : field)
        if (c >= 'a' && c <= 'z') c -= 32;
      expect_kw({"FROM"});
      std::string e = parse_expr();
      expect_op(")");
      return calln("EXTRACT", {lit_sym(field), e}, pos);
    }
    if (u == "SUBSTRING" && at_op({"("}, 1)) {
      i_ += 2;
      std::string e = parse_expr();
      std::string start, length = "";
      if (!eat_kw({"FROM"}).empty()) {
        start = parse_expr();
        if (!eat_kw({"FOR"}).empty()) length = parse_expr();
      } else {
        expect_op(",");
        start = parse_expr();
        if (eat_op(",")) length = parse_expr();
      }
      expect_op(")");
      std::vector<std::string> args{e, start};
      if (!length.empty()) args.push_back(length);
      return calln("SUBSTRING", args, pos);
    }
    if (u == "TRIM" && at_op({"("}, 1)) {
      i_ += 2;
      std::string side = "BOTH";
      if (at_kw({"BOTH", "LEADING", "TRAILING"})) {
        side = cur().upper;
        ++i_;
      }
      std::string chars = "";
      if (!at_kw({"FROM"})) chars = parse_expr();
      std::string e;
      if (!eat_kw({"FROM"}).empty()) {
        e = parse_expr();
      } else {
        e = chars;  // TRIM(x) form
        chars = "";
      }
      expect_op(")");
      std::string chars_arg =
          !chars.empty()
              ? chars
              : R"({"t":"Literal","value":" ","type_name":"VARCHAR","pos":[0,0]})";
      return calln("TRIM", {lit_sym(side), chars_arg, e}, pos);
    }
    if (u == "POSITION" && at_op({"("}, 1)) {
      i_ += 2;
      std::string needle = parse_additive_chain();
      expect_kw({"IN"});
      std::string hay = parse_expr();
      expect_op(")");
      return calln("POSITION", {needle, hay}, pos);
    }
    if (u == "OVERLAY" && at_op({"("}, 1)) {
      i_ += 2;
      std::string e = parse_expr();
      expect_kw({"PLACING"});
      std::string repl = parse_expr();
      expect_kw({"FROM"});
      std::string start = parse_expr();
      std::string length = "";
      if (!eat_kw({"FOR"}).empty()) length = parse_expr();
      expect_op(")");
      std::vector<std::string> args{e, repl, start};
      if (!length.empty()) args.push_back(length);
      return calln("OVERLAY", args, pos);
    }
    if ((u == "CEIL" || u == "CEILING" || u == "FLOOR") && at_op({"("}, 1)) {
      i_ += 2;
      std::string e = parse_expr();
      std::string op = (u == "FLOOR") ? "FLOOR" : "CEIL";
      if (!eat_kw({"TO"}).empty()) {
        std::string unit = any_identifier();
        for (auto& c : unit)
          if (c >= 'a' && c <= 'z') c -= 32;
        expect_op(")");
        return calln(op, {e, lit_sym(unit)}, pos);
      }
      expect_op(")");
      return calln(op, {e}, pos);
    }
    if ((u == "CURRENT_DATE" || u == "CURRENT_TIMESTAMP" || u == "CURRENT_TIME" ||
         u == "LOCALTIME" || u == "LOCALTIMESTAMP") &&
        !at_op({"("}, 1)) {
      ++i_;
      return calln(u, {}, pos);
    }
    if (u == "ROW" && at_op({"("}, 1)) {
      i_ += 2;
      std::vector<std::string> items{parse_expr()};
      while (eat_op(",")) items.push_back(parse_expr());
      expect_op(")");
      return calln("ROW", items, pos);
    }
    return parse_identifier_expr();
  }

  std::string parse_identifier_expr() {
    std::string pos = pos_here();
    Token first = cur();
    if (first.kind == Tk::IDENT && kReserved.count(first.upper) &&
        first.upper != "LEFT" && first.upper != "RIGHT")
      error("Expected expression");
    std::string name = any_identifier();
    if (at_op({"("}) && first.kind == Tk::IDENT) return parse_call(name, pos);
    std::vector<std::string> parts{name};
    while (at_op({"."})) {
      if (at_op({"*"}, 1)) {
        i_ += 2;
        return R"({"t":"Star","table":)" + jstr(parts.back()) + ",\"pos\":" + pos + "}";
      }
      ++i_;
      parts.push_back(any_identifier());
    }
    return R"({"t":"ColumnRef","parts":)" + jstrarr(parts) + ",\"pos\":" + pos + "}";
  }

  std::string parse_call(const std::string& name, const std::string& pos) {
    expect_op("(");
    bool distinct = false;
    std::vector<std::string> args;
    if (at_op({"*"}) && peek(1).kind == Tk::OP && peek(1).text == ")") {
      ++i_;
      args.push_back(R"({"t":"Star","table":null,"pos":[0,0]})");
    } else if (!at_op({")"})) {
      if (!eat_kw({"DISTINCT"}).empty())
        distinct = true;
      else
        eat_kw({"ALL"});
      args.push_back(parse_expr());
      while (eat_op(",")) args.push_back(parse_expr());
    }
    expect_op(")");
    std::string upper = name;
    for (auto& c : upper)
      if (c >= 'a' && c <= 'z') c -= 32;
    std::string filter = "null";
    if (!eat_kw({"FILTER"}).empty()) {
      expect_op("(");
      expect_kw({"WHERE"});
      filter = parse_expr();
      expect_op(")");
    }
    if (!eat_kw({"WITHIN"}).empty()) {
      // WITHIN GROUP (ORDER BY ...) — parsed and discarded, like the python
      // parser (sort keys unsupported downstream)
      expect_kw({"GROUP"});
      expect_op("(");
      expect_kw({"ORDER"});
      expect_kw({"BY"});
      parse_sort_key();
      while (eat_op(",")) parse_sort_key();
      expect_op(")");
    }
    std::string over = "null";
    if (!eat_kw({"OVER"}).empty()) over = parse_window_spec();
    // "orig" keeps the source-case function name for case-sensitive UDF lookup
    return R"({"t":"Call","op":)" + jstr(upper) + ",\"args\":" + jarr(args) +
           ",\"distinct\":" + (distinct ? "true" : "false") +
           ",\"filter\":" + filter + ",\"over\":" + over +
           ",\"orig\":" + jstr(name) + ",\"pos\":" + pos + "}";
  }

  std::string parse_window_spec() {
    expect_op("(");
    std::vector<std::string> partition_by, order_by;
    std::string frame = "null";
    if (!eat_kw({"PARTITION"}).empty()) {
      expect_kw({"BY"});
      partition_by.push_back(parse_expr());
      while (eat_op(",")) partition_by.push_back(parse_expr());
    }
    if (at_kw({"ORDER"})) {
      ++i_;
      expect_kw({"BY"});
      order_by.push_back(parse_sort_key());
      while (eat_op(",")) order_by.push_back(parse_sort_key());
    }
    if (at_kw({"ROWS", "RANGE"})) {
      std::string kind = cur().upper;
      ++i_;
      std::string lo, hi;
      if (!eat_kw({"BETWEEN"}).empty()) {
        lo = parse_frame_bound();
        expect_kw({"AND"});
        hi = parse_frame_bound();
      } else {
        lo = parse_frame_bound();
        hi = R"(["CURRENT",null])";
      }
      frame = "[" + jstr(kind) + "," + lo + "," + hi + "]";
    }
    expect_op(")");
    return R"({"t":"WindowSpec","partition_by":)" + jarr(partition_by) +
           ",\"order_by\":" + jarr(order_by) + ",\"frame\":" + frame + "}";
  }

  std::string parse_frame_bound() {
    if (!eat_kw({"UNBOUNDED"}).empty()) {
      std::string which = expect_kw({"PRECEDING", "FOLLOWING"});
      return "[\"UNBOUNDED_" + which + "\",null]";
    }
    if (!eat_kw({"CURRENT"}).empty()) {
      expect_kw({"ROW"});
      return R"(["CURRENT",null])";
    }
    const Token& t = cur();
    if (t.kind != Tk::NUMBER) error("Expected frame bound");
    ++i_;
    std::string n = t.text;
    std::string which = expect_kw({"PRECEDING", "FOLLOWING"});
    return "[" + jstr(which) + "," + n + "]";
  }

  std::string parse_case() {
    std::string pos = pos_here();
    expect_kw({"CASE"});
    std::string operand = "null";
    if (!at_kw({"WHEN"})) operand = parse_expr();
    std::vector<std::string> whens;
    while (!eat_kw({"WHEN"}).empty()) {
      std::string cond = parse_expr();
      expect_kw({"THEN"});
      std::string val = parse_expr();
      whens.push_back("[" + cond + "," + val + "]");
    }
    std::string else_ = "null";
    if (!eat_kw({"ELSE"}).empty()) else_ = parse_expr();
    expect_kw({"END"});
    return R"({"t":"Case","operand":)" + operand + ",\"whens\":" + jarr(whens) +
           ",\"else_\":" + else_ + ",\"pos\":" + pos + "}";
  }

  std::string parse_interval() {
    std::string pos = pos_here();
    expect_kw({"INTERVAL"});
    int sign = 1;
    if (eat_op("-")) sign = -1;
    const Token& t = cur();
    std::string value;        // JSON-encoded
    bool numeric = false;     // value is a JSON number
    std::string raw_text;     // original text for string values
    if (t.kind == Tk::STRING) {
      ++i_;
      raw_text = t.text;
    } else if (t.kind == Tk::NUMBER) {
      ++i_;
      value = jnum(t.text);
      numeric = true;
    } else {
      error("Expected interval value");
    }
    std::string unit = any_identifier();
    for (auto& c : unit)
      if (c >= 'a' && c <= 'z') c -= 32;
    while (!unit.empty() && unit.back() == 'S') unit.pop_back();  // DAYS -> DAY
    std::string to_unit = "null";
    if (!eat_kw({"TO"}).empty()) {
      std::string tu = any_identifier();
      for (auto& c : tu)
        if (c >= 'a' && c <= 'z') c -= 32;
      while (!tu.empty() && tu.back() == 'S') tu.pop_back();
      to_unit = jstr(tu);
    }
    if (!numeric) {
      // string values: try int, then float, else keep the raw string
      // (compound forms like '1-2' are handled by the binder)
      char* end = nullptr;
      const char* s = raw_text.c_str();
      long long iv = std::strtoll(s, &end, 10);
      if (end && *end == '\0' && end != s) {
        value = std::to_string(iv);
        numeric = true;
      } else {
        double dv = std::strtod(s, &end);
        if (end && *end == '\0' && end != s) {
          if (std::isnan(dv)) {
            value = "NaN";  // Python's json.loads accepts NaN/Infinity
          } else if (std::isinf(dv)) {
            value = dv > 0 ? "Infinity" : "-Infinity";
          } else {
            std::ostringstream os;
            os.precision(17);
            os << dv;
            value = os.str();
            if (value.find('.') == std::string::npos &&
                value.find('e') == std::string::npos)
              value += ".0";
          }
          numeric = true;
        } else {
          value = jstr(raw_text);
        }
      }
    }
    if (numeric && sign < 0) value = "-" + value;
    return R"({"t":"IntervalLiteral","value":)" + value + ",\"unit\":" + jstr(unit) +
           ",\"to_unit\":" + to_unit + ",\"pos\":" + pos + "}";
  }
};

}  // namespace

std::string parse_statements_json(const std::string& sql) {
  Parser p(sql);
  return p.parse_statements();
}

}  // namespace dsql
