// Minimal JSON DOM for the native planner: parse + emit with int64/double
// distinction preserved (plan ordinals and literals must round-trip exactly).
// The parser front-end (parser.cpp) only EMITS JSON; the optimizer
// (optimizer.cpp) must also READ plans serialized by the Python bridge
// (dask_sql_tpu/plan/native_planner.py), hence this DOM.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace dsql {

struct JV;
using JVP = std::shared_ptr<JV>;

struct JsonError : std::runtime_error {
  explicit JsonError(const std::string& m) : std::runtime_error(m) {}
};

struct JV {
  enum Kind { NUL, BOOL, INT, DBL, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;
  std::vector<JVP> arr;
  // insertion-ordered object (plans are emitted with stable key order)
  std::vector<std::pair<std::string, JVP>> obj;

  static JVP null() { return std::make_shared<JV>(); }
  static JVP boolean(bool v) {
    auto j = std::make_shared<JV>(); j->kind = BOOL; j->b = v; return j;
  }
  static JVP integer(int64_t v) {
    auto j = std::make_shared<JV>(); j->kind = INT; j->i = v; return j;
  }
  static JVP dbl(double v) {
    auto j = std::make_shared<JV>(); j->kind = DBL; j->d = v; return j;
  }
  static JVP str(const std::string& v) {
    auto j = std::make_shared<JV>(); j->kind = STR; j->s = v; return j;
  }
  static JVP array() {
    auto j = std::make_shared<JV>(); j->kind = ARR; return j;
  }
  static JVP object() {
    auto j = std::make_shared<JV>(); j->kind = OBJ; return j;
  }

  void push(const JVP& v) { arr.push_back(v); }
  void set(const std::string& k, const JVP& v) { obj.emplace_back(k, v); }

  const JVP* find(const std::string& k) const {
    for (const auto& kv : obj)
      if (kv.first == k) return &kv.second;
    return nullptr;
  }
  const JVP& at(const std::string& k) const {
    const JVP* p = find(k);
    if (!p) throw JsonError("missing key: " + k);
    return *p;
  }
  int64_t as_int() const {
    if (kind == INT) return i;
    if (kind == DBL) return (int64_t)d;
    throw JsonError("not an int");
  }
  double as_double() const {
    if (kind == DBL) return d;
    if (kind == INT) return (double)i;
    throw JsonError("not a number");
  }
  const std::string& as_str() const {
    if (kind != STR) throw JsonError("not a string");
    return s;
  }
  bool as_bool() const {
    if (kind != BOOL) throw JsonError("not a bool");
    return b;
  }
  bool is_null() const { return kind == NUL; }
};

JVP json_parse(const std::string& text);
std::string json_emit(const JVP& v);

}  // namespace dsql
