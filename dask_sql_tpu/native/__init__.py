"""Loader for the native (C++) planner front-end.

The reference's planner is native too (Java/Calcite compiled to DaskSQL.jar
and loaded in-process, /root/reference/dask_sql/java.py:62-98, setup.py:25-42).
Here the native piece is a C++ recursive-descent parser built into
``libdsqlparser.so`` (sources in ``native/`` at the repo root) and loaded via
ctypes.  If the prebuilt library is missing we try one lazy ``make``; on any
failure the pure-Python parser in ``dask_sql_tpu.sql.parser`` serves as the
fallback, keeping the package importable without a toolchain.
"""
from __future__ import annotations

import ctypes
import json
import logging
import os
import subprocess
from typing import Optional

logger = logging.getLogger(__name__)

_LIB_NAME = "libdsqlparser.so"
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _try_build() -> bool:
    """One best-effort build of the native library (repo checkouts only)."""
    native_src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")
    if not os.path.isfile(os.path.join(native_src, "Makefile")):
        return False
    try:
        subprocess.run(["make", "-C", native_src], capture_output=True,
                       timeout=120, check=True)
        return True
    except Exception as exc:  # toolchain missing, build error, timeout
        logger.debug("native parser build failed: %s", exc)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if necessary) the native parser library, or None."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("DSQL_NATIVE", "1") == "0":
        return None
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), _LIB_NAME)
    if not os.path.isfile(path) and not _try_build():
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.dsql_parse.argtypes = [ctypes.c_char_p]
        lib.dsql_parse.restype = ctypes.c_void_p  # keep pointer for dsql_free
        lib.dsql_free.argtypes = [ctypes.c_void_p]
        lib.dsql_free.restype = None
        if hasattr(lib, "dsql_optimize"):
            lib.dsql_optimize.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.dsql_optimize.restype = ctypes.c_void_p
        _lib = lib
    except OSError as exc:
        logger.debug("native parser load failed: %s", exc)
        _lib = None
    return _lib


def available() -> bool:
    """True when the native parser library is loadable (CI gate)."""
    return load() is not None


def parse_to_json(sql: str) -> Optional[dict]:
    """Parse via the native library; returns the decoded JSON envelope.

    ``{"ok": [...statements]}`` on success, ``{"error": {...}}`` on parse
    error, or None when the native library is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    ptr = lib.dsql_parse(sql.encode("utf-8"))
    if not ptr:
        return None
    try:
        raw = ctypes.string_at(ptr)
    finally:
        lib.dsql_free(ptr)
    return json.loads(raw.decode("utf-8"))


def optimize_to_json(plan_json: str, enable_pruning: bool = True
                     ) -> Optional[dict]:
    """Optimize a serialized plan via the native library.

    ``{"ok": <plan>}`` on success, ``{"error": {...}}`` on a native
    failure, or None when the library (or entry point) is unavailable.
    """
    lib = load()
    if lib is None or not hasattr(lib, "dsql_optimize"):
        return None
    ptr = lib.dsql_optimize(plan_json.encode("utf-8"),
                            1 if enable_pruning else 0)
    if not ptr:
        return None
    try:
        raw = ctypes.string_at(ptr)
    finally:
        lib.dsql_free(ptr)
    return json.loads(raw.decode("utf-8"))
