"""SQL lexer: text -> token stream with line/col positions.

Dialect decisions follow the reference's DaskSqlDialect
(/root/reference/planner/src/main/java/com/dask/sql/application/DaskSqlDialect.java:25-26):
unquoted identifiers KEEP their case (pandas-compatible `df.Name` columns),
keywords are case-insensitive; quoted identifiers use double quotes or
backticks; strings use single quotes with '' escaping.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional


class LexError(Exception):
    def __init__(self, message: str, line: int, col: int):
        super().__init__(message)
        self.line = line
        self.col = col


@dataclass
class Token:
    kind: str          # IDENT | QIDENT | STRING | NUMBER | OP | EOF
    text: str          # raw text (identifier case preserved; string unescaped)
    line: int
    col: int

    @property
    def upper(self) -> str:
        return self.text.upper()

    def __repr__(self):
        return f"{self.kind}({self.text!r}@{self.line}:{self.col})"


_MULTI_OPS = ["<>", "!=", ">=", "<=", "||", "::", "=>"]
_SINGLE_OPS = set("+-*/%=<>(),.;[]{}?&^|~:$")


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    i, n = 0, len(sql)
    line, col = 1, 1

    def advance(k: int):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and sql[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = sql[i]
        # whitespace
        if c in " \t\r\n":
            advance(1)
            continue
        # line comment
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            while i < n and sql[i] != "\n":
                advance(1)
            continue
        # block comment
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            start_line, start_col = line, col
            advance(2)
            while i < n and not (sql[i] == "*" and i + 1 < n and sql[i + 1] == "/"):
                advance(1)
            if i >= n:
                raise LexError("Unterminated block comment", start_line, start_col)
            advance(2)
            continue
        # string literal
        if c == "'":
            start_line, start_col = line, col
            advance(1)
            buf = []
            while True:
                if i >= n:
                    raise LexError("Unterminated string literal", start_line, start_col)
                if sql[i] == "'":
                    if i + 1 < n and sql[i + 1] == "'":
                        buf.append("'")
                        advance(2)
                        continue
                    advance(1)
                    break
                buf.append(sql[i])
                advance(1)
            tokens.append(Token("STRING", "".join(buf), start_line, start_col))
            continue
        # quoted identifier
        if c in ('"', "`"):
            quote = c
            start_line, start_col = line, col
            advance(1)
            buf = []
            while True:
                if i >= n:
                    raise LexError("Unterminated quoted identifier", start_line, start_col)
                if sql[i] == quote:
                    if i + 1 < n and sql[i + 1] == quote:
                        buf.append(quote)
                        advance(2)
                        continue
                    advance(1)
                    break
                buf.append(sql[i])
                advance(1)
            tokens.append(Token("QIDENT", "".join(buf), start_line, start_col))
            continue
        # number
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            start_line, start_col = line, col
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                    sql[j + 1].isdigit() or (sql[j + 1] in "+-" and j + 2 < n and sql[j + 2].isdigit())
                ):
                    seen_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            text = sql[i:j]
            advance(j - i)
            tokens.append(Token("NUMBER", text, start_line, start_col))
            continue
        # identifier / keyword
        if c.isalpha() or c == "_":
            start_line, start_col = line, col
            j = i
            while j < n and (sql[j].isalnum() or sql[j] in "_$"):
                j += 1
            text = sql[i:j]
            advance(j - i)
            tokens.append(Token("IDENT", text, start_line, start_col))
            continue
        # operators
        two = sql[i : i + 2]
        if two in _MULTI_OPS:
            tokens.append(Token("OP", two, line, col))
            advance(2)
            continue
        if c in _SINGLE_OPS:
            tokens.append(Token("OP", c, line, col))
            advance(1)
            continue
        raise LexError(f"Unexpected character {c!r}", line, col)

    tokens.append(Token("EOF", "", line, col))
    return tokens
