"""Native SQL parser: tokens -> AST.

Hand-written recursive-descent statement parser with a Pratt expression
parser.  Covers the reference's SQL surface: the Calcite-core query grammar it
relies on (SELECT/joins/GROUP BY/HAVING/window OVER/ORDER/LIMIT/UNION/VALUES/
TABLESAMPLE) plus the custom statement grammar defined in
/root/reference/planner/src/main/codegen/includes/{create,model,show,utils}.ftl:
CREATE TABLE/VIEW ... WITH kwargs | AS (query), CREATE/DROP/USE SCHEMA,
DROP TABLE/MODEL, ANALYZE TABLE, SHOW SCHEMAS/TABLES/COLUMNS/MODELS,
DESCRIBE [MODEL], CREATE MODEL/EXPERIMENT ... WITH kwargs AS (query),
EXPORT MODEL, SELECT ... FROM PREDICT(MODEL name, query), and the
``key = value`` kwargs dicts with ARRAY/MAP/nested-dict values.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..utils import ParsingException
from .ast import *  # noqa: F401,F403
from .ast import (
    AnalyzeTable, Between, Call, Case, Cast, ColumnRef, CreateExperiment,
    CreateMaterializedView, CreateModel, CreateSchema, CreateTable,
    CreateTableAs, DescribeModel, DescribeTable, DropMaterializedView,
    DropModel, DropSchema, DropTable, ExplainStatement, ExportModel, Expr,
    DeallocateStatement, ExecuteStatement, PrepareStatement,
    InList, InsertInto, IntervalLiteral, IsBool, IsDistinctFrom, IsNull,
    JoinRelation, Like, Literal, Param, PredictRelation, QueryStatement,
    RefreshMaterializedView, Relation, Select, SelectLike, SetOp, ShowColumns,
    ShowModels, ShowSchemas, ShowTables, SortKey, Star, Statement, Subquery,
    SubqueryRelation, TableRef, UseSchema, ValuesQuery, WindowSpec,
)
from .lexer import LexError, Token, tokenize

# Words that terminate expressions / cannot be bare identifiers in most spots.
RESERVED = {
    "SELECT", "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET",
    "UNION", "INTERSECT", "EXCEPT", "JOIN", "INNER", "LEFT", "RIGHT", "FULL",
    "CROSS", "ON", "USING", "AS", "AND", "OR", "NOT", "CASE", "WHEN", "THEN",
    "ELSE", "END", "IS", "NULL", "TRUE", "FALSE", "BETWEEN", "IN", "LIKE",
    "ILIKE", "SIMILAR", "EXISTS", "DISTINCT", "ALL", "ANY", "SOME", "BY",
    "ASC", "DESC", "NULLS", "FIRST", "LAST", "CAST", "INTERVAL", "CREATE",
    "DROP", "SHOW", "DESCRIBE", "ANALYZE", "WITH", "VALUES", "OVER",
    "PARTITION", "TABLESAMPLE", "FETCH", "FILTER", "THEN", "TO", "FOR",
    "NATURAL",  # else the table-alias rule swallows it before join parsing
}

_COMPARISONS = {"=", "<>", "!=", "<", "<=", ">", ">="}

_JOIN_TYPES = {"INNER", "LEFT", "RIGHT", "FULL", "CROSS"}


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        try:
            self.tokens = tokenize(sql)
        except LexError as e:
            raise ParsingException(sql, str(e), e.line, e.col) from None
        self.i = 0
        # positional-parameter bookkeeping: ``?`` markers number
        # left-to-right in token order; ``$n`` names an explicit 1-based
        # slot.  num_params() reports how many values a statement needs.
        self._param_seq = 0
        self._param_max = 0

    def num_params(self) -> int:
        """Parameter slots referenced by everything parsed so far."""
        return max(self._param_seq, self._param_max)

    # ------------------------------------------------------------------ utils
    @property
    def cur(self) -> Token:
        # clamped: the lexer always appends an EOF token, so running past the
        # end keeps returning it instead of raising IndexError
        return self.tokens[min(self.i, len(self.tokens) - 1)]

    def peek(self, k: int = 0) -> Token:
        j = min(self.i + k, len(self.tokens) - 1)
        return self.tokens[j]

    def at_kw(self, *words: str, k: int = 0) -> bool:
        t = self.peek(k)
        return t.kind == "IDENT" and t.upper in words

    def at_op(self, *ops: str, k: int = 0) -> bool:
        t = self.peek(k)
        return t.kind == "OP" and t.text in ops

    def eat_kw(self, *words: str) -> Optional[str]:
        if self.at_kw(*words):
            w = self.cur.upper
            self.i += 1
            return w
        return None

    def eat_op(self, *ops: str) -> Optional[str]:
        if self.at_op(*ops):
            op = self.cur.text
            self.i += 1
            return op
        return None

    def expect_kw(self, *words: str) -> str:
        w = self.eat_kw(*words)
        if w is None:
            self.error(f"Expected {' or '.join(words)}")
        return w

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            self.error(f"Expected '{op}'")

    def error(self, message: str, token: Optional[Token] = None):
        t = token or self.cur
        got = t.text if t.kind != "EOF" else "end of statement"
        raise ParsingException(
            self.sql, f"{message} (got {got!r})", t.line, t.col,
            max(1, len(t.text)),
        )

    def identifier(self, what: str = "identifier") -> str:
        t = self.cur
        if t.kind == "QIDENT":
            self.i += 1
            return t.text
        if t.kind == "IDENT" and t.upper not in RESERVED:
            self.i += 1
            return t.text
        self.error(f"Expected {what}")

    def any_identifier(self) -> str:
        """Identifier where even reserved words are fine (e.g. after a dot)."""
        t = self.cur
        if t.kind in ("IDENT", "QIDENT"):
            self.i += 1
            return t.text
        self.error("Expected identifier")

    def compound_identifier(self) -> List[str]:
        parts = [self.identifier()]
        while self.eat_op("."):
            parts.append(self.any_identifier())
        return parts

    # ------------------------------------------------------------- statements
    def parse_statements(self) -> List[Statement]:
        stmts = []
        while self.cur.kind != "EOF":
            stmts.append(self.parse_statement())
            while self.eat_op(";"):
                pass
        return stmts

    def parse_statement(self) -> Statement:
        t = self.cur
        if t.kind == "IDENT":
            u = t.upper
            if u == "CREATE":
                return self._parse_create()
            if u == "DROP":
                return self._parse_drop()
            if u == "SHOW":
                return self._parse_show()
            if u == "DESCRIBE" or u == "DESC":
                return self._parse_describe()
            if u == "ANALYZE":
                return self._parse_analyze()
            if u == "USE":
                return self._parse_use()
            if u == "EXPORT":
                return self._parse_export()
            if u == "INSERT":
                return self._parse_insert()
            if u == "REFRESH":
                return self._parse_refresh()
            if u == "PREPARE":
                return self._parse_prepare()
            if u == "EXECUTE":
                return self._parse_execute()
            if u == "DEALLOCATE":
                return self._parse_deallocate()
            if u == "EXPLAIN":
                self.i += 1
                analyze = bool(self.eat_kw("ANALYZE"))
                profile = (False if analyze
                           else bool(self.eat_kw("PROFILE")))
                return ExplainStatement(query=self.parse_query(),
                                        analyze=analyze, profile=profile,
                                        pos=(t.line, t.col))
        if t.kind == "IDENT" and t.upper in ("SELECT", "WITH", "VALUES") or self.at_op("("):
            return QueryStatement(query=self.parse_query())
        self.error("Expected a SQL statement")

    # -- PREPARE / EXECUTE / DEALLOCATE ------------------------------------
    def _parse_prepare(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("PREPARE")
        name = self.identifier("prepared statement name")
        self.expect_kw("AS")
        before = self.num_params()
        query = self._parse_parenthesized_or_plain_query()
        return PrepareStatement(name=name, query=query, sql=self.sql,
                                num_params=self.num_params() - before,
                                pos=pos)

    def _parse_execute(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("EXECUTE")
        name = self.identifier("prepared statement name")
        params: List = []
        if self.eat_op("("):
            if not self.at_op(")"):
                params.append(self._parse_param_value())
                while self.eat_op(","):
                    params.append(self._parse_param_value())
            self.expect_op(")")
        return ExecuteStatement(name=name, params=params, pos=pos)

    def _parse_deallocate(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("DEALLOCATE")
        self.eat_kw("PREPARE")
        if self.eat_kw("ALL"):
            return DeallocateStatement(name=None, pos=pos)
        return DeallocateStatement(
            name=self.identifier("prepared statement name"), pos=pos)

    def _parse_param_value(self):
        """EXECUTE argument: a (possibly signed) literal python value."""
        t = self.cur
        sign = 1
        while self.at_op("-", "+"):
            if self.cur.text == "-":
                sign = -sign
            self.i += 1
            t = self.cur
        if t.kind == "NUMBER":
            self.i += 1
            return sign * _number_value(t.text)
        if t.kind == "STRING":
            self.i += 1
            return t.text
        if self.eat_kw("TRUE"):
            return True
        if self.eat_kw("FALSE"):
            return False
        if self.eat_kw("NULL"):
            return None
        self.error("Expected a literal EXECUTE argument")

    # -- CREATE ------------------------------------------------------------
    def _parse_create(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("CREATE")
        or_replace = False
        if self.eat_kw("OR"):
            self.expect_kw("REPLACE")
            or_replace = True
        materialized = bool(self.eat_kw("MATERIALIZED"))
        if materialized:
            self.expect_kw("VIEW")
            kind = "MATERIALIZED VIEW"
        else:
            kind = self.expect_kw("TABLE", "VIEW", "MODEL", "SCHEMA",
                                  "EXPERIMENT")
        if_not_exists = False
        if self.eat_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            if_not_exists = True

        if kind == "SCHEMA":
            name = self.identifier("schema name")
            return CreateSchema(name=name, if_not_exists=if_not_exists,
                                or_replace=or_replace, pos=pos)

        name = self.compound_identifier()

        if kind == "MATERIALIZED VIEW":
            self.expect_kw("AS")
            query = self._parse_parenthesized_or_plain_query()
            return CreateMaterializedView(
                name=name, query=query, if_not_exists=if_not_exists,
                or_replace=or_replace, pos=pos)

        if kind in ("MODEL", "EXPERIMENT"):
            kwargs = {}
            if self.eat_kw("WITH"):
                kwargs = self._parse_kwargs()
            self.expect_kw("AS")
            query = self._parse_parenthesized_or_plain_query()
            cls = CreateModel if kind == "MODEL" else CreateExperiment
            return cls(name=name, kwargs=kwargs, query=query,
                       if_not_exists=if_not_exists, or_replace=or_replace, pos=pos)

        # TABLE or VIEW
        if self.eat_kw("WITH"):
            kwargs = self._parse_kwargs()
            return CreateTable(name=name, kwargs=kwargs,
                               if_not_exists=if_not_exists,
                               or_replace=or_replace, pos=pos)
        self.expect_kw("AS")
        query = self._parse_parenthesized_or_plain_query()
        return CreateTableAs(name=name, query=query, if_not_exists=if_not_exists,
                             or_replace=or_replace, view=(kind == "VIEW"), pos=pos)

    def _parse_parenthesized_or_plain_query(self) -> SelectLike:
        if self.at_op("(") :
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return q
        return self.parse_query()

    def _parse_kwargs(self) -> dict:
        self.expect_op("(")
        kwargs = {}
        if not self.at_op(")"):
            while True:
                key = self.any_identifier()
                self.expect_op("=")
                kwargs[key] = self._parse_kwarg_value()
                if not self.eat_op(","):
                    break
        self.expect_op(")")
        return kwargs

    def _parse_kwarg_value(self):
        t = self.cur
        if self.at_op("("):
            # nested dict (reference: MULTISET of key-values, utils.ftl:62-106)
            return self._parse_kwargs()
        if self.at_kw("ARRAY"):
            self.i += 1
            self.expect_op("[")
            vals = []
            if not self.at_op("]"):
                while True:
                    vals.append(self._parse_kwarg_value())
                    if not self.eat_op(","):
                        break
            self.expect_op("]")
            return vals
        if self.at_kw("MAP"):
            self.i += 1
            self.expect_op("[")
            items = []
            if not self.at_op("]"):
                while True:
                    items.append(self._parse_kwarg_value())
                    if not self.eat_op(","):
                        break
            self.expect_op("]")
            return dict(zip(items[0::2], items[1::2]))
        if t.kind == "STRING":
            self.i += 1
            return t.text
        if t.kind == "NUMBER":
            self.i += 1
            return _number_value(t.text)
        if self.eat_op("-"):
            t = self.cur
            if t.kind == "NUMBER":
                self.i += 1
                return -_number_value(t.text)
            self.error("Expected number")
        if t.kind == "IDENT":
            u = t.upper
            self.i += 1
            if u == "TRUE":
                return True
            if u == "FALSE":
                return False
            if u == "NULL":
                return None
            return t.text  # bare identifier value, e.g. format = csv
        self.error("Expected kwarg value")

    # -- DROP / SHOW / DESCRIBE / ANALYZE / USE / EXPORT -------------------
    def _parse_drop(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("DROP")
        materialized = bool(self.eat_kw("MATERIALIZED"))
        if materialized:
            self.expect_kw("VIEW")
            kind = "MATERIALIZED VIEW"
        else:
            kind = self.expect_kw("TABLE", "MODEL", "SCHEMA", "VIEW")
        if_exists = False
        if self.eat_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        if kind == "SCHEMA":
            return DropSchema(name=self.identifier(), if_exists=if_exists, pos=pos)
        name = self.compound_identifier()
        if kind == "MODEL":
            return DropModel(name=name, if_exists=if_exists, pos=pos)
        if kind == "MATERIALIZED VIEW":
            return DropMaterializedView(name=name, if_exists=if_exists,
                                        pos=pos)
        return DropTable(name=name, if_exists=if_exists, pos=pos)

    def _parse_insert(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        table = self.compound_identifier()
        columns = None
        # '(' here is ambiguous: a column list or a parenthesized query —
        # a following SELECT/VALUES/WITH token decides
        if self.at_op("(") and not self.at_kw("SELECT", "VALUES", "WITH",
                                              k=1):
            self.expect_op("(")
            columns = [self.identifier("column name")]
            while self.eat_op(","):
                columns.append(self.identifier("column name"))
            self.expect_op(")")
        query = self.parse_query()
        return InsertInto(table=table, columns=columns, query=query, pos=pos)

    def _parse_refresh(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("REFRESH")
        self.expect_kw("MATERIALIZED")
        self.expect_kw("VIEW")
        return RefreshMaterializedView(name=self.compound_identifier(),
                                       pos=pos)

    def _parse_show(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("SHOW")
        kind = self.expect_kw("SCHEMAS", "TABLES", "COLUMNS", "MODELS")
        if kind == "SCHEMAS":
            like = None
            if self.eat_kw("LIKE"):
                if self.cur.kind != "STRING":
                    self.error("Expected a string literal after LIKE")
                like = self.cur.text
                self.i += 1
            return ShowSchemas(like=like, pos=pos)
        if kind == "TABLES":
            schema = None
            if self.eat_kw("FROM", "IN"):
                schema = self.identifier()
            return ShowTables(schema=schema, pos=pos)
        if kind == "COLUMNS":
            self.expect_kw("FROM", "IN")
            return ShowColumns(table=self.compound_identifier(), pos=pos)
        return ShowModels(pos=pos)

    def _parse_describe(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.i += 1  # DESCRIBE
        if self.eat_kw("MODEL"):
            return DescribeModel(name=self.compound_identifier(), pos=pos)
        self.eat_kw("TABLE")
        return DescribeTable(table=self.compound_identifier(), pos=pos)

    def _parse_analyze(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("ANALYZE")
        self.expect_kw("TABLE")
        table = self.compound_identifier()
        columns = None
        self.expect_kw("COMPUTE")
        self.expect_kw("STATISTICS")
        if self.eat_kw("FOR"):
            if self.eat_kw("ALL"):
                self.expect_kw("COLUMNS")
            else:
                self.expect_kw("COLUMNS")
                columns = [self.identifier()]
                while self.eat_op(","):
                    columns.append(self.identifier())
        return AnalyzeTable(table=table, columns=columns, pos=pos)

    def _parse_use(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("USE")
        self.expect_kw("SCHEMA")
        return UseSchema(name=self.identifier(), pos=pos)

    def _parse_export(self) -> Statement:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("EXPORT")
        self.expect_kw("MODEL")
        name = self.compound_identifier()
        kwargs = {}
        if self.eat_kw("WITH"):
            kwargs = self._parse_kwargs()
        return ExportModel(name=name, kwargs=kwargs, pos=pos)

    # ---------------------------------------------------------------- queries
    def parse_query(self) -> SelectLike:
        ctes: List[Tuple[str, SelectLike]] = []
        if self.at_kw("WITH"):
            self.i += 1
            while True:
                name = self.identifier("CTE name")
                self.expect_kw("AS")
                self.expect_op("(")
                ctes.append((name, self.parse_query()))
                self.expect_op(")")
                if not self.eat_op(","):
                    break
        body = self._parse_set_expr()
        order_by, limit, offset = self._parse_order_limit()
        # A "raw" body (VALUES, or a parenthesized/nested-WITH query that
        # already owns its ORDER BY/LIMIT) is opaque: outer clauses must wrap
        # it in a Select over a subquery, never merge into it (they would
        # apply twice).  Mirror of the native parser's parse_query_parts,
        # where these bodies are kind=RAW.
        raw = not isinstance(body, (Select, SetOp)) or \
            getattr(body, "_raw_body", False)
        if not raw and isinstance(body, Select) and not body.order_by:
            body.ctes = ctes + body.ctes
            body.order_by = order_by
            body.limit = limit if body.limit is None else body.limit
            body.offset = offset if body.offset is None else body.offset
            return body
        outer = bool(order_by) or limit is not None or offset is not None
        needs_wrap = bool(ctes) or (raw and outer)
        if isinstance(body, SetOp) and not raw and not needs_wrap:
            body.order_by = order_by
            body.limit = limit
            body.offset = offset
        if needs_wrap:
            # wrap in a Select to carry CTEs and/or outer ORDER BY/LIMIT
            sel = Select(projections=[(Star(), None)],
                         from_=SubqueryRelation(query=body, alias="__cte_body__"))
            sel.ctes = ctes
            sel.order_by = order_by
            sel.limit, sel.offset = limit, offset
            return sel
        return body

    def _parse_order_limit(self):
        order_by: List[SortKey] = []
        limit = offset = None
        if self.at_kw("ORDER"):
            self.i += 1
            self.expect_kw("BY")
            while True:
                order_by.append(self._parse_sort_key())
                if not self.eat_op(","):
                    break
        if self.eat_kw("LIMIT"):
            limit = self.parse_expr()
        if self.eat_kw("OFFSET"):
            offset = self.parse_expr()
            self.eat_kw("ROWS", "ROW")
        if self.eat_kw("FETCH"):
            self.expect_kw("FIRST", "NEXT")
            limit = self.parse_expr()
            self.eat_kw("ROWS", "ROW")
            self.expect_kw("ONLY")
        return order_by, limit, offset

    def _parse_sort_key(self) -> SortKey:
        e = self.parse_expr()
        asc = True
        if self.eat_kw("DESC"):
            asc = False
        else:
            self.eat_kw("ASC")
        nulls_first = None
        if self.eat_kw("NULLS"):
            nulls_first = self.expect_kw("FIRST", "LAST") == "FIRST"
        return SortKey(expr=e, ascending=asc, nulls_first=nulls_first)

    def _parse_set_expr(self) -> SelectLike:
        left = self._parse_select_core()
        while True:
            pos = (self.cur.line, self.cur.col)
            op = self.eat_kw("UNION", "INTERSECT", "EXCEPT", "MINUS")
            if op is None:
                return left
            if op == "MINUS":
                op = "EXCEPT"
            all_ = bool(self.eat_kw("ALL"))
            if not all_:
                self.eat_kw("DISTINCT")
            right = self._parse_select_core()
            left = SetOp(op=op, all=all_, left=left, right=right, pos=pos)

    def _parse_select_core(self) -> SelectLike:
        if self.at_op("("):
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            # a parenthesized query is opaque ("raw"): outer ORDER BY/LIMIT
            # must wrap it, never merge into it (native parser kind=RAW)
            q._raw_body = True
            return q
        pos = (self.cur.line, self.cur.col)
        if self.at_kw("VALUES"):
            self.i += 1
            rows = []
            while True:
                self.expect_op("(")
                row = [self.parse_expr()]
                while self.eat_op(","):
                    row.append(self.parse_expr())
                self.expect_op(")")
                rows.append(row)
                if not self.eat_op(","):
                    break
            return ValuesQuery(rows=rows, pos=pos)
        if self.at_kw("WITH"):
            q = self.parse_query()
            q._raw_body = True
            return q
        self.expect_kw("SELECT")
        distinct = False
        if self.eat_kw("DISTINCT"):
            distinct = True
        else:
            self.eat_kw("ALL")
        projections = []
        while True:
            proj_pos = (self.cur.line, self.cur.col)
            if self.at_op("*"):
                self.i += 1
                projections.append((Star(pos=proj_pos), None))
            else:
                e = self.parse_expr()
                # t.*
                alias = None
                if self.eat_kw("AS"):
                    alias = self.any_identifier()
                elif self.cur.kind == "QIDENT" or (
                    self.cur.kind == "IDENT" and self.cur.upper not in RESERVED
                ):
                    alias = self.cur.text
                    self.i += 1
                projections.append((e, alias))
            if not self.eat_op(","):
                break
        sel = Select(projections=projections, distinct=distinct, pos=pos)
        if self.eat_kw("FROM"):
            sel.from_ = self._parse_relation()
        if self.eat_kw("WHERE"):
            sel.where = self.parse_expr()
        if self.at_kw("GROUP"):
            self.i += 1
            self.expect_kw("BY")
            sel.group_by = []
            if not self.at_op("("):
                pass
            while True:
                if self.eat_op("("):
                    # GROUP BY () — empty grouping set
                    if not self.eat_op(")"):
                        sel.group_by.append(self.parse_expr())
                        while self.eat_op(","):
                            sel.group_by.append(self.parse_expr())
                        self.expect_op(")")
                else:
                    sel.group_by.append(self.parse_expr())
                if not self.eat_op(","):
                    break
        if self.eat_kw("HAVING"):
            sel.having = self.parse_expr()
        return sel

    # -------------------------------------------------------------- relations
    def _parse_relation(self) -> Relation:
        left = self._parse_table_factor()
        while True:
            pos = (self.cur.line, self.cur.col)
            if self.eat_op(","):
                right = self._parse_table_factor()
                left = JoinRelation(left=left, right=right, join_type="CROSS", pos=pos)
                continue
            jt = None
            natural = False
            if self.at_kw("NATURAL"):
                self.i += 1
                natural = True
            if self.at_kw("JOIN"):
                jt = "INNER"
                self.i += 1
            elif self.at_kw(*_JOIN_TYPES):
                jt = self.cur.upper
                self.i += 1
                self.eat_kw("OUTER")
                self.expect_kw("JOIN")
            else:
                if natural:
                    self.error("Expected JOIN after NATURAL")
                return left
            right = self._parse_table_factor()
            cond = None
            using = None
            if jt != "CROSS" and not natural:
                if self.eat_kw("ON"):
                    cond = self.parse_expr()
                elif self.eat_kw("USING"):
                    self.expect_op("(")
                    using = [self.identifier()]
                    while self.eat_op(","):
                        using.append(self.identifier())
                    self.expect_op(")")
                else:
                    self.error("Expected ON or USING after JOIN")
            if natural:
                using = "NATURAL"  # resolved by binder against both schemas
            left = JoinRelation(left=left, right=right, join_type=jt,
                                condition=cond, using=using, pos=pos)

    def _parse_table_factor(self) -> Relation:
        pos = (self.cur.line, self.cur.col)
        if self.at_op("("):
            self.expect_op("(")
            # could be (query) or (join relation)
            if self.at_kw("SELECT", "WITH", "VALUES") or self.at_op("("):
                q = self.parse_query()
                self.expect_op(")")
                alias, cols = self._parse_alias()
                return SubqueryRelation(query=q, alias=alias, column_aliases=cols, pos=pos)
            rel = self._parse_relation()
            self.expect_op(")")
            return rel
        if self.at_kw("PREDICT"):
            self.i += 1
            self.expect_op("(")
            self.expect_kw("MODEL")
            model = self.compound_identifier()
            self.expect_op(",")
            q = self.parse_query()
            self.expect_op(")")
            alias, _ = self._parse_alias()
            return PredictRelation(model=model, query=q, alias=alias, pos=pos)
        parts = self.compound_identifier()
        sample = None
        if self.at_kw("TABLESAMPLE"):
            self.i += 1
            method = self.expect_kw("SYSTEM", "BERNOULLI")
            self.expect_op("(")
            pct_tok = self.cur
            if pct_tok.kind != "NUMBER":
                self.error("Expected sample percentage")
            self.i += 1
            self.expect_op(")")
            seed = None
            if self.eat_kw("REPEATABLE"):
                self.expect_op("(")
                seed = int(self.cur.text)
                self.i += 1
                self.expect_op(")")
            sample = (method, float(pct_tok.text), seed)
        alias, cols = self._parse_alias()
        return TableRef(parts=parts, alias=alias, column_aliases=cols,
                        sample=sample, pos=pos)

    def _parse_alias(self):
        alias = None
        cols = None
        if self.eat_kw("AS"):
            alias = self.any_identifier()
        elif self.cur.kind == "QIDENT" or (
            self.cur.kind == "IDENT" and self.cur.upper not in RESERVED
        ):
            alias = self.cur.text
            self.i += 1
        if alias and self.at_op("("):
            self.expect_op("(")
            cols = [self.identifier()]
            while self.eat_op(","):
                cols.append(self.identifier())
            self.expect_op(")")
        return alias, cols

    # ------------------------------------------------------------ expressions
    def parse_expr(self) -> Expr:
        return self._parse_or()

    def _parse_or(self) -> Expr:
        left = self._parse_and()
        while self.at_kw("OR"):
            pos = (self.cur.line, self.cur.col)
            self.i += 1
            right = self._parse_and()
            left = Call(op="OR", args=[left, right], pos=pos)
        return left

    def _parse_and(self) -> Expr:
        left = self._parse_not()
        while self.at_kw("AND"):
            pos = (self.cur.line, self.cur.col)
            self.i += 1
            right = self._parse_not()
            left = Call(op="AND", args=[left, right], pos=pos)
        return left

    def _parse_not(self) -> Expr:
        if self.at_kw("NOT"):
            pos = (self.cur.line, self.cur.col)
            self.i += 1
            return Call(op="NOT", args=[self._parse_not()], pos=pos)
        return self._parse_predicate()

    def _parse_predicate(self) -> Expr:
        left = self._parse_additive_chain()
        while True:
            pos = (self.cur.line, self.cur.col)
            negated = False
            save = self.i
            if self.at_kw("NOT"):
                self.i += 1
                negated = True
            if self.at_kw("BETWEEN"):
                self.i += 1
                self.eat_kw("ASYMMETRIC")
                sym = bool(self.eat_kw("SYMMETRIC"))
                low = self._parse_additive_chain()
                self.expect_kw("AND")
                high = self._parse_additive_chain()
                left = Between(expr=left, low=low, high=high, negated=negated,
                               symmetric=sym, pos=pos)
                continue
            if self.at_kw("IN"):
                self.i += 1
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH", "VALUES"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = Subquery(query=q, kind="in", outer=left, negated=negated, pos=pos)
                else:
                    vals = [self.parse_expr()]
                    while self.eat_op(","):
                        vals.append(self.parse_expr())
                    self.expect_op(")")
                    left = InList(expr=left, values=vals, negated=negated, pos=pos)
                continue
            if self.at_kw("LIKE", "ILIKE"):
                kind = self.cur.upper
                self.i += 1
                pattern = self._parse_additive_chain()
                escape = None
                if self.eat_kw("ESCAPE"):
                    escape = self._parse_additive_chain()
                left = Like(expr=left, pattern=pattern, escape=escape,
                            negated=negated, kind=kind, pos=pos)
                continue
            if self.at_kw("SIMILAR"):
                self.i += 1
                self.expect_kw("TO")
                pattern = self._parse_additive_chain()
                escape = None
                if self.eat_kw("ESCAPE"):
                    escape = self._parse_additive_chain()
                left = Like(expr=left, pattern=pattern, escape=escape,
                            negated=negated, kind="SIMILAR", pos=pos)
                continue
            if negated:
                self.i = save
                return left
            if self.at_kw("IS"):
                self.i += 1
                neg = bool(self.eat_kw("NOT"))
                if self.eat_kw("NULL"):
                    left = IsNull(expr=left, negated=neg, pos=pos)
                elif self.eat_kw("TRUE"):
                    left = IsBool(expr=left, value=True, negated=neg, pos=pos)
                elif self.eat_kw("FALSE"):
                    left = IsBool(expr=left, value=False, negated=neg, pos=pos)
                elif self.eat_kw("UNKNOWN"):
                    left = IsNull(expr=left, negated=neg, pos=pos)
                elif self.eat_kw("DISTINCT"):
                    self.expect_kw("FROM")
                    right = self._parse_additive_chain()
                    left = IsDistinctFrom(left=left, right=right, negated=neg, pos=pos)
                else:
                    self.error("Expected NULL/TRUE/FALSE/DISTINCT after IS")
                continue
            if self.cur.kind == "OP" and self.cur.text in _COMPARISONS:
                op = self.cur.text
                if op == "!=":
                    op = "<>"
                self.i += 1
                if self.at_kw("ANY", "SOME", "ALL"):
                    quant = self.cur.upper
                    self.i += 1
                    self.expect_op("(")
                    q = self.parse_query()
                    self.expect_op(")")
                    left = Subquery(query=q, kind="all" if quant == "ALL" else "any",
                                    outer=left, op=op, pos=pos)
                else:
                    right = self._parse_additive_chain()
                    left = Call(op=op, args=[left, right], pos=pos)
                continue
            return left

    def _parse_additive_chain(self) -> Expr:
        # handles || + - * / % with precedence
        return self._parse_concat()

    def _parse_concat(self) -> Expr:
        left = self._parse_add()
        while self.at_op("||"):
            pos = (self.cur.line, self.cur.col)
            self.i += 1
            right = self._parse_add()
            left = Call(op="||", args=[left, right], pos=pos)
        return left

    def _parse_add(self) -> Expr:
        left = self._parse_mul()
        while self.at_op("+", "-"):
            pos = (self.cur.line, self.cur.col)
            op = self.cur.text
            self.i += 1
            right = self._parse_mul()
            left = Call(op=op, args=[left, right], pos=pos)
        return left

    def _parse_mul(self) -> Expr:
        left = self._parse_unary()
        while self.at_op("*", "/", "%"):
            pos = (self.cur.line, self.cur.col)
            op = self.cur.text
            self.i += 1
            right = self._parse_unary()
            left = Call(op=op, args=[left, right], pos=pos)
        return left

    def _parse_unary(self) -> Expr:
        pos = (self.cur.line, self.cur.col)
        if self.eat_op("-"):
            return Call(op="NEGATE", args=[self._parse_unary()], pos=pos)
        if self.eat_op("+"):
            return self._parse_unary()
        return self._parse_postfix()

    def _parse_postfix(self) -> Expr:
        e = self._parse_primary()
        while self.at_op("::"):
            pos = (self.cur.line, self.cur.col)
            self.i += 1
            tn, prec, scale = self._parse_type_name()
            e = Cast(expr=e, type_name=tn, precision=prec, scale=scale, pos=pos)
        return e

    def _parse_type_name(self):
        name = self.any_identifier().upper()
        if name == "DOUBLE" and self.at_kw("PRECISION"):
            self.i += 1
            name = "DOUBLE"
        prec = scale = None
        if self.at_op("("):
            self.i += 1
            prec = self._type_param()
            if self.eat_op(","):
                scale = self._type_param()
            self.expect_op(")")
        return name, prec, scale

    def _type_param(self) -> int:
        if self.cur.kind != "NUMBER" or not self.cur.text.isdigit():
            self.error("Expected an integer type parameter")
        value = int(self.cur.text)
        self.i += 1
        return value

    def _parse_primary(self) -> Expr:
        t = self.cur
        pos = (t.line, t.col)

        if t.kind == "NUMBER":
            self.i += 1
            v = _number_value(t.text)
            return Literal(value=v, type_name="DOUBLE" if isinstance(v, float) else "BIGINT", pos=pos)
        if t.kind == "STRING":
            self.i += 1
            return Literal(value=t.text, type_name="VARCHAR", pos=pos)
        if self.at_op("?"):
            self.i += 1
            idx = self._param_seq
            self._param_seq += 1
            return Param(index=idx, pos=pos)
        if self.at_op("$"):
            self.i += 1
            if self.cur.kind != "NUMBER" or not self.cur.text.isdigit():
                self.error("Expected a parameter number after '$'")
            n = int(self.cur.text)
            if n < 1:
                self.error("Parameter numbers are 1-based")
            self.i += 1
            self._param_max = max(self._param_max, n)
            return Param(index=n - 1, pos=pos)
        if self.at_op("("):
            self.i += 1
            if self.at_kw("SELECT", "WITH", "VALUES"):
                q = self.parse_query()
                self.expect_op(")")
                return Subquery(query=q, kind="scalar", pos=pos)
            e = self.parse_expr()
            if self.at_op(","):
                # row constructor (a, b) — used by IN ((..)) etc.
                items = [e]
                while self.eat_op(","):
                    items.append(self.parse_expr())
                self.expect_op(")")
                return Call(op="ROW", args=items, pos=pos)
            self.expect_op(")")
            return e

        if t.kind == "QIDENT":
            return self._parse_identifier_expr()

        if t.kind != "IDENT":
            self.error("Expected expression")

        u = t.upper
        # keyword-led primaries
        if u == "CASE":
            return self._parse_case()
        if u == "CAST" or u == "TRY_CAST":
            self.i += 1
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            tn, prec, scale = self._parse_type_name()
            self.expect_op(")")
            return Cast(expr=e, type_name=tn, precision=prec, scale=scale, pos=pos)
        if u == "EXISTS":
            self.i += 1
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return Subquery(query=q, kind="exists", pos=pos)
        if u == "NOT":
            self.i += 1
            return Call(op="NOT", args=[self._parse_not()], pos=pos)
        if u == "TRUE":
            self.i += 1
            return Literal(value=True, type_name="BOOLEAN", pos=pos)
        if u == "FALSE":
            self.i += 1
            return Literal(value=False, type_name="BOOLEAN", pos=pos)
        if u == "NULL":
            self.i += 1
            return Literal(value=None, type_name="NULL", pos=pos)
        if u == "INTERVAL":
            return self._parse_interval()
        if u in ("DATE", "TIME", "TIMESTAMP") and self.peek(1).kind == "STRING":
            self.i += 1
            s = self.cur.text
            self.i += 1
            return Literal(value=s, type_name=u, pos=pos)
        if u == "EXTRACT" and self.at_op("(", k=1):
            self.i += 2
            field_tok = self.any_identifier().upper()
            self.expect_kw("FROM")
            e = self.parse_expr()
            self.expect_op(")")
            return Call(op="EXTRACT", args=[Literal(value=field_tok, type_name="SYMBOL"), e], pos=pos)
        if u == "SUBSTRING" and self.at_op("(", k=1):
            self.i += 2
            e = self.parse_expr()
            if self.eat_kw("FROM"):
                start = self.parse_expr()
                length = None
                if self.eat_kw("FOR"):
                    length = self.parse_expr()
            else:
                self.expect_op(",")
                start = self.parse_expr()
                length = None
                if self.eat_op(","):
                    length = self.parse_expr()
            self.expect_op(")")
            args = [e, start] + ([length] if length is not None else [])
            return Call(op="SUBSTRING", args=args, pos=pos)
        if u == "TRIM" and self.at_op("(", k=1):
            self.i += 2
            side = "BOTH"
            if self.at_kw("BOTH", "LEADING", "TRAILING"):
                side = self.cur.upper
                self.i += 1
            chars = None
            if not self.at_kw("FROM"):
                chars = self.parse_expr()
            if self.eat_kw("FROM"):
                e = self.parse_expr()
            else:
                # TRIM(x) form
                e = chars
                chars = None
            self.expect_op(")")
            args = [Literal(value=side, type_name="SYMBOL"),
                    chars if chars is not None else Literal(value=" ", type_name="VARCHAR"), e]
            return Call(op="TRIM", args=args, pos=pos)
        if u == "POSITION" and self.at_op("(", k=1):
            self.i += 2
            needle = self._parse_additive_chain()
            self.expect_kw("IN")
            hay = self.parse_expr()
            self.expect_op(")")
            return Call(op="POSITION", args=[needle, hay], pos=pos)
        if u == "OVERLAY" and self.at_op("(", k=1):
            self.i += 2
            e = self.parse_expr()
            self.expect_kw("PLACING")
            repl = self.parse_expr()
            self.expect_kw("FROM")
            start = self.parse_expr()
            length = None
            if self.eat_kw("FOR"):
                length = self.parse_expr()
            self.expect_op(")")
            args = [e, repl, start] + ([length] if length is not None else [])
            return Call(op="OVERLAY", args=args, pos=pos)
        if u in ("CEIL", "CEILING", "FLOOR") and self.at_op("(", k=1):
            self.i += 2
            e = self.parse_expr()
            if self.eat_kw("TO"):
                unit = self.any_identifier().upper()
                self.expect_op(")")
                return Call(op="CEIL" if u != "FLOOR" else "FLOOR",
                            args=[e, Literal(value=unit, type_name="SYMBOL")], pos=pos)
            self.expect_op(")")
            return Call(op="CEIL" if u != "FLOOR" else "FLOOR", args=[e], pos=pos)
        if u in ("CURRENT_DATE", "CURRENT_TIMESTAMP", "CURRENT_TIME", "LOCALTIME", "LOCALTIMESTAMP") and not self.at_op("(", k=1):
            self.i += 1
            return Call(op=u, args=[], pos=pos)
        if u == "ROW" and self.at_op("(", k=1):
            self.i += 2
            items = [self.parse_expr()]
            while self.eat_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return Call(op="ROW", args=items, pos=pos)

        return self._parse_identifier_expr()

    def _parse_identifier_expr(self) -> Expr:
        """Identifier, compound identifier, star-suffix, or function call."""
        pos = (self.cur.line, self.cur.col)
        first = self.cur
        if first.kind == "IDENT" and first.upper in RESERVED and first.upper not in (
            "LEFT", "RIGHT",  # also string functions LEFT(s,n)/RIGHT(s,n)
        ):
            self.error("Expected expression")
        name = self.any_identifier()
        # function call?
        if self.at_op("(") and first.kind == "IDENT":
            return self._parse_call(name, pos)
        parts = [name]
        while self.at_op("."):
            if self.at_op("*", k=1):
                self.i += 2
                return Star(table=parts[-1], pos=pos)
            self.i += 1
            parts.append(self.any_identifier())
        return ColumnRef(parts=parts, pos=pos)

    def _parse_call(self, name: str, pos) -> Expr:
        self.expect_op("(")
        distinct = False
        args: List[Expr] = []
        if self.at_op("*") and self.peek(1).kind == "OP" and self.peek(1).text == ")":
            self.i += 1
            args = [Star()]
        elif not self.at_op(")"):
            if self.eat_kw("DISTINCT"):
                distinct = True
            else:
                self.eat_kw("ALL")
            args.append(self.parse_expr())
            while self.eat_op(","):
                args.append(self.parse_expr())
        self.expect_op(")")
        call = Call(op=name.upper(), args=args, distinct=distinct, pos=pos)
        # preserve original case for UDF lookup (case-sensitive registration)
        call.original_name = name  # type: ignore[attr-defined]
        if self.eat_kw("FILTER"):
            self.expect_op("(")
            self.expect_kw("WHERE")
            call.filter = self.parse_expr()
            self.expect_op(")")
        if self.eat_kw("WITHIN"):
            self.expect_kw("GROUP")
            self.expect_op("(")
            self.expect_kw("ORDER")
            self.expect_kw("BY")
            self._parse_sort_key()
            while self.eat_op(","):
                self._parse_sort_key()
            self.expect_op(")")
        if self.eat_kw("OVER"):
            call.over = self._parse_window_spec()
        return call

    def _parse_window_spec(self) -> WindowSpec:
        self.expect_op("(")
        spec = WindowSpec()
        if self.eat_kw("PARTITION"):
            self.expect_kw("BY")
            spec.partition_by.append(self.parse_expr())
            while self.eat_op(","):
                spec.partition_by.append(self.parse_expr())
        if self.at_kw("ORDER"):
            self.i += 1
            self.expect_kw("BY")
            spec.order_by.append(self._parse_sort_key())
            while self.eat_op(","):
                spec.order_by.append(self._parse_sort_key())
        if self.at_kw("ROWS", "RANGE"):
            kind = self.cur.upper
            self.i += 1
            if self.eat_kw("BETWEEN"):
                lo = self._parse_frame_bound()
                self.expect_kw("AND")
                hi = self._parse_frame_bound()
            else:
                lo = self._parse_frame_bound()
                hi = ("CURRENT", None)
            spec.frame = (kind, lo, hi)
        self.expect_op(")")
        return spec

    def _parse_frame_bound(self):
        if self.eat_kw("UNBOUNDED"):
            which = self.expect_kw("PRECEDING", "FOLLOWING")
            return (f"UNBOUNDED_{which}", None)
        if self.eat_kw("CURRENT"):
            self.expect_kw("ROW")
            return ("CURRENT", None)
        t = self.cur
        if t.kind != "NUMBER":
            self.error("Expected frame bound")
        self.i += 1
        n = int(t.text)
        which = self.expect_kw("PRECEDING", "FOLLOWING")
        return (which, n)

    def _parse_case(self) -> Expr:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        whens = []
        while self.eat_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            val = self.parse_expr()
            whens.append((cond, val))
        else_ = None
        if self.eat_kw("ELSE"):
            else_ = self.parse_expr()
        self.expect_kw("END")
        return Case(operand=operand, whens=whens, else_=else_, pos=pos)

    def _parse_interval(self) -> Expr:
        pos = (self.cur.line, self.cur.col)
        self.expect_kw("INTERVAL")
        sign = 1
        if self.eat_op("-"):
            sign = -1
        t = self.cur
        if t.kind == "STRING":
            self.i += 1
            value = t.text
        elif t.kind == "NUMBER":
            self.i += 1
            value = _number_value(t.text)
        else:
            self.error("Expected interval value")
        unit = self.any_identifier().upper().rstrip("S")  # DAYS -> DAY
        to_unit = None
        if self.eat_kw("TO"):
            to_unit = self.any_identifier().upper().rstrip("S")
        if isinstance(value, str):
            try:
                value = int(value)
            except ValueError:
                try:
                    value = float(value)
                except ValueError:
                    pass  # compound like '1-2' handled by binder
        if isinstance(value, (int, float)):
            value = sign * value
        return IntervalLiteral(value=value, unit=unit, to_unit=to_unit, pos=pos)


def _number_value(text: str):
    if "." in text or "e" in text or "E" in text:
        return float(text)
    return int(text)


import re as _re

# EXPLAIN ANALYZE / EXPLAIN PROFILE are Python-parser-only extensions for
# now: the native C++ grammar predates them and would report a parse error
# at the modifier keyword, so such statements route directly to the Python
# parser (which stays the lockstep superset) instead of bouncing off a
# native error.
_EXPLAIN_ANALYZE_RE = _re.compile(r"^\s*EXPLAIN\s+(ANALYZE|PROFILE)\b",
                                  _re.IGNORECASE)

# Same story for the materialized-view / append grammar (ISSUE 14): the
# native C++ grammar predates CREATE/DROP MATERIALIZED VIEW, REFRESH
# MATERIALIZED VIEW and INSERT INTO, so these statements route directly to
# the Python parser instead of bouncing off a native parse error.
_MATVIEW_STMT_RE = _re.compile(
    r"^\s*(INSERT|REFRESH)\b"
    r"|^\s*(CREATE|DROP)\s+(OR\s+REPLACE\s+)?MATERIALIZED\b",
    _re.IGNORECASE)


def parse_sql(sql: str) -> List[Statement]:
    """Parse SQL text into AST statements.

    Prefers the native C++ parser (native/parser.cpp via ctypes — the
    counterpart of the reference's native Java planner front-end,
    RelationalAlgebraGenerator.java:87); the pure-Python parser below is the
    fallback when the library is unavailable (``DSQL_NATIVE=0`` disables the
    native path explicitly) and the only parser for ``EXPLAIN ANALYZE``.
    """
    from .. import native as _native
    from . import native_bridge

    if _EXPLAIN_ANALYZE_RE.match(sql) or _MATVIEW_STMT_RE.match(sql):
        return Parser(sql).parse_statements()
    envelope = _native.parse_to_json(sql)
    if envelope is not None:
        stmts = native_bridge.json_to_statements(envelope, sql)
        if stmts is not None:
            return stmts
    return Parser(sql).parse_statements()


def parse_one(sql: str) -> Statement:
    stmts = parse_sql(sql)
    if len(stmts) != 1:
        raise ParsingException(sql, f"Expected exactly one statement, got {len(stmts)}")
    return stmts[0]
