"""AST for the Postgres-ish SQL dialect plus the custom statement extensions.

Statement vocabulary mirrors the reference's grammar
(/root/reference/planner/src/main/codegen/: create.ftl, model.ftl, show.ftl,
utils.ftl and the core Calcite grammar it extends): SELECT with joins /
group-by / having / window functions / order / limit / union, VALUES, plus the
17 custom statements (CREATE TABLE|VIEW [WITH|AS], CREATE|DROP|USE SCHEMA,
DROP TABLE, ANALYZE TABLE, SHOW SCHEMAS|TABLES|COLUMNS|MODELS, DESCRIBE MODEL,
CREATE MODEL, DROP MODEL, PREDICT, CREATE EXPERIMENT, EXPORT MODEL) and the
``key = value`` kwargs-dict syntax (ARRAY/MAP nesting, utils.ftl:1-136).

Every node keeps ``pos`` = (line, col) for caret-marked error messages.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple, Union


class Node:
    pos: Tuple[int, int] = (0, 0)


# ===========================================================================
# Expressions
# ===========================================================================

@dataclass
class Expr(Node):
    pass


@dataclass
class Literal(Expr):
    value: Any            # python value (int/float/str/bool/None/date...)
    type_name: str        # "INTEGER" | "DOUBLE" | "VARCHAR" | "BOOLEAN" | "NULL" | ...
    pos: Tuple[int, int] = (0, 0)


@dataclass
class IntervalLiteral(Expr):
    value: Any            # numeric magnitude or string like '1-2'
    unit: str             # DAY/HOUR/MINUTE/SECOND/MONTH/YEAR/WEEK...
    to_unit: Optional[str] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ColumnRef(Expr):
    parts: List[str]      # ["tbl", "col"] or ["col"]
    pos: Tuple[int, int] = (0, 0)

    @property
    def name(self) -> str:
        return self.parts[-1]


@dataclass
class Star(Expr):
    table: Optional[str] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class Param(Expr):
    """Positional parameter: ``?`` (indexed left-to-right in statement
    order) or ``$n`` (1-based explicit index, stored 0-based)."""
    index: int = 0
    pos: Tuple[int, int] = (0, 0)


@dataclass
class WindowSpec(Node):
    partition_by: List[Expr] = field(default_factory=list)
    order_by: List["SortKey"] = field(default_factory=list)
    # frame: (kind, lo, hi) — kind in {"ROWS","RANGE"}; bounds are
    # ("UNBOUNDED_PRECEDING"|"PRECEDING"|"CURRENT"|"FOLLOWING"|"UNBOUNDED_FOLLOWING", n|None)
    frame: Optional[Tuple[str, Tuple[str, Optional[int]], Tuple[str, Optional[int]]]] = None


@dataclass
class Call(Expr):
    op: str               # canonical upper-case operator/function name
    args: List[Expr] = field(default_factory=list)
    distinct: bool = False
    filter: Optional[Expr] = None          # FILTER (WHERE ...)
    over: Optional[WindowSpec] = None      # OVER (...)
    pos: Tuple[int, int] = (0, 0)


@dataclass
class Case(Expr):
    operand: Optional[Expr]
    whens: List[Tuple[Expr, Expr]] = field(default_factory=list)
    else_: Optional[Expr] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class Cast(Expr):
    expr: Expr = None
    type_name: str = ""
    precision: Optional[int] = None
    scale: Optional[int] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class InList(Expr):
    expr: Expr = None
    values: List[Expr] = field(default_factory=list)
    negated: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class Between(Expr):
    expr: Expr = None
    low: Expr = None
    high: Expr = None
    negated: bool = False
    symmetric: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class Like(Expr):
    expr: Expr = None
    pattern: Expr = None
    escape: Optional[Expr] = None
    negated: bool = False
    kind: str = "LIKE"    # LIKE | ILIKE | SIMILAR
    pos: Tuple[int, int] = (0, 0)


@dataclass
class IsNull(Expr):
    expr: Expr = None
    negated: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class IsBool(Expr):
    expr: Expr = None
    value: bool = True    # IS TRUE / IS FALSE
    negated: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class IsDistinctFrom(Expr):
    left: Expr = None
    right: Expr = None
    negated: bool = False  # negated => IS NOT DISTINCT FROM
    pos: Tuple[int, int] = (0, 0)


@dataclass
class Subquery(Expr):
    query: "SelectLike" = None
    kind: str = "scalar"  # scalar | exists | in | any | all
    outer: Optional[Expr] = None   # for IN / quantified comparisons
    op: Optional[str] = None       # comparison op for ANY/ALL
    negated: bool = False
    pos: Tuple[int, int] = (0, 0)


# ===========================================================================
# Relations (FROM clause)
# ===========================================================================

@dataclass
class Relation(Node):
    pass


@dataclass
class TableRef(Relation):
    parts: List[str] = field(default_factory=list)  # [schema, table] or [table]
    alias: Optional[str] = None
    column_aliases: Optional[List[str]] = None
    sample: Optional[Tuple[str, float, Optional[int]]] = None  # (SYSTEM|BERNOULLI, pct, seed)
    pos: Tuple[int, int] = (0, 0)


@dataclass
class SubqueryRelation(Relation):
    query: "SelectLike" = None
    alias: Optional[str] = None
    column_aliases: Optional[List[str]] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class JoinRelation(Relation):
    left: Relation = None
    right: Relation = None
    join_type: str = "INNER"   # INNER|LEFT|RIGHT|FULL|CROSS
    condition: Optional[Expr] = None
    using: Optional[List[str]] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class PredictRelation(Relation):
    """``FROM PREDICT(MODEL name, <select>)`` — reference model.ftl:1-60."""
    model: List[str] = field(default_factory=list)
    query: "SelectLike" = None
    alias: Optional[str] = None
    pos: Tuple[int, int] = (0, 0)


# ===========================================================================
# Query statements
# ===========================================================================

@dataclass
class SortKey(Node):
    expr: Expr = None
    ascending: bool = True
    nulls_first: Optional[bool] = None   # None = dialect default (= NULLS LAST asc, FIRST desc like postgres)


@dataclass
class SelectLike(Node):
    """Base for things usable as a query body (Select, SetOp, ValuesQuery)."""


@dataclass
class Select(SelectLike):
    projections: List[Tuple[Expr, Optional[str]]] = field(default_factory=list)
    distinct: bool = False
    from_: Optional[Relation] = None
    where: Optional[Expr] = None
    group_by: Optional[List[Expr]] = None   # None = no GROUP BY clause
    having: Optional[Expr] = None
    order_by: List[SortKey] = field(default_factory=list)
    limit: Optional[Expr] = None
    offset: Optional[Expr] = None
    ctes: List[Tuple[str, "SelectLike"]] = field(default_factory=list)
    pos: Tuple[int, int] = (0, 0)


@dataclass
class SetOp(SelectLike):
    op: str = "UNION"     # UNION | INTERSECT | EXCEPT
    all: bool = False
    left: SelectLike = None
    right: SelectLike = None
    order_by: List[SortKey] = field(default_factory=list)
    limit: Optional[Expr] = None
    offset: Optional[Expr] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ValuesQuery(SelectLike):
    rows: List[List[Expr]] = field(default_factory=list)
    pos: Tuple[int, int] = (0, 0)


# ===========================================================================
# Custom / DDL statements  (reference: planner/src/main/java/com/dask/sql/parser/)
# ===========================================================================

@dataclass
class Statement(Node):
    pass


@dataclass
class QueryStatement(Statement):
    query: SelectLike = None


@dataclass
class PrepareStatement(Statement):
    """PREPARE name AS <query> — the query text is stored verbatim (and
    the parsed AST alongside) in the per-context registry; binding is
    deferred to EXECUTE so each execution binds fresh parameter values."""
    name: str = ""
    query: SelectLike = None
    sql: str = ""                 # original statement text (for system.prepared)
    num_params: int = 0
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ExecuteStatement(Statement):
    """EXECUTE name [(expr, ...)] — args must be literals (possibly signed)."""
    name: str = ""
    params: List[Any] = field(default_factory=list)   # python values
    pos: Tuple[int, int] = (0, 0)


@dataclass
class DeallocateStatement(Statement):
    """DEALLOCATE [PREPARE] name | ALL"""
    name: Optional[str] = None    # None == ALL
    pos: Tuple[int, int] = (0, 0)


@dataclass
class CreateTable(Statement):
    """CREATE [OR REPLACE] TABLE [IF NOT EXISTS] name WITH (k = v, ...)"""
    name: List[str] = field(default_factory=list)
    kwargs: dict = field(default_factory=dict)
    if_not_exists: bool = False
    or_replace: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class CreateTableAs(Statement):
    """CREATE [OR REPLACE] TABLE|VIEW [IF NOT EXISTS] name AS (query)"""
    name: List[str] = field(default_factory=list)
    query: SelectLike = None
    if_not_exists: bool = False
    or_replace: bool = False
    view: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class DropTable(Statement):
    name: List[str] = field(default_factory=list)
    if_exists: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class CreateMaterializedView(Statement):
    """CREATE [OR REPLACE] MATERIALIZED VIEW [IF NOT EXISTS] name AS (query)

    Unlike the lazy CREATE VIEW, the result is materialized eagerly and kept
    incrementally fresh against base-table appends (runtime/matview.py)."""
    name: List[str] = field(default_factory=list)
    query: SelectLike = None
    if_not_exists: bool = False
    or_replace: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class DropMaterializedView(Statement):
    name: List[str] = field(default_factory=list)
    if_exists: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class RefreshMaterializedView(Statement):
    name: List[str] = field(default_factory=list)
    pos: Tuple[int, int] = (0, 0)


@dataclass
class InsertInto(Statement):
    """INSERT INTO t [(col, ...)] VALUES (...) | <query> — the append path:
    rows land as a delta record on the table's epoch, not a bare tombstone."""
    table: List[str] = field(default_factory=list)
    columns: Optional[List[str]] = None
    query: SelectLike = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class CreateSchema(Statement):
    name: str = ""
    if_not_exists: bool = False
    or_replace: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class DropSchema(Statement):
    name: str = ""
    if_exists: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class UseSchema(Statement):
    name: str = ""
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ShowSchemas(Statement):
    like: Optional[str] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ShowTables(Statement):
    schema: Optional[str] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ShowColumns(Statement):
    table: List[str] = field(default_factory=list)
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ShowModels(Statement):
    pos: Tuple[int, int] = (0, 0)


@dataclass
class DescribeModel(Statement):
    name: List[str] = field(default_factory=list)
    pos: Tuple[int, int] = (0, 0)


@dataclass
class AnalyzeTable(Statement):
    table: List[str] = field(default_factory=list)
    columns: Optional[List[str]] = None
    pos: Tuple[int, int] = (0, 0)


@dataclass
class CreateModel(Statement):
    name: List[str] = field(default_factory=list)
    kwargs: dict = field(default_factory=dict)
    query: SelectLike = None
    if_not_exists: bool = False
    or_replace: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class DropModel(Statement):
    name: List[str] = field(default_factory=list)
    if_exists: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class CreateExperiment(Statement):
    name: List[str] = field(default_factory=list)
    kwargs: dict = field(default_factory=dict)
    query: SelectLike = None
    if_not_exists: bool = False
    or_replace: bool = False
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ExportModel(Statement):
    name: List[str] = field(default_factory=list)
    kwargs: dict = field(default_factory=dict)
    pos: Tuple[int, int] = (0, 0)


@dataclass
class DescribeTable(Statement):
    table: List[str] = field(default_factory=list)
    pos: Tuple[int, int] = (0, 0)


@dataclass
class ExplainStatement(Statement):
    query: SelectLike = None
    # EXPLAIN ANALYZE: execute the query (instrumented per plan node) and
    # annotate the rendered tree with measured wall-time + row counts
    analyze: bool = False
    # EXPLAIN PROFILE: execute the query through the NORMAL engine path
    # and render the device-level profile (per-stage flops/bytes/ms,
    # per-device HBM, shard skew, collective bytes) captured on its spans
    profile: bool = False
    pos: Tuple[int, int] = (0, 0)
