"""Per-shard partial aggregates + combine trees (in-trace, shard_map body).

The partial-aggregate algebra ("Partial Partial Aggregates", PAPERS.md):
every supported SQL aggregate decomposes into a per-shard PARTIAL that is
local to one device plus an associative COMBINE over the mesh axis —
``psum`` for SUM/COUNT (AVG = psum(sum)/psum(count)), ``pmin``/``pmax`` for
MIN/MAX.  Grouped aggregation combines per-device group tables with
``all_gather`` after a hash exchange has made group ownership disjoint
(parallel/exchange.py), so the gathered slot tables need no cross-device
merge at all.

Like exchange.py these run INSIDE an enclosing ``shard_map`` trace on local
shards; ``sharded=False`` callers (replicated interior tables) use the
local-only halves and skip the collectives entirely — a psum over an
already-replicated value would multiply by the device count.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .mesh import ROW_AXIS

#: Local slot id for rows outside every group (dead rows / cap overflow):
#: segment reductions use ``cap + 1`` segments and drop the trash slot.
_TRASH = -1  # sentinel doc only; the trash slot is index ``cap``


def _widen(data: jax.Array) -> jax.Array:
    """Accumulator dtype: f64 for floats, i64 for ints/bools (matches the
    single-device whole_table_aggregate so answers agree bit-for-pattern)."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        return data.astype(jnp.float64)
    return data.astype(jnp.int64)


def global_sum(data: jax.Array, ok: jax.Array, sharded: bool,
               axis: str = ROW_AXIS) -> Tuple[jax.Array, jax.Array]:
    """(sum, valid_count) over all live rows, combined across the mesh."""
    s = jnp.sum(jnp.where(ok, _widen(data), 0))
    c = jnp.sum(ok.astype(jnp.int64))
    if sharded:
        s = jax.lax.psum(s, axis)
        c = jax.lax.psum(c, axis)
    return s, c


def global_count(ok: jax.Array, sharded: bool,
                 axis: str = ROW_AXIS) -> jax.Array:
    c = jnp.sum(ok.astype(jnp.int64))
    return jax.lax.psum(c, axis) if sharded else c


def minmax_sentinel(data: jax.Array, is_min: bool):
    """The identity element masking dead rows out of a min/max reduction."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        return jnp.inf if is_min else -jnp.inf
    if data.dtype == jnp.bool_:
        return True if is_min else False
    info = jnp.iinfo(data.dtype)
    return info.max if is_min else info.min


def global_minmax(data: jax.Array, ok: jax.Array, is_min: bool, sharded: bool,
                  axis: str = ROW_AXIS) -> jax.Array:
    sent = minmax_sentinel(data, is_min)
    work = jnp.where(ok, data, sent)
    if work.dtype == jnp.bool_:
        work = work.astype(jnp.int32)
    local = jnp.min(work) if is_min else jnp.max(work)
    if sharded:
        op = jax.lax.pmin if is_min else jax.lax.pmax
        local = op(local, axis)
    return local


# ---------------------------------------------------------------------------
# grouped partials: local slot tables after the hash exchange
# ---------------------------------------------------------------------------

def local_slots(codes: jax.Array, cap: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Assign each local row a dense group slot in ``[0, cap)``.

    ``codes`` are int64 group codes with -1 for dead rows.  Post-exchange
    every group lives wholly on this device, so local slotting IS global
    slotting for the keys this device owns.  Returns ``(slot, slot_codes,
    overflow)``: ``slot[i]`` in ``[0, cap]`` (``cap`` = trash for dead rows
    and groups beyond the cap), ``slot_codes[g]`` the group's code (-1 for
    empty slots), and ``overflow`` a LOCAL traced bool set when more than
    ``cap`` distinct groups appeared (answers would be silently wrong —
    the caller must replicate it into a fallback flag).
    """
    n = codes.shape[0]
    big = jnp.int64(1 << 62)
    skey = jnp.where(codes >= 0, codes, big)
    order = jnp.argsort(skey)
    sc = skey[order]
    live = sc < big
    first = jnp.concatenate([live[:1], (sc[1:] != sc[:-1]) & live[1:]])
    rank = jnp.cumsum(first.astype(jnp.int64)) - 1
    overflow = jnp.any(live & (rank >= cap))
    slot_sorted = jnp.where(live & (rank < cap), rank, cap).astype(jnp.int32)
    slot = jnp.full((n,), cap, dtype=jnp.int32).at[order].set(slot_sorted)
    buf = jnp.full((cap + 1,), -1, dtype=jnp.int64)
    buf = buf.at[slot_sorted].set(jnp.where(live, sc, -1))
    return slot, buf[:cap], overflow


def slot_sum(data: jax.Array, ok: jax.Array, slot: jax.Array, cap: int
             ) -> Tuple[jax.Array, jax.Array]:
    """(per-slot sum, per-slot valid count) — the grouped partial for
    SUM/AVG/COUNT(col).  Dead rows ride to the trash slot and fall off."""
    work = jnp.where(ok, _widen(data), 0)
    s = jax.ops.segment_sum(work, slot, cap + 1)[:cap]
    c = jax.ops.segment_sum(ok.astype(jnp.int64), slot, cap + 1)[:cap]
    return s, c


def slot_count(ok: jax.Array, slot: jax.Array, cap: int) -> jax.Array:
    return jax.ops.segment_sum(ok.astype(jnp.int64), slot, cap + 1)[:cap]


def slot_minmax(data: jax.Array, ok: jax.Array, slot: jax.Array, cap: int,
                is_min: bool) -> jax.Array:
    sent = minmax_sentinel(data, is_min)
    work = jnp.where(ok, data, sent)
    if work.dtype == jnp.bool_:
        work = work.astype(jnp.int32)
    f = jax.ops.segment_min if is_min else jax.ops.segment_max
    return f(work, slot, cap + 1)[:cap]


def gather_groups(arr: jax.Array, sharded: bool,
                  axis: str = ROW_AXIS) -> jax.Array:
    """Combine disjoint per-device slot tables into the replicated global
    group table: a plain all_gather — ownership is disjoint post-exchange,
    so concatenation IS the merge."""
    return jax.lax.all_gather(arr, axis, tiled=True) if sharded else arr


def psum_table(arr: jax.Array, sharded: bool,
               axis: str = ROW_AXIS) -> jax.Array:
    """Combine OVERLAPPING per-device partials (static-domain path, where
    every device aggregates into the same dense slot table): a psum tree."""
    return jax.lax.psum(arr, axis) if sharded else arr
