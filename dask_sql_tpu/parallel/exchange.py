"""In-trace hash-partition exchange + broadcast primitives for SPMD stages.

``distributed.py`` wraps each collective kernel in its OWN ``shard_map`` —
right for standalone use, wrong for a stage program, where the whole stage
(scan -> filter -> join -> aggregate) must trace as ONE program so XLA fuses
across the collectives.  The functions here are the un-wrapped bodies: they
run INSIDE an enclosing ``shard_map`` trace (physical/spmd lowering,
parallel/spmd.py), operate on per-device LOCAL shards, and call ``jax.lax``
collectives directly against the row axis.

Conventions shared with the SPMD lowering:

- Partition codes are int64; ``-1`` marks a dead slot (row invalid / key
  NULL for joins).  ``exchange`` routes row ``code % n_dev`` and pads every
  destination bucket to the full local length, so no row is ever dropped.
- Broadcast-side keys use ``BROADCAST_SENTINEL`` for dead rows instead
  (sorts last, never matches a live probe).
- Flags (duplicate build keys, radix overflow, slot-cap overflow) are
  returned as traced bools; device-local observations must pass through
  ``replicated_flag`` before leaving the shard_map body.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .mesh import ROW_AXIS

#: Dead-slot key for the broadcast (all_gather) join path: larger than any
#: real key the engine produces (keys are table values or dictionary codes),
#: sorts after every live key, and never equals a live probe key.  A live
#: key colliding with it is flagged by the lowering, not silently dropped.
BROADCAST_SENTINEL = (1 << 62)


def replicated_flag(flag: jax.Array, axis: str = ROW_AXIS) -> jax.Array:
    """Combine a device-local bool observation into a replicated bool.

    Everything leaving a shard_map body with a replicated out-spec must
    actually BE replicated; pmax is the cheapest any() across the mesh.
    """
    return jax.lax.pmax(flag.astype(jnp.int32), axis) > 0


def shard_replicated(r: jax.Array, n_dev: int, axis: str = ROW_AXIS
                     ) -> Tuple[jax.Array, int]:
    """Emit a replicated per-device array through a P(ROW_AXIS) out-spec.

    Stage programs use ONE uniform row-sharded out-spec for every output
    (specs must be known before tracing; the output arity is not).  A
    replicated value of length k pads to ``ceil(k/n) * n`` and each device
    emits its own slice — the reassembled global array carries the value
    once.  Returns (local slice, padded global length); the host reads
    ``global_out[:k]``.
    """
    k = int(r.shape[0])
    per = max(1, -(-k // n_dev))
    kp = per * n_dev
    if kp != k:
        pad = [(0, kp - k)] + [(0, 0)] * (r.ndim - 1)
        r = jnp.pad(r, pad)
    i = jax.lax.axis_index(axis)
    return jax.lax.dynamic_slice_in_dim(r, i * per, per), kp


def exchange(codes: jax.Array, payloads: Sequence[jax.Array], n_dev: int,
             axis: str = ROW_AXIS) -> Tuple[jax.Array, List[jax.Array]]:
    """Radix-partition local rows by ``code % n_dev`` and all_to_all them.

    Static shapes: each device sends a full local-length bucket to every
    destination (code -1 padding), so the output is ``[n_dev * local]`` per
    device — a sparse but lossless redistribution where equal codes are
    guaranteed co-resident.  Payload arrays ride the same permutation with
    0-fill (their dead slots are identified via ``codes_out < 0``).
    """
    local = codes.shape[0]
    dest = jnp.where(codes >= 0, codes % n_dev, 0).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True)
    sorted_dest = dest[order]
    start = jnp.searchsorted(sorted_dest, jnp.arange(n_dev))
    pos = jnp.arange(local) - start[sorted_dest]

    def scatter(x: jax.Array, fill) -> jax.Array:
        buf = jnp.full((n_dev, local), fill, dtype=x.dtype)
        return buf.at[sorted_dest, pos].set(x[order])

    codes_out = jax.lax.all_to_all(
        scatter(codes, -1), axis, 0, 0, tiled=False).reshape(-1)
    payload_out = [
        jax.lax.all_to_all(scatter(v, 0), axis, 0, 0,
                           tiled=False).reshape(-1)
        for v in payloads
    ]
    return codes_out, payload_out


def exchange_bytes(codes: jax.Array, payloads: Sequence[jax.Array],
                   n_dev: int) -> int:
    """Static estimate of bytes moved by one ``exchange`` call, across all
    devices (send-buffer volume; shapes are static at trace time)."""
    total = 0
    for a in (codes, *payloads):
        total += int(a.size) * a.dtype.itemsize * n_dev * n_dev
    return total


def gather_bytes(arrs: Sequence[jax.Array], n_dev: int) -> int:
    """Static estimate of bytes moved by all_gather-ing ``arrs``: every
    device receives every other device's shard (same trace-time shape
    accounting as :func:`exchange_bytes`)."""
    total = 0
    for a in arrs:
        total += int(a.size) * a.dtype.itemsize * n_dev * n_dev
    return total


def psum_bytes(arrs: Sequence[jax.Array], n_dev: int) -> int:
    """Static estimate of bytes reduced by psum-ing ``arrs`` across the
    mesh (ring all-reduce moves ~2× the buffer per device; this reports
    the simpler buffer × n_dev upper-bound volume, consistent with the
    other per-kind estimates)."""
    total = 0
    for a in arrs:
        total += int(a.size) * a.dtype.itemsize * n_dev
    return total


def gather_build(arr: jax.Array, axis: str = ROW_AXIS) -> jax.Array:
    """all_gather a (small) build-side array: the replicate half of the
    broadcast join.  tiled=True concatenates shards along rows."""
    return jax.lax.all_gather(arr, axis, tiled=True)


def sorted_probe(build_keys: jax.Array, probe_keys: jax.Array,
                 sentinel: int = BROADCAST_SENTINEL
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Probe ``probe_keys`` against ``build_keys`` via sort + searchsorted.

    Dead rows on either side carry ``sentinel``.  Returns
    ``(idx, hit, dup)``: ``idx[i]`` indexes into build-row order for probe
    row i (valid only where ``hit``), and ``dup`` is a traced bool set when
    two LIVE build keys are equal — the single-match formulation is then
    wrong (multi-match join) and the caller must raise its fallback flag.
    """
    order = jnp.argsort(build_keys, stable=True)
    sk = build_keys[order]
    hi = max(int(sk.shape[0]) - 1, 0)
    pos = jnp.clip(jnp.searchsorted(sk, probe_keys), 0, hi)
    hit = (sk[pos] == probe_keys) & (probe_keys != sentinel)
    if sk.shape[0] > 1:
        dup = jnp.any((sk[1:] == sk[:-1]) & (sk[1:] != sentinel))
    else:
        dup = jnp.zeros((), dtype=bool)
    return order[pos], hit, dup
