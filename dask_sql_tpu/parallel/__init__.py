"""Multi-chip execution over the device mesh.

- ``mesh``        — mesh construction + row-sharded table placement with
                    validity-mask padding (``shard_table_with_validity``)
- ``exchange``    — in-trace hash-partition ``all_to_all`` exchange and
                    broadcast (``all_gather``) join primitives
- ``partial_agg`` — per-shard partial aggregates + ``psum``/``all_gather``
                    combine trees
- ``spmd``        — the stage-level SPMD executor: whole query stages as
                    explicit ``shard_map`` programs (``try_execute_spmd``)
- ``distributed`` — standalone shard_map collective kernels (each wrapped
                    in its own program; the SPMD executor uses the
                    un-wrapped bodies from exchange/partial_agg instead)
"""
from .mesh import (ROW_AXIS, default_mesh, replicated, row_sharding,
                   shard_table, shard_table_with_validity)
from .spmd import spmd_enabled, try_execute_spmd

__all__ = [
    "ROW_AXIS", "default_mesh", "replicated", "row_sharding",
    "shard_table", "shard_table_with_validity",
    "spmd_enabled", "try_execute_spmd",
]
