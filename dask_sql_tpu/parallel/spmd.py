"""SPMD stage execution: whole query stages as explicit shard_map programs.

This is the multi-chip execution backend (ROADMAP item 1).  Where the
compiled executor traces a plan over GLOBAL arrays and lets GSPMD infer a
partitioning, this module lowers each stage of the stage graph
(physical/stages.py) into ONE ``shard_map`` program over the row mesh with
the collectives placed explicitly:

- base-table scans read the catalog's row-sharded columns as local shards
  (mesh-mode ``create_table`` pads + row-shards with a validity mask);
- Project/Filter run unchanged per shard — the rex evaluator operates on
  whatever arrays the Columns hold, local shards included;
- equi joins lower to a hash-partitioned ``all_to_all`` exchange + local
  probe, or to an ``all_gather`` broadcast of a small build side — chosen
  by TableStats cardinality estimates (parallel/exchange.py);
- GROUP BY / global aggregates lower to per-shard partial aggregates
  combined via ``psum`` trees (small static key domains) or via hash
  exchange + disjoint ``all_gather`` slot tables (parallel/partial_agg.py);
- stage boundaries stay row-sharded: every program output rides a uniform
  ``P(ROW_AXIS)`` out-spec (replicated values are emitted through
  ``shard_replicated``), so boundary temps are sharded arrays and the next
  stage scans them like any mesh table.

Correctness over silent degradation: anything the lowering cannot express
(multi-key equi joins, distinct aggregates, duplicate build keys, group
caps, radix overflow) either refuses up front (``spmd_unsupported``) or
raises a traced runtime flag checked after execution (``spmd_fallbacks``);
both return None so the caller's compiled/eager path serves the query.

Stage programs are AOT-compiled and persist to the cross-process program
store keyed by (canonical stage plan, input layout, mesh signature) — a
fresh process re-serves sharded queries with zero XLA compiles.

Env knobs: ``DSQL_MESH=0`` disables the backend; ``DSQL_SPMD_BROADCAST_ROWS``
(default 65536) is the build-side estimate at which joins switch from
broadcast to exchange; ``DSQL_SPMD_GROUP_CAP`` (default 8192) caps distinct
groups per device post-exchange; ``DSQL_SPMD_DENSE_CAP`` (default 4096)
caps the static key-domain product for the psum-tree group-by path.
"""
from __future__ import annotations

import logging
import os
import re
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # jax < 0.5: the experimental spelling
    from jax.experimental.shard_map import shard_map

from ..plan.nodes import (AggCall, Field, LogicalAggregate, LogicalFilter,
                          LogicalJoin, LogicalProject, LogicalSort,
                          LogicalTableScan, RelNode, RexScalarSubquery,
                          RexUdf)
from ..table import Column, Scalar, Table
from ..types import physical_dtype
from ..runtime import telemetry as _tel
from . import exchange as X, partial_agg as PA
from .mesh import ROW_AXIS

logger = logging.getLogger(__name__)

_SPMD_SCHEMA = "__spmd__"
_TEMP_NAME_RE = re.compile(r"__spmd__\.t[0-9a-f]{16}")
_SUPPORTED_AGGS = ("SUM", "$SUM0", "COUNT", "AVG", "MIN", "MAX")


class Unsupported(Exception):
    """Plan shape outside the SPMD lowering's envelope (clean refusal)."""


def spmd_enabled(context) -> bool:
    """The backend runs iff the context HAS a mesh of >= 2 devices and the
    kill switch (DSQL_MESH=0) is off.  Default-on with a mesh: passing
    ``Context(mesh=...)`` is itself the opt-in."""
    if getattr(context, "mesh", None) is None:
        return False
    if os.environ.get("DSQL_MESH", "1") == "0":
        return False
    return int(context.mesh.devices.size) >= 2


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _broadcast_rows_cap() -> int:
    return _env_int("DSQL_SPMD_BROADCAST_ROWS", 65536)


def _group_cap() -> int:
    return max(1, _env_int("DSQL_SPMD_GROUP_CAP", 8192))


def _dense_cap() -> int:
    return max(2, _env_int("DSQL_SPMD_DENSE_CAP", 4096))


# ---------------------------------------------------------------------------
# in-trace table wrapper
# ---------------------------------------------------------------------------

class _ST:
    """A traced table inside the shard_map body.

    ``sharded`` distinguishes per-device row shards (collectives required
    for any cross-row operation) from replicated tables (aggregate
    outputs — identical on every device, local ops suffice and psum-style
    combines must NOT run).  ``valid`` is the local row-validity mask
    (None = all rows live)."""

    __slots__ = ("table", "valid", "sharded")

    def __init__(self, table: Table, valid, sharded: bool):
        self.table = table
        self.valid = valid
        self.sharded = sharded

    @property
    def n(self) -> int:
        return self.table.num_rows

    def vmask(self) -> jax.Array:
        if self.valid is None:
            return jnp.ones(self.n, dtype=bool)
        return self.valid


# ---------------------------------------------------------------------------
# static support gate (no tracing, no side effects)
# ---------------------------------------------------------------------------

def _check_rex(rex) -> None:
    if isinstance(rex, (RexScalarSubquery, RexUdf)):
        raise Unsupported(type(rex).__name__)
    for o in getattr(rex, "operands", None) or ():
        _check_rex(o)


def _gate_plan(rel: RelNode) -> None:
    """Refuse plan shapes the walker cannot lower BEFORE any stage runs."""
    if isinstance(rel, LogicalTableScan):
        return
    if isinstance(rel, LogicalProject):
        for e in rel.exprs:
            _check_rex(e)
    elif isinstance(rel, LogicalFilter):
        _check_rex(rel.condition)
    elif isinstance(rel, LogicalJoin):
        if rel.join_type != "INNER":
            raise Unsupported(f"join type {rel.join_type}")
        from ..plan.optimizer import split_join_condition
        equi, residual = split_join_condition(rel)
        if residual or len(equi) != 1:
            raise Unsupported("non-single-key equi join")
        li, ri = equi[0]
        for side, i in ((rel.inputs[0], li), (rel.inputs[1], ri)):
            st = side.schema[i].stype
            if st.is_string or st.name in ("DOUBLE", "FLOAT", "REAL",
                                           "DECIMAL"):
                raise Unsupported(f"join key type {st.name}")
    elif isinstance(rel, LogicalAggregate):
        for agg in rel.aggs:
            if agg.udaf is not None or agg.distinct:
                raise Unsupported("distinct/udaf agg")
            if agg.op not in _SUPPORTED_AGGS:
                raise Unsupported(f"agg {agg.op}")
            if agg.op in ("MIN", "MAX") and agg.args:
                if rel.inputs[0].schema[agg.args[0]].stype.is_string:
                    raise Unsupported("string MIN/MAX")
        for k in rel.group_keys:
            st = rel.inputs[0].schema[k].stype
            if st.name in ("DOUBLE", "FLOAT", "REAL", "DECIMAL"):
                raise Unsupported(f"float group key {st.name}")
    else:
        # Sort inside the core (the root chain was peeled), Window, Union,
        # Values, set ops, samples: no SPMD lowering yet
        raise Unsupported(type(rel).__name__)
    for i in rel.inputs:
        _gate_plan(i)


# ---------------------------------------------------------------------------
# the stage walker (runs INSIDE the shard_map trace)
# ---------------------------------------------------------------------------

class _Walker:
    """Lowers one stage subtree over local shards.

    ``meta`` is shared across the (up to two) traces of one stage — the
    eval_shape structure pass records dispatch decisions, counters and
    output descriptors; the compile trace REPLAYS the recorded decisions so
    both traces build byte-identical programs even if statistics shift
    between them."""

    def __init__(self, context, n_dev: int, scan_tables: Dict, meta: Dict):
        self.context = context
        self.n_dev = n_dev
        self.scan_tables = scan_tables
        self.meta = meta
        self.record = not meta.get("recorded")
        self._decision_idx = 0
        self.flags: List[Tuple[str, jax.Array]] = []  # replicated bools

    # -- bookkeeping -------------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        if self.record:
            self.meta["counts"][key] = self.meta["counts"].get(key, 0) + n

    def _decide(self, op: str, variant: str, **info) -> str:
        """Record (first trace) or replay (later traces) one dispatch
        decision, keeping traces deterministic."""
        if self.record:
            self.meta["decisions"].append((op, variant, info))
            return variant
        op_, variant_, _ = self.meta["decisions"][self._decision_idx]
        self._decision_idx += 1
        assert op_ == op, f"decision replay drift: {op_} vs {op}"
        return variant_

    def _flag(self, label: str, replicated_bool: jax.Array) -> None:
        self.flags.append((label, replicated_bool))

    # -- dispatch ----------------------------------------------------------
    def walk(self, rel: RelNode) -> _ST:
        if isinstance(rel, LogicalTableScan):
            return self._scan(rel)
        if isinstance(rel, LogicalProject):
            return self._project(rel)
        if isinstance(rel, LogicalFilter):
            return self._filter(rel)
        if isinstance(rel, LogicalJoin):
            return self._join(rel)
        if isinstance(rel, LogicalAggregate):
            return self._aggregate(rel)
        raise Unsupported(type(rel).__name__)

    def _scan(self, rel: LogicalTableScan) -> _ST:
        st = self.scan_tables[(rel.schema_name, rel.table_name)]
        # the optimizer prunes/reorders scan schemas; honor it (the flat
        # arg list still carries the full table — selection is trace-time)
        want = [f.name for f in rel.schema]
        if st.table.names != want:
            st = _ST(st.table.limit_to(want), st.valid, sharded=st.sharded)
        return st

    def _project(self, rel: LogicalProject) -> _ST:
        src = self.walk(rel.inputs[0])
        cols = []
        for expr, f in zip(rel.exprs, rel.schema):
            v = evaluate_rex_local(expr, src.table)
            if isinstance(v, Scalar):
                v = Column.from_scalar(v, src.n)
            cols.append(v)
        return _ST(Table([f.name for f in rel.schema], cols), src.valid,
                   src.sharded)

    def _filter(self, rel: LogicalFilter) -> _ST:
        from ..physical.rex.evaluate import evaluate_predicate

        src = self.walk(rel.inputs[0])
        pred = evaluate_predicate(rel.condition, src.table)
        if isinstance(pred, bool):
            valid = src.valid if pred else jnp.zeros(src.n, dtype=bool)
        else:
            valid = src.vmask() & pred
        return _ST(src.table, valid, src.sharded)

    # -- joins -------------------------------------------------------------
    def _join_key(self, st: _ST, idx: int, sentinel: int
                  ) -> Tuple[jax.Array, jax.Array]:
        """(key int64 with ``sentinel`` for dead rows, live mask)."""
        col = st.table.columns[idx]
        if not (jnp.issubdtype(col.data.dtype, jnp.integer)
                or col.data.dtype == jnp.bool_):
            raise Unsupported(f"join key dtype {col.data.dtype}")
        live = st.vmask()
        if col.mask is not None:
            live = live & col.mask
        d = col.data.astype(jnp.int64)
        # a live key equal to the sentinel would silently drop its row
        self._flag("join_key_sentinel",
                   X.replicated_flag(jnp.any(live & (d == sentinel))))
        return jnp.where(live, d, sentinel), live

    def _join(self, rel: LogicalJoin) -> _ST:
        from ..plan.optimizer import split_join_condition
        from ..runtime import statistics as _stats

        left = self.walk(rel.inputs[0])
        right = self.walk(rel.inputs[1])
        equi, residual = split_join_condition(rel)
        if rel.join_type != "INNER" or residual or len(equi) != 1:
            raise Unsupported("join shape")
        li, ri = equi[0]

        # build/probe + broadcast/exchange dispatch: TableStats estimates
        # when available, physical (padded) row counts otherwise.  Recorded
        # once and replayed so re-traces can't flip sides.
        if self.record:
            def est(node, st):
                e = None
                try:
                    e = _stats.estimate_rows(node, self.context)
                except Exception:
                    e = None
                if e is None:
                    e = st.n * (self.n_dev if st.sharded else 1)
                return float(e)

            est_l, est_r = est(rel.inputs[0], left), est(rel.inputs[1], right)
            build_side = "right" if est_r <= est_l else "left"
            est_build = min(est_l, est_r)
            both_sharded = left.sharded and right.sharded
            variant = ("exchange" if both_sharded
                       and est_build > _broadcast_rows_cap() else "broadcast")
            if os.environ.get("DSQL_AUTOPILOT", "0").strip() not in ("", "0"):
                # autopilot re-plan hint flips the strategy for THIS
                # recording; the decision folds into the stage digest so
                # the hinted plan compiles its own program (env checked
                # before import).  "exchange" only applies when legal.
                from ..runtime import autopilot as _ap
                hj = _ap.current_hint("join")
                if hj == "broadcast" or (hj == "exchange" and both_sharded):
                    variant = hj
            di = len(self.meta["decisions"])
            self._decide("spmd_join", variant, build=build_side,
                         est_build=int(est_build),
                         est_probe=int(max(est_l, est_r)))
        else:
            di = self._decision_idx
            _, variant, info = self.meta["decisions"][self._decision_idx]
            self._decision_idx += 1
            build_side = info["build"]

        if build_side == "right":
            build, bi, probe, pi = right, ri, left, li
        else:
            build, bi, probe, pi = left, li, right, ri

        if variant == "exchange":
            out = self._join_exchange(rel, build, bi, probe, pi, di)
            self._count("spmd_exchange_joins")
        else:
            out = self._join_broadcast(rel, build, bi, probe, pi, di)
            self._count("spmd_broadcast_joins")
        # reassemble output columns in join-schema order (left then right)
        bcols, pcols = out
        if build_side == "right":
            cols = pcols + bcols
        else:
            cols = bcols + pcols
        names = [f.name for f in rel.schema]
        table = Table(names, [c for c, _ in cols])
        valid = cols[0][1]  # every entry carries the same out-valid
        return _ST(table, valid, probe.sharded)

    def _gather_cols(self, build: _ST, idx, hit, do_gather: bool):
        """Pick build-side columns at probe positions (post all_gather)."""
        out = []
        for c in build.table.columns:
            data = X.gather_build(c.data) if do_gather else c.data
            mask = None
            if c.mask is not None:
                mask = (X.gather_build(c.mask) if do_gather else c.mask)[idx]
                mask = mask & hit
            picked = data[idx]
            out.append(Column(picked, c.stype, mask, c.dictionary))
        return out

    def _join_broadcast(self, rel, build, bi, probe, pi, di):
        sent = X.BROADCAST_SENTINEL
        bkey, _ = self._join_key(build, bi, sent)
        pkey, _ = self._join_key(probe, pi, sent)
        if build.sharded:
            # collective accounting by kind: the key plus every build
            # column (data + mask) rides an all_gather in _gather_cols
            gathered = [bkey]
            for c in build.table.columns:
                gathered.append(c.data)
                if c.mask is not None:
                    gathered.append(c.mask)
            self._count("spmd_all_gather_bytes",
                        X.gather_bytes(gathered, self.n_dev))
            bkey = X.gather_build(bkey)
        idx, hit, dup = X.sorted_probe(bkey, pkey, sent)
        # tagged with the decision index so the stage runner can flip this
        # join's build side and retry instead of abandoning the whole query
        self._flag(f"dup_build_keys@{di}", X.replicated_flag(dup))
        out_valid = probe.vmask() & hit
        bcols = [(c, out_valid) for c in
                 self._gather_cols(build, idx, hit, build.sharded)]
        pcols = [(c, out_valid) for c in probe.table.columns]
        return bcols, pcols

    def _join_exchange(self, rel, build, bi, probe, pi, di):
        sent = X.BROADCAST_SENTINEL
        bkey, _ = self._join_key(build, bi, sent)
        pkey, _ = self._join_key(probe, pi, sent)
        # normalize to non-negative partition codes via the joint minimum
        both_min = jnp.minimum(
            jnp.min(jnp.where(bkey == sent, jnp.int64(1 << 62), bkey)),
            jnp.min(jnp.where(pkey == sent, jnp.int64(1 << 62), pkey)))
        gmin = jax.lax.pmin(both_min, ROW_AXIS)
        bcode = jnp.where(bkey == sent, jnp.int64(-1), bkey - gmin)
        pcode = jnp.where(pkey == sent, jnp.int64(-1), pkey - gmin)

        bpay, bspec = _flatten_st(build)
        ppay, pspec = _flatten_st(probe)
        self._count("spmd_exchanges", 2)
        self._count("spmd_exchange_bytes",
                    X.exchange_bytes(bcode, bpay, self.n_dev)
                    + X.exchange_bytes(pcode, ppay, self.n_dev))
        bcode2, bpay2 = X.exchange(bcode, bpay, self.n_dev)
        pcode2, ppay2 = X.exchange(pcode, ppay, self.n_dev)
        build2 = _unflatten_st(build, bpay2, bspec, bcode2 >= 0)
        probe2 = _unflatten_st(probe, ppay2, pspec, pcode2 >= 0)

        bkey2 = jnp.where(bcode2 >= 0, bcode2, sent)
        pkey2 = jnp.where(pcode2 >= 0, pcode2, sent)
        idx, hit, dup = X.sorted_probe(bkey2, pkey2, sent)
        self._flag(f"dup_build_keys@{di}", X.replicated_flag(dup))
        out_valid = probe2.vmask() & hit
        bcols = [(c, out_valid) for c in
                 self._gather_cols(build2, idx, hit, False)]
        pcols = [(c, out_valid) for c in probe2.table.columns]
        return bcols, pcols

    # -- aggregates --------------------------------------------------------
    def _agg_inputs(self, agg: AggCall, src: _ST):
        """(col|None, ok): the argument column and its live-row mask."""
        ok = src.vmask()
        col = src.table.columns[agg.args[0]] if agg.args else None
        if col is not None and col.mask is not None:
            ok = ok & col.mask
        if agg.filter_arg is not None:
            fc = src.table.columns[agg.filter_arg]
            fm = fc.data.astype(bool)
            if fc.mask is not None:
                fm = fm & fc.mask
            ok = ok & fm
        return col, ok

    def _aggregate(self, rel: LogicalAggregate) -> _ST:
        src = self.walk(rel.inputs[0])
        self._count("spmd_partial_aggs", max(1, len(rel.aggs)))
        if not rel.group_keys:
            return self._agg_global(rel, src)
        key_cols = [src.table.columns[i] for i in rel.group_keys]
        static_doms = _static_domains(key_cols)
        if static_doms is not None and int(np.prod(static_doms)) <= _dense_cap():
            variant = self._decide("spmd_groupby", "psum_tree",
                                   domain=int(np.prod(static_doms)))
            return self._agg_grouped_static(rel, src, key_cols, static_doms)
        self._decide("spmd_groupby", "exchange", cap=_group_cap())
        return self._agg_grouped_exchange(rel, src, key_cols)

    def _agg_global(self, rel: LogicalAggregate, src: _ST) -> _ST:
        cols = []
        for agg, f in zip(rel.aggs, rel.schema):
            col, ok = self._agg_inputs(agg, src)
            out_dt = physical_dtype(f.stype)
            if agg.op == "COUNT":
                c = PA.global_count(ok, src.sharded)
                cols.append(Column(c.reshape(1).astype(out_dt), f.stype, None))
                continue
            if col is None:
                raise Unsupported(f"{agg.op} without argument")
            if agg.op in ("SUM", "$SUM0", "AVG"):
                s, c = PA.global_sum(col.data, ok, src.sharded)
                has = (c > 0).reshape(1)
                if agg.op == "AVG":
                    mean = s.astype(jnp.float64) / jnp.maximum(c, 1)
                    cols.append(Column(mean.reshape(1).astype(out_dt),
                                       f.stype, has))
                else:
                    mask = None if agg.op == "$SUM0" else has
                    cols.append(Column(s.reshape(1).astype(out_dt),
                                       f.stype, mask))
                continue
            # MIN / MAX (non-string; gated)
            is_min = agg.op == "MIN"
            m = PA.global_minmax(col.data, ok, is_min, src.sharded)
            c = PA.global_count(ok, src.sharded)
            cols.append(Column(m.reshape(1).astype(out_dt), f.stype,
                               (c > 0).reshape(1)))
        if src.sharded:
            # global partials are scalar psums: tiny, but the per-kind
            # ledger stays complete
            self._count("spmd_psum_bytes",
                        X.psum_bytes([c.data for c in cols], self.n_dev))
        names = [f.name for f in rel.schema]
        return _ST(Table(names, cols), None, sharded=False)

    def _slot_agg_columns(self, rel, src, slot, cap, combine, counts_rows):
        """Shared slot-table aggregation for both grouped paths.

        ``combine(arr, is_minmax, is_min)`` folds per-device slot tables
        into the global group table (psum tree or disjoint all_gather)."""
        cols = []
        nk = len(rel.group_keys)
        for agg, f in zip(rel.aggs, rel.schema[nk:]):
            col, ok = self._agg_inputs(agg, src)
            ok = ok & (slot < cap)
            out_dt = physical_dtype(f.stype)
            if agg.op == "COUNT":
                c = combine(PA.slot_count(ok, slot, cap), False, False)
                cols.append(Column(c.astype(out_dt), f.stype, None))
                continue
            if col is None:
                raise Unsupported(f"{agg.op} without argument")
            if agg.op in ("SUM", "$SUM0", "AVG"):
                s, c = PA.slot_sum(col.data, ok, slot, cap)
                s, c = combine(s, False, False), combine(c, False, False)
                has = c > 0
                if agg.op == "AVG":
                    mean = s.astype(jnp.float64) / jnp.maximum(c, 1)
                    cols.append(Column(mean.astype(out_dt), f.stype, has))
                elif agg.op == "$SUM0":
                    cols.append(Column(s.astype(out_dt), f.stype, None))
                else:
                    cols.append(Column(s.astype(out_dt), f.stype, has))
                continue
            is_min = agg.op == "MIN"
            m = combine(PA.slot_minmax(col.data, ok, slot, cap, is_min),
                        True, is_min)
            c = combine(PA.slot_count(ok, slot, cap), False, False)
            cols.append(Column(m.astype(out_dt), f.stype, c > 0))
        return cols

    def _agg_grouped_static(self, rel, src: _ST, key_cols, doms) -> _ST:
        """Small static key domain (dict strings / bools): dense codes,
        local segment partials, psum-tree combine — no exchange at all."""
        G = int(np.prod(doms))
        rows_ok = src.vmask()
        code = jnp.zeros(src.n, dtype=jnp.int64)
        for col, dom in zip(key_cols, doms):
            d = col.data.astype(jnp.int64)
            if col.mask is not None:           # slot 0 = NULL
                d = jnp.where(col.mask, d + 1, 0)
            code = code * dom + d
        slot = jnp.where(rows_ok, code, G).astype(jnp.int32)

        def combine(arr, is_minmax, is_min):
            if src.sharded:
                # psum / pmin / pmax are all mesh reductions of the slot
                # table: account them under the psum kind
                self._count("spmd_psum_bytes",
                            X.psum_bytes([arr], self.n_dev))
            if not is_minmax:
                return PA.psum_table(arr, src.sharded)
            if not src.sharded:
                return arr
            return (jax.lax.pmin if is_min else jax.lax.pmax)(arr, ROW_AXIS)

        rows = combine(PA.slot_count(rows_ok, slot, G), False, False)
        acols = self._slot_agg_columns(rel, src, slot, G, combine, rows)
        kcols = _decode_static_keys(key_cols, doms, G)
        names = [f.name for f in rel.schema]
        return _ST(Table(names, kcols + acols), rows > 0, sharded=False)

    def _agg_grouped_exchange(self, rel, src: _ST, key_cols) -> _ST:
        """Arbitrary integer-typed keys: runtime mixed-radix codes from
        global pmin/pmax spans, hash exchange for disjoint ownership, local
        slot tables, all_gather combine, in-trace key decode."""
        cap = _group_cap()
        rows_ok = src.vmask()
        n = src.n

        # runtime spans (replicated) + packed codes
        gmins, spans = [], []
        code = jnp.zeros(n, dtype=jnp.int64)
        prod = jnp.float64(1.0)
        for col in key_cols:
            if not (jnp.issubdtype(col.data.dtype, jnp.integer)
                    or col.data.dtype == jnp.bool_):
                raise Unsupported(f"group key dtype {col.data.dtype}")
            d = col.data.astype(jnp.int64)
            ok = rows_ok if col.mask is None else (rows_ok & col.mask)
            big = jnp.int64(1 << 62)
            lo = jnp.min(jnp.where(ok, d, big))
            hi = jnp.max(jnp.where(ok, d, -big))
            if src.sharded:
                lo = jax.lax.pmin(lo, ROW_AXIS)
                hi = jax.lax.pmax(hi, ROW_AXIS)
            span = jnp.clip(hi - lo + 2, 2, None)   # +1 NULL slot, +1 range
            term = jnp.where(ok, d - lo + 1, 0)
            code = code * span + term
            prod = prod * span.astype(jnp.float64)
            gmins.append(lo)
            spans.append(span)
        self._flag("radix_overflow",
                   X.replicated_flag(prod > jnp.float64(2.0 ** 62)))
        codes = jnp.where(rows_ok, code, jnp.int64(-1))

        if src.sharded:
            pay, spec = _flatten_st(src)
            self._count("spmd_exchanges")
            self._count("spmd_exchange_bytes",
                        X.exchange_bytes(codes, pay, self.n_dev))
            codes, pay2 = X.exchange(codes, pay, self.n_dev)
            src = _unflatten_st(src, pay2, spec, codes >= 0)
            rows_ok = codes >= 0

        slot, slot_codes, overflow = PA.local_slots(codes, cap)
        self._flag("group_cap_overflow", X.replicated_flag(overflow))

        def combine(arr, is_minmax, is_min):
            if src.sharded:
                self._count("spmd_all_gather_bytes",
                            X.gather_bytes([arr], self.n_dev))
            return PA.gather_groups(arr, src.sharded)

        rows = combine(PA.slot_count(rows_ok, slot, cap), False, False)
        acols = self._slot_agg_columns(rel, src, slot, cap, combine, rows)
        gcodes = combine(slot_codes, False, False)
        kcols = _decode_runtime_keys(key_cols, gcodes, gmins, spans)
        names = [f.name for f in rel.schema]
        return _ST(Table(names, kcols + acols), rows > 0, sharded=False)


def evaluate_rex_local(expr, table: Table):
    from ..physical.rex.evaluate import evaluate_rex
    return evaluate_rex(expr, table)


def _flatten_st(st: _ST) -> Tuple[List[jax.Array], List[bool]]:
    """Flatten a traced table's arrays for an exchange ride: per column
    data (+ mask when present) then the validity mask; ``spec`` records
    mask presence for _unflatten_st."""
    pay: List[jax.Array] = []
    spec: List[bool] = []
    for c in st.table.columns:
        pay.append(c.data)
        spec.append(c.mask is not None)
        if c.mask is not None:
            pay.append(c.mask)
    pay.append(st.vmask())
    return pay, spec


def _unflatten_st(st: _ST, pay: List[jax.Array], spec: List[bool],
                  live: jax.Array) -> _ST:
    cols = []
    i = 0
    for c, has_mask in zip(st.table.columns, spec):
        data = pay[i]
        i += 1
        mask = None
        if has_mask:
            mask = pay[i]
            i += 1
        cols.append(Column(data, c.stype, mask, c.dictionary))
    valid = pay[i] & live
    return _ST(Table(list(st.table.names), cols), valid, st.sharded)


def _static_domains(key_cols) -> Optional[List[int]]:
    """Static per-key domain sizes when EVERY key is a dictionary-coded
    string or a bool (NULLs add one slot); None otherwise."""
    doms = []
    for c in key_cols:
        if c.stype.is_string and c.dictionary is not None:
            base = max(1, len(c.dictionary))
        elif c.data.dtype == jnp.bool_:
            base = 2
        else:
            return None
        doms.append(base + (1 if c.mask is not None else 0))
    return doms


def _decode_static_keys(key_cols, doms, G: int) -> List[Column]:
    """Slot index -> key columns, computed on HOST numpy and baked into the
    trace as constants (the domain is static)."""
    slots = np.arange(G, dtype=np.int64)
    cols = []
    rem = slots
    strides = []
    s = 1
    for dom in reversed(doms):
        strides.append(s)
        s *= dom
    strides = list(reversed(strides))
    for c, dom, stride in zip(key_cols, doms, strides):
        v = (slots // stride) % dom
        has_null = c.mask is not None
        if has_null:
            null = v == 0
            v = np.maximum(v - 1, 0)
        if c.stype.is_string:
            data = jnp.asarray(np.clip(v, 0, max(len(c.dictionary) - 1, 0))
                               .astype(np.int32))
        elif c.data.dtype == jnp.bool_:
            data = jnp.asarray(v.astype(bool))
        else:
            data = jnp.asarray(v.astype(np.int64)).astype(c.data.dtype)
        mask = jnp.asarray(~null) if has_null else None
        cols.append(Column(data, c.stype, mask, c.dictionary))
    return cols


def _decode_runtime_keys(key_cols, gcodes, gmins, spans) -> List[Column]:
    """Global slot codes -> key columns, in-trace (spans are traced)."""
    live = gcodes >= 0
    c0 = jnp.where(live, gcodes, 0)
    cols: List[Column] = []
    for col, lo, span in zip(reversed(key_cols), reversed(gmins),
                             reversed(spans)):
        v = c0 % span
        c0 = c0 // span
        null = v == 0
        data = (lo + jnp.maximum(v, 1) - 1)
        if col.stype.is_string:
            hi = max(len(col.dictionary) - 1, 0)
            data = jnp.clip(data, 0, hi).astype(jnp.int32)
        else:
            data = data.astype(col.data.dtype)
        mask = None
        if col.mask is not None:
            mask = (~null) & live
        cols.append(Column(data, col.stype, mask, col.dictionary))
    return list(reversed(cols))


# ---------------------------------------------------------------------------
# epilogue peel: terminal ORDER BY / LIMIT (+ projections above it) run on
# the HOST over the compacted result — a global sort inside the shard_map
# body would be a full repartition for rows the host materializes anyway
# ---------------------------------------------------------------------------

def _peel_epilogue(plan: RelNode) -> Tuple[RelNode, List[RelNode]]:
    """(core, epilogue): plan/optimizer.peel_root_epilogue — the terminal
    Project/Sort chain applies on the host, everything below runs sharded."""
    from ..plan.optimizer import peel_root_epilogue
    return peel_root_epilogue(plan)


def _apply_epilogue(table: Table, epilogue: List[RelNode]) -> Table:
    from ..ops.sort import apply_offset_limit, apply_sort

    for node in epilogue:
        if isinstance(node, LogicalSort):
            if node.collation:
                table = apply_sort(
                    table, [(c.index, c.ascending, c.effective_nulls_first)
                            for c in node.collation])
            if node.limit is not None or node.offset is not None:
                table = apply_offset_limit(table, node.offset, node.limit)
        else:
            cols = []
            for expr, f in zip(node.exprs, node.schema):
                v = evaluate_rex_local(expr, table)
                if isinstance(v, Scalar):
                    v = Column.from_scalar(v, table.num_rows)
                cols.append(v)
            table = Table([f.name for f in node.schema], cols)
    return table


# ---------------------------------------------------------------------------
# stage programs: build, cache, persist, execute
# ---------------------------------------------------------------------------

class _Fallback(Exception):
    """A runtime safety flag tripped — answers would be wrong; the caller
    falls back to the single-device path for this query (unless the stage
    runner can repair the plan, e.g. by flipping a join's build side)."""

    def __init__(self, tripped: List[str]):
        super().__init__(", ".join(tripped))
        self.tripped = list(tripped)


_prog_lock = threading.Lock()
_prog_cache: "OrderedDict[str, object]" = OrderedDict()  # digest -> compiled
_PROG_CACHE_CAP = 64


def _make_spmd_scan(node: RelNode, context) -> LogicalTableScan:
    from ..physical.compiled import _stage_table_name
    return LogicalTableScan(
        schema_name=_SPMD_SCHEMA,
        table_name=_stage_table_name(node, context),
        schema=[Field(f"c{i}", f.stype)
                for i, f in enumerate(node.schema)])


def _make_stage_body(stage_plan: RelNode, context, scans, n_dev: int,
                     meta: Dict):
    """The shard_map body: rebuild per-device local tables from the flat
    arg list (physical/compiled._flatten_tables order), walk the stage
    plan, emit every output through the uniform P(ROW_AXIS) out-spec."""

    def body(*flat):
        scan_tables: Dict[Tuple[str, str], _ST] = {}
        i = 0
        for key, tbl, row_valid in scans:
            cols = []
            for c in tbl.columns:
                data = flat[i]
                i += 1
                mask = None
                if c.mask is not None:
                    mask = flat[i]
                    i += 1
                cols.append(Column(data, c.stype, mask, c.dictionary))
            valid = None
            if row_valid is not None:
                valid = flat[i]
                i += 1
            scan_tables[key] = _ST(Table(list(tbl.names), cols), valid,
                                   sharded=True)
        walker = _Walker(context, n_dev, scan_tables, meta)
        st = walker.walk(stage_plan)

        outs: List[jax.Array] = []
        if st.sharded:
            for c in st.table.columns:
                outs.append(c.data)
                if c.mask is not None:
                    outs.append(c.mask)
            outs.append(st.vmask())
            layout = {"sharded": True, "k": None, "kp": None}
        else:
            kp = None
            for c in st.table.columns:
                d, kp = X.shard_replicated(c.data, n_dev)
                outs.append(d)
                if c.mask is not None:
                    outs.append(X.shard_replicated(c.mask, n_dev)[0])
            v, kp = X.shard_replicated(st.vmask(), n_dev)
            outs.append(v)
            layout = {"sharded": False, "k": st.n, "kp": kp}
        if walker.flags:
            fl = jnp.stack([f.astype(jnp.int32).reshape(())
                            for _, f in walker.flags])
            outs.append(X.shard_replicated(fl, n_dev)[0])
        # out/layout/flags are a pure function of the (possibly edited)
        # decisions, so every trace re-records them: a dup-retry that flips
        # a join's build side may change the output sharding/layout
        meta["out"] = [(c.stype, c.mask is not None, c.dictionary)
                       for c in st.table.columns]
        meta["layout"] = layout
        meta["flags"] = [lbl for lbl, _ in walker.flags]
        meta["recorded"] = True
        return tuple(outs)

    return body


def _mesh_sig(mesh) -> str:
    return "x".join(f"{n}:{s}" for n, s in
                    zip(mesh.axis_names, mesh.devices.shape))


def _stage_digest(plan_fp: str, inputs_fp, mesh, meta: Dict) -> str:
    """Cross-process identity of one stage program: canonical plan (temp
    names -> position-stable placeholders, mirroring compiled.py), input
    layout, mesh signature, the recorded dispatch decisions (a different
    statistics state compiles its own variant instead of colliding), and
    the lowering knobs baked into the trace.  The program store digest
    additionally folds its runtime fingerprint (jax/device/devices)."""
    from ..runtime import program_store as _pstore

    mapping: Dict[str, str] = {}

    def sub(m):
        return mapping.setdefault(m.group(0), f"__spmd__.#{len(mapping)}")

    canon = _TEMP_NAME_RE.sub(sub, plan_fp)
    key = ("spmd1", canon, inputs_fp, _mesh_sig(mesh),
           repr(meta.get("decisions")),
           (_broadcast_rows_cap(), _group_cap(), _dense_cap()))
    return _pstore.get_store().digest(key)


def _pstore_load(digest: str, flat, n_outs: int):
    """Load + run this stage program from the persistent store (zero XLA
    compiles); None on miss/corruption — mirrors compiled._pstore_attempt."""
    from ..runtime import program_store as _pstore

    store = _pstore.get_store()
    if not store.enabled():
        return None
    raw = store.load(digest)
    if raw is None:
        return None
    try:
        import jax.tree_util as _jtu
        from jax.experimental import serialize_executable as _se
        if (int(raw.get("v", 0)) != 1 or raw.get("kind") != "spmd"
                or int(raw["n_args"]) != len(flat)
                or int(raw["n_outs"]) != n_outs):
            raise ValueError("entry layout mismatch")
        in_tree = _jtu.tree_structure((tuple(range(len(flat))), {}))
        out_tree = _jtu.tree_structure(tuple(range(n_outs)))
        fn = _se.deserialize_and_load(raw["payload"], in_tree, out_tree)
        outs = fn(*flat)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        _tel.inc("program_store_errors")
        logger.warning("spmd store load failed (%s: %s); recompiling",
                       type(e).__name__, str(e)[:120])
        return None
    return fn, outs


def _pstore_save(digest: str, fn, n_args: int, n_outs: int) -> None:
    from ..runtime import program_store as _pstore

    store = _pstore.get_store()
    if not store.enabled():
        return
    try:
        from jax.experimental import serialize_executable as _se
        payload, _, _ = _se.serialize(fn)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        _tel.inc("program_store_errors")
        logger.debug("spmd program serialize failed (%s); not persisted", e)
        return
    store.store(digest, {"v": 1, "kind": "spmd", "payload": payload,
                         "n_args": int(n_args), "n_outs": int(n_outs)})


def _annotate_stage_cost(fn) -> None:
    """Put the stage program's XLA cost prediction on the current span
    (EXPLAIN PROFILE and the query report's cost_err read it there).
    Env-gated before any profiler import; AOT/deserialized executables
    without a cost model just annotate nothing."""
    from ..physical.compiled import _profile_on
    if not _profile_on():
        return
    try:
        from ..runtime import profiler as _prof
        cost = _prof.cost_summary(fn)
        if cost is not None:
            _tel.annotate(cost_flops=cost["flops"],
                          cost_bytes=cost["bytes"])
    except Exception:
        logger.debug("spmd cost capture failed", exc_info=True)


def _execute_stage_program(wrapped, flat, n_outs: int, digest: str,
                           counts: Dict[str, int]):
    """in-process cache -> persistent store -> AOT compile."""
    with _prog_lock:
        fn = _prog_cache.get(digest)
        if fn is not None:
            _prog_cache.move_to_end(digest)
    if fn is not None:
        _annotate_stage_cost(fn)
        return fn(*flat)

    hit = _pstore_load(digest, flat, n_outs)
    if hit is not None:
        fn, outs = hit
        counts["spmd_store_hits"] = counts.get("spmd_store_hits", 0) + 1
    else:
        fn = jax.jit(wrapped).lower(*flat).compile()
        counts["spmd_compiles"] = counts.get("spmd_compiles", 0) + 1
        _pstore_save(digest, fn, len(flat), n_outs)
        outs = fn(*flat)
    _annotate_stage_cost(fn)
    with _prog_lock:
        _prog_cache[digest] = fn
        while len(_prog_cache) > _PROG_CACHE_CAP:
            _prog_cache.popitem(last=False)
    return outs


def _parse_stage_outputs(stage_plan: RelNode, outs, meta: Dict):
    """(table, valid): reassemble global output arrays per the recorded
    layout and raise _Fallback if any runtime safety flag tripped."""
    outs = list(outs)
    if meta["flags"]:
        fl = np.asarray(outs.pop())[:len(meta["flags"])]
        tripped = [lbl for lbl, v in zip(meta["flags"], fl) if int(v) != 0]
        if tripped:
            raise _Fallback(tripped)
    layout = meta["layout"]
    k = layout["k"]
    sliced = not layout["sharded"]
    cols: List[Column] = []
    i = 0
    for (stype, has_mask, dictionary), f in zip(meta["out"],
                                                stage_plan.schema):
        data = outs[i]
        i += 1
        mask = None
        if has_mask:
            mask = outs[i]
            i += 1
        # replicated layouts keep their padded kp length (divisible by
        # n_dev, so a consumer stage can re-shard the temp); the validity
        # clamp below hides rows past k and _compact drops them at the root
        cols.append(Column(data, stype, mask, dictionary))
    valid = outs[i]
    if sliced:
        # the reassembled global arrays are kp long with pad garbage past
        # k: clamp validity so pad rows can never surface
        kp = layout["kp"]
        valid = jnp.where(jnp.arange(kp) < k, valid, False)
    table = Table([f.name for f in stage_plan.schema], cols)
    return table, valid


def _register_temp(context, name: str, table: Table, valid) -> None:
    from ..datacontainer import TableEntry

    if _SPMD_SCHEMA not in context.schema:
        context.create_schema(_SPMD_SCHEMA)
    table = Table([f"c{i}" for i in range(table.num_columns)],
                  list(table.columns))
    context.schema[_SPMD_SCHEMA].tables[name] = TableEntry(
        table=table, row_valid=valid)


def _unregister_temp(context, name: str) -> None:
    sch = context.schema.get(_SPMD_SCHEMA)
    if sch is not None:
        sch.tables.pop(name, None)


def _compact(table: Table, valid) -> Table:
    """Host-side compaction of the root stage output to its live rows."""
    idx = jnp.asarray(np.flatnonzero(np.asarray(valid)))
    cols = [Column(c.data[idx], c.stype,
                   None if c.mask is None else c.mask[idx], c.dictionary)
            for c in table.columns]
    return Table(list(table.names), cols)


def _run_stage(stage, context, mesh, counts: Dict[str, int]):
    """Execute one stage as a shard_map program; returns (table, valid,
    meta).  Raises Unsupported / compiled.Unsupported / _Fallback."""
    from ..physical import compiled as _C

    n_dev = int(mesh.devices.size)
    scans: list = []
    plan_fp = _C._fp_plan(stage.plan, context, scans)
    inputs_fp = _C._fp_inputs(scans)
    flat = _C._flatten_tables(scans)
    for a in flat:
        if a.shape[0] % n_dev:
            raise Unsupported(f"global length {a.shape[0]} not divisible "
                              f"by {n_dev} devices")

    meta: Dict = {"counts": {}, "decisions": []}
    flipped: set = set()
    while True:
        # a FRESH body closure per attempt: jax traces cache on function
        # identity, so re-tracing the same closure after a decision edit
        # would silently reuse the stale program
        body = _make_stage_body(stage.plan, context, scans, n_dev, meta)
        wrapped = shard_map(body, mesh=mesh, in_specs=P(ROW_AXIS),
                            out_specs=P(ROW_AXIS))
        # structure pass: fills meta (decisions, output descriptors,
        # flags) without paying an XLA compile
        out_shapes = jax.eval_shape(wrapped, *flat)
        n_outs = len(out_shapes)
        digest = _stage_digest(plan_fp, inputs_fp, mesh, meta)
        outs = _execute_stage_program(wrapped, flat, n_outs, digest, counts)
        try:
            table, valid = _parse_stage_outputs(stage.plan, outs, meta)
        except _Fallback as e:
            if not _flip_dup_joins(meta, e.tripped, flipped):
                raise
            counts["spmd_join_flips"] = (counts.get("spmd_join_flips", 0)
                                         + len(e.tripped))
            continue
        if valid is not None and _C._profile_on():
            # per-shard row counts -> skew ratio (max/mean): one host
            # fetch of the validity vector, paid only when profiling
            try:
                per = np.asarray(valid).reshape(n_dev, -1).sum(axis=1)
                mean = float(per.mean())
                if mean > 0:
                    meta["skew_ratio"] = round(float(per.max()) / mean, 3)
                    meta["shard_rows"] = [int(x) for x in per]
            except Exception:
                logger.debug("spmd skew probe failed", exc_info=True)
        return table, valid, meta


_DUP_FLAG_RE = re.compile(r"^dup_build_keys@(\d+)$")


def _flip_dup_joins(meta: Dict, tripped: List[str], flipped: set) -> bool:
    """Repair a dup_build_keys trip by flipping the offending joins' build
    side (probe-side duplicates are fine under sorted_probe; build-side
    ones would mean a many-to-many join, which we don't attempt).  True if
    EVERY tripped flag is such a join not yet flipped — the stage is then
    re-traced in replay mode against the edited decisions and recompiled
    under a new digest."""
    idxs = []
    for lbl in tripped:
        m = _DUP_FLAG_RE.match(lbl)
        if m is None or int(m.group(1)) in flipped:
            return False
        idxs.append(int(m.group(1)))
    for di in idxs:
        op, variant, info = meta["decisions"][di]
        info = dict(info,
                    build=("left" if info["build"] == "right" else "right"),
                    flip="dup_build_keys")
        meta["decisions"][di] = (op, variant, info)
        flipped.add(di)
        logger.info("spmd: dup build keys at join decision %d; retrying "
                    "with build=%s", di, info["build"])
    return True


def try_execute_spmd(plan: RelNode, context) -> Optional[Table]:
    """Execute ``plan`` sharded over the context's device mesh.

    Returns the result Table, or None when the plan is outside the SPMD
    envelope (``spmd_unsupported``) or a runtime safety flag tripped
    (``spmd_fallbacks``) — the caller then serves the query through the
    single-device compiled/eager path.  Never raises.
    """
    if not spmd_enabled(context):
        return None
    from ..physical import compiled as _C
    from ..physical.stages import partition, stage_budget
    from ..runtime.statistics import record_choice

    mesh = context.mesh
    n_dev = int(mesh.devices.size)
    counts: Dict[str, int] = {}
    try:
        core, epilogue = _peel_epilogue(plan)
        _gate_plan(core)
        graph = partition(core, stage_budget(None),
                          lambda sub: _make_spmd_scan(sub, context))
    except (Unsupported, _C.Unsupported) as e:
        _tel.inc("spmd_unsupported")
        logger.debug("spmd: unsupported plan (%s)", e)
        return None
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:  # pragma: no cover - gate must never fail a query
        _tel.inc("spmd_unsupported")
        logger.debug("spmd: gate error (%s: %s)", type(e).__name__, e)
        return None

    registered: List[str] = []
    metas: List[Dict] = []
    try:
        result = None
        for si, stage in enumerate(graph.stages):
            # one span per SPMD stage: the stage program's cost
            # annotations and the shard-skew probe land here, giving
            # EXPLAIN PROFILE its per-stage rows
            with _tel.span("spmd_stage", index=si):
                table, valid, meta = _run_stage(stage, context, mesh,
                                                counts)
                if meta.get("skew_ratio") is not None:
                    _tel.annotate(skew_ratio=meta["skew_ratio"],
                                  shard_rows=meta["shard_rows"])
            metas.append(meta)
            if stage.scan is not None:
                name = stage.scan.table_name
                _register_temp(context, name, table, valid)
                registered.append(name)
            else:
                result = _apply_epilogue(_compact(table, valid), epilogue)
    except (Unsupported, _C.Unsupported) as e:
        _tel.inc("spmd_unsupported")
        logger.debug("spmd: unsupported at trace (%s)", e)
        return None
    except _Fallback as e:
        _tel.inc("spmd_fallbacks")
        logger.info("spmd: runtime flag tripped (%s); falling back", e)
        return None
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        _tel.inc("spmd_fallbacks")
        logger.warning("spmd: execution failed (%s: %s); falling back",
                       type(e).__name__, str(e)[:200])
        return None
    finally:
        for name in registered:
            _unregister_temp(context, name)

    # success: apply counters, dispatch choices and span telemetry ONCE
    _tel.inc("spmd_queries")
    _tel.inc("spmd_stages", len(graph.stages))
    for k, v in counts.items():
        _tel.inc(k, v)
    bytes_moved = 0
    gather_moved = 0
    psum_moved = 0
    skew = None
    for meta in metas:
        for k, v in meta["counts"].items():
            _tel.inc(k, v)
            if k == "spmd_exchange_bytes":
                bytes_moved += int(v)
            elif k == "spmd_all_gather_bytes":
                gather_moved += int(v)
            elif k == "spmd_psum_bytes":
                psum_moved += int(v)
        r = meta.get("skew_ratio")
        if r is not None:
            skew = max(skew, r) if skew is not None else r
        for op, variant, info in meta["decisions"]:
            try:
                record_choice(op, variant, **info)
            except Exception:  # pragma: no cover
                pass
    ann = dict(tier="spmd", spmd_devices=n_dev,
               spmd_stages=len(graph.stages),
               spmd_exchange_bytes=bytes_moved)
    # per-kind collective accounting + worst-stage shard skew annotate
    # ONLY here (the query report sums byte attrs over all spans, so the
    # per-stage spans deliberately do not repeat them)
    if gather_moved:
        ann["spmd_all_gather_bytes"] = gather_moved
    if psum_moved:
        ann["spmd_psum_bytes"] = psum_moved
    if skew is not None:
        ann["skew_ratio"] = skew
    _tel.annotate(**ann)
    if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
        try:
            from ..runtime import events as _ev
            _ev.publish("spmd.query", devices=n_dev,
                        stages=len(graph.stages),
                        exchange_bytes=bytes_moved,
                        skew_ratio=skew)
        except Exception:  # pragma: no cover - bus is advisory
            pass
    return result
