"""Distributed query kernels: shard_map + XLA collectives over the row mesh.

These are the TPU-native equivalents of the reference's shuffle-backed
operators (SURVEY §2.3): where dask re-partitions dataframes through a
task-graph shuffle (join.py:241 merge, utils/sort.py:82 set_index,
aggregate.py:356 tree reduction), these kernels run ONE compiled SPMD program
per stage:

- ``dist_segment_sum`` — local segment reduction + ``psum`` tree over ICI
  (groupby aggregation when the group-key domain is bounded/known).
- ``hash_exchange`` — radix partition by key hash + ``all_to_all`` (the shuffle
  for large-domain groupby / hash join); static shapes via per-bucket padding.
- ``ring_shift`` — ``ppermute`` neighbor exchange (sort/window boundaries).
- ``dist_join_broadcast`` — ``all_gather`` the (small) build side, local probe
  (the broadcast-join path; skew-free, no exchange).

All are jit-compiled over a Mesh and run on virtual CPU meshes in tests and
the driver's multi-chip dry-run identically to real ICI meshes.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # jax < 0.5: the experimental spelling
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import ROW_AXIS


# ---------------------------------------------------------------------------
# distributed segmented aggregation (groupby)
# ---------------------------------------------------------------------------

def dist_segment_sum(mesh: Mesh, values: jax.Array, codes: jax.Array,
                     num_groups: int) -> jax.Array:
    """Global segment_sum over a row-sharded array: local partials + psum.

    The result is replicated on every device (小 G): the SQL analogue of a
    tree-reduction groupby aggregate.
    """

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(ROW_AXIS), P(ROW_AXIS)), out_specs=P(),
    )
    def kernel(v, c):
        local = jax.ops.segment_sum(v, c, num_groups)
        return jax.lax.psum(local, ROW_AXIS)

    return kernel(values, codes)


def dist_segment_minmax(mesh: Mesh, values: jax.Array, codes: jax.Array,
                        num_groups: int, is_min: bool, sentinel) -> jax.Array:
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(ROW_AXIS), P(ROW_AXIS)), out_specs=P(),
    )
    def kernel(v, c):
        f = jax.ops.segment_min if is_min else jax.ops.segment_max
        local = f(v, c, num_groups, indices_are_sorted=False)
        local = jnp.where(jnp.isfinite(local) | (local == sentinel), local, sentinel)
        op = jax.lax.pmin if is_min else jax.lax.pmax
        return op(local, ROW_AXIS)

    return kernel(values, codes)


# ---------------------------------------------------------------------------
# hash exchange (the all_to_all shuffle)
# ---------------------------------------------------------------------------

def hash_exchange(mesh: Mesh, codes: jax.Array, *payload: jax.Array
                  ) -> Tuple[jax.Array, ...]:
    """Radix-partition rows by ``hash(code) % n_devices`` and exchange via
    all_to_all so equal keys land on the same device.

    Static shapes: each device sends exactly ``rows_per_device`` slots per
    destination bucket (rows beyond capacity are impossible for balanced
    hashing only in expectation — capacity is the full local length, so no
    row is ever dropped; unused slots carry code -1).

    Returns (new_codes, *new_payload) with shape [n_dev * local] per device —
    i.e. a bucketed re-distribution with -1 padding.  Downstream kernels mask
    on code >= 0.
    """
    n_dev = mesh.devices.size

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(ROW_AXIS),) * (1 + len(payload)),
        out_specs=(P(ROW_AXIS),) * (1 + len(payload)),
    )
    def kernel(c, *vs):
        local = c.shape[0]
        dest = jnp.where(c >= 0, c % n_dev, 0).astype(jnp.int32)
        # stable sort rows by destination; build per-destination slots
        order = jnp.argsort(dest, stable=True)
        sorted_dest = dest[order]
        # position within destination bucket
        ones = jnp.ones_like(sorted_dest)
        pos_in_bucket = jnp.cumsum(ones) - 1
        bucket_start = jnp.searchsorted(sorted_dest, jnp.arange(n_dev))
        pos_in_bucket = pos_in_bucket - bucket_start[sorted_dest]
        # scatter into [n_dev, local] send buffer (-1 padded)
        def scatter(x, fill):
            buf = jnp.full((n_dev, local), fill, dtype=x.dtype)
            return buf.at[sorted_dest, pos_in_bucket].set(x[order])
        c_buf = scatter(c, -1)
        v_bufs = [scatter(v, 0) for v in vs]
        # exchange: dimension 0 is the destination axis
        c_out = jax.lax.all_to_all(c_buf, ROW_AXIS, 0, 0, tiled=False)
        v_outs = [jax.lax.all_to_all(v, ROW_AXIS, 0, 0, tiled=False) for v in v_bufs]
        return (c_out.reshape(-1), *[v.reshape(-1) for v in v_outs])

    return kernel(codes, *payload)


def dist_groupby_sum_exchange(mesh: Mesh, codes: jax.Array, values: jax.Array,
                              num_groups: int) -> jax.Array:
    """Large-domain groupby: hash-exchange rows so each device owns a key
    range, reduce locally, all_gather the per-device partials.

    Returns the global [num_groups] sums replicated on all devices.
    """
    new_codes, new_vals = hash_exchange(mesh, codes, values)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(ROW_AXIS), P(ROW_AXIS)), out_specs=P(),
    )
    def reduce_local(c, v):
        valid = c >= 0
        local = jax.ops.segment_sum(jnp.where(valid, v, 0),
                                    jnp.where(valid, c, 0), num_groups)
        # after exchange each key lives on exactly one device: psum merges the
        # disjoint partials
        return jax.lax.psum(local, ROW_AXIS)

    return reduce_local(new_codes, new_vals)


# ---------------------------------------------------------------------------
# broadcast join (small build side)
# ---------------------------------------------------------------------------

def dist_join_broadcast(mesh: Mesh, probe_codes: jax.Array,
                        build_codes: jax.Array, build_values: jax.Array,
                        default) -> jax.Array:
    """Broadcast-join: all_gather the build side, local sorted probe.

    Returns for each probe row the matching build value (or ``default``) —
    the inner-join gather step for 1:1 build keys (dimension tables).
    """

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS)), out_specs=P(ROW_AXIS),
    )
    def kernel(pc, bc, bv):
        bc_all = jax.lax.all_gather(bc, ROW_AXIS, tiled=True)
        bv_all = jax.lax.all_gather(bv, ROW_AXIS, tiled=True)
        order = jnp.argsort(bc_all, stable=True)
        sc = bc_all[order]
        sv = bv_all[order]
        pos = jnp.searchsorted(sc, pc)
        pos = jnp.clip(pos, 0, sc.shape[0] - 1)
        hit = (sc[pos] == pc) & (pc >= 0)
        return jnp.where(hit, sv[pos], default)

    return kernel(probe_codes, build_codes, build_values)


# ---------------------------------------------------------------------------
# ring boundary exchange (sort / window frames across shards)
# ---------------------------------------------------------------------------

def ring_shift(mesh: Mesh, x: jax.Array, shift: int = 1) -> jax.Array:
    """ppermute neighbor exchange: device i receives from i-shift (ring)."""
    n_dev = mesh.devices.size
    perm = [(i, (i + shift) % n_dev) for i in range(n_dev)]

    @functools.partial(shard_map, mesh=mesh, in_specs=P(ROW_AXIS),
                       out_specs=P(ROW_AXIS))
    def kernel(v):
        return jax.lax.ppermute(v, ROW_AXIS, perm)

    return kernel(x)


def dist_prefix_sum(mesh: Mesh, x: jax.Array) -> jax.Array:
    """Global inclusive prefix sum over a row-sharded array: local cumsum +
    exclusive scan of shard totals via all_gather (windows/LIMIT borders —
    the reference's partition-length cumsum, sort.py:88)."""

    @functools.partial(shard_map, mesh=mesh, in_specs=P(ROW_AXIS),
                       out_specs=P(ROW_AXIS))
    def kernel(v):
        local = jnp.cumsum(v)
        total = local[-1] if v.shape[0] else jnp.zeros((), v.dtype)
        totals = jax.lax.all_gather(total, ROW_AXIS)
        idx = jax.lax.axis_index(ROW_AXIS)
        offset = jnp.where(jnp.arange(totals.shape[0]) < idx, totals, 0).sum()
        return local + offset

    return kernel(x)
