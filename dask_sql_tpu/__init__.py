"""dask_sql_tpu: a TPU-native distributed SQL query engine.

Brand-new implementation of the capability surface of dask-sql
(/root/reference): a ``Context`` catalog + SQL entry point, a native SQL
parser/planner with rule-based optimization, and a plugin-registry physical
layer — lowering relational algebra to compiled JAX/XLA columnar kernels over
mesh-sharded ``jax.Array`` tables instead of lazy Dask dataframe graphs.
"""

# SQL semantics need BIGINT/DOUBLE: enable 64-bit JAX before anything imports
# jax.numpy.  (TPU-hot kernels downcast explicitly where it matters.)
import jax as _jax

_jax.config.update("jax_enable_x64", True)

from .context import Context  # noqa: E402
from .cmd import cmd_loop  # noqa: E402
from .server.app import run_server  # noqa: E402

__version__ = "0.1.0"

__all__ = ["Context", "cmd_loop", "run_server", "__version__"]
