"""Placeholder — real Context lands with the physical layer."""
class Context:
    pass
