"""Context: the single user-facing object — catalog + SQL entry point.

API parity with the reference Context (/root/reference/dask_sql/context.py:36-826):
``create_table``, ``drop_table``, ``create_schema``, ``register_function``,
``register_aggregation``, ``register_model``, ``sql``, ``explain``, ``fqn``,
``ipython_magic``, ``run_server``.  Differences are intentional and TPU-native:
``sql`` returns a device-columnar ``Table`` (the analogue of the lazy dask
frame — data lives on device; ``.to_pandas()`` is the ``.compute()``
equivalent), and the planner is our native parser/binder/optimizer instead of
the JPype/Calcite bridge.
"""
from __future__ import annotations

import itertools
import logging
import os
import threading
from typing import Any, Callable, List, Optional, Tuple, Union

from .datacontainer import FunctionDescription, SchemaContainer, TableEntry
from .io.inputs import (
    ArrowInputPlugin, BaseInputPlugin, DeviceTableInputPlugin, DictInputPlugin,
    HiveInputPlugin, InputUtil, IntakeCatalogInputPlugin, LocationInputPlugin,
    PandasLikeInputPlugin,
)
from .plan.binder import Binder
from .plan.nodes import Field, RelNode
from .plan.optimizer import optimize
from .sql import ast as A
from .sql.parser import parse_sql
from .table import Table
from .types import SqlType, parse_type_name, sql_type_from_numpy
from .utils import ParsingException

logger = logging.getLogger(__name__)


def _tenancy_on() -> bool:
    # tenancy gate (runtime/tenancy.py): env checked BEFORE any import so
    # DSQL_TENANCY=0 keeps the module out of the process entirely
    return os.environ.get("DSQL_TENANCY", "1").strip() not in ("", "0")


def _ingest_on() -> bool:
    # continuous-ingestion gate (runtime/ingest.py): DSQL_INGEST_DIR arms,
    # DSQL_INGEST=0 kills — both checked BEFORE any import so the unarmed
    # write/read paths stay bit-for-bit baseline with the module absent
    return bool(os.environ.get("DSQL_INGEST_DIR")) and \
        os.environ.get("DSQL_INGEST", "1").strip() not in ("0", "false")


class Context:
    """Main entry point: holds schemas/tables/functions/models and runs SQL.

    Example (reference README):

        from dask_sql_tpu import Context
        c = Context()
        c.create_table("t", df)
        result = c.sql("SELECT name, SUM(x) FROM t GROUP BY name")
    """

    DEFAULT_SCHEMA_NAME = "root"

    def __init__(self, logging_level=logging.INFO, mesh=None):
        """``mesh``: a 1-D ``jax.sharding.Mesh`` — tables registered on this
        context are row-sharded over it and queries compile to SPMD programs
        with XLA-inserted collectives (the distributed mode; the reference
        attaches a dask cluster instead, SURVEY §2.3)."""
        self.schema_name = self.DEFAULT_SCHEMA_NAME
        self.schema = {self.DEFAULT_SCHEMA_NAME: SchemaContainer(self.DEFAULT_SCHEMA_NAME)}
        self.server = None
        self.mesh = mesh
        self._has_chunked = False
        # catalog epochs: monotonic per-table versions bumped by every
        # mutating path (create/drop/alter, CTAS, schema ops) — the
        # correctness backbone of the result cache (runtime/result_cache.py):
        # the epoch joins every cache key, so a mutated table can never
        # serve a stale cached result
        self._table_epochs: dict = {}
        self._epoch_counter = itertools.count(1)
        # per-table append serialization: _apply_delta's read-concat-swap
        # must not interleave between two writers on the same table (the
        # later swap would discard the earlier batch's rows); the ingest
        # log holds the same lock across its WAL write so WAL order
        # matches apply order.  RLock: replay calls _apply_delta under it.
        self._append_locks: dict = {}
        self._append_locks_guard = threading.Lock()
        # the lazily-materialized builtin "system" schema sentinel
        # (runtime/system_tables.py): created on first system.* resolution;
        # a user schema literally named "system" shadows it
        self._system_schema: Optional[SchemaContainer] = None
        # PREPARE registry: name -> PrepareStatement (parsed AST + text).
        # EXECUTE binds the stored AST with fresh values; system.prepared
        # lists entries (physical/rel/custom.py, runtime/system_tables.py)
        self._prepared: dict = {}
        # fleet plane (runtime/fleet.py): arm once per process when a
        # shared fleet dir is configured — env checked BEFORE the import
        # so the unarmed path stays zero-import (the recorder/profiler
        # discipline).  Idempotent: the second Context is a no-op.
        if os.environ.get("DSQL_FLEET_DIR"):
            try:
                from .runtime import fleet as _fleet
                _fleet.ensure_armed()
            except Exception:
                logger.debug("fleet arming failed", exc_info=True)
        # continuous ingestion (runtime/ingest.py): same env-before-import
        # discipline — an unset DSQL_INGEST_DIR (or DSQL_INGEST=0) leaves
        # the module un-imported and the write path bit-for-bit baseline.
        # Arming opens the per-table WAL and replays committed batches for
        # tables registered later (create_table calls maybe_replay).
        if _ingest_on():
            try:
                from .runtime import ingest as _ing
                _ing.ensure_armed(self)
            except Exception:
                logger.debug("ingest arming failed", exc_info=True)
        # register default input plugins (reference context.py:113-119 order)
        for plugin in (DeviceTableInputPlugin(), PandasLikeInputPlugin(),
                       DictInputPlugin(), ArrowInputPlugin(), HiveInputPlugin(),
                       IntakeCatalogInputPlugin(), LocationInputPlugin()):
            InputUtil.add_plugin(type(plugin).__name__, plugin, replace=False)
        # statement plugins live in physical/rel/custom.py; import registers them
        from .physical.rel import custom  # noqa: F401

    # ------------------------------------------------------------- epochs
    def table_epoch(self, schema_name: str, table_name: str) -> int:
        """Current catalog epoch of (schema, table); 0 = never mutated
        since this Context was created.  Under an armed ingest subsystem
        a query running inside a snapshot pin (runtime/ingest.py) reads
        the epoch AS OF admission, so result-cache keys stay consistent
        with the pinned table contents."""
        if _ingest_on():
            from .runtime import ingest as _ing
            pinned = _ing.pinned_epoch(schema_name, table_name.lower())
            if pinned is not None:
                return pinned
        return self._table_epochs.get((schema_name, table_name.lower()), 0)

    def catalog_entry(self, schema_name: str, table_name: str):
        """The executor-facing catalog read (physical/rel/executor.py,
        physical/compiled.py): identical to
        ``self.schema[schema_name].tables[table_name]`` except that inside
        a snapshot pin it returns the entry captured at admission — a
        query sees one consistent prefix of every table it scans even
        while the ingest writer keeps appending.  Raises KeyError exactly
        like the direct lookup."""
        if _ingest_on():
            from .runtime import ingest as _ing
            entry = _ing.pinned_entry(schema_name, table_name)
            if entry is not None:
                return entry
        return self.schema[schema_name].tables[table_name]

    def bump_table_epoch(self, schema_name: str, table_name: str,
                         delta: Optional[Table] = None) -> int:
        """Advance the table's epoch (every mutating path calls this) and
        drop any cached results that reference it.

        ``delta``: the appended batch, when the mutation is a pure append
        (``append_rows`` / INSERT INTO).  Recorded on the materialized-view
        registry so dependent maintainable views refresh in O(delta);
        omitted (every other caller) the bump is a hard tombstone — the
        delta log clears and dependents recompute in full."""
        key = (schema_name, table_name.lower())
        epoch = next(self._epoch_counter)
        self._table_epochs[key] = epoch
        from .runtime import result_cache as _rc
        _rc.get_cache().invalidate_table(schema_name, table_name.lower())
        reg = self.__dict__.get("_matview_registry")
        if reg is not None:
            if delta is not None:
                reg.record_delta(key, epoch, delta)
            else:
                reg.record_overwrite(key, epoch)
        return epoch

    # ------------------------------------------------------------- schemas
    def create_schema(self, schema_name: str):
        self.schema[schema_name] = SchemaContainer(schema_name)

    def drop_schema(self, schema_name: str):
        if schema_name == self.DEFAULT_SCHEMA_NAME:
            raise RuntimeError(f"Default schema {schema_name} cannot be deleted")
        reg = self.__dict__.get("_matview_registry")
        if reg is not None:
            reg.discard_schema(schema_name)
        for table_name in list(self.schema[schema_name].tables):
            self.bump_table_epoch(schema_name, table_name)
        del self.schema[schema_name]
        if self.schema_name == schema_name:
            self.schema_name = self.DEFAULT_SCHEMA_NAME

    # -------------------------------------------------------------- tables
    def create_table(self, table_name: str, input_table: Any,
                     format: Optional[str] = None, persist: bool = False,
                     schema_name: Optional[str] = None,
                     statistics: Optional[dict] = None, gpu: bool = False,
                     chunked: bool = False, batch_rows: Optional[int] = None,
                     **kwargs):
        """Register anything the input plugins understand as a SQL table.

        ``persist`` keeps parity with the reference (context.py:121-204); data
        always lives on device here, so it is a no-op flag.

        ``chunked=True``: out-of-HBM mode — the data stays host-resident as
        encoded columnar batches (``batch_rows`` rows each) and queries
        stream it through the device one batch at a time
        (physical/streaming.py), the TPU analogue of the reference's
        partitioned-dataframe ingestion (input_utils/convert.py:38-62).
        Accepts a pandas frame or a parquet path.
        """
        schema_name = schema_name or self.schema_name
        reg = self.__dict__.get("_matview_registry")
        if reg is not None:
            # re-registering a name that was a materialized view is an
            # overwrite: the registry entry goes, the bump below tombstones
            reg.discard_view(schema_name, table_name.lower())
        if chunked:
            # composes with mesh= : the streaming executor row-shards each
            # uploaded batch over the mesh (physical/streaming.py
            # _set_batch_entry), so execution is out-of-core AND
            # distributed at once, like the reference's partitioned model
            from .io.chunked import DEFAULT_BATCH_ROWS, ChunkedSource
            rows = batch_rows or DEFAULT_BATCH_ROWS
            if isinstance(input_table, ChunkedSource):
                source = input_table  # pre-built (e.g. from_parquet caller)
            elif isinstance(input_table, str):
                source = ChunkedSource.from_parquet(input_table,
                                                    batch_rows=rows)
            else:
                import pandas as pd
                if not isinstance(input_table, pd.DataFrame):
                    raise TypeError("chunked=True accepts a pandas frame "
                                    "or a parquet path")
                source = ChunkedSource.from_pandas(input_table,
                                                   batch_rows=rows)
            self._has_chunked = True
            entry = TableEntry(
                table=source.schema_table(), chunked=source,
                statistics=statistics or {"row_count": source.n_rows},
                filepath=input_table if isinstance(input_table, str) else None)
            self.schema[schema_name].tables[table_name.lower()] = entry
            self.bump_table_epoch(schema_name, table_name)
            logger.debug("Registered chunked table %s.%s (%d rows, %d batches)",
                         schema_name, table_name, source.n_rows,
                         source.n_batches)
            return
        table = InputUtil.to_table(input_table, file_format=format,
                                   table_name=table_name, **kwargs)
        row_valid = None
        if self.mesh is not None:
            from .parallel.mesh import shard_table_with_validity
            table, row_valid = shard_table_with_validity(table, self.mesh)
        # ingest-time statistics (runtime/statistics.py): NDV/min-max/null
        # fraction/dense-int detection per column — the base layer of the
        # adaptive-dispatch vertical.  Best-effort: a failed collection
        # leaves entry.stats None and every consumer falls back to the
        # pre-stats behavior.
        from .runtime.statistics import collect_table_stats
        stats = collect_table_stats(table, row_valid=row_valid)
        entry = TableEntry(table=table, statistics=statistics,
                           filepath=input_table if isinstance(input_table, str) else None,
                           gpu=gpu, row_valid=row_valid, stats=stats)
        self.schema[schema_name].tables[table_name.lower()] = entry
        self.bump_table_epoch(schema_name, table_name)
        if _ingest_on():
            # restart path: committed WAL batches recorded against this
            # table in a previous process apply as soon as the base is
            # re-registered (crash recovery loses zero committed batches).
            # With nothing pending this is a mid-run (re-)register: the
            # new source supersedes any logged history, so the table's
            # segments truncate — replaying them onto the fresh base on
            # a later restart would double-apply rows (and the WAL stays
            # bounded by re-registration instead of growing forever).
            try:
                from .runtime import ingest as _ing
                log = _ing.get_log(self, create=True)
                if log.has_pending(schema_name, table_name.lower()):
                    log.maybe_replay(schema_name, table_name.lower())
                else:
                    log.truncate(schema_name, table_name.lower())
            except Exception:
                logger.debug("ingest replay failed", exc_info=True)
        logger.debug("Registered table %s.%s (%d rows)", schema_name,
                     table_name, table.num_rows)

    def drop_table(self, table_name: str, schema_name: Optional[str] = None):
        schema_name = schema_name or self.schema_name
        reg = self.__dict__.get("_matview_registry")
        if reg is not None:
            # DROP TABLE on a materialized view tears down its registry
            # state too (maintained cache entry, delta pins)
            reg.discard_view(schema_name, table_name.lower())
        del self.schema[schema_name].tables[table_name.lower()]
        self.bump_table_epoch(schema_name, table_name)
        if _ingest_on():
            # the table's WAL history dies with it: replaying old deltas
            # into a future table registered under the same name would
            # resurrect dropped rows
            try:
                from .runtime import ingest as _ing
                log = _ing.get_log(self)
                if log is not None:
                    log.truncate(schema_name, table_name.lower())
            except Exception:
                logger.debug("ingest truncate failed", exc_info=True)

    def alter_schema(self, old_schema_name, new_schema_name):
        reg = self.__dict__.get("_matview_registry")
        if reg is not None:
            # renames re-key the catalog under the views' feet: registered
            # views (old or new schema) and views over tables in either are
            # invalidated by the tombstone bumps below; drop the registry
            # entries so stale maintained state cannot survive the rename
            reg.discard_schema(old_schema_name)
            reg.discard_schema(new_schema_name)
        self.schema[new_schema_name] = self.schema.pop(old_schema_name)
        for table_name in list(self.schema[new_schema_name].tables):
            self.bump_table_epoch(old_schema_name, table_name)
            self.bump_table_epoch(new_schema_name, table_name)

    def alter_table(self, old_table_name, new_table_name, schema_name=None):
        schema_name = schema_name or self.schema_name
        reg = self.__dict__.get("_matview_registry")
        if reg is not None:
            reg.discard_view(schema_name, old_table_name.lower())
            reg.discard_view(schema_name, new_table_name.lower())
        s = self.schema[schema_name]
        s.tables[new_table_name.lower()] = s.tables.pop(old_table_name.lower())
        self.bump_table_epoch(schema_name, old_table_name)
        self.bump_table_epoch(schema_name, new_table_name)

    def append_rows(self, table_name: str, rows: Any,
                    schema_name: Optional[str] = None) -> int:
        """Append ``rows`` to a registered resident table — the delta path
        (ISSUE 14): unlike re-``create_table``, the epoch bump carries the
        appended batch, so materialized views over the table refresh in
        O(delta) instead of recomputing (runtime/matview.py).

        ``rows``: a device ``Table``, pandas DataFrame, dict of columns, or
        list of row tuples (matched positionally).  Columns align to the
        target case-insensitively (or positionally when the names do not
        match; a named strict subset NULL-fills the rest), values cast to
        the target column types — anything that does not fit raises a
        typed ``SchemaMismatch``.  Returns the number of rows appended.
        ``INSERT INTO`` lowers to this.

        With the ingest subsystem armed (DSQL_INGEST_DIR, ISSUE 20) the
        batch goes through the write-ahead log first — durable before
        visible, possibly coalesced with neighbors (DSQL_INGEST_BATCH_*),
        priced through the memory broker (IngestBackpressure when the
        budget cannot absorb it).  The return value is then the rows made
        visible NOW (0 = accepted into the micro-batch buffer).
        """
        from .runtime.resilience import UserError

        schema_name = schema_name or self.schema_name
        entry = self.schema[schema_name].tables.get(table_name.lower())
        if entry is None:
            raise UserError(f"Table {table_name} not found in schema "
                            f"{schema_name}; create it before INSERT INTO.")
        if entry.chunked is not None:
            raise UserError(
                f"Table {table_name} is chunked (host-resident batches); "
                "appends are not supported — re-create it from the extended "
                "source instead.")
        if entry.table is None:
            raise UserError(
                f"{table_name} is a view; INSERT INTO targets tables. "
                "Append to its base tables instead.")
        reg = self.__dict__.get("_matview_registry")
        if reg is not None and (schema_name, table_name.lower()) in \
                getattr(reg, "views", {}):
            raise UserError(
                f"{table_name} is a materialized view; INSERT INTO targets "
                "base tables — the view refreshes from their appends.")
        delta = _coerce_delta(entry.table, rows)
        if delta.num_rows == 0:
            return 0
        if _ingest_on():
            from .runtime import ingest as _ing
            log = _ing.get_log(self, create=True)
            return log.commit(schema_name, table_name.lower(), delta)
        return self._apply_delta(schema_name, table_name.lower(), delta)

    def _append_lock(self, schema_name: str, table_name: str):
        """The per-(schema, table) lock every append takes across its whole
        read-concat-swap (and, under an armed ingest log, across the WAL
        write too, so WAL order matches apply order)."""
        key = (schema_name, table_name.lower())
        with self._append_locks_guard:
            lock = self._append_locks.get(key)
            if lock is None:
                lock = self._append_locks[key] = threading.RLock()
            return lock

    def _apply_delta(self, schema_name: str, table_name: str,
                     delta: Table) -> int:
        """Make one coerced batch visible: new catalog entry + delta-carrying
        epoch bump.  The tail of the pre-ingest ``append_rows``; the ingest
        log calls it after the WAL write (and on replay).  Re-fetches the
        entry and re-coerces — under micro-batching the table may have been
        swapped (or its schema altered) since the batch was coerced.

        Serialized per table: concurrent appends (ThreadingHTTPServer runs
        /v1/ingest handlers concurrently) each read the entry, concat, and
        swap under ``_append_lock`` — without it two writers read the same
        entry and the later swap silently discards the earlier batch."""
        with self._append_lock(schema_name, table_name):
            return self._apply_delta_locked(schema_name, table_name, delta)

    def _apply_delta_locked(self, schema_name: str, table_name: str,
                            delta: Table) -> int:
        from .ops.join import concat_tables
        from .runtime.resilience import UserError
        from .runtime.statistics import collect_table_stats

        entry = self.schema[schema_name].tables.get(table_name)
        if entry is None or entry.table is None:
            raise UserError(f"Table {table_name} not found in schema "
                            f"{schema_name}; create it before INSERT INTO.")
        delta = _coerce_delta(entry.table, delta)
        if self.mesh is not None:
            # sharded base: concat on host against the valid prefix, then
            # re-shard — appends are rare relative to scans, so the round
            # trip beats keeping a resharding kernel alive
            import numpy as np
            import pandas as pd
            from .parallel.mesh import shard_table_with_validity
            base_df = entry.table.to_pandas()
            if entry.row_valid is not None:
                base_df = base_df.iloc[
                    :int(np.asarray(entry.row_valid).sum())]
            combined = pd.concat([base_df, delta.to_pandas()],
                                 ignore_index=True)
            new_table = _coerce_delta(entry.table,
                                      Table.from_pandas(combined))
            new_table, row_valid = shard_table_with_validity(new_table,
                                                             self.mesh)
        else:
            new_table = concat_tables([entry.table, delta])
            row_valid = None
        stats = collect_table_stats(new_table, row_valid=row_valid)
        new_entry = TableEntry(
            table=new_table, statistics=entry.statistics,
            filepath=entry.filepath, gpu=entry.gpu, row_valid=row_valid,
            stats=stats)
        reg = self.__dict__.get("_matview_registry")
        if reg is not None:
            # the catalog swap and the delta record must be one atomic
            # step under the registry lock: a refresh that reads the new
            # table before its delta is logged would double-count the
            # appended rows (delta-join slices old prefixes by row count)
            with reg.lock:
                self.schema[schema_name].tables[table_name] = new_entry
                self.bump_table_epoch(schema_name, table_name, delta=delta)
        else:
            self.schema[schema_name].tables[table_name] = new_entry
            self.bump_table_epoch(schema_name, table_name, delta=delta)
        logger.debug("Appended %d rows to %s.%s (now %d)", delta.num_rows,
                     schema_name, table_name, new_table.num_rows)
        return delta.num_rows

    # ------------------------------------------------------------ functions
    def register_function(self, f: Callable, name: str,
                          parameters: List[Tuple[str, Any]] = None,
                          return_type: Any = None, replace: bool = False,
                          schema_name: Optional[str] = None,
                          row_udf: bool = False):
        """Register a scalar UDF (reference context.py:245-310).

        ``parameters``/``return_type`` accept numpy dtypes or SQL type names.
        """
        self._register_callable(f, name, False, parameters, return_type,
                                replace, schema_name, row_udf)

    def register_aggregation(self, f: Callable, name: str,
                             parameters: List[Tuple[str, Any]] = None,
                             return_type: Any = None, replace: bool = False,
                             schema_name: Optional[str] = None):
        """Register a custom aggregation (reference context.py:312-377)."""
        self._register_callable(f, name, True, parameters, return_type,
                                replace, schema_name, False)

    def _register_callable(self, f, name, aggregation, parameters, return_type,
                           replace, schema_name, row_udf):
        schema_name = schema_name or self.schema_name
        params = [(pname, _to_sql_type(t)) for pname, t in (parameters or [])]
        rt = _to_sql_type(return_type) if return_type is not None else SqlType("DOUBLE")
        fd = FunctionDescription(name=name, parameters=params, return_type=rt,
                                 aggregation=aggregation, func=f, row_udf=row_udf)
        schema = self.schema[schema_name]
        lower = name.lower()
        if not replace and lower in schema.functions and \
                schema.functions[lower].func is not f:
            raise ValueError(f"Function {name} is already registered")
        schema.functions[lower] = fd
        schema.function_lists.append(fd)

    # --------------------------------------------------------------- models
    def register_model(self, model_name: str, model: Any,
                       training_columns: List[str],
                       schema_name: Optional[str] = None):
        """Register a fitted model for PREDICT (reference context.py:497-520)."""
        schema_name = schema_name or self.schema_name
        self.schema[schema_name].models[model_name.lower()] = (model, list(training_columns))

    def _get_model(self, parts: List[str]):
        info = self.resolve_model(parts)
        if info is None:
            raise KeyError(f"Model {'.'.join(parts)} not found")
        return info

    # ------------------------------------------------------------ SQL entry
    def sql(self, sql: str, return_futures: bool = True,
            dataframes: Optional[dict] = None, gpu: bool = False,
            config_options: Optional[dict] = None,
            timeout: Optional[float] = None,
            priority: Optional[str] = None,
            params: Optional[list] = None,
            tenant: Optional[str] = None) -> Union[Table, Any]:
        """Parse, plan, optimize and execute a SQL statement.

        Returns a device ``Table`` (``return_futures=True``, the analogue of
        the reference's lazy dask frame) or a pandas DataFrame
        (``return_futures=False``, the ``.compute()`` path).

        ``timeout`` (seconds) opens a per-query deadline enforced at every
        layer checkpoint — compile attempts, stage scheduling, streamed
        batches, eager plan nodes — raising a typed
        ``runtime.resilience.DeadlineExceeded`` instead of running past the
        budget.  Defaults to ``DSQL_QUERY_TIMEOUT_MS`` (unset/0 = none);
        nested calls inherit the sooner enclosing deadline.

        Every call records a ``runtime.telemetry.QueryReport`` (span tree,
        phase timings, counter deltas, row/byte counts) on
        ``self.last_report``; ``DSQL_SLOW_QUERY_MS`` arms a slow-query log
        and ``DSQL_CHROME_TRACE_DIR`` exports each query's span tree as
        chrome://tracing JSON.

        ``priority`` (``"interactive"`` | ``"batch"`` | ``"background"``)
        sets the query's workload-manager class (runtime/scheduler.py):
        under concurrency, slots are granted by deficit-weighted priority
        with anti-starvation aging.  Defaults to ``DSQL_DEFAULT_PRIORITY``
        (or ``interactive``); the server maps its ``X-DSQL-Priority``
        header here.  Time spent queued counts against ``timeout`` and
        shows up as the ``queued`` phase of the QueryReport.

        ``params`` binds positional ``?`` / ``$n`` markers in the statement
        to python values (client-side prepared statements).  Combined with
        parameterized plan identity (plan/parameterize.py) every distinct
        value list reuses one compiled program per query shape.

        ``tenant`` names the tenant this query bills against
        (runtime/tenancy.py; the server maps its ``X-DSQL-Tenant`` header
        here): per-tenant token-bucket rate (``DSQL_TENANT_QPS``) and
        concurrency (``DSQL_TENANT_CONCURRENT``) quotas plus a per-tenant
        circuit breaker (``DSQL_TENANT_BREAKER``) are enforced at
        admission, raising typed ``TenantQuotaExceeded`` /
        ``TenantCircuitOpen`` (429 + Retry-After on the server wire).
        Unset = the ``default`` tenant; all quotas default to unlimited,
        and ``DSQL_TENANCY=0`` disables the subsystem entirely.
        """
        from .runtime import (resilience as _res, scheduler as _sched,
                              telemetry as _tel)

        from contextlib import nullcontext
        ten_scope = nullcontext()
        if tenant is not None and _tenancy_on():
            from .runtime import tenancy as _ten
            ten_scope = _ten.tenant_scope(tenant)

        if dataframes is not None:
            for df_name, df in dataframes.items():
                self.create_table(df_name, df, gpu=gpu)

        # per-call wall breakdown, overwritten by every sql() call: over a
        # remote TPU the interesting split is host planning vs the (single)
        # device round trip vs host decode — bench.py journals this so a
        # slow query names its own bottleneck
        import time as _time
        trace = None
        try:
            with _res.query_scope(timeout_s=timeout), \
                    _tel.trace_scope(sql) as trace, \
                    _sched.priority_scope(priority), ten_scope:
                t0 = _time.perf_counter()
                with _tel.span("parse"):
                    stmts = parse_sql(sql)
                timings = {"parse_ms": (_time.perf_counter() - t0) * 1e3,
                           "plan_ms": 0.0, "exec_ms": 0.0, "fetch_ms": 0.0}
                self.last_timings = timings
                result = None
                for stmt in stmts:
                    result = self._execute_statement(stmt, sql,
                                                     params=params)
                if result is None:
                    result = Table([], [])
                if trace is not None and isinstance(result, Table):
                    trace.root.attrs["rows_out"] = result.num_rows
                    trace.root.attrs["bytes_out"] = sum(
                        int(getattr(c.data, "nbytes", 0))
                        for c in result.columns)
                if not return_futures and isinstance(result, Table):
                    t0 = _time.perf_counter()
                    with _tel.span("fetch"):
                        result = result.to_pandas()
                    timings["fetch_ms"] = (_time.perf_counter() - t0) * 1e3
                    return result
                return result
        finally:
            # the report is built when the trace CLOSES (the with-exit
            # above), so it is published here — on success and failure
            # alike; nested sql() calls (trace is None) ride the outer
            # query's report instead of overwriting it
            if trace is not None and trace.report is not None:
                self.last_report = trace.report
                timings = getattr(self, "last_timings", None)
                if timings is not None:
                    # compile/device/materialize phase split joins the
                    # bench-journaled breakdown (attributable BENCH_r*.json)
                    for k in ("compile", "device", "materialize"):
                        v = trace.report.phases.get(k)
                        if v is not None:
                            timings[f"{k}_ms"] = v

    def _execute_statement(self, stmt: A.Statement, sql: str,
                           params: Optional[list] = None):
        from .physical.rel.custom import StatementDispatcher
        from .runtime import telemetry as _tel

        import time as _time
        timings = getattr(self, "last_timings", None)
        if isinstance(stmt, A.QueryStatement):
            t0 = _time.perf_counter()
            with _tel.span("plan"):
                plan = self._get_plan(stmt.query, sql, params=params)
            if timings is not None:
                timings["plan_ms"] += (_time.perf_counter() - t0) * 1e3
                t0 = _time.perf_counter()
                try:
                    with _tel.span("execute"):
                        return self._execute_query_plan(plan)
                finally:
                    timings["exec_ms"] += (_time.perf_counter() - t0) * 1e3
            with _tel.span("execute"):
                return self._execute_query_plan(plan)
        handler = StatementDispatcher.get_plugin(type(stmt).__name__)
        with _tel.span("execute", statement=type(stmt).__name__):
            return handler(stmt, self, sql)

    def _execute_query_plan(self, plan):
        # every device-executing plan — server, direct sql(), streaming,
        # CREATE MODEL's training query — passes through the workload
        # manager first: bounded admission, priority pick, working-set
        # reservation.  Disabled (DSQL_MAX_CONCURRENT_QUERIES=0) or nested
        # plans pass straight through (admission yields None).
        # Tenancy admission wraps OUTSIDE the scheduler's: a tenant over
        # quota must be rejected before it consumes a slot or queue
        # position (env-gated before import; a server pre-claim is
        # adopted here instead of re-claimed).
        from contextlib import nullcontext
        from .runtime import scheduler as _sched

        ten_adm = nullcontext()
        if _tenancy_on():
            from .runtime import tenancy as _ten
            ten_adm = _ten.admission()
        # snapshot isolation under the ingest writer (runtime/ingest.py):
        # pin every scanned table's (entry, epoch) at admission — the
        # query then reads one consistent prefix of the delta log however
        # long it runs and wherever its scans execute
        pin = nullcontext()
        if _ingest_on():
            from .runtime import ingest as _ing
            pin = _ing.pin_scope(self, plan)
        with ten_adm, _sched.get_manager().admission(plan, self), pin:
            return self._run_query_plan(plan)

    def _run_query_plan(self, plan):
        from .physical.rel.executor import RelExecutor
        from .runtime import result_cache as _rc, telemetry as _tel

        # out-of-HBM tables route through the streaming executor — the
        # resident paths below must never compute on their binding stubs.
        # (_has_chunked guards the per-query plan walk + import: contexts
        # that never registered a chunked table skip it entirely)
        if self._has_chunked:
            from .physical.streaming import (execute_streaming,
                                             plan_references_chunked)
            if plan_references_chunked(plan, self):
                if (os.environ.get("DSQL_AUTOPILOT", "0").strip()
                        not in ("", "0")):
                    # adaptive re-planning covers the streaming tier too
                    # (the grace-join partition hint lives there), but the
                    # fingerprint rides a SEPARATE attr: chunked sources
                    # have no stable content identity, so they must stay
                    # out of the flight recorder's plan_fp stats and out
                    # of system.view_candidates
                    from .runtime import autopilot as _ap
                    from .runtime import flight_recorder as _fr
                    fp = None
                    try:
                        fp = _fr.plan_fingerprint(plan, self)
                        if fp is not None:
                            _tel.annotate(autopilot_fp=fp)
                    except Exception:
                        logger.debug("plan fingerprint failed",
                                     exc_info=True)
                    _ap.begin_query(fp, self)
                    try:
                        return execute_streaming(plan, self)
                    finally:
                        _ap.end_query()
                return execute_streaming(plan, self)
        # result cache: an identical plan over unmutated tables (same
        # catalog epochs + table uids) replays its materialized result and
        # skips device execution entirely; volatile plans key to None
        cache = _rc.get_cache()
        ckey = _rc.plan_key(plan, self) if cache.enabled() else None
        if ckey is not None:
            # EXPLAIN PROFILE measures a real execution: the lookup is
            # skipped (the store below still refreshes the entry)
            if getattr(self, "_rc_bypass", False):
                _tel.annotate(result_cache="bypass")
            else:
                hit = cache.get(ckey)
                if hit is not None:
                    table, tier = hit
                    _tel.inc("result_cache_hits")
                    _tel.annotate(result_cache="hit",
                                  result_cache_tier=tier)
                    # the hit bypasses execution, so stamp the plan
                    # fingerprint HERE: the cache-hit envelope keeps the
                    # hot query's rank in system.view_candidates accruing
                    # (the candidate-starvation fix)
                    if os.environ.get("DSQL_HISTORY_FILE"):
                        try:
                            from .runtime import flight_recorder as _fr
                            fp = _fr.plan_fingerprint(plan, self)
                            if fp is not None:
                                _tel.annotate(plan_fp=fp)
                        except Exception:
                            logger.debug("plan fingerprint failed",
                                         exc_info=True)
                    return table
                _tel.inc("result_cache_misses")
        autopilot_on = (os.environ.get("DSQL_AUTOPILOT", "0").strip()
                        not in ("", "0"))
        # flight recorder (runtime/flight_recorder.py): stamp the canonical
        # plan fingerprint on the execute span so the completion envelope
        # and the EWMA statistics history key to it.  Env-gated BEFORE the
        # import — with the recorder off this path allocates nothing.
        # (autopilot keys its hints on the same fingerprint)
        fp = None
        if os.environ.get("DSQL_HISTORY_FILE") or autopilot_on:
            try:
                from .runtime import flight_recorder as _fr
                fp = _fr.plan_fingerprint(plan, self)
                if fp is not None:
                    _tel.annotate(plan_fp=fp)
            except Exception:
                logger.debug("plan fingerprint failed", exc_info=True)
        if autopilot_on:
            # autopilot (runtime/autopilot.py): exact repeats of a managed
            # view's defining query answer from the maintained state, and
            # any active re-plan hint for this fingerprint scopes to this
            # execution (env checked before the import, same discipline)
            from .runtime import autopilot as _ap
            served = _ap.try_serve(plan, self)
            if served is not None:
                return served
            _ap.begin_query(fp, self)
        try:
            # SPMD multi-chip backend (parallel/spmd.py): with a device
            # mesh attached, stages execute as explicit shard_map programs
            # over row-sharded tables.  None means the plan is outside the
            # SPMD envelope or a runtime safety flag tripped — the
            # single-device tiers below serve it instead.
            result = None
            span = _tel.current_span()
            if self.mesh is not None:
                from .parallel.spmd import try_execute_spmd
                result = try_execute_spmd(plan, self)
                if result is not None and span is not None:
                    span.attrs.setdefault("tier", "spmd")
            # whole-plan jit (one device dispatch per query); falls back to
            # the eager per-op executor for plan shapes outside its subset
            if result is None:
                from .physical.compiled import try_execute_compiled
                result = try_execute_compiled(plan, self)
            # execution-tier annotation (tiered execution,
            # physical/compiled): "compiled", "eager", or the gate's own
            # "eager-compiling" — the gate's verdict wins, so only fill in
            # when it said nothing
            if result is None:
                if span is not None:
                    span.attrs.setdefault("tier", "eager")
                result = RelExecutor(self).execute(plan)
            elif span is not None:
                span.attrs.setdefault("tier", "compiled")
            # populate only on the success path: a crashed /
            # deadline-exceeded execution raised before this line and
            # never reaches the cache
            if ckey is not None and result is not None \
                    and cache.put(ckey, result):
                _tel.annotate(result_cache="store")
            return result
        finally:
            if autopilot_on:
                from .runtime import autopilot as _ap
                _ap.end_query()

    def _get_plan(self, query: A.SelectLike, sql: str = "",
                  params: Optional[list] = None) -> RelNode:
        binder = Binder(self, sql, params=params)
        plan = binder.bind(query)
        # context threads through so the stats-driven join-order pass
        # (plan/optimizer.py reorder_joins_stats) can rank join orders by
        # estimated output cardinality
        return optimize(plan, context=self)

    def explain(self, sql: str, dataframes: Optional[dict] = None) -> str:
        """Return the optimized plan as a string (reference context.py:442-468)."""
        if dataframes is not None:
            for df_name, df in dataframes.items():
                self.create_table(df_name, df)
        stmts = parse_sql(sql)
        stmt = stmts[0]
        if isinstance(stmt, A.ExplainStatement):
            query = stmt.query
        elif isinstance(stmt, A.QueryStatement):
            query = stmt.query
        else:
            return f"-- {type(stmt).__name__}"
        return self._get_plan(query, sql).explain()

    def visualize(self, sql: str, filename: str = "mydask.png"):
        """Plan visualization: writes the text plan (no graphviz dependency)."""
        text = self.explain(sql)
        with open(filename.rsplit(".", 1)[0] + ".txt", "w") as f:
            f.write(text)
        return text

    def profile(self, sql: str, trace_dir: str = "/tmp/dsql_trace"):
        """Run a query under the XLA/JAX profiler and return the result.

        The reference delegates profiling to the dask dashboard (SURVEY §5);
        here device-side timing lives in an XLA trace viewable with
        TensorBoard or Perfetto (``trace_dir`` holds the .trace files).
        """
        import jax

        with jax.profiler.trace(trace_dir):
            result = self.sql(sql)
            for col in getattr(result, "columns", []):
                col.data.block_until_ready()
        logger.info("XLA trace written to %s", trace_dir)
        return result

    # ----------------------------------------------------- catalog interface
    def fqn(self, identifier: Union[str, List[str]]) -> Tuple[str, str]:
        """Split a (qualified) name into (schema, name) (reference context.py:608-632)."""
        if isinstance(identifier, str):
            parts = identifier.split(".")
        else:
            parts = list(identifier)
        if len(parts) == 2 and parts[0] in self.schema:
            return parts[0], parts[1].lower()
        return self.schema_name, ".".join(parts).lower()

    def resolve_table(self, parts: List[str]):
        """Binder hook: (schema, table, fields, view_plan) or None."""
        if len(parts) == 2 and parts[0] == "system":
            resolved = self._resolve_system_table(parts[1])
            if resolved is not None:
                return resolved
        candidates = []
        if len(parts) == 1:
            candidates.append((self.schema_name, parts[0]))
        elif len(parts) >= 2:
            candidates.append((parts[0], ".".join(parts[1:])))
            candidates.append((self.schema_name, ".".join(parts)))
        for schema_name, table_name in candidates:
            schema = self.schema.get(schema_name)
            if schema is None:
                continue
            entry = schema.tables.get(table_name.lower())
            if entry is None:
                entry = schema.tables.get(table_name)
            if entry is not None:
                # materialized-view serve hook (runtime/matview.py): a view
                # whose base tables advanced refreshes HERE, before the scan
                # binds — stale maintained state is never served.  getattr
                # keeps the common no-MV path allocation-free.
                reg = self.__dict__.get("_matview_registry")
                if reg is not None:
                    entry = reg.maybe_serve(self, schema_name,
                                            table_name.lower(), entry)
                if entry.table is not None:
                    fields = [Field(n, c.stype) for n, c in
                              zip(entry.table.names, entry.table.columns)]
                    return schema_name, table_name.lower(), fields, None
                return schema_name, table_name.lower(), list(entry.plan.schema), entry.plan
        return None

    def _resolve_system_table(self, table_name: str):
        """Lazily bind ``system.<name>`` to a FRESH snapshot of live engine
        state (runtime/system_tables.py).  The snapshot Table is registered
        into a sentinel SchemaContainer so the executor's ordinary
        schema[..].tables[..] lookup scans the exact rows the binder saw;
        the next resolution rebuilds it.  A user-created schema named
        "system" takes precedence (None falls through to normal lookup);
        catalog epochs are never touched — system scans are marked volatile
        by the result cache instead (result_cache._canon_rel)."""
        existing = self.schema.get("system")
        if existing is not None and existing is not self._system_schema:
            return None  # user schema shadows the builtin
        from .runtime import system_tables as _sys

        name = table_name.lower()
        tbl = _sys.build(name, self)
        if tbl is None:
            return None
        if self._system_schema is None:
            self._system_schema = SchemaContainer("system")
        self.schema["system"] = self._system_schema
        self._system_schema.tables[name] = TableEntry(table=tbl)
        fields = [Field(n, c.stype)
                  for n, c in zip(tbl.names, tbl.columns)]
        return "system", name, fields, None

    def get_function(self, name: str) -> Optional[FunctionDescription]:
        for schema_name in (self.schema_name, self.DEFAULT_SCHEMA_NAME):
            schema = self.schema.get(schema_name)
            if schema is None:
                continue
            fd = schema.functions.get(name.lower())
            if fd is not None:
                return fd
        return None

    def resolve_model(self, parts: List[str]):
        if len(parts) == 2 and parts[0] in self.schema:
            schema_name, model_name = parts[0], parts[1]
        else:
            schema_name, model_name = self.schema_name, ".".join(parts)
        return self.schema[schema_name].models.get(model_name.lower())

    # --------------------------------------------------------- integrations
    def ipython_magic(self, auto_include: bool = False):
        """Register the %%sql magic (reference integrations/ipython.py:62-133)."""
        from .integrations.ipython import ipython_integration
        ipython_integration(self, auto_include=auto_include)

    def run_server(self, **kwargs):
        """Start the Presto-protocol HTTP server on this context
        (reference context.py:585-605)."""
        from .server.app import run_server
        return run_server(context=self, **kwargs)

    def stop_server(self):
        if self.server is not None:
            self.server.shutdown()
            self.server = None


def _coerce_delta(target: Table, rows: Any) -> Table:
    """Shape ``rows`` into a Table matching ``target``'s column names and
    types (append_rows' alignment/cast step).  Anything that does not fit
    the target schema raises a typed ``SchemaMismatch`` (a ``UserError``:
    the server wire maps it to HTTP 400) naming the offending columns —
    never a raw coercion traceback."""
    import pandas as pd

    from .physical.rex.cast import cast_column
    from .runtime.resilience import SchemaMismatch, UserError

    if isinstance(rows, Table):
        df = rows.to_pandas()
    elif isinstance(rows, pd.DataFrame):
        df = rows
    elif isinstance(rows, dict):
        df = pd.DataFrame(rows)
    elif isinstance(rows, (list, tuple)):
        width = {len(r) for r in rows if isinstance(r, (list, tuple))}
        if width - {len(target.names)}:
            raise SchemaMismatch(
                f"appended row tuples have {sorted(width)} values but the "
                f"table has {len(target.names)} columns "
                f"({list(target.names)})")
        df = pd.DataFrame(list(rows), columns=list(target.names))
    else:
        raise UserError(
            "append_rows accepts a Table, pandas DataFrame, dict of "
            f"columns, or list of row tuples; got {type(rows).__name__}")
    lower_map = {str(c).lower(): c for c in df.columns}
    target_lower = {n.lower() for n in target.names}
    if all(n.lower() in lower_map for n in target.names) and \
            len(df.columns) == len(target.names):
        df = df[[lower_map[n.lower()] for n in target.names]]
        df = df.set_axis(list(target.names), axis=1)
    elif len(df.columns) == len(target.names):
        df = df.set_axis(list(target.names), axis=1)  # positional order
    elif 0 < len(df.columns) < len(target.names) and \
            set(lower_map) <= target_lower:
        # named strict subset: the batch supplies some target columns by
        # name — NULL-fill the rest (INSERT INTO t (a, c) semantics)
        df = pd.DataFrame({
            n: (df[lower_map[n.lower()]].reset_index(drop=True)
                if n.lower() in lower_map
                else pd.Series([None] * len(df), dtype=object))
            for n in target.names})
    else:
        extra = sorted(set(lower_map) - target_lower)
        missing = sorted(target_lower - set(lower_map))
        detail = []
        if extra:
            detail.append(f"unknown column(s) {extra}")
        if missing:
            detail.append(f"missing column(s) {missing}")
        raise SchemaMismatch(
            f"appended rows have columns {list(df.columns)} but the table "
            f"has {list(target.names)}: " + "; ".join(detail) +
            " — supply target columns by name (any case, a subset "
            "NULL-fills the rest) or all of them positionally")
    delta = Table.from_pandas(df)
    cols = []
    for col, tgt, name in zip(delta.columns, target.columns, target.names):
        if col.stype.name != tgt.stype.name:
            try:
                col = cast_column(col, tgt.stype)
            except Exception as exc:
                raise SchemaMismatch(
                    f"column {name!r} of the appended rows "
                    f"({col.stype.name}) does not cast to the table's "
                    f"{tgt.stype.name}: {exc}") from exc
        cols.append(col)
    return Table(list(target.names), cols)


def _to_sql_type(t) -> SqlType:
    if isinstance(t, SqlType):
        return t
    if isinstance(t, str):
        return parse_type_name(t)
    if t is int:
        return SqlType("BIGINT")
    if t is float:
        return SqlType("DOUBLE")
    if t is str:
        return SqlType("VARCHAR")
    if t is bool:
        return SqlType("BOOLEAN")
    return sql_type_from_numpy(t)
