"""Host-side runtime supervision: resilience policy + fault injection.

``resilience``  — typed error taxonomy, per-query deadlines/cancellation,
                  bounded retry/backoff, and the graceful-degradation ladder
                  the compile/execute/serve layers share.
``faults``      — deterministic named-site fault injection so every rung of
                  the ladder is exercised in CI, not only in production.
"""
from . import faults, resilience  # noqa: F401
