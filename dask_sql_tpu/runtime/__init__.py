"""Host-side runtime supervision: resilience policy + fault injection.

``resilience``  — typed error taxonomy, per-query deadlines/cancellation,
                  bounded retry/backoff, and the graceful-degradation ladder
                  the compile/execute/serve layers share.
``faults``      — deterministic named-site fault injection so every rung of
                  the ladder is exercised in CI, not only in production.
``telemetry``   — per-query span tracer, the process metrics registry
                  (counters + gauges + bounded histograms), and QueryReports.
``result_cache``— memory-governed result & subplan cache with catalog
                  epochs (two-tier byte-accounted LRU: device → host → drop).
``scheduler``   — workload manager every query passes through before
                  execution: bounded deadline-aware admission queue,
                  deficit-weighted priority classes with anti-starvation
                  aging, and the shared device-bytes ledger the result
                  cache is a tenant of.
``quarantine``  — cross-process crash/hang quarantine (a JSON store of
                  verdicts keyed by program + device fingerprint, with
                  expiry and half-open probes) plus the compile watchdog
                  that catches builds wedged inside XLA where cooperative
                  deadline checks cannot run.
``kvstore``     — the shared cross-process JSON store plumbing (content
                  digests, atomic tmp+rename writes, corrupt-file
                  tolerance, mtime-cached reads) the caps file, the
                  quarantine store, and the program store index all use.
``program_store`` — persistent cross-process program store: serialized
                  compiled stage executables keyed by canonical program
                  identity + device/jax fingerprint, with a byte-budget
                  LRU, so a fresh process serves previously-seen queries
                  with zero XLA recompilation.
"""
from . import (faults, kvstore, program_store, quarantine,  # noqa: F401
               resilience, result_cache, scheduler, telemetry)
