"""Device-level query profiler: HBM sampling, XLA cost capture, ledger.

Armed by ``DSQL_PROFILE=1`` and costing nothing when disabled: every hot
path checks the env var BEFORE importing this module (the exact
``DSQL_HISTORY_FILE``/flight-recorder discipline — tests assert this
module never lands in ``sys.modules`` for an unprofiled query).  Three
concerns live here:

1. **Per-device memory sampling.**  Every local device's
   ``memory_stats()`` (HBM bytes in use / peak / limit) folds into the
   ``profile_hbm_*`` gauges and a bounded ring of timestamped snapshots.
   A daemon sampler thread ticks every ``DSQL_PROFILE_SAMPLE_MS``
   (default 500); every query completion also samples, so short-lived
   processes still leave snapshots.  CPU devices report no memory stats
   — rows degrade to zeros, never to an error.

2. **XLA cost-model capture.**  ``compiled.cost_analysis()`` (flops,
   bytes accessed, transcendentals) normalizes through
   :func:`cost_summary` at compile time and persists alongside the
   program-store entry (``"cost"`` key, missing-tolerant), so a warm
   process has cost estimates with zero recompilation.  Backends
   without a cost model yield ``None`` and every consumer degrades:
   EXPLAIN PROFILE prints ``n/a``, the scheduler skips its rung, store
   entries simply lack the key.

3. **Model-vs-measured ledger.**  Predicted bytes/flops accumulate per
   (query fingerprint, program digest); measured bytes/ms fold in from
   stage records.  The scheduler's estimate ladder reads
   :func:`plan_cost_bytes` as its fourth rung (history → chunked →
   stats → **cost_model** → heuristic, ``est_source="cost_model"``),
   and the predicted-vs-measured error is journaled on flight-recorder
   envelopes (``cost_err``) exactly like the history/stats rungs'
   errors — the EWMA fold-in goes through
   ``flight_recorder._observe_stat`` under ``cost_bytes``/``cost_flops``
   keys when a history file is armed.
"""
from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import telemetry as _tel

logger = logging.getLogger(__name__)

#: bounded snapshot ring: at the default 500 ms cadence this holds the
#: last minute of device-memory truth without growing
RING_LEN = 120


def enabled() -> bool:
    """True when profiling is armed (``DSQL_PROFILE`` set and not 0)."""
    return os.environ.get("DSQL_PROFILE", "0").strip() not in ("", "0")


def sample_interval_ms() -> float:
    try:
        ms = float(os.environ.get("DSQL_PROFILE_SAMPLE_MS", "500") or 500)
    except ValueError:
        ms = 500.0
    return max(ms, 10.0)


_lock = threading.Lock()
_ring: deque = deque(maxlen=RING_LEN)
_sampler_started = False

# model-vs-measured ledger: query fingerprint -> program digest ->
# predicted {"flops","bytes","transcendentals"}; and per-digest measured
# fold-ins.  Keyed per digest so repeat executions OVERWRITE instead of
# double-counting.
_ledger: Dict[str, Dict[str, Dict[str, float]]] = {}
_measured: Dict[str, Dict[str, float]] = {}


def _fp_key(query_fp: Optional[str]) -> Optional[str]:
    """Normalize compiled.py's ``query_fp`` (the ROOT plan's canonical
    compiled-pipeline text, threaded to every compile/store site) into
    the ledger key.  Writers (record_program_cost) and the reader
    (plan_cost_bytes, which recomputes the text via ``_fp_plan``) MUST
    agree, so both go through here."""
    if not query_fp:
        return None
    from .kvstore import digest_key
    return digest_key(("cost", str(query_fp)))


# ---------------------------------------------------------------------------
# device memory sampling
# ---------------------------------------------------------------------------

def device_memory_rows() -> List[Dict[str, Any]]:
    """One row per local device.  ``memory_stats()`` may be None or
    absent entirely (CPU backends) — such devices report zeros."""
    rows: List[Dict[str, Any]] = []
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # jax missing/not initialized: no rows, no error
        return rows
    for d in devices:
        try:
            mem = d.memory_stats() or {}
        except Exception:
            mem = {}
        rows.append({
            "id": int(getattr(d, "id", len(rows))),
            "platform": str(getattr(d, "platform", "?")),
            "kind": str(getattr(d, "device_kind", "?")),
            "bytes_in_use": int(mem.get("bytes_in_use", 0) or 0),
            "peak_bytes_in_use": int(mem.get("peak_bytes_in_use", 0) or 0),
            "bytes_limit": int(mem.get("bytes_limit", 0) or 0),
        })
    return rows


def sample() -> List[Dict[str, Any]]:
    """One snapshot: per-device rows into the ring + summed gauges."""
    rows = device_memory_rows()
    _tel.REGISTRY.set_gauge("profile_hbm_bytes_in_use",
                            sum(r["bytes_in_use"] for r in rows))
    _tel.REGISTRY.set_gauge("profile_hbm_peak_bytes",
                            sum(r["peak_bytes_in_use"] for r in rows))
    _tel.REGISTRY.set_gauge("profile_hbm_bytes_limit",
                            sum(r["bytes_limit"] for r in rows))
    _tel.inc("profile_samples")
    with _lock:
        _ring.append({"unix": time.time(), "devices": rows})
    return rows


def snapshots() -> List[Dict[str, Any]]:
    with _lock:
        return list(_ring)


def ensure_sampler() -> None:
    """Start the daemon sampling thread once (no-op when disabled)."""
    global _sampler_started
    if not enabled():
        return
    with _lock:
        if _sampler_started:
            return
        _sampler_started = True
    threading.Thread(target=_sample_loop, name="dsql-profiler",
                     daemon=True).start()


def _sample_loop() -> None:
    while enabled():
        try:
            sample()
        except Exception:  # sampling must never hurt the engine
            logger.debug("profiler sample failed", exc_info=True)
        time.sleep(sample_interval_ms() / 1e3)


# ---------------------------------------------------------------------------
# XLA cost-model capture
# ---------------------------------------------------------------------------

def cost_summary(compiled) -> Optional[Dict[str, float]]:
    """Normalize ``compiled.cost_analysis()`` to a small plain dict
    (``flops`` / ``bytes`` / ``transcendentals``), or None when the
    backend has no cost model (absent method, raise, None, empty or
    non-finite values) — the universal ``n/a`` signal downstream."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    # jax <= 0.4.x returns [dict] (one per computation); newer returns
    # the dict directly
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None

    def num(key: str) -> float:
        try:
            v = float(ca.get(key, 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0
        return v if math.isfinite(v) and v > 0 else 0.0

    out = {"flops": num("flops"), "bytes": num("bytes accessed"),
           "transcendentals": num("transcendentals")}
    if not (out["flops"] or out["bytes"]):
        return None
    return out


# ---------------------------------------------------------------------------
# the model-vs-measured ledger
# ---------------------------------------------------------------------------

def record_program_cost(query_fp: Optional[str], digest: str,
                        cost: Optional[Dict[str, float]]) -> None:
    """Register one program's predicted cost under a query fingerprint
    (at compile time or program-store load time).  None cost = no-op."""
    key = _fp_key(query_fp)
    if key is None or not cost:
        return
    with _lock:
        _ledger.setdefault(key, {})[str(digest)] = dict(cost)
    _tel.inc("profile_cost_captures")
    if os.environ.get("DSQL_HISTORY_FILE"):
        # fold into the flight-recorder EWMA so the cost estimate
        # survives the process (the scheduler rung's warm-read path)
        try:
            from . import flight_recorder as _fr
            _fr._observe_stat(key,
                              cost_bytes=float(cost.get("bytes", 0.0)),
                              cost_flops=float(cost.get("flops", 0.0)))
        except Exception:
            logger.debug("cost EWMA fold failed", exc_info=True)


def record_measured(digest: str, nbytes: Optional[int] = None,
                    wall_ms: Optional[float] = None,
                    device_ms: Optional[float] = None) -> None:
    """Fold one stage's measured truth into the ledger's measured side."""
    with _lock:
        ent = _measured.setdefault(str(digest), {})
        if nbytes is not None:
            ent["bytes"] = float(nbytes)
        if wall_ms is not None:
            ent["ms"] = float(wall_ms)
        if device_ms is not None:
            ent["device_ms"] = float(device_ms)


def program_costs(query_fp: Optional[str]) -> Dict[str, Dict[str, float]]:
    """Predicted costs per program digest for one query fingerprint
    (each dict also carries the measured fold-ins when present)."""
    key = _fp_key(query_fp)
    if key is None:
        return {}
    with _lock:
        out = {}
        for digest, cost in _ledger.get(key, {}).items():
            row = dict(cost)
            row.update({f"measured_{k}": v
                        for k, v in _measured.get(digest, {}).items()})
            out[digest] = row
        return out


def plan_cost_bytes(plan, context) -> Optional[int]:
    """The scheduler's ``cost_model`` rung: predicted working-set bytes
    = XLA "bytes accessed" summed over the plan's captured programs.
    The key is recomputed from the plan exactly the way the compiled
    pipeline fingerprints its root (``_fp_plan`` — an uncompilable plan
    never produced a ledger entry, so Unsupported here is just None).
    Falls back to the flight-recorder-persisted cost EWMA when this
    process hasn't compiled (or store-loaded) the plan yet.  None =
    nothing captured, the caller keeps the shape heuristic."""
    try:
        from ..physical.compiled import _fp_plan
        key = _fp_key(_fp_plan(plan, context, []))
    except Exception:
        return None
    if key is None:
        return None
    with _lock:
        costs = _ledger.get(key)
        total = (sum(c.get("bytes", 0.0) for c in costs.values())
                 if costs else 0.0)
    if total <= 0 and os.environ.get("DSQL_HISTORY_FILE"):
        try:
            from . import flight_recorder as _fr
            total = float((_fr.get_stats(key) or {}).get("cost_bytes", 0.0)
                          or 0.0)
        except Exception:
            total = 0.0
    return int(total) if total > 0 else None


def cost_error(predicted_bytes: Optional[float],
               measured_bytes: Optional[float]) -> Optional[float]:
    """Relative model error |predicted - measured| / measured, the same
    shape the bench journals for the history/stats rungs."""
    if not predicted_bytes or not measured_bytes or measured_bytes <= 0:
        return None
    return abs(float(predicted_bytes) - float(measured_bytes)) \
        / float(measured_bytes)


def on_query_complete(report) -> None:
    """Per-query hook from telemetry._close_trace (profile-gated there):
    keep the sampler alive and take one completion-time snapshot."""
    ensure_sampler()
    try:
        sample()
    except Exception:
        logger.debug("completion sample failed", exc_info=True)


def engine_section() -> Dict[str, Any]:
    """The ``profile`` section of ``GET /v1/engine``."""
    with _lock:
        plans = len(_ledger)
        programs = sum(len(v) for v in _ledger.values())
        last = _ring[-1] if _ring else None
    return {
        "enabled": True,
        "sampleMs": sample_interval_ms(),
        "samples": int(_tel.REGISTRY.get("profile_samples")),
        "costCaptures": int(_tel.REGISTRY.get("profile_cost_captures")),
        "costPlans": plans,
        "costPrograms": programs,
        "lastSnapshot": last,
    }


def reset() -> None:
    """Test hook: drop ledger + ring (the sampler flag survives)."""
    with _lock:
        _ledger.clear()
        _measured.clear()
        _ring.clear()
