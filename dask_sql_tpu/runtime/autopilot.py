"""Autopilot: the engine acting on its own telemetry (ROADMAP item 3).

Every sensor this module consumes already exists — the cost-model-vs-
measured ledger and skew probes (runtime/profiler.py annotations), the
EWMA operator history (runtime/flight_recorder.py), the ranked
``system.view_candidates`` shortlist (runtime/matview.py), SLO burn rates
(runtime/events.py) — but apart from the burn-driven shed every feedback
loop terminated in a human.  This is the third telemetry-actuated loop
after shedding and quarantine, and the first that acts on the *planner*.
Two loops:

**Matview autopilot.**  A background daemon (and the synchronous
:func:`tick` the tests/smoke drive) ranks ``system.view_candidates``
and auto-CREATEs the top unmaterialized candidate as an
``auto_mv_<fp>`` view under an explicit byte budget
(``DSQL_AUTOPILOT_MV_MB``; the state itself lives in the result cache's
ledger tenancy, so admission already prices it).  Managed views are
REFRESHed opportunistically on the tick (paying the O(delta)
maintenance off the user path) and DROPPed when their serve counter
goes cold for ``DSQL_AUTOPILOT_COLD_S``.  Volatile/system-scan plans
can never materialize — ``create_matview`` rejects them and the
fingerprint is blacklisted.  Repeats of a managed view's exact defining
query (value-mode canonical digest, so literals must match — a SHAPE
match is NOT sufficient to serve state) are served straight from the
maintained view: after a base-table append the result cache misses but
the view refreshes in O(delta).

**Adaptive re-planning.**  When a completed query's measured
``skew_ratio`` or ``cost_err`` trips ``DSQL_AUTOPILOT_SKEW`` /
``DSQL_AUTOPILOT_COST_ERR``, a per-fingerprint plan hint is recorded in
a kvstore-backed cross-process file (``DSQL_AUTOPILOT_FILE``, default
``<DSQL_HISTORY_FILE>.hints`` — the same discipline as quarantine and
caps) that flips the NEXT execution's decisions: broadcast<->exchange
join strategy at the SPMD ``_join`` seam, the group-by variant at the
``choose_groupby_variant`` seam, and the grace-hash re-partition count
in physical/morsel.py.  Decisions fold into the stage digest, so a
hinted plan compiles its own program and composes with the program
store.  Each hinted run is measured against the recorded baseline: two
strikes slower (``wall > baseline * 1.1``) and the hint reverts itself,
permanently, with the revert journaled.

Every action lands in a bounded in-memory journal (``system.autopilot``
and the ``/v1/engine`` autopilot section read it), publishes an
``autopilot.*`` event when the bus is armed, and appends a ``kind:
"autopilot"`` record to the flight recorder when the history ring is
armed.

**Zero import when off.**  Callers check ``DSQL_AUTOPILOT`` BEFORE
importing this module (the same arm-check-before-import pattern as
events/fleet/profiler); ``DSQL_AUTOPILOT=0`` restores baseline
behavior bit-for-bit and tests pin that the module never lands in
``sys.modules``.  The ``autopilot`` fault site (runtime/faults.py)
degrades a whole tick to a journaled no-op — the advisor may stall,
never break a query.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from . import telemetry as _tel
from .kvstore import MtimeCachedJsonFile

logger = logging.getLogger(__name__)

_EWMA_ALPHA = 0.3         # matches the flight recorder / scheduler EWMAs
_SLOWER_MARGIN = 1.1      # hinted run must stay under baseline x margin
_MAX_STRIKES = 2          # two measured-slower runs revert the hint
_JOURNAL_CAP = 256


# ---------------------------------------------------------------------------
# configuration (env-read per call so tests/operators flip without restart)
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return os.environ.get("DSQL_AUTOPILOT", "0").strip() not in ("", "0")


def _env_float(name: str, default: float) -> float:
    try:
        raw = os.environ.get(name, "")
        return float(raw) if raw else default
    except ValueError:
        return default


def mv_budget_bytes() -> int:
    """Total state bytes autopilot-created views may hold
    (``DSQL_AUTOPILOT_MV_MB``, default 64)."""
    return int(_env_float("DSQL_AUTOPILOT_MV_MB", 64.0) * 2**20)


def skew_threshold() -> float:
    """Measured ``skew_ratio`` at/above which a re-plan hint records
    (``DSQL_AUTOPILOT_SKEW``, default 2.0 — max/mean partition rows)."""
    return _env_float("DSQL_AUTOPILOT_SKEW", 2.0)


def cost_err_threshold() -> float:
    """Measured ``cost_err`` at/above which a re-plan hint records
    (``DSQL_AUTOPILOT_COST_ERR``, default 1.0 — the cost model was off
    by 100%)."""
    return _env_float("DSQL_AUTOPILOT_COST_ERR", 1.0)


def cold_after_s() -> float:
    """Seconds without a new serve before a managed view is dropped
    (``DSQL_AUTOPILOT_COLD_S``, default 300)."""
    return _env_float("DSQL_AUTOPILOT_COLD_S", 300.0)


def interval_s() -> float:
    """Daemon tick cadence (``DSQL_AUTOPILOT_INTERVAL_S``, default 5;
    <= 0 disables the background thread — ticks are then explicit)."""
    return _env_float("DSQL_AUTOPILOT_INTERVAL_S", 5.0)


def min_hits() -> int:
    """Candidate hit floor before auto-materialization
    (``DSQL_AUTOPILOT_MIN_HITS``, default 3)."""
    return max(int(_env_float("DSQL_AUTOPILOT_MIN_HITS", 3)), 1)


# ---------------------------------------------------------------------------
# the cross-process hint store (kvstore discipline, like quarantine/caps)
# ---------------------------------------------------------------------------

def hints_path() -> Optional[str]:
    p = os.environ.get("DSQL_AUTOPILOT_FILE")
    if p:
        return p
    h = os.environ.get("DSQL_HISTORY_FILE")
    return f"{h}.hints" if h else None


_HINTS = MtimeCachedJsonFile(hints_path)
# fallback store when neither path env is set: hints still work within
# the process (the smoke/bench always arm a file)
_MEM_HINTS: Dict[str, dict] = {}
_MEM_LOCK = threading.Lock()


def _read_hints() -> Dict[str, dict]:
    if hints_path():
        data = _HINTS.read()
        return data if isinstance(data, dict) else {}
    with _MEM_LOCK:
        return {k: dict(v) for k, v in _MEM_HINTS.items()}


def get_hint(fp: str) -> Optional[dict]:
    e = _read_hints().get(fp)
    return dict(e) if isinstance(e, dict) else None


def _write_hint(fp: str, entry: dict) -> None:
    if hints_path():
        data = _HINTS.read()
        data[fp] = entry
        _HINTS.write(data)
    else:
        with _MEM_LOCK:
            _MEM_HINTS[fp] = dict(entry)


# ---------------------------------------------------------------------------
# the action journal (system.autopilot / GET /v1/engine)
# ---------------------------------------------------------------------------

_JOURNAL: "deque[dict]" = deque(maxlen=_JOURNAL_CAP)
_J_LOCK = threading.Lock()


def _journal(action: str, *, trigger: str = "", fingerprint: str = "",
             verdict: str = "", nbytes: int = 0, **detail: Any) -> None:
    rec = {
        "unix": round(time.time(), 3),
        "action": action,
        "trigger": str(trigger)[:200],
        "fingerprint": str(fingerprint or ""),
        "verdict": str(verdict)[:200],
        "bytes": int(nbytes),
        "detail": (json.dumps(detail, sort_keys=True, default=str)[:300]
                   if detail else ""),
    }
    with _J_LOCK:
        _JOURNAL.append(rec)
    if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
        try:
            from . import events as _ev
            _ev.publish(f"autopilot.{action}", trigger=rec["trigger"],
                        fingerprint=rec["fingerprint"],
                        verdict=rec["verdict"], bytes=rec["bytes"])
        except Exception:  # pragma: no cover - the bus is advisory
            logger.debug("autopilot event publish failed", exc_info=True)
    if os.environ.get("DSQL_HISTORY_FILE"):
        try:
            from . import flight_recorder as _fr
            path = _fr.history_path()
            if path:
                _fr._append(path, {"kind": "autopilot", **rec})
        except Exception:  # pragma: no cover - history is advisory
            logger.debug("autopilot history append failed", exc_info=True)


def journal_rows() -> List[dict]:
    """Newest-last action rows for ``system.autopilot``."""
    with _J_LOCK:
        return [dict(r) for r in _JOURNAL]


# ---------------------------------------------------------------------------
# per-query hint scope (context._run_query_plan brackets executions)
# ---------------------------------------------------------------------------

class _Tls(threading.local):
    fp: Optional[str] = None
    hints: Optional[Dict[str, Any]] = None


_tls = _Tls()
_CTX_REF: Optional["weakref.ref"] = None   # daemon's tick target


def begin_query(fp: Optional[str], context) -> None:
    """Install this execution's active hints (thread-local) and remember
    the context for the daemon.  ``fp`` is the SHAPE-mode plan
    fingerprint the caller already computed (hints compose across
    literal variants, exactly like the program store)."""
    global _CTX_REF
    try:
        _CTX_REF = weakref.ref(context)
    except TypeError:  # pragma: no cover - contexts are weakrefable
        pass
    _ensure_daemon()
    _tls.fp = fp
    _tls.hints = None
    if not fp:
        return
    entry = get_hint(fp)
    if (entry and entry.get("state") == "active"
            and isinstance(entry.get("hints"), dict)):
        _tls.hints = dict(entry["hints"])
        # the feedback hook keys its measured-vs-baseline verdict on this
        # annotation: only executions that actually ran hinted are judged
        _tel.annotate(autopilot_hinted=1)
        _tel.inc("autopilot_hints_applied")


def end_query() -> None:
    _tls.fp = None
    _tls.hints = None


def current_hint(op: str) -> Optional[Any]:
    """The active hint for one decision seam ("join" / "groupby" /
    "partitions") of the query executing on THIS thread, or None."""
    h = _tls.hints
    return h.get(op) if h else None


# ---------------------------------------------------------------------------
# matview serving: exact-repeat queries answer from the maintained view
# ---------------------------------------------------------------------------

# autopilot-created views: name -> bookkeeping.  In-process state (the
# views themselves live in the context's registry); _M_LOCK guards it
# against daemon/test tick races.
_MANAGED: Dict[str, dict] = {}
_BLACKLIST: set = set()     # fingerprints that can never materialize
# cold-dropped shape fps -> drop time: the candidate's hit history stays
# hot in the flight recorder, so without a cooldown the very next tick
# would re-create the view it just dropped (create/drop thrash)
_COOLDOWN: Dict[str, float] = {}
_M_LOCK = threading.RLock()


def try_serve(plan, context):
    """Serve an exact repeat of a managed view's defining query from the
    maintained state (refresh-if-stale first).  Exactness is the
    VALUE-mode canonical digest — a shape match with different literals
    must never serve another literal's rows.  None -> execute normally."""
    with _M_LOCK:
        managed = {n: dict(m) for n, m in _MANAGED.items()}
    if not managed:
        return None
    try:
        from . import matview as _mv
        from . import result_cache as _rc
        from .kvstore import digest_key
        reg = _mv.get_registry(context)
        if reg is None or not _mv.mv_enabled():
            return None
        text, volatile, _scans = _rc.canonical_plan(plan, context)
        if volatile:
            return None
        fpv = digest_key(text)
        for name, m in managed.items():
            if m.get("value_fp") != fpv:
                continue
            entry = context.schema.get(m["schema"])
            entry = entry.tables.get(name) if entry is not None else None
            if entry is None:
                continue
            served = reg.maybe_serve(context, m["schema"], name, entry)
            if served is None or served.table is None:
                return None
            _tel.inc("autopilot_mv_serves")
            _tel.annotate(autopilot="mv_serve")
            return served.table
    except Exception:
        # serving is an optimization: any failure degrades to execution
        logger.debug("autopilot serve failed", exc_info=True)
    return None


def _table_bytes(table) -> int:
    try:
        total = 0
        for col in getattr(table, "columns", ()) or ():
            data = getattr(col, "data", None)
            nb = getattr(data, "nbytes", None)
            if nb is None:
                nb = getattr(col, "nbytes", None)
            total += int(nb or 0)
        if total:
            return total
        rows = int(getattr(table, "num_rows", 0) or 0)
        cols = len(getattr(table, "columns", ()) or ())
        return rows * max(cols, 1) * 8
    except Exception:  # pragma: no cover - sizing is best-effort
        return 0


# ---------------------------------------------------------------------------
# the tick: rank candidates, create/refresh under budget, drop cold views
# ---------------------------------------------------------------------------

def tick(context=None, now: Optional[float] = None) -> dict:
    """One synchronous autopilot pass.  Public: the unit/integration
    tests and the smoke drive it directly; the daemon calls it on its
    own cadence.  Never raises — the ``autopilot`` fault site (and any
    internal failure) degrades the whole pass to a journaled no-op."""
    if not enabled():
        return {}
    ctx = context if context is not None else (_CTX_REF() if _CTX_REF
                                               else None)
    if ctx is None:
        return {}
    if now is None:
        now = time.time()
    from . import faults as _faults
    try:
        _faults.maybe_fail("autopilot")
    except Exception as e:
        _journal("tick_fault", verdict=type(e).__name__)
        return {"faulted": True}
    _tel.inc("autopilot_ticks")
    out = {"created": 0, "refreshed": 0, "dropped": 0}
    try:
        out.update(_mv_tick(ctx, now))
    except Exception:
        logger.debug("autopilot mv tick failed", exc_info=True)
    return out


def _mv_tick(ctx, now: float) -> dict:
    from . import matview as _mv
    out = {"created": 0, "refreshed": 0, "dropped": 0}
    if not _mv.mv_enabled():
        return out
    with _M_LOCK:
        reg = _mv.get_registry(ctx)
        views = reg.views if reg is not None else {}
        # reconcile: a managed view dropped behind our back (DROP TABLE,
        # schema drop) leaves the books, freeing its budget share
        for name in list(_MANAGED):
            if (_MANAGED[name]["schema"], name) not in views:
                _MANAGED.pop(name)
        # 1) cold-drop: a view nobody served within the window goes away
        for name, m in list(_MANAGED.items()):
            mv = views.get((m["schema"], name))
            if mv is None:
                continue
            if mv.serves > m["serves_seen"]:
                m["serves_seen"] = mv.serves
                m["last_advance"] = now
            elif now - m["last_advance"] >= cold_after_s():
                try:
                    _mv.drop_matview(ctx, [m["schema"], name],
                                     if_exists=True)
                except Exception:
                    logger.debug("autopilot drop failed", exc_info=True)
                    continue
                freed = int(m["bytes"])
                _MANAGED.pop(name)
                _COOLDOWN[m["shape_fp"]] = now
                _tel.inc("autopilot_mv_drops")
                _journal("mv_drop",
                         trigger=f"cold>{cold_after_s():g}s",
                         fingerprint=m["shape_fp"], nbytes=freed,
                         view=name)
                out["dropped"] += 1
        # 2) refresh stale managed views on the tick, so maintenance is
        # paid here (idle/background) instead of on the next user read
        for name, m in list(_MANAGED.items()):
            mv = views.get((m["schema"], name))
            if mv is None:
                continue
            try:
                with reg.lock:
                    kind, _info = reg._staleness(ctx, mv)
            except Exception:
                continue
            if kind == "fresh":
                continue
            try:
                _mv.refresh_matview(ctx, [m["schema"], name])
            except Exception:
                logger.debug("autopilot refresh failed", exc_info=True)
                continue
            _tel.inc("autopilot_mv_refreshes")
            _journal("mv_refresh", trigger=kind,
                     fingerprint=m["shape_fp"], view=name)
            out["refreshed"] += 1
            entry = ctx.schema[m["schema"]].tables.get(name)
            if entry is not None and entry.table is not None:
                m["bytes"] = _table_bytes(entry.table)
        # 3) create the top unmaterialized candidate under the budget
        out["created"] = _maybe_create(ctx, now)
    return out


def _maybe_create(ctx, now: float) -> int:
    """Materialize the best-ranked eligible candidate; at most ONE per
    tick (a gentle actuator — convergence over thrash)."""
    from . import matview as _mv
    try:
        from . import flight_recorder as _fr
        if not _fr.enabled():
            return 0        # candidates come from the flight recorder
        candidates = _mv.view_candidate_rows(ctx)
    except Exception:
        logger.debug("autopilot candidate scan failed", exc_info=True)
        return 0
    budget = mv_budget_bytes()
    used = sum(int(m["bytes"]) for m in _MANAGED.values())
    floor = min_hits()
    # candidates carry the SHAPE-mode fingerprint while mv.fingerprint is
    # value-mode, so the `materialized` flag misses literal-bearing shapes
    # we already acted on — track our own shape fps too
    managed_fps = {m["shape_fp"] for m in _MANAGED.values()}
    for cand in candidates:
        fp = cand.get("fingerprint") or ""
        if (not fp or cand.get("materialized") or fp in _BLACKLIST
                or fp in managed_fps):
            continue
        if now - _COOLDOWN.get(fp, float("-inf")) < cold_after_s():
            continue        # just cold-dropped: don't thrash it back
        if int(cand.get("hits", 0)) < floor:
            continue
        sql = (cand.get("example_sql") or "").strip()
        # the history ring truncates envelopes at 500 chars: a cut-off
        # SQL text would parse to a DIFFERENT query — never act on it
        if not sql or len(sql) >= 500:
            continue
        est = int(float((_fr.get_stats(fp) or {}).get("bytes", 0) or 0))
        if used + max(est, 0) > budget:
            _journal("mv_skip", trigger="budget", fingerprint=fp,
                     nbytes=est)
            continue
        name = f"auto_mv_{fp[:12]}"
        try:
            from ..sql.parser import parse_sql
            stmts = parse_sql(sql)
            query = getattr(stmts[0], "query", None) if len(stmts) == 1 \
                else None
            if query is None:
                raise ValueError("example SQL is not a single SELECT")
            _mv.create_matview(ctx, [name], query, sql,
                               if_not_exists=True, or_replace=False)
        except Exception as e:
            # volatile / system-scan / unparseable / failed: one strike
            # and the fingerprint can never materialize
            _BLACKLIST.add(fp)
            _journal("mv_reject", trigger=type(e).__name__,
                     fingerprint=fp, error=str(e)[:160])
            continue
        schema_name, lname = ctx.fqn([name])
        entry = ctx.schema[schema_name].tables.get(lname)
        actual = (_table_bytes(entry.table)
                  if entry is not None and entry.table is not None else est)
        if used + actual > budget and actual > est:
            # the materialized state blew the estimate past the budget:
            # undo, and never retry this fingerprint
            try:
                _mv.drop_matview(ctx, [schema_name, lname], if_exists=True)
            except Exception:
                logger.debug("autopilot undo-drop failed", exc_info=True)
            _BLACKLIST.add(fp)
            _journal("mv_reject", trigger="over_budget", fingerprint=fp,
                     nbytes=actual)
            continue
        reg = _mv.get_registry(ctx)
        mvobj = reg.views.get((schema_name, lname)) if reg else None
        _MANAGED[lname] = {
            "schema": schema_name,
            "shape_fp": fp,
            # value-mode digest: the exact-match serving key
            "value_fp": mvobj.fingerprint if mvobj is not None else "",
            "bytes": int(actual),
            "serves_seen": 0,
            "last_advance": now,
            "created": now,
        }
        _tel.inc("autopilot_mv_creates")
        _journal("mv_create",
                 trigger=(f"score={float(cand.get('score', 0)):.0f} "
                          f"hits={int(cand.get('hits', 0))}"),
                 fingerprint=fp, nbytes=int(actual), view=lname)
        return 1
    return 0


# ---------------------------------------------------------------------------
# feedback: telemetry._close_trace hook (armed callers only)
# ---------------------------------------------------------------------------

def on_query_complete(report, error: Optional[BaseException] = None) -> None:
    """Judge a hinted execution against its baseline, or record a new
    hint when a threshold tripped.  Joins the _close_trace hook chain —
    never raises."""
    try:
        _feedback(report, error)
    except Exception:
        logger.debug("autopilot feedback failed", exc_info=True)


def _feedback(report, error: Optional[BaseException]) -> None:
    root = getattr(report, "root", None)
    if root is None:
        return
    fp = None
    hinted = False
    for s in root.walk():
        # autopilot_fp is the streaming tier's fingerprint attr (chunked
        # plans carry it instead of plan_fp so they stay out of the
        # flight recorder's candidate stats); either keys the hint store
        if fp is None and "plan_fp" in s.attrs:
            fp = s.attrs.get("plan_fp")
        if fp is None and "autopilot_fp" in s.attrs:
            fp = s.attrs.get("autopilot_fp")
        if s.attrs.get("autopilot_hinted"):
            hinted = True
        if s.attrs.get("autopilot") == "mv_serve":
            return          # served from a view: not an execution sample
    if not fp or error is not None or report.cache.get("hit"):
        return
    wall = float(report.wall_ms)
    entry = get_hint(fp)
    if hinted and entry is not None and entry.get("state") == "active":
        baseline = float(entry.get("baseline_ms") or 0.0)
        if baseline <= 0.0:
            entry["baseline_ms"] = wall
            entry["updated"] = time.time()
            _write_hint(fp, entry)
            return
        if wall > baseline * _SLOWER_MARGIN:
            entry["strikes"] = int(entry.get("strikes", 0)) + 1
            entry["verdict"] = "slower"
            verdict = (f"slower {wall:.1f}ms vs {baseline:.1f}ms baseline "
                       f"(strike {entry['strikes']}/{_MAX_STRIKES})")
            if entry["strikes"] >= _MAX_STRIKES:
                entry["state"] = "reverted"
                _tel.inc("autopilot_hints_reverted")
                _journal("hint_revert", trigger=entry.get("trigger", ""),
                         fingerprint=fp, verdict=verdict)
            else:
                _journal("hint_strike", trigger=entry.get("trigger", ""),
                         fingerprint=fp, verdict=verdict)
        else:
            entry["strikes"] = 0
            entry["verdict"] = "faster"
            prev = entry.get("hinted_ms")
            entry["hinted_ms"] = (wall if prev is None
                                  else _EWMA_ALPHA * wall
                                  + (1.0 - _EWMA_ALPHA) * float(prev))
            _journal("hint_verdict", trigger=entry.get("trigger", ""),
                     fingerprint=fp,
                     verdict=(f"faster {wall:.1f}ms vs {baseline:.1f}ms "
                              "baseline"))
        entry["updated"] = time.time()
        _write_hint(fp, entry)
        return
    if entry is not None:
        # recorded-but-not-yet-applied, or permanently reverted: leave it
        return
    skew = getattr(report, "skew_ratio", None)
    cerr = getattr(report, "cost_err", None)
    trigger = None
    if skew is not None and float(skew) >= skew_threshold():
        trigger = f"skew_ratio={float(skew):g}>={skew_threshold():g}"
    elif cerr is not None and float(cerr) >= cost_err_threshold():
        trigger = f"cost_err={float(cerr):g}>={cost_err_threshold():g}"
    if trigger is None:
        return
    hints = _derive_hints(report)
    if not hints:
        return
    _write_hint(fp, {
        "hints": hints, "trigger": trigger, "baseline_ms": wall,
        "state": "active", "strikes": 0, "verdict": "",
        "hinted_ms": None, "created": time.time(),
        "updated": time.time(),
    })
    _tel.inc("autopilot_hints_recorded")
    _journal("hint_record", trigger=trigger, fingerprint=fp, hints=hints)


def _derive_hints(report) -> Dict[str, Any]:
    """Flip the decisions this execution actually took — parsed from the
    recorded operator-choice lines and span attributes, never guessed."""
    hints: Dict[str, Any] = {}
    join_cur = None
    gb_cur = None
    for line in getattr(report, "operators", ()) or ():
        head = str(line).split(" ", 1)[0]
        if "=" not in head:
            continue
        op, _, var = head.partition("=")
        if op == "spmd_join" and join_cur is None:
            join_cur = var
        elif op == "groupby" and gb_cur is None:
            gb_cur = var
    if join_cur == "broadcast":
        hints["join"] = "exchange"
    elif join_cur == "exchange":
        hints["join"] = "broadcast"
    # dense is a strict win when legal — only the hash<->sorted crossover
    # is worth second-guessing from measurements
    if gb_cur == "hash":
        hints["groupby"] = "sorted"
    elif gb_cur == "sorted":
        hints["groupby"] = "hash"
    root = getattr(report, "root", None)
    if root is not None:
        for s in root.walk():
            p = s.attrs.get("partitions")
            if s.name == "grace_join" and p:
                # a skewed grace join re-partitions finer next time
                hints["partitions"] = max(int(p) * 2, 2)
                break
    return hints


# ---------------------------------------------------------------------------
# the daemon (periodic + idle-accelerated ticks)
# ---------------------------------------------------------------------------

_DAEMON: Optional[threading.Thread] = None
_D_LOCK = threading.Lock()


def _ensure_daemon() -> None:
    if interval_s() <= 0:
        return
    global _DAEMON
    with _D_LOCK:
        if _DAEMON is not None and _DAEMON.is_alive():
            return
        t = threading.Thread(target=_daemon_loop, name="dsql-autopilot",
                             daemon=True)
        _DAEMON = t
        t.start()


def _daemon_loop() -> None:
    global _DAEMON
    last = time.monotonic()
    while enabled() and interval_s() > 0:
        iv = max(interval_s(), 0.05)
        time.sleep(min(iv / 4.0, 0.5))
        now = time.monotonic()
        due = now - last >= iv
        if not due:
            # idle acceleration: an empty scheduler halves the wait —
            # maintenance runs when the engine has nothing better to do
            try:
                from . import scheduler as _sched
                mgr = _sched.get_manager()
                idle = (mgr.running_count() == 0
                        and mgr.queue_depth() == 0)
            except Exception:
                idle = False
            due = idle and (now - last) >= iv / 2.0
        if not due:
            continue
        last = now
        try:
            tick()
        except Exception:  # pragma: no cover - tick already swallows
            logger.debug("autopilot daemon tick failed", exc_info=True)
    # disarmed (kill switch flipped mid-run): exit; a later armed query
    # restarts the thread via begin_query
    with _D_LOCK:
        _DAEMON = None


# ---------------------------------------------------------------------------
# surfaces
# ---------------------------------------------------------------------------

def engine_section() -> dict:
    """The ``/v1/engine`` autopilot section (armed callers only)."""
    with _M_LOCK:
        used = sum(int(m["bytes"]) for m in _MANAGED.values())
        names = sorted(_MANAGED)
    hints = _read_hints()
    active = sum(1 for e in hints.values()
                 if isinstance(e, dict) and e.get("state") == "active")
    reverted = sum(1 for e in hints.values()
                   if isinstance(e, dict) and e.get("state") == "reverted")
    with _J_LOCK:
        n = len(_JOURNAL)
        last = dict(_JOURNAL[-1]) if _JOURNAL else None
    return {
        "enabled": True,
        "mvBudgetBytes": mv_budget_bytes(),
        "mvUsedBytes": used,
        "managedViews": names,
        "hintsActive": active,
        "hintsReverted": reverted,
        "actions": n,
        "lastAction": last,
    }


def _reset_for_tests() -> None:
    global _CTX_REF
    with _M_LOCK:
        _MANAGED.clear()
        _BLACKLIST.clear()
        _COOLDOWN.clear()
    with _J_LOCK:
        _JOURNAL.clear()
    with _MEM_LOCK:
        _MEM_HINTS.clear()
    _CTX_REF = None
    end_query()
