"""Engine-wide resilience layer: taxonomy, deadlines, retries, degradation.

The paper's north star is a production engine under heavy traffic; there a
single XLA compile crash, device OOM, or hung TPU program must never take
down a query (let alone the server) with an untyped exception.  Flare
(PAPERS.md) keeps a deoptimization path from native code back to its
interpreted engine, and DrJAX observes that long-running JAX programs need
host-side supervision — this module is that discipline for dask_sql_tpu:

**Taxonomy.**  Every failure is classified into exactly one of

  ``UserError``       the query/input is wrong; retrying cannot help
                      (Presto ``USER_ERROR``);
  ``TransientError``  the attempt failed but a retry or a lower rung can
                      succeed — compile crashes, device OOM, transfer/tunnel
                      drops (Presto ``INTERNAL_ERROR``, or
                      ``INSUFFICIENT_RESOURCES`` for ``kind="oom"``);
  ``FatalError``      an engine invariant broke; retrying is pointless and
                      the failure must surface (Presto ``INTERNAL_ERROR``);

plus supervision verdicts: ``DeadlineExceeded`` (the per-query budget
ran out — Presto ``INSUFFICIENT_RESOURCES``, like Trino's
EXCEEDED_TIME_LIMIT), ``QueryCancelled`` (the client abandoned the
query), and the admission verdicts ``AdmissionRejected`` /
``AdmissionTimeout`` raised by the workload manager
(runtime/scheduler.py) when the system is saturated — time spent in the
admission queue counts against the query's deadline, so a queued query
can expire or be cancelled exactly like a running one.  ``classify`` maps raw exceptions into the taxonomy; call sites
choose the default bucket for unrecognized types (the server boundary
defaults to ``UserError`` to match Presto semantics; internal sites default
to ``FatalError``).

**Deadlines + cancellation.**  ``Context.sql(..., timeout=)`` (seconds) or
``DSQL_QUERY_TIMEOUT_MS`` opens a ``query_scope`` carrying a monotonic
deadline and a cancel event; ``check()`` at layer checkpoints (compile
attempts, capacity-escalation iterations, stage scheduling, streamed
batches, eager plan nodes) raises the typed verdict instead of letting work
run past its budget.  Worker threads (the stage compile pool) re-enter the
scope via ``scoped`` — thread locals do not cross pools on their own.

**Retry/backoff.**  ``retry_transient`` retries TransientErrors with
bounded exponential backoff (``DSQL_RETRY_MAX`` attempts,
``DSQL_RETRY_BASE_MS`` base), always re-checking the deadline before
sleeping — a retry loop must never become the hang it exists to prevent.

**Degradation ladder.**  ``LADDER`` declares the compile-layer policy the
executor follows (physical/compiled.py): whole-plan jit → bounded stages →
eager → typed failure.  Each rung change increments
``compiled.stats["degradations"]``; each in-rung retry increments
``"retries"``; deadline verdicts increment ``"deadline_exceeded"``; fault
injections increment their per-site ``"fault_*"`` counter — so CI can
assert the ladder actually ran (tests/integration/test_resilience.py).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional, Tuple

logger = logging.getLogger(__name__)

# the declared compile-layer degradation policy, top rung first (the old
# implicit "two-strike" special case in physical/compiled.py, made explicit)
LADDER: Tuple[str, ...] = ("whole", "stages", "eager", "fail")


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------

class ResilienceError(RuntimeError):
    """Base of the typed taxonomy.  ``error_type``/``error_name``/
    ``error_code`` are the Presto wire classification the server emits."""

    error_type = "INTERNAL_ERROR"
    error_name = "GENERIC_INTERNAL_ERROR"
    error_code = 0x10000          # Trino GENERIC_INTERNAL_ERROR range


class UserError(ResilienceError):
    """The query or its inputs are wrong; no retry can help."""

    error_type = "USER_ERROR"
    error_name = "GENERIC_USER_ERROR"
    error_code = 0x0


class TransientError(ResilienceError):
    """A retry — or a lower degradation rung — can succeed.

    ``kind`` labels the failure class: ``"compile"`` (backend compile
    crash), ``"oom"`` (device memory), ``"io"`` (transfer/tunnel),
    ``"device"`` (other runtime errors), ``"injected"`` (test faults)."""

    error_name = "TRANSIENT_ERROR"

    def __init__(self, message: str = "", kind: str = "device"):
        super().__init__(message)
        self.kind = kind
        if kind == "oom":
            self.error_type = "INSUFFICIENT_RESOURCES"
            self.error_name = "EXCEEDED_MEMORY_LIMIT"
            self.error_code = 0x20000


class FatalError(ResilienceError):
    """An engine invariant broke; surface it, never retry."""

    error_name = "GENERIC_INTERNAL_ERROR"


class DeadlineExceeded(ResilienceError):
    """The per-query time budget ran out (Trino EXCEEDED_TIME_LIMIT)."""

    error_type = "INSUFFICIENT_RESOURCES"
    error_name = "EXCEEDED_TIME_LIMIT"
    error_code = 0x20000


class QueryCancelled(UserError):
    """The client abandoned the query (DELETE /v1/cancel)."""

    error_name = "USER_CANCELED"


class SchemaMismatch(UserError):
    """An append batch (``append_rows`` / ``INSERT INTO ... SELECT`` /
    ``POST /v1/ingest``) does not fit the target table's schema: missing
    or extra columns, wrong arity, or a value that cannot cast to the
    target column type.  A user mistake by construction — the server
    surfaces it as HTTP 400 rather than a raw coercion traceback."""

    error_name = "SCHEMA_MISMATCH"


class AdmissionRejected(ResilienceError):
    """The workload manager (runtime/scheduler.py) refused the query at
    submit time: queue full, or the deadline would expire before a slot
    could plausibly free.  The server surfaces this as HTTP 429 with a
    ``Retry-After`` derived from ``retry_after_s``."""

    error_type = "INSUFFICIENT_RESOURCES"
    error_name = "QUERY_QUEUE_FULL"
    error_code = 0x20000

    def __init__(self, message: str = "", retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)


class AdmissionTimeout(AdmissionRejected):
    """The query waited in the admission queue past DSQL_QUEUE_TIMEOUT_MS
    without winning a slot (queue time always counts against the query's
    own deadline too — see scheduler.WorkloadManager.acquire)."""

    error_name = "QUERY_QUEUE_TIMEOUT"


class ServerDraining(AdmissionRejected):
    """The process is draining (SIGTERM/SIGINT): in-flight queries run to
    completion within ``DSQL_DRAIN_TIMEOUT_S`` but NEW admissions are
    refused — the server surfaces this as HTTP 503 + ``Retry-After`` so a
    load balancer retries against another instance."""

    error_name = "SERVER_SHUTTING_DOWN"


class TenantQuotaExceeded(AdmissionRejected):
    """The tenant's token-bucket rate (``DSQL_TENANT_QPS``) or concurrency
    quota (``DSQL_TENANT_CONCURRENT``) is exhausted (runtime/tenancy.py).
    Rides the AdmissionRejected wire path: HTTP 429 + ``Retry-After``
    derived from the bucket's refill time."""

    error_name = "TENANT_QUOTA_EXCEEDED"


class TenantCircuitOpen(AdmissionRejected):
    """The tenant's circuit breaker is open (``DSQL_TENANT_BREAKER``
    consecutive fatal/timeout verdicts): admissions are refused
    immediately until a half-open probe succeeds — the tenant's failure
    loop must not keep burning engine slots.  HTTP 429 + ``Retry-After``
    set to the remaining open window."""

    error_name = "TENANT_CIRCUIT_OPEN"


class LoadShedRejected(AdmissionRejected):
    """Burn-driven load shed (runtime/scheduler.py): a priority class is
    burning its SLO error budget past ``DSQL_SLO_BURN`` on BOTH burn
    windows, so background-class admissions are refused before the SLO
    actually breaches.  HTTP 429 + ``Retry-After``; clears on its own
    when the burn recovers."""

    error_name = "SLO_LOAD_SHED"


class IngestBackpressure(AdmissionRejected):
    """The continuous-ingestion write path (runtime/ingest.py) priced an
    append batch through the scheduler's memory broker and the device
    budget cannot absorb it right now: the writer must back off.  Rides
    the AdmissionRejected wire path (HTTP 429 + ``Retry-After``) so a
    well-behaved writer client retries instead of growing the working
    set past what readers were admitted against."""

    error_name = "INGEST_BACKPRESSURE"


# exception type NAMES (not imports: the parser/binder layer must stay
# importable without this module) that are user mistakes by construction
_USER_ERROR_NAMES = frozenset({
    "ParsingException", "ValidationException", "BinderError",
    "StreamingUnsupported",
})

# XlaRuntimeError status substrings that mean the PROGRAM is wrong (no
# retry will change the verdict) vs the ATTEMPT failed (retry/degrade)
_XLA_FATAL_MARKERS = ("INVALID_ARGUMENT", "UNIMPLEMENTED", "FAILED_PRECONDITION")
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "OUT OF MEMORY", "OOM")


def _is_xla_error(exc: BaseException) -> bool:
    t = type(exc)
    return (t.__name__ == "XlaRuntimeError"
            or t.__module__.startswith(("jaxlib", "jax.")))


def classify(exc: BaseException, *, default=FatalError
             ) -> Optional[ResilienceError]:
    """Map a raw exception into the taxonomy.

    Returns a typed error (the original object when already typed, with
    ``__cause__`` set to the original otherwise), or None for control-flow
    exceptions the caller must re-raise untouched.  ``default`` is the
    bucket for unrecognized types: ``UserError`` at the serve boundary
    (anything escaping ``Context.sql`` on user input is the user's query),
    ``FatalError`` inside the engine.
    """
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return None
    if isinstance(exc, ResilienceError):
        return exc

    def wrap(cls, *args, **kw) -> ResilienceError:
        err = cls(*args, **kw)
        err.__cause__ = exc
        return err

    msg = f"{type(exc).__name__}: {exc}"
    if isinstance(exc, MemoryError):
        return wrap(TransientError, msg, kind="oom")
    if type(exc).__name__ in _USER_ERROR_NAMES:
        return wrap(UserError, str(exc))
    if _is_xla_error(exc):
        text = str(exc).upper()
        if any(m in text for m in _OOM_MARKERS):
            return wrap(TransientError, msg, kind="oom")
        if any(m in text for m in _XLA_FATAL_MARKERS):
            return wrap(FatalError, msg)
        # INTERNAL / UNAVAILABLE / ABORTED / DEADLINE_EXCEEDED / tunnel
        # drops: the attempt failed, the program may be fine
        return wrap(TransientError, msg, kind="compile")
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return wrap(TransientError, msg, kind="io")
    return wrap(default, msg)


# ---------------------------------------------------------------------------
# per-query runtime: deadline + cancellation
# ---------------------------------------------------------------------------

def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class QueryRuntime:
    """Deadline + cancel token one query's execution threads share.

    ``backoff_s`` accumulates wall time this query spent SLEEPING in
    retry backoff while holding resources — the workload manager subtracts
    it from the slot-hold time feeding its queue-wait EWMA, so a query
    riding a long in-rung retry chain does not inflate the admission
    estimator (and spuriously fast-reject queued work)."""

    __slots__ = ("deadline_at", "cancel", "backoff_s")

    def __init__(self, timeout_s: Optional[float] = None,
                 cancel: Optional[threading.Event] = None):
        self.deadline_at = (None if timeout_s is None
                            else time.monotonic() + max(timeout_s, 0.0))
        self.cancel = cancel
        self.backoff_s = 0.0

    def remaining(self) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.monotonic()

    def merged(self, timeout_s: Optional[float],
               cancel: Optional[threading.Event]) -> "QueryRuntime":
        """A nested scope can only tighten: the sooner deadline wins and
        either cancel token aborts (outer cancellation must reach work a
        nested sql() call started)."""
        rt = QueryRuntime(timeout_s, cancel or self.cancel)
        if self.deadline_at is not None and (
                rt.deadline_at is None or self.deadline_at < rt.deadline_at):
            rt.deadline_at = self.deadline_at
        if rt.cancel is None:
            rt.cancel = self.cancel
        return rt


_tls = threading.local()


def current() -> Optional[QueryRuntime]:
    return getattr(_tls, "runtime", None)


@contextmanager
def scoped(rt: Optional[QueryRuntime]):
    """Install an existing runtime in THIS thread (worker-pool re-entry)."""
    prev = current()
    _tls.runtime = rt
    try:
        yield rt
    finally:
        _tls.runtime = prev


@contextmanager
def query_scope(timeout_s: Optional[float] = None,
                cancel: Optional[threading.Event] = None):
    """Open (or tighten) the per-query supervision scope.

    ``timeout_s=None`` reads ``DSQL_QUERY_TIMEOUT_MS`` (unset/0 = no
    deadline).  Nested scopes merge: the sooner deadline and any cancel
    token win."""
    if timeout_s is None:
        ms = _env_int("DSQL_QUERY_TIMEOUT_MS", 0)
        timeout_s = ms / 1e3 if ms > 0 else None
    outer = current()
    rt = (QueryRuntime(timeout_s, cancel) if outer is None
          else outer.merged(timeout_s, cancel))
    with scoped(rt):
        yield rt


def _bump(key: str, n: int = 1) -> None:
    # counters live in the telemetry registry (runtime/telemetry.py);
    # ``physical.compiled.stats`` is a deprecated read-through alias of it
    from . import telemetry as _tel
    _tel.inc(key, n)


def check(site: str = "") -> None:
    """Deadline/cancellation checkpoint; raises the typed verdict."""
    rt = current()
    if rt is None:
        return
    if rt.cancel is not None and rt.cancel.is_set():
        raise QueryCancelled(
            f"query cancelled{f' at {site}' if site else ''}")
    rem = rt.remaining()
    if rem is not None and rem <= 0:
        _bump("deadline_exceeded")
        raise DeadlineExceeded(
            f"query deadline exceeded{f' at {site}' if site else ''} "
            f"({-rem * 1e3:.0f} ms past)")


def interruptible_sleep(seconds: float, site: str = "") -> None:
    """Sleep in small slices so cancellation/deadline cut it short."""
    end = time.monotonic() + max(seconds, 0.0)
    while True:
        check(site)
        left = end - time.monotonic()
        if left <= 0:
            return
        time.sleep(min(left, 0.01))


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

def retry_max() -> int:
    return max(_env_int("DSQL_RETRY_MAX", 2), 0)


def backoff_s(attempt: int) -> float:
    """Exponential backoff for retry ``attempt`` (1-based), capped at 2 s."""
    base = _env_int("DSQL_RETRY_BASE_MS", 25) / 1e3
    return min(base * (2 ** (attempt - 1)), 2.0)


def backoff(attempt: int, site: str = "") -> None:
    """Sleep before retry ``attempt`` — but never past the deadline: if the
    budget cannot cover the sleep, raise DeadlineExceeded NOW instead of
    burning the remainder on a doomed wait.

    The sleep runs under a ``retry_backoff`` telemetry span and accrues
    into ``QueryRuntime.backoff_s``, so slot-hold accounting (the
    scheduler's queue-wait EWMA) can subtract time spent deliberately
    idle from time spent actually computing."""
    from . import telemetry as _tel
    delay = backoff_s(attempt)
    rt = current()
    if rt is not None:
        rem = rt.remaining()
        if rem is not None and rem <= delay:
            _bump("deadline_exceeded")
            raise DeadlineExceeded(
                f"deadline cannot cover retry backoff at {site or 'site'} "
                f"({delay * 1e3:.0f} ms needed, {max(rem, 0) * 1e3:.0f} ms "
                "left)")
    t0 = time.monotonic()
    try:
        with _tel.span("retry_backoff", site=site, attempt=attempt):
            interruptible_sleep(delay, site)
    finally:
        # the actually-slept wall (an interrupting deadline/cancel cuts it
        # short), accumulated even on the exception path — the time was
        # spent either way
        if rt is not None:
            rt.backoff_s += time.monotonic() - t0


def retry_transient(fn: Callable, *, site: str,
                    passthrough: Tuple[type, ...] = ()):
    """Run ``fn``, retrying TransientErrors with bounded backoff.

    ``passthrough`` exceptions (control flow like _NeedsRecompile) are
    re-raised untouched.  Non-transient failures are re-raised as their
    classified type; retries count into ``compiled.stats["retries"]``.
    """
    attempt = 0
    while True:
        check(site)
        try:
            return fn()
        except passthrough:
            raise
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            err = classify(e)
            if err is None:
                raise
            if not isinstance(err, TransientError):
                raise err if err is e else err from e
            attempt += 1
            if attempt > retry_max():
                raise err if err is e else err from e
            _bump("retries")
            logger.warning("transient failure at %s (%s); retry %d/%d",
                           site, str(err)[:200], attempt, retry_max())
            backoff(attempt, site)
