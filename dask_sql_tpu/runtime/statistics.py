"""Table statistics + adaptive operator selection (ROADMAP item 3).

One ``TableStats`` object — row count, per-column NDV estimate, min/max,
null fraction, dense-int detection — collected cheaply at ingest
(context.create_table) and refined by the runtime measurements already
flowing through the flight recorder's EWMA history, threaded through the
whole vertical:

- **operator dispatch** (physical/rel/executor.py → ops/groupby.py,
  ops/join.py, ops/kernels.py): the hash/sort crossover of "Hash-Based
  vs. Sort-Based Group-By-Aggregate" (PAPERS.md) picks sorted-segment vs
  hash aggregation from key NDV vs row count, and a dense-int
  direct-index path (``codes = key - min``, no hashing — "Fine-Tuning
  Data Structures for Analytical Query Processing", PAPERS.md) takes
  over when the observed key domain is small and dense;
- **planner** (plan/optimizer.py): join chains rank by estimated output
  cardinality (NDV-based equi-join selectivity), and group-capacity
  hints shrink the compiled executor's padded capacity classes toward
  measured cardinality (physical/compiled.py, physical/stages.py);
- **scheduler** (runtime/scheduler.py): ``estimate_plan_bytes`` consumes
  the same stats for the admission reservation (``est_source=stats``).

Every decision is advisory: the compiled path keeps its overflow-flag
escalation net (a wrong cap hint costs one recompile, never a wrong
result), the eager variants all produce the same group numbering as the
status-quo factorize, and ``DSQL_ADAPTIVE=0`` restores pre-stats
dispatch bit-for-bit.  ``DSQL_FORCE_GROUPBY=hash|sorted|dense`` pins the
group-by variant for testing; every choice is recorded on the current
span, a counter (``operator_choice_<op>_<variant>``), and EXPLAIN.
"""
from __future__ import annotations

import logging
import math
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry as _tel

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# env gates
# ---------------------------------------------------------------------------

def adaptive_enabled() -> bool:
    """Master kill-switch: ``DSQL_ADAPTIVE=0`` restores pre-stats dispatch
    everywhere (collection still runs at ingest; it is pure metadata)."""
    return os.environ.get("DSQL_ADAPTIVE", "1") != "0"


def forced_groupby() -> Optional[str]:
    """``DSQL_FORCE_GROUPBY=hash|sorted|dense``: pin the eager group-by
    variant regardless of stats (testing/bench).  Unknown values → None."""
    v = os.environ.get("DSQL_FORCE_GROUPBY", "").strip().lower()
    return v if v in ("hash", "sorted", "dense") else None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def dense_domain_cap() -> int:
    """Largest key domain (max-min+1) the dense direct-index group-by will
    allocate slots for; beyond it the crossover table decides."""
    return _env_int("DSQL_DENSE_DOMAIN_CAP", 4096)


#: domain above which exact ingest-time NDV probing (bincount) is skipped
_NDV_PROBE_DOMAIN = 1 << 20
#: sample size for the strided NDV estimator on wide-domain columns
_NDV_SAMPLE = 65536
#: sorted-segment aggregation stays profitable up to this many groups …
SORT_NDV_CAP = 4096
#: … and only while groups stay "fat" (ndv <= rows / SORT_ROW_FRACTION)
SORT_ROW_FRACTION = 16


# ---------------------------------------------------------------------------
# the stats objects
# ---------------------------------------------------------------------------

@dataclass
class ColumnStats:
    """Per-column ingest statistics.  ``ndv`` is an ESTIMATE above
    ``_NDV_PROBE_DOMAIN``-sized domains (strided-sample extrapolation);
    exact (bincount over the domain) for narrow integer columns —
    exactly the columns the dense dispatch cares about."""

    name: str
    ndv: Optional[int] = None
    min: Optional[float] = None
    max: Optional[float] = None
    null_frac: float = 0.0
    is_int: bool = False
    #: int column whose domain (max-min+1) fits dense_domain_cap()
    dense: bool = False
    domain: Optional[int] = None

    def to_row(self) -> dict:
        return {
            "column": self.name,
            "ndv": -1 if self.ndv is None else int(self.ndv),
            "min": float("nan") if self.min is None else float(self.min),
            "max": float("nan") if self.max is None else float(self.max),
            "null_frac": float(self.null_frac),
            "is_int": bool(self.is_int),
            "dense": bool(self.dense),
            "domain": -1 if self.domain is None else int(self.domain),
        }


@dataclass
class TableStats:
    rows: int = 0
    cols: Dict[str, ColumnStats] = field(default_factory=dict)
    collected_ms: float = 0.0

    def col(self, name: str) -> Optional[ColumnStats]:
        return self.cols.get(name)


def collect_table_stats(table, row_valid=None) -> Optional[TableStats]:
    """Cheap ingest-time collection over a resident device Table.

    One host pass per column (XLA:CPU arrays view for free; on TPU this
    runs once at create_table, not per query).  Never raises — a column
    that resists profiling is simply absent from the stats dict, and any
    failure returns None (the engine then behaves exactly as pre-stats).
    """
    t0 = time.perf_counter()
    try:
        rows = int(table.num_rows)
        valid_rows = None
        if row_valid is not None:
            valid_rows = np.asarray(row_valid).reshape(-1)
            rows = int(valid_rows.sum())
        ts = TableStats(rows=rows)
        for name, col in zip(table.names, table.columns):
            cs = _collect_column(name, col, rows, valid_rows)
            if cs is not None:
                ts.cols[name] = cs
        ts.collected_ms = (time.perf_counter() - t0) * 1e3
        _tel.inc("stats_tables_collected")
        return ts
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        logger.debug("stats collection failed", exc_info=True)
        _tel.inc("stats_collect_errors")
        return None


def _collect_column(name, col, rows: int, valid_rows) -> Optional[ColumnStats]:
    try:
        mask = None if col.mask is None else np.asarray(col.mask).reshape(-1)
        if valid_rows is not None:
            mask = valid_rows if mask is None else (mask & valid_rows)
        n = rows if rows else 1
        nulls = 0 if mask is None else int(rows - mask.sum()) if valid_rows \
            is None else int(valid_rows.sum() - mask.sum())
        null_frac = max(0.0, min(1.0, nulls / n))

        if col.stype.is_string:
            # dictionary-encoded: the dictionary bounds NDV exactly
            ndv = int(len(col.dictionary)) if col.dictionary is not None \
                else None
            return ColumnStats(name=name, ndv=ndv, null_frac=null_frac)

        data = np.asarray(col.data).reshape(-1)
        vals = data if mask is None else data[mask.astype(bool)]
        if vals.size == 0:
            return ColumnStats(name=name, ndv=0, null_frac=null_frac,
                               is_int=bool(np.issubdtype(data.dtype,
                                                         np.integer)))
        if data.dtype == np.bool_:
            return ColumnStats(name=name, ndv=int(np.unique(vals).size),
                               min=float(vals.min()), max=float(vals.max()),
                               null_frac=null_frac)
        mn, mx = vals.min(), vals.max()
        is_int = bool(np.issubdtype(data.dtype, np.integer))
        domain = None
        ndv: Optional[int] = None
        if is_int:
            domain = int(mx) - int(mn) + 1
            if 0 < domain <= _NDV_PROBE_DOMAIN:
                # exact NDV in O(n + domain): one bincount over the domain
                counts = np.bincount((vals.astype(np.int64) - int(mn)),
                                     minlength=domain)
                ndv = int(np.count_nonzero(counts))
        if ndv is None:
            ndv = _sampled_ndv(vals)
        dense = bool(is_int and domain is not None
                     and domain <= dense_domain_cap())
        mnf, mxf = float(mn), float(mx)
        if not (math.isfinite(mnf) and math.isfinite(mxf)):
            mnf = mxf = None  # type: ignore[assignment]
        return ColumnStats(name=name, ndv=ndv, min=mnf, max=mxf,
                           null_frac=null_frac, is_int=is_int, dense=dense,
                           domain=domain)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        logger.debug("column stats failed for %s", name, exc_info=True)
        return None


def _sampled_ndv(vals: np.ndarray) -> int:
    """Strided-sample NDV estimator for wide domains.

    A high distinct fraction in the sample extrapolates linearly (key-like
    columns really do have ~n distinct values); a low fraction is reported
    as the sample's own count — a LOWER bound, which biases the crossover
    toward sorted aggregation only when groups genuinely looked fat."""
    n = vals.size
    if n <= _NDV_SAMPLE:
        return int(np.unique(vals).size)
    stride = max(1, n // _NDV_SAMPLE)
    sample = vals[::stride]
    d = int(np.unique(sample).size)
    s = sample.size
    if d >= 0.5 * s:
        return min(n, int(n * (d / s)))
    return d


# ---------------------------------------------------------------------------
# plan-level estimation: column stats + cardinality through operators
# ---------------------------------------------------------------------------

def _scan_entry(rel, context):
    schema = context.schema.get(rel.schema_name)
    if schema is None:
        return None
    return schema.tables.get(rel.table_name)


def table_stats_for_scan(rel, context) -> Optional[TableStats]:
    entry = _scan_entry(rel, context)
    return getattr(entry, "stats", None) if entry is not None else None


def column_stats_for(rel, ordinal: int, context) -> Optional[ColumnStats]:
    """Trace output ordinal ``ordinal`` of ``rel`` back to a base-table
    column and return its ingest stats (None when the column is computed
    or the lineage can't be followed — callers then use defaults)."""
    from ..plan import nodes as N

    if isinstance(rel, N.LogicalTableScan):
        ts = table_stats_for_scan(rel, context)
        if ts is None or ordinal >= len(rel.schema):
            return None
        return ts.col(rel.schema[ordinal].name)
    if isinstance(rel, N.LogicalProject):
        e = rel.exprs[ordinal] if ordinal < len(rel.exprs) else None
        if isinstance(e, N.RexInputRef):
            return column_stats_for(rel.input, e.index, context)
        return None
    if isinstance(rel, (N.LogicalFilter, N.LogicalSort)):
        # filters/sorts keep values; NDV/min/max stay valid upper bounds
        return column_stats_for(rel.input, ordinal, context)
    if isinstance(rel, N.LogicalAggregate):
        if ordinal < len(rel.group_keys):
            return column_stats_for(rel.input, rel.group_keys[ordinal],
                                    context)
        return None
    if isinstance(rel, N.LogicalJoin):
        nl = len(rel.left.schema)
        if rel.join_type in ("SEMI", "ANTI") or ordinal < nl:
            return column_stats_for(rel.left, ordinal, context)
        return column_stats_for(rel.right, ordinal - nl, context)
    return None


_DEFAULT_EQ_SEL = 0.1
_DEFAULT_RANGE_SEL = 0.3
_DEFAULT_SEL = 0.25
_MIN_SEL = 5e-4


def _literal_value(rex):
    from ..plan import nodes as N

    # RexParam carries its current literal value — selectivity estimates
    # use it exactly like an inline literal (estimates are advisory; only
    # program identity must be value-free)
    if isinstance(rex, (N.RexLiteral, N.RexParam)):
        v = rex.value
        if isinstance(v, bool):
            return float(v)
        if isinstance(v, (int, float)):
            return float(v)
    return None


def selectivity(rex, rel, context) -> float:
    """Fraction of ``rel``'s rows estimated to satisfy ``rex`` —
    textbook System-R style rules over the ingest min/max/NDV."""
    from ..plan import nodes as N

    if isinstance(rex, N.RexLiteral):
        if rex.value is True:
            return 1.0
        if rex.value is False:
            return 0.0
        return _DEFAULT_SEL
    if not isinstance(rex, N.RexCall):
        return _DEFAULT_SEL
    op = rex.op
    if op == "AND":
        s = 1.0
        for o in rex.operands:
            s *= selectivity(o, rel, context)
        return max(s, _MIN_SEL)
    if op == "OR":
        s = 0.0
        for o in rex.operands:
            s += selectivity(o, rel, context)
        return min(s, 1.0)
    if op == "NOT":
        return min(max(1.0 - selectivity(rex.operands[0], rel, context),
                       _MIN_SEL), 1.0)
    if op in ("IS NULL", "IS NOT NULL") and len(rex.operands) == 1:
        o = rex.operands[0]
        cs = column_stats_for(rel, o.index, context) \
            if isinstance(o, N.RexInputRef) else None
        nf = cs.null_frac if cs is not None else 0.05
        return max(nf if op == "IS NULL" else 1.0 - nf, _MIN_SEL)
    if op in ("=", "<>", "!=", "<", "<=", ">", ">=") \
            and len(rex.operands) == 2:
        a, b = rex.operands
        ref, lit = (a, b) if isinstance(a, N.RexInputRef) else (b, a)
        if not isinstance(ref, N.RexInputRef):
            return _DEFAULT_SEL
        cs = column_stats_for(rel, ref.index, context)
        if op == "=":
            if cs is not None and cs.ndv:
                return max(1.0 / cs.ndv, _MIN_SEL)
            return _DEFAULT_EQ_SEL
        if op in ("<>", "!="):
            if cs is not None and cs.ndv:
                return max(1.0 - 1.0 / cs.ndv, _MIN_SEL)
            return 1.0 - _DEFAULT_EQ_SEL
        lv = _literal_value(lit)
        if cs is None or lv is None or cs.min is None or cs.max is None \
                or cs.max <= cs.min:
            return _DEFAULT_RANGE_SEL
        frac = (lv - cs.min) / (cs.max - cs.min)
        if (op in ("<", "<=")) == (ref is a):
            s = frac          # col < lit  (or lit > col)
        else:
            s = 1.0 - frac    # col > lit  (or lit < col)
        return min(max(s, _MIN_SEL), 1.0)
    return _DEFAULT_SEL


def estimate_rows(rel, context, _depth: int = 0) -> Optional[float]:
    """Estimated output cardinality of a plan subtree; None = unknown.

    Ingest stats drive the base numbers; the flight recorder's EWMA
    history (keyed by canonical plan fingerprint) REFINES the root of
    each estimate with rows the engine actually measured for this exact
    subtree shape on earlier runs."""
    from ..plan import nodes as N

    if _depth == 0:
        measured = measured_rows(rel, context)
        if measured is not None:
            return float(measured)
    if _depth > 64:
        return None
    if isinstance(rel, N.LogicalTableScan):
        ts = table_stats_for_scan(rel, context)
        if ts is not None:
            return float(ts.rows)
        entry = _scan_entry(rel, context)
        if entry is None:
            return None
        chunked = getattr(entry, "chunked", None)
        if chunked is not None:
            return float(getattr(chunked, "n_rows", 0))
        table = getattr(entry, "table", None)
        return float(table.num_rows) if table is not None else None
    if isinstance(rel, N.LogicalValues):
        return float(len(rel.rows))
    if isinstance(rel, N.LogicalFilter):
        child = estimate_rows(rel.input, context, _depth + 1)
        if child is None:
            return None
        return child * selectivity(rel.condition, rel.input, context)
    if isinstance(rel, N.LogicalProject):
        return estimate_rows(rel.input, context, _depth + 1)
    if isinstance(rel, N.LogicalSort):
        child = estimate_rows(rel.input, context, _depth + 1)
        if child is None:
            return None
        if rel.limit is not None:
            return min(child, float(rel.limit))
        return child
    if isinstance(rel, N.LogicalAggregate):
        child = estimate_rows(rel.input, context, _depth + 1)
        if not rel.group_keys:
            return 1.0
        if child is None:
            return None
        prod = 1.0
        for k in rel.group_keys:
            cs = column_stats_for(rel.input, k, context)
            if cs is None or not cs.ndv:
                return child  # unknown key: no group reduction claimed
            prod *= cs.ndv
            if prod > child:
                return child
        return min(child, prod)
    if isinstance(rel, N.LogicalJoin):
        return _estimate_join_rows(rel, context, _depth)
    # set ops and anything else with inputs: sum of known inputs
    if rel.inputs:
        total = 0.0
        for i in rel.inputs:
            c = estimate_rows(i, context, _depth + 1)
            if c is None:
                return None
            total += c
        return total
    return None


def _equi_pairs(rel):
    from ..plan.optimizer import split_join_condition
    try:
        equi, _residual = split_join_condition(rel)
        return equi
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return []


def _estimate_join_rows(rel, context, _depth: int) -> Optional[float]:
    lrows = estimate_rows(rel.left, context, _depth + 1)
    rrows = estimate_rows(rel.right, context, _depth + 1)
    if lrows is None or rrows is None:
        return None
    jt = rel.join_type
    if jt == "SEMI":
        return lrows * 0.5
    if jt == "ANTI":
        return lrows * 0.5
    out = lrows * rrows
    for lk, rk in _equi_pairs(rel):
        lcs = column_stats_for(rel.left, lk, context)
        rcs = column_stats_for(rel.right, rk, context)
        ndv = max(lcs.ndv if lcs is not None and lcs.ndv else 0,
                  rcs.ndv if rcs is not None and rcs.ndv else 0)
        out /= max(ndv, 10) if ndv else 10
    if jt in ("LEFT", "FULL"):
        out = max(out, lrows)
    if jt in ("RIGHT", "FULL"):
        out = max(out, rrows)
    return max(out, 1.0)


def measured_rows(rel, context) -> Optional[float]:
    """EWMA-measured output rows for this exact subtree shape, when the
    flight recorder has seen it (env-gated; zero cost when off)."""
    if not os.environ.get("DSQL_HISTORY_FILE"):
        return None
    try:
        from . import flight_recorder as _fr
        fp = _fr.plan_fingerprint(rel, context)
        if fp is None:
            return None
        stats = _fr.get_stats(fp)
        if stats and stats.get("rows"):
            return float(stats["rows"])
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        logger.debug("measured_rows failed", exc_info=True)
    return None


# ---------------------------------------------------------------------------
# the crossover decision table (group-by dispatch)
# ---------------------------------------------------------------------------

def choose_groupby_variant(rows: Optional[float], ndv: Optional[float],
                           dense_ok: bool) -> str:
    """The hash/sort/dense crossover:

    - ``dense``  — single int key over a small dense domain: direct index
      (``codes = key - min``), no hashing, no sort;
    - ``sorted`` — few fat groups (NDV <= min(SORT_NDV_CAP, rows/16)):
      one stable lexsort + boundary scan beats building a table whose
      size scales with NDV, and the sorted stream aggregates scatter-free;
    - ``hash``   — everything else (the status-quo factorize path), and
      the fallback whenever stats are unknown.
    """
    if dense_ok:
        return "dense"
    if rows is None or ndv is None:
        return "hash"
    if ndv <= min(SORT_NDV_CAP, rows / SORT_ROW_FRACTION):
        return "sorted"
    return "hash"


def groupby_decision(rel, context) -> Tuple[str, Dict[str, Any]]:
    """(variant, info) for a LogicalAggregate's eager dispatch.

    ``info`` carries the driving stats for spans/EXPLAIN and, for the
    dense variant, the (lo, hi) domain hint so the kernel skips its own
    min/max probe.  Forced (``DSQL_FORCE_GROUPBY``) overrides everything;
    adaptive off (or no usable stats) keeps the status quo ("hash")."""
    info: Dict[str, Any] = {}
    forced = forced_groupby()
    if forced is not None:
        info["forced"] = 1
        return forced, info
    if os.environ.get("DSQL_AUTOPILOT", "0").strip() not in ("", "0"):
        # an autopilot re-plan hint for this fingerprint overrides the
        # crossover (but never a forced pin); env checked before import
        from . import autopilot as _ap
        hinted = _ap.current_hint("groupby")
        if hinted in ("hash", "sorted", "dense"):
            info["autopilot"] = 1
            return hinted, info
    if not adaptive_enabled() or not rel.group_keys:
        return "hash", info
    rows = estimate_rows(rel.input, context)
    ndv: Optional[float] = 1.0
    dense_ok = False
    for k in rel.group_keys:
        cs = column_stats_for(rel.input, k, context)
        if cs is None or not cs.ndv:
            ndv = None
            break
        ndv *= cs.ndv
    if len(rel.group_keys) == 1:
        cs = column_stats_for(rel.input, rel.group_keys[0], context)
        if cs is not None and cs.dense and cs.min is not None \
                and cs.max is not None:
            dense_ok = True
            info["lo"] = int(cs.min)
            info["hi"] = int(cs.max)
    if rows is not None:
        info["rows"] = int(rows)
    if ndv is not None:
        info["ndv"] = int(ndv)
    return choose_groupby_variant(rows, ndv, dense_ok), info


def join_decision(rel, left_cols, right_cols, context
                  ) -> Tuple[str, Dict[str, Any]]:
    """(variant, info) for an equi join's key factorization: ``dense``
    skips the shared-domain sort entirely when the single key pair is
    integer-typed (``codes = key - min`` on both sides); anything else
    keeps the status-quo shared factorize ("hash")."""
    import jax.numpy as jnp

    info: Dict[str, Any] = {}
    if not adaptive_enabled() or len(left_cols) != 1:
        return "hash", info
    lc, rc = left_cols[0], right_cols[0]
    if lc.stype.is_string or rc.stype.is_string:
        return "hash", info
    if not (jnp.issubdtype(lc.data.dtype, jnp.integer)
            and jnp.issubdtype(rc.data.dtype, jnp.integer)):
        return "hash", info
    if context is not None and rel is not None:
        lrows = estimate_rows(rel.left, context)
        rrows = estimate_rows(rel.right, context)
        if lrows is not None:
            info["lrows"] = int(lrows)
        if rrows is not None:
            info["rrows"] = int(rrows)
    return "dense", info


# ---------------------------------------------------------------------------
# compiled-path capacity hints (physical/compiled.py, physical/stages.py)
# ---------------------------------------------------------------------------

def _pad_pow2(n: int, lo: int = 64, hi: int = 1 << 20) -> int:
    n = max(int(n), 1)
    return min(max(1 << (n - 1).bit_length(), lo), hi)


def compiled_cap_hints(plan, context) -> Dict[str, int]:
    """Stats-derived starting caps for the compiled executor's padded
    group-capacity classes.

    Tags are assigned in trace order (``agg0``, ``agg1``, …), which this
    host-side walk cannot reproduce for arbitrary plans (scalar
    subqueries interleave), so hints are only offered when the plan holds
    EXACTLY ONE grouped aggregate — unambiguously ``agg0`` — which covers
    the single-agg stage programs the partitioner produces.  A wrong hint
    is always safe: too small trips the overflow flag into one
    capacity-escalation recompile, too large is just the old padding."""
    if not adaptive_enabled() or forced_groupby() is not None:
        return {}
    from ..plan import nodes as N

    aggs: List[Any] = []

    def walk(rel) -> None:
        if isinstance(rel, N.LogicalAggregate) and rel.group_keys:
            aggs.append(rel)
        for i in rel.inputs:
            walk(i)

    try:
        walk(plan)
        if len(aggs) != 1:
            return {}
        rel = aggs[0]
        groups = estimate_rows(rel, context)
        if groups is None:
            return {}
        return {"agg0": _pad_pow2(int(groups * 1.25) + 1)}
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        logger.debug("cap hints failed", exc_info=True)
        return {}


def estimate_plan_bytes_stats(plan, context) -> Optional[int]:
    """Stats-driven working-set estimate for the scheduler: the resident
    scan bytes (they are touched regardless) plus every heavy operator's
    estimated output (rows × 9 bytes/column — 8 data + amortized mask).
    None when adaptive is off or the plan's cardinality can't be
    estimated — the caller keeps the shape heuristic."""
    if not adaptive_enabled():
        return None
    from ..plan import nodes as N

    try:
        scan_bytes = 0
        inter_bytes = 0.0
        ok = True
        stack = [plan]
        while stack:
            rel = stack.pop()
            if isinstance(rel, N.LogicalTableScan):
                entry = _scan_entry(rel, context)
                if entry is not None:
                    from .scheduler import _entry_bytes
                    scan_bytes += _entry_bytes(entry)
            elif isinstance(rel, (N.LogicalJoin, N.LogicalAggregate,
                                  N.LogicalWindow, N.LogicalSort)):
                est = estimate_rows(rel, context)
                if est is None:
                    ok = False
                    break
                inter_bytes += est * max(len(rel.schema), 1) * 9
            stack.extend(getattr(rel, "inputs", ()) or ())
        if not ok:
            return None
        return int(scan_bytes + inter_bytes)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        logger.debug("stats byte estimate failed", exc_info=True)
        return None


# ---------------------------------------------------------------------------
# choice recording: counters + spans + an optional thread-local capture
# ---------------------------------------------------------------------------

_tls = threading.local()


@contextmanager
def capture():
    """Collect every record_choice() on this thread (EXPLAIN ANALYZE's
    eager run uses it to print the choices the run actually took)."""
    prev = getattr(_tls, "capture", None)
    buf: List[Tuple[str, str, Dict[str, Any]]] = []
    _tls.capture = buf
    try:
        yield buf
    finally:
        _tls.capture = prev


def record_choice(op: str, variant: str, **info) -> None:
    """One dispatch decision: counter ``operator_choice_<op>_<variant>``,
    an ``operators`` list entry on the current span (flows into
    QueryReport / flight-recorder envelopes / system.queries / the wire),
    and the thread-local capture buffer when one is open."""
    _tel.inc(f"operator_choice_{op}_{variant}")
    line = format_choice(op, variant, info)
    span = _tel.current_span()
    if span is not None:
        span.attrs.setdefault("operators", []).append(line)
    buf = getattr(_tls, "capture", None)
    if buf is not None:
        buf.append((op, variant, dict(info)))


def format_choice(op: str, variant: str, info: Dict[str, Any]) -> str:
    parts = [f"{op}={variant}"]
    for k in sorted(info):
        parts.append(f"{k}={info[k]}")
    return " ".join(parts)


# ---------------------------------------------------------------------------
# EXPLAIN surface
# ---------------------------------------------------------------------------

def explain_lines(plan, context) -> List[str]:
    """``-- operator:`` trailer lines for plain EXPLAIN: the variant each
    group-by/join WOULD take under current stats (EXPLAIN ANALYZE prints
    the measured choices instead).  Silent when adaptive is off."""
    if not adaptive_enabled() and forced_groupby() is None:
        return []
    from ..plan import nodes as N

    lines: List[str] = []

    def walk(rel) -> None:
        for i in rel.inputs:
            walk(i)
        if isinstance(rel, N.LogicalAggregate) and rel.group_keys:
            try:
                variant, info = groupby_decision(rel, context)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:
                return
            lines.append("-- operator: "
                         + format_choice("groupby", variant, info))
        elif isinstance(rel, N.LogicalJoin):
            pairs = _equi_pairs(rel)
            if len(pairs) != 1:
                return
            try:
                lk, rk = pairs[0]
                lcs = column_stats_for(rel.left, lk, context)
                rcs = column_stats_for(rel.right, rk, context)
                dense = bool(lcs is not None and rcs is not None
                             and lcs.is_int and rcs.is_int
                             and adaptive_enabled())
                info: Dict[str, Any] = {}
                lrows = estimate_rows(rel.left, context)
                rrows = estimate_rows(rel.right, context)
                if lrows is not None:
                    info["lrows"] = int(lrows)
                if rrows is not None:
                    info["rrows"] = int(rrows)
                lines.append("-- operator: " + format_choice(
                    "join", "dense" if dense else "hash", info))
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:
                return

    try:
        walk(plan)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return []
    return lines


# ---------------------------------------------------------------------------
# system.table_stats export
# ---------------------------------------------------------------------------

def system_rows(context) -> List[dict]:
    """One row per (schema, table, column) with ingest stats — the
    ``system.table_stats`` builder's payload."""
    rows: List[dict] = []
    for schema_name, schema in sorted(context.schema.items()):
        for table_name, entry in sorted(schema.tables.items()):
            ts = getattr(entry, "stats", None)
            if ts is None:
                continue
            base = {"schema": schema_name, "table": table_name,
                    "rows": int(ts.rows),
                    "collected_ms": float(ts.collected_ms)}
            if not ts.cols:
                rows.append({**base, "column": "", "ndv": -1,
                             "min": float("nan"), "max": float("nan"),
                             "null_frac": 0.0, "is_int": False,
                             "dense": False, "domain": -1})
            for name in ts.cols:
                rows.append({**base, **ts.cols[name].to_row()})
    return rows
