"""The read-only ``system`` schema: the engine introspected through its own
SQL.

Six virtual tables, each BUILT FRESH at name-resolution time
(context.resolve_table) from live process state and the flight-recorder
ring — never persisted in the catalog, never cacheable
(result_cache._canon_rel marks ``system`` scans volatile so they can't
occupy result-cache budget or interact with catalog epochs):

- ``system.queries``     persistent query history (the JSONL ring)
- ``system.active``      in-flight queries + scheduler queue + background
                         compiles, with phase/tier/per-stage progress
- ``system.metrics``     the telemetry registry (counters + gauges)
- ``system.cache``       result-cache entries with tier/bytes/hits
- ``system.quarantine``  standing compiler-crash verdicts
- ``system.programs``    persistent program-store index
- ``system.devices``     per-local-device HBM in-use/peak/limit
- ``system.events``      watchtower event bus ring (DSQL_EVENTS armed;
                         all replicas' rings merged when DSQL_FLEET_DIR
                         is armed, each row stamped with its replica)
- ``system.slo``         per-class latency objectives + burn rates
- ``system.replicas``    fleet heartbeat registry (DSQL_FLEET_DIR armed)

Every table has a FIXED column schema with explicit dtypes so an empty
engine still binds and executes ``SELECT * FROM system.queries`` — object
columns stay object, numeric columns stay float64/int64 at zero rows.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..table import Table

TABLE_NAMES = ("queries", "active", "metrics", "cache", "quarantine",
               "programs", "table_stats", "mesh", "spill", "devices",
               "matviews", "view_candidates", "events", "slo", "prepared",
               "tenants", "replicas", "autopilot")


def _fleet_on() -> bool:
    """Fleet-plane gate (runtime/fleet.py): env checked BEFORE any
    import, like ``_events``/``_slo`` below — with ``DSQL_FLEET_DIR``
    unset the module stays out of sys.modules and the fleet tables
    yield their fixed empty schemas."""
    import os

    return bool(os.environ.get("DSQL_FLEET_DIR"))


def _col(rows: List[dict], key: str, dtype, default):
    vals = []
    for r in rows:
        v = r.get(key)
        vals.append(default if v is None else v)
    if dtype is object:
        if not vals:
            # an empty object array crashes host_encode_numpy's null scan;
            # an empty unicode array types as VARCHAR just the same
            return np.array([], dtype="U1")
        return np.array([str(v) for v in vals], dtype=object)
    return np.array(vals, dtype=dtype)


def _queries() -> Table:
    from . import flight_recorder as _fr

    if _fleet_on():
        # fleet mode: every replica's envelope ring merged in timestamp
        # order, each row stamped with its replica (runtime/fleet.py)
        from . import fleet as _fleet

        rows = _fleet.merged_query_rows()
    else:
        rows = _fr.read_events(kind="query")
    return Table.from_pydict({
        "replica": _col(rows, "replica", object, ""),
        "unix": _col(rows, "unix", np.float64, 0.0),
        "pid": _col(rows, "pid", np.int64, 0),
        "query": _col(rows, "query", object, ""),
        "outcome": _col(rows, "outcome", object, ""),
        "error": _col(rows, "error", object, ""),
        "wall_ms": _col(rows, "wall_ms", np.float64, 0.0),
        "tier": _col(rows, "tier", object, ""),
        "priority": _col(rows, "priority", object, ""),
        "cache_hit": _col(rows, "cache_hit", np.bool_, False),
        "tenant": _col(rows, "tenant", object, ""),
        "rows_out": _col(rows, "rows_out", np.int64, 0),
        "bytes_out": _col(rows, "bytes_out", np.int64, 0),
        "measured_bytes": _col(rows, "measured_bytes", np.int64, 0),
        "est_bytes": _col(rows, "est_bytes", np.int64, 0),
        "est_source": _col(rows, "est_source", object, ""),
        "queued_ms": _col(rows, "queued_ms", np.float64, 0.0),
        "plan_fp": _col(rows, "plan_fp", object, ""),
        # adaptive operator choices, "; "-joined record_choice lines
        # ("groupby=dense rows=... ndv=..."); older envelopes lack the
        # field and render empty
        "operators": _col([{"operators": "; ".join(r.get("operators")
                                                   or [])}
                           for r in rows], "operators", object, ""),
        # device-profile fields (runtime/profiler.py): worst shard skew,
        # collective bytes by kind, and the cost-model error (-1 = not
        # profiled / no prediction); older envelopes render the defaults
        "skew_ratio": _col(rows, "skew_ratio", np.float64, 0.0),
        "all_to_all_bytes": _col(
            [{"v": (r.get("collective_bytes") or {}).get("all_to_all", 0)}
             for r in rows], "v", np.int64, 0),
        "all_gather_bytes": _col(
            [{"v": (r.get("collective_bytes") or {}).get("all_gather", 0)}
             for r in rows], "v", np.int64, 0),
        "psum_bytes": _col(
            [{"v": (r.get("collective_bytes") or {}).get("psum", 0)}
             for r in rows], "v", np.int64, 0),
        "cost_err": _col(rows, "cost_err", np.float64, -1.0),
    })


def _active() -> Table:
    import os

    from ..physical import compiled as _compiled
    from . import flight_recorder as _fr
    from . import scheduler as _sched

    rows: List[dict] = []
    for a in _fr.active_snapshot():
        rows.append({"state": "running", "query": a["query"],
                     "phase": a["phase"], "tier": a["tier"],
                     "priority": a["priority"],
                     "elapsed_ms": a["elapsedMillis"], "est_bytes": 0,
                     "stages_done": a["stagesDone"],
                     "stages_total": a["stagesTotal"], "pid": a["pid"]})
    for w in _sched.get_manager().waiting_snapshot():
        rows.append({"state": "queued", "query": "", "phase": "queued",
                     "tier": "", "priority": w["priority"],
                     "elapsed_ms": w["waitedMillis"],
                     "est_bytes": w["estBytes"], "stages_done": 0,
                     "stages_total": 0, "pid": os.getpid()})
    for fp in _compiled.inflight_background_compiles():
        rows.append({"state": "bg-compile",
                     "query": f"<background-compile:{fp[:32]}>",
                     "phase": "compile", "tier": "background",
                     "priority": "", "elapsed_ms": 0.0, "est_bytes": 0,
                     "stages_done": 0, "stages_total": 0,
                     "pid": os.getpid()})
    return Table.from_pydict({
        "state": _col(rows, "state", object, ""),
        "query": _col(rows, "query", object, ""),
        "phase": _col(rows, "phase", object, ""),
        "tier": _col(rows, "tier", object, ""),
        "priority": _col(rows, "priority", object, ""),
        "elapsed_ms": _col(rows, "elapsed_ms", np.float64, 0.0),
        "est_bytes": _col(rows, "est_bytes", np.int64, 0),
        "stages_done": _col(rows, "stages_done", np.int64, 0),
        "stages_total": _col(rows, "stages_total", np.int64, 0),
        "pid": _col(rows, "pid", np.int64, 0),
    })


def _metrics() -> Table:
    from . import telemetry as _tel

    snap = _tel.REGISTRY.snapshot()
    rows = [{"name": k, "kind": "counter", "value": float(v)}
            for k, v in sorted(snap["counters"].items())]
    rows += [{"name": k, "kind": "gauge", "value": float(v)}
             for k, v in sorted(snap["gauges"].items())]
    return Table.from_pydict({
        "name": _col(rows, "name", object, ""),
        "kind": _col(rows, "kind", object, ""),
        "value": _col(rows, "value", np.float64, 0.0),
    })


def _cache() -> Table:
    from . import result_cache as _rc

    rows = _rc.get_cache().entries_snapshot()
    return Table.from_pydict({
        "key": _col(rows, "key", object, ""),
        "tier": _col(rows, "tier", object, ""),
        "nbytes": _col(rows, "nbytes", np.int64, 0),
        "hits": _col(rows, "hits", np.int64, 0),
        "tables": _col(rows, "tables", object, ""),
    })


def _quarantine() -> Table:
    from . import quarantine as _quar

    rows = [{"key": k, **(e if isinstance(e, dict) else {})}
            for k, e in sorted(_quar.get_store().entries().items())]
    return Table.from_pydict({
        "key": _col(rows, "key", object, ""),
        "verdict": _col(rows, "verdict", object, ""),
        "reason": _col(rows, "reason", object, ""),
        "strikes": _col(rows, "strikes", np.int64, 0),
        "at": _col(rows, "at", np.float64, 0.0),
        "expires_at": _col(rows, "expires_at", np.float64, 0.0),
    })


def _programs() -> Table:
    from . import program_store as _pstore

    rows = [{"digest": d, **(e if isinstance(e, dict) else {})}
            for d, e in sorted(_pstore.get_store().entries().items())]
    return Table.from_pydict({
        "digest": _col(rows, "digest", object, ""),
        "nbytes": _col(rows, "bytes", np.int64, 0),
        "used_at": _col(rows, "used_at", np.float64, 0.0),
        "stored_at": _col(rows, "stored_at", np.float64, 0.0),
        # XLA cost prediction captured at store time (profiler armed);
        # zeros for entries stored without profiling
        "cost_flops": _col(rows, "cost_flops", np.float64, 0.0),
        "cost_bytes": _col(rows, "cost_bytes", np.float64, 0.0),
    })


def _table_stats(context=None) -> Table:
    """Ingest-time TableStats (runtime/statistics.py) for every resident
    catalog table: one row per column with NDV / min / max / null fraction
    / dense-domain flags — the numbers adaptive operator selection runs
    on.  Needs the resolving context (the catalog lives there); a
    context-less build yields the empty schema."""
    from . import statistics as _stats

    rows = _stats.system_rows(context) if context is not None else []
    return Table.from_pydict({
        "schema": _col(rows, "schema", object, ""),
        "table": _col(rows, "table", object, ""),
        "column": _col(rows, "column", object, ""),
        "rows": _col(rows, "rows", np.int64, 0),
        "ndv": _col(rows, "ndv", np.int64, -1),
        "min": _col(rows, "min", np.float64, float("nan")),
        "max": _col(rows, "max", np.float64, float("nan")),
        "null_frac": _col(rows, "null_frac", np.float64, 0.0),
        "is_int": _col(rows, "is_int", np.bool_, False),
        "dense": _col(rows, "dense", np.bool_, False),
        "domain": _col(rows, "domain", np.int64, -1),
        "collected_ms": _col(rows, "collected_ms", np.float64, 0.0),
    })


def _mesh(context=None) -> Table:
    """One row per visible device, with the context's mesh placement and
    whether the SPMD backend would serve queries on it (parallel/spmd.py
    spmd_enabled: a >=2-device mesh attached and DSQL_MESH != 0)."""
    import jax

    mesh = getattr(context, "mesh", None) if context is not None else None
    axis = ""
    mesh_size = 0
    enabled = False
    if mesh is not None:
        axis = "x".join(f"{n}:{s}" for n, s in
                        zip(mesh.axis_names, mesh.devices.shape))
        mesh_size = int(mesh.devices.size)
        mesh_ids = {d.id for d in mesh.devices.flat}
        from ..parallel.spmd import spmd_enabled
        enabled = spmd_enabled(context)
    else:
        mesh_ids = set()
    rows = []
    try:
        devices = jax.devices()
    except Exception:  # pragma: no cover
        devices = []
    for d in devices:
        rows.append({
            "device_id": int(d.id),
            "platform": str(getattr(d, "platform", "")),
            "kind": str(getattr(d, "device_kind", "")),
            "process": int(getattr(d, "process_index", 0)),
            "in_mesh": d.id in mesh_ids,
            "mesh_axes": axis,
            "mesh_size": mesh_size,
            "spmd_enabled": enabled,
        })
    return Table.from_pydict({
        "device_id": _col(rows, "device_id", np.int64, 0),
        "platform": _col(rows, "platform", object, ""),
        "kind": _col(rows, "kind", object, ""),
        "process": _col(rows, "process", np.int64, 0),
        "in_mesh": _col(rows, "in_mesh", np.bool_, False),
        "mesh_axes": _col(rows, "mesh_axes", object, ""),
        "mesh_size": _col(rows, "mesh_size", np.int64, 0),
        "spmd_enabled": _col(rows, "spmd_enabled", np.bool_, False),
    })


def _devices() -> Table:
    """Per-device HBM truth: one row per LOCAL device with live
    ``memory_stats()`` readings (bytes in use / peak / limit — zeros on
    backends without memory stats, e.g. CPU).  Deliberately reads jax
    directly rather than importing runtime.profiler, so querying
    ``system.devices`` keeps the profiler's zero-import guarantee when
    ``DSQL_PROFILE`` is off."""
    import jax

    rows: List[dict] = []
    try:
        devices = jax.local_devices()
    except Exception:  # pragma: no cover
        devices = []
    for d in devices:
        try:
            mem = d.memory_stats() or {}
        except Exception:
            mem = {}
        rows.append({
            "device_id": int(getattr(d, "id", len(rows))),
            "platform": str(getattr(d, "platform", "")),
            "kind": str(getattr(d, "device_kind", "")),
            "bytes_in_use": int(mem.get("bytes_in_use", 0) or 0),
            "peak_bytes_in_use": int(mem.get("peak_bytes_in_use", 0) or 0),
            "bytes_limit": int(mem.get("bytes_limit", 0) or 0),
        })
    return Table.from_pydict({
        "device_id": _col(rows, "device_id", np.int64, 0),
        "platform": _col(rows, "platform", object, ""),
        "kind": _col(rows, "kind", object, ""),
        "bytes_in_use": _col(rows, "bytes_in_use", np.int64, 0),
        "peak_bytes_in_use": _col(rows, "peak_bytes_in_use", np.int64, 0),
        "bytes_limit": _col(rows, "bytes_limit", np.int64, 0),
    })


def _spill() -> Table:
    """One row per live spill run (grace-hash partition / out-of-core join
    output), with its tier placement — a mid-query `SELECT * FROM
    system.spill` from a second connection shows exactly which partitions
    sit on device vs host vs disk.  Usually empty: runs are freed as each
    partition pair completes."""
    from . import spill as _spill_mod

    rows = _spill_mod.get_store().runs_snapshot()
    return Table.from_pydict({
        "run": _col(rows, "run", object, ""),
        "chunks": _col(rows, "chunks", np.int64, 0),
        "rows": _col(rows, "rows", np.int64, 0),
        "nbytes": _col(rows, "nbytes", np.int64, 0),
        "device_chunks": _col(rows, "device_chunks", np.int64, 0),
        "host_chunks": _col(rows, "host_chunks", np.int64, 0),
        "disk_chunks": _col(rows, "disk_chunks", np.int64, 0),
    })


def _prepared(context=None) -> Table:
    """One row per PREPARE-registered statement on the resolving context
    (physical/rel/custom.py): name, parameter count, and the statement
    text EXECUTE will bind."""
    reg = getattr(context, "_prepared", None) or {}
    rows = [{"name": name, "num_params": int(stmt.num_params),
             "statement": stmt.sql}
            for name, stmt in sorted(reg.items())]
    return Table.from_pydict({
        "name": _col(rows, "name", object, ""),
        "num_params": _col(rows, "num_params", np.int64, 0),
        "statement": _col(rows, "statement", object, ""),
    })


def _matviews(context=None) -> Table:
    """One row per registered materialized view (runtime/matview.py):
    maintainability verdict with the full-recompute reason, delta backlog,
    and the serve/refresh counters the acceptance criteria reconcile."""
    from . import matview as _mv

    rows = _mv.matview_rows(context) if context is not None else []
    return Table.from_pydict({
        "schema": _col(rows, "schema", object, ""),
        "name": _col(rows, "name", object, ""),
        "rows": _col(rows, "rows", np.int64, 0),
        "maintainable": _col(rows, "maintainable", object, ""),
        "reason": _col(rows, "reason", object, ""),
        "base_tables": _col(rows, "base_tables", object, ""),
        "pending_deltas": _col(rows, "pending_deltas", np.int64, 0),
        "pending_rows": _col(rows, "pending_rows", np.int64, 0),
        "staleness_s": _col(rows, "staleness_s", np.float64, 0.0),
        "serves": _col(rows, "serves", np.int64, 0),
        "refresh_incremental": _col(rows, "refresh_incremental",
                                    np.int64, 0),
        "refresh_full": _col(rows, "refresh_full", np.int64, 0),
        "last_refresh": _col(rows, "last_refresh", object, ""),
        "fingerprint": _col(rows, "fingerprint", object, ""),
    })


def _view_candidates(context=None) -> Table:
    """Hot repeated plan fingerprints from the flight recorder's EWMA
    history ranked by hits x recompute cost — the operator's shortlist of
    what to CREATE MATERIALIZED VIEW next.  Empty when the recorder
    (DSQL_HISTORY_FILE) is off."""
    from . import matview as _mv

    rows = _mv.view_candidate_rows(context) if context is not None else []
    return Table.from_pydict({
        "fingerprint": _col(rows, "fingerprint", object, ""),
        "hits": _col(rows, "hits", np.int64, 0),
        "ewma_ms": _col(rows, "ewma_ms", np.float64, 0.0),
        "score": _col(rows, "score", np.float64, 0.0),
        "materialized": _col(rows, "materialized", np.bool_, False),
        "example_sql": _col(rows, "example_sql", object, ""),
    })


def _events() -> Table:
    """Watchtower bus ring (runtime/events.py): one row per structured
    event, trace-correlatable with ``system.queries``.  Reads the env gate
    BEFORE importing events — with ``DSQL_EVENTS`` off this yields the
    fixed empty schema and the module stays un-imported."""
    import os

    rows: List[dict] = []
    if _fleet_on():
        # fleet mode: all replicas' event rings merged in timestamp
        # order — one trace id stitches across the replicas it touched
        from . import fleet as _fleet

        rows = _fleet.merged_events_rows()
    elif os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
        from . import events as _ev

        rows = _ev.events_rows()
    return Table.from_pydict({
        "seq": _col(rows, "seq", np.int64, 0),
        "unix": _col(rows, "unix", np.float64, 0.0),
        "pid": _col(rows, "pid", np.int64, 0),
        "trace": _col(rows, "trace", object, ""),
        "type": _col(rows, "type", object, ""),
        "replica": _col(rows, "replica", object, ""),
        "detail": _col(rows, "detail", object, ""),
    })


def _slo() -> Table:
    """Per-priority-class latency objectives and their multi-window burn
    rates (runtime/events.py SloMonitor).  Same zero-import discipline as
    ``system.events`` — empty fixed schema when the watchtower is off."""
    import os

    rows: List[dict] = []
    if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
        from . import events as _ev

        rows = _ev.slo_rows()
    return Table.from_pydict({
        "class": _col(rows, "class", object, ""),
        "objective_ms": _col(rows, "objective_ms", np.float64, 0.0),
        "target": _col(rows, "target", np.float64, 0.0),
        "window_fast_s": _col(rows, "window_fast_s", np.float64, 0.0),
        "window_slow_s": _col(rows, "window_slow_s", np.float64, 0.0),
        "total": _col(rows, "total", np.int64, 0),
        "breaches": _col(rows, "breaches", np.int64, 0),
        "attainment": _col(rows, "attainment", np.float64, 1.0),
        "burn_fast": _col(rows, "burn_fast", np.float64, 0.0),
        "burn_slow": _col(rows, "burn_slow", np.float64, 0.0),
        "breach": _col(rows, "breach", np.bool_, False),
    })


def _tenants() -> Table:
    """Per-tenant admission accounting and circuit state
    (runtime/tenancy.py TenantRegistry).  Same env-gate-before-import
    discipline as ``system.events`` — ``DSQL_TENANCY=0`` yields the fixed
    empty schema and the module stays un-imported."""
    import os

    rows: List[dict] = []
    if os.environ.get("DSQL_TENANCY", "1").strip() not in ("", "0"):
        from . import tenancy as _ten

        rows = _ten.tenant_rows()
    return Table.from_pydict({
        "tenant": _col(rows, "tenant", object, ""),
        "inflight": _col(rows, "inflight", np.int64, 0),
        "tokens": _col(rows, "tokens", np.float64, 0.0),
        "submitted": _col(rows, "submitted", np.int64, 0),
        "admitted": _col(rows, "admitted", np.int64, 0),
        "completed": _col(rows, "completed", np.int64, 0),
        "failed": _col(rows, "failed", np.int64, 0),
        "quota_rejects": _col(rows, "quota_rejects", np.int64, 0),
        "circuit_rejects": _col(rows, "circuit_rejects", np.int64, 0),
        "circuit_opens": _col(rows, "circuit_opens", np.int64, 0),
        "consecutive_failures": _col(rows, "consecutive_failures",
                                     np.int64, 0),
        "circuit": _col(rows, "circuit", object, ""),
    })


def _replicas() -> Table:
    """One row per registered fleet replica (runtime/fleet.py heartbeat
    registry): identity, liveness (``alive`` = beat within TTL),
    scheduler/cache/spill occupancy, and the shared-warmth counters
    (program-store hits/misses/hit-rate per replica).  Same
    env-gate-before-import discipline as ``system.events`` — an unset
    ``DSQL_FLEET_DIR`` yields the fixed empty schema."""
    rows: List[dict] = []
    if _fleet_on():
        from . import fleet as _fleet

        rows = _fleet.replica_rows()
    return Table.from_pydict({
        "replica": _col(rows, "replica", object, ""),
        "pid": _col(rows, "pid", np.int64, 0),
        "host": _col(rows, "host", object, ""),
        "alive": _col(rows, "alive", np.bool_, False),
        "started": _col(rows, "started", np.float64, 0.0),
        "beat": _col(rows, "beat", np.float64, 0.0),
        "age_s": _col(rows, "age_s", np.float64, 0.0),
        "running": _col(rows, "running", np.int64, 0),
        "queue_depth": _col(rows, "queue_depth", np.int64, 0),
        "slots": _col(rows, "slots", np.int64, 0),
        "queries": _col(rows, "queries", np.int64, 0),
        "cache_bytes": _col(rows, "cache_bytes", np.int64, 0),
        "spill_bytes": _col(rows, "spill_bytes", np.int64, 0),
        "reserved_bytes": _col(rows, "reserved_bytes", np.int64, 0),
        "program_entries": _col(rows, "program_entries", np.int64, 0),
        "program_hits": _col(rows, "program_hits", np.int64, 0),
        "program_misses": _col(rows, "program_misses", np.int64, 0),
        "program_hit_rate": _col(rows, "program_hit_rate", np.float64, 0.0),
        "compiles": _col(rows, "compiles", np.int64, 0),
    })


def _autopilot() -> Table:
    """The autopilot's action journal (runtime/autopilot.py): one row per
    matview create/refresh/drop, re-plan hint record/verdict/revert, or
    faulted tick, newest last.  Same env-gate-before-import discipline as
    ``system.events`` — ``DSQL_AUTOPILOT=0`` yields the fixed empty
    schema and the module stays un-imported."""
    import os

    rows: List[dict] = []
    if os.environ.get("DSQL_AUTOPILOT", "0").strip() not in ("", "0"):
        from . import autopilot as _ap

        rows = _ap.journal_rows()
    return Table.from_pydict({
        "unix": _col(rows, "unix", np.float64, 0.0),
        "action": _col(rows, "action", object, ""),
        "trigger": _col(rows, "trigger", object, ""),
        "fingerprint": _col(rows, "fingerprint", object, ""),
        "verdict": _col(rows, "verdict", object, ""),
        "bytes": _col(rows, "bytes", np.int64, 0),
        "detail": _col(rows, "detail", object, ""),
    })


_BUILDERS: Dict[str, object] = {
    "queries": _queries,
    "active": _active,
    "metrics": _metrics,
    "cache": _cache,
    "quarantine": _quarantine,
    "programs": _programs,
    "table_stats": _table_stats,
    "mesh": _mesh,
    "spill": _spill,
    "devices": _devices,
    "matviews": _matviews,
    "view_candidates": _view_candidates,
    "events": _events,
    "slo": _slo,
    "prepared": _prepared,
    "tenants": _tenants,
    "replicas": _replicas,
    "autopilot": _autopilot,
}

#: builders that need the resolving context (catalog / mesh live there)
_CONTEXT_BUILDERS = (_table_stats, _mesh, _matviews, _view_candidates,
                     _prepared)


def build(name: str, context=None) -> Optional[Table]:
    """A fresh snapshot Table for ``system.<name>``, or None for unknown
    names (the binder then reports the table as undefined)."""
    builder = _BUILDERS.get(name.lower())
    if builder is None:
        return None
    if builder in _CONTEXT_BUILDERS:
        return builder(context)  # type: ignore[operator]
    return builder()  # type: ignore[operator]
