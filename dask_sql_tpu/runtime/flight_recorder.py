"""Flight recorder: persistent query history + measured operator statistics.

Everything the engine observes about itself (runtime/telemetry.py) is
in-process and dies with the interpreter: QueryReports, per-stage timings
and measured row counts evaporate on exit, and the workload manager's
memory broker still plans from the scan-bytes×multiplier guess
(scheduler.estimate_plan_bytes).  This module is the durable half of that
loop — the recording side of ROADMAP item 3's statistics subsystem:

**Event log.**  ``DSQL_HISTORY_FILE`` names a JSONL ring holding one
``query`` envelope per completed query (outcome, tier, priority class,
cache/admission verdicts, typed error, measured bytes) and one ``stage``
record per executed stage of a stage graph (canonical stage digest,
measured input/output rows vs the padded power-of-2 capacity class,
wall/device ms, boundary bytes).  Appends are single ``os.write`` calls
with ``O_APPEND`` — atomic across processes for any sane line length — and
read-back tolerates corrupt/torn lines (skipped, never fatal), the same
degrade-to-empty discipline as runtime/kvstore.py.  When the file outgrows
``DSQL_HISTORY_MB`` (default 16) it is truncated to its newest half via
tmp + ``os.replace`` — a bounded ring, not an unbounded log.

**Operator-statistics history.**  Every envelope/stage record also folds
into an EWMA statistics file (``<DSQL_HISTORY_FILE>.stats``, kvstore
plumbing) keyed by canonical plan/stage fingerprint
(result_cache.canonical_plan text digest — stable across restarts and
reloads, unlike uid-folded cache keys).  The scheduler's memory broker
consults it FIRST (``scheduler.estimate_working_set`` →
:func:`plan_history_bytes`, counter ``estimate_from_history``) and only
falls back to the multiplier heuristic for never-seen plans; this is the
seam adaptive operator selection plugs into later.

**Live registry.**  Traces register here while open (gated on the same env
knob) so ``system.active`` and ``GET /v1/engine`` can report in-flight
queries with phase, tier and per-stage progress.

**Zero overhead when disabled.**  With ``DSQL_HISTORY_FILE`` unset every
hook is a single ``os.environ.get`` returning early — no lock, no
allocation, no import of this module from the hot path (callers check the
env var themselves before importing).  tests/unit/test_flight_recorder.py
pins this.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import telemetry as _tel
from .kvstore import MtimeCachedJsonFile, digest_key

logger = logging.getLogger(__name__)

_DEFAULT_LIMIT_MB = 16.0
_EWMA_ALPHA = 0.3               # matches the scheduler's slot-hold EWMA
_DEFAULT_HEADROOM = 1.5         # reservation = measured EWMA × headroom

# serializes THIS process's appends + ring maintenance; cross-process
# interleaving is handled by O_APPEND single-write lines + atomic replace
_LOCK = threading.Lock()

# live traces: id(trace) -> QueryTrace.  Plain-dict ops only (GIL-atomic) —
# registration is gated on enabled(), removal is an unconditional cheap pop.
_ACTIVE: Dict[int, Any] = {}


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def history_path() -> Optional[str]:
    """The JSONL ring path, or None when the recorder is disabled."""
    return os.environ.get("DSQL_HISTORY_FILE") or None


def enabled() -> bool:
    return bool(os.environ.get("DSQL_HISTORY_FILE"))


def history_limit_bytes() -> int:
    """``DSQL_HISTORY_MB`` (fractional accepted — tests use KB-scale
    rings) as bytes; never below 4 KiB so the ring keeps SOME history."""
    raw = os.environ.get("DSQL_HISTORY_MB", "")
    try:
        mb = float(raw) if raw else _DEFAULT_LIMIT_MB
    except ValueError:
        mb = _DEFAULT_LIMIT_MB
    return max(int(mb * 2**20), 4096)


def stats_path() -> Optional[str]:
    path = history_path()
    return f"{path}.stats" if path else None


def stats_ttl_s() -> float:
    """``DSQL_HISTORY_STATS_TTL_S``: fingerprints whose EWMA entry was
    not refreshed within this window are pruned at ring truncation
    (default 7 days — long enough to survive a weekend of idleness,
    short enough that one-off ad-hoc plans don't accrete forever)."""
    raw = os.environ.get("DSQL_HISTORY_STATS_TTL_S", "")
    try:
        ttl = float(raw) if raw else 7 * 86400.0
    except ValueError:
        ttl = 7 * 86400.0
    return max(ttl, 0.0)


def stats_max_entries() -> int:
    """``DSQL_HISTORY_STATS_MAX``: hard entry cap on the sidecar (newest
    ``updated`` wins) — the TTL alone cannot bound a fast churn of
    *recent* fingerprints."""
    raw = os.environ.get("DSQL_HISTORY_STATS_MAX", "")
    try:
        n = int(raw) if raw else 4096
    except ValueError:
        n = 4096
    return max(n, 16)


_STATS = MtimeCachedJsonFile(stats_path)


def _fleet_replica() -> Optional[str]:
    """Replica id when the fleet plane (runtime/fleet.py) is armed, else
    None — env checked BEFORE the import, so unarmed envelopes stay
    byte-identical and the fleet module stays un-imported."""
    if not os.environ.get("DSQL_FLEET_DIR"):
        return None
    from . import fleet as _fleet
    return _fleet.replica_id()


# ---------------------------------------------------------------------------
# the JSONL ring
# ---------------------------------------------------------------------------

def _append(path: str, rec: dict) -> None:
    """One event → one line → one O_APPEND write (atomic cross-process),
    then bounded ring maintenance."""
    line = (json.dumps(rec, separators=(",", ":"), default=str) + "\n"
            ).encode()
    with _LOCK:
        fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        try:
            os.write(fd, line)
            size = os.fstat(fd).st_size
        finally:
            os.close(fd)
    _tel.inc("history_records")
    if size > history_limit_bytes():
        _truncate_ring(path)


def _truncate_ring(path: str) -> None:
    """Drop the OLDEST half of the ring via tmp + atomic replace.

    Concurrency model matches kvstore: a writer racing the replace can lose
    a few lines (events are advisory history, never correctness state) but
    can never corrupt the file or block a query."""
    limit = history_limit_bytes()
    with _LOCK:
        try:
            with open(path, "rb") as f:
                lines = f.readlines()
            kept: List[bytes] = []
            budget = limit // 2
            total = 0
            for raw in reversed(lines):
                total += len(raw)
                if total > budget:
                    break
                kept.append(raw)
            kept.reverse()
            tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.writelines(kept)
            os.replace(tmp, path)
            _tel.inc("history_truncations")
        except OSError:
            logger.debug("history ring truncation failed", exc_info=True)
            _tel.inc("history_errors")
    # sidecar maintenance rides the ring's own cadence: the sidecar only
    # grows while envelopes are appended, and appends are what trigger
    # truncation — so pruning here bounds the .stats file under churn
    # without a timer thread
    _prune_stats()


def _prune_stats() -> None:
    """Bound the EWMA sidecar: drop fingerprints not observed within
    ``stats_ttl_s()``, then cap survivors to ``stats_max_entries()``
    newest-by-``updated``.  Read-filter-replace under kvstore discipline:
    a racing ``_observe_stat`` can resurrect one entry, never corrupt."""
    try:
        data = _STATS.read()
        if not data:
            return
        now = time.time()
        ttl = stats_ttl_s()
        keep = {fp: e for fp, e in data.items()
                if isinstance(e, dict)
                and now - float(e.get("updated", 0) or 0) <= ttl}
        cap = stats_max_entries()
        if len(keep) > cap:
            newest = sorted(keep.items(),
                            key=lambda kv: float(kv[1].get("updated", 0)
                                                 or 0),
                            reverse=True)[:cap]
            keep = dict(newest)
        if len(keep) != len(data):
            _STATS.write(keep)
    except Exception:
        logger.debug("stats sidecar prune failed", exc_info=True)


def read_events(kind: Optional[str] = None,
                limit: Optional[int] = None) -> List[dict]:
    """Read the ring back, newest LAST; corrupt/torn lines are skipped.
    Missing/unreadable file (or recorder disabled) reads as empty."""
    path = history_path()
    if not path:
        return []
    try:
        with open(path, "rb") as f:
            lines = f.readlines()
    except OSError:
        return []
    out: List[dict] = []
    for raw in lines:
        try:
            rec = json.loads(raw)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        if kind is not None and rec.get("kind") != kind:
            continue
        out.append(rec)
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


# ---------------------------------------------------------------------------
# EWMA operator-statistics history (cross-process, like caps/quarantine)
# ---------------------------------------------------------------------------

def _observe_stat(fp: str, nbytes: Optional[int] = None,
                  rows: Optional[int] = None,
                  ms: Optional[float] = None,
                  cost_bytes: Optional[float] = None,
                  cost_flops: Optional[float] = None) -> None:
    """Fold one measurement into the per-fingerprint EWMA entry.
    Read-merge-replace (kvstore discipline): a lost race costs one
    observation, never corruption.  ``cost_bytes``/``cost_flops`` are the
    profiler's XLA cost-model predictions (runtime/profiler.py) — the
    model-vs-measured ledger shares one entry with the measured EWMA so
    the scheduler's cost_model rung survives the process boundary."""
    data = _STATS.read()
    e = dict(data.get(fp) or {})
    for key, v in (("bytes", nbytes), ("rows", rows), ("ms", ms),
                   ("cost_bytes", cost_bytes), ("cost_flops", cost_flops)):
        if v is None:
            continue
        prev = e.get(key)
        e[key] = (float(v) if prev is None
                  else _EWMA_ALPHA * float(v)
                  + (1.0 - _EWMA_ALPHA) * float(prev))
    e["n"] = int(e.get("n", 0)) + 1
    e["updated"] = time.time()
    data[fp] = e
    _STATS.write(data)


def get_stats(fp: str) -> Optional[dict]:
    """The EWMA entry for one canonical plan/stage fingerprint, or None."""
    return _STATS.read().get(fp)


def plan_fingerprint(plan, context) -> Optional[str]:
    """Canonical fingerprint of an optimized plan: digest of
    result_cache.canonical_plan TEXT only — no epochs, no uids — so the
    same query shape keys the same history entry across restarts and table
    reloads.  None for volatile plans (their measurements would mix
    unrelated executions).

    The plan is parameterized first (plan/parameterize.py) and serialized
    in SHAPE mode, so every literal variant of a query shape shares one
    EWMA history entry: cost/working-set estimates learned from
    ``x > 10`` inform admission of ``x > 20``.  With DSQL_PARAM_PLANS=0
    the pass is the identity and fingerprints match the pre-param era
    bit-for-bit."""
    from . import result_cache as _rc
    from ..plan.parameterize import param_plans_enabled, parameterize_plan

    if param_plans_enabled():
        plan, _ = parameterize_plan(plan)
    text, volatile, _scans = _rc.canonical_plan(plan, context, shape=True)
    if volatile:
        return None
    return digest_key(text)


def plan_history_bytes(plan, context) -> Optional[int]:
    """Measured working-set reservation for this plan from history, with
    ``DSQL_HISTORY_HEADROOM`` (default 1.5×) on top — or None when the
    recorder is off / the plan was never measured.  The scheduler's
    estimate path (scheduler.estimate_working_set) calls this FIRST."""
    if not enabled():
        return None
    fp = plan_fingerprint(plan, context)
    if fp is None:
        return None
    entry = get_stats(fp)
    if not entry or "bytes" not in entry:
        return None
    try:
        headroom = float(os.environ.get("DSQL_HISTORY_HEADROOM", "") or
                         _DEFAULT_HEADROOM)
    except ValueError:
        headroom = _DEFAULT_HEADROOM
    return int(float(entry["bytes"]) * max(headroom, 1.0))


# ---------------------------------------------------------------------------
# recording hooks (telemetry._close_trace / physical.compiled.run_stage)
# ---------------------------------------------------------------------------

def record_query(report, error: Optional[BaseException] = None) -> None:
    """Append one envelope for a completed query and feed its plan-level
    EWMA entry.  Called from telemetry._close_trace AFTER the env gate —
    this function may assume the recorder is on (but re-checks cheaply so
    direct callers cannot crash)."""
    path = history_path()
    if not path:
        return
    plan_fp = None
    est_bytes = 0
    est_source = None
    queued_ms = None
    stage_bytes = 0
    for s in report.root.walk():
        if plan_fp is None and "plan_fp" in s.attrs:
            plan_fp = s.attrs.get("plan_fp")
        if s.name == "queued":
            est_bytes = int(s.attrs.get("est_bytes", est_bytes) or 0)
            est_source = s.attrs.get("est_source", est_source)
            queued_ms = s.attrs.get("queued_ms", queued_ms)
        stage_bytes += int(s.attrs.get("stage_bytes", 0) or 0)
    # measured working-set proxy: the result plus every materialized stage
    # boundary this query produced — all bytes the engine actually touched
    # and the broker would have had to host concurrently
    measured = int(report.bytes_out) + stage_bytes
    cache_hit = bool(report.cache.get("hit"))
    rec = {
        "kind": "query",
        "unix": round(report.started_unix, 3),
        "pid": os.getpid(),
        "query": report.query.strip()[:500],
        "outcome": ("error" if error is not None
                    else "cache_hit" if cache_hit else "ok"),
        "error": type(error).__name__ if error is not None else "",
        "wall_ms": round(report.wall_ms, 3),
        "tier": report.tier or "",
        "priority": report.priority or "",
        "cache_hit": cache_hit,
        "cache_tier": report.cache.get("tier") or "",
        "cache_stored": bool(report.cache.get("stored")),
        "rows_out": int(report.rows_out),
        "bytes_out": int(report.bytes_out),
        "measured_bytes": measured,
        "est_bytes": est_bytes,
        "est_source": est_source or "",
        "queued_ms": float(queued_ms or 0.0),
        "plan_fp": plan_fp or "",
        "operators": list(getattr(report, "operators", ()) or ()),
        "phases": {k: round(v, 3) for k, v in report.phases.items()},
        # device-level profile fields (ISSUE 13): worst shard/partition
        # skew, collective bytes split by kind, and the XLA cost-model
        # error vs measured bytes — so system.queries answers "which
        # queries are skew-bound" in SQL.  Zeros when nothing annotated.
        "skew_ratio": float(getattr(report, "skew_ratio", None) or 0.0),
        "collective_bytes": dict(getattr(report, "collective_bytes", None)
                                 or {}),
        "cost_err": (float(report.cost_err)
                     if getattr(report, "cost_err", None) is not None
                     else -1.0),
    }
    # end-to-end trace ID (runtime/events.py, DSQL_EVENTS=1): present
    # only when one was minted, so unarmed envelopes stay byte-identical
    tid = getattr(report, "trace_id", None)
    if tid:
        rec["trace"] = str(tid)
    # tenant identity (runtime/tenancy.py): same conditional-field
    # discipline — only an explicitly-tenanted query carries it, so
    # default-tenant envelopes stay byte-identical
    ten = getattr(report, "tenant", None)
    if ten:
        rec["tenant"] = str(ten)
    rid = _fleet_replica()
    if rid:
        rec["replica"] = rid
    _append(path, rec)
    if plan_fp and error is None:
        if cache_hit:
            # a cache hit bypassed execution: bump the hit count ONLY, so
            # hot queries keep accruing rank in system.view_candidates
            # without folding a near-zero wall into the recompute-cost
            # EWMA (which would crater score = n × ewma_ms)
            _observe_stat(plan_fp)
        elif measured > 0:
            _observe_stat(plan_fp, nbytes=measured, rows=report.rows_out,
                          ms=report.wall_ms)


def record_stage(digest: str, rows_in: int, rows_out: int, capacity: int,
                 nbytes: int, wall_ms: float,
                 device_ms: Optional[float] = None,
                 query_fp: str = "") -> None:
    """Append one stats record for an executed stage and feed the
    stage-fingerprint EWMA entry.  Callers gate on DSQL_HISTORY_FILE."""
    path = history_path()
    if not path:
        return
    rec = {
        "kind": "stage",
        "unix": round(time.time(), 3),
        "pid": os.getpid(),
        "digest": digest,
        "query_fp": query_fp,
        "rows_in": int(rows_in),
        "rows_out": int(rows_out),
        "capacity": int(capacity),
        "bytes": int(nbytes),
        "wall_ms": round(float(wall_ms), 3),
        "device_ms": round(float(device_ms), 3) if device_ms else 0.0,
    }
    rid = _fleet_replica()
    if rid:
        rec["replica"] = rid
    _append(path, rec)
    _observe_stat(digest, nbytes=nbytes, rows=rows_out, ms=wall_ms)


# ---------------------------------------------------------------------------
# live-query registry (system.active / GET /v1/engine)
# ---------------------------------------------------------------------------

def begin_query(trace) -> bool:
    """Register an opening trace; True when registered (the caller then
    owes an end_query).  No-op (False) when the recorder is off."""
    if not enabled():
        return False
    _ACTIVE[id(trace)] = trace
    return True


def end_query(trace) -> None:
    _ACTIVE.pop(id(trace), None)


def active_snapshot() -> List[dict]:
    """Live in-flight queries of THIS process: phase (deepest open span),
    tier, priority, elapsed, and per-stage progress.  Safe against
    concurrent span appends (Span.walk copies child lists)."""
    out: List[dict] = []
    now = time.time()
    for trace in list(_ACTIVE.values()):
        root = trace.root
        phase = root.name
        tier = None
        priority = None
        stages_total = 0
        stages_done = 0
        for s in root.walk():
            if s.t1 is None:
                phase = s.name
            t = s.attrs.get("tier")
            if tier is None and t is not None:
                tier = str(t)
            if s.name == "queued" and priority is None:
                priority = s.attrs.get("priority")
            if s.name == "stage_graph":
                stages_total += int(s.attrs.get("stages", 0) or 0)
            elif s.name == "stage" and s.t1 is not None:
                stages_done += 1
        out.append({
            "query": trace.query.strip()[:500],
            "phase": phase,
            "tier": tier or "",
            "priority": priority or "",
            "elapsedMillis": round(max(now - trace.started_unix, 0.0) * 1e3,
                                   1),
            "stagesTotal": stages_total,
            "stagesDone": stages_done,
            "pid": os.getpid(),
        })
    return out
