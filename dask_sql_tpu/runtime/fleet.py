"""Fleet plane: replica registry, cross-replica merge, shared warmth.

Every observability surface the engine grew so far — ``GET /v1/engine``,
``system.events``, SLO burn rates, ``/metrics`` — is process-local, so a
multi-replica deployment (several server processes pointed at one
``DSQL_PROGRAM_STORE`` so any replica's compile warms the fleet, ROADMAP
item 1) is invisible as a whole: no registry of who is alive, no merged
event stream, no fleet-wide SLO, and no way to *prove* replica B served
replica A's compiled shapes.  This module is that missing plane, built
on the same crash-tolerant shared-dir substrate the program store
(kvstore) and the watchtower/flight-recorder JSONL rings already use:

**Arming.**  ``DSQL_FLEET_DIR`` names the shared directory; the env var
is checked BEFORE importing this module everywhere (the PR 8/14
discipline — the disabled path stays zero-import and the wire stays
byte-identical, pinned by tests).  :func:`ensure_armed` is the one
idempotent entry point (``Context.__init__`` and ``run_server`` call it
behind the gate): it redirects the watchtower event ring and the
flight-recorder envelope ring into per-replica files inside the fleet
dir (``events-<replica>.jsonl`` / ``history-<replica>.jsonl``) by
installing the existing ``DSQL_EVENTS``/``DSQL_EVENTS_FILE``/
``DSQL_HISTORY_FILE`` env defaults in-process — every downstream gate
then works unchanged — and starts the heartbeater.

**Replica registry.**  Each replica writes a heartbeat JSON file
(``replicas/<replica>.json``, kvstore ``atomic_write_json``) every
``DSQL_FLEET_BEAT_S`` (default 2 s): identity (replica id, pid, host,
started), scheduler slots/queue, memory-ledger and cache/spill
occupancy, program-store stats, per-class SLO rows, per-tenant
attainment gauges and live anomaly flags.  :func:`read_replicas` scans
the registry with corrupt-file tolerance (an unreadable heartbeat reads
as absent, never raises) and TTL expiry: a replica whose last beat is
older than ``DSQL_FLEET_TTL_S`` (default 3x beat) is reported
``alive=False`` — a kill -9'd replica ages out, nothing to clean up.

**Merged streams.**  Every event and envelope a fleet-armed replica
writes is stamped with its replica id, so
:func:`merged_events_rows`/:func:`merged_query_rows` can merge all
replicas' rings in timestamp order — one trace id stitches across the
replicas it touched — and :func:`read_merged_since` long-polls the
union with a COMPOSITE cursor (``replica:seq;replica:seq``): a k-way
merge over per-replica seq order, so per-replica delivery is monotonic
and lossless even while children append concurrently.

**Shared-warmth proof.**  Replicas pointed at one program store share
compiled executables; the fleet snapshot (``GET /v1/fleet``) sums each
replica's ``program_store_hits`` into ``warmServes`` and computes
per-replica hit rates — the counters that prove replica B served
replica A's shapes with zero compiles (scripts/fleet_obs_smoke.py
drives exactly that).
"""
from __future__ import annotations

import heapq
import json
import logging
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry as _tel
from .kvstore import atomic_write_json

logger = logging.getLogger(__name__)

_ID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-.")

_STARTED_UNIX = time.time()


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def fleet_dir() -> Optional[str]:
    """The shared fleet directory, or None (fleet plane disabled)."""
    return os.environ.get("DSQL_FLEET_DIR") or None


def enabled() -> bool:
    return bool(fleet_dir())


def _env_float(name: str, default: float) -> float:
    try:
        raw = os.environ.get(name, "")
        return float(raw) if raw else default
    except ValueError:
        return default


def beat_interval_s() -> float:
    """``DSQL_FLEET_BEAT_S``: heartbeat refresh cadence (default 2 s;
    tests run sub-second beats)."""
    return max(_env_float("DSQL_FLEET_BEAT_S", 2.0), 0.05)


def ttl_s() -> float:
    """``DSQL_FLEET_TTL_S``: a replica whose last beat is older than
    this is expired (default 3x the beat interval, never below one
    beat) — the registry's only liveness mechanism, so a killed replica
    needs no cleanup."""
    return max(_env_float("DSQL_FLEET_TTL_S", 3.0 * beat_interval_s()),
               beat_interval_s())


def _sanitize_id(raw: Any) -> Optional[str]:
    if not raw:
        return None
    s = str(raw).strip()
    if not s or len(s) > 64 or not all(c in _ID_CHARS for c in s):
        return None
    return s


_RID_LOCK = threading.Lock()
_RID: Optional[str] = None


def replica_id() -> str:
    """This process's stable replica identity: ``DSQL_REPLICA_ID``
    (sanitized) when set — fleet children are usually launched with an
    explicit one — else ``<hostname>-<pid>``.  Cached after first use so
    every stamp this process writes agrees."""
    global _RID
    with _RID_LOCK:
        if _RID is None:
            rid = _sanitize_id(os.environ.get("DSQL_REPLICA_ID"))
            if rid is None:
                host = "".join(c if c in _ID_CHARS else "-"
                               for c in socket.gethostname())[:32] or "host"
                rid = f"{host}-{os.getpid()}"
            _RID = rid
        return _RID


def replicas_dir() -> str:
    return os.path.join(fleet_dir() or ".", "replicas")


def heartbeat_path(rid: Optional[str] = None) -> str:
    return os.path.join(replicas_dir(), f"{rid or replica_id()}.json")


def events_path(rid: Optional[str] = None) -> str:
    return os.path.join(fleet_dir() or ".",
                        f"events-{rid or replica_id()}.jsonl")


def history_path(rid: Optional[str] = None) -> str:
    return os.path.join(fleet_dir() or ".",
                        f"history-{rid or replica_id()}.jsonl")


# ---------------------------------------------------------------------------
# arming: env redirection + the heartbeater
# ---------------------------------------------------------------------------

_ARM_LOCK = threading.Lock()
_ARMED = False
_BEATER: Optional["_Heartbeater"] = None


class _Heartbeater(threading.Thread):
    """Daemon thread refreshing this replica's heartbeat file every
    ``beat_interval_s()``; a failed beat counts ``fleet_heartbeat_errors``
    and never propagates."""

    def __init__(self):
        super().__init__(name="dsql-fleet-heartbeat", daemon=True)
        self.stop = threading.Event()

    def run(self) -> None:
        while not self.stop.wait(beat_interval_s()):
            try:
                write_heartbeat_now()
            except Exception:
                _tel.inc("fleet_heartbeat_errors")
                logger.debug("fleet heartbeat failed", exc_info=True)


def ensure_armed() -> bool:
    """Idempotently arm the fleet plane for this process: create the
    shared dir, install the watchtower/recorder env redirection (every
    existing ``DSQL_EVENTS``/``DSQL_HISTORY_FILE`` gate then fires
    unchanged; explicit user-set values win via ``setdefault``), write
    the first heartbeat, and start the heartbeater.  Returns False —
    doing nothing — when ``DSQL_FLEET_DIR`` is unset."""
    global _ARMED, _BEATER
    d = fleet_dir()
    if not d:
        return False
    with _ARM_LOCK:
        if _ARMED:
            return True
        os.makedirs(replicas_dir(), exist_ok=True)
        rid = replica_id()
        # the redirection: per-replica rings inside the shared dir, and
        # a pinned replica id so worker children of THIS replica stamp
        # consistently.  setdefault — an operator pointing the rings
        # elsewhere explicitly keeps their paths.
        os.environ.setdefault("DSQL_REPLICA_ID", rid)
        os.environ.setdefault("DSQL_EVENTS", "1")
        os.environ.setdefault("DSQL_EVENTS_FILE", events_path(rid))
        os.environ.setdefault("DSQL_HISTORY_FILE", history_path(rid))
        try:
            write_heartbeat_now()
        except Exception:
            _tel.inc("fleet_heartbeat_errors")
            logger.debug("initial fleet heartbeat failed", exc_info=True)
        _BEATER = _Heartbeater()
        _BEATER.start()
        _ARMED = True
        return True


def _reset_for_tests() -> None:
    """Stop the heartbeater and forget cached identity (unit tests
    re-arm under fresh env)."""
    global _ARMED, _BEATER, _RID
    with _ARM_LOCK:
        if _BEATER is not None:
            _BEATER.stop.set()
            _BEATER = None
        _ARMED = False
    with _RID_LOCK:
        _RID = None


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------

def collect_heartbeat() -> dict:
    """This replica's heartbeat payload.  Every engine probe is wrapped:
    a minimal process (no scheduler, no store) still beats with zeros —
    liveness never depends on feature surface."""
    counters = _tel.REGISTRY.counters()
    gauges = _tel.REGISTRY.gauges()
    hb: Dict[str, Any] = {
        "replica": replica_id(),
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "started": round(_STARTED_UNIX, 3),
        "beat": round(time.time(), 3),
        "beat_interval_s": beat_interval_s(),
        "counters": {k: int(counters.get(k, 0)) for k in (
            "queries", "query_errors", "server_queries", "compiles",
            "stage_compiles", "program_store_hits", "program_store_misses",
            "program_store_stores", "param_plan_hits", "param_plan_misses",
            "events_published", "history_records", "result_pages_served",
            "tenant_queries")},
    }
    try:
        from . import scheduler as _sched
        mgr = _sched.get_manager()
        hb["scheduler"] = {
            "enabled": mgr.enabled(),
            "limit": int(mgr.limit()),
            "queueDepth": int(mgr.queue_depth()),
            "running": int(mgr.running_count()),
            "draining": bool(mgr.draining()),
        }
        hb["memory"] = {"budgetBytes": int(mgr.ledger.budget()),
                        "reservedBytes": int(mgr.ledger.reserved_bytes())}
    except Exception:
        logger.debug("heartbeat scheduler probe failed", exc_info=True)
    hb["cache"] = {
        "bytes": int(gauges.get("result_cache_bytes", 0)),
        "hostBytes": int(gauges.get("result_cache_host_bytes", 0)),
    }
    hb["spill"] = {
        "deviceBytes": int(gauges.get("spill_device_bytes", 0)),
        "hostBytes": int(gauges.get("spill_host_bytes", 0)),
        "diskBytes": int(gauges.get("spill_disk_bytes", 0)),
    }
    try:
        from . import program_store as _pstore
        store = _pstore.get_store()
        hits = int(counters.get("program_store_hits", 0))
        misses = int(counters.get("program_store_misses", 0))
        hb["programStore"] = {
            "enabled": store.enabled(),
            "entries": len(store.entries()) if store.enabled() else 0,
            "bytes": store.total_bytes() if store.enabled() else 0,
            "hits": hits,
            "misses": misses,
            "hitRate": round(hits / (hits + misses), 6)
            if hits + misses else 0.0,
        }
    except Exception:
        logger.debug("heartbeat program-store probe failed", exc_info=True)
    # SLO + anomaly sections ride the watchtower (armed whenever the
    # fleet is — ensure_armed set DSQL_EVENTS)
    try:
        from . import events as _ev
        if _ev.enabled():
            hb["slo"] = _ev.slo_rows()
            hb["anomalies"] = _ev.anomalies()
    except Exception:
        logger.debug("heartbeat slo probe failed", exc_info=True)
    hb["tenant_slo"] = {
        k[len("slo_attainment_tenant_"):]: round(float(v), 6)
        for k, v in gauges.items()
        if k.startswith("slo_attainment_tenant_")}
    return hb


def write_heartbeat_now() -> dict:
    """Collect + atomically publish this replica's heartbeat (the
    heartbeater's tick, also called synchronously by ``GET /v1/fleet``
    so the serving replica's own row is never stale)."""
    hb = collect_heartbeat()
    os.makedirs(replicas_dir(), exist_ok=True)
    atomic_write_json(heartbeat_path(), hb)
    _tel.inc("fleet_heartbeats")
    return hb


def read_replicas() -> List[dict]:
    """Every registered replica's last heartbeat, corrupt files skipped,
    each row annotated with ``alive`` (beat within TTL) and ``age_s``.
    Sorted by replica id for stable output."""
    rows: List[dict] = []
    try:
        names = sorted(os.listdir(replicas_dir()))
    except OSError:
        return rows
    now = time.time()
    ttl = ttl_s()
    for name in names:
        if not name.endswith(".json"):
            continue
        # kvstore.read_json_dict filters scalar top-level values (it is
        # a {key: dict} reader); heartbeats are flat documents, so read
        # them with the same degrade-to-empty discipline directly
        try:
            with open(os.path.join(replicas_dir(), name)) as f:
                hb = json.load(f)
        except (OSError, ValueError):
            continue                      # corrupt/torn/vanished: skipped
        if not isinstance(hb, dict) or "replica" not in hb:
            continue                      # corrupt/torn/foreign: skipped
        try:
            beat = float(hb.get("beat", 0.0) or 0.0)
        except (TypeError, ValueError):
            continue
        hb["age_s"] = round(max(now - beat, 0.0), 3)
        hb["alive"] = (now - beat) <= ttl
        rows.append(hb)
    return rows


# ---------------------------------------------------------------------------
# merged event / envelope streams
# ---------------------------------------------------------------------------

def _read_jsonl(path: str) -> List[dict]:
    """Corrupt/torn-line-tolerant JSONL read (the ring discipline)."""
    try:
        with open(path, "rb") as f:
            lines = f.readlines()
    except OSError:
        return []
    out: List[dict] = []
    for raw in lines:
        try:
            rec = json.loads(raw)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def _ring_files(prefix: str) -> List[Tuple[str, str]]:
    """(replica_id, path) for every per-replica ring of one kind in the
    shared dir, sorted by replica id."""
    d = fleet_dir()
    if not d:
        return []
    out: List[Tuple[str, str]] = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in sorted(names):
        if name.startswith(prefix) and name.endswith(".jsonl"):
            rid = name[len(prefix):-len(".jsonl")]
            if rid:
                out.append((rid, os.path.join(d, name)))
    return out


def merged_events_rows(limit: int = 2000) -> List[dict]:
    """``system.events`` fleet mode: all replicas' event rings merged in
    timestamp order (ties broken by replica id then seq), newest
    ``limit`` kept.  One trace id spanning several replicas interleaves
    here — the cross-replica stitch the smoke gate asserts."""
    merged: List[Tuple[float, str, int, dict]] = []
    for rid, path in _ring_files("events-"):
        for rec in _read_jsonl(path):
            merged.append((float(rec.get("unix", 0.0) or 0.0),
                           str(rec.get("replica", rid) or rid),
                           int(rec.get("seq", 0) or 0), rec))
    merged.sort(key=lambda t: (t[0], t[1], t[2]))
    _tel.inc("fleet_merged_reads")
    rows: List[dict] = []
    core = ("seq", "unix", "pid", "trace", "type", "replica")
    for unix, rid, seq, rec in merged[-max(int(limit), 1):]:
        extra = {k: v for k, v in rec.items() if k not in core}
        rows.append({
            "seq": seq,
            "unix": unix,
            "pid": int(rec.get("pid", 0) or 0),
            "trace": str(rec.get("trace", "") or ""),
            "type": str(rec.get("type", "") or ""),
            "replica": rid,
            "detail": (json.dumps(extra, separators=(",", ":"),
                                  default=str, sort_keys=True)
                       if extra else ""),
        })
    return rows


def merged_query_rows(limit: int = 2000) -> List[dict]:
    """``system.queries`` fleet mode: every replica's flight-recorder
    query envelopes merged in timestamp order, each stamped with the
    replica whose ring it came from."""
    merged: List[Tuple[float, str, dict]] = []
    for rid, path in _ring_files("history-"):
        for rec in _read_jsonl(path):
            if rec.get("kind") != "query":
                continue
            rec = dict(rec)
            rec.setdefault("replica", rid)
            merged.append((float(rec.get("unix", 0.0) or 0.0), rid, rec))
    merged.sort(key=lambda t: (t[0], t[1]))
    _tel.inc("fleet_merged_reads")
    return [rec for _, _, rec in merged[-max(int(limit), 1):]]


# -- composite cursor --------------------------------------------------------

def encode_cursor(cur: Dict[str, int]) -> str:
    """``replica:seq;replica:seq`` with replicas sorted — the
    ``X-DSQL-Cursor`` value of ``GET /v1/events?fleet=1``."""
    return ";".join(f"{rid}:{seq}" for rid, seq in sorted(cur.items())
                    if seq > 0)


def parse_cursor(raw: Optional[str]) -> Dict[str, int]:
    """Tolerant composite-cursor parse: malformed segments are dropped
    (the reader simply re-reads from that replica's start — the merged
    stream is advisory, like the rings)."""
    cur: Dict[str, int] = {}
    for part in (raw or "").split(";"):
        if ":" not in part:
            continue
        rid, _, seq = part.rpartition(":")
        rid = _sanitize_id(rid) or ""
        try:
            n = int(seq)
        except ValueError:
            continue
        if rid and n > 0:
            cur[rid] = n
    return cur


def read_merged_since(cursor: Optional[str], limit: int = 500,
                      timeout_s: float = 0.0,
                      poll_s: float = 0.1) -> Tuple[List[dict], str]:
    """The fleet long-poll: events with per-replica ``seq`` beyond the
    composite cursor, k-way-merged by (unix, replica, seq), capped at
    ``limit``; blocks (re-reading the rings every ``poll_s``) until at
    least one event arrives or ``timeout_s`` passes.

    Per-replica streams are consumed in seq order via the heap merge, so
    for any returned batch each replica's events are a contiguous
    seq-prefix of its pending set — the composite cursor advances
    monotonically and never skips an event that a later read could still
    deliver."""
    cur = parse_cursor(cursor)
    deadline = time.monotonic() + max(timeout_s, 0.0)
    limit = max(int(limit), 1)
    while True:
        streams: List[List[dict]] = []
        for rid, path in _ring_files("events-"):
            floor = cur.get(rid, 0)
            pend = [r for r in _read_jsonl(path)
                    if int(r.get("seq", 0) or 0) > floor]
            if pend:
                pend.sort(key=lambda r: int(r.get("seq", 0) or 0))
                for r in pend:
                    r.setdefault("replica", rid)
                streams.append(pend)
        if streams:
            break
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        time.sleep(min(max(poll_s, 0.01), remaining))
    heads = []
    for i, pend in enumerate(streams):
        r = pend[0]
        heads.append(((float(r.get("unix", 0.0) or 0.0),
                       str(r.get("replica", "")),
                       int(r.get("seq", 0) or 0)), i, 0))
    heapq.heapify(heads)
    out: List[dict] = []
    while heads and len(out) < limit:
        _, i, j = heapq.heappop(heads)
        rec = streams[i][j]
        out.append(rec)
        rid = str(rec.get("replica", ""))
        cur[rid] = max(cur.get(rid, 0), int(rec.get("seq", 0) or 0))
        if j + 1 < len(streams[i]):
            r = streams[i][j + 1]
            heapq.heappush(heads,
                           ((float(r.get("unix", 0.0) or 0.0),
                             str(r.get("replica", "")),
                             int(r.get("seq", 0) or 0)), i, j + 1))
    _tel.inc("fleet_merged_reads")
    return out, encode_cursor(cur)


# ---------------------------------------------------------------------------
# merged SLO over the union of envelopes
# ---------------------------------------------------------------------------

def merged_slo() -> dict:
    """Per-class attainment and multi-window burn computed over the
    UNION of all replicas' query envelopes (not an average of per-replica
    gauges — a replica serving 10x the traffic weighs 10x), plus
    per-tenant attainment over the same union."""
    from . import events as _ev

    now = time.time()
    budget = max(1.0 - _ev.slo_target(), 1e-6)
    win_f, win_s = _ev.window_fast_s(), _ev.window_slow_s()
    per_class: Dict[str, List[Tuple[float, bool]]] = {
        c: [] for c in _ev.SLO_CLASSES}
    tenants: Dict[str, List[int]] = {}
    for rec in merged_query_rows(limit=100_000):
        cls = _ev.SloMonitor._class(rec.get("priority") or None)
        try:
            wall = float(rec.get("wall_ms", 0.0) or 0.0)
            unix = float(rec.get("unix", 0.0) or 0.0)
        except (TypeError, ValueError):
            continue
        ok = wall <= _ev.objective_ms(cls)
        per_class[cls].append((unix, ok))
        ten = rec.get("tenant")
        if ten:
            tot = tenants.setdefault(str(ten), [0, 0])
            tot[0] += 1
            if ok:
                tot[1] += 1
    classes = []
    for cls in _ev.SLO_CLASSES:
        samples = per_class[cls]
        total = len(samples)
        good = sum(1 for _, ok in samples if ok)
        burns = []
        for win in (win_f, win_s):
            inwin = [ok for (t, ok) in samples if now - t <= win]
            if not inwin:
                burns.append(0.0)
                continue
            frac = sum(1 for ok in inwin if not ok) / len(inwin)
            burns.append(frac / budget)
        classes.append({
            "class": cls,
            "objective_ms": _ev.objective_ms(cls),
            "total": total,
            "attainment": round(good / total, 6) if total else 1.0,
            "burn_fast": round(burns[0], 6),
            "burn_slow": round(burns[1], 6),
        })
    return {
        "target": _ev.slo_target(),
        "classes": classes,
        "tenants": {t: round(good / total, 6)
                    for t, (total, good) in sorted(tenants.items())
                    if total},
    }


# ---------------------------------------------------------------------------
# the fleet snapshot (GET /v1/fleet)
# ---------------------------------------------------------------------------

def snapshot() -> dict:
    """The aggregated fleet view: per-replica heartbeat rows, fleet-wide
    sums over the ALIVE replicas, merged SLO over the union of
    envelopes, and every replica's anomaly flags promoted with its id.
    Also refreshes this replica's own heartbeat first (when armed) so
    the serving replica is never its own stale row, and publishes the
    ``fleet_replicas_alive``/``fleet_warm_serves`` gauges."""
    if _ARMED:
        try:
            write_heartbeat_now()
        except Exception:
            logger.debug("snapshot heartbeat refresh failed", exc_info=True)
    replicas = read_replicas()
    alive = [r for r in replicas if r.get("alive")]
    totals = {
        "replicas": len(replicas),
        "alive": len(alive),
        "running": 0, "queueDepth": 0, "slots": 0,
        "queries": 0, "serverQueries": 0,
        "cacheBytes": 0, "spillBytes": 0, "reservedBytes": 0,
        "warmServes": 0, "compiles": 0,
        "programStoreEntries": 0, "programStoreBytes": 0,
    }
    anomalies: List[dict] = []
    for r in alive:
        sched = r.get("scheduler") or {}
        mem = r.get("memory") or {}
        cache = r.get("cache") or {}
        spill = r.get("spill") or {}
        ps = r.get("programStore") or {}
        cnt = r.get("counters") or {}
        totals["running"] += int(sched.get("running", 0) or 0)
        totals["queueDepth"] += int(sched.get("queueDepth", 0) or 0)
        totals["slots"] += int(sched.get("limit", 0) or 0)
        totals["queries"] += int(cnt.get("queries", 0) or 0)
        totals["serverQueries"] += int(cnt.get("server_queries", 0) or 0)
        totals["cacheBytes"] += (int(cache.get("bytes", 0) or 0)
                                 + int(cache.get("hostBytes", 0) or 0))
        totals["spillBytes"] += (int(spill.get("deviceBytes", 0) or 0)
                                 + int(spill.get("hostBytes", 0) or 0)
                                 + int(spill.get("diskBytes", 0) or 0))
        totals["reservedBytes"] += int(mem.get("reservedBytes", 0) or 0)
        totals["warmServes"] += int(ps.get("hits", 0) or 0)
        totals["compiles"] += int(cnt.get("compiles", 0) or 0)
        # replicas share ONE store — entries/bytes are the max observed,
        # not a sum that would double-count the shared index
        totals["programStoreEntries"] = max(
            totals["programStoreEntries"], int(ps.get("entries", 0) or 0))
        totals["programStoreBytes"] = max(
            totals["programStoreBytes"], int(ps.get("bytes", 0) or 0))
        for a in r.get("anomalies") or []:
            if isinstance(a, dict):
                anomalies.append({**a, "replica": r.get("replica", "")})
    _tel.REGISTRY.set_gauge("fleet_replicas_alive", len(alive))
    _tel.REGISTRY.set_gauge("fleet_warm_serves", totals["warmServes"])
    try:
        slo = merged_slo()
    except Exception:
        logger.debug("merged slo failed", exc_info=True)
        slo = {"classes": [], "tenants": {}}
    return {
        "dir": fleet_dir() or "",
        "replica": replica_id(),
        "beatIntervalS": beat_interval_s(),
        "ttlS": ttl_s(),
        "replicas": replicas,
        "totals": totals,
        "slo": slo,
        "anomalies": anomalies,
    }


def replica_rows() -> List[dict]:
    """Flat per-replica rows for ``system.replicas``."""
    rows: List[dict] = []
    for r in read_replicas():
        sched = r.get("scheduler") or {}
        mem = r.get("memory") or {}
        cache = r.get("cache") or {}
        spill = r.get("spill") or {}
        ps = r.get("programStore") or {}
        cnt = r.get("counters") or {}
        rows.append({
            "replica": str(r.get("replica", "")),
            "pid": int(r.get("pid", 0) or 0),
            "host": str(r.get("host", "")),
            "alive": bool(r.get("alive")),
            "started": float(r.get("started", 0.0) or 0.0),
            "beat": float(r.get("beat", 0.0) or 0.0),
            "age_s": float(r.get("age_s", 0.0) or 0.0),
            "running": int(sched.get("running", 0) or 0),
            "queue_depth": int(sched.get("queueDepth", 0) or 0),
            "slots": int(sched.get("limit", 0) or 0),
            "queries": int(cnt.get("queries", 0) or 0),
            "cache_bytes": (int(cache.get("bytes", 0) or 0)
                            + int(cache.get("hostBytes", 0) or 0)),
            "spill_bytes": (int(spill.get("deviceBytes", 0) or 0)
                            + int(spill.get("hostBytes", 0) or 0)
                            + int(spill.get("diskBytes", 0) or 0)),
            "reserved_bytes": int(mem.get("reservedBytes", 0) or 0),
            "program_entries": int(ps.get("entries", 0) or 0),
            "program_hits": int(ps.get("hits", 0) or 0),
            "program_misses": int(ps.get("misses", 0) or 0),
            "program_hit_rate": float(ps.get("hitRate", 0.0) or 0.0),
            "compiles": int(cnt.get("compiles", 0) or 0),
        })
    return rows
