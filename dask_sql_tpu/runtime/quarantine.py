"""Cross-process failure quarantine + compile watchdog.

BENCH_r05 names the failure domain this module contains: compile is both
the dominant cost (per-query compiles up to 615 s over the tunneled TPU)
and the dominant failure site (10 compile_errors in one bench run), and a
compile that crashes or wedges the XLA helper dies WITH the process — the
in-memory exile verdict (physical/compiled.py ``_cache[key] = _UNSUPPORTED``)
is gone on restart, so every new process re-pays the doomed compile.
Flare (PAPERS.md) keeps the same discipline for Spark native compilation:
a hung or crashing program build must be remembered, not re-attempted.

Two cooperating parts:

**Quarantine store.**  A small JSON file (``DSQL_QUARANTINE_FILE``;
unset = disabled) of crash/hang verdicts keyed by a digest of the
canonical program key (plan fingerprint + input-layout fingerprint +
backend strategy) folded with the device fingerprint — the same
content-addressing discipline as the learned-caps store
(``DSQL_CAPS_FILE``), so a verdict can only ever match the same program
over the same data layout on the same device class.  A FATAL compile
verdict or a watchdog hang mark persists with an expiry
(``DSQL_QUARANTINE_TTL_S``); while an entry is live, every process
sharing the file serves that plan via the eager fallback *without a
compile attempt*.  After expiry the store goes **half-open**: exactly one
caller is handed a ``"probe"`` verdict (the entry's expiry is pushed out
by ``DSQL_QUARANTINE_PROBE_S`` so concurrent callers — and other
processes — keep skipping while the probe runs); a successful compile
clears the entry, a failed probe re-arms it for a full TTL.  Corrupt or
unreadable store files read as empty — quarantine is an optimization,
never a crash source.

**Compile watchdog.**  ``DSQL_COMPILE_WATCHDOG_S`` arms a monitor thread
over every compile+first-call section.  The cooperative deadline
checkpoints (``resilience.check``) cannot fire while the worker is wedged
*inside* XLA; the watchdog can — when a watched section exceeds the wall
budget it increments ``watchdog_trips`` and marks the program's
fingerprint suspect (verdict ``"hang"``) in the quarantine store, so even
if the process never returns (or is killed by the operator), the next
process refuses the same compile.  A section that eventually finishes
cleanly lifts its own suspect mark — the watchdog records *wedged right
now*, not *slow once*.

Telemetry: ``quarantine_skips`` / ``quarantine_probes`` /
``quarantine_marks`` / ``watchdog_trips`` (all in the stable-name
contract, runtime/telemetry.py).
"""
from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from . import kvstore as _kv
from . import telemetry as _tel

logger = logging.getLogger(__name__)

DEFAULT_TTL_S = 3600.0
DEFAULT_PROBE_S = 60.0

VERDICTS = ("fatal", "hang")


def _env_float(name: str, default: float) -> float:
    try:
        raw = os.environ.get(name, "")
        return float(raw) if raw else default
    except ValueError:
        return default


_device_fp_cache: Optional[str] = None


def device_fingerprint() -> str:
    """Stable identity of the device class this process compiles for; a
    verdict earned on one backend must never gate a different one (the
    same plan that wedges the tunneled TPU compiler is fine on XLA:CPU)."""
    global _device_fp_cache
    if _device_fp_cache is None:
        try:
            import jax
            d = jax.local_devices()[0]
            _device_fp_cache = (f"{d.platform}:{getattr(d, 'device_kind', '?')}"
                                f":{jax.local_device_count()}")
        except Exception:  # pragma: no cover - jax not initialized
            _device_fp_cache = "unknown"
    return _device_fp_cache


def program_key(base_key) -> str:
    """Content digest of a compiled program's identity: the executor's
    base key (plan fingerprint, input-layout fingerprint, strategy) folded
    with the device fingerprint."""
    h = hashlib.blake2b(repr(base_key).encode(), digest_size=16)
    h.update(b"|" + device_fingerprint().encode())
    return h.hexdigest()


class QuarantineStore:
    """JSON-file store of crash/hang verdicts with expiry + half-open
    probes.  Disk plumbing rides runtime/kvstore.py (shared with the
    learned-caps file and the program store's index): reads are
    mtime-cached and corrupt-tolerant; writes are read-merge-replace with
    an atomic rename, so concurrent writers can lose a race — costing one
    re-mark — but never corrupt."""

    def __init__(self, path: Optional[str] = None):
        self._path_override = path
        self._file = _kv.MtimeCachedJsonFile(self.path)

    # -- config (env-read per call so tests/operators flip without restart)
    def path(self) -> Optional[str]:
        return self._path_override or os.environ.get("DSQL_QUARANTINE_FILE")

    def enabled(self) -> bool:
        return bool(self.path())

    def ttl_s(self) -> float:
        return max(_env_float("DSQL_QUARANTINE_TTL_S", DEFAULT_TTL_S), 0.0)

    def probe_ttl_s(self) -> float:
        return max(_env_float("DSQL_QUARANTINE_PROBE_S", DEFAULT_PROBE_S),
                   0.001)

    # -- disk (runtime/kvstore.py: mtime-cached tolerant reads, atomic
    # tmp+rename writes — a broken quarantine file must degrade to 'no
    # quarantine', never fail a query) ------------------------------------
    def _read(self) -> Dict[str, dict]:
        return self._file.read()

    def _write(self, data: Dict[str, dict]) -> None:
        self._file.write(data)

    # -- verdicts -----------------------------------------------------------
    def check(self, key: str) -> Optional[str]:
        """``"quarantined"`` (skip the compile), ``"probe"`` (half-open:
        THIS caller re-attempts while everyone else keeps skipping), or
        None (no verdict on record)."""
        if not self.enabled():
            return None
        data = self._read()
        entry = data.get(key)
        if entry is None:
            return None
        now = time.time()
        if now < float(entry.get("expires_at", 0)):
            return "quarantined"
        # expired: half-open.  Push the expiry out by the probe window and
        # persist BEFORE returning, so concurrent checkers (and other
        # processes) see a live entry and skip while this probe runs.
        entry["expires_at"] = now + self.probe_ttl_s()
        entry["probing"] = True
        data[key] = entry
        self._write(data)
        return "probe"

    def mark(self, key: str, verdict: str, reason: str = "") -> None:
        """Record (or re-arm after a failed probe) a crash/hang verdict."""
        if not self.enabled():
            return
        data = self._read()
        prev = data.get(key) or {}
        now = time.time()
        data[key] = {
            "verdict": verdict,
            "reason": str(reason)[:200],
            "at": now,
            "expires_at": now + self.ttl_s(),
            "strikes": int(prev.get("strikes", 0)) + 1,
        }
        self._write(data)
        _tel.inc("quarantine_marks")
        logger.warning("quarantined program %s (%s): %s",
                       key[:12], verdict, str(reason)[:120])

    def clear(self, key: str) -> None:
        """Lift a verdict (successful probe, or a watched section that
        finished after its watchdog trip)."""
        if not self.enabled():
            return
        data = self._read()
        if key not in data:
            return
        del data[key]
        self._write(data)
        logger.info("quarantine lifted for program %s", key[:12])

    def entries(self) -> Dict[str, dict]:
        return self._read()


_store = QuarantineStore()


def get_store() -> QuarantineStore:
    """The process-global quarantine store (env-configured, like the
    result cache and the workload manager)."""
    return _store


# ---------------------------------------------------------------------------
# compile watchdog
# ---------------------------------------------------------------------------

class CompileWatchdog:
    """Monitor thread over compile/first-call sections.

    A wedged XLA compile holds the GIL-released worker inside native code
    where no cooperative ``resilience.check`` can run; this thread is the
    host-side supervisor that still observes wall time.  It cannot unwedge
    the worker (Python cannot interrupt native code) — what it CAN do is
    persist the hang verdict so the cost is paid at most once per process
    lineage, which is exactly the cross-process guarantee the quarantine
    store exists for."""

    _POLL_S = 0.1

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, list] = {}  # token -> [deadline, key, label, fired]
        self._next_token = 0
        self._thread: Optional[threading.Thread] = None

    def budget_s(self) -> float:
        return max(_env_float("DSQL_COMPILE_WATCHDOG_S", 0.0), 0.0)

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="dsql-compile-watchdog", daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while True:
            time.sleep(self._POLL_S)
            now = time.monotonic()
            fired: list = []
            with self._lock:
                for entry in self._entries.values():
                    if not entry[3] and now >= entry[0]:
                        entry[3] = True
                        fired.append(entry)
            for deadline, key, label, _ in fired:
                _tel.inc("watchdog_trips")
                budget = self.budget_s()
                logger.error(
                    "compile watchdog: %s exceeded the %.1f s wall budget "
                    "(still wedged); marking fingerprint suspect", label
                    or key[:12], budget)
                get_store().mark(
                    key, "hang",
                    reason=f"exceeded DSQL_COMPILE_WATCHDOG_S={budget:g}"
                           f" at {label or 'compile'}")

    @contextmanager
    def watch(self, key: str, label: str = ""):
        """Supervise the enclosed compile/first-call section.  No-op when
        ``DSQL_COMPILE_WATCHDOG_S`` is unset/0.  A section that trips the
        watchdog but then finishes CLEANLY lifts its own suspect mark —
        the persisted verdict means 'wedged', not 'slow'."""
        budget = self.budget_s()
        if budget <= 0:
            yield
            return
        entry = [time.monotonic() + budget, key, label, False]
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._entries[token] = entry
            self._ensure_thread()
        ok = False
        try:
            yield
            ok = True
        finally:
            with self._lock:
                self._entries.pop(token, None)
            if ok and entry[3]:
                logger.warning(
                    "compile watchdog: %s finished after tripping; lifting "
                    "the suspect mark", label or key[:12])
                get_store().clear(key)


_watchdog = CompileWatchdog()


def get_watchdog() -> CompileWatchdog:
    return _watchdog
