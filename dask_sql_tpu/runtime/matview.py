"""Incremental materialized views: O(delta) maintenance on the partial
aggregate algebra (ISSUE 14; ROADMAP item 3).

``CREATE MATERIALIZED VIEW v AS <query>`` materializes the query once and
keeps the result registered as an ordinary catalog entry, so scans of ``v``
bind to a plain table.  What makes it a *view* is freshness: every
resolution checks the base tables' catalog epochs, and a view whose bases
advanced refreshes BEFORE it is served — a stale result is never visible.

The refresh is where the partial-aggregate decomposition pays off a third
time (streaming batches and SPMD shards are the other two).  Appends via
``INSERT INTO`` / ``Context.append_rows`` bump the base epoch with a
**delta record** (the appended batch + rowcount) instead of the bare
tombstone every other mutation leaves.  A maintainable view then refreshes
from (cached partial state ⊕ partial-aggregate over the delta) in
O(delta):

    maintainable            shape
    ------------------      ------------------------------------------
    incremental (agg)       [Sort] [Project|Filter]* Aggregate
                            (Project|Filter)* Scan — every call in
                            SUM / $SUM0 / COUNT / MIN / MAX / AVG,
                            no DISTINCT, no UDAF, single base scan
    incremental (append)    (Project|Filter)+ Scan — no ORDER BY/LIMIT
    full recompute          everything else (joins, DISTINCT, windows,
                            set ops, nested aggregates, subqueries,
                            chunked bases) — the reason is surfaced in
                            ``system.matviews`` and the log

Overwrites (CREATE OR REPLACE, DROP, ALTER) still hard-tombstone: the
delta log for the table is cleared and the tombstone epoch forces the next
serve through a full recompute, so a maintained view can never serve state
derived from a replaced base.

The maintained partial state lives in the result cache under a
``("__mv__", <view>)`` table key: it is a tenant of the shared memory
ledger (spills to host / evicts under pressure like any entry), base-table
invalidations do not touch it, and an evicted state simply downgrades the
next refresh to a full recompute — wrong-never, slower-ok.

``DSQL_MV=0`` kills the subsystem: MV statements raise a typed UserError,
appends record plain tombstones, and resolution never consults the
registry — bit-for-bit the pre-subsystem behavior.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..datacontainer import TableEntry
from ..plan.nodes import (
    Field, LogicalAggregate, LogicalFilter, LogicalProject, LogicalSort,
    LogicalTableScan, RelNode, RexCall, RexInputRef, RexScalarSubquery,
)
from ..table import Table
from ..types import BIGINT, DOUBLE
from .kvstore import digest_key
from . import faults as _faults, resilience as _res, telemetry as _tel

logger = logging.getLogger(__name__)

MV_SCHEMA = "__matview__"

# ledger tenancy: maintained state keys under this pseudo-table so base
# bumps never invalidate it and DROP MATERIALIZED VIEW can drop it exactly
STATE_SCHEMA = "__mv__"

# delta-log bound: a table accumulating more un-applied appends than this
# converts to a tombstone (next refresh recomputes) instead of pinning
# unbounded delta batches on device
MAX_DELTAS = int(os.environ.get("DSQL_MV_MAX_DELTAS", "64"))


def mv_enabled() -> bool:
    return os.environ.get("DSQL_MV", "1").strip() not in ("0", "false")


class MatViewError(_res.UserError):
    """Materialized-view statement the subsystem rejects (disabled via
    DSQL_MV=0, volatile defining query, unknown view...).  A typed
    UserError: the message always names the remedy."""


def _require_enabled() -> None:
    if not mv_enabled():
        raise MatViewError(
            "materialized views are disabled (DSQL_MV=0); unset DSQL_MV "
            "to enable the subsystem")


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------

@dataclass
class DeltaRecord:
    epoch: int          # the epoch this append advanced the table TO
    rows: int
    table: Table        # the appended batch, base-table column order
    ts: float = 0.0     # wall-clock record time (staleness accounting)


@dataclass
class _Shape:
    """Maintenance shape of a maintainable plan (see module docstring)."""
    kind: str                          # "agg" | "append" | "join" | "cdistinct"
    scan: LogicalTableScan = None
    below: RelNode = None              # agg: pipeline under the aggregate
    agg: Optional[LogicalAggregate] = None
    above: List[RelNode] = field(default_factory=list)  # root-first
    partial_aggs: list = field(default_factory=list)
    partial_schema: list = field(default_factory=list)
    merge_aggs: list = field(default_factory=list)
    merge_schema: list = field(default_factory=list)
    post_exprs: list = field(default_factory=list)
    needs_project: bool = False
    scans: list = field(default_factory=list)  # join: left-to-right leaves
    cd_arg: int = -1                   # cdistinct: DISTINCT arg in `below`


@dataclass
class MatView:
    name: str                          # lowercased
    schema_name: str
    sql: str                           # the CREATE statement text
    plan: RelNode                      # optimized defining plan
    fingerprint: str                   # canonical-plan digest
    base_tables: Tuple[Tuple[str, str], ...]
    base_epochs: Dict[Tuple[str, str], int] = field(default_factory=dict)
    maintainable: bool = False
    reason: str = ""                   # why not maintainable ("" when it is)
    shape: Optional[_Shape] = None
    serves: int = 0
    refresh_incremental: int = 0
    refresh_full: int = 0
    last_refresh_reason: str = "initial materialization"


# ---------------------------------------------------------------------------
# maintainability analysis
# ---------------------------------------------------------------------------

def _rex_has_subquery(rex) -> bool:
    if isinstance(rex, RexScalarSubquery):
        return True
    return any(_rex_has_subquery(o)
               for o in getattr(rex, "operands", []) or [])


def _analyze(plan: RelNode, context) -> Tuple[Optional[_Shape], str]:
    """(shape, reason): shape None means every refresh recomputes in full,
    and ``reason`` says why — surfaced through system.matviews."""
    from ..physical.streaming import StreamingUnsupported, \
        _partial_and_merge_aggs

    from ..plan.nodes import LogicalJoin

    chain: List[RelNode] = []
    cur = plan
    while not isinstance(cur, LogicalTableScan):
        if isinstance(cur, (LogicalProject, LogicalFilter, LogicalSort,
                            LogicalAggregate)):
            chain.append(cur)
            cur = cur.inputs[0]
            continue
        if isinstance(cur, LogicalJoin):
            from . import delta as _delta
            return _delta.analyze_join(plan, chain, cur, context)
        return None, (f"{cur.node_name()} requires full recompute (only "
                      "selection/projection pipelines, INNER join trees, "
                      "and single-level mergeable group-bys maintain "
                      "incrementally)")
    scan = cur
    schema = context.schema.get(scan.schema_name)
    entry = schema.tables.get(scan.table_name) if schema is not None else None
    if entry is None:
        return None, f"base table {scan.table_name} not resolvable"
    if entry.chunked is not None:
        return None, ("chunked base table streams from host; appends are "
                      "not delta-tracked")
    for node in chain:
        exprs = (node.exprs if isinstance(node, LogicalProject)
                 else [node.condition] if isinstance(node, LogicalFilter)
                 else [])
        if any(_rex_has_subquery(e) for e in exprs if e is not None):
            return None, "scalar subquery requires full recompute"

    aggs = [n for n in chain if isinstance(n, LogicalAggregate)]
    if len(aggs) > 1:
        return None, "nested aggregates do not merge incrementally"
    if not aggs:
        if any(isinstance(n, LogicalSort) for n in chain):
            return None, ("ORDER BY/LIMIT over a selection pipeline "
                          "requires full recompute (appended rows "
                          "interleave with the existing order)")
        return _Shape(kind="append", scan=scan, below=plan), ""

    agg = aggs[0]
    ai = chain.index(agg)
    above, below_chain = chain[:ai], chain[ai + 1:]
    if any(isinstance(n, (LogicalSort, LogicalAggregate))
           for n in below_chain):
        return None, "ORDER BY/LIMIT below the aggregate requires full " \
                     "recompute"
    if any(c.distinct for c in agg.aggs):
        # the streaming algebra refuses DISTINCT outright; the refcounted
        # state in runtime/delta.py maintains the COUNT(DISTINCT col) form
        from . import delta as _delta
        return _delta.analyze_distinct_agg(plan, scan, agg, above,
                                           below_chain)
    try:
        (partial_aggs, partial_fields, merge_aggs, post_exprs,
         needs_project) = _partial_and_merge_aggs(agg)
    except StreamingUnsupported as e:
        return None, str(e)
    gk = len(agg.group_keys)
    group_fields = list(agg.schema[:gk])
    return _Shape(
        kind="agg", scan=scan, below=agg.inputs[0], agg=agg, above=above,
        partial_aggs=partial_aggs,
        partial_schema=group_fields + partial_fields,
        merge_aggs=merge_aggs,
        merge_schema=group_fields + [Field(a.name, a.stype)
                                     for a in merge_aggs],
        post_exprs=post_exprs, needs_project=needs_project), ""


# ---------------------------------------------------------------------------
# plan execution plumbing (no admission, no result-cache lookup: refreshes
# run nested inside the outer query's binding)
# ---------------------------------------------------------------------------

_tmp_counter = [0]


def _register_temp(context, table: Table, fields) -> LogicalTableScan:
    """Register a temp under __matview__ (own schema: refreshes must not
    race the streaming executor's __stream__ lifecycle) and return its
    scan re-typed to ``fields``' stypes."""
    if MV_SCHEMA not in context.schema:
        context.create_schema(MV_SCHEMA)
    _tmp_counter[0] += 1
    name = f"t{_tmp_counter[0]}"
    names = [f"c{i}" for i in range(table.num_columns)]
    table = table.with_names(names)
    context.schema[MV_SCHEMA].tables[name] = TableEntry(table=table)
    return LogicalTableScan(
        schema_name=MV_SCHEMA, table_name=name,
        schema=[Field(n, f.stype) for n, f in zip(names, fields)])


def _cleanup_temps(context) -> None:
    context.schema.pop(MV_SCHEMA, None)


def _execute_plan(context, plan: RelNode, eager: bool = False) -> Table:
    """Compiled-else-eager execution; chunked bases stream as usual.

    ``eager=True`` skips the compiled tier outright: refresh temps carry
    fresh Table uids every round, so the compiled-query cache can never
    hit, and an XLA compile per delta would dwarf the delta itself.  The
    interpreter is the right tier for delta/group-count-sized inputs."""
    if getattr(context, "_has_chunked", False):
        from ..physical.streaming import (execute_streaming,
                                          plan_references_chunked)
        if plan_references_chunked(plan, context):
            return execute_streaming(plan, context)
    if eager:
        from ..physical.rel.executor import RelExecutor
        return RelExecutor(context).execute(plan)
    from ..physical.streaming import _run_resident
    return _run_resident(plan, context)


def _replace(plan: RelNode, old: RelNode, new: RelNode) -> RelNode:
    if plan is old:
        return new
    if not plan.inputs:
        return plan
    return plan.with_inputs([_replace(i, old, new) for i in plan.inputs])


def _state_key(mv: MatView):
    from . import result_cache as _rc
    return _rc.CacheKey(
        f"mv-state:{mv.fingerprint}:{mv.schema_name}.{mv.name}",
        ((STATE_SCHEMA, f"{mv.schema_name}.{mv.name}"),))


class _StateMissing(Exception):
    """Maintained partial state not in the cache (evicted / never stored /
    cache disabled) — the refresh downgrades to a full recompute."""


# ---------------------------------------------------------------------------
# the registry (one per Context, created on first CREATE MATERIALIZED VIEW)
# ---------------------------------------------------------------------------

class MatViewRegistry:
    def __init__(self):
        self.views: Dict[Tuple[str, str], MatView] = {}
        self.deltas: Dict[Tuple[str, str], List[DeltaRecord]] = {}
        self.tombstones: Dict[Tuple[str, str], int] = {}
        self.lock = threading.RLock()

    # -- epoch seam (called from Context.bump_table_epoch) -----------------
    def record_delta(self, key: Tuple[str, str], epoch: int,
                     table: Table) -> None:
        with self.lock:
            if not mv_enabled():
                # kill switch: appends degrade to the pre-subsystem
                # tombstone so a later re-enable never serves from a gap
                self._tombstone_locked(key, epoch)
                return
            if not any(key in v.base_epochs for v in self.views.values()):
                return  # no dependent views: nothing to maintain
            log = self.deltas.setdefault(key, [])
            if len(log) >= MAX_DELTAS:
                # before giving up on incremental maintenance, coalesce
                # the unconsumed tail into one record: a steady trickle
                # of tiny appends then stays O(delta) instead of
                # tombstoning into a full recompute
                self._compact_locked(key, log)
            if len(log) >= MAX_DELTAS:
                logger.info("matview: delta log for %s.%s overflowed "
                            "(%d records); tombstoning", key[0], key[1],
                            len(log))
                self._tombstone_locked(key, epoch)
                return
            log.append(DeltaRecord(epoch=epoch, rows=table.num_rows,
                                   table=table, ts=time.time()))
            _tel.inc("mv_deltas_recorded")
            self._update_gauges_locked()

    def record_overwrite(self, key: Tuple[str, str], epoch: int) -> None:
        with self.lock:
            self._tombstone_locked(key, epoch)

    def _tombstone_locked(self, key, epoch: int) -> None:
        self.deltas.pop(key, None)
        self.tombstones[key] = epoch
        self._update_gauges_locked()

    def _compact_locked(self, key, log: List[DeltaRecord]) -> None:
        """Merge adjacent unconsumed records into one batch.  Only records
        strictly ABOVE every dependent view's watermark may merge — a
        record a view has partially consumed must keep its epoch so
        _staleness's hole detection stays exact."""
        from ..ops.join import concat_tables

        hi = max((v.base_epochs.get(key, 0) for v in self.views.values()
                  if key in v.base_epochs), default=0)
        tail = [r for r in log if r.epoch > hi]
        if len(tail) < 2:
            return
        merged = DeltaRecord(
            epoch=max(r.epoch for r in tail),
            rows=sum(r.rows for r in tail),
            table=concat_tables([r.table for r in tail]),
            ts=min(r.ts for r in tail))
        log[:] = [r for r in log if r.epoch <= hi] + [merged]
        _tel.inc("mv_delta_compactions")
        logger.info("matview: compacted %d delta record(s) for %s.%s into "
                    "one %d-row batch", len(tail), key[0], key[1],
                    merged.rows)

    def _update_gauges_locked(self) -> None:
        pending = sum(r.rows for recs in self.deltas.values() for r in recs)
        oldest = min((r.ts for recs in self.deltas.values()
                      for r in recs if r.ts), default=0.0)
        _tel.REGISTRY.set_gauge("mv_pending_rows", pending)
        _tel.REGISTRY.set_gauge(
            "mv_staleness_s", max(time.time() - oldest, 0.0)
            if oldest else 0.0)

    def discard_view(self, schema_name: str, name: str) -> None:
        """Registry-side cleanup when the catalog entry goes away through
        a non-MV path (DROP TABLE, DROP/ALTER SCHEMA, rename)."""
        with self.lock:
            mv = self.views.pop((schema_name, name.lower()), None)
            if mv is not None:
                from . import result_cache as _rc
                _rc.get_cache().invalidate_table(
                    STATE_SCHEMA, f"{mv.schema_name}.{mv.name}")
                self._prune_locked()

    def discard_schema(self, schema_name: str) -> None:
        with self.lock:
            for s, n in [k for k in self.views if k[0] == schema_name]:
                self.discard_view(s, n)

    # -- serving -----------------------------------------------------------
    def maybe_serve(self, context, schema_name: str, name: str,
                    entry: TableEntry) -> TableEntry:
        """resolve_table hook: refresh-if-stale, then serve the (possibly
        replaced) catalog entry.  Non-MV entries pass through untouched."""
        mv = self.views.get((schema_name, name))
        if mv is None or not mv_enabled():
            return entry
        with self.lock:
            self.ensure_fresh(context, mv)
            _tel.inc("mv_serves")
            mv.serves += 1
            return context.schema[schema_name].tables[name]

    # -- freshness ---------------------------------------------------------
    def _staleness(self, context, mv: MatView):
        """("fresh", None) | ("incremental", {base: [DeltaRecord...]})
        | ("full", reason)."""
        pending: Dict[Tuple[str, str], List[DeltaRecord]] = {}
        for key in mv.base_tables:
            # a base that is itself a materialized view refreshes first, so
            # its epoch reflects ITS bases before this view reads it
            inner = self.views.get(key)
            if inner is not None and inner is not mv:
                self.ensure_fresh(context, inner)
            cur = context.table_epoch(*key)
            last = mv.base_epochs.get(key, 0)
            if cur == last:
                continue
            if not mv.maintainable:
                return "full", mv.reason
            if self.tombstones.get(key, 0) > last:
                return "full", f"base table {key[0]}.{key[1]} overwritten"
            recs = [r for r in self.deltas.get(key, ()) if r.epoch > last]
            # every bump since `last` is either a logged delta or a
            # tombstone (checked above); the newest record must account
            # for the current epoch or the log has a hole
            if not recs or max(r.epoch for r in recs) != cur:
                return "full", (f"delta log for {key[0]}.{key[1]} does not "
                                "cover the epoch gap")
            pending[key] = recs
        if not pending:
            return "fresh", None
        return "incremental", pending

    def ensure_fresh(self, context, mv: MatView) -> None:
        """Refresh ``mv`` if its bases advanced.  Raises on failure (the
        caller's query fails rather than reading a stale view); the
        registry state only moves AFTER a successful materialization."""
        kind, info = self._staleness(context, mv)
        if kind == "fresh":
            return
        if kind == "incremental":
            try:
                # the chaos site: an injected fault abandons the
                # incremental path and recomputes in full — wrong-never
                _faults.maybe_fail("mv_refresh")
                self._refresh_incremental(context, mv, info)
                _tel.inc("mv_refresh_incremental")
                if os.environ.get("DSQL_EVENTS", "0").strip() \
                        not in ("", "0"):
                    try:
                        from . import events as _ev
                        _ev.publish("mv.refresh", view=mv.name,
                                    mode="incremental")
                    except Exception:  # pragma: no cover
                        pass
                mv.refresh_incremental += 1
                mv.last_refresh_reason = "incremental"
                self._prune_locked()
                return
            except _StateMissing as e:
                info = str(e)
            except _res.TransientError as e:
                logger.warning("matview %s.%s: incremental refresh failed "
                               "(%s); recomputing in full", mv.schema_name,
                               mv.name, e)
                info = f"incremental refresh failed: {e}"
        self._refresh_full(context, mv, reason=str(info))
        self._prune_locked()

    # -- refresh paths -----------------------------------------------------
    def _swap(self, context, mv: MatView, result: Table) -> None:
        """Install the refreshed result transactionally: new entry, MV
        epoch bump (stale cached queries OVER the view drop), base-epoch
        watermark advance."""
        # temp registration sanitized intermediate names to c0..cN; the
        # served view keeps the defining query's output names
        result = result.with_names([f.name for f in mv.plan.schema])
        context.schema[mv.schema_name].tables[mv.name] = \
            TableEntry(table=result)
        context.bump_table_epoch(mv.schema_name, mv.name)
        for key in mv.base_tables:
            mv.base_epochs[key] = context.table_epoch(*key)

    def _refresh_incremental(self, context, mv: MatView,
                             pending: Dict) -> None:
        from ..ops.join import concat_tables
        from . import result_cache as _rc

        shape = mv.shape
        if shape.kind == "join":
            from . import delta as _delta
            try:
                _delta.refresh_join(self, context, mv, pending)
            finally:
                _cleanup_temps(context)
            return
        (key,) = pending.keys()  # single-scan shapes have one base scan
        delta = concat_tables([r.table for r in pending[key]])
        # the scan may be column-pruned/reordered relative to the base
        # table layout the delta was recorded in — align by name
        lut = {n.lower(): col
               for n, col in zip(delta.names, delta.columns)}
        try:
            delta = Table([f.name for f in shape.scan.schema],
                          [lut[f.name.lower()] for f in shape.scan.schema])
        except KeyError as exc:
            raise _StateMissing(
                f"delta does not cover scanned column {exc}") from exc
        try:
            delta_scan = _register_temp(context, delta, shape.scan.schema)
            if shape.kind == "cdistinct":
                from . import delta as _delta
                _delta.refresh_cdistinct(self, context, mv, delta_scan)
                return
            if shape.kind == "append":
                new_rows = _execute_plan(
                    context, _replace(mv.plan, shape.scan, delta_scan),
                    eager=True)
                current = context.schema[mv.schema_name].tables[mv.name]
                result = concat_tables([current.table, new_rows])
                self._swap(context, mv, result)
                return
            # agg: partial over the delta pipeline, merge with cached state
            cache = _rc.get_cache()
            state = cache.get(_state_key(mv)) if cache.enabled() else None
            if state is None:
                raise _StateMissing("maintained state not in result cache")
            state_table, _tier = state
            agg = shape.agg
            partial = _execute_plan(context, LogicalAggregate(
                input=_replace(shape.below, shape.scan, delta_scan),
                group_keys=list(agg.group_keys), aggs=shape.partial_aggs,
                schema=list(shape.partial_schema)), eager=True)
            merged_in = _register_temp(
                context, concat_tables([state_table, partial]),
                shape.partial_schema)
            gk = len(agg.group_keys)
            new_state = _execute_plan(context, LogicalAggregate(
                input=merged_in, group_keys=list(range(gk)),
                aggs=list(shape.merge_aggs),
                schema=list(shape.merge_schema)), eager=True)
            result = self._finalize_agg(context, mv, new_state)
            self._swap(context, mv, result)
            cache.put(_state_key(mv), new_state)
        finally:
            _cleanup_temps(context)

    def _finalize_agg(self, context, mv: MatView, state: Table) -> Table:
        """State (merge layout) -> view output: AVG division + the nodes
        above the aggregate (HAVING / projections / ORDER BY), mirroring
        the streaming merge tail."""
        shape = mv.shape
        agg = shape.agg
        gk = len(agg.group_keys)
        node: RelNode = _register_temp(context, state, shape.merge_schema)
        if shape.needs_project:
            exprs = [RexInputRef(i, f.stype)
                     for i, f in enumerate(agg.schema[:gk])]
            for kind, i, j, f in shape.post_exprs:
                if kind == "ref":
                    exprs.append(RexInputRef(i, f.stype))
                else:
                    num = RexInputRef(i, shape.merge_schema[i].stype)
                    den = RexCall("CAST", [RexInputRef(j, BIGINT)], DOUBLE,
                                  info=DOUBLE)
                    exprs.append(RexCall("/", [num, den], f.stype))
            node = LogicalProject(input=node, exprs=exprs,
                                  schema=list(agg.schema))
        for outer in reversed(shape.above):
            node = outer.with_inputs([node])
        # group-count-sized input: the interpreter beats a fresh compile
        return _execute_plan(context, node, eager=True)

    def _refresh_full(self, context, mv: MatView, reason: str) -> None:
        from . import result_cache as _rc

        try:
            if mv.maintainable and mv.shape.kind == "agg":
                # one pass builds the partial state, a small merge derives
                # the output from it — so the NEXT refresh is O(delta)
                shape = mv.shape
                agg = shape.agg
                state = _execute_plan(context, LogicalAggregate(
                    input=shape.below, group_keys=list(agg.group_keys),
                    aggs=shape.partial_aggs,
                    schema=list(shape.partial_schema)))
                # partial layout == merge layout (the merge of one partial
                # is itself), so it finalizes directly
                result = self._finalize_agg(context, mv, state)
                self._swap(context, mv, result)
                cache = _rc.get_cache()
                if cache.enabled():
                    cache.put(_state_key(mv), state)
            elif mv.maintainable and mv.shape.kind == "cdistinct":
                # same seeding discipline, refcounted state
                from . import delta as _delta
                _delta.refresh_full_cdistinct(self, context, mv)
            else:
                result = _execute_plan(context, mv.plan)
                self._swap(context, mv, result)
            # consumed everything up to the new watermark
            for key in mv.base_tables:
                self.tombstones.pop(key, None)
            _tel.inc("mv_refresh_full")
            if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
                try:
                    from . import events as _ev
                    _ev.publish("mv.refresh", view=mv.name, mode="full",
                                reason=reason or None)
                except Exception:  # pragma: no cover
                    pass
            mv.refresh_full += 1
            mv.last_refresh_reason = f"full ({reason})" if reason else "full"
            if reason:
                logger.info("matview %s.%s refreshed in full: %s",
                            mv.schema_name, mv.name, reason)
        finally:
            _cleanup_temps(context)

    def _prune_locked(self) -> None:
        """Drop delta records no live view still needs."""
        for key in list(self.deltas):
            needed = [v.base_epochs[key] for v in self.views.values()
                      if key in v.base_epochs]
            if not needed:
                del self.deltas[key]
                continue
            lo = min(needed)
            self.deltas[key] = [r for r in self.deltas[key] if r.epoch > lo]
            if not self.deltas[key]:
                del self.deltas[key]
        self._update_gauges_locked()


def get_registry(context, create: bool = False) -> Optional[MatViewRegistry]:
    reg = getattr(context, "_matview_registry", None)
    if reg is None and create:
        reg = MatViewRegistry()
        context._matview_registry = reg
    return reg


# ---------------------------------------------------------------------------
# statement entry points (physical/rel/custom.py handlers call these)
# ---------------------------------------------------------------------------

def create_matview(context, name_parts: List[str], query, sql: str,
                   if_not_exists: bool, or_replace: bool) -> None:
    from . import result_cache as _rc

    _require_enabled()
    schema_name, name = context.fqn(name_parts)
    if name in context.schema[schema_name].tables:
        if if_not_exists:
            return
        if not or_replace:
            raise MatViewError(
                f"A table with the name {name} is already present; use "
                "CREATE OR REPLACE MATERIALIZED VIEW to replace it.")
    plan = context._get_plan(query, sql)
    text, volatile, scans = _rc.canonical_plan(plan, context)
    if volatile:
        raise MatViewError(
            "CREATE MATERIALIZED VIEW rejects volatile queries "
            "(RAND/CURRENT_DATE/CURRENT_TIME/NOW, UDFs, system-table "
            "scans, unseeded TABLESAMPLE): the materialized result would "
            "freeze a value that must change per query. Materialize a "
            "deterministic query instead.")
    # same keying as the flight recorder's plan_fingerprint, so
    # system.view_candidates can mark materialized candidates
    fingerprint = digest_key(text)
    shape, reason = _analyze(plan, context)
    mv = MatView(
        name=name, schema_name=schema_name, sql=sql, plan=plan,
        fingerprint=fingerprint,
        base_tables=tuple(dict.fromkeys((s, t) for s, t in scans)),
        maintainable=shape is not None, reason=reason, shape=shape)
    reg = get_registry(context, create=True)
    with reg.lock:
        reg.discard_view(schema_name, name)  # OR REPLACE over an old view
        reg._refresh_full(context, mv, reason="")
        mv.last_refresh_reason = "initial materialization"
        reg.views[(schema_name, name)] = mv
    logger.info("matview %s.%s created: %s", schema_name, name,
                "maintainable (%s)" % mv.shape.kind if mv.maintainable
                else "full-recompute (%s)" % reason)


def drop_matview(context, name_parts: List[str], if_exists: bool) -> None:
    _require_enabled()
    schema_name, name = context.fqn(name_parts)
    reg = get_registry(context)
    mv = reg.views.get((schema_name, name)) if reg is not None else None
    if mv is None:
        if if_exists:
            return
        raise MatViewError(
            f"A materialized view with the name {name} is not present.")
    with reg.lock:
        reg.discard_view(schema_name, name)
        context.schema[schema_name].tables.pop(name, None)
        context.bump_table_epoch(schema_name, name)


def refresh_matview(context, name_parts: List[str]) -> None:
    _require_enabled()
    schema_name, name = context.fqn(name_parts)
    reg = get_registry(context)
    mv = reg.views.get((schema_name, name)) if reg is not None else None
    if mv is None:
        raise MatViewError(
            f"A materialized view with the name {name} is not present.")
    with reg.lock:
        reg.ensure_fresh(context, mv)


def matview_rows(context) -> List[dict]:
    """system.matviews source: one row per registered view."""
    reg = get_registry(context)
    if reg is None:
        return []
    out = []
    with reg.lock:
        for (schema_name, name), mv in sorted(reg.views.items()):
            entry = context.schema.get(schema_name)
            entry = entry.tables.get(name) if entry is not None else None
            pending = [r for k in mv.base_tables
                       for r in reg.deltas.get(k, ())
                       if r.epoch > mv.base_epochs.get(k, 0)]
            ts = [r.ts for r in pending if r.ts]
            out.append({
                "schema": schema_name,
                "name": name,
                "rows": (entry.table.num_rows
                         if entry is not None and entry.table is not None
                         else 0),
                "maintainable": ("incremental:" + mv.shape.kind
                                 if mv.maintainable else "full"),
                "reason": mv.reason,
                "base_tables": ",".join(f"{s}.{t}"
                                        for s, t in mv.base_tables),
                "pending_deltas": len(pending),
                "pending_rows": sum(r.rows for r in pending),
                "staleness_s": (round(max(time.time() - min(ts), 0.0), 3)
                                if ts else 0.0),
                "serves": mv.serves,
                "refresh_incremental": mv.refresh_incremental,
                "refresh_full": mv.refresh_full,
                "last_refresh": mv.last_refresh_reason,
                "fingerprint": mv.fingerprint,
            })
    return out


def view_candidate_rows(context) -> List[dict]:
    """system.view_candidates source: hot repeated plan fingerprints from
    the flight recorder's EWMA history, ranked by hits x recompute cost —
    the operator's shortlist of what to CREATE MATERIALIZED VIEW next."""
    from . import flight_recorder as _fr

    if not _fr.enabled():
        return []
    stats = _fr._STATS.read()
    if not stats:
        return []
    # last seen SQL per fingerprint, from the query event ring
    examples: Dict[str, str] = {}
    for ev in _fr.read_events(kind="query"):
        fp = ev.get("plan_fp")
        if fp and ev.get("query"):
            examples[fp] = ev["query"]
    reg = get_registry(context)
    materialized = {mv.fingerprint for mv in reg.views.values()} \
        if reg is not None else set()
    rows = []
    for fp, e in stats.items():
        if not isinstance(e, dict):
            continue
        n = int(e.get("n", 0) or 0)
        ms = float(e.get("ms", 0.0) or 0.0)
        if n <= 0 or ms <= 0.0:
            continue
        rows.append({
            "fingerprint": fp,
            "hits": n,
            "ewma_ms": ms,
            "score": n * ms,
            "materialized": fp in materialized,
            "example_sql": examples.get(fp, ""),
        })
    rows.sort(key=lambda r: r["score"], reverse=True)
    return rows
