"""Continuous ingestion: a crash-tolerant write-ahead delta log per
append-only table, with snapshot-isolated reads (ISSUE 20).

``DSQL_INGEST_DIR`` arms the subsystem — checked BEFORE this module is
imported (the fleet/autopilot discipline: an unset dir keeps the module
un-imported and every byte of the engine identical).  ``DSQL_INGEST=0``
is the bit-for-bit kill switch with the dir still set.

The write path (``Context.append_rows``, which INSERT INTO and
``POST /v1/ingest`` lower to) becomes::

    coerce -> fault site -> backpressure -> [buffer] -> WAL -> apply

* **WAL**: one newline-terminated JSON envelope per committed batch,
  written with a single ``os.write`` on an ``O_APPEND`` fd and fsynced —
  the commit point — so an ack survives OS crash/power loss, not just
  process death (``DSQL_INGEST_FSYNC=0`` trades that down to
  process-crash-only durability for throughput).  A crash mid-write
  leaves a torn tail that fails the CRC/JSON check and is skipped on
  replay: a batch is committed iff its line is whole, so replay recovers
  exactly the committed prefix and nothing half-written ("degraded never
  wrong").  Segments rotate per table at ``DSQL_INGEST_SEGMENT_MB``, and
  a table's segments truncate when it is dropped or re-registered from
  source mid-run — the new base supersedes the log (this is the
  checkpoint story: persist the table to its source, re-register, and
  the history is gone instead of replaying forever).
* **Replay**: arming (``Context.__init__`` / ``run_server``) loads the
  log; batches for tables that already exist apply immediately, the
  rest wait for ``create_table`` to re-register the base and then apply
  (``maybe_replay``) — a fresh process recovers every committed batch.
* **Micro-batch coalescing**: ``DSQL_INGEST_BATCH_ROWS`` > 1 buffers
  appends per table and commits them as one WAL line + one catalog
  swap + one matview delta once the buffer fills or outlives
  ``DSQL_INGEST_BATCH_MS`` (a daemon flusher drains aged buffers).
  The default (1) is fully synchronous.
* **Backpressure**: every commit prices its batch through the
  scheduler's memory broker (``MemoryLedger.reserve``); a writer that
  outruns the budget gets a typed ``IngestBackpressure`` (HTTP 429 +
  Retry-After on the wire) instead of silently growing the device
  working set.
* **Snapshot isolation**: ``pin_scope`` captures the ``TableEntry`` and
  epoch of every scan in a plan at admission; the executors'
  catalog reads (``Context.catalog_entry`` / ``table_epoch``) consult
  the thread's pin stack, so one query sees one consistent prefix of
  the log across all its scans while the writer keeps appending.
"""
from __future__ import annotations

import glob as _glob
import json
import logging
import os
import threading
import time
import zlib
from contextlib import contextmanager

from . import faults as _faults
from . import resilience as _res
from . import telemetry as _tel

logger = logging.getLogger(__name__)

WAL_SUBDIR = "wal"
WAL_VERSION = 1


# ---------------------------------------------------------------------------
# env knobs (read per call: tests flip them with monkeypatch)
# ---------------------------------------------------------------------------

def ingest_dir():
    return os.environ.get("DSQL_INGEST_DIR") or None


def enabled() -> bool:
    """Armed (dir set) AND not killed (DSQL_INGEST=0).  Callers check the
    same condition inline BEFORE importing this module."""
    if not ingest_dir():
        return False
    return os.environ.get("DSQL_INGEST", "1").strip() not in ("0", "false")


def batch_rows() -> int:
    try:
        return max(int(os.environ.get("DSQL_INGEST_BATCH_ROWS", "") or 1), 1)
    except ValueError:
        return 1


def batch_ms() -> float:
    try:
        return max(float(os.environ.get("DSQL_INGEST_BATCH_MS", "") or 25.0),
                   0.0)
    except ValueError:
        return 25.0


def _fsync_on() -> bool:
    return os.environ.get("DSQL_INGEST_FSYNC", "1").strip() \
        not in ("0", "false")


def _segment_bytes() -> int:
    try:
        mb = float(os.environ.get("DSQL_INGEST_SEGMENT_MB", "") or 64.0)
    except ValueError:
        mb = 64.0
    return max(int(mb * 2**20), 1 << 16)


# ---------------------------------------------------------------------------
# batch <-> JSON (WAL line payload)
# ---------------------------------------------------------------------------

def _encode_table(t) -> dict:
    """Columnar JSON for a coerced delta batch.  Types round-trip through
    the dtype hint + Context._coerce_delta's cast on replay."""
    import numpy as np

    df = t.to_pandas()
    cols = []
    for name in df.columns:
        s = df[name]
        if np.issubdtype(s.dtype, np.datetime64):
            vals = [None if v is None or str(v) == "NaT" else int(v.value)
                    for v in s]
            cols.append({"n": str(name), "d": "datetime64[ns]", "v": vals})
        elif s.dtype == object or s.dtype.kind in ("U", "S"):
            vals = [None if v is None or (isinstance(v, float) and v != v)
                    else str(v) for v in s.tolist()]
            cols.append({"n": str(name), "d": "str", "v": vals})
        else:
            cols.append({"n": str(name), "d": str(s.dtype),
                         "v": s.tolist()})
    return {"rows": int(t.num_rows), "cols": cols}


def _decode_table(data: dict):
    """Inverse of ``_encode_table``; the caller re-coerces against the
    live target schema so dtype drift degrades to a cast, not a crash."""
    import pandas as pd

    out = {}
    for c in data["cols"]:
        vals = c["v"]
        if c["d"] == "datetime64[ns]":
            out[c["n"]] = pd.to_datetime(
                [None if v is None else int(v) for v in vals])
        elif c["d"] == "str":
            out[c["n"]] = pd.Series(vals, dtype=object)
        else:
            try:
                out[c["n"]] = pd.Series(vals, dtype=c["d"])
            except (ValueError, TypeError):
                out[c["n"]] = pd.Series(vals)
    return pd.DataFrame(out)


def _table_nbytes(t) -> int:
    total = 0
    for col in t.columns:
        data = getattr(col, "data", None)
        total += int(getattr(data, "nbytes", 0) or 0)
        mask = getattr(col, "mask", None)
        total += int(getattr(mask, "nbytes", 0) or 0)
    return total or t.num_rows * 8 * max(t.num_columns, 1)


# ---------------------------------------------------------------------------
# the per-context log
# ---------------------------------------------------------------------------

class _Buffer:
    __slots__ = ("tables", "rows", "born", "grants")

    def __init__(self):
        self.tables = []
        self.rows = 0
        self.born = time.monotonic()
        # (ledger, grant) per buffered batch: the memory-broker
        # reservation stays alive while the rows sit here — they occupy
        # real memory until the flush applies them — so trickle writers
        # cannot park unbounded bytes outside the backpressure budget
        self.grants = []

    def release_grants(self) -> None:
        grants, self.grants = self.grants, []
        for ledger, grant in grants:
            try:
                ledger.release(grant)
            except Exception:  # pragma: no cover
                logger.debug("ingest: grant release failed", exc_info=True)


class _Flusher(threading.Thread):
    def __init__(self, log, interval_s: float):
        super().__init__(name="dsql-ingest-flush", daemon=True)
        self.log = log
        self.interval_s = interval_s
        self.stop = threading.Event()

    def run(self):
        while not self.stop.wait(self.interval_s):
            try:
                self.log.flush_aged()
            except Exception:  # pragma: no cover
                logger.debug("ingest flush failed", exc_info=True)


class IngestLog:
    """WAL + buffers + replay state for one Context."""

    def __init__(self, context, root: str):
        self.context = context
        self.wal_dir = os.path.join(root, WAL_SUBDIR)
        os.makedirs(self.wal_dir, exist_ok=True)
        self.lock = threading.RLock()
        self._fds = {}        # (schema, table) -> (fd, path, seq)
        self._buffers = {}    # (schema, table) -> _Buffer
        self._stats = {}      # (schema, table) -> dict (engine_section)
        self._replay = {}     # (schema, table) -> [payload dicts]
        self._wal_bytes = 0
        self._flusher = None
        self._load_replay()
        _ALL_LOGS.append(self)

    # -- WAL segments ------------------------------------------------------
    def _seg_glob(self, key):
        return os.path.join(self.wal_dir, f"{key[0]}.{key[1]}.*.log")

    def _open_segment(self, key, seq: int):
        path = os.path.join(self.wal_dir, f"{key[0]}.{key[1]}.{seq:05d}.log")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        return fd, path, seq

    def _fd_for(self, key):
        ent = self._fds.get(key)
        if ent is None:
            segs = sorted(_glob.glob(self._seg_glob(key)))
            seq = int(segs[-1].rsplit(".", 2)[-2]) if segs else 1
            ent = self._fds[key] = self._open_segment(key, seq)
        fd, path, seq = ent
        try:
            if os.fstat(fd).st_size >= _segment_bytes():
                os.close(fd)
                ent = self._fds[key] = self._open_segment(key, seq + 1)
        except OSError:  # pragma: no cover
            pass
        return ent[0]

    def _wal_write(self, key, delta) -> None:
        """The commit point: one line, one write syscall, one fsync.  A
        crash that truncates the line leaves an invalid tail replay skips;
        the fsync makes an acked batch survive OS crash/power loss, not
        just process death (DSQL_INGEST_FSYNC=0 drops it for throughput,
        degrading the guarantee to process-crash-only durability)."""
        payload = json.dumps(
            {"s": key[0], "t": key[1], "d": _encode_table(delta)},
            separators=(",", ":"))
        line = (json.dumps(
            {"v": WAL_VERSION, "crc": zlib.crc32(payload.encode()),
             "p": payload}, separators=(",", ":")) + "\n").encode()
        fd = self._fd_for(key)
        os.write(fd, line)
        if _fsync_on():
            try:
                os.fsync(fd)
            except OSError:  # pragma: no cover - e.g. fs without fsync
                logger.debug("ingest: WAL fsync failed", exc_info=True)
        self._wal_bytes += len(line)
        _tel.REGISTRY.set_gauge("ingest_wal_bytes", self._wal_bytes)

    # -- replay ------------------------------------------------------------
    def _load_replay(self) -> None:
        torn = 0
        for seg in sorted(_glob.glob(os.path.join(self.wal_dir, "*.log"))):
            try:
                with open(seg, "rb") as f:
                    raw = f.read()
            except OSError:  # pragma: no cover
                continue
            self._wal_bytes += len(raw)
            for ln in raw.split(b"\n"):
                if not ln.strip():
                    continue
                try:
                    env = json.loads(ln)
                    p = env["p"]
                    if env.get("crc") != zlib.crc32(p.encode()):
                        raise ValueError("wal crc mismatch")
                    rec = json.loads(p)
                except (ValueError, TypeError, KeyError):
                    # torn/garbled line: the writer never acked this batch
                    # (the commit point is the complete line), so skipping
                    # it loses nothing committed
                    torn += 1
                    continue
                self._replay.setdefault((rec["s"], rec["t"]),
                                        []).append(rec["d"])
        if torn:
            _tel.inc("ingest_wal_torn_lines", torn)
            logger.warning("ingest: skipped %d torn WAL line(s) under %s",
                           torn, self.wal_dir)
        _tel.REGISTRY.set_gauge("ingest_wal_bytes", self._wal_bytes)

    def maybe_replay(self, schema_name: str, table_name: str) -> int:
        """Apply pending WAL batches for a freshly-registered table.
        Called on arming (already-registered tables) and from
        ``create_table`` (the restart path registers bases first)."""
        key = (schema_name, table_name)
        with self.lock:
            recs = self._replay.pop(key, None)
        if not recs:
            return 0
        rows = 0
        for d in recs:
            try:
                rows += self.context._apply_delta(
                    schema_name, table_name, _decode_table(d))
            except Exception:
                logger.warning("ingest: WAL replay batch for %s.%s failed",
                               schema_name, table_name, exc_info=True)
        _tel.inc("ingest_replayed_batches", len(recs))
        _tel.inc("ingest_replayed_rows", rows)
        st = self._stats.setdefault(key, _new_stats())
        st["replayed_batches"] += len(recs)
        st["replayed_rows"] += rows
        logger.info("ingest: replayed %d batch(es) / %d row(s) into %s.%s",
                    len(recs), rows, schema_name, table_name)
        return rows

    # -- the write path ----------------------------------------------------
    def commit(self, schema_name: str, table_name: str, delta) -> int:
        """WAL-then-apply (or buffer) one coerced batch.  Returns rows
        applied now (0 = buffered, flushed later by size/age)."""
        # chaos site: fires BEFORE anything durable or visible, so a
        # failed append is cleanly rejected — never half-committed
        _faults.maybe_fail("ingest")
        key = (schema_name, table_name)
        nbytes = _table_nbytes(delta)
        from . import scheduler as _sched
        ledger = _sched.get_manager().ledger
        grant = ledger.reserve(nbytes)
        if grant is None:
            _tel.inc("ingest_backpressure_rejects")
            raise _res.IngestBackpressure(
                f"ingest batch of {delta.num_rows} rows ({nbytes} bytes) "
                "does not fit the device budget; back off and retry "
                "(DSQL_DEVICE_BUDGET_MB prices writers and readers from "
                "the same ledger)", retry_after_s=0.25)
        if batch_rows() > 1:
            handed_off = False
            try:
                with self.lock:
                    buf = self._buffers.setdefault(key, _Buffer())
                    buf.tables.append(delta)
                    buf.rows += delta.num_rows
                    # the buffer owns the reservation from here: buffered
                    # rows occupy memory until the flush applies them, so
                    # the grant releases in _flush, not on ack
                    buf.grants.append((ledger, grant))
                    handed_off = True
                    if buf.rows < batch_rows():
                        _tel.inc("ingest_batches_buffered")
                        st = self._stats.setdefault(key, _new_stats())
                        st["buffered_rows"] = buf.rows
                        _tel.REGISTRY.set_gauge(
                            "ingest_buffered_rows", self._buffered_rows())
                        return 0
            finally:
                if not handed_off:
                    ledger.release(grant)
            return self._flush(key)
        try:
            return self._commit_now(key, delta)
        finally:
            ledger.release(grant)

    def _commit_now(self, key, delta) -> int:
        # the table's append lock spans WAL write AND apply so (a) two
        # concurrent writers cannot interleave read-concat-swap and lose
        # a batch, and (b) WAL order is apply order — replay reproduces
        # exactly the sequence readers observed
        with self.context._append_lock(key[0], key[1]):
            with self.lock:
                self._wal_write(key, delta)
            rows = self.context._apply_delta_locked(key[0], key[1], delta)
        _tel.inc("ingest_batches_committed")
        _tel.inc("ingest_rows_committed", rows)
        st = self._stats.setdefault(key, _new_stats())
        st["batches"] += 1
        st["rows"] += rows
        return rows

    def _flush(self, key) -> int:
        from ..ops.join import concat_tables
        with self.lock:
            buf = self._buffers.pop(key, None)
            if buf is None or not buf.tables:
                if buf is not None:
                    buf.release_grants()
                return 0
            delta = (buf.tables[0] if len(buf.tables) == 1
                     else concat_tables(buf.tables))
            st = self._stats.setdefault(key, _new_stats())
            st["buffered_rows"] = 0
            _tel.REGISTRY.set_gauge("ingest_buffered_rows",
                                    self._buffered_rows())
        try:
            _tel.inc("ingest_flushes")
            return self._commit_now(key, delta)
        finally:
            buf.release_grants()

    def flush_aged(self) -> int:
        """Flusher-thread entry: commit buffers older than the batch
        window so a trickle writer never strands rows."""
        limit_s = batch_ms() / 1000.0
        now = time.monotonic()
        with self.lock:
            aged = [k for k, b in self._buffers.items()
                    if now - b.born >= limit_s]
        rows = 0
        for key in aged:
            rows += self._flush(key)
        return rows

    def flush_all(self) -> int:
        with self.lock:
            keys = list(self._buffers)
        return sum(self._flush(k) for k in keys)

    def _buffered_rows(self) -> int:
        return sum(b.rows for b in self._buffers.values())

    # -- lifecycle ---------------------------------------------------------
    def start_flusher(self) -> None:
        if self._flusher is None and batch_rows() > 1:
            interval = max(batch_ms() / 1000.0, 0.01)
            self._flusher = _Flusher(self, interval)
            self._flusher.start()

    def close(self) -> None:
        if self._flusher is not None:
            self._flusher.stop.set()
            self._flusher = None
        # buffered rows were acked BUFFERED over the wire; a graceful
        # close must commit them before the fds go away or the accepted
        # batch silently vanishes (the drain path calls this too)
        try:
            self.flush_all()
        except Exception:
            logger.warning("ingest: flush on close failed", exc_info=True)
        with self.lock:
            for fd, _path, _seq in self._fds.values():
                try:
                    os.close(fd)
                except OSError:  # pragma: no cover
                    pass
            self._fds.clear()

    def has_pending(self, schema_name: str, table_name: str) -> bool:
        """True when replayable WAL batches await this table's
        registration (the restart path)."""
        with self.lock:
            return (schema_name, table_name) in self._replay

    def truncate(self, schema_name: str, table_name: str) -> None:
        """Drop a table's WAL history: segments, buffers, pending replay.

        Called when the base is dropped or re-registered from source with
        nothing pending — the new (or absent) base supersedes the log, and
        replaying the old deltas on a later restart would double-apply
        rows the source now carries, or resurrect a dropped table's rows.
        Re-registration is also the checkpoint/compaction path: persist
        the table to its source and re-register, and the WAL stops
        growing instead of replaying the full history every restart."""
        key = (schema_name, table_name)
        with self.lock:
            ent = self._fds.pop(key, None)
            if ent is not None:
                try:
                    os.close(ent[0])
                except OSError:  # pragma: no cover
                    pass
            buf = self._buffers.pop(key, None)
            if buf is not None:
                buf.release_grants()
            self._replay.pop(key, None)
            removed = 0
            for seg in _glob.glob(self._seg_glob(key)):
                try:
                    removed += os.path.getsize(seg)
                    os.remove(seg)
                except OSError:  # pragma: no cover
                    pass
            if removed:
                self._wal_bytes = max(self._wal_bytes - removed, 0)
                _tel.REGISTRY.set_gauge("ingest_wal_bytes", self._wal_bytes)
                _tel.inc("ingest_wal_truncations")
                logger.info("ingest: truncated %d WAL byte(s) for %s.%s",
                            removed, schema_name, table_name)

    def tables_snapshot(self) -> dict:
        with self.lock:
            out = {}
            for key, st in sorted(self._stats.items()):
                out[f"{key[0]}.{key[1]}"] = dict(st)
            for key, buf in self._buffers.items():
                out.setdefault(f"{key[0]}.{key[1]}",
                               _new_stats())["buffered_rows"] = buf.rows
            return out


def _new_stats() -> dict:
    return {"batches": 0, "rows": 0, "buffered_rows": 0,
            "replayed_batches": 0, "replayed_rows": 0}


_ALL_LOGS: list = []


# ---------------------------------------------------------------------------
# arming (Context.__init__ / run_server hook; env checked by the caller)
# ---------------------------------------------------------------------------

_ARM_LOCK = threading.Lock()


def get_log(context, create: bool = False):
    log = getattr(context, "_ingest_log", None)
    if log is None and create and enabled():
        with _ARM_LOCK:
            log = getattr(context, "_ingest_log", None)
            if log is None:
                log = IngestLog(context, ingest_dir())
                context._ingest_log = log
    return log


def ensure_armed(context) -> bool:
    """Idempotent per-context arming: open the WAL, replay committed
    batches for tables that already exist, start the flusher."""
    if not enabled():
        return False
    log = get_log(context, create=True)
    for schema_name, sc in list(context.schema.items()):
        for table_name, entry in list(sc.tables.items()):
            if entry.table is not None and entry.chunked is None:
                log.maybe_replay(schema_name, table_name)
    log.start_flusher()
    return True


def _reset_for_tests() -> None:
    while _ALL_LOGS:
        log = _ALL_LOGS.pop()
        try:
            log.close()
            log.context.__dict__.pop("_ingest_log", None)
        except Exception:  # pragma: no cover
            pass


# ---------------------------------------------------------------------------
# snapshot-isolated reads: the per-thread pin stack
# ---------------------------------------------------------------------------

_TLS = threading.local()


def _collect_scans(plan, out) -> None:
    from ..plan.nodes import LogicalTableScan, RexScalarSubquery

    def walk_rex(rex):
        if isinstance(rex, RexScalarSubquery) and rex.plan is not None:
            _collect_scans(rex.plan, out)
            return
        for op in getattr(rex, "operands", []) or []:
            walk_rex(op)

    if isinstance(plan, LogicalTableScan):
        out.append(plan)
        return
    for e in getattr(plan, "exprs", []) or []:
        walk_rex(e)
    cond = getattr(plan, "condition", None)
    if cond is not None:
        walk_rex(cond)
    for i in plan.inputs:
        _collect_scans(i, out)


@contextmanager
def pin_scope(context, plan):
    """Snapshot-isolate one query: capture (TableEntry, epoch) for every
    scan in ``plan`` at admission.  ``Context.catalog_entry`` /
    ``table_epoch`` consult the top of this thread's stack during
    execution, so all scans — and the result-cache key — see the same
    consistent prefix of the log even while the writer keeps appending
    (tables are immutable and appends swap whole entries, so a pinned
    entry stays valid forever)."""
    pins = {}
    try:
        scans = []
        _collect_scans(plan, scans)
        for scan in scans:
            sc = context.schema.get(scan.schema_name)
            entry = (sc.tables.get(scan.table_name)
                     if sc is not None else None)
            if entry is not None and entry.table is not None:
                key = (scan.schema_name, scan.table_name)
                pins[key] = (entry,
                             context.table_epoch(scan.schema_name,
                                                 scan.table_name))
    except Exception:  # pragma: no cover - pinning must never fail a query
        logger.debug("snapshot pin capture failed", exc_info=True)
        pins = {}
    stack = getattr(_TLS, "pins", None)
    if stack is None:
        stack = _TLS.pins = []
    stack.append(pins)
    try:
        yield
    finally:
        stack.pop()


def pinned_entry(schema_name: str, table_name: str):
    stack = getattr(_TLS, "pins", None)
    if not stack:
        return None
    hit = stack[-1].get((schema_name, table_name))
    return None if hit is None else hit[0]


def pinned_epoch(schema_name: str, table_name: str):
    stack = getattr(_TLS, "pins", None)
    if not stack:
        return None
    hit = stack[-1].get((schema_name, table_name))
    return None if hit is None else hit[1]


# ---------------------------------------------------------------------------
# /v1/engine section
# ---------------------------------------------------------------------------

def engine_section(context) -> dict:
    counters = _tel.REGISTRY.counters()
    gauges = _tel.REGISTRY.gauges()
    log = get_log(context)
    out = {
        "armed": log is not None,
        "dir": ingest_dir() or "",
        "batchRows": batch_rows(),
        "batchMs": batch_ms(),
        "batchesCommitted": int(counters.get("ingest_batches_committed", 0)),
        "rowsCommitted": int(counters.get("ingest_rows_committed", 0)),
        "replayedBatches": int(counters.get("ingest_replayed_batches", 0)),
        "backpressureRejects": int(
            counters.get("ingest_backpressure_rejects", 0)),
        "tornWalLines": int(counters.get("ingest_wal_torn_lines", 0)),
        "walBytes": int(gauges.get("ingest_wal_bytes", 0)),
        "bufferedRows": int(gauges.get("ingest_buffered_rows", 0)),
        "mvPendingRows": int(gauges.get("mv_pending_rows", 0)),
        "mvStalenessS": float(gauges.get("mv_staleness_s", 0.0)),
    }
    if log is not None:
        out["tables"] = log.tables_snapshot()
    return out
