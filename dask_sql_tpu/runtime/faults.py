"""Deterministic and probabilistic named-site fault injection.

The degradation ladder (runtime/resilience.py) is only trustworthy if CI
exercises it; production faults (remote-TPU helper SIGSEGVs, tunnel drops,
device OOM) cannot be scheduled.  This module plants named injection sites
at the layer boundaries —

  ``compile``        a stage/whole-plan program build+first call
                     (physical/compiled.py _execute_single)
  ``materialize``    decoding a program's outputs to a host Table
                     (physical/compiled.py _materialize)
  ``stage_exec``     one stage-execution ATTEMPT of a stage-graph
                     (physical/compiled.py _execute_stage_graph; fired
                     once per attempt, so a replay fires it again)
  ``stage_replay``   a checkpointed stage REPLAY — the re-execution of a
                     failed stage from its materialized boundary temps
                     (physical/compiled.py run_stage) — so CI can prove a
                     sabotaged replay path still degrades cleanly
  ``chunked_read``   uploading one out-of-HBM batch
                     (io/chunked.py ChunkedSource.batch_table)
  ``host_transfer``  fetching streamed partials to host
                     (physical/streaming.py _host_partial)
  ``cache_populate`` storing a result/subplan into the result cache
                     (runtime/result_cache.py ResultCache.put) — population
                     is best-effort, so a fired fault here skips the store
                     without failing the query
  ``admission``      admitting a query through the workload manager
                     (runtime/scheduler.py WorkloadManager.acquire) — a
                     fired fault fails THAT query with a typed transient
                     error before it takes a slot, proving a broken
                     admission path degrades cleanly instead of wedging
                     the queue or the server
  ``drain``          the server's graceful-drain procedure
                     (server/app.py) — the drain path catches a fired
                     fault and still shuts down, proving a broken drain
                     cannot wedge process exit

— each calling ``maybe_fail(site)``, a no-op unless armed.  Arm via the
environment ``DSQL_FAULT_INJECT`` (comma-separated specs) or the
``inject(...)`` context manager in tests.  Two arming forms:

deterministic, ``site:nth[+]``:

  ``compile:1``           the 1st compile call raises FaultInjected
  ``compile:2+``          every compile call from the 2nd on raises
  ``compile:1:sleep=500`` the 1st compile call STALLS ~500 ms first (in
                          cancellable slices) — a deterministic "hung
                          program" for deadline/cancel tests — then raises

probabilistic, ``site:p=P[:seed=N]`` (the chaos-soak form,
scripts/chaos_soak.py): every call at the site fails independently with
probability ``P`` from a dedicated ``random.Random(N)`` stream —
deterministic given the seed and the call sequence:

  ``compile:p=0.05:seed=7``   ~5% of compile calls raise

Both forms accept ``:sleep=MS`` (stall before raising) and ``:fatal``
(raise ``FatalFaultInjected`` — a FatalError — instead of the transient
``FaultInjected``; this is how CI reaches the exile/quarantine paths,
which transient faults deliberately never trigger).

Counters are process-global (sites fire from worker threads) and 1-based;
a fired fault increments ``compiled.stats["fault_<site>"]``.  FaultInjected
is a TransientError, so the ordinary retry/degradation machinery handles
it exactly like the production faults it stands in for.
"""
from __future__ import annotations

import os
import random
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from .resilience import FatalError, TransientError, interruptible_sleep

SITES = ("compile", "materialize", "stage_exec", "stage_replay",
         "chunked_read", "host_transfer", "cache_populate", "admission",
         "drain", "spill", "mv_refresh", "result_spool", "autopilot",
         "ingest")


class FaultInjected(TransientError):
    """An armed injection site fired (stands in for a production fault)."""

    error_name = "FAULT_INJECTED"

    def __init__(self, site: str, nth: int):
        super().__init__(f"injected fault at site {site!r} (call #{nth})",
                         kind="injected")
        self.site = site
        self.nth = nth


class FatalFaultInjected(FatalError):
    """An armed ``:fatal`` site fired: stands in for a crash verdict (the
    program is doomed, not the attempt), reaching the exile + quarantine
    paths that transient faults never touch."""

    error_name = "FAULT_INJECTED"

    def __init__(self, site: str, nth: int):
        super().__init__(
            f"injected FATAL fault at site {site!r} (call #{nth})")
        self.site = site
        self.nth = nth


class _Spec:
    __slots__ = ("site", "nth", "from_on", "prob", "rng", "sleep_ms",
                 "fatal")

    def __init__(self, site: str, nth: Optional[int], from_on: bool,
                 prob: Optional[float], seed: int,
                 sleep_ms: Optional[int], fatal: bool):
        self.site = site
        self.nth = nth
        self.from_on = from_on
        self.prob = prob
        # dedicated stream per spec: deterministic given (seed, call seq),
        # independent of any other random use in the process
        self.rng = random.Random(seed) if prob is not None else None
        self.sleep_ms = sleep_ms
        self.fatal = fatal

    def matches(self, count: int) -> bool:
        if self.prob is not None:
            return self.rng.random() < self.prob
        return count >= self.nth if self.from_on else count == self.nth


def parse_spec(raw: str) -> List[_Spec]:
    """Parse a DSQL_FAULT_INJECT value; unknown sites/shapes are rejected
    loudly — a typo must not silently disarm a fault test."""
    specs: List[_Spec] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(f"DSQL_FAULT_INJECT spec {part!r}: want "
                             "site:nth[+] or site:p=P[:seed=N]")
        site = fields[0]
        if site not in SITES:
            raise ValueError(f"DSQL_FAULT_INJECT: unknown site {site!r} "
                             f"(sites: {', '.join(SITES)})")
        arm = fields[1]
        nth: Optional[int] = None
        from_on = False
        prob: Optional[float] = None
        if arm.startswith("p="):
            prob = float(arm[len("p="):])
            if not 0.0 < prob <= 1.0:
                raise ValueError(
                    f"DSQL_FAULT_INJECT: probability {prob!r} outside (0, 1]")
        else:
            from_on = arm.endswith("+")
            nth = int(arm[:-1] if from_on else arm)
        seed = 0
        sleep_ms = None
        fatal = False
        for extra in fields[2:]:
            if extra.startswith("sleep="):
                sleep_ms = int(extra[len("sleep="):])
            elif extra.startswith("seed="):
                seed = int(extra[len("seed="):])
            elif extra == "fatal":
                fatal = True
            else:
                raise ValueError(
                    f"DSQL_FAULT_INJECT: unknown action {extra!r}")
        specs.append(_Spec(site, nth, from_on, prob, seed, sleep_ms, fatal))
    return specs


_lock = threading.Lock()
_counts: Dict[str, int] = {}
_override: Optional[List[_Spec]] = None      # inject() context manager
_env_cache: Tuple[Optional[str], List[_Spec]] = (None, [])


def _active_specs() -> List[_Spec]:
    global _env_cache
    if _override is not None:
        return _override
    raw = os.environ.get("DSQL_FAULT_INJECT")
    if not raw:
        return []
    if _env_cache[0] != raw:
        _env_cache = (raw, parse_spec(raw))
    return _env_cache[1]


def reset() -> None:
    """Zero all site counters (between tests / smoke queries)."""
    with _lock:
        _counts.clear()


def maybe_fail(site: str) -> None:
    """The injection site.  No-op unless a spec is armed for ``site``."""
    specs = _active_specs()
    if not specs:
        return
    with _lock:
        count = _counts.get(site, 0) + 1
        _counts[site] = count
        # probabilistic draws mutate the spec's rng; keep them under the
        # lock so the stream stays a deterministic function of the call
        # sequence
        hit = next((s for s in specs
                    if s.site == site and s.matches(count)), None)
    if hit is None:
        return
    from .resilience import _bump
    _bump(f"fault_{site}")
    if hit.sleep_ms:
        # a "hung program": stall in cancellable slices so deadline/cancel
        # supervision — not the fault itself — decides the outcome
        interruptible_sleep(hit.sleep_ms / 1e3, site)
    if hit.fatal:
        raise FatalFaultInjected(site, count)
    raise FaultInjected(site, count)


@contextmanager
def inject(spec: str):
    """Arm injection for a test body, e.g. ``inject("compile:1")`` or
    ``inject("stage_exec:1+")``; counters reset on entry AND exit so
    specs never leak across tests."""
    global _override
    parsed = parse_spec(spec)
    with _lock:
        prev = _override
    reset()
    _override = parsed
    try:
        yield
    finally:
        _override = prev
        reset()
