"""Workload manager: admission control, priority scheduling, memory broker.

The north star is an engine serving heavy concurrent traffic, yet until this
module every query ran the moment it arrived: the Presto server funneled
everything into a hardcoded 4-thread pool with no queue bounds, no notion of
priority, and no coordination between a query's memory appetite and the
device budget the result cache already accounts against.  The reference
dask-sql delegates all of this to dask.distributed's dynamic task scheduler;
a TPU-native engine has no task scheduler to lean on — one compiled XLA
program per stage — so workload management must live at the host boundary,
in the spirit of Flare's native scheduling of heterogeneous workloads and
DrJAX's explicit resource-mapped execution (PAPERS.md).

Every query — server, ``Context.sql()``, streaming — passes through the
process-global :class:`WorkloadManager` before touching the device.  Three
cooperating parts:

**Admission controller.**  At most ``DSQL_MAX_CONCURRENT_QUERIES`` queries
execute at once (0 disables the whole subsystem); excess queries wait in a
bounded queue (``DSQL_QUEUE_DEPTH``).  Admission rejects *immediately* —
typed :class:`resilience.AdmissionRejected`, surfaced by the server as HTTP
429 + ``Retry-After`` — when the queue is full, or when the caller's
resilience deadline would expire before a slot could plausibly free (the
manager keeps an EWMA of slot-hold times to estimate the wait).  A wait that
outlives ``DSQL_QUEUE_TIMEOUT_MS`` raises ``AdmissionTimeout``; queue time
always counts against the query's deadline (the wait loop runs
``resilience.check`` — a queued query can be cancelled or time out exactly
like a running one).

**Priority scheduler.**  Three weighted classes — ``interactive`` >
``batch`` > ``background`` — settable per query via
``Context.sql(..., priority=...)`` or the ``X-DSQL-Priority`` server header.
When a slot frees, the next query is chosen by deficit-weighted round-robin:
each non-empty class accrues credit proportional to its weight and the
winner pays the round's full cost, so long-run service converges to the
weight ratio while an unserved class accumulates credit until it must win
(anti-starvation).  Waiting time adds a direct aging boost on top
(``DSQL_QUEUE_AGING_MS`` of waiting ≈ one extra credit), so a background
query can never be starved by a steady interactive arrival stream.

**Memory broker.**  Admission reserves an estimated working set — scanned
table bytes × per-operator multipliers (:func:`estimate_plan_bytes`) —
against a shared device-bytes ledger (``DSQL_DEVICE_BUDGET_MB``; 0 turns
the broker off).  The result cache is a *tenant* of this ledger: its
effective device budget shrinks to the ledger's free headroom
(``cache_allowance``), and reservation pressure actively spills/evicts the
cache's device tier (``ResultCache.shrink_device_to``) before giving up —
a large admitted query transiently shrinks the cache instead of OOMing.
A reservation that still cannot fit leaves the query queued (over-
reservation queues rather than crashes); estimates larger than the whole
budget are clamped so the query can run once it is alone.

**Drain.**  ``begin_drain()`` (flipped by the server's SIGTERM/SIGINT
handling, server/app.py) refuses every NEW admission with the typed
:class:`resilience.ServerDraining` (HTTP 503 + ``Retry-After`` at the
server) while in-flight queries keep their slots and finish within
``DSQL_DRAIN_TIMEOUT_S``; the ``server_draining`` gauge is 1 for the
duration.  The hold-time EWMA feeding the queue-wait estimate subtracts
retry-backoff sleep (``Ticket.backoff_s``, accrued by
``resilience.backoff``) so a query riding a long in-rung retry chain
cannot inflate the estimator and trigger spurious deadline fast-rejects.

Telemetry: ``sched_queue_depth`` / ``sched_running`` /
``sched_reserved_bytes`` gauges, per-class
``sched_admitted_*``/``sched_rejected_*``/``sched_timeout_*`` counters
(admitted + rejected + timeout always sums to queries submitted), and a
``queued`` span in every admitted query's QueryReport.  The ``admission``
fault-injection site (runtime/faults.py) fires at the top of ``acquire`` so
CI can prove a failing admission path degrades into the typed-error
machinery instead of crashing the server.

Lock order (deadlock discipline): manager condition lock > ledger lock >
result-cache lock.  The cache never takes a manager or ledger lock — its
tenancy reads (``cache_allowance``) are lock-free attribute reads.
"""
from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from . import faults as _faults, telemetry as _tel
from . import resilience as _res
from .resilience import (AdmissionRejected, AdmissionTimeout,
                         LoadShedRejected, ServerDraining, _env_int)

logger = logging.getLogger(__name__)


def _events_on() -> bool:
    # watchtower gate: env checked BEFORE importing events.py, so the
    # bus stays un-imported (zero cost) when disarmed
    return os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0")


def _shed_on() -> bool:
    # burn-driven load shedding (needs the SLO monitor, so it is inert
    # unless the watchtower is also armed); DSQL_SLO_SHED=0 disables
    return os.environ.get("DSQL_SLO_SHED", "1").strip() not in ("", "0")


_SHED_RETRY_AFTER_S = 5.0   # shed lifts as breaching samples age out of
                            # the fast window; 5 s is a sane re-poll pace


PRIORITIES = ("interactive", "batch", "background")

# DWRR weights: long-run slot share under sustained mixed load.  interactive
# wins ~8 of every 12 contended slots, batch ~3, background ~1 — but the
# deficit carry + aging boost guarantee every class is eventually served.
WEIGHTS: Dict[str, float] = {"interactive": 8.0, "batch": 3.0,
                             "background": 1.0}

DEFAULT_MAX_CONCURRENT = 4      # matches the server's historical pool width
DEFAULT_QUEUE_DEPTH = 32
DEFAULT_QUEUE_TIMEOUT_MS = 30_000
DEFAULT_AGING_MS = 2_000
DEFAULT_DEVICE_BUDGET_MB = 4_096
DEFAULT_DRAIN_TIMEOUT_S = 30


def drain_timeout_s() -> float:
    """How long a draining process waits for in-flight queries before
    typed cancellation (``DSQL_DRAIN_TIMEOUT_S``)."""
    return float(max(_env_int("DSQL_DRAIN_TIMEOUT_S",
                              DEFAULT_DRAIN_TIMEOUT_S), 1))

# deficit clamp: bounds the catch-up burst a long-unserved (or long-empty)
# class can accumulate, so one stale credit pile cannot monopolize a window
_DEFICIT_CAP = 8.0 * sum(WEIGHTS.values())


def tenant_weights() -> Dict[str, float]:
    """``DSQL_TENANT_WEIGHTS="gold:8,default:1"`` parsed to a weight map;
    empty when unset (fairness classes stay priority-only).  Weights clamp
    to a small positive floor — a zero weight would starve the class
    forever, which is what the deficit scheduler exists to prevent."""
    raw = os.environ.get("DSQL_TENANT_WEIGHTS", "").strip()
    if not raw:
        return {}
    out: Dict[str, float] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        try:
            out[name.strip().lower()] = max(float(w), 0.01)
        except ValueError:
            continue
    return out


def _fairness_tenant() -> Optional[str]:
    """The fairness-class tenant of THIS thread's query, or None when
    ``DSQL_TENANT_WEIGHTS`` is unset (scheduling stays priority-keyed,
    bit-for-bit the pre-weights behavior).  Untenanted queries fall into
    the "default" class so a weighted tenant contends against SOMETHING."""
    if not tenant_weights():
        return None
    try:
        from . import tenancy as _ten
        return (_ten.current_tenant() or "default").lower()
    except Exception:  # pragma: no cover - tenancy is optional
        return "default"

# estimator: per-operator working-set multipliers over scanned input bytes.
# Joins/windows buffer both sides plus outputs; aggregates/sorts roughly
# double; unlisted operators pass input bytes through.
_OP_MULTIPLIERS = {
    "LogicalJoin": 3.0,
    "LogicalWindow": 3.0,
    "LogicalAggregate": 2.0,
    "LogicalSort": 2.0,
    "LogicalUnion": 1.5,
    "LogicalIntersect": 1.5,
    "LogicalExcept": 1.5,
}
_MULTIPLIER_CAP = 16.0
_MIN_ESTIMATE = 1 << 20         # every query reserves at least 1 MiB


def normalize_priority(raw: Optional[str]) -> str:
    """Map user/header input to a priority class; unknown values fall back
    to the default instead of failing the query at the wire boundary."""
    if raw:
        p = str(raw).strip().lower()
        if p in PRIORITIES:
            return p
    return default_priority()


def default_priority() -> str:
    import os

    p = os.environ.get("DSQL_DEFAULT_PRIORITY", "").strip().lower()
    return p if p in PRIORITIES else "interactive"


# ---------------------------------------------------------------------------
# working-set estimator
# ---------------------------------------------------------------------------

def _entry_bytes(entry) -> int:
    """Resident bytes of one catalog entry; chunked (out-of-HBM) sources
    estimate from their BATCH size, not their total row count — the
    streaming executor keeps exactly one padded batch resident at a
    time, so a chunked plan's device working set is O(batch_rows).
    (Estimating from n_rows made every SF10 chunked query reserve the
    whole budget and serialized the morsel pipelines the broker is
    supposed to run concurrently.)"""
    chunked = getattr(entry, "chunked", None)
    table = getattr(entry, "table", None)
    if chunked is not None:
        n_rows = int(getattr(chunked, "n_rows", 0))
        batch_rows = int(getattr(chunked, "batch_rows", 0)) or n_rows
        n_cols = len(getattr(table, "columns", ())) or 1
        return min(n_rows, batch_rows) * n_cols * 8
    total = 0
    for c in getattr(table, "columns", ()):
        total += int(getattr(c.data, "nbytes", 0))
        if getattr(c, "mask", None) is not None:
            total += int(getattr(c.mask, "nbytes", 0))
    return total


def estimate_plan_bytes(plan, context) -> int:
    """Estimated device working set of an optimized plan: the bytes of every
    scanned table times the product of per-operator multipliers (capped).
    A shape heuristic, not an oracle — the broker clamps it to the budget,
    so an overestimate delays a query rather than wedging it."""
    scan_bytes = 0
    mult = 1.0
    stack = [plan]
    while stack:
        rel = stack.pop()
        t = type(rel).__name__
        if t == "LogicalTableScan":
            schema = context.schema.get(rel.schema_name)
            entry = (schema.tables.get(rel.table_name)
                     if schema is not None else None)
            if entry is not None:
                scan_bytes += _entry_bytes(entry)
        else:
            mult *= _OP_MULTIPLIERS.get(t, 1.0)
        stack.extend(getattr(rel, "inputs", ()) or ())
    return int(scan_bytes * min(mult, _MULTIPLIER_CAP)) + _MIN_ESTIMATE


def estimate_working_set(plan, context) -> "Tuple[int, str]":
    """(bytes, source) for the admission reservation: MEASURED history
    first, shape heuristic as fallback.

    When the flight recorder (runtime/flight_recorder.py) has an EWMA
    entry for this plan's canonical fingerprint, the reservation comes
    from bytes the engine actually touched on previous runs of the same
    shape (× DSQL_HISTORY_HEADROOM) instead of the scan-bytes×multiplier
    guess — counter ``estimate_from_history`` tallies those.  Never-seen
    plans (and a disabled recorder) keep the heuristic.

    Between those two sits the TableStats path (runtime/statistics.py):
    never-seen plans whose heavy operators are all estimable from ingest
    stats reserve estimated-cardinality bytes instead of the blunt
    scan-bytes×multiplier guess — counter ``estimate_from_stats``."""
    from . import flight_recorder as _fr
    from . import statistics as _stats

    hist = _fr.plan_history_bytes(plan, context)
    if hist is not None:
        _tel.inc("estimate_from_history")
        return max(int(hist), _MIN_ESTIMATE), "history"
    try:
        from ..physical.streaming import plan_references_chunked
        if plan_references_chunked(plan, context):
            # chunked plans stream one batch at a time: the heuristic's
            # scan bytes are already batch-bounded (_entry_bytes) and the
            # operator multipliers stand in for live pipeline depth —
            # journaled as its own source so admission decisions over
            # out-of-core plans are auditable
            return estimate_plan_bytes(plan, context), "chunked"
    except Exception:    # estimator must never fail a query
        logger.debug("chunked estimate failed", exc_info=True)
    est = _stats.estimate_plan_bytes_stats(plan, context)
    if est is not None:
        _tel.inc("estimate_from_stats")
        return max(int(est), _MIN_ESTIMATE), "stats"
    # fourth rung (runtime/profiler.py): the XLA cost model's "bytes
    # accessed" for this plan's captured programs — available once the
    # plan compiled anywhere (program-store entries persist the cost, so
    # a warm process has it before any history accrues).  The env gate
    # keeps the disabled path import-free, like the recorder's.
    if os.environ.get("DSQL_PROFILE", "0").strip() not in ("", "0"):
        try:
            from . import profiler as _prof
            est = _prof.plan_cost_bytes(plan, context)
        except Exception:   # estimator must never fail a query
            logger.debug("cost-model estimate failed", exc_info=True)
            est = None
        if est is not None:
            _tel.inc("estimate_from_cost_model")
            return max(int(est), _MIN_ESTIMATE), "cost_model"
    return estimate_plan_bytes(plan, context), "heuristic"


# ---------------------------------------------------------------------------
# memory broker
# ---------------------------------------------------------------------------

class MemoryLedger:
    """Shared device-bytes ledger: query reservations + the result cache's
    device tier must fit ``DSQL_DEVICE_BUDGET_MB`` together.

    ``reserve`` may be called with the manager lock held; it takes the
    ledger lock and may nest the result-cache lock (via
    ``shrink_device_to``) — never the other way around.  ``reserved_bytes``
    is a lock-free read so the cache's tenancy check can call it from under
    the cache's own lock without inverting the order.
    """

    def __init__(self, cache_fn=None):
        self._lock = threading.Lock()
        self._reserved = 0
        self._cache_fn = cache_fn

    def _cache(self):
        if self._cache_fn is not None:
            return self._cache_fn()
        from . import result_cache as _rc
        return _rc.get_cache()

    @staticmethod
    def _spill():
        """The spill store's device tier is the ledger's SECOND tenant
        (after the result cache); absent/disabled stores count zero."""
        from . import spill as _spill
        if not _spill.enabled():
            return None
        return _spill.get_store()

    def budget(self) -> int:
        mb = _env_int("DSQL_DEVICE_BUDGET_MB", DEFAULT_DEVICE_BUDGET_MB)
        return max(mb, 0) * 2**20

    def reserved_bytes(self) -> int:
        return self._reserved        # lock-free: GIL-atomic int read

    def reserve(self, nbytes: int) -> Optional[int]:
        """Reserve ``nbytes`` (clamped to the budget) against the ledger.

        Returns the bytes actually reserved (0 when the broker is off), or
        None when the reservation cannot fit even after shrinking the cache
        tenant — the caller keeps the query queued.
        """
        budget = self.budget()
        if budget <= 0:
            return 0                 # broker disabled: admission-only mode
        n = min(max(int(nbytes), 0), budget)
        with self._lock:
            cache = self._cache()
            spill = self._spill()
            spill_dev = int(spill.device_bytes) if spill is not None else 0
            free = (budget - self._reserved - int(cache.device_bytes)
                    - spill_dev)
            if free < n:
                # pressure-driven tenant shrink: spill/evict the cache's
                # device tier down to what this reservation leaves over,
                # then demote the spill store's device chunks to host
                target = max(budget - self._reserved - n, 0)
                cache.shrink_device_to(target)
                if spill is not None:
                    spill.shrink_device_to(
                        max(target - int(cache.device_bytes), 0))
                    spill_dev = int(spill.device_bytes)
                free = (budget - self._reserved - int(cache.device_bytes)
                        - spill_dev)
            if free < n:
                return None
            self._reserved += n
            return n

    def release(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._lock:
            self._reserved = max(self._reserved - int(nbytes), 0)


# ---------------------------------------------------------------------------
# tickets / seats
# ---------------------------------------------------------------------------

class Ticket:
    """One query's passage through admission: enqueue -> admit -> release."""

    __slots__ = ("priority", "est_bytes", "reserved_bytes", "enqueued_at",
                 "admitted_at", "queued_ms", "admitted", "released",
                 "backoff_s", "tenant")

    def __init__(self, priority: str, est_bytes: int, enqueued_at: float,
                 tenant: Optional[str] = None):
        self.priority = priority
        # fairness-class tenant (None unless DSQL_TENANT_WEIGHTS is set):
        # the ticket queues under "priority@tenant" instead of "priority"
        self.tenant = tenant
        self.est_bytes = est_bytes
        self.reserved_bytes = 0
        self.enqueued_at = enqueued_at
        self.admitted_at: Optional[float] = None
        self.queued_ms: Optional[float] = None
        self.admitted = False
        self.released = False
        # retry-backoff sleep accrued while holding the slot (filled at
        # release from QueryRuntime.backoff_s): subtracted from the
        # hold-time EWMA so in-rung retries cannot inflate the admission
        # queue-wait estimate
        self.backoff_s = 0.0


class Seat:
    """A server-side pre-claim made at POST time, before a worker thread
    picks the query up.  Counts toward the queue bound (so saturation 429s
    immediately instead of hiding in the thread pool's unbounded backlog)
    and carries the true enqueue timestamp, so ``queuedTimeMillis`` covers
    pool wait + scheduler wait."""

    __slots__ = ("priority", "enqueued_at", "consumed")

    def __init__(self, priority: str, enqueued_at: float):
        self.priority = priority
        self.enqueued_at = enqueued_at
        self.consumed = False


class _Tls(threading.local):
    ticket: Optional[Ticket] = None
    seat: Optional[Seat] = None
    priority: Optional[str] = None
    last_queued_ms: Optional[float] = None


_tls = _Tls()


@contextmanager
def priority_scope(priority: Optional[str]):
    """Install the explicit ``Context.sql(priority=...)`` choice for this
    thread; admission resolves explicit > seat > DSQL_DEFAULT_PRIORITY."""
    if priority is not None and priority not in PRIORITIES:
        raise ValueError(
            f"unknown priority {priority!r} (expected one of {PRIORITIES})")
    prev = _tls.priority
    _tls.priority = priority
    try:
        yield
    finally:
        _tls.priority = prev


@contextmanager
def seat_scope(seat: Optional[Seat]):
    """Install a server-claimed seat for this worker thread; the next
    admission consumes it (timestamp + priority)."""
    prev = _tls.seat
    _tls.seat = seat
    try:
        yield
    finally:
        _tls.seat = prev


def clear_thread_queued_ms() -> None:
    _tls.last_queued_ms = None


def thread_queued_ms() -> Optional[float]:
    """Measured queue time of the last admission on THIS thread (from the
    seat/enqueue timestamp to the admit timestamp) — race-free per-query
    attribution for the server's wire stats."""
    return _tls.last_queued_ms


# ---------------------------------------------------------------------------
# the workload manager
# ---------------------------------------------------------------------------

class WorkloadManager:
    """Process-global admission controller + priority scheduler + broker."""

    def __init__(self, cache_fn=None):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._running = 0
        self._seats = 0
        # fairness classes: keyed by priority alone until
        # DSQL_TENANT_WEIGHTS arms, then "priority@tenant" keys appear on
        # demand (bounded: one per priority x tenant ever seen); with the
        # knob unset the keys ARE exactly PRIORITIES and every code path
        # below reduces to the pre-weights behavior bit-for-bit
        self._waiting: Dict[str, "deque[Ticket]"] = {
            p: deque() for p in PRIORITIES}
        self._deficit: Dict[str, float] = {p: 0.0 for p in PRIORITIES}
        self._run_ewma_s: Optional[float] = None
        self._drain = threading.Event()
        self._shedding = False          # edge-trigger state for slo.shed
        self.ledger = MemoryLedger(cache_fn)

    # -- drain (SIGTERM/SIGINT graceful shutdown) ---------------------------
    def begin_drain(self) -> None:
        """Flip into draining: in-flight queries keep their slots and run
        to completion, but every NEW admission (seat claim or acquire)
        raises the typed ServerDraining verdict — the server surfaces it
        as HTTP 503 + Retry-After.  Independent of ``enabled()``: a
        process on its way out refuses new work even with the scheduler
        subsystem off."""
        self._drain.set()
        _tel.REGISTRY.set_gauge("server_draining", 1)

    def end_drain(self) -> None:
        self._drain.clear()
        _tel.REGISTRY.set_gauge("server_draining", 0)

    def draining(self) -> bool:
        return self._drain.is_set()

    def _drain_verdict(self) -> ServerDraining:
        return ServerDraining(
            "server is draining (shutdown in progress); retry against "
            "another instance", retry_after_s=drain_timeout_s())

    # -- config (env-read per call, like the result cache, so tests and
    # -- operators can flip knobs without a restart) ------------------------
    def limit(self) -> int:
        return max(_env_int("DSQL_MAX_CONCURRENT_QUERIES",
                            DEFAULT_MAX_CONCURRENT), 0)

    def depth(self) -> int:
        return max(_env_int("DSQL_QUEUE_DEPTH", DEFAULT_QUEUE_DEPTH), 0)

    def queue_timeout_s(self) -> float:
        return max(_env_int("DSQL_QUEUE_TIMEOUT_MS",
                            DEFAULT_QUEUE_TIMEOUT_MS), 0) / 1e3

    def aging_ms(self) -> float:
        return float(max(_env_int("DSQL_QUEUE_AGING_MS", DEFAULT_AGING_MS),
                         0))

    def enabled(self) -> bool:
        return self.limit() > 0

    def cache_allowance(self) -> Optional[int]:
        """Device bytes the result cache may hold right now under ledger
        tenancy, or None when the subsystem/broker is off.  Lock-free —
        called from under the cache's own lock."""
        if not self.enabled():
            return None
        budget = self.ledger.budget()
        if budget <= 0:
            return None
        return max(budget - self.ledger.reserved_bytes(), 0)

    def spill_allowance(self) -> int:
        """Device bytes the spill store's device tier may hold right now
        under ledger tenancy (runtime/spill.py put_table consults this
        before pinning a join output on device).  Lock-free, like
        cache_allowance; an unlimited broker answers a large sentinel so
        the static DSQL_SPILL_DEVICE_MB cap still governs."""
        if not self.enabled():
            return 1 << 62
        budget = self.ledger.budget()
        if budget <= 0:
            return 1 << 62
        return max(budget - self.ledger.reserved_bytes(), 0)

    # -- live introspection (server wire stats) -----------------------------
    def queue_depth(self) -> int:
        with self._lock:
            return self._waiting_count_locked() + self._seats

    def running_count(self) -> int:
        with self._lock:
            return self._running

    def waiting_snapshot(self) -> "List[dict]":
        """Per-ticket view of the admission queue (system.active /
        GET /v1/engine): priority class, time waited, requested bytes
        (plus the fairness tenant when weighted classes are armed)."""
        now = time.monotonic()
        out: List[dict] = []
        with self._lock:
            for q in self._waiting.values():
                for t in q:
                    row = {"priority": t.priority,
                           "waitedMillis": round(
                               (now - t.enqueued_at) * 1e3, 1),
                           "estBytes": int(t.est_bytes)}
                    if t.tenant:
                        row["tenant"] = t.tenant
                    out.append(row)
        return out

    # -- burn-driven load shedding (ISSUE 17) -------------------------------
    def _check_shed(self, priority: str) -> None:
        """Reject a background-class admission with the typed
        :class:`resilience.LoadShedRejected` while any SLO class is
        burning its error budget past ``DSQL_SLO_BURN`` on BOTH windows
        (the live recompute — events.SloMonitor.burning_classes — so the
        shed lifts by itself as breaching samples age out).  Shedding the
        lowest class *before* the protected classes breach is the whole
        point: deficit weights divide slots fairly, but fairness is the
        wrong policy once the error budget is on fire.  Interactive and
        batch admissions are never shed."""
        if priority != "background" or not _shed_on() or not _events_on():
            return
        from . import events as _ev
        try:
            burning = _ev.get_monitor().burning_classes()
        except Exception:       # never let the shed probe fail admission
            logger.debug("shed probe failed", exc_info=True)
            return
        shedding = bool(burning)
        fire = False
        with self._cv:
            if shedding != self._shedding:
                self._shedding = shedding
                fire = True
        _tel.REGISTRY.set_gauge("slo_shedding", 1 if shedding else 0)
        if fire:
            _ev.publish("slo.shed", active=shedding,
                        burning=sorted(burning))
        if not shedding:
            return
        _tel.inc("sched_shed_background")
        # ALSO counts into the rejected family: admitted + rejected +
        # timeout == submitted must keep holding (chaos_soak invariant)
        _tel.inc("sched_rejected_background")
        raise LoadShedRejected(
            f"background admissions shed: class(es) "
            f"{', '.join(sorted(burning))} burning SLO error budget past "
            f"{_ev.burn_threshold():g}x on both windows",
            retry_after_s=_SHED_RETRY_AFTER_S)

    # -- seats (server POST-time pre-claims) --------------------------------
    def claim_seat(self, priority: str) -> Optional[Seat]:
        """Claim a place in line at submit time; raises AdmissionRejected
        (HTTP 429 at the server) when running + queued + seats already fill
        every slot and queue position."""
        if self.draining():
            _tel.inc(f"sched_rejected_{normalize_priority(priority)}")
            raise self._drain_verdict()
        if not self.enabled():
            return None
        priority = normalize_priority(priority)
        self._check_shed(priority)
        with self._cv:
            limit, depth = self.limit(), self.depth()
            outstanding = (self._running + self._waiting_count_locked()
                           + self._seats)
            if outstanding >= limit + depth:
                _tel.inc(f"sched_rejected_{priority}")
                raise AdmissionRejected(
                    f"admission queue full ({outstanding} queries "
                    f"outstanding >= {limit} slots + {depth} queued)",
                    retry_after_s=self._retry_after_locked())
            self._seats += 1
            self._publish_locked()
        return Seat(priority, time.monotonic())

    def release_seat(self, seat: Optional[Seat]) -> None:
        """Return an unconsumed seat (query failed before admission, or was
        a DDL statement that never executes a plan)."""
        if seat is None or seat.consumed:
            return
        with self._cv:
            self._consume_seat_locked(seat)
            self._publish_locked()

    def _consume_seat_locked(self, seat: Seat) -> None:
        if not seat.consumed:
            seat.consumed = True
            self._seats = max(self._seats - 1, 0)

    # -- admission ----------------------------------------------------------
    def acquire(self, priority: str, est_bytes: int,
                seat: Optional[Seat] = None) -> Ticket:
        """Block until admitted; raises the typed verdict otherwise.

        The wait is deadline/cancellation-aware (``resilience.check`` runs
        every slice, so queue time counts against the query budget), aging-
        aware, and bounded by ``DSQL_QUEUE_TIMEOUT_MS``.  ``seat`` transfers
        a server pre-claim: its timestamp becomes the queue-time origin.
        """
        _faults.maybe_fail("admission")
        priority = normalize_priority(priority)
        # weighted tenant fairness (DSQL_TENANT_WEIGHTS): resolve the
        # fairness class once, and keep per-tenant books on THIS path so
        # submitted == admitted + rejected + timeout holds per tenant
        # (claim_seat rejections happen before acquire and are out of
        # these books by construction)
        ften = _fairness_tenant()
        if ften:
            _tel.inc(f"sched_submitted_tenant_{ften}")
        if self.draining():
            _tel.inc(f"sched_rejected_{priority}")
            if ften:
                _tel.inc(f"sched_rejected_tenant_{ften}")
            raise self._drain_verdict()
        if seat is None:
            # server-submitted queries were already shed-checked at seat
            # claim time; checking their pre-claimed seat again here would
            # double-count the reject counters for one submission
            try:
                self._check_shed(priority)
            except Exception:
                if ften:
                    _tel.inc(f"sched_rejected_tenant_{ften}")
                raise
        enqueued_at = seat.enqueued_at if seat is not None else \
            time.monotonic()
        ticket = Ticket(priority, int(est_bytes), enqueued_at, tenant=ften)
        with self._cv:
            if seat is not None:
                self._consume_seat_locked(seat)
            limit, depth = self.limit(), self.depth()
            n_wait = self._waiting_count_locked()
            if self._running >= limit and n_wait >= depth:
                _tel.inc(f"sched_rejected_{priority}")
                if ften:
                    _tel.inc(f"sched_rejected_tenant_{ften}")
                self._publish_locked()
                raise AdmissionRejected(
                    f"admission queue full ({n_wait} waiting >= depth "
                    f"{depth})", retry_after_s=self._retry_after_locked())
            # deadline-aware fast reject: do not enqueue a query whose
            # budget cannot plausibly survive the wait for a slot
            rt = _res.current()
            if rt is not None and self._running >= limit:
                rem = rt.remaining()
                expected = self._expected_wait_locked(n_wait)
                if (rem is not None and expected is not None
                        and rem < expected * 0.5):
                    _tel.inc(f"sched_rejected_{priority}")
                    if ften:
                        _tel.inc(f"sched_rejected_tenant_{ften}")
                    self._publish_locked()
                    raise AdmissionRejected(
                        f"deadline would expire while queued "
                        f"({rem * 1e3:.0f} ms left, ~{expected * 1e3:.0f} "
                        f"ms expected wait)",
                        retry_after_s=self._retry_after_locked())
            key = self._class_key(ticket)
            self._waiting.setdefault(key, deque())
            self._deficit.setdefault(key, 0.0)
            self._waiting[key].append(ticket)
            self._publish_locked()
            self._dispatch_locked()
            give_up = (time.monotonic() + self.queue_timeout_s()
                       if self.queue_timeout_s() > 0 else None)
            try:
                while not ticket.admitted:
                    _res.check("admission")
                    if give_up is not None and time.monotonic() >= give_up:
                        raise AdmissionTimeout(
                            f"queued {priority} query timed out after "
                            f"{self.queue_timeout_s() * 1e3:.0f} ms",
                            retry_after_s=self._retry_after_locked())
                    self._cv.wait(0.05)
            except BaseException:
                if ticket.admitted:
                    # admitted in the same instant the wait was abandoned:
                    # hand the slot straight back
                    self._release_locked(ticket)
                else:
                    self._abandon_locked(ticket)
                    # any abandoned wait — queue timeout, deadline expiry,
                    # cancellation — counts into the timeout family so
                    # admitted + rejected + timeout == submitted, always
                    _tel.inc(f"sched_timeout_{priority}")
                    if ften:
                        _tel.inc(f"sched_timeout_tenant_{ften}")
                self._publish_locked()
                raise
        _tls.last_queued_ms = ticket.queued_ms
        return ticket

    def release(self, ticket: Optional[Ticket]) -> None:
        if ticket is None:
            return
        with self._cv:
            self._release_locked(ticket)
            self._publish_locked()

    # -- internals (condition lock held) ------------------------------------
    @staticmethod
    def _class_key(ticket: Ticket) -> str:
        return (f"{ticket.priority}@{ticket.tenant}" if ticket.tenant
                else ticket.priority)

    @staticmethod
    def _weight_of(key: str) -> float:
        """DWRR weight of a fairness class: the priority weight alone for
        plain keys, x the tenant weight for "priority@tenant" keys (an
        unlisted tenant inherits the "default" entry, else 1.0)."""
        if "@" in key:
            p, _, t = key.partition("@")
            tw = tenant_weights()
            return WEIGHTS[p] * tw.get(t, tw.get("default", 1.0))
        return WEIGHTS[key]

    def _waiting_count_locked(self) -> int:
        return sum(len(q) for q in self._waiting.values())

    def _abandon_locked(self, ticket: Ticket) -> None:
        try:
            self._waiting[self._class_key(ticket)].remove(ticket)
        except (KeyError, ValueError):  # pragma: no cover - double abandon
            pass

    def _expected_wait_locked(self, n_ahead: int) -> Optional[float]:
        """Rough wait estimate: EWMA slot-hold time × queue position /
        slots.  None until at least one query has completed (no history —
        never reject on a guess)."""
        if self._run_ewma_s is None:
            return None
        return self._run_ewma_s * (n_ahead + 1) / max(self.limit(), 1)

    def _retry_after_locked(self) -> float:
        expected = self._expected_wait_locked(self._waiting_count_locked())
        if expected is None:
            return 1.0
        return min(max(math.ceil(expected), 1.0), 60.0)

    def _pick_locked(self) -> Optional[str]:
        """Deficit-weighted round-robin with aging: every non-empty class
        gains its weight; the winner (highest deficit + aging boost) pays
        the round's total, so service converges to the weight ratio and an
        unserved class accumulates credit until it must win.  With tenant
        weights armed the classes are "priority@tenant" and the weight is
        the product, so a noisy tenant's flood cannot starve a quiet
        tenant even inside one priority band; unarmed, the keys are
        exactly PRIORITIES and this is the pre-weights loop unchanged
        (the computed cap equals _DEFICIT_CAP)."""
        active = [k for k in self._waiting if self._waiting[k]]
        if not active:
            return None
        cap = 8.0 * sum(self._weight_of(k) for k in self._waiting)
        for k in active:
            self._deficit[k] = min(self._deficit[k] + self._weight_of(k),
                                   cap)
        aging = self.aging_ms()
        now = time.monotonic()

        def score(k: str) -> float:
            head = self._waiting[k][0]
            waited_ms = (now - head.enqueued_at) * 1e3
            boost = waited_ms / aging if aging > 0 else 0.0
            return self._deficit[k] + boost

        best = max(active, key=score)
        self._deficit[best] -= sum(self._weight_of(k) for k in active)
        return best

    def _dispatch_locked(self) -> None:
        limit = self.limit()
        while self._running < limit:
            k = self._pick_locked()
            if k is None:
                break
            ticket = self._waiting[k][0]
            reserved = self.ledger.reserve(ticket.est_bytes)
            if reserved is None:
                # over-reservation queues rather than crashes: refund the
                # round's deficit charge and retry at the next release
                self._deficit[k] += sum(
                    self._weight_of(q) for q in self._waiting
                    if self._waiting[q])
                break
            self._waiting[k].popleft()
            if not self._waiting[k]:
                self._deficit[k] = 0.0   # classic DRR: empty queue resets
            ticket.reserved_bytes = reserved
            ticket.admitted = True
            ticket.admitted_at = time.monotonic()
            ticket.queued_ms = (ticket.admitted_at
                                - ticket.enqueued_at) * 1e3
            self._running += 1
            # counters stay PRIORITY-keyed (the chaos-soak reconciliation
            # invariant sums over PRIORITIES), with per-tenant books added
            _tel.inc(f"sched_admitted_{ticket.priority}")
            if ticket.tenant:
                _tel.inc(f"sched_admitted_tenant_{ticket.tenant}")
            self._cv.notify_all()
        self._publish_locked()

    def _release_locked(self, ticket: Ticket) -> None:
        if ticket.released or not ticket.admitted:
            return
        ticket.released = True
        self._running = max(self._running - 1, 0)
        self.ledger.release(ticket.reserved_bytes)
        if ticket.admitted_at is not None:
            # hold time minus retry-backoff sleeps: the EWMA estimates how
            # long a slot stays BUSY, and a query asleep in backoff is not
            # representative work — counting it inflated queue-wait
            # estimates and triggered spurious deadline fast-rejects
            held = max(time.monotonic() - ticket.admitted_at
                       - max(ticket.backoff_s, 0.0), 0.0)
            self._run_ewma_s = (held if self._run_ewma_s is None
                                else 0.3 * held + 0.7 * self._run_ewma_s)
        self._dispatch_locked()
        self._cv.notify_all()

    def _publish_locked(self) -> None:
        _tel.REGISTRY.set_gauge("sched_queue_depth",
                                self._waiting_count_locked() + self._seats)
        _tel.REGISTRY.set_gauge("sched_running", self._running)
        _tel.REGISTRY.set_gauge("sched_reserved_bytes",
                                self.ledger.reserved_bytes())

    # -- the one call site: Context._execute_query_plan ---------------------
    @contextmanager
    def admission(self, plan=None, context=None,
                  priority: Optional[str] = None):
        """Admit one query plan for execution: resolve priority, estimate
        the working set, wait for a slot + memory under a ``queued`` span,
        and release both on exit.  Yields None (pass-through) when the
        subsystem is disabled or when this thread already holds a slot
        (nested plans — CREATE MODEL's training query, views — ride the
        outer admission instead of deadlocking on a second slot)."""
        if not self.enabled() or _tls.ticket is not None:
            yield None
            return
        seat, _tls.seat = _tls.seat, None      # consume the seat exactly once
        pr = priority or _tls.priority or \
            (seat.priority if seat is not None else None) or \
            default_priority()
        est = 0
        est_src = "none"
        if plan is not None and context is not None:
            try:
                est, est_src = estimate_working_set(plan, context)
            except Exception:      # estimator must never fail a query
                logger.debug("working-set estimate failed", exc_info=True)
                est, est_src = _MIN_ESTIMATE, "floor"
        with _tel.span("queued", priority=pr):
            try:
                ticket = self.acquire(pr, est, seat=seat)
            except Exception as e:
                if _events_on():
                    from . import events as _ev
                    _ev.publish("sched.rejected", priority=pr,
                                est_bytes=int(est),
                                error=type(e).__name__)
                raise
            _tel.annotate(queued_ms=round(ticket.queued_ms or 0.0, 3),
                          reserved_bytes=ticket.reserved_bytes,
                          est_bytes=int(est), est_source=est_src)
        if _events_on():
            from . import events as _ev
            _ev.publish("sched.admitted", priority=pr,
                        queued_ms=round(ticket.queued_ms or 0.0, 3),
                        est_bytes=int(est), est_source=est_src)
        rt = _res.current()
        backoff0 = rt.backoff_s if rt is not None else 0.0
        _tls.ticket = ticket
        try:
            yield ticket
        finally:
            _tls.ticket = None
            if rt is not None:
                # retry-backoff sleep accrued WHILE holding this slot;
                # _release_locked subtracts it from the hold-time EWMA
                ticket.backoff_s = max(rt.backoff_s - backoff0, 0.0)
            self.release(ticket)


_MANAGER = WorkloadManager()


def get_manager() -> WorkloadManager:
    """The process-global workload manager (like the result cache: one
    ledger and one queue per process, shared by every Context)."""
    return _MANAGER
