"""Persistent cross-process program store: compiled stage executables on disk.

BENCH_r05's wall is compilation, not execution: 19-615 s warm/compile per
TPC-H query over the tunneled TPU, re-paid by EVERY fresh process, while
per-query execution is already sub-2 s.  The in-memory program cache
(physical/compiled.py ``_cache``) and the learned-caps file soften repeat
cost *within* a process lineage; this module removes the cross-process
bill entirely: a successfully compiled stage program is serialized (the
XLA executable itself, via ``jax.experimental.serialize_executable``) and
persisted under ``DSQL_PROGRAM_STORE``, so a restarted server or a brand
new process serves every previously-seen plan shape with ZERO XLA
recompilation — Flare's "never compile the same native program twice"
discipline (PAPERS.md) carried across process boundaries.

Keying.  An entry is addressed by a digest of the executor's *canonical*
program identity — the plan fingerprint with stage-boundary temp names
rewritten to position-stable placeholders (boundary names embed per-process
table uids, physical/compiled.py ``_stage_table_name``; the program itself
is uid-independent: it depends only on plan shape + input layout), the
input-layout fingerprint (shapes/dtypes/dictionary CONTENT), and the
backend strategy — folded with ``quarantine.device_fingerprint()`` and the
jax/jaxlib versions.  A program can therefore only ever be served to the
same plan shape over the same data layout on the same device class and
runtime version; DDL that changes a plan's shape or layout changes the
digest, and result staleness is impossible by construction (programs are
pure functions of their inputs — result freshness is the result cache's
catalog-epoch problem, not this store's).

Safety.  The serialized blob additionally embeds the fingerprint it was
built under and is verified again at load (belt and suspenders against
digest collisions or hand-copied entries); a mismatch rejects the entry
(``program_store_rejects``) and falls back to a normal compile.  Corrupt,
truncated, or undeserializable entries are tolerated the same way
(``program_store_errors``) and evicted.  Writes are atomic (tmp+rename);
the metadata index rides the shared kvstore plumbing (runtime/kvstore.py)
with read-merge-replace semantics, so concurrent processes can lose an
index race but never corrupt it.

Budget.  ``DSQL_PROGRAM_STORE_MB`` (default 512) bounds the payload bytes
on disk with a least-recently-used eviction over the index's ``used_at``
stamps (``program_store_evictions``).

Telemetry: ``program_store_hits`` / ``program_store_misses`` /
``program_store_stores`` / ``program_store_rejects`` /
``program_store_evictions`` / ``program_store_errors`` (stable-name
contract, runtime/telemetry.py).
"""
from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from typing import Dict, Optional

from . import kvstore as _kv
from . import telemetry as _tel

logger = logging.getLogger(__name__)

DEFAULT_BUDGET_MB = 512.0

_FORMAT_VERSION = 1
_INDEX_NAME = "index.json"


def _env_float(name: str, default: float) -> float:
    try:
        raw = os.environ.get(name, "")
        return float(raw) if raw else default
    except ValueError:
        return default


def runtime_fingerprint() -> Dict[str, str]:
    """Identity of the runtime a serialized executable is only valid for:
    device class + jax/jaxlib versions.  A deserialized XLA executable is
    NOT portable across any of these."""
    from . import quarantine as _quar

    try:
        import jax
        jax_v = getattr(jax, "__version__", "?")
    except Exception:  # pragma: no cover - jax always present in practice
        jax_v = "?"
    try:
        import jaxlib
        jaxlib_v = getattr(jaxlib, "__version__", "?")
    except Exception:  # pragma: no cover
        jaxlib_v = "?"
    try:
        import jax
        # executables are device-LAYOUT specific too: an SPMD program
        # compiled for an 8-device mesh cannot load on a 1-device process
        n_dev = str(jax.device_count())
    except Exception:  # pragma: no cover
        n_dev = "?"
    return {"device": _quar.device_fingerprint(), "devices": n_dev,
            "jax": jax_v, "jaxlib": jaxlib_v, "format": str(_FORMAT_VERSION)}


class ProgramStore:
    """Directory of serialized compiled programs + a JSON metadata index.

    Layout: ``<dir>/<digest>.prog`` (pickled entry dict) and
    ``<dir>/index.json`` ({digest: {bytes, used_at, stored_at}}).  One
    entry per program digest; re-stores (capacity-escalated recompiles)
    overwrite in place.
    """

    def __init__(self, path: Optional[str] = None):
        self._path_override = path
        self._lock = threading.Lock()
        self._index = _kv.MtimeCachedJsonFile(self._index_path)

    # -- config (env-read per call so tests/operators flip without restart)
    def path(self) -> Optional[str]:
        return self._path_override or os.environ.get("DSQL_PROGRAM_STORE")

    def enabled(self) -> bool:
        return bool(self.path())

    def budget_bytes(self) -> int:
        return int(max(_env_float("DSQL_PROGRAM_STORE_MB",
                                  DEFAULT_BUDGET_MB), 0.0) * (1 << 20))

    def _index_path(self) -> Optional[str]:
        p = self.path()
        return os.path.join(p, _INDEX_NAME) if p else None

    def _entry_path(self, digest: str) -> str:
        return os.path.join(self.path(), f"{digest}.prog")

    def digest(self, store_key) -> str:
        """Content address of a program: canonical program identity folded
        with the runtime fingerprint."""
        return _kv.digest_key((store_key,
                               tuple(sorted(runtime_fingerprint().items()))))

    # -- lookup -------------------------------------------------------------
    def contains(self, digest: str) -> bool:
        """Cheap presence probe (index only; used by the tier decision)."""
        if not self.enabled():
            return False
        return digest in self._index.read()

    def load(self, digest: str) -> Optional[dict]:
        """The stored entry dict, or None (miss / corrupt / fingerprint
        mismatch — all of which fall back to a normal compile)."""
        if not self.enabled():
            return None
        path = self._entry_path(digest)
        try:
            with open(path, "rb") as f:
                entry = pickle.load(f)
        except FileNotFoundError:
            _tel.inc("program_store_misses")
            return None
        except Exception as e:  # corrupt/truncated/unpicklable: evict it
            _tel.inc("program_store_errors")
            logger.warning("program store entry %s unreadable (%s); "
                           "dropping it", digest[:12], type(e).__name__)
            self._drop(digest)
            return None
        if not isinstance(entry, dict) \
                or entry.get("fingerprint") != runtime_fingerprint():
            # a different device class / jax version / format: the
            # executable bytes are not safe to load here
            _tel.inc("program_store_rejects")
            logger.warning("program store entry %s rejected: runtime "
                           "fingerprint mismatch", digest[:12])
            return None
        self._touch(digest)
        return entry

    # -- mutation -----------------------------------------------------------
    def store(self, digest: str, entry: dict) -> bool:
        """Persist ``entry`` (atomic write), update the index, and enforce
        the byte budget.  Best-effort: False on any failure."""
        if not self.enabled():
            return False
        entry = dict(entry)
        entry["fingerprint"] = runtime_fingerprint()
        try:
            os.makedirs(self.path(), exist_ok=True)
            blob = pickle.dumps(entry, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            _tel.inc("program_store_errors")
            logger.warning("program store serialize failed: %s", e)
            return False
        path = self._entry_path(digest)
        tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError as e:
            logger.debug("program store %s not writable: %s", path, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        now = time.time()
        rec = {"bytes": len(blob), "used_at": now, "stored_at": now}
        cost = entry.get("cost")
        if isinstance(cost, dict):
            # the profiler's XLA cost prediction rides the index too, so
            # system.programs answers "which stored programs are heavy"
            # without deserializing any payload
            rec["cost_flops"] = float(cost.get("flops", 0.0) or 0.0)
            rec["cost_bytes"] = float(cost.get("bytes", 0.0) or 0.0)
        with self._lock:
            index = self._index.read()
            index[digest] = rec
            index = self._evict_locked(index, keep=digest)
            self._index.write(index)
        _tel.inc("program_store_stores")
        return True

    def _touch(self, digest: str) -> None:
        """LRU recency stamp on a hit (best-effort)."""
        with self._lock:
            index = self._index.read()
            e = index.get(digest)
            if e is not None:
                e["used_at"] = time.time()
                index[digest] = e
                self._index.write(index)

    def _drop(self, digest: str) -> None:
        try:
            os.unlink(self._entry_path(digest))
        except OSError:
            pass
        with self._lock:
            index = self._index.read()
            if digest in index:
                del index[digest]
                self._index.write(index)

    def _evict_locked(self, index: Dict[str, dict], keep: str
                      ) -> Dict[str, dict]:
        """Drop least-recently-used entries until the payload fits the
        byte budget (the newest store is never its own victim)."""
        budget = self.budget_bytes()
        total = sum(int(e.get("bytes", 0)) for e in index.values())
        if total <= budget:
            return index
        order = sorted((d for d in index if d != keep),
                       key=lambda d: float(index[d].get("used_at", 0)))
        for d in order:
            if total <= budget:
                break
            total -= int(index[d].get("bytes", 0))
            del index[d]
            try:
                os.unlink(self._entry_path(d))
            except OSError:
                pass
            _tel.inc("program_store_evictions")
        return index

    # -- introspection ------------------------------------------------------
    def entries(self) -> Dict[str, dict]:
        return self._index.read()

    def total_bytes(self) -> int:
        return sum(int(e.get("bytes", 0)) for e in self._index.read().values())


_store = ProgramStore()


def get_store() -> ProgramStore:
    """The process-global program store (env-configured, like the result
    cache, scheduler, and quarantine store)."""
    return _store
