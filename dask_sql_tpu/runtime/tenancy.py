"""Multi-tenant admission armor: identity, quotas, circuit breakers.

The paper's end-state is a compiled engine serving heterogeneous
production traffic (Flare, PAPERS.md); production traffic is MULTI-TENANT
traffic, and without per-tenant isolation one hostile client starves
everyone through the shared admission queue.  This module gives every
query a tenant identity and enforces three per-tenant policies at the
admission boundary, BEFORE the workload manager spends a slot or queue
position on the query:

**Identity.**  The server's ``X-DSQL-Tenant`` header or
``Context.sql(tenant=)``, sanitized to the trace-ID charset
(``[A-Za-z0-9_-]``, ≤64 chars — header injection and metric-name abuse
both die here); everything else maps to the ``"default"`` tenant, so
single-tenant deployments see no behavioral change.

**Token-bucket rate quota.**  ``DSQL_TENANT_QPS`` tokens/second per
tenant with a one-second burst; an empty bucket raises the typed
``TenantQuotaExceeded`` (HTTP 429) with ``Retry-After`` derived from the
actual refill time — honest backpressure, not a constant.

**Concurrency quota.**  ``DSQL_TENANT_CONCURRENT`` outstanding queries
per tenant (claimed at POST/submit, released at completion) — a tenant
can saturate its own share and nothing more.

**Circuit breaker.**  ``DSQL_TENANT_BREAKER`` CONSECUTIVE fatal/timeout
verdicts trip the tenant's breaker open for
``DSQL_TENANT_BREAKER_TTL_S``: further admissions raise the typed
``TenantCircuitOpen`` immediately (the tenant's failure loop must not
keep burning engine slots).  On expiry the breaker goes half-open on the
quarantine pattern (runtime/quarantine.py): exactly ONE probe query is
admitted (the expiry is pushed out by ``DSQL_TENANT_BREAKER_PROBE_S`` so
concurrent calls keep rejecting); a clean probe closes the breaker, a
failed one re-arms the full TTL.

All three quotas default OFF (0 = unlimited / no breaker), so the module
being importable changes nothing until an operator arms a knob; the
``DSQL_TENANCY=0`` kill switch additionally keeps the module un-imported
everywhere (env-gate-before-import, like the watchtower) and restores
pre-PR behavior exactly.  Enforcement has ONE call site per path:
``admission()`` wraps ``Context._execute_query_plan`` (direct SQL), and
the server pre-claims at POST time via ``claim()`` + ``grant_scope`` so
a rejected tenant gets its 429 before the query ever enters the pool —
the pre-claim is consumed by ``admission()`` exactly once, mirroring the
scheduler's seat pre-claims.
"""
from __future__ import annotations

import logging
import os
import string
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from . import telemetry as _tel
from .resilience import (AdmissionTimeout, DeadlineExceeded, FatalError,
                         TenantCircuitOpen, TenantQuotaExceeded)

logger = logging.getLogger(__name__)

DEFAULT_TENANT = "default"

_TENANT_CHARS = frozenset(string.ascii_letters + string.digits + "_-")
_MAX_TENANT_LEN = 64


def enabled() -> bool:
    """Subsystem gate: callers check this BEFORE importing the module
    (``DSQL_TENANCY=0`` keeps tenancy bit-for-bit absent)."""
    return os.environ.get("DSQL_TENANCY", "1").strip() not in ("", "0")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# env-read per call (like the scheduler's knobs) so tests and operators
# flip quotas without a restart; 0 = unlimited / breaker off
def qps_limit() -> float:
    return max(_env_float("DSQL_TENANT_QPS", 0.0), 0.0)


def concurrent_limit() -> int:
    return max(_env_int("DSQL_TENANT_CONCURRENT", 0), 0)


def breaker_threshold() -> int:
    return max(_env_int("DSQL_TENANT_BREAKER", 0), 0)


def breaker_ttl_s() -> float:
    return max(_env_float("DSQL_TENANT_BREAKER_TTL_S", 30.0), 0.1)


def breaker_probe_s() -> float:
    return max(_env_float("DSQL_TENANT_BREAKER_PROBE_S", 5.0), 0.1)


def sanitize_tenant(raw: Any) -> Optional[str]:
    """A safe tenant name ([A-Za-z0-9_-], ≤64 chars) or None.  Same
    charset discipline as events.sanitize_trace_id: the name travels in
    response payloads, log lines and gauge names."""
    if raw is None:
        return None
    s = str(raw).strip()
    if not s or len(s) > _MAX_TENANT_LEN:
        return None
    if not all(c in _TENANT_CHARS for c in s):
        return None
    return s


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class Grant:
    """One admitted claim against a tenant's quotas.  ``consumed`` flips
    when ``admission()`` adopts a server pre-claim (exactly once, like a
    scheduler seat); ``released`` makes release idempotent."""

    __slots__ = ("tenant", "probe", "consumed", "released")

    def __init__(self, tenant: str, probe: bool = False):
        self.tenant = tenant
        self.probe = probe
        self.consumed = False
        self.released = False


class _TenantState:
    __slots__ = ("name", "tokens", "stamp", "inflight", "consec",
                 "open_until", "probing", "submitted", "admitted",
                 "completed", "failed", "quota_rejects", "circuit_rejects",
                 "opens")

    def __init__(self, name: str):
        self.name = name
        self.tokens = max(qps_limit(), 1.0)   # start with a full bucket
        self.stamp = time.monotonic()
        self.inflight = 0
        self.consec = 0
        self.open_until: Optional[float] = None
        self.probing = False
        self.submitted = 0
        self.admitted = 0
        self.completed = 0
        self.failed = 0
        self.quota_rejects = 0
        self.circuit_rejects = 0
        self.opens = 0

    def circuit(self, now: float) -> str:
        if self.open_until is None:
            return "closed"
        if self.probing:
            return "half-open"
        return "open" if now < self.open_until else "half-open"


class TenantRegistry:
    """Process-global per-tenant state (one lock — claim/release are a
    few arithmetic ops; never held across I/O or other locks)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantState] = {}

    def _state_locked(self, name: str) -> _TenantState:
        st = self._tenants.get(name)
        if st is None:
            st = _TenantState(name)
            self._tenants[name] = st
            _tel.REGISTRY.set_gauge("tenants_known", len(self._tenants))
        return st

    # -- admission ----------------------------------------------------------
    def claim(self, tenant: Optional[str]) -> Grant:
        """Claim one admission against ``tenant``'s quotas; raises the
        typed verdict (TenantCircuitOpen / TenantQuotaExceeded) or
        returns a Grant whose release the caller owes."""
        name = sanitize_tenant(tenant) or DEFAULT_TENANT
        now = time.monotonic()
        with self._lock:
            st = self._state_locked(name)
            st.submitted += 1
            _tel.inc("tenant_queries")
            # circuit breaker first: an open breaker rejects before any
            # token is spent, a half-open one admits exactly one probe
            probe = False
            if breaker_threshold() > 0 and st.open_until is not None:
                if now < st.open_until and not st.probing:
                    st.circuit_rejects += 1
                    _tel.inc("tenant_circuit_rejects")
                    raise TenantCircuitOpen(
                        f"tenant {name!r} circuit open "
                        f"({st.consec} consecutive failures); probing in "
                        f"{st.open_until - now:.1f} s",
                        retry_after_s=st.open_until - now)
                if st.probing:
                    # a probe is already in flight; keep rejecting until
                    # its verdict lands (quarantine half-open semantics)
                    st.circuit_rejects += 1
                    _tel.inc("tenant_circuit_rejects")
                    raise TenantCircuitOpen(
                        f"tenant {name!r} circuit half-open (probe in "
                        "flight)",
                        retry_after_s=max(st.open_until - now, 0.5))
                # expired: go half-open — this caller becomes THE probe,
                # the window is pushed out so concurrent claims reject
                st.open_until = now + breaker_probe_s()
                st.probing = True
                probe = True
                _tel.inc("tenant_circuit_probes")
            # token-bucket rate quota (burst = one second of tokens)
            qps = qps_limit()
            if qps > 0:
                cap = max(qps, 1.0)
                # max(elapsed, 0): a state created inside this call
                # stamped AFTER ``now`` was captured — the bucket must
                # not lose tokens to a negative refill
                st.tokens = min(st.tokens + max(now - st.stamp, 0.0) * qps,
                                cap)
                st.stamp = now
                if st.tokens < 1.0:
                    st.quota_rejects += 1
                    _tel.inc("tenant_quota_rejects")
                    raise TenantQuotaExceeded(
                        f"tenant {name!r} over rate quota "
                        f"({qps:g} qps)",
                        retry_after_s=(1.0 - st.tokens) / qps)
                st.tokens -= 1.0
            else:
                st.stamp = now
            # concurrency quota
            climit = concurrent_limit()
            if climit > 0 and st.inflight >= climit:
                st.quota_rejects += 1
                _tel.inc("tenant_quota_rejects")
                raise TenantQuotaExceeded(
                    f"tenant {name!r} at concurrency limit "
                    f"({st.inflight} >= {climit})", retry_after_s=1.0)
            st.inflight += 1
            st.admitted += 1
        return Grant(name, probe=probe)

    def release(self, grant: Optional[Grant],
                outcome: Optional[str] = None) -> None:
        """Return a grant.  ``outcome`` is ``"ok"`` / ``"fatal"`` /
        ``"timeout"`` / ``"error"`` for an executed query, or None for a
        claim that never executed a plan (DDL, pre-execution failure) —
        those feed neither the breaker nor the completion counts.
        Idempotent."""
        if grant is None or grant.released:
            return
        grant.released = True
        opened = False
        with self._lock:
            st = self._state_locked(grant.tenant)
            st.inflight = max(st.inflight - 1, 0)
            if outcome is None:
                return
            st.completed += 1
            if outcome == "ok":
                st.consec = 0
                if st.open_until is not None:
                    # clean probe (or a straggler admitted pre-trip that
                    # finished fine): close the breaker
                    st.open_until = None
                    st.probing = False
            elif outcome in ("fatal", "timeout"):
                st.failed += 1
                st.consec += 1
                thresh = breaker_threshold()
                if thresh > 0 and (grant.probe
                                   or (st.consec >= thresh
                                       and st.open_until is None)):
                    # trip (or re-arm after a failed probe) for the full
                    # TTL; the next claim past expiry goes half-open
                    st.open_until = time.monotonic() + breaker_ttl_s()
                    st.probing = False
                    st.opens += 1
                    opened = True
                    _tel.inc("tenant_circuit_opens")
            else:
                # user errors / transient verdicts do not trip (the
                # breaker watches fatal/timeout streaks), but a failed
                # probe of EITHER kind ends the probe window
                st.failed += 1
                if grant.probe:
                    st.probing = False
        if opened:
            logger.warning(
                "tenant %r circuit OPEN (%d consecutive fatal/timeout "
                "verdicts); rejecting for %.0f s", grant.tenant,
                breaker_threshold(), breaker_ttl_s())
            if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
                try:
                    from . import events as _ev
                    _ev.publish("tenant.circuit_open", tenant=grant.tenant,
                                ttl_s=round(breaker_ttl_s(), 1))
                except Exception:
                    pass

    # -- introspection ------------------------------------------------------
    def rows(self) -> List[dict]:
        """One row per known tenant (``system.tenants``)."""
        now = time.monotonic()
        with self._lock:
            return [{
                "tenant": st.name,
                "inflight": st.inflight,
                "tokens": round(st.tokens, 3),
                "submitted": st.submitted,
                "admitted": st.admitted,
                "completed": st.completed,
                "failed": st.failed,
                "quota_rejects": st.quota_rejects,
                "circuit_rejects": st.circuit_rejects,
                "circuit_opens": st.opens,
                "consecutive_failures": st.consec,
                "circuit": st.circuit(now),
            } for _, st in sorted(self._tenants.items())]

    def snapshot(self) -> dict:
        """Compact section for ``GET /v1/engine``."""
        now = time.monotonic()
        with self._lock:
            return {
                "enabled": True,
                "tenants": len(self._tenants),
                "inflight": sum(st.inflight
                                for st in self._tenants.values()),
                "open_circuits": sum(
                    1 for st in self._tenants.values()
                    if st.circuit(now) != "closed"),
            }

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._tenants.clear()
            _tel.REGISTRY.set_gauge("tenants_known", 0)


_REGISTRY_LOCK = threading.Lock()
_REGISTRY: Optional[TenantRegistry] = None


def get_registry() -> TenantRegistry:
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = TenantRegistry()
        return _REGISTRY


def tenant_rows() -> List[dict]:
    return get_registry().rows()


# ---------------------------------------------------------------------------
# thread-local scopes + the one enforcement site
# ---------------------------------------------------------------------------

class _Tls(threading.local):
    tenant: Optional[str] = None     # explicit tenant name for this thread
    grant: Optional[Grant] = None    # server POST-time pre-claim
    active: bool = False             # an admission() scope is open


_tls = _Tls()


def current_tenant() -> Optional[str]:
    return _tls.tenant


@contextmanager
def tenant_scope(tenant: Optional[str]):
    """Install an explicit tenant name for this thread
    (``Context.sql(tenant=)``).  Invalid names raise ValueError — a user
    API must not silently coerce garbage into ``default``."""
    if tenant is not None and sanitize_tenant(tenant) is None:
        raise ValueError(
            f"invalid tenant name {tenant!r} (allowed: [A-Za-z0-9_-], "
            f"max {_MAX_TENANT_LEN} chars)")
    prev = _tls.tenant
    _tls.tenant = sanitize_tenant(tenant)
    try:
        yield
    finally:
        _tls.tenant = prev


@contextmanager
def grant_scope(grant: Optional[Grant]):
    """Install a server POST-time pre-claim for the worker thread;
    ``admission()`` consumes it exactly once (scheduler-seat pattern)."""
    prev_g, prev_t = _tls.grant, _tls.tenant
    _tls.grant = grant
    if grant is not None:
        _tls.tenant = grant.tenant
    try:
        yield
    finally:
        _tls.grant, _tls.tenant = prev_g, prev_t


def _classify_outcome(exc: BaseException) -> str:
    if isinstance(exc, FatalError):
        return "fatal"
    if isinstance(exc, (DeadlineExceeded, AdmissionTimeout)):
        return "timeout"
    return "error"


@contextmanager
def admission():
    """Enforce the tenant's quotas around one executing query plan — the
    single call site is ``Context._execute_query_plan``, wrapping the
    scheduler's admission (a tenant reject must not consume a scheduler
    slot or queue position).  Nested plans ride the outer claim; a server
    pre-claim (``grant_scope``) is adopted instead of re-claiming, so
    the POST-time token is the only token spent."""
    if _tls.active:
        yield None
        return
    grant, _tls.grant = _tls.grant, None    # consume the pre-claim once
    if grant is None:
        grant = get_registry().claim(_tls.tenant)   # may raise typed
    grant.consumed = True
    # stamp the tenant on the trace root (explicit tenants only) so the
    # QueryReport / flight-recorder envelope / slow-query log carry it;
    # default-tenant queries leave every envelope byte-identical
    if grant.tenant != DEFAULT_TENANT:
        tr = _tel.current_trace()
        if tr is not None:
            tr.root.attrs.setdefault("tenant", grant.tenant)
    _tls.active = True
    outcome = "ok"
    try:
        yield grant
    except BaseException as e:
        outcome = _classify_outcome(e)
        raise
    finally:
        _tls.active = False
        get_registry().release(grant, outcome)
