"""Engine watchtower: trace IDs, a structured event bus, SLO burn rates.

The engine's telemetry so far is per-query-after-the-fact: QueryReports
and flight-recorder envelopes describe what happened, but nothing
*correlates* the hops one request takes (server POST -> admission ->
tiers -> SPMD stages -> spill -> result), nothing judges latency against
per-class objectives, and the only live view is polling ``GET
/v1/engine``.  This module is the correlation-and-objectives layer
(ROADMAP item 4's observability prerequisite); three concerns live here:

**Trace IDs.**  :func:`mint_trace_id` mints a short hex ID at ingress;
the server accepts/returns ``X-DSQL-Trace`` (client-supplied IDs are
sanitized to ``[A-Za-z0-9_-]{1,64}``) and installs it for the worker via
:func:`trace_id_scope`.  ``telemetry.trace_scope`` stamps it on the span
tree root (``trace_id`` attr), from where it flows into the QueryReport,
the slow-query log, the chrome-trace export, and the flight-recorder
envelope.  Cross-process propagation (bench children, tests) rides the
``DSQL_TRACE_ID`` env var — :func:`current_trace_id` resolves
thread-local scope > open trace > env, in that order.

**Event bus.**  :func:`publish` appends ``{seq, unix, pid, trace, type,
...fields}`` records to a bounded in-memory ring (``DSQL_EVENTS_RING``,
default 2048) with a monotonic cursor and a condition variable for
long-polling (``GET /v1/events``, :func:`read_since`).  When
``DSQL_EVENTS_FILE`` is set every record also lands in a crash-tolerant
JSONL ring — O_APPEND single-write lines, newest-half truncation at
``DSQL_EVENTS_MB`` (default 4) via tmp + ``os.replace`` — the exact
flight-recorder discipline, so ``system.events`` correlates across
processes.  Publish failures count ``events_dropped`` and never fail the
caller.

**SLO monitor.**  Per-priority-class latency objectives
(``DSQL_SLO_INTERACTIVE_MS``/``_BATCH_MS``/``_BACKGROUND_MS``, defaults
1000/10000/60000) against an attainment target (``DSQL_SLO_TARGET``,
default 0.99).  Every query completion folds into per-class sample
windows; burn rate = (breach fraction over the window) / (1 - target),
computed over a fast (``DSQL_SLO_FAST_S``, 300) and a slow
(``DSQL_SLO_SLOW_S``, 3600) window — the classic multi-window alert: a
burn rate of 1.0 spends the error budget exactly at the sustainable
pace; both windows above ``DSQL_SLO_BURN`` (2.0) is a breach (counter
``slo_breaches`` + edge-triggered ``slo.breach`` event).  Surfaced as
``slo_*`` gauges, ``system.slo`` rows, and the ``slo`` section (with
:func:`anomalies` flags) on ``GET /v1/engine``.

**Zero cost when disabled.**  Like the flight recorder and profiler:
every hot-path caller checks ``DSQL_EVENTS`` BEFORE importing this
module (tests assert it never lands in ``sys.modules`` for an unarmed
query), responses carry no trace headers, and ``GET /v1/events`` falls
through to the generic 404.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry as _tel

logger = logging.getLogger(__name__)

_TRACE_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-")


def enabled() -> bool:
    """True when the watchtower is armed (``DSQL_EVENTS`` set, not 0)."""
    return os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0")


def _env_float(name: str, default: float) -> float:
    try:
        raw = os.environ.get(name, "")
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        raw = os.environ.get(name, "")
        return int(raw) if raw else default
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# trace IDs
# ---------------------------------------------------------------------------

class _Tls(threading.local):
    trace_id: Optional[str] = None


_tls = _Tls()


def mint_trace_id() -> str:
    """A fresh ingress trace ID: 16 hex chars, unique enough to join the
    three surfaces (wire, span tree, event/history rings) of one query."""
    return uuid.uuid4().hex[:16]


def sanitize_trace_id(raw: Any) -> Optional[str]:
    """A client-supplied ``X-DSQL-Trace`` value, validated — or None.
    IDs are reflected into headers, log lines and JSONL rings, so the
    charset is locked down and the length capped."""
    if not raw:
        return None
    s = str(raw).strip()
    if not s or len(s) > 64 or not all(c in _TRACE_CHARS for c in s):
        return None
    return s


def current_trace_id() -> Optional[str]:
    """The trace ID in effect on THIS thread: explicit scope first
    (server worker), then the open trace's stamped root attr (stage
    workers re-entering via ``telemetry.scoped``), then the
    ``DSQL_TRACE_ID`` env fallback (child processes)."""
    tid = _tls.trace_id
    if tid:
        return tid
    t = _tel.current_trace()
    if t is not None:
        tid = t.root.attrs.get("trace_id")
        if tid:
            return str(tid)
    return sanitize_trace_id(os.environ.get("DSQL_TRACE_ID"))


@contextmanager
def trace_id_scope(tid: Optional[str]):
    """Install a trace ID on this thread for the duration (the server
    wraps each worker's ``context.sql`` in one)."""
    prev = _tls.trace_id
    _tls.trace_id = sanitize_trace_id(tid)
    try:
        yield _tls.trace_id
    finally:
        _tls.trace_id = prev


# ---------------------------------------------------------------------------
# the event bus
# ---------------------------------------------------------------------------

def ring_len() -> int:
    return max(_env_int("DSQL_EVENTS_RING", 2048), 16)


def events_file() -> Optional[str]:
    """The cross-process JSONL ring path, or None (in-memory only)."""
    return os.environ.get("DSQL_EVENTS_FILE") or None


def file_limit_bytes() -> int:
    return max(int(_env_float("DSQL_EVENTS_MB", 4.0) * 2**20), 4096)


class EventBus:
    """Bounded in-memory event ring with a monotonic seq cursor.

    ``publish`` appends under the condition variable and notifies
    long-poll waiters; the deque's maxlen bounds memory, the seq keeps
    cursors valid across evictions (a reader slower than the ring simply
    skips what was evicted)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._ring: deque = deque(maxlen=ring_len())
        self._seq = 0

    def append(self, rec: dict) -> dict:
        with self._cond:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            self._cond.notify_all()
        return rec

    def last_seq(self) -> int:
        with self._cond:
            return self._seq

    def snapshot(self) -> List[dict]:
        with self._cond:
            return list(self._ring)

    def read_since(self, cursor: int, limit: int = 500,
                   timeout_s: float = 0.0) -> Tuple[List[dict], int]:
        """Events with ``seq > cursor`` (oldest first, capped at
        ``limit``) and the next cursor.  With ``timeout_s`` > 0 blocks
        until at least one event arrives or the deadline passes — the
        ``GET /v1/events`` long-poll."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        with self._cond:
            while True:
                evs = [e for e in self._ring if e["seq"] > cursor]
                if evs:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            evs = evs[:max(int(limit), 1)]
            nxt = evs[-1]["seq"] if evs else max(int(cursor), 0)
            return evs, nxt


_BUS_LOCK = threading.Lock()
_BUS: Optional[EventBus] = None


def get_bus() -> EventBus:
    global _BUS
    with _BUS_LOCK:
        if _BUS is None:
            _BUS = EventBus()
        return _BUS


# serializes THIS process's file appends; cross-process interleaving is
# handled by O_APPEND single-write lines + atomic replace (flight-recorder
# concurrency model)
_FILE_LOCK = threading.Lock()


def _append_file(path: str, rec: dict) -> None:
    line = (json.dumps(rec, separators=(",", ":"), default=str) + "\n"
            ).encode()
    with _FILE_LOCK:
        fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        try:
            os.write(fd, line)
            size = os.fstat(fd).st_size
        finally:
            os.close(fd)
    if size > file_limit_bytes():
        _truncate_file(path)


def _truncate_file(path: str) -> None:
    """Drop the oldest half via tmp + atomic replace; a writer racing the
    replace can lose a few lines (events are advisory), never corrupt."""
    limit = file_limit_bytes()
    with _FILE_LOCK:
        try:
            with open(path, "rb") as f:
                lines = f.readlines()
            kept: List[bytes] = []
            budget = limit // 2
            total = 0
            for raw in reversed(lines):
                total += len(raw)
                if total > budget:
                    break
                kept.append(raw)
            kept.reverse()
            tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.writelines(kept)
            os.replace(tmp, path)
        except OSError:
            logger.debug("event ring truncation failed", exc_info=True)


def _read_file(path: str) -> List[dict]:
    try:
        with open(path, "rb") as f:
            lines = f.readlines()
    except OSError:
        return []
    out: List[dict] = []
    for raw in lines:
        try:
            rec = json.loads(raw)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


_CORE_FIELDS = ("seq", "unix", "pid", "trace", "type", "replica")


def _fleet_replica() -> Optional[str]:
    """This process's replica id when the fleet plane (runtime/fleet.py)
    is armed, else None — env checked BEFORE the import, so an unarmed
    process never loads the fleet module and unarmed events stay
    byte-identical (no ``replica`` field at all)."""
    if not os.environ.get("DSQL_FLEET_DIR"):
        return None
    from . import fleet as _fleet
    return _fleet.replica_id()


def publish(etype: str, **fields) -> Optional[dict]:
    """Publish one structured event; never raises (a failed publish
    counts ``events_dropped`` and the caller proceeds).  ``trace`` may be
    passed explicitly; otherwise the thread's current trace ID rides
    along.  Callers gate on ``DSQL_EVENTS`` before importing."""
    try:
        tid = fields.pop("trace", None) or current_trace_id()
        rec: Dict[str, Any] = {"unix": round(time.time(), 3),
                               "pid": os.getpid(),
                               "trace": str(tid) if tid else "",
                               "type": str(etype)}
        rid = _fleet_replica()
        if rid:
            rec["replica"] = rid
        for k, v in fields.items():
            if v is not None and k not in _CORE_FIELDS:
                rec[k] = v
        get_bus().append(rec)
        _tel.inc("events_published")
        path = events_file()
        if path:
            _append_file(path, rec)
        return rec
    except Exception:
        _tel.inc("events_dropped")
        logger.debug("event publish failed", exc_info=True)
        return None


def read_since(cursor: int, limit: int = 500,
               timeout_s: float = 0.0) -> Tuple[List[dict], int]:
    return get_bus().read_since(cursor, limit=limit, timeout_s=timeout_s)


def events_rows(limit: int = 2000) -> List[dict]:
    """Rows for ``system.events``: the cross-process file ring when
    armed (all processes' events, this one's included), else this
    process's in-memory ring.  Extra fields compact into ``detail``."""
    path = events_file()
    recs = _read_file(path) if path else get_bus().snapshot()
    rows: List[dict] = []
    for rec in recs[-max(int(limit), 1):]:
        extra = {k: v for k, v in rec.items() if k not in _CORE_FIELDS}
        row = {
            "seq": int(rec.get("seq", 0) or 0),
            "unix": float(rec.get("unix", 0.0) or 0.0),
            "pid": int(rec.get("pid", 0) or 0),
            "trace": str(rec.get("trace", "") or ""),
            "type": str(rec.get("type", "") or ""),
            "detail": (json.dumps(extra, separators=(",", ":"),
                                  default=str, sort_keys=True)
                       if extra else ""),
        }
        # stamped only when a fleet replica published it — unarmed rows
        # keep the historical key set
        if rec.get("replica"):
            row["replica"] = str(rec["replica"])
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------

SLO_CLASSES = ("interactive", "batch", "background")
_DEFAULT_OBJECTIVE_MS = {"interactive": 1000.0, "batch": 10000.0,
                         "background": 60000.0}
#: per-class sample window capacity; at 4096 completions per class the
#: oldest samples age out of BOTH time windows long before eviction
#: matters at any sustainable query rate
_SAMPLES_PER_CLASS = 4096


def objective_ms(cls: str) -> float:
    return max(_env_float(f"DSQL_SLO_{cls.upper()}_MS",
                          _DEFAULT_OBJECTIVE_MS.get(cls, 1000.0)), 1.0)


def slo_target() -> float:
    t = _env_float("DSQL_SLO_TARGET", 0.99)
    return min(max(t, 0.5), 0.9999)


def window_fast_s() -> float:
    return max(_env_float("DSQL_SLO_FAST_S", 300.0), 0.1)


def window_slow_s() -> float:
    return max(_env_float("DSQL_SLO_SLOW_S", 3600.0), window_fast_s())


def burn_threshold() -> float:
    return max(_env_float("DSQL_SLO_BURN", 2.0), 0.1)


class SloMonitor:
    """Per-priority-class latency objectives as multi-window burn rates.

    One (unix, ok) sample per completed query; burn rate over a window =
    breach_fraction / error_budget where error_budget = 1 - target.
    Gauges update on every observation so ``GET /metrics`` is always
    current without a sampler thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: Dict[str, deque] = {
            c: deque(maxlen=_SAMPLES_PER_CLASS) for c in SLO_CLASSES}
        self._totals: Dict[str, List[int]] = {
            c: [0, 0] for c in SLO_CLASSES}          # [total, breaches]
        self._breached: Dict[str, bool] = {c: False for c in SLO_CLASSES}

    @staticmethod
    def _class(priority: Optional[str]) -> str:
        c = str(priority or "interactive").strip().lower()
        return c if c in SLO_CLASSES else "interactive"

    def observe(self, priority: Optional[str], wall_ms: float) -> None:
        cls = self._class(priority)
        obj = objective_ms(cls)
        ok = float(wall_ms) <= obj
        now = time.time()
        with self._lock:
            self._samples[cls].append((now, ok))
            tot = self._totals[cls]
            tot[0] += 1
            if not ok:
                tot[1] += 1
        burn_f, burn_s = self._burns(cls, now)
        att = self._attainment(cls)
        _tel.REGISTRY.set_gauge(f"slo_attainment_{cls}", round(att, 6))
        _tel.REGISTRY.set_gauge(f"slo_burn_fast_{cls}", round(burn_f, 6))
        _tel.REGISTRY.set_gauge(f"slo_burn_slow_{cls}", round(burn_s, 6))
        # edge-triggered multi-window breach: both windows burning past
        # the threshold fires ONE event until the condition clears
        thresh = burn_threshold()
        breach = burn_f > thresh and burn_s > thresh
        with self._lock:
            fire = breach and not self._breached[cls]
            self._breached[cls] = breach
        if fire:
            _tel.inc("slo_breaches")
            publish("slo.breach", cls=cls, objective_ms=obj,
                    burn_fast=round(burn_f, 3), burn_slow=round(burn_s, 3))

    def _burns(self, cls: str, now: float) -> Tuple[float, float]:
        budget = max(1.0 - slo_target(), 1e-6)
        with self._lock:
            samples = list(self._samples[cls])
        out = []
        for win in (window_fast_s(), window_slow_s()):
            inwin = [ok for (t, ok) in samples if now - t <= win]
            if not inwin:
                out.append(0.0)
                continue
            frac = sum(1 for ok in inwin if not ok) / len(inwin)
            out.append(frac / budget)
        return out[0], out[1]

    def _attainment(self, cls: str) -> float:
        with self._lock:
            total, breaches = self._totals[cls]
        if total <= 0:
            return 1.0
        return (total - breaches) / total

    def breached_classes(self) -> List[str]:
        with self._lock:
            return [c for c in SLO_CLASSES if self._breached[c]]

    def burning_classes(self) -> List[str]:
        """Classes whose fast AND slow burns exceed the threshold RIGHT
        NOW, recomputed from the sample windows.  The ``_breached`` flags
        only update when a query of that class completes — a class that
        stops completing queries would stay flagged forever — so the
        load shedder (runtime/scheduler.py) must use this live view: as
        the breaching samples age out of the windows the burns fall and
        shedding lifts on its own."""
        now = time.time()
        thresh = burn_threshold()
        out = []
        for cls in SLO_CLASSES:
            burn_f, burn_s = self._burns(cls, now)
            if burn_f > thresh and burn_s > thresh:
                out.append(cls)
        return out

    def rows(self) -> List[dict]:
        """One row per class for ``system.slo`` / the engine section."""
        now = time.time()
        rows = []
        for cls in SLO_CLASSES:
            burn_f, burn_s = self._burns(cls, now)
            with self._lock:
                total, breaches = self._totals[cls]
                breached = self._breached[cls]
            rows.append({
                "class": cls,
                "objective_ms": objective_ms(cls),
                "target": slo_target(),
                "window_fast_s": window_fast_s(),
                "window_slow_s": window_slow_s(),
                "total": total,
                "breaches": breaches,
                "attainment": round(self._attainment(cls), 6),
                "burn_fast": round(burn_f, 6),
                "burn_slow": round(burn_s, 6),
                "breach": breached,
            })
        return rows


_MONITOR_LOCK = threading.Lock()
_MONITOR: Optional[SloMonitor] = None


def get_monitor() -> SloMonitor:
    global _MONITOR
    with _MONITOR_LOCK:
        if _MONITOR is None:
            _MONITOR = SloMonitor()
        return _MONITOR


def slo_rows() -> List[dict]:
    return get_monitor().rows()


# ---------------------------------------------------------------------------
# anomaly flags
# ---------------------------------------------------------------------------

#: (unix, compile_errors, spill_churn) counter samples — one per query
#: completion — so compile-error/spill deltas over the fast window need
#: no sampler thread; bounded like the profiler's snapshot ring
_counter_ring: deque = deque(maxlen=512)
_counter_lock = threading.Lock()


def _sample_counters(now: float) -> None:
    c = _tel.REGISTRY.counters()
    with _counter_lock:
        _counter_ring.append((now,
                              int(c.get("compile_errors", 0)),
                              int(c.get("spill_demotions", 0))
                              + int(c.get("spill_loads", 0))))


def _window_delta(idx: int, now: float) -> int:
    """Delta of counter-sample column ``idx`` over the fast window."""
    win = window_fast_s()
    with _counter_lock:
        samples = [s for s in _counter_ring if now - s[0] <= win]
    if len(samples) < 2:
        return 0
    return samples[-1][idx] - samples[0][idx]


def anomalies() -> List[dict]:
    """Live anomaly flags for ``GET /v1/engine``; empty list = healthy.
    Each flag names its evidence so an operator can act without a
    follow-up query."""
    out: List[dict] = []
    now = time.time()
    _sample_counters(now)
    for cls in get_monitor().breached_classes():
        out.append({"kind": "burn_rate_breach", "cls": cls,
                    "detail": f"{cls} burning error budget past "
                              f"{burn_threshold():g}x on both windows"})
    try:
        from . import scheduler as _sched
        mgr = _sched.get_manager()
        if mgr.enabled():
            depth = int(mgr.queue_depth())
            cap = int(mgr.limit()) + int(mgr.depth())
            if cap > 0 and depth >= max(int(0.8 * cap), 1):
                out.append({"kind": "queue_depth_runaway", "depth": depth,
                            "capacity": cap,
                            "detail": f"admission queue at {depth}/{cap}"})
    except Exception:
        logger.debug("queue anomaly probe failed", exc_info=True)
    spike = _window_delta(1, now)
    if spike >= _env_int("DSQL_EVENTS_COMPILE_SPIKE", 3):
        out.append({"kind": "compile_error_spike", "errors": spike,
                    "detail": f"{spike} compile errors within "
                              f"{window_fast_s():g}s"})
    thrash = _window_delta(2, now)
    if thrash >= _env_int("DSQL_EVENTS_SPILL_THRASH", 32):
        out.append({"kind": "spill_thrash", "moves": thrash,
                    "detail": f"{thrash} spill tier moves within "
                              f"{window_fast_s():g}s"})
    return out


def engine_section() -> dict:
    """The ``slo`` section of ``GET /v1/engine`` (imported only when
    ``DSQL_EVENTS`` is armed, mirroring the profiler's section)."""
    return {
        "enabled": True,
        "classes": slo_rows(),
        "anomalies": anomalies(),
        "bus": {"seq": get_bus().last_seq(),
                "ring": ring_len(),
                "file": events_file() or ""},
    }


# ---------------------------------------------------------------------------
# telemetry hooks (trace_scope / _close_trace call these after the gate)
# ---------------------------------------------------------------------------

def on_trace_open(trace) -> None:
    """Stamp the ingress trace ID on a freshly opened trace root and
    publish ``query.begin``.  The ID resolves scope > env > fresh mint,
    so a server-minted or child-process-propagated ID wins over a new
    one and a bare ``Context.sql`` still gets correlated."""
    tid = current_trace_id() or mint_trace_id()
    trace.root.attrs["trace_id"] = tid
    publish("query.begin", trace=tid, query=trace.query.strip()[:200])


#: per-tenant [total, within-objective] completion counts — feeds the
#: ``slo_attainment_tenant_<name>`` gauges (ISSUE 17: per-tenant SLO
#: attainment); tenant names are pre-sanitized to the trace-ID charset
#: (runtime/tenancy.py), so they are safe as gauge-name suffixes
_tenant_slo: Dict[str, List[int]] = {}
_tenant_slo_lock = threading.Lock()


def max_tenant_gauges() -> int:
    """``DSQL_MAX_TENANT_GAUGES`` (default 64): distinct per-tenant SLO
    gauges before overflow tenants fold into one ``_other`` series — a
    hostile/bursty tenant-id space can no longer grow ``/metrics``
    without bound."""
    return max(_env_int("DSQL_MAX_TENANT_GAUGES", 64), 1)


def observe_tenant(tenant: str, priority: Optional[str],
                   wall_ms: float) -> None:
    """Fold one completed query into the tenant's SLO attainment gauge,
    judged against the query's own class objective.  Cardinality is
    bounded: once ``max_tenant_gauges()`` distinct tenants have a
    series, every NEW tenant folds into the shared ``_other`` series
    (existing tenants keep their own)."""
    cls = SloMonitor._class(priority)
    ok = float(wall_ms) <= objective_ms(cls)
    key = str(tenant)
    with _tenant_slo_lock:
        if key not in _tenant_slo and len(_tenant_slo) >= max_tenant_gauges():
            key = "_other"
        tot = _tenant_slo.setdefault(key, [0, 0])
        tot[0] += 1
        if ok:
            tot[1] += 1
        total, good = tot
    _tel.REGISTRY.set_gauge(f"slo_attainment_tenant_{key}",
                            round(good / total, 6))


def on_query_complete(report, error: Optional[BaseException]) -> None:
    """Fold one completed query into the SLO monitor and publish
    ``query.done``; called from ``telemetry._close_trace`` after the
    ``DSQL_EVENTS`` gate."""
    get_monitor().observe(getattr(report, "priority", None), report.wall_ms)
    tenant = getattr(report, "tenant", None)
    if tenant:
        observe_tenant(tenant, getattr(report, "priority", None),
                       report.wall_ms)
    publish("query.done",
            trace=getattr(report, "trace_id", None),
            outcome="error" if error is not None else "ok",
            error=type(error).__name__ if error is not None else None,
            wall_ms=round(report.wall_ms, 3),
            tier=getattr(report, "tier", None),
            priority=getattr(report, "priority", None),
            tenant=tenant,
            cache_hit=bool((getattr(report, "cache", None) or {})
                           .get("hit")),
            rows_out=int(getattr(report, "rows_out", 0)))


def _reset_for_tests() -> None:
    """Fresh bus + monitor + counter ring (unit tests only)."""
    global _BUS, _MONITOR
    with _BUS_LOCK:
        _BUS = None
    with _MONITOR_LOCK:
        _MONITOR = None
    with _counter_lock:
        _counter_ring.clear()
    with _tenant_slo_lock:
        _tenant_slo.clear()
