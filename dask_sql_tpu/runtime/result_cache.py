"""Memory-governed result & subplan cache with catalog epochs.

The engine reuses compiled *programs* across queries (physical/compiled.py's
stage-graph cache) but until now re-executed every query from scratch —
repeated dashboard-style queries paid full device time every run, the
dominant steady-state cost over a remote TPU.  Flare (PAPERS.md) shows
native SQL engines win by reusing compiled/materialized artifacts across
queries; this module is the data-reuse layer on top of the program-reuse
layer: it memoizes **query results** and **materialized stage-graph
intermediates**, keyed by a canonical fingerprint of the optimized plan plus
the catalog epochs (and table uids) of every referenced table.

**Correctness backbone: catalog epochs.**  ``Context`` keeps a monotonic
per-table version bumped by every mutating path (``create_table``,
``DROP/ALTER TABLE``, ``CREATE TABLE AS``, schema ops); the epoch joins the
cache key, and a bump proactively drops every entry that references the
table — a stale entry can never be served.  Table uids (monotonic, never
reused — table.py) join the key too, so even a mutation path that somehow
missed its epoch bump would still miss the cache: replacing a table always
creates a new ``Table`` object.

**Volatility gate.**  Plans containing non-deterministic or
environment-dependent constructs (RAND, CURRENT_TIMESTAMP, python UDFs,
unseeded TABLESAMPLE, PREDICT over a mutable model registry) are never
cached; ``plan_key`` returns None for them.

**Memory governance.**  The cache is a byte-accounted LRU with a two-tier
eviction ladder: entries live **device-resident** (tier "device") under a
``DSQL_RESULT_CACHE_MB`` budget; the LRU device entry is **spilled to host
numpy** (tier "host") under ``DSQL_RESULT_CACHE_HOST_MB``; the LRU host
entry is **dropped**.  A host hit re-uploads and re-promotes to device.
``DSQL_RESULT_CACHE_MB=0`` disables the subsystem (and releases anything
held).  When the workload manager (runtime/scheduler.py) is active the
cache is additionally a **tenant of the shared device-bytes ledger**: its
effective device budget shrinks to the ledger's free headroom and admitted
queries' reservations actively spill the device tier
(``shrink_device_to``), so a big concurrent query displaces cached results
instead of OOMing.  Current tier sizes are exported as the ``result_cache_bytes`` /
``result_cache_host_bytes`` gauges; hits/misses/stores/evictions/spills/
invalidations are stable counters (runtime/telemetry.py contract).

**Resilience integration.**  Population runs through the ``cache_populate``
fault-injection site (runtime/faults.py): an injected (or real transient)
failure while storing skips the store and never fails the query.  A crashed
or deadline-exceeded execution never reaches ``put`` at all — the store
happens strictly after a successful materialization.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from . import faults as _faults, resilience as _res, telemetry as _tel

# non-deterministic / environment-dependent operators: results must never be
# replayed from cache (the seeded RAND variants still read per-row state)
VOLATILE_OPS = frozenset({
    "RAND", "RANDOM", "RAND_INTEGER",
    "CURRENT_DATE", "CURRENT_TIMESTAMP", "NOW", "LOCALTIMESTAMP",
    "CURRENT_TIME", "LOCALTIME",
})

_SPLIT_SCHEMA = "__split__"

DEFAULT_DEVICE_MB = 256.0
DEFAULT_HOST_MB = 1024.0


def _env_mb(name: str, default: float) -> float:
    import os

    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# canonical plan fingerprints
# ---------------------------------------------------------------------------

class _Canon:
    """Accumulator for one canonicalization walk.

    ``shape=True`` serializes hoisted parameters (RexParam) by slot and
    type only — the SHAPE identity the flight recorder's EWMA history
    keys on, so cost estimates transfer across literal variants.  The
    default stays value-bearing: result-cache keys, stage boundary names
    and SPMD digests must distinguish literals, or two variants of a
    shape would replay each other's ANSWERS."""

    __slots__ = ("parts", "scans", "volatile", "shape")

    def __init__(self, shape: bool = False):
        self.parts: List[str] = []
        self.scans: List[Tuple[str, str]] = []
        self.volatile = False
        self.shape = shape


def _canon_rex(rex, acc: _Canon) -> None:
    from ..plan.nodes import (RexCall, RexInputRef, RexLiteral, RexOuterRef,
                              RexParam, RexScalarSubquery, RexUdf)

    if isinstance(rex, RexInputRef):
        acc.parts.append(f"${rex.index}")
    elif isinstance(rex, RexParam):
        if acc.shape:
            acc.parts.append(f"P{rex.slot}:{rex.stype.name}")
        else:
            acc.parts.append(f"P{rex.slot}:{rex.stype.name}={rex.value!r}")
    elif isinstance(rex, RexLiteral):
        acc.parts.append(f"L{rex.stype.name}:{rex.value!r}")
    elif isinstance(rex, RexCall):
        if rex.op in VOLATILE_OPS:
            acc.volatile = True
        info = getattr(rex, "info", None)
        extra = f"!{getattr(info, 'name', info)}" if info is not None else ""
        acc.parts.append(f"C{rex.op}{extra}[")
        for o in rex.operands:
            _canon_rex(o, acc)
        acc.parts.append(f"]:{rex.stype.name}")
    elif isinstance(rex, RexScalarSubquery):
        acc.parts.append("S[")
        _canon_rel(rex.plan, acc)
        acc.parts.append("]")
    elif isinstance(rex, RexOuterRef):
        acc.parts.append(f"$outer{rex.index}")
    elif isinstance(rex, RexUdf):
        # python callables: identity is not content-addressable and the
        # function may be stateful — never replay from cache
        acc.volatile = True
        acc.parts.append(f"udf:{rex.name}")
        for o in rex.operands:
            _canon_rex(o, acc)
    else:
        acc.volatile = True
        acc.parts.append(f"?rex:{type(rex).__name__}")


def _canon_collation(collation, acc: _Canon) -> None:
    acc.parts.append(",".join(
        f"{c.index}{'a' if c.ascending else 'd'}"
        f"{'nf' if c.effective_nulls_first else 'nl'}" for c in collation))


def _canon_rel(rel, acc: _Canon) -> None:
    """Total canonical serialization: unlike ``compiled._fp_plan`` it never
    raises and covers every node type (unknown constructs serialize by type
    name and mark the plan volatile), and unlike ``RelNode.explain`` it
    includes the contents of VALUES rows and scalar-subquery plans — two
    different subplans can never share a fingerprint."""
    from ..plan.nodes import (LogicalAggregate, LogicalExcept, LogicalFilter,
                              LogicalIntersect, LogicalJoin, LogicalProject,
                              LogicalSample, LogicalSort, LogicalTableScan,
                              LogicalUnion, LogicalValues, LogicalWindow)
    from ..plan.predict import LogicalPredict

    t = type(rel).__name__
    schema = ";".join(f"{f.name}:{f.stype.name}" for f in rel.schema)
    if isinstance(rel, LogicalTableScan):
        if rel.schema_name == "system":
            # system tables are views over live engine state (and the
            # flight-recorder file): never cacheable, and they must not
            # occupy result-cache budget or bump catalog epochs.  A user
            # schema literally named "system" shadows the builtin in
            # resolution but still pays this exemption — acceptable cost
            # for a reserved name.
            acc.volatile = True
        if rel.schema_name != _SPLIT_SCHEMA:
            acc.scans.append((rel.schema_name, rel.table_name))
        # a __split__ boundary name is already a content digest of its
        # producing subtree (physical/compiled._stage_table_name)
        acc.parts.append(f"Scan({rel.schema_name}.{rel.table_name})[{schema}]")
        return
    acc.parts.append(f"{t}(")
    if isinstance(rel, LogicalProject):
        for e in rel.exprs:
            _canon_rex(e, acc)
            acc.parts.append(",")
    elif isinstance(rel, LogicalFilter):
        _canon_rex(rel.condition, acc)
    elif isinstance(rel, LogicalAggregate):
        acc.parts.append(f"g={rel.group_keys}|")
        for a in rel.aggs:
            if a.udaf is not None:
                acc.volatile = True  # python callable, like a UDF
            acc.parts.append(
                f"{a.op}{'d' if a.distinct else ''}({a.args})f{a.filter_arg};")
    elif isinstance(rel, LogicalJoin):
        na = "N" if getattr(rel, "null_aware", False) else ""
        acc.parts.append(f"{rel.join_type}{na}|")
        if rel.condition is not None:
            _canon_rex(rel.condition, acc)
    elif isinstance(rel, LogicalSort):
        _canon_collation(rel.collation, acc)
        acc.parts.append(f"|o={rel.offset}|l={rel.limit}")
    elif isinstance(rel, LogicalWindow):
        for call in rel.calls:
            acc.parts.append(f"{call.op}({call.args})p{call.partition}o")
            _canon_collation(call.order, acc)
            acc.parts.append(f"f{call.frame!r};")
    elif isinstance(rel, (LogicalUnion, LogicalIntersect, LogicalExcept)):
        acc.parts.append(f"all={rel.all}")
    elif isinstance(rel, LogicalValues):
        acc.parts.append(repr([[f"{l.stype.name}:{l.value!r}" for l in row]
                               for row in rel.rows]))
    elif isinstance(rel, LogicalSample):
        if rel.seed is None:
            acc.volatile = True
        acc.parts.append(f"{rel.method}|{rel.percentage}|{rel.seed}")
    elif isinstance(rel, LogicalPredict):
        # the model registry is mutable and carries no versioning the key
        # could fold in — never replay PREDICT results
        acc.volatile = True
        acc.parts.append(".".join(rel.model_name))
    else:
        acc.volatile = True
    acc.parts.append(f")[{schema}]<")
    for i in rel.inputs:
        _canon_rel(i, acc)
    acc.parts.append(">")


def canonical_plan(rel, context=None, shape: bool = False) -> Tuple[
        str, bool, List[Tuple[str, str]]]:
    """(canonical text, volatile, referenced (schema, table) pairs).

    ``shape=True`` collapses hoisted literals (RexParam) to slot+type so
    the text names the query SHAPE — see ``_Canon``."""
    acc = _Canon(shape=shape)
    _canon_rel(rel, acc)
    return "".join(acc.parts), acc.volatile, acc.scans


class CacheKey:
    """A fully-resolved cache key: plan digest folded with every referenced
    table's catalog epoch AND table uid at key-build time."""

    __slots__ = ("digest", "tables")

    def __init__(self, digest: str, tables: Tuple[Tuple[str, str], ...]):
        self.digest = digest
        self.tables = tables


def plan_key(plan, context) -> Optional[CacheKey]:
    """Cache key for an optimized query plan, or None when the plan is
    uncacheable (volatile constructs, unresolvable/chunked scans)."""
    text, volatile, scans = canonical_plan(plan, context)
    if volatile:
        return None
    h = hashlib.blake2b(text.encode(), digest_size=16)
    tables: List[Tuple[str, str]] = []
    for schema_name, table_name in scans:
        schema = context.schema.get(schema_name)
        entry = schema.tables.get(table_name) if schema is not None else None
        if entry is None or entry.table is None or entry.chunked is not None:
            # views resolve through the binder before this point; a chunked
            # source has no stable content identity to key on
            return None
        epoch = context.table_epoch(schema_name, table_name)
        h.update(f"|{schema_name}.{table_name}:e{epoch}"
                 f":u{entry.table.uid}".encode())
        tables.append((schema_name, table_name))
    return CacheKey(h.hexdigest(), tuple(dict.fromkeys(tables)))


def stage_key(name: str) -> CacheKey:
    """Key for a stage-boundary subplan output.  ``name`` is the boundary
    temp-table digest (physical/compiled._stage_table_name), which already
    content-addresses the subtree INCLUDING the uids of every scanned table
    — a catalog mutation changes the uids and therefore the name."""
    return CacheKey(f"stage:{name}", ())


# ---------------------------------------------------------------------------
# the cache
# ---------------------------------------------------------------------------

class _Entry:
    __slots__ = ("key", "tier", "table", "host", "nbytes", "tables", "hits")

    def __init__(self, key: str, table, nbytes: int,
                 tables: Tuple[Tuple[str, str], ...]):
        self.key = key
        self.tier = "device"
        self.table = table          # device Table (tier == "device")
        self.host = None            # (names, [(data, mask, stype, dict)])
        self.nbytes = nbytes
        self.tables = tables
        self.hits = 0


def _table_nbytes(table) -> int:
    total = 0
    for c in table.columns:
        total += int(getattr(c.data, "nbytes", 0))
        if c.mask is not None:
            total += int(getattr(c.mask, "nbytes", 0))
    return total


def _snapshot(table):
    """Shallow copy: shared immutable columns, private names/columns lists
    and a fresh uid — callers can never corrupt the cached copy (or each
    other's) through list surgery on a shared Table object."""
    from ..table import Table

    return Table(list(table.names), list(table.columns))


class ResultCache:
    """Byte-accounted two-tier LRU over query results and stage outputs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._by_table: Dict[Tuple[str, str], Set[str]] = {}
        self.device_bytes = 0
        self.host_bytes = 0

    # -- config ------------------------------------------------------------
    def _base_device_budget(self) -> int:
        return int(_env_mb("DSQL_RESULT_CACHE_MB", DEFAULT_DEVICE_MB) * 2**20)

    def device_budget(self) -> int:
        """Effective device budget: the configured ceiling, shrunk to the
        workload manager's ledger headroom when that subsystem is active —
        the cache is a TENANT of the shared device-bytes ledger
        (runtime/scheduler.py), so admitted queries' reservations squeeze
        the cache before they squeeze each other.  The allowance read is
        lock-free on the scheduler side, so calling this under the cache
        lock cannot invert the ledger->cache lock order."""
        base = self._base_device_budget()
        if base <= 0:
            return 0
        from . import scheduler as _sched
        allowance = _sched.get_manager().cache_allowance()
        return base if allowance is None else min(base, allowance)

    def host_budget(self) -> int:
        return int(_env_mb("DSQL_RESULT_CACHE_HOST_MB",
                           DEFAULT_HOST_MB) * 2**20)

    def enabled(self) -> bool:
        # the BASE budget decides liveness: ledger pressure (allowance 0)
        # must shrink the device tier, not clear the whole cache
        if self._base_device_budget() > 0:
            return True
        if self._entries:
            self.clear()  # flipping the env off releases held memory
        return False

    # -- gauges ------------------------------------------------------------
    def _publish_gauges(self) -> None:
        _tel.REGISTRY.set_gauge("result_cache_bytes", self.device_bytes)
        _tel.REGISTRY.set_gauge("result_cache_host_bytes", self.host_bytes)

    # -- core --------------------------------------------------------------
    def probe(self, key: Optional[CacheKey]) -> Optional[str]:
        """Tier of the live entry for ``key`` (no LRU touch), else None."""
        if key is None:
            return None
        with self._lock:
            e = self._entries.get(key.digest)
            return e.tier if e is not None else None

    def get(self, key: Optional[CacheKey]):
        """(Table, tier) on a hit — the tier the entry was found in — or
        None.  Host entries re-upload and re-promote to the device tier."""
        if key is None or not self.enabled():
            return None
        with self._lock:
            e = self._entries.get(key.digest)
            if e is None:
                return None
            self._entries.move_to_end(key.digest)
            e.hits += 1
            found_tier = e.tier
            if e.tier == "host":
                self._promote(e)
            table = e.table
            # re-balance AFTER capturing the table: if the budget shrank
            # since the store, the promotion may immediately spill again
            self._evict_to_budget()
            self._publish_gauges()
        return _snapshot(table), found_tier

    def put(self, key: Optional[CacheKey], table) -> bool:
        """Store a successfully-materialized result.  Returns True when the
        entry landed.  Runs through the ``cache_populate`` fault site: an
        injected/transient failure skips the store, never the query."""
        if key is None or not self.enabled():
            return False
        try:
            _faults.maybe_fail("cache_populate")
        except _res.TransientError:
            return False  # population is best-effort by contract
        nbytes = _table_nbytes(table)
        budget = self.device_budget()
        if nbytes > budget:
            return False  # larger than the whole tier: not worth churning
        snap = _snapshot(table)
        with self._lock:
            old = self._entries.pop(key.digest, None)
            if old is not None:
                self._unaccount(old)
            e = _Entry(key.digest, snap, nbytes, key.tables)
            self._entries[key.digest] = e
            self.device_bytes += nbytes
            for t in key.tables:
                self._by_table.setdefault(t, set()).add(key.digest)
            self._evict_to_budget()
            self._publish_gauges()
        _tel.inc("result_cache_stores")
        return True

    # -- invalidation ------------------------------------------------------
    def invalidate_table(self, schema_name: str, table_name: str) -> int:
        """Drop every entry referencing (schema, table); returns the count.
        Called on every catalog-epoch bump — stale entries are released
        immediately instead of lingering until LRU pressure."""
        dropped = 0
        with self._lock:
            keys = self._by_table.pop((schema_name, table_name.lower()), ())
            for k in list(keys):
                e = self._entries.pop(k, None)
                if e is not None:
                    self._unaccount(e)
                    dropped += 1
            if dropped:
                self._publish_gauges()
        if dropped:
            _tel.inc("result_cache_invalidations", dropped)
        return dropped

    def shrink_device_to(self, target_bytes: int) -> int:
        """Pressure-driven eviction callback for the workload manager's
        memory broker: spill (or drop) device-tier LRU entries until the
        device tier fits ``target_bytes``.  Returns the bytes freed.  The
        entries keep their value when the host tier can hold them — a
        large admitted query transiently displaces the cache to host
        instead of destroying it (or OOMing the device)."""
        target = max(int(target_bytes), 0)
        host_budget = self.host_budget()
        freed = 0
        with self._lock:
            before = self.device_bytes
            while self.device_bytes > target:
                victim = self._lru_of_tier("device")
                if victim is None:  # pragma: no cover - accounting invariant
                    break
                if host_budget > 0 and victim.nbytes <= host_budget:
                    self._spill(victim)
                else:
                    self._drop(victim)
            # spills may now overflow the host tier; run the normal ladder
            while self.host_bytes > host_budget:
                victim = self._lru_of_tier("host")
                if victim is None:  # pragma: no cover - accounting invariant
                    break
                self._drop(victim)
            freed = before - self.device_bytes
            if freed:
                self._publish_gauges()
        return freed

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_table.clear()
            self.device_bytes = 0
            self.host_bytes = 0
            self._publish_gauges()

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "device_bytes": self.device_bytes,
                "host_bytes": self.host_bytes,
                "device_budget": self.device_budget(),
                "host_budget": self.host_budget(),
            }

    def entries_snapshot(self) -> List[dict]:
        """Per-entry view for ``system.cache`` (LRU order, oldest first)."""
        with self._lock:
            return [{"key": e.key, "tier": e.tier, "nbytes": int(e.nbytes),
                     "hits": int(e.hits),
                     "tables": ",".join(f"{s}.{t}" for s, t in e.tables)}
                    for e in self._entries.values()]

    # -- internals (lock held) ---------------------------------------------
    def _unaccount(self, e: _Entry) -> None:
        if e.tier == "device":
            self.device_bytes -= e.nbytes
        else:
            self.host_bytes -= e.nbytes
        for t in e.tables:
            keys = self._by_table.get(t)
            if keys is not None:
                keys.discard(e.key)
                if not keys:
                    self._by_table.pop(t, None)

    def _drop(self, e: _Entry) -> None:
        self._entries.pop(e.key, None)
        self._unaccount(e)
        _tel.inc("result_cache_evictions")

    def _lru_of_tier(self, tier: str) -> Optional[_Entry]:
        for e in self._entries.values():  # insertion order == LRU order
            if e.tier == tier:
                return e
        return None

    def _evict_to_budget(self) -> None:
        """The eviction ladder: device LRU spills to host; host LRU drops."""
        budget = self.device_budget()
        host_budget = self.host_budget()
        while self.device_bytes > budget:
            victim = self._lru_of_tier("device")
            if victim is None:  # pragma: no cover - accounting invariant
                break
            if host_budget > 0 and victim.nbytes <= host_budget:
                self._spill(victim)
            else:
                self._drop(victim)
        while self.host_bytes > host_budget:
            victim = self._lru_of_tier("host")
            if victim is None:  # pragma: no cover - accounting invariant
                break
            self._drop(victim)

    def _spill(self, e: _Entry) -> None:
        """device -> host: one bulk transfer, numpy-resident thereafter."""
        import jax

        table = e.table
        bufs = []
        for c in table.columns:
            bufs.append(c.data)
            if c.mask is not None:
                bufs.append(c.mask)
        fetched = iter(jax.device_get(bufs) if bufs else [])
        cols = []
        for c in table.columns:
            data = next(fetched)
            mask = next(fetched) if c.mask is not None else None
            cols.append((data, mask, c.stype, c.dictionary))
        e.host = (list(table.names), cols)
        e.table = None
        e.tier = "host"
        self.device_bytes -= e.nbytes
        self.host_bytes += e.nbytes
        _tel.inc("result_cache_spills")

    def _promote(self, e: _Entry) -> None:
        """host -> device re-upload on a host-tier hit."""
        import jax.numpy as jnp

        from ..table import Column, Table

        names, host_cols = e.host
        cols = [Column(jnp.asarray(data), stype,
                       None if mask is None else jnp.asarray(mask),
                       dictionary, host_cache=(data, mask))
                for data, mask, stype, dictionary in host_cols]
        e.table = Table(names, cols)
        e.host = None
        e.tier = "device"
        self.host_bytes -= e.nbytes
        self.device_bytes += e.nbytes


_CACHE = ResultCache()


def get_cache() -> ResultCache:
    """The process-global cache (keys fold table uids, so entries from
    different Contexts/tests can never collide)."""
    return _CACHE
