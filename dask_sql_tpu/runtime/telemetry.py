"""Query-lifecycle telemetry: spans, a metrics registry, and QueryReports.

A compiled-query engine lives or dies by visibility into where wall time
goes — parse vs plan vs (the dominant, 40-200 s over a tunneled TPU)
compile vs device execute vs host materialize.  Flare (PAPERS.md) makes the
same argument for Spark native compilation.  Before this module that
visibility was scattered and partly broken: a module-global ``stats`` dict
in physical/compiled.py with unlocked ``+= 1`` read-modify-writes, a
process-global ``last_exec_profile`` that concurrent server queries
clobbered, and ad-hoc counters in server/app.py.  Everything now funnels
through here:

**Span tracer.**  ``trace_scope(sql)`` opens a per-query trace (the same
thread-local propagation pattern as ``resilience.QueryRuntime``; worker
threads re-enter via ``scoped``, exactly like ``resilience.scoped``).
``span(name)`` nests timed spans under the current one; ``annotate``
attaches attributes (row/byte counts, cache hit/miss, degradation rung,
retry counts) to the innermost open span.  Spans record wall time, the
owning thread, and exceptions; child append is lock-protected because
stage-graph workers attach concurrently.

**Metrics registry.**  ``REGISTRY`` holds process-global thread-safe
counters and bounded histograms.  It absorbs and deprecates the old
``physical.compiled.stats`` dict (kept as a read-through alias) and the
resilience ``_bump`` path — every increment is atomic under one lock.

**Metric-name stability contract.**  The counter keys in
``STABLE_COUNTERS`` and the histogram names in ``STABLE_HISTOGRAMS`` are a
public, append-only interface: dashboards, ``GET /metrics`` scrapers and
the BENCH_r*.json trajectory all key on them.  Renaming or repurposing one
is a breaking change; add new names instead, and never reuse a retired
name for a different meaning.  Prometheus names derive mechanically:
counter ``k`` exports as ``dsql_<k>_total``, histogram ``h`` as
``dsql_<h>`` with ``_bucket``/``_sum``/``_count`` series.

**QueryReport.**  Closing a trace builds a ``QueryReport``: phase timings
aggregated from the span tree, process counter deltas, row/byte counts,
and the tree itself.  ``Context.sql`` stashes it on ``context.last_report``
and (thread-locally) for the server's per-query wire stats.  Reports
render as text (``render()``) or export as ``chrome://tracing`` JSON
(``to_chrome_trace()``; ``DSQL_CHROME_TRACE_DIR`` writes one file per
query).  ``DSQL_SLOW_QUERY_MS`` arms an opt-in slow-query log at trace
close.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# stable metric names (see the module docstring's stability contract)
# ---------------------------------------------------------------------------

# compile/execute pipeline counters (the old physical.compiled.stats keys,
# meanings unchanged) + streaming + server counters
STABLE_COUNTERS: Tuple[str, ...] = (
    # compiled pipeline
    "compiles", "hits", "fallbacks", "unsupported", "recompiles",
    "compile_errors", "exiled", "split_hints",
    # stage-graph observability
    "stage_graphs", "stage_compiles", "stage_hits", "cross_query_hits",
    # resilience observability
    "retries", "degradations", "deadline_exceeded",
    "fault_compile", "fault_materialize", "fault_stage_exec",
    "fault_stage_replay", "fault_chunked_read", "fault_host_transfer",
    "fault_cache_populate", "fault_admission", "fault_drain",
    "fault_spill",
    # failure-domain recovery (stage replay + quarantine + watchdog):
    # stage_execs counts stage-execution ATTEMPTS; stage_replays counts
    # checkpointed re-executions of a single failed stage;
    # stage_replay_saved_stages counts the already-materialized stages a
    # replay did NOT have to re-run
    "stage_execs", "stage_replays", "stage_replay_saved_stages",
    "quarantine_skips", "quarantine_probes", "quarantine_marks",
    "watchdog_trips",
    # tiered execution (physical/compiled.py): queries answered on the
    # eager tier while their stage programs compiled in the background,
    # background compiles that landed / errored, and compile-worker
    # halvings under consecutive-compile-failure pressure
    "served_eager_while_compiling", "background_compiles_done",
    "background_compile_errors", "compile_backoffs",
    # persistent cross-process program store (runtime/program_store.py)
    "program_store_hits", "program_store_misses", "program_store_stores",
    "program_store_rejects", "program_store_evictions",
    "program_store_errors",
    # workload manager (runtime/scheduler.py): per-class admission
    # outcomes; for any submission mix, admitted + rejected + timeout
    # always sums to the queries that entered admission
    "sched_admitted_interactive", "sched_admitted_batch",
    "sched_admitted_background",
    "sched_rejected_interactive", "sched_rejected_batch",
    "sched_rejected_background",
    "sched_timeout_interactive", "sched_timeout_batch",
    "sched_timeout_background",
    # result & subplan cache (runtime/result_cache.py)
    "result_cache_hits", "result_cache_misses", "result_cache_stores",
    "result_cache_evictions", "result_cache_spills",
    "result_cache_invalidations", "result_cache_subplan_hits",
    # streaming (out-of-HBM) execution
    "stream_batches", "stream_batch_rows",
    # out-of-core spill store (runtime/spill.py): runs opened
    # (spill_partitions — the EXPLAIN ANALYZE "spilled" signal), chunks
    # written, tier movement (host->disk flushes, disk->host loads,
    # device->host demotions), monotonic bytes written per tier, and
    # typed spill-IO failures
    "spill_partitions", "spill_chunks", "spill_flushes", "spill_loads",
    "spill_demotions", "spill_bytes_host", "spill_bytes_disk",
    "spill_errors",
    # grace-hash morsel driver (physical/morsel.py): joins lowered to
    # the partitioned path, partition pairs actually joined on device,
    # and pairs whose padded capacity blew past the skew threshold
    "morsel_joins", "morsel_pairs", "morsel_skew_warnings",
    # query lifecycle
    "queries", "query_errors", "slow_queries",
    # server boundary
    "server_queries", "server_query_errors", "server_cancels",
    "server_throttled", "server_drain_rejects",
    # flight recorder (runtime/flight_recorder.py): persisted event-log
    # appends / ring truncations / swallowed recording failures, and the
    # memory-broker estimates served from MEASURED history instead of the
    # scan-bytes×multiplier heuristic (scheduler.estimate_working_set)
    "history_records", "history_truncations", "history_errors",
    "estimate_from_history",
    # SPMD multi-chip backend (parallel/spmd.py): queries/stages served
    # sharded, program compiles vs cross-process store hits, collective
    # traffic (hash-exchange rounds + bytes moved, partial-aggregate
    # trees, broadcast-vs-exchange join dispatch), and the two refusal
    # paths — static gate (unsupported) vs runtime safety flag (fallback)
    "spmd_queries", "spmd_stages", "spmd_compiles", "spmd_store_hits",
    "spmd_exchanges", "spmd_exchange_bytes", "spmd_partial_aggs",
    "spmd_broadcast_joins", "spmd_exchange_joins", "spmd_join_flips",
    "spmd_fallbacks", "spmd_unsupported",
    # collective bytes by kind (parallel/spmd.py via exchange.py static
    # estimators): spmd_exchange_bytes above is the all_to_all channel;
    # these split out the broadcast-join gathers and psum combine trees
    "spmd_all_gather_bytes", "spmd_psum_bytes",
    # device-level profiler (runtime/profiler.py, DSQL_PROFILE=1):
    # memory snapshots taken, XLA cost-analysis captures (compile or
    # program-store load), and scheduler estimates served from the
    # captured cost model (the ladder's fourth rung)
    "profile_samples", "profile_cost_captures", "estimate_from_cost_model",
    # materialized views (runtime/matview.py): serves through the
    # resolve_table hook, O(delta) vs full refreshes (incremental + full
    # reconciles against the staleness events a soak drives), appended
    # batches logged on the delta seam, and the refresh chaos site
    "mv_serves", "mv_refresh_incremental", "mv_refresh_full",
    "mv_deltas_recorded", "fault_mv_refresh",
    # watchtower event bus + SLO monitor (runtime/events.py,
    # DSQL_EVENTS=1): events published to the bounded bus, publishes
    # that failed and were dropped (never the caller's problem), and
    # edge-triggered multi-window SLO burn-rate breaches
    "events_published", "events_dropped", "slo_breaches",
    # parameterized plan identity (plan/parameterize.py, ISSUE 16):
    # plans that had ≥1 literal hoisted, total literals hoisted, and
    # compiled-path program lookups for parameterized plans that hit
    # (in-memory cache or program store) vs compiled fresh;
    # prepared_executes counts EXECUTE statements served from the
    # per-context PREPARE registry
    "param_plans", "param_literals_hoisted",
    "param_plan_hits", "param_plan_misses",
    "prepared_executes",
    # result spooler (server/app.py, ISSUE 17): results larger than
    # DSQL_RESULT_PAGE_ROWS spool into the spill store and stream out
    # through nextUri pages; the reaper GCs abandoned results/futures
    # after DSQL_RESULT_TTL_S; fault_result_spool is the injection site
    # (a fired spool fault degrades to the unpaged response, never loses
    # the result)
    "result_spooled", "result_pages_spooled", "result_pages_served",
    "result_reaped", "fault_result_spool",
    # multi-tenancy (runtime/tenancy.py): admissions claimed under a
    # tenant, token-bucket/concurrency quota rejects, circuit-breaker
    # rejects/opens and half-open probes
    "tenant_queries", "tenant_quota_rejects", "tenant_circuit_rejects",
    "tenant_circuit_opens", "tenant_circuit_probes",
    # burn-driven load shedding (runtime/scheduler.py): background-class
    # admissions refused while a class burns its SLO error budget past
    # DSQL_SLO_BURN on both windows (each shed ALSO counts into
    # sched_rejected_background, so the admission reconciliation
    # invariant admitted + rejected + timeout == submitted still holds)
    "sched_shed_background",
    # fleet plane (runtime/fleet.py, DSQL_FLEET_DIR): heartbeat files
    # written / beat failures swallowed, and merged-ring reads served
    # (system.events fleet mode, /v1/events?fleet=1, /v1/fleet)
    "fleet_heartbeats", "fleet_heartbeat_errors", "fleet_merged_reads",
    # autopilot (runtime/autopilot.py, DSQL_AUTOPILOT=1): advisor ticks,
    # matview actuator actions (auto-create / drop / background refresh /
    # exact-repeat serves), and the re-planning loop's hint lifecycle
    # (recorded on a tripped threshold, applied to an execution, reverted
    # after two measured-slower strikes)
    "autopilot_ticks", "autopilot_mv_creates", "autopilot_mv_drops",
    "autopilot_mv_refreshes", "autopilot_mv_serves",
    "autopilot_hints_recorded", "autopilot_hints_applied",
    "autopilot_hints_reverted",
    # continuous ingestion (runtime/ingest.py, ISSUE 20): WAL-committed
    # batches/rows, micro-batch buffer traffic (buffered appends + flushes
    # that drained them), restart replay, memory-broker backpressure
    # rejects, torn WAL lines skipped on replay, /v1/ingest requests, the
    # fault_ingest injection site, and delta-log compactions that kept a
    # trickle of tiny appends on the incremental path (runtime/matview.py)
    "ingest_batches_committed", "ingest_rows_committed",
    "ingest_batches_buffered", "ingest_flushes",
    "ingest_replayed_batches", "ingest_replayed_rows",
    "ingest_backpressure_rejects", "ingest_wal_torn_lines",
    "server_ingest_requests", "fault_ingest",
    "mv_delta_compactions",
)

STABLE_HISTOGRAMS: Tuple[str, ...] = (
    "query_wall_ms", "parse_ms", "plan_ms", "execute_ms", "compile_ms",
    "materialize_ms",
)

# gauges (point-in-time values, may go down): same append-only contract
STABLE_GAUGES: Tuple[str, ...] = (
    "result_cache_bytes", "result_cache_host_bytes",
    # workload manager: live queue depth (incl. server seats), queries
    # currently executing, and device bytes reserved by admitted queries
    "sched_queue_depth", "sched_running", "sched_reserved_bytes",
    # 1 while the process is draining (SIGTERM/SIGINT received, in-flight
    # queries finishing, new admissions refused), else 0
    "server_draining",
    # spill-store tier occupancy (runtime/spill.py), point-in-time
    "spill_device_bytes", "spill_host_bytes", "spill_disk_bytes",
    # device-memory profiler (runtime/profiler.py): summed local-device
    # HBM truth from the latest memory_stats() sample (zeros on backends
    # without memory stats, e.g. CPU)
    "profile_hbm_bytes_in_use", "profile_hbm_peak_bytes",
    "profile_hbm_bytes_limit",
    # SLO monitor (runtime/events.py, DSQL_EVENTS=1): per-priority-class
    # lifetime attainment and multi-window burn rates (breach fraction
    # over the window / error budget; 1.0 = spending the budget exactly
    # at the sustainable pace)
    "slo_attainment_interactive", "slo_attainment_batch",
    "slo_attainment_background",
    "slo_burn_fast_interactive", "slo_burn_fast_batch",
    "slo_burn_fast_background",
    "slo_burn_slow_interactive", "slo_burn_slow_batch",
    "slo_burn_slow_background",
    # result spooler: live spooled pages + bytes awaiting collection
    "result_spool_pages", "result_spool_bytes",
    # 1 while burn-driven background shedding is active, else 0
    "slo_shedding",
    # tenants the registry has seen this process (runtime/tenancy.py)
    "tenants_known",
    # fleet plane (runtime/fleet.py): replicas within heartbeat TTL at
    # the last fleet snapshot, and the fleet-wide sum of every alive
    # replica's program_store_hits — the shared-warmth proof counter
    "fleet_replicas_alive", "fleet_warm_serves",
    # continuous ingestion (runtime/ingest.py): WAL bytes on disk, rows
    # sitting in un-flushed micro-batch buffers, and view staleness —
    # un-applied delta rows across all registered matview base tables +
    # age in seconds of the oldest pending delta (0 when fully fresh)
    "ingest_wal_bytes", "ingest_buffered_rows",
    "mv_pending_rows", "mv_staleness_s",
)

# exponential-ish bucket bounds in milliseconds; histograms are BOUNDED by
# construction (fixed bucket count + running sum/count, O(1) per observe)
_BUCKETS_MS: Tuple[float, ...] = (
    1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 30000,
    120000,
)


class _Histogram:
    __slots__ = ("counts", "total", "count")

    def __init__(self):
        self.counts = [0] * (len(_BUCKETS_MS) + 1)
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = 0
        for i, b in enumerate(_BUCKETS_MS):
            if value <= b:
                break
        else:
            i = len(_BUCKETS_MS)
        self.counts[i] += 1
        self.total += value
        self.count += 1

    def snapshot(self) -> dict:
        return {"buckets": list(zip(_BUCKETS_MS, self.counts)),
                "overflow": self.counts[-1],
                "sum": self.total, "count": self.count}


class MetricsRegistry:
    """Process-global thread-safe counters + bounded histograms.

    ``inc`` is the atomic replacement for every unlocked
    ``stats["k"] += 1`` read-modify-write the engine used to do; ``set``
    exists only for the deprecated dict-alias write path.  Counter names
    in STABLE_COUNTERS pre-exist at zero so snapshot consumers (bench
    deltas, fault_smoke) never KeyError on a counter that has not fired.
    """

    def __init__(self, seed: Tuple[str, ...] = (),
                 gauge_seed: Tuple[str, ...] = ()):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in seed}
        self._gauges: Dict[str, float] = {k: 0 for k in gauge_seed}
        self._hists: Dict[str, _Histogram] = {}

    # -- counters ----------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._counters[name] = int(value)

    def get(self, name: str, default: Optional[int] = None) -> Optional[int]:
        with self._lock:
            return self._counters.get(name, default)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # -- gauges ------------------------------------------------------------
    def set_gauge(self, name: str, value: float) -> None:
        """Point-in-time value (cache sizes, pool depths): unlike counters
        a gauge may go DOWN; prometheus renders it without ``_total``."""
        with self._lock:
            self._gauges[name] = value

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    # -- histograms --------------------------------------------------------
    def observe(self, name: str, value_ms: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram()
            h.observe(float(value_ms))

    def snapshot(self) -> dict:
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "histograms": {k: h.snapshot()
                                   for k, h in self._hists.items()}}

    def reset(self) -> None:
        """Zero everything (tests only; production counters are
        monotonic by contract)."""
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            for k in self._gauges:
                self._gauges[k] = 0
            self._hists.clear()

    # -- prometheus --------------------------------------------------------
    def render_prometheus(self,
                          labels: Optional[Dict[str, str]] = None) -> str:
        """Prometheus text exposition (text/plain; version=0.0.4).

        Counter ``k`` -> ``dsql_<k>_total``; histogram ``h`` ->
        ``dsql_<h>`` with le-bucketed ``_bucket`` series + ``_sum`` +
        ``_count``.  Names are sanitized to the prometheus charset.
        ``labels`` (e.g. ``{"replica": "r1"}`` when a fleet dir is
        armed) are stamped on EVERY series; with none the exposition is
        byte-identical to the label-free historical format.
        """
        def clean(name: str) -> str:
            return "".join(c if (c.isalnum() or c == "_") else "_"
                           for c in name)

        base = ""
        if labels:
            base = ",".join(f'{clean(k)}="{v}"'
                            for k, v in sorted(labels.items()))

        def series(m: str, extra: str = "") -> str:
            parts = ",".join(p for p in (base, extra) if p)
            return f"{m}{{{parts}}}" if parts else m

        snap = self.snapshot()
        out: List[str] = []
        for k in sorted(snap["counters"]):
            m = f"dsql_{clean(k)}_total"
            out.append(f"# TYPE {m} counter")
            out.append(f"{series(m)} {snap['counters'][k]}")
        for k in sorted(snap.get("gauges", ())):
            m = f"dsql_{clean(k)}"
            out.append(f"# TYPE {m} gauge")
            out.append(f"{series(m)} {snap['gauges'][k]:g}")
        for k in sorted(snap["histograms"]):
            h = snap["histograms"][k]
            m = f"dsql_{clean(k)}"
            out.append(f"# TYPE {m} histogram")
            acc = 0
            for bound, c in h["buckets"]:
                acc += c
                le = 'le="%g"' % bound
                out.append(f"{series(m + '_bucket', le)} {acc}")
            acc += h["overflow"]
            inf = 'le="+Inf"'
            out.append(f"{series(m + '_bucket', inf)} {acc}")
            out.append(f"{series(m + '_sum')} {h['sum']:.6g}")
            out.append(f"{series(m + '_count')} {h['count']}")
        return "\n".join(out) + "\n"


REGISTRY = MetricsRegistry(seed=STABLE_COUNTERS, gauge_seed=STABLE_GAUGES)


def inc(name: str, n: int = 1) -> None:
    """Atomic counter increment on the global registry (the replacement
    for every former ``stats[name] += 1`` site)."""
    REGISTRY.inc(name, n)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class Span:
    """One timed node of a query's span tree."""

    __slots__ = ("name", "t0", "t1", "attrs", "children", "tid")

    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.children: List["Span"] = []
        self.tid = threading.get_ident()

    @property
    def wall_ms(self) -> float:
        end = self.t1 if self.t1 is not None else time.perf_counter()
        return (end - self.t0) * 1e3

    def walk(self):
        yield self
        for c in list(self.children):
            yield from c.walk()

    def to_dict(self) -> dict:
        return {"name": self.name, "wall_ms": round(self.wall_ms, 3),
                "attrs": dict(self.attrs),
                "children": [c.to_dict() for c in self.children]}


class QueryTrace:
    """One query's span tree + the registry snapshot at open.

    ``lock`` guards child append: stage-graph worker threads attach spans
    to the same parent concurrently."""

    __slots__ = ("query", "root", "lock", "counters0", "report",
                 "started_unix")

    def __init__(self, query: str = ""):
        self.query = query
        self.root = Span("query")
        self.lock = threading.Lock()
        self.counters0 = REGISTRY.counters()
        self.report: Optional["QueryReport"] = None
        self.started_unix = time.time()


class _Tls(threading.local):
    trace: Optional[QueryTrace] = None
    span: Optional[Span] = None
    node_recorder = None
    exec_profile: Optional[Dict[str, float]] = None
    last_report: Optional["QueryReport"] = None


_tls = _Tls()


def current_trace() -> Optional[QueryTrace]:
    return _tls.trace


def current_span() -> Optional[Span]:
    return _tls.span


@contextmanager
def scoped(trace: Optional[QueryTrace], parent: Optional[Span] = None):
    """Install an existing trace in THIS thread (worker-pool re-entry —
    the telemetry analogue of ``resilience.scoped``)."""
    prev_t, prev_s = _tls.trace, _tls.span
    _tls.trace = trace
    _tls.span = parent if parent is not None else (
        trace.root if trace is not None else None)
    try:
        yield
    finally:
        _tls.trace, _tls.span = prev_t, prev_s


@contextmanager
def span(name: str, **attrs):
    """Open a child span under the current one; no-op outside a trace.

    An escaping exception stamps ``error=<type name>`` on the span and
    re-raises — the span tree always closes consistently."""
    trace = _tls.trace
    parent = _tls.span
    if trace is None or parent is None:
        yield None
        return
    s = Span(name, attrs)
    with trace.lock:
        parent.children.append(s)
    _tls.span = s
    try:
        yield s
    except BaseException as e:
        s.attrs["error"] = type(e).__name__
        raise
    finally:
        s.t1 = time.perf_counter()
        _tls.span = parent


def annotate(**attrs) -> None:
    """Attach attributes to the innermost open span (no-op outside)."""
    s = _tls.span
    if s is not None:
        s.attrs.update(attrs)


# ---------------------------------------------------------------------------
# per-thread exec profile (the last_exec_profile race fix)
# ---------------------------------------------------------------------------

def exec_profile() -> Dict[str, float]:
    """THIS thread's device/materialize timing scratchpad.

    Replaces the old process-global ``compiled.last_exec_profile`` dict,
    which concurrent server queries clobbered; each query thread now owns
    its own, and the authoritative copy lands on the query's span."""
    p = _tls.exec_profile
    if p is None:
        p = _tls.exec_profile = {}
    return p


# ---------------------------------------------------------------------------
# per-node instrumentation (EXPLAIN ANALYZE)
# ---------------------------------------------------------------------------

class NodeRecorder:
    """Per-plan-node (wall, rows, calls) accumulator, keyed by node id.

    Installed thread-locally by ``record_nodes()``; the eager executor
    feeds it from ``RelExecutor.execute``.  Timings are INCLUSIVE of
    children (the executor recurses through the same entry point);
    renderers derive self-time by subtracting child totals."""

    def __init__(self):
        self.records: Dict[int, List[float]] = {}  # id -> [ms, rows, calls]

    def add(self, rel, ms: float, rows: int) -> None:
        rec = self.records.get(id(rel))
        if rec is None:
            self.records[id(rel)] = [ms, rows, 1]
        else:
            rec[0] += ms
            rec[1] += rows
            rec[2] += 1

    def get(self, rel):
        return self.records.get(id(rel))


def active_node_recorder() -> Optional[NodeRecorder]:
    return _tls.node_recorder


@contextmanager
def record_nodes():
    prev = _tls.node_recorder
    rec = NodeRecorder()
    _tls.node_recorder = rec
    try:
        yield rec
    finally:
        _tls.node_recorder = prev


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

def _fleet_replica() -> Optional[str]:
    """Replica id when the fleet plane (runtime/fleet.py) is armed, else
    None — env checked BEFORE the import (the profiler/recorder gate
    discipline), so the unarmed path costs one dict lookup."""
    if not os.environ.get("DSQL_FLEET_DIR"):
        return None
    try:
        from . import fleet as _fleet
        return _fleet.replica_id()
    except Exception:
        return None


# span names that aggregate into the phase breakdown; "device"/"materialize"
# values may also arrive as span ATTRS (device_ms) when DSQL_TIME_DEVICE
# splits the execute wall
_PHASE_SPANS = ("parse", "plan", "execute", "fetch", "compile",
                "materialize", "stage", "stage_graph", "stream_batch",
                "queued", "retry_backoff", "drain")


class QueryReport:
    """Everything one ``Context.sql`` call did, in one object.

    ``phases``: wall-ms sums per span name (parse/plan/execute/fetch at
    the top level; compile/materialize/stage nested under execute — so
    only parse+plan+execute+fetch partition the wall).  ``counters``:
    process-global registry deltas between trace open and close (exact
    per-query attribution when queries do not overlap; an upper bound
    under concurrency).  ``root``: the span tree."""

    __slots__ = ("query", "wall_ms", "phases", "counters", "root",
                 "rows_out", "bytes_out", "started_unix", "cache", "tier",
                 "priority", "operators", "spilled", "skew_ratio",
                 "collective_bytes", "cost_err", "trace_id", "tenant",
                 "replica")

    def __init__(self, trace: QueryTrace):
        root = trace.root
        self.query = trace.query
        self.started_unix = trace.started_unix
        self.wall_ms = root.wall_ms
        self.root = root
        # end-to-end trace ID (runtime/events.py stamps it on the root at
        # trace open when DSQL_EVENTS is armed); None when the
        # watchtower is off — consumers emit it only when present
        tid = root.attrs.get("trace_id")
        self.trace_id = str(tid) if tid else None
        # tenant identity (runtime/tenancy.py stamps it on the root when
        # an explicit tenant was supplied); None otherwise — consumers
        # emit it only when present, like the trace ID
        ten = root.attrs.get("tenant")
        self.tenant = str(ten) if ten else None
        # replica identity (runtime/fleet.py): present only when a fleet
        # dir is armed — env checked before the import so single-process
        # reports stay byte-identical and the fleet module un-imported
        self.replica = _fleet_replica()
        self.rows_out = int(root.attrs.get("rows_out", 0))
        self.bytes_out = int(root.attrs.get("bytes_out", 0))
        phases: Dict[str, float] = {}
        for s in root.walk():
            if s is root:
                continue
            if s.name in _PHASE_SPANS:
                phases[s.name] = phases.get(s.name, 0.0) + s.wall_ms
            for k in ("device_ms", "materialize_ms"):
                v = s.attrs.get(k)
                if v is not None:
                    key = k[:-3]
                    phases[key] = phases.get(key, 0.0) + float(v)
        self.phases = phases
        now = REGISTRY.counters()
        self.counters = {k: now[k] - trace.counters0.get(k, 0)
                         for k in now
                         if now[k] != trace.counters0.get(k, 0)}
        # result-cache section: exact per-query attribution from span attrs
        # (runtime/result_cache.py annotates the execute/stage spans), plus
        # the current tier sizes from the gauges
        hit = False
        tier: Optional[str] = None
        stored = False
        subplan_hits = 0
        # execution tier (tiered execution, physical/compiled.py):
        # "compiled" / "eager" / "eager-compiling" (served on the eager
        # tier while the stage programs build in the background)
        exec_tier: Optional[str] = None
        # workload-manager class: the admission path stamps it on the
        # queued span; None when the scheduler is disabled
        priority: Optional[str] = None
        for s in root.walk():
            rc = s.attrs.get("result_cache")
            if rc == "hit":
                hit = True
                tier = s.attrs.get("result_cache_tier", tier)
            elif rc == "store":
                stored = True
            if s.attrs.get("subplan_cache") == "hit":
                subplan_hits += 1
            t = s.attrs.get("tier")
            if t is not None and exec_tier is None:
                exec_tier = str(t)
            if s.name == "queued" and priority is None:
                p = s.attrs.get("priority")
                priority = str(p) if p is not None else None
        self.tier = exec_tier
        self.priority = priority
        # adaptive operator choices (runtime/statistics.py record_choice
        # appends "groupby=dense ..." lines to span attrs) in span order
        operators: List[str] = []
        for s in root.walk():
            ops = s.attrs.get("operators")
            if ops:
                operators.extend(str(o) for o in ops)
        self.operators = operators
        # out-of-core marker: the grace-hash driver annotates its morsel
        # spans with spilled=True; the counter delta catches spills from
        # nested plans that never opened a span under this trace
        self.spilled = (self.counters.get("spill_partitions", 0) > 0
                        or any(s.attrs.get("spilled")
                               for s in root.walk()))
        # device-level profile surface (ISSUE 13): worst shard/partition
        # skew (max/mean row ratio — SPMD stages and grace-hash morsel
        # joins both annotate ``skew_ratio``), collective bytes by kind,
        # and the XLA cost-model error vs measured stage bytes; all None
        # when nothing annotated them (profiler off / single device)
        skew: Optional[float] = None
        coll: Dict[str, int] = {}
        cost_bytes = 0.0
        measured = 0
        for s in root.walk():
            r = s.attrs.get("skew_ratio")
            if r is not None:
                skew = max(float(r), skew) if skew is not None else float(r)
            for attr, kind in (("spmd_exchange_bytes", "all_to_all"),
                               ("spmd_all_gather_bytes", "all_gather"),
                               ("spmd_psum_bytes", "psum")):
                v = s.attrs.get(attr)
                if v:
                    coll[kind] = coll.get(kind, 0) + int(v)
            cb = s.attrs.get("cost_bytes")
            if cb:
                cost_bytes += float(cb)
            sb = s.attrs.get("stage_bytes")
            if sb:
                measured += int(sb)
        self.skew_ratio = round(skew, 3) if skew is not None else None
        self.collective_bytes = coll or None
        # measured working set mirrors the flight recorder's definition:
        # result bytes plus every materialized stage boundary
        measured += self.bytes_out
        self.cost_err = (round(abs(cost_bytes - measured) / measured, 4)
                         if cost_bytes and measured else None)
        self.cache = {"hit": hit, "tier": tier, "stored": stored,
                      "subplan_hits": subplan_hits,
                      "bytes": int(REGISTRY.get_gauge("result_cache_bytes")),
                      "host_bytes":
                          int(REGISTRY.get_gauge("result_cache_host_bytes"))}

    def span_count(self, name: str) -> int:
        return sum(1 for s in self.root.walk() if s.name == name)

    def to_dict(self) -> dict:
        out = {"query": self.query, "wall_ms": round(self.wall_ms, 3),
               "trace_id": self.trace_id,
               "tenant": self.tenant,
               "phases": {k: round(v, 3) for k, v in self.phases.items()},
               "counters": dict(self.counters),
               "cache": dict(self.cache),
               "tier": self.tier,
               "priority": self.priority,
               "operators": list(self.operators),
               "spilled": self.spilled,
               "skew_ratio": self.skew_ratio,
               "collective_bytes": self.collective_bytes,
               "cost_err": self.cost_err,
               "rows_out": self.rows_out, "bytes_out": self.bytes_out,
               "spans": self.root.to_dict()}
        # fleet-armed only, so the unarmed dict stays key-identical
        if self.replica:
            out["replica"] = self.replica
        return out

    def render(self) -> str:
        """Human-readable report: header + indented span tree."""
        lines = [f"query: {self.query.strip()[:200]}",
                 f"wall: {self.wall_ms:.2f} ms  rows_out: {self.rows_out}"
                 f"  bytes_out: {self.bytes_out}"]
        if self.phases:
            lines.append("phases: " + "  ".join(
                f"{k}={v:.2f}ms" for k, v in sorted(self.phases.items())))
        if self.counters:
            lines.append("counters: " + "  ".join(
                f"{k}=+{v}" for k, v in sorted(self.counters.items())))
        if self.operators:
            lines.append("operators: " + "; ".join(self.operators))
        if self.spilled:
            lines.append("spilled: true")
        if self.skew_ratio is not None:
            lines.append(f"skew_ratio: {self.skew_ratio}")
        if self.collective_bytes:
            lines.append("collective_bytes: " + "  ".join(
                f"{k}={v}" for k, v in sorted(self.collective_bytes.items())))
        if self.cost_err is not None:
            lines.append(f"cost_err: {self.cost_err}")

        def walk(s: Span, depth: int):
            attrs = "".join(f" {k}={v}" for k, v in sorted(s.attrs.items()))
            lines.append(f"{'  ' * depth}{s.name}: {s.wall_ms:.2f} ms"
                         + attrs)
            for c in s.children:
                walk(c, depth + 1)

        walk(self.root, 0)
        return "\n".join(lines)

    def to_chrome_trace(self) -> dict:
        """chrome://tracing ("Trace Event Format") JSON of the span tree:
        complete ("X") events in microseconds relative to the root."""
        t0 = self.root.t0
        events = []
        for s in self.root.walk():
            end = s.t1 if s.t1 is not None else time.perf_counter()
            events.append({
                "name": s.name, "ph": "X", "pid": os.getpid(),
                "tid": s.tid,
                "ts": round((s.t0 - t0) * 1e6, 1),
                "dur": round((end - s.t0) * 1e6, 1),
                "args": {k: (v if isinstance(v, (int, float, str, bool))
                             else repr(v))
                         for k, v in s.attrs.items()},
            })
        other = {"query": self.query[:500]}
        if self.trace_id:
            other["trace_id"] = self.trace_id
        if self.replica:
            other["replica"] = self.replica
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": other}


def last_report() -> Optional[QueryReport]:
    """The report of the most recent trace CLOSED on this thread —
    race-free per-query attribution for the server's worker threads."""
    return _tls.last_report


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else None
    except ValueError:
        return None


_chrome_counter = [0]
_chrome_lock = threading.Lock()


def _export_chrome_trace(report: QueryReport) -> None:
    """Write the span tree as chrome://tracing JSON when
    ``DSQL_CHROME_TRACE_DIR`` is armed; shared by the per-query close and
    the background-compile daemon threads (close_background_trace)."""
    trace_dir = os.environ.get("DSQL_CHROME_TRACE_DIR")
    if not trace_dir:
        return
    try:
        os.makedirs(trace_dir, exist_ok=True)
        with _chrome_lock:
            _chrome_counter[0] += 1
            n = _chrome_counter[0]
        path = os.path.join(
            trace_dir, f"query_{os.getpid()}_{n:05d}.trace.json")
        with open(path, "w") as f:
            json.dump(report.to_chrome_trace(), f)
    except OSError as e:  # telemetry must never fail the query
        logger.debug("chrome trace export failed: %s", e)


def close_background_trace(trace: QueryTrace) -> QueryReport:
    """Close a NON-query trace (background compile daemon threads carry
    their own — physical/compiled._background_compile): builds the report
    and exports the chrome trace WITHOUT counting a query, arming the
    slow-query log, or recording a history envelope."""
    trace.root.t1 = time.perf_counter()
    report = QueryReport(trace)
    trace.report = report
    _export_chrome_trace(report)
    return report


def _close_trace(trace: QueryTrace, error: Optional[BaseException]) -> None:
    trace.root.t1 = time.perf_counter()
    if error is not None:
        trace.root.attrs["error"] = type(error).__name__
        REGISTRY.inc("query_errors")
    report = QueryReport(trace)
    trace.report = report
    _tls.last_report = report
    REGISTRY.inc("queries")
    REGISTRY.observe("query_wall_ms", report.wall_ms)
    for name in ("parse", "plan", "execute", "compile", "materialize"):
        v = report.phases.get(name)
        if v is not None:
            REGISTRY.observe(f"{name}_ms", v)

    slow_ms = _env_float("DSQL_SLOW_QUERY_MS")
    if slow_ms is not None and report.wall_ms >= slow_ms:
        REGISTRY.inc("slow_queries")
        logger.warning(
            "slow query (%.0f ms >= DSQL_SLOW_QUERY_MS=%.0f): %s | tier: %s "
            "| cacheHit: %s | priority: %s | skew: %s | collectives: %s "
            "| costErr: %s | phases: %s | counters: %s%s%s%s",
            report.wall_ms, slow_ms, report.query.strip()[:500],
            report.tier or "eager", bool(report.cache.get("hit")),
            report.priority or "-",
            report.skew_ratio if report.skew_ratio is not None else "-",
            report.collective_bytes or "-",
            report.cost_err if report.cost_err is not None else "-",
            {k: round(v, 1) for k, v in sorted(report.phases.items())},
            dict(sorted(report.counters.items())),
            # trace/tenant/replica correlation suffixes only when they
            # exist, so the line stays byte-identical with the features off
            f" | trace: {report.trace_id}" if report.trace_id else "",
            f" | tenant: {report.tenant}" if report.tenant else "",
            f" | replica: {report.replica}" if report.replica else "")

    _export_chrome_trace(report)

    # flight recorder (runtime/flight_recorder.py): the env gate keeps the
    # disabled hot path at ONE dict lookup — no import, no lock
    if os.environ.get("DSQL_HISTORY_FILE"):
        try:
            from . import flight_recorder as _fr
            _fr.record_query(report, error)
        except Exception:
            REGISTRY.inc("history_errors")
            logger.debug("flight recorder append failed", exc_info=True)

    # device profiler (runtime/profiler.py): same env-gate-before-import
    # discipline — DSQL_PROFILE=0 costs one dict lookup, zero imports
    if os.environ.get("DSQL_PROFILE", "0").strip() not in ("", "0"):
        try:
            from . import profiler as _prof
            _prof.on_query_complete(report)
        except Exception:
            logger.debug("profiler query hook failed", exc_info=True)

    # watchtower (runtime/events.py): SLO fold-in + query.done event —
    # same env-gate-before-import discipline as the two hooks above
    if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
        try:
            from . import events as _ev
            _ev.on_query_complete(report, error)
        except Exception:
            logger.debug("event hook failed", exc_info=True)

    # autopilot feedback (runtime/autopilot.py): hinted-run verdicts and
    # threshold-tripped hint recording — same env-gate-before-import
    if os.environ.get("DSQL_AUTOPILOT", "0").strip() not in ("", "0"):
        try:
            from . import autopilot as _ap
            _ap.on_query_complete(report, error)
        except Exception:
            logger.debug("autopilot hook failed", exc_info=True)


@contextmanager
def trace_scope(query: str = ""):
    """Open the per-query trace on this thread; yields the QueryTrace.

    Nested calls (a query issued from inside another query's execution)
    yield None and ride the enclosing trace as ordinary spans — one trace
    and one report per outermost ``Context.sql``."""
    if _tls.trace is not None:
        yield None
        return
    trace = QueryTrace(query)
    _tls.trace = trace
    _tls.span = trace.root
    _tls.exec_profile = {}
    # live-query registry for system.active / GET /v1/engine — gated on the
    # recorder's env knob so the disabled path allocates nothing
    registered = False
    if os.environ.get("DSQL_HISTORY_FILE"):
        try:
            from . import flight_recorder as _fr
            registered = _fr.begin_query(trace)
        except Exception:
            logger.debug("flight recorder begin failed", exc_info=True)
    # watchtower ingress: stamp the end-to-end trace ID on the root span
    # (server-minted / env-propagated / fresh) and publish query.begin —
    # env gate BEFORE import, zero cost when DSQL_EVENTS is off
    if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
        try:
            from . import events as _ev
            _ev.on_trace_open(trace)
        except Exception:
            logger.debug("event trace-open hook failed", exc_info=True)
    err: Optional[BaseException] = None
    try:
        yield trace
    except BaseException as e:
        err = e
        raise
    finally:
        _tls.trace = None
        _tls.span = None
        try:
            _close_trace(trace, err)
        except Exception:  # pragma: no cover - never mask the query result
            logger.exception("telemetry close failed")
        if registered:
            try:
                _fr.end_query(trace)
            except Exception:  # pragma: no cover - registry is advisory
                logger.debug("flight recorder end failed", exc_info=True)


# ---------------------------------------------------------------------------
# deprecated dict alias support (physical.compiled.stats)
# ---------------------------------------------------------------------------

try:
    from collections.abc import MutableMapping as _MutableMapping
except ImportError:  # pragma: no cover
    from collections import MutableMapping as _MutableMapping  # type: ignore


class CounterAlias(_MutableMapping):
    """DEPRECATED dict-shaped read-through view of REGISTRY's counters.

    Exists so the long-standing ``physical.compiled.stats`` surface keeps
    working (tests, fault_smoke, bench all read it, and ``dict(stats)``
    must keep snapshotting every counter).  Writes forward to the registry
    atomically — but note ``alias[k] += 1`` is still a two-step
    read-modify-write at the CALL SITE; new code must use
    ``telemetry.inc`` instead."""

    def __getitem__(self, key: str) -> int:
        v = REGISTRY.get(key)
        if v is None:
            raise KeyError(key)
        return v

    def __setitem__(self, key: str, value: int) -> None:
        REGISTRY.set(key, value)

    def __delitem__(self, key: str) -> None:
        raise TypeError("registry counters cannot be deleted")

    def __iter__(self):
        return iter(REGISTRY.counters())

    def __len__(self) -> int:
        return len(REGISTRY.counters())

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"CounterAlias({REGISTRY.counters()!r})"
