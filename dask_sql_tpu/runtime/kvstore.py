"""Shared cross-process JSON store plumbing.

Three subsystems persist small keyed JSON documents across process
boundaries with the SAME discipline — content-digest keys, atomic
tmp+rename writes, and corrupt-file tolerance (a broken store file must
degrade to "empty", never fail a query):

- the learned-caps file (``DSQL_CAPS_FILE``, physical/compiled.py),
- the quarantine store (``DSQL_QUARANTINE_FILE``, runtime/quarantine.py),
- the program store's metadata index (``DSQL_PROGRAM_STORE``,
  runtime/program_store.py).

Before this module each carried its own copy of the read/replace logic
(drifting in small ways: tmp-name collision scope, mtime caching, value
filtering).  This is the one implementation they all share.

Concurrency model (unchanged from the originals): writes are
read-merge-replace under an atomic ``os.replace``, so concurrent writers
can lose a race — costing one re-learn / re-mark — but can never corrupt
or interleave bytes.  Tmp names are per-(pid, thread) so two threads of
one process cannot collide either.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)


def digest_key(obj, size: int = 16) -> str:
    """Stable content digest of ``repr(obj)`` — the shared keying scheme
    of every cross-process store (caps, quarantine, programs)."""
    return hashlib.blake2b(repr(obj).encode(), digest_size=size).hexdigest()


def read_json_dict(path: str) -> Dict[str, dict]:
    """Load a {key: dict} JSON file, tolerant of a missing, corrupt, or
    truncated file and of non-dict values (both read as absent)."""
    try:
        with open(path) as f:
            loaded = json.load(f)
        if not isinstance(loaded, dict):
            return {}
        return {k: dict(v) for k, v in loaded.items() if isinstance(v, dict)}
    except (OSError, ValueError):
        return {}


def atomic_write_json(path: str, data: dict) -> bool:
    """Write ``data`` as JSON via tmp + atomic rename; False (logged at
    debug) when the path is unwritable — persistence is an optimization,
    never a crash source."""
    tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)
        return True
    except OSError:
        logger.debug("store file %s not writable", path)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


class MtimeCachedJsonFile:
    """A {key: dict} JSON file with an mtime-validated in-memory cache
    (reads are cheap enough for per-query hot paths) and read-merge-replace
    writes.  ``path`` is re-resolved per call via the callable so env-flipped
    configuration (tests, operators) takes effect without restart."""

    def __init__(self, path_fn):
        self._path_fn = path_fn
        self._lock = threading.Lock()
        self._cached: Dict[str, dict] = {}
        self._cached_mtime: Optional[int] = None

    def path(self) -> Optional[str]:
        return self._path_fn()

    def read(self) -> Dict[str, dict]:
        path = self.path()
        if not path:
            return {}
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            with self._lock:
                self._cached, self._cached_mtime = {}, None
            return {}
        with self._lock:
            if self._cached_mtime == mtime:
                return dict(self._cached)
        data = read_json_dict(path)
        with self._lock:
            self._cached, self._cached_mtime = data, mtime
        return dict(data)

    def write(self, data: Dict[str, dict]) -> None:
        path = self.path()
        if not path:
            return
        if atomic_write_json(path, data):
            with self._lock:
                self._cached = dict(data)
                try:
                    self._cached_mtime = os.stat(path).st_mtime_ns
                except OSError:
                    self._cached_mtime = None
