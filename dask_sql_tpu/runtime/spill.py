"""Byte-accounted three-tier spill store: the out-of-core backbone.

Grace-hash partitioned joins (physical/morsel.py) stream both chunked
sides to host and hash-partition their rows into *runs* — named append-
only sequences of column chunks.  Those chunks have to live somewhere
that is not the device: the whole point of out-of-core execution is that
the working set exceeds one chip's HBM.  This store gives runs three
tiers with strict byte accounting and LRU movement between them:

- **device** — join *outputs* that are about to be consumed again stay
  as jax Tables when small enough, avoiding a host round trip.  The
  device tier is a tenant of the memory-broker ledger
  (runtime/scheduler.py MemoryLedger): ``reserve`` counts
  ``spill_device_bytes`` against the budget and calls
  ``shrink_device_to`` under pressure, demoting LRU chunks to host
  exactly like the result cache's device tier.
- **host** — numpy column layout ``(data, mask|None, stype, dictionary)``
  matching streaming's host-partial convention, capped by
  ``DSQL_SPILL_MB`` (MB, default 1024; **0 disables spilling** and with
  it the whole grace-hash path).
- **disk** — ``.npz`` files under ``DSQL_SPILL_DIR`` (default: a
  per-process directory in the system tempdir), written with the
  kvstore discipline: tmp + atomic ``os.replace``, content-digest
  names, corrupt-file tolerance surfacing as a TYPED error
  (``SpillCorrupt``) instead of a stack-trace lottery.

Fault discipline: every disk write/read passes the ``spill`` injection
site (runtime/faults.py) and is wrapped in ``retry_transient``, so
chaos soaks rehearse spill-IO transients on the same retry machinery as
every other fault site.  Counters (``spill_*``) and gauges
(``spill_{device,host,disk}_bytes``) are stable telemetry names.

Thread safety: one RLock per store guards run/tier mutation; byte
totals are plain ints readable without the lock (GIL-atomic) so the
ledger's admission math never blocks on spill IO.  Lock order: the
spill lock sits at the result-cache level — it never acquires the
ledger or manager locks (allowance reads are lock-free).
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import faults as _faults
from . import resilience as _res
from . import telemetry as _tel
from .kvstore import digest_key

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def spill_budget_bytes() -> int:
    """Host-tier cap in bytes; 0 disables spilling (and grace-hash)."""
    return max(_env_int("DSQL_SPILL_MB", 1024), 0) * (1 << 20)


def device_cap_bytes() -> int:
    """Device-tier cap (DSQL_SPILL_DEVICE_MB, default 64 MB) — a static
    ceiling; the broker's live allowance can only lower it further."""
    return max(_env_int("DSQL_SPILL_DEVICE_MB", 64), 0) * (1 << 20)


def enabled() -> bool:
    return spill_budget_bytes() > 0


def spill_dir() -> str:
    d = os.environ.get("DSQL_SPILL_DIR", "")
    if not d:
        d = os.path.join(tempfile.gettempdir(), f"dsql-spill-{os.getpid()}")
    return d


# ---------------------------------------------------------------------------
# typed errors
# ---------------------------------------------------------------------------

class SpillError(_res.FatalError):
    """A spill-store invariant broke (unknown run/chunk, impossible
    state).  Fatal: retrying the same attempt cannot help."""

    error_name = "SPILL_ERROR"


class SpillCorrupt(SpillError):
    """A disk chunk failed to load back (truncated / corrupt / vanished
    file).  The run's data is gone; the query must fail typed, not
    produce wrong rows."""

    error_name = "SPILL_CORRUPT"


# ---------------------------------------------------------------------------
# chunk records
# ---------------------------------------------------------------------------

#: host column layout, matching streaming's host-partial convention
HostCols = List[Tuple[np.ndarray, Optional[np.ndarray], object,
                      Optional[np.ndarray]]]


class _Chunk:
    __slots__ = ("run", "idx", "tier", "names", "stypes", "dicts",
                 "payload", "path", "nbytes", "rows")

    def __init__(self, run: str, idx: int, tier: str, names: List[str],
                 stypes: list, dicts: list, payload, nbytes: int,
                 rows: int):
        self.run = run
        self.idx = idx
        self.tier = tier            # "device" | "host" | "disk"
        self.names = names
        self.stypes = stypes        # per-column SqlType
        self.dicts = dicts          # per-column dictionary (or None)
        self.payload = payload      # device: Table; host: [(data, mask)]
        self.path: Optional[str] = None
        self.nbytes = nbytes
        self.rows = rows


def _host_cols_bytes(cols: HostCols) -> int:
    n = 0
    for data, mask, _stype, dictionary in cols:
        n += int(data.nbytes)
        if mask is not None:
            n += int(mask.nbytes)
        if dictionary is not None:
            n += int(getattr(dictionary, "nbytes", 0))
    return n


def _table_bytes(table) -> int:
    n = 0
    for col in table.columns:
        n += int(getattr(col.data, "nbytes", 0))
        if col.mask is not None:
            n += int(getattr(col.mask, "nbytes", 0))
    return n


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

class SpillStore:
    """Named runs of column chunks across device/host/disk tiers."""

    def __init__(self):
        self._lock = threading.RLock()
        self._runs: Dict[str, List[_Chunk]] = {}
        # LRU order within the movable tiers (front = coldest)
        self._device_lru: "OrderedDict[Tuple[str, int], _Chunk]" = \
            OrderedDict()
        self._host_lru: "OrderedDict[Tuple[str, int], _Chunk]" = \
            OrderedDict()
        # plain-int byte totals: lock-free reads for the ledger
        self.device_bytes = 0
        self.host_bytes = 0
        self.disk_bytes = 0
        self.peak_device_bytes = 0
        self._dir_ready = False
        self._seq = 0

    # -- writes ------------------------------------------------------------

    def put_host(self, run: str, names: List[str], cols: HostCols,
                 rows: Optional[int] = None) -> int:
        """Append one host-layout chunk to ``run``; returns its index.
        May flush LRU host chunks to disk to stay under DSQL_SPILL_MB."""
        if rows is None:
            rows = int(len(cols[0][0])) if cols else 0
        nbytes = _host_cols_bytes(cols)
        with self._lock:
            chunks = self._new_or_existing_run(run)
            idx = len(chunks)
            chunk = _Chunk(run, idx, "host", list(names),
                           [c[2] for c in cols], [c[3] for c in cols],
                           [(c[0], c[1]) for c in cols], nbytes, rows)
            chunks.append(chunk)
            self._host_lru[(run, idx)] = chunk
            self.host_bytes += nbytes
            _tel.inc("spill_chunks")
            _tel.inc("spill_bytes_host", nbytes)
            self._enforce_host_budget_locked()
            self._publish_gauges_locked()
        return idx

    def put_table(self, run: str, table) -> int:
        """Append a device Table chunk.  Stays on device when it fits
        both the static cap and the broker's live allowance; otherwise
        it is demoted to host layout immediately (counted as a
        demotion — the device tier REJECTED it, which is the signal
        skew diagnostics look for)."""
        nbytes = _table_bytes(table)
        if self._device_room_for(nbytes):
            with self._lock:
                if self._device_room_for(nbytes):
                    chunks = self._new_or_existing_run(run)
                    idx = len(chunks)
                    chunk = _Chunk(run, idx, "device", list(table.names),
                                   [c.stype for c in table.columns],
                                   [c.dictionary for c in table.columns],
                                   table, nbytes, int(table.num_rows))
                    chunks.append(chunk)
                    self._device_lru[(run, idx)] = chunk
                    self.device_bytes += nbytes
                    self.peak_device_bytes = max(self.peak_device_bytes,
                                                 self.device_bytes)
                    _tel.inc("spill_chunks")
                    self._publish_gauges_locked()
                    return idx
        _tel.inc("spill_demotions")
        return self.put_host(run, list(table.names),
                             self._table_to_host(table))

    # -- reads -------------------------------------------------------------

    def get_chunk(self, run: str, idx: int):
        """Fetch chunk ``idx`` of ``run`` as
        ``("device", names, Table)`` or ``("host", names, HostCols)``.
        Disk chunks load back to the host tier (a ``spill_loads``);
        either movable tier is touched to LRU-hot."""
        with self._lock:
            chunk = self._chunk_locked(run, idx)
            if chunk.tier == "device":
                self._device_lru.move_to_end((run, idx))
                return ("device", list(chunk.names), chunk.payload)
            if chunk.tier == "disk":
                self._load_locked(chunk)
            else:
                self._host_lru.move_to_end((run, idx))
            cols: HostCols = [
                (data, mask, chunk.stypes[ci], chunk.dicts[ci])
                for ci, (data, mask) in enumerate(chunk.payload)]
            return ("host", list(chunk.names), cols)

    def get_host_cols(self, run: str, idx: int) -> Tuple[List[str],
                                                         HostCols]:
        """Like get_chunk but always in host layout (device chunks are
        converted on the fly without changing their tier)."""
        tier, names, payload = self.get_chunk(run, idx)
        if tier == "device":
            return names, self._table_to_host(payload)
        return names, payload

    def chunk_meta(self, run: str, idx: int):
        """(names, stypes, dicts, rows) of one chunk WITHOUT touching its
        payload — disk chunks stay on disk (metadata lives in memory)."""
        with self._lock:
            chunk = self._chunk_locked(run, idx)
            return (list(chunk.names), list(chunk.stypes),
                    list(chunk.dicts), chunk.rows)

    def n_chunks(self, run: str) -> int:
        with self._lock:
            return len(self._runs.get(run, ()))

    def run_rows(self, run: str) -> int:
        with self._lock:
            return sum(c.rows for c in self._runs.get(run, ()))

    def run_bytes(self, run: str) -> int:
        with self._lock:
            return sum(c.nbytes for c in self._runs.get(run, ()))

    def has_run(self, run: str) -> bool:
        with self._lock:
            return run in self._runs

    # -- lifecycle ---------------------------------------------------------

    def free_run(self, run: str) -> None:
        """Drop a run and every chunk of it, across all tiers."""
        with self._lock:
            chunks = self._runs.pop(run, None)
            if not chunks:
                return
            for chunk in chunks:
                self._drop_chunk_locked(chunk)
            self._publish_gauges_locked()

    def clear(self) -> None:
        with self._lock:
            for run in list(self._runs):
                self.free_run(run)
            self.peak_device_bytes = 0

    def shrink_device_to(self, target: int) -> None:
        """Ledger pressure hook: demote LRU device chunks to host until
        the device tier occupies at most ``target`` bytes (mirrors
        result_cache.shrink_device_to)."""
        with self._lock:
            while self.device_bytes > max(target, 0) and self._device_lru:
                _key, chunk = next(iter(self._device_lru.items()))
                self._demote_locked(chunk)
            self._enforce_host_budget_locked()
            self._publish_gauges_locked()

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": enabled(),
                "runs": len(self._runs),
                "chunks": sum(len(c) for c in self._runs.values()),
                "device_bytes": self.device_bytes,
                "host_bytes": self.host_bytes,
                "disk_bytes": self.disk_bytes,
                "peak_device_bytes": self.peak_device_bytes,
                "host_budget": spill_budget_bytes(),
                "device_cap": device_cap_bytes(),
                "dir": spill_dir(),
            }

    def runs_snapshot(self) -> List[dict]:
        with self._lock:
            rows = []
            for run in sorted(self._runs):
                chunks = self._runs[run]
                tiers = {}
                for c in chunks:
                    tiers[c.tier] = tiers.get(c.tier, 0) + 1
                rows.append({
                    "run": run,
                    "chunks": len(chunks),
                    "rows": sum(c.rows for c in chunks),
                    "nbytes": sum(c.nbytes for c in chunks),
                    "device_chunks": tiers.get("device", 0),
                    "host_chunks": tiers.get("host", 0),
                    "disk_chunks": tiers.get("disk", 0),
                })
            return rows

    # -- internals ---------------------------------------------------------

    def _new_or_existing_run(self, run: str) -> List[_Chunk]:
        chunks = self._runs.get(run)
        if chunks is None:
            chunks = self._runs[run] = []
            _tel.inc("spill_partitions")
            if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
                try:
                    from . import events as _ev
                    _ev.publish("spill.run", run=run)
                except Exception:  # pragma: no cover - bus is advisory
                    pass
        return chunks

    def _chunk_locked(self, run: str, idx: int) -> _Chunk:
        chunks = self._runs.get(run)
        if chunks is None or not 0 <= idx < len(chunks):
            raise SpillError(f"spill: unknown chunk {run!r}[{idx}]")
        return chunks[idx]

    def _device_room_for(self, nbytes: int) -> bool:
        cap = device_cap_bytes()
        try:
            from . import scheduler as _sched
            cap = min(cap, _sched.get_manager().spill_allowance())
        except Exception:  # pragma: no cover - broker absent in bare use
            pass
        return self.device_bytes + nbytes <= cap

    @staticmethod
    def _table_to_host(table) -> HostCols:
        def fetch():
            _faults.maybe_fail("host_transfer")
            out: HostCols = []
            for col in table.columns:
                data = np.asarray(col.data)
                mask = None if col.mask is None else np.asarray(col.mask)
                out.append((data, mask, col.stype, col.dictionary))
            return out
        return _res.retry_transient(fetch, site="spill_fetch")

    def _demote_locked(self, chunk: _Chunk) -> None:
        """device -> host, in place."""
        cols = self._table_to_host(chunk.payload)
        self._device_lru.pop((chunk.run, chunk.idx), None)
        self.device_bytes -= chunk.nbytes
        chunk.tier = "host"
        chunk.payload = [(c[0], c[1]) for c in cols]
        chunk.stypes = [c[2] for c in cols]
        chunk.dicts = [c[3] for c in cols]
        chunk.nbytes = _host_cols_bytes(cols)
        self._host_lru[(chunk.run, chunk.idx)] = chunk
        self.host_bytes += chunk.nbytes
        _tel.inc("spill_demotions")
        _tel.inc("spill_bytes_host", chunk.nbytes)

    def _enforce_host_budget_locked(self, keep=None) -> None:
        """Flush coldest host chunks until under budget.  ``keep`` pins one
        (run, idx) — the chunk a caller is about to hand out — so a load
        that itself overflows the budget evicts OTHERS but never flushes
        the payload back out from under its reader."""
        budget = spill_budget_bytes()
        while self.host_bytes > budget and self._host_lru:
            key, chunk = next(iter(self._host_lru.items()))
            if key == keep:
                break
            self._flush_locked(chunk)

    def _ensure_dir(self) -> str:
        d = spill_dir()
        if not self._dir_ready:
            os.makedirs(d, exist_ok=True)
            self._dir_ready = True
        return d

    def _flush_locked(self, chunk: _Chunk) -> None:
        """host -> disk: atomic npz write on the kvstore discipline."""
        d = self._ensure_dir()
        self._seq += 1
        name = digest_key((chunk.run, chunk.idx, os.getpid(), self._seq))
        path = os.path.join(d, f"{name}.npz")
        arrays = {}
        for ci, (data, mask) in enumerate(chunk.payload):
            arrays[f"d{ci}"] = data
            if mask is not None:
                arrays[f"m{ci}"] = mask

        def write():
            _faults.maybe_fail("spill")
            tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
            try:
                with open(tmp, "wb") as f:
                    np.savez(f, **arrays)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        try:
            _res.retry_transient(write, site="spill_write")
        except _res.TransientError:
            _tel.inc("spill_errors")
            raise
        nbytes = os.path.getsize(path)
        self._host_lru.pop((chunk.run, chunk.idx), None)
        self.host_bytes -= chunk.nbytes
        chunk.tier = "disk"
        chunk.payload = [(None, mask is not None)
                         for _data, mask in chunk.payload]
        chunk.path = path
        chunk.nbytes = nbytes
        self.disk_bytes += nbytes
        _tel.inc("spill_flushes")
        _tel.inc("spill_bytes_disk", nbytes)

    def _load_locked(self, chunk: _Chunk) -> None:
        """disk -> host; corrupt/vanished files surface as SpillCorrupt."""
        path = chunk.path

        def read():
            _faults.maybe_fail("spill")
            with open(path, "rb") as f:
                with np.load(f, allow_pickle=False) as z:
                    cols = []
                    for ci, (_none, has_mask) in enumerate(chunk.payload):
                        data = z[f"d{ci}"]
                        mask = z[f"m{ci}"] if has_mask else None
                        cols.append((data, mask))
                    return cols

        try:
            # passthrough: a raw decode error must reach the except arm
            # below AS ITSELF (the classifier would wrap ValueError into
            # FatalError first and the SpillCorrupt conversion would miss)
            cols = _res.retry_transient(
                read, site="spill_read",
                passthrough=(OSError, ValueError, KeyError, EOFError))
        except _res.TransientError:
            _tel.inc("spill_errors")
            raise
        except (OSError, ValueError, KeyError, EOFError) as exc:
            _tel.inc("spill_errors")
            raise SpillCorrupt(
                f"spill: chunk {chunk.run!r}[{chunk.idx}] unreadable "
                f"at {path}: {exc}") from exc
        self.disk_bytes -= chunk.nbytes
        try:
            os.unlink(path)
        except OSError:
            pass
        chunk.tier = "host"
        chunk.payload = cols
        chunk.path = None
        chunk.nbytes = _host_cols_bytes(
            [(d, m, chunk.stypes[ci], chunk.dicts[ci])
             for ci, (d, m) in enumerate(cols)])
        self.host_bytes += chunk.nbytes
        self._host_lru[(chunk.run, chunk.idx)] = chunk
        _tel.inc("spill_loads")
        _tel.inc("spill_bytes_host", chunk.nbytes)
        # the load may push the host tier over budget; evict OTHERS — the
        # pinned key guarantees this chunk's payload survives the sweep
        # even when it alone exceeds the budget
        self._host_lru.move_to_end((chunk.run, chunk.idx))
        self._enforce_host_budget_locked(keep=(chunk.run, chunk.idx))

    def _drop_chunk_locked(self, chunk: _Chunk) -> None:
        if chunk.tier == "device":
            self._device_lru.pop((chunk.run, chunk.idx), None)
            self.device_bytes -= chunk.nbytes
        elif chunk.tier == "host":
            self._host_lru.pop((chunk.run, chunk.idx), None)
            self.host_bytes -= chunk.nbytes
        else:
            self.disk_bytes -= chunk.nbytes
            if chunk.path:
                try:
                    os.unlink(chunk.path)
                except OSError:
                    pass
        chunk.payload = None

    def _publish_gauges_locked(self) -> None:
        _tel.REGISTRY.set_gauge("spill_device_bytes", self.device_bytes)
        _tel.REGISTRY.set_gauge("spill_host_bytes", self.host_bytes)
        _tel.REGISTRY.set_gauge("spill_disk_bytes", self.disk_bytes)


# ---------------------------------------------------------------------------
# process-global store
# ---------------------------------------------------------------------------

_STORE: Optional[SpillStore] = None
_STORE_LOCK = threading.Lock()


def get_store() -> SpillStore:
    global _STORE
    if _STORE is None:
        with _STORE_LOCK:
            if _STORE is None:
                _STORE = SpillStore()
    return _STORE


def reset_store() -> None:
    """Testing hook: drop every run and forget the singleton."""
    global _STORE
    with _STORE_LOCK:
        if _STORE is not None:
            _STORE.clear()
        _STORE = None
