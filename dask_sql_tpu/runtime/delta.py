"""Delta maintenance beyond single-scan aggregates (ISSUE 20): the
algebra that lets `matview.py` refresh join- and COUNT(DISTINCT)-bearing
views in O(delta) instead of full recompute.

**Delta-join.**  For an INNER join tree over append-only bases, the new
result is multilinear in the inputs::

    J(A+ΔA, B+ΔB) = J(A,B) ∪ J(ΔA,B) ∪ J(A,ΔB) ∪ J(ΔA,ΔB)

Generalized to N scans left-to-right, the delta is the sum of one term
per appended scan i: scan i replaced by its delta, scans left of i by
their CURRENT table (old+delta), scans right of i by their OLD prefix —
each pair (old, delta) then meets exactly once across the terms.  Every
term executes through the *existing* compiled join stages (the defining
plan with its scans swapped for temps), so selection/projection
pipelines below or above the join ride along unchanged; the old prefix
is a zero-copy `Table.slice` because appends only ever concatenate.
Self-joins fall out for free — each scan position gets its own term.

**COUNT(DISTINCT).**  Maintained via refcounted value state: the cached
partial is ``GROUP BY keys, value -> COUNT(*) AS $rc``.  An append
merges by summing refcounts ($SUM0 over the concatenated state+delta
partials) and the view finalizes as ``COUNT(value) GROUP BY keys`` over
the state — O(distinct values), never a rescan.  Plain
``SELECT DISTINCT`` needs none of this (the binder lowers it to a
group-by that the base "agg" shape already maintains); this covers the
aggregate-call form the streaming algebra refuses.

Both shapes degrade exactly like the base machinery: any condition the
algebra cannot prove (outer joins, validity-masked or resharded bases,
a delta-log hole) raises ``_StateMissing``/refuses at analysis, and the
refresh falls back to a full recompute — wrong-never, slower-ok.
"""
from __future__ import annotations

import logging

from ..plan.nodes import (
    AggCall, Field, LogicalAggregate, LogicalFilter, LogicalJoin,
    LogicalProject, LogicalSort, LogicalTableScan,
)
from ..table import Table
from ..types import BIGINT
from . import matview as _mv

logger = logging.getLogger(__name__)

RC = "$rc"   # refcount column of the COUNT(DISTINCT) state


# ---------------------------------------------------------------------------
# analysis (called from matview._analyze)
# ---------------------------------------------------------------------------

def analyze_join(plan, chain, join, context):
    """(shape, reason) for a plan whose pipeline bottoms out at a
    LogicalJoin.  ``chain`` holds the nodes above the join, root-first."""
    if any(isinstance(n, LogicalAggregate) for n in chain):
        return None, ("aggregates over joins require full recompute "
                      "(group state is not linear in the join inputs)")
    if any(isinstance(n, LogicalSort) for n in chain):
        return None, ("ORDER BY/LIMIT over a join requires full recompute "
                      "(appended join results interleave with the "
                      "existing order)")
    for node in chain:
        exprs = (node.exprs if isinstance(node, LogicalProject)
                 else [node.condition] if isinstance(node, LogicalFilter)
                 else [])
        if any(_mv._rex_has_subquery(e) for e in exprs if e is not None):
            return None, "scalar subquery requires full recompute"
    scans = []
    reason = _walk_join(join, scans, context)
    if reason:
        return None, reason
    if len({id(s) for s in scans}) != len(scans):
        return None, ("shared scan node below a join requires full "
                      "recompute")
    if getattr(context, "mesh", None) is not None:
        return None, ("mesh-sharded bases reshard on append; delta-join "
                      "requires stable row prefixes")
    return _mv._Shape(kind="join", scan=scans[0], below=plan,
                      scans=list(scans)), ""


def _walk_join(node, scans, context):
    """Collect scans left-to-right; non-empty return = refusal reason."""
    if isinstance(node, LogicalJoin):
        if node.join_type != "INNER":
            return (f"{node.join_type} join requires full recompute (only "
                    "INNER joins maintain incrementally: outer/semi/anti "
                    "deltas can retract previously-emitted rows)")
        if getattr(node, "null_aware", False):
            return "null-aware join requires full recompute"
        if node.condition is not None \
                and _mv._rex_has_subquery(node.condition):
            return "scalar subquery requires full recompute"
        for i in node.inputs:
            r = _walk_join(i, scans, context)
            if r:
                return r
        return ""
    if isinstance(node, (LogicalProject, LogicalFilter)):
        exprs = (node.exprs if isinstance(node, LogicalProject)
                 else [node.condition])
        if any(_mv._rex_has_subquery(e) for e in exprs if e is not None):
            return "scalar subquery requires full recompute"
        return _walk_join(node.inputs[0], scans, context)
    if isinstance(node, LogicalTableScan):
        schema = context.schema.get(node.schema_name)
        entry = (schema.tables.get(node.table_name)
                 if schema is not None else None)
        if entry is None:
            return f"base table {node.table_name} not resolvable"
        if entry.chunked is not None:
            return ("chunked base table streams from host; appends are "
                    "not delta-tracked")
        if entry.row_valid is not None:
            return ("validity-masked (mesh-padded) base requires full "
                    "recompute")
        scans.append(node)
        return ""
    return (f"{node.node_name()} below a join requires full recompute "
            "(only scan/filter/project pipelines feed delta-join terms)")


def analyze_distinct_agg(plan, scan, agg, above, below_chain):
    """(shape, reason) for an aggregate carrying DISTINCT calls.  Only
    the single unfiltered COUNT(DISTINCT col) form maintains (refcounted
    state); anything else stays a full recompute with a reason."""
    refuse = ("only a single unfiltered COUNT(DISTINCT col) maintains "
              "incrementally (refcounted value state); other DISTINCT "
              "aggregates require full recompute")
    if len(agg.aggs) != 1:
        return None, refuse
    call = agg.aggs[0]
    if (call.op != "COUNT" or not call.distinct or len(call.args) != 1
            or call.filter_arg is not None or call.udaf is not None):
        return None, refuse
    cd_arg = call.args[0]
    if cd_arg in agg.group_keys:
        return None, ("COUNT(DISTINCT) over a grouping column requires "
                      "full recompute")
    below = agg.inputs[0]
    gk = len(agg.group_keys)
    group_fields = [Field(f.name, f.stype) for f in agg.schema[:gk]]
    state_schema = group_fields + [Field("$v", below.schema[cd_arg].stype),
                                   Field(RC, BIGINT)]
    return _mv._Shape(kind="cdistinct", scan=scan, below=below, agg=agg,
                      above=list(above), partial_schema=state_schema,
                      cd_arg=cd_arg), ""


# ---------------------------------------------------------------------------
# shared plumbing
# ---------------------------------------------------------------------------

def _align(table: Table, scan: LogicalTableScan) -> Table:
    """Project a base-layout table onto the (possibly column-pruned,
    reordered) scan schema by name; a miss degrades to full recompute."""
    lut = {n.lower(): col for n, col in zip(table.names, table.columns)}
    try:
        return Table([f.name for f in scan.schema],
                     [lut[f.name.lower()] for f in scan.schema])
    except KeyError as exc:
        raise _mv._StateMissing(
            f"delta does not cover scanned column {exc}") from exc


# ---------------------------------------------------------------------------
# delta-join refresh
# ---------------------------------------------------------------------------

def refresh_join(reg, context, mv, pending) -> None:
    """current view ∪ one multilinear term per appended scan position.
    Runs under the registry lock (appends hold it while swapping the
    catalog, so every entry read here is one consistent snapshot)."""
    from ..ops.join import concat_tables

    shape = mv.shape
    cur = {}
    for key in mv.base_tables:
        schema = context.schema.get(key[0])
        entry = schema.tables.get(key[1]) if schema is not None else None
        if entry is None or entry.table is None:
            raise _mv._StateMissing(
                f"base table {key[0]}.{key[1]} not resident")
        if entry.row_valid is not None:
            raise _mv._StateMissing(
                f"base table {key[0]}.{key[1]} grew a validity mask")
        cur[key] = entry.table
    deltas, appended = {}, {}
    for key, recs in pending.items():
        deltas[key] = (recs[0].table if len(recs) == 1
                       else concat_tables([r.table for r in recs]))
        appended[key] = sum(r.rows for r in recs)
    terms = []
    for i, scan in enumerate(shape.scans):
        ki = (scan.schema_name, scan.table_name)
        if ki not in deltas or deltas[ki].num_rows == 0:
            continue
        plan = _mv._replace(
            mv.plan, scan,
            _mv._register_temp(context, _align(deltas[ki], scan),
                               scan.schema))
        for j, other in enumerate(shape.scans):
            if j == i:
                continue
            kj = (other.schema_name, other.table_name)
            t = cur[kj]
            if j > i:
                # scans right of the delta position see the OLD prefix
                # (pre-append rows): appends only concatenate, so old is
                # a prefix slice of the current table
                n_old = t.num_rows - appended.get(kj, 0)
                if n_old < 0:
                    raise _mv._StateMissing(
                        f"delta log for {kj[0]}.{kj[1]} exceeds the "
                        "table size")
                if n_old != t.num_rows:
                    t = t.slice(0, n_old)
            plan = _mv._replace(
                plan, other,
                _mv._register_temp(context, _align(t, other), other.schema))
        terms.append(_mv._execute_plan(context, plan, eager=True))
    current = context.schema[mv.schema_name].tables[mv.name]
    result = (concat_tables([current.table] + terms)
              if terms else current.table)
    reg._swap(context, mv, result)


# ---------------------------------------------------------------------------
# COUNT(DISTINCT) refresh (refcounted state)
# ---------------------------------------------------------------------------

def _partial_plan(shape, input_node) -> LogicalAggregate:
    """GROUP BY keys, value -> COUNT(value) AS $rc over ``input_node``."""
    agg = shape.agg
    return LogicalAggregate(
        input=input_node,
        group_keys=list(agg.group_keys) + [shape.cd_arg],
        aggs=[AggCall("COUNT", [shape.cd_arg], False, BIGINT, RC)],
        schema=list(shape.partial_schema))


def _finalize_cdistinct(context, mv, state: Table) -> Table:
    """State (keys, value, $rc) -> view output: COUNT(value) per key
    group (COUNT skips the NULL-value refcount row, matching
    COUNT(DISTINCT)'s NULL semantics), then the nodes above the agg."""
    shape = mv.shape
    agg = shape.agg
    gk = len(agg.group_keys)
    out_field = agg.schema[gk]
    node = _mv._register_temp(context, state, shape.partial_schema)
    node = LogicalAggregate(
        input=node, group_keys=list(range(gk)),
        aggs=[AggCall("COUNT", [gk], False, out_field.stype,
                      out_field.name)],
        schema=list(agg.schema))
    for outer in reversed(shape.above):
        node = outer.with_inputs([node])
    return _mv._execute_plan(context, node, eager=True)


def refresh_full_cdistinct(reg, context, mv) -> None:
    """Full pass that also SEEDS the refcounted state, so the next
    refresh is O(delta) — mirrors matview's agg-kind full refresh."""
    from . import result_cache as _rc

    state = _mv._execute_plan(context, _partial_plan(mv.shape,
                                                     mv.shape.below))
    result = _finalize_cdistinct(context, mv, state)
    reg._swap(context, mv, result)
    cache = _rc.get_cache()
    if cache.enabled():
        cache.put(_mv._state_key(mv), state)


def refresh_cdistinct(reg, context, mv, delta_scan) -> None:
    """cached state ⊕ refcount partial over the delta -> new state."""
    from ..ops.join import concat_tables
    from . import result_cache as _rc

    shape = mv.shape
    gk = len(shape.agg.group_keys)
    cache = _rc.get_cache()
    state = cache.get(_mv._state_key(mv)) if cache.enabled() else None
    if state is None:
        raise _mv._StateMissing("maintained state not in result cache")
    state_table, _tier = state
    partial = _mv._execute_plan(
        context,
        _partial_plan(shape, _mv._replace(shape.below, shape.scan,
                                          delta_scan)),
        eager=True)
    merged_in = _mv._register_temp(
        context, concat_tables([state_table, partial]),
        shape.partial_schema)
    new_state = _mv._execute_plan(context, LogicalAggregate(
        input=merged_in, group_keys=list(range(gk + 1)),
        aggs=[AggCall("$SUM0", [gk + 1], False, BIGINT, RC)],
        schema=list(shape.partial_schema)), eager=True)
    result = _finalize_cdistinct(context, mv, new_state)
    reg._swap(context, mv, result)
    cache.put(_mv._state_key(mv), new_state)
