"""Bridge to the native (C++) plan optimizer.

The reference's planner is native end-to-end — parse, validate, plan and
HepPlanner optimization all happen inside DaskSQL.jar
(/root/reference/planner/src/main/java/com/dask/sql/application/
RelationalAlgebraGenerator.java:87-224).  Here the parse step has been
native since round 2 (native/parser.cpp); this module makes the rule
OPTIMIZER native too: the bound plan serializes to JSON, native/optimizer.cpp
(a lockstep port of plan/optimizer.py) applies the PASSES pipeline +
subplan optimization + column pruning, and the result deserializes back.

The Python optimizer remains the fallback — and the semantics reference —
for plans carrying Python-only payloads the wire format cannot express:
scalar/UDF calls (RexUdf), custom aggregations (AggCall.udaf), plan nodes
outside the core vocabulary (e.g. LogicalPredict), or non-finite float
literals.  ``serialize_plan`` returns None for those and the caller runs
the Python pipeline; tests/unit/test_native_optimizer.py asserts explain()
equality between the two paths over the TPC-H + fixture corpus.
"""
from __future__ import annotations

import json
import logging
from typing import Any, List, Optional

from ..types import SqlType
from .nodes import (
    AggCall, Field, LogicalAggregate, LogicalExcept, LogicalFilter,
    LogicalIntersect, LogicalJoin, LogicalProject, LogicalSample, LogicalSort,
    LogicalTableScan, LogicalUnion, LogicalValues, LogicalWindow, RelNode,
    RexCall, RexInputRef, RexLiteral, RexNode, RexScalarSubquery,
    SortCollation, WindowCall,
)

logger = logging.getLogger(__name__)

_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1


class _Unserializable(Exception):
    """Plan carries a payload the native wire format cannot express."""


# --------------------------------------------------------------- serialize

def _type_to_json(t: SqlType) -> list:
    return [t.name, t.precision, t.scale, t.nullable]


def _type_from_json(v: list) -> SqlType:
    return SqlType(v[0], v[1], v[2], v[3])


def _field_to_json(f: Field) -> list:
    return [f.name, _type_to_json(f.stype)]


def _schema_to_json(schema: List[Field]) -> list:
    return [_field_to_json(f) for f in schema]


def _schema_from_json(v: list) -> List[Field]:
    return [Field(name, _type_from_json(t)) for name, t in v]


def _rex_to_json(r: RexNode) -> list:
    if isinstance(r, RexInputRef):
        return ["in", r.index, _type_to_json(r.stype)]
    if isinstance(r, RexLiteral):
        v = r.value
        if v is None:
            return ["lit", "n", None, _type_to_json(r.stype)]
        if isinstance(v, bool):
            return ["lit", "b", v, _type_to_json(r.stype)]
        if isinstance(v, int):
            if not (_INT64_MIN <= v <= _INT64_MAX):
                raise _Unserializable("int literal outside int64")
            return ["lit", "i", v, _type_to_json(r.stype)]
        if isinstance(v, float):
            if v != v or v in (float("inf"), float("-inf")):
                raise _Unserializable("non-finite float literal")
            return ["lit", "f", v, _type_to_json(r.stype)]
        if isinstance(v, str):
            return ["lit", "s", v, _type_to_json(r.stype)]
        raise _Unserializable(f"literal of type {type(v).__name__}")
    if isinstance(r, RexScalarSubquery):
        return ["subq", _rel_to_json(r.plan), _type_to_json(r.stype)]
    if isinstance(r, RexCall):
        if r.info is not None and not isinstance(r.info, SqlType):
            raise _Unserializable("non-type call info")
        return ["call", r.op, [_rex_to_json(o) for o in r.operands],
                _type_to_json(r.stype),
                None if r.info is None else _type_to_json(r.info)]
    # RexUdf, RexOuterRef, anything unknown
    raise _Unserializable(f"rex {type(r).__name__}")


def _agg_to_json(a: AggCall) -> list:
    if a.udaf is not None:
        raise _Unserializable("custom aggregation (udaf)")
    return [a.op, list(a.args), a.distinct, _type_to_json(a.stype), a.name,
            a.filter_arg]


def _coll_to_json(c: SortCollation) -> list:
    return [c.index, c.ascending, c.nulls_first]


def _frame_to_json(frame) -> Any:
    # opaque round-trip: (kind, (bound, n|None), (bound, n|None)) or None
    if frame is None:
        return None
    kind, lo, hi = frame
    return [kind, list(lo), list(hi)]


def _rel_to_json(rel: RelNode) -> dict:
    if isinstance(rel, LogicalTableScan):
        out = {"k": "scan", "sn": rel.schema_name, "tn": rel.table_name}
    elif isinstance(rel, LogicalProject):
        out = {"k": "proj", "in": _rel_to_json(rel.input),
               "exprs": [_rex_to_json(e) for e in rel.exprs]}
    elif isinstance(rel, LogicalFilter):
        out = {"k": "filt", "in": _rel_to_json(rel.input),
               "cond": _rex_to_json(rel.condition)}
    elif isinstance(rel, LogicalAggregate):
        out = {"k": "agg", "in": _rel_to_json(rel.input),
               "gk": list(rel.group_keys),
               "aggs": [_agg_to_json(a) for a in rel.aggs]}
    elif isinstance(rel, LogicalJoin):
        out = {"k": "join", "l": _rel_to_json(rel.left),
               "r": _rel_to_json(rel.right), "jt": rel.join_type,
               "cond": (None if rel.condition is None
                        else _rex_to_json(rel.condition)),
               "na": bool(getattr(rel, "null_aware", False))}
    elif isinstance(rel, LogicalSort):
        out = {"k": "sort", "in": _rel_to_json(rel.input),
               "coll": [_coll_to_json(c) for c in rel.collation],
               "limit": rel.limit, "offset": rel.offset}
    elif isinstance(rel, (LogicalUnion, LogicalIntersect, LogicalExcept)):
        kinds = {LogicalUnion: "union", LogicalIntersect: "intersect",
                 LogicalExcept: "except"}
        out = {"k": kinds[type(rel)],
               "ins": [_rel_to_json(i) for i in rel.inputs_],
               "all": rel.all}
    elif isinstance(rel, LogicalValues):
        out = {"k": "values",
               "rows": [[_rex_to_json(e) for e in row] for row in rel.rows]}
    elif isinstance(rel, LogicalWindow):
        out = {"k": "window", "in": _rel_to_json(rel.input),
               "calls": [[c.op, list(c.args), list(c.partition),
                          [_coll_to_json(k) for k in c.order],
                          _frame_to_json(c.frame), _type_to_json(c.stype),
                          c.name] for c in rel.calls]}
    elif isinstance(rel, LogicalSample):
        out = {"k": "sample", "in": _rel_to_json(rel.input),
               "method": rel.method, "pct": float(rel.percentage),
               "seed": rel.seed}
    else:
        # LogicalPredict and any future node type: Python pipeline only
        raise _Unserializable(f"rel {type(rel).__name__}")
    out["schema"] = _schema_to_json(rel.schema)
    return out


# ------------------------------------------------------------- deserialize

def _rex_from_json(v: list) -> RexNode:
    tag = v[0]
    if tag == "in":
        return RexInputRef(v[1], _type_from_json(v[2]))
    if tag == "lit":
        lt, val = v[1], v[2]
        stype = _type_from_json(v[3])
        if lt == "n":
            return RexLiteral(None, stype)
        if lt == "b":
            return RexLiteral(bool(val), stype)
        if lt == "i":
            return RexLiteral(int(val), stype)
        if lt == "f":
            return RexLiteral(float(val), stype)
        return RexLiteral(val, stype)
    if tag == "call":
        return RexCall(v[1], [_rex_from_json(o) for o in v[2]],
                       _type_from_json(v[3]),
                       None if v[4] is None else _type_from_json(v[4]))
    if tag == "subq":
        return RexScalarSubquery(_rel_from_json(v[1]), _type_from_json(v[2]))
    raise ValueError(f"unknown rex tag {tag!r}")


def _coll_from_json(v: list) -> SortCollation:
    return SortCollation(v[0], v[1], v[2])


def _frame_from_json(v) -> Any:
    if v is None:
        return None
    kind, lo, hi = v
    return (kind, (lo[0], lo[1]), (hi[0], hi[1]))


def _rel_from_json(v: dict) -> RelNode:
    k = v["k"]
    schema = _schema_from_json(v["schema"])
    if k == "scan":
        return LogicalTableScan(v["sn"], v["tn"], schema)
    if k == "proj":
        return LogicalProject(_rel_from_json(v["in"]),
                              [_rex_from_json(e) for e in v["exprs"]], schema)
    if k == "filt":
        return LogicalFilter(_rel_from_json(v["in"]),
                             _rex_from_json(v["cond"]), schema)
    if k == "agg":
        aggs = [AggCall(a[0], list(a[1]), a[2], _type_from_json(a[3]), a[4],
                        a[5], None) for a in v["aggs"]]
        return LogicalAggregate(_rel_from_json(v["in"]), list(v["gk"]), aggs,
                                schema)
    if k == "join":
        out = LogicalJoin(_rel_from_json(v["l"]), _rel_from_json(v["r"]),
                          v["jt"],
                          None if v["cond"] is None
                          else _rex_from_json(v["cond"]), schema)
        if v["na"]:
            out.null_aware = True  # type: ignore[attr-defined]
        return out
    if k == "sort":
        return LogicalSort(_rel_from_json(v["in"]),
                           [_coll_from_json(c) for c in v["coll"]],
                           v["limit"], v["offset"], schema)
    if k in ("union", "intersect", "except"):
        cls = {"union": LogicalUnion, "intersect": LogicalIntersect,
               "except": LogicalExcept}[k]
        return cls([_rel_from_json(i) for i in v["ins"]], v["all"], schema)
    if k == "values":
        return LogicalValues([[_rex_from_json(e) for e in row]
                              for row in v["rows"]], schema)
    if k == "window":
        calls = [WindowCall(c[0], list(c[1]), list(c[2]),
                            [_coll_from_json(x) for x in c[3]],
                            _frame_from_json(c[4]), _type_from_json(c[5]),
                            c[6]) for c in v["calls"]]
        return LogicalWindow(_rel_from_json(v["in"]), calls, schema)
    if k == "sample":
        return LogicalSample(_rel_from_json(v["in"]), v["method"], v["pct"],
                             v["seed"], schema)
    raise ValueError(f"unknown rel kind {k!r}")


# ------------------------------------------------------------------ public

def serialize_plan(plan: RelNode) -> Optional[str]:
    """Plan -> wire JSON, or None when the plan carries Python-only
    payloads (UDF/UDAF/unknown nodes) the native optimizer must not see."""
    try:
        return json.dumps(_rel_to_json(plan), ensure_ascii=False,
                          separators=(",", ":"))
    except _Unserializable as e:
        logger.debug("native optimizer skipped: %s", e)
        return None


def deserialize_plan(text: str) -> RelNode:
    return _rel_from_json(json.loads(text))


def optimize_native(plan: RelNode,
                    enable_pruning: bool = True) -> Optional[RelNode]:
    """Run the native optimizer; None => caller falls back to Python."""
    import os

    from .. import native as _native

    # checked per CALL, not only at library load: load() memoizes, so its
    # own DSQL_NATIVE check cannot honor a runtime toggle
    if os.environ.get("DSQL_NATIVE", "1") == "0":
        return None
    lib = _native.load()
    if lib is None or not hasattr(lib, "dsql_optimize"):
        return None
    wire = serialize_plan(plan)
    if wire is None:
        return None
    envelope = _native.optimize_to_json(wire, enable_pruning)
    if envelope is None:
        return None
    if "error" in envelope:
        # a native failure on a serializable plan is a lockstep bug: log
        # loudly (tests compare the two paths), run the Python pipeline
        logger.warning("native optimizer error: %s",
                       envelope["error"].get("msg"))
        return None
    try:
        return _rel_from_json(envelope["ok"])
    except Exception as e:
        logger.warning("native optimizer result undecodable: %s", e)
        return None
