"""Logical relational algebra: the plan-node vocabulary the physical layer lowers.

Same node vocabulary as the reference's Calcite plans (SURVEY §2.2): TableScan,
Project, Filter, Aggregate, Join, Sort(+limit/offset), Union/Intersect/Except,
Values, Window, Sample — produced by our native binder instead of
Calcite's SqlToRelConverter.  Expressions are *bound* REX trees: input
references by ordinal, typed literals in physical representation, and calls
with inferred result types (reference's RexInputRef/RexLiteral/RexCall
handled in /root/reference/dask_sql/physical/rex/core/).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from ..types import SqlType


@dataclass(frozen=True)
class Field:
    name: str
    stype: SqlType


# ===========================================================================
# REX: bound expressions
# ===========================================================================

class RexNode:
    stype: SqlType


@dataclass
class RexInputRef(RexNode):
    index: int
    stype: SqlType

    def __repr__(self):
        return f"${self.index}"


@dataclass
class RexLiteral(RexNode):
    value: Any              # physical representation (or None for NULL)
    stype: SqlType

    def __repr__(self):
        return repr(self.value)


@dataclass
class RexParam(RexNode):
    """A literal hoisted into a runtime argument (plan/parameterize.py).

    Deliberately NOT a ``RexLiteral`` subclass: every site that bakes a
    literal's VALUE into a compiled trace or a shape-level fingerprint
    dispatches on ``isinstance(rex, RexLiteral)``, and a param must never
    take those branches — unknown rex kinds fail safe everywhere
    (``compiled._fp_rex`` raises Unsupported, ``result_cache._canon_rex``
    marks the plan volatile) until a site opts in explicitly.

    The node carries its CURRENT value, so any (sub)plan containing params
    can self-supply its bound-argument vector: the compiled path collects
    params in fingerprint-traversal order and passes the values as trailing
    scalar jit arguments, while the eager/SPMD paths (which key on values)
    simply read ``value`` like a literal.  ``slot`` is the hoisting pass's
    deterministic numbering over the whole plan — stable per shape."""
    slot: int
    value: Any
    stype: SqlType

    def __repr__(self):
        return f"?p{self.slot}={self.value!r}"


@dataclass
class RexCall(RexNode):
    op: str                 # canonical operator name, e.g. "+", "AND", "SUBSTRING"
    operands: List[RexNode]
    stype: SqlType
    # extra payload for special ops (EXTRACT field symbols, cast targets...)
    info: Any = None

    def __repr__(self):
        return f"{self.op}({', '.join(map(repr, self.operands))})"


@dataclass
class RexScalarSubquery(RexNode):
    plan: "RelNode"
    stype: SqlType

    def __repr__(self):
        return "$scalar_subquery"


@dataclass
class RexOuterRef(RexNode):
    """Column of the enclosing query inside a correlated subquery.

    Exists only transiently during binding: the binder's decorrelation
    rewrites (EXISTS -> SEMI/ANTI join condition, scalar aggregate
    comparison -> grouped-aggregate join) eliminate every occurrence; a
    surviving one is a binder bug and has no executor."""
    index: int = 0
    stype: SqlType = None

    def __repr__(self):
        return f"$outer{self.index}"


@dataclass
class RexUdf(RexNode):
    """A registered python scalar UDF call (Context.register_function)."""
    name: str
    func: Any
    operands: List[RexNode]
    stype: SqlType
    row_udf: bool = False

    def __repr__(self):
        return f"udf:{self.name}({', '.join(map(repr, self.operands))})"


# ===========================================================================
# Aggregate / window call descriptors
# ===========================================================================

@dataclass
class AggCall:
    op: str                     # SUM, COUNT, AVG, MIN, MAX, ...
    args: List[int]             # input column ordinals
    distinct: bool
    stype: SqlType
    name: str
    filter_arg: Optional[int] = None   # ordinal of a BOOLEAN filter column
    udaf: Any = None                   # registered custom aggregation


@dataclass
class SortCollation:
    index: int
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = postgres default

    @property
    def effective_nulls_first(self) -> bool:
        # Postgres: NULLS LAST for ASC, NULLS FIRST for DESC
        if self.nulls_first is None:
            return not self.ascending
        return self.nulls_first


@dataclass
class WindowCall:
    op: str                     # ROW_NUMBER, SUM, COUNT, MAX, MIN, FIRST_VALUE...
    args: List[int]
    partition: List[int]
    order: List[SortCollation]
    frame: Optional[Tuple[str, Tuple[str, Optional[int]], Tuple[str, Optional[int]]]]
    stype: SqlType
    name: str


# ===========================================================================
# REL: plan nodes
# ===========================================================================

class RelNode:
    schema: List[Field]

    @property
    def inputs(self) -> List["RelNode"]:
        return []

    def with_inputs(self, inputs: List["RelNode"]) -> "RelNode":
        raise NotImplementedError

    def node_name(self) -> str:
        return type(self).__name__

    def explain(self, indent: int = 0, annotate=None) -> str:
        """Indented plan tree.  ``annotate``, when given, is a callback
        ``node -> str`` whose non-empty return is appended to that node's
        line — EXPLAIN ANALYZE uses it to attach measured wall-time and
        row counts without the tree renderer knowing about telemetry."""
        line = ("  " * indent) + self._explain_line()
        if annotate is not None:
            suffix = annotate(self)
            if suffix:
                line += " " + suffix
        lines = [line]
        for child in self.inputs:
            lines.append(child.explain(indent + 1, annotate))
        return "\n".join(lines)

    def _explain_line(self) -> str:
        return self.node_name()


@dataclass
class LogicalTableScan(RelNode):
    schema_name: str
    table_name: str
    schema: List[Field] = field(default_factory=list)

    def _explain_line(self):
        return f"LogicalTableScan(table=[[{self.schema_name}, {self.table_name}]])"


@dataclass
class LogicalProject(RelNode):
    input: RelNode = None
    exprs: List[RexNode] = field(default_factory=list)
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return LogicalProject(inputs[0], self.exprs, self.schema)

    def _explain_line(self):
        cols = ", ".join(f"{f.name}=[{e!r}]" for f, e in zip(self.schema, self.exprs))
        return f"LogicalProject({cols})"


@dataclass
class LogicalFilter(RelNode):
    input: RelNode = None
    condition: RexNode = None
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return LogicalFilter(inputs[0], self.condition, self.schema)

    def _explain_line(self):
        return f"LogicalFilter(condition=[{self.condition!r}])"


@dataclass
class LogicalAggregate(RelNode):
    input: RelNode = None
    group_keys: List[int] = field(default_factory=list)
    aggs: List[AggCall] = field(default_factory=list)
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return LogicalAggregate(inputs[0], self.group_keys, self.aggs, self.schema)

    def _explain_line(self):
        aggs = ", ".join(
            f"{a.name}=[{a.op}({'DISTINCT ' if a.distinct else ''}{', '.join('$%d' % i for i in a.args)})]"
            for a in self.aggs
        )
        return f"LogicalAggregate(group=[{self.group_keys}], {aggs})"


@dataclass
class LogicalJoin(RelNode):
    left: RelNode = None
    right: RelNode = None
    join_type: str = "INNER"       # INNER | LEFT | RIGHT | FULL | CROSS | SEMI | ANTI
    condition: Optional[RexNode] = None   # over [left fields..., right fields...]
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return [self.left, self.right]

    def with_inputs(self, inputs):
        out = LogicalJoin(inputs[0], inputs[1], self.join_type,
                          self.condition, self.schema)
        if hasattr(self, "null_aware"):
            out.null_aware = self.null_aware  # type: ignore[attr-defined]
        return out

    def _explain_line(self):
        return f"LogicalJoin(condition=[{self.condition!r}], joinType=[{self.join_type.lower()}])"


@dataclass
class LogicalSort(RelNode):
    """ORDER BY + LIMIT/OFFSET (Calcite folds fetch into Sort too)."""
    input: RelNode = None
    collation: List[SortCollation] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return LogicalSort(inputs[0], self.collation, self.limit, self.offset, self.schema)

    def _explain_line(self):
        parts = []
        for c in self.collation:
            parts.append(f"sort0=[${c.index} {'ASC' if c.ascending else 'DESC'}]")
        if self.limit is not None:
            parts.append(f"fetch=[{self.limit}]")
        if self.offset is not None:
            parts.append(f"offset=[{self.offset}]")
        return f"LogicalSort({', '.join(parts)})"


@dataclass
class LogicalUnion(RelNode):
    inputs_: List[RelNode] = field(default_factory=list)
    all: bool = False
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return list(self.inputs_)

    def with_inputs(self, inputs):
        return LogicalUnion(list(inputs), self.all, self.schema)

    def _explain_line(self):
        return f"LogicalUnion(all=[{self.all}])"


@dataclass
class LogicalIntersect(RelNode):
    inputs_: List[RelNode] = field(default_factory=list)
    all: bool = False
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return list(self.inputs_)

    def with_inputs(self, inputs):
        return LogicalIntersect(list(inputs), self.all, self.schema)


@dataclass
class LogicalExcept(RelNode):
    inputs_: List[RelNode] = field(default_factory=list)
    all: bool = False
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return list(self.inputs_)

    def with_inputs(self, inputs):
        return LogicalExcept(list(inputs), self.all, self.schema)


@dataclass
class LogicalValues(RelNode):
    rows: List[List[RexLiteral]] = field(default_factory=list)
    schema: List[Field] = field(default_factory=list)

    def _explain_line(self):
        return f"LogicalValues(tuples=[{len(self.rows)} rows])"


@dataclass
class LogicalWindow(RelNode):
    """Adds window-function result columns to the input schema."""
    input: RelNode = None
    calls: List[WindowCall] = field(default_factory=list)
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return LogicalWindow(inputs[0], self.calls, self.schema)

    def _explain_line(self):
        return f"LogicalWindow({', '.join(c.op for c in self.calls)})"


@dataclass
class LogicalSample(RelNode):
    input: RelNode = None
    method: str = "BERNOULLI"      # SYSTEM | BERNOULLI
    percentage: float = 100.0
    seed: Optional[int] = None
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return LogicalSample(inputs[0], self.method, self.percentage, self.seed, self.schema)

    def _explain_line(self):
        return f"LogicalSample(mode=[{self.method.lower()}], rate=[{self.percentage}])"


# ---------------------------------------------------------------------------
# rex utilities
# ---------------------------------------------------------------------------

def rex_inputs(rex: RexNode) -> List[int]:
    """All input ordinals referenced by a rex tree."""
    out: List[int] = []

    def walk(r: RexNode):
        if isinstance(r, RexInputRef):
            out.append(r.index)
        elif isinstance(r, (RexCall, RexUdf)):
            for o in r.operands:
                walk(o)

    walk(rex)
    return out


def shift_rex(rex: RexNode, delta: int, start: int = 0) -> RexNode:
    """Shift input refs >= start by delta (used when splicing plans)."""
    if isinstance(rex, RexInputRef):
        if rex.index >= start:
            return RexInputRef(rex.index + delta, rex.stype)
        return rex
    if isinstance(rex, RexCall):
        return RexCall(rex.op, [shift_rex(o, delta, start) for o in rex.operands],
                       rex.stype, rex.info)
    if isinstance(rex, RexUdf):
        return RexUdf(rex.name, rex.func, [shift_rex(o, delta, start) for o in rex.operands],
                      rex.stype, rex.row_udf)
    return rex


def remap_rex(rex: RexNode, mapping: dict) -> RexNode:
    """Rewrite input refs through an old->new ordinal mapping."""
    if isinstance(rex, RexInputRef):
        return RexInputRef(mapping[rex.index], rex.stype)
    if isinstance(rex, RexCall):
        return RexCall(rex.op, [remap_rex(o, mapping) for o in rex.operands],
                       rex.stype, rex.info)
    if isinstance(rex, RexUdf):
        return RexUdf(rex.name, rex.func, [remap_rex(o, mapping) for o in rex.operands],
                      rex.stype, rex.row_udf)
    return rex
