"""Binder/validator: AST -> typed logical plan.

Replaces the reference's Calcite validate + SqlToRelConverter step
(/root/reference/planner/.../RelationalAlgebraGenerator.java:97-115) with a
native implementation: name resolution against the Context catalog, result
type inference, aggregate/window extraction, star expansion, subquery
de-correlation (uncorrelated IN/EXISTS -> SEMI/ANTI joins, scalar subqueries ->
eagerly-evaluated scalars), and ordinal/alias resolution in GROUP BY/ORDER BY.
"""
from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..types import (
    BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, INTERVAL_DAY_TIME,
    INTERVAL_YEAR_MONTH, NULLTYPE, SqlType, TIMESTAMP, TIME, VARCHAR,
    parse_type_name, promote, python_value_to_physical,
)
from ..utils import ValidationException
from ..sql import ast as A
from . import functions as F
from .nodes import (
    AggCall, Field, LogicalAggregate, LogicalExcept, LogicalFilter,
    LogicalIntersect, LogicalJoin, LogicalProject, LogicalSample, LogicalSort,
    LogicalTableScan, LogicalUnion, LogicalValues, LogicalWindow, RelNode,
    RexCall, RexInputRef, RexLiteral, RexNode, RexOuterRef,
    RexScalarSubquery, RexUdf,
    SortCollation, WindowCall, rex_inputs, shift_rex,
)


# ---------------------------------------------------------------------------
# Scope
# ---------------------------------------------------------------------------

@dataclass
class ScopeEntry:
    qualifier: Optional[str]
    name: str
    stype: SqlType
    hidden: bool = False   # e.g. right-side duplicate of a USING column


class Scope:
    def __init__(self, entries: List[ScopeEntry]):
        self.entries = entries

    @staticmethod
    def from_fields(fields: List[Field], qualifier: Optional[str]) -> "Scope":
        return Scope([ScopeEntry(qualifier, f.name, f.stype) for f in fields])

    def concat(self, other: "Scope") -> "Scope":
        return Scope(self.entries + other.entries)

    def resolve(self, parts: List[str]) -> Optional[int]:
        """Return ordinal for a (possibly qualified) column name, None if absent."""
        if len(parts) == 1:
            name = parts[0]
            hits = [i for i, e in enumerate(self.entries) if e.name == name and not e.hidden]
            if not hits:
                hits = [i for i, e in enumerate(self.entries)
                        if e.name.lower() == name.lower() and not e.hidden]
            if len(hits) > 1:
                # identical duplicated names: ambiguous
                raise ValidationException("", f"Column '{name}' is ambiguous")
            return hits[0] if hits else None
        qual, name = parts[-2], parts[-1]
        hits = [
            i for i, e in enumerate(self.entries)
            if e.qualifier is not None
            and e.qualifier.lower() == qual.lower()
            and (e.name == name or e.name.lower() == name.lower())
        ]
        if len(hits) > 1:
            exact = [i for i in hits if self.entries[i].name == name]
            if len(exact) == 1:
                return exact[0]
            raise ValidationException("", f"Column '{qual}.{name}' is ambiguous")
        return hits[0] if hits else None


# ---------------------------------------------------------------------------
# internal placeholder rex for aggregate / window calls found while binding
# ---------------------------------------------------------------------------

@dataclass
class RexAggPlaceholder(RexNode):
    op: str
    operands: List[RexNode]
    distinct: bool
    filter: Optional[RexNode]
    stype: SqlType
    udaf: Any = None


@dataclass
class RexWindowPlaceholder(RexNode):
    op: str
    operands: List[RexNode]
    partition: List[RexNode]
    order: List[Tuple[RexNode, bool, Optional[bool]]]
    frame: Optional[tuple]
    stype: SqlType


def _rex_equal(a: RexNode, b: RexNode) -> bool:
    if type(a) is not type(b):
        return False
    if isinstance(a, RexInputRef):
        return a.index == b.index
    if isinstance(a, RexLiteral):
        return a.value == b.value and a.stype.name == b.stype.name
    if isinstance(a, RexCall):
        return (a.op == b.op and a.info == b.info and len(a.operands) == len(b.operands)
                and all(_rex_equal(x, y) for x, y in zip(a.operands, b.operands)))
    return a is b


def _contains_placeholder(rex: RexNode, cls) -> bool:
    if isinstance(rex, cls):
        return True
    if isinstance(rex, (RexCall, RexUdf)):
        return any(_contains_placeholder(o, cls) for o in rex.operands)
    if isinstance(rex, RexAggPlaceholder):
        return any(_contains_placeholder(o, cls) for o in rex.operands)
    return False


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------

def _ast_contains_agg(e: A.Expr, catalog) -> bool:
    if isinstance(e, A.Call):
        if e.over is None:
            name = e.op
            if F.is_aggregate(name):
                return True
            fd = catalog.get_function(getattr(e, "original_name", name))
            if fd is not None and fd.aggregation:
                return True
        return any(_ast_contains_agg(a, catalog) for a in e.args) or (
            e.filter is not None and _ast_contains_agg(e.filter, catalog)
        )
    for child in _ast_children(e):
        if _ast_contains_agg(child, catalog):
            return True
    return False


def _ast_children(e: A.Expr) -> List[A.Expr]:
    if isinstance(e, A.Call):
        return list(e.args)
    if isinstance(e, A.Case):
        out = []
        if e.operand:
            out.append(e.operand)
        for c, v in e.whens:
            out += [c, v]
        if e.else_:
            out.append(e.else_)
        return out
    if isinstance(e, A.Cast):
        return [e.expr]
    if isinstance(e, A.InList):
        return [e.expr] + list(e.values)
    if isinstance(e, A.Between):
        return [e.expr, e.low, e.high]
    if isinstance(e, A.Like):
        return [e.expr, e.pattern] + ([e.escape] if e.escape else [])
    if isinstance(e, A.IsNull):
        return [e.expr]
    if isinstance(e, A.IsBool):
        return [e.expr]
    if isinstance(e, A.IsDistinctFrom):
        return [e.left, e.right]
    if isinstance(e, A.Subquery):
        return [e.outer] if e.outer is not None else []
    return []


_INTERVAL_UNIT_MS = {
    "SECOND": 1000,
    "MINUTE": 60_000,
    "HOUR": 3_600_000,
    "DAY": 86_400_000,
    "WEEK": 7 * 86_400_000,
    "MILLISECOND": 1,
}


# ===========================================================================
# Binder
# ===========================================================================

class Binder:
    """Binds one statement. ``catalog`` is a Context-like object exposing
    resolve_table(parts) and get_function(name)."""

    def __init__(self, catalog, sql: str = "", outer_scope: Optional[Scope] = None,
                 params: Optional[list] = None):
        self.catalog = catalog
        self.sql = sql
        # positional parameter values for ?/$n markers (Context.sql(params=...)
        # and EXECUTE); None means "no parameters were supplied" — a marker
        # then stays a binding error exactly as before
        self.params = params
        self.cte_stack: List[Dict[str, RelNode]] = [{}]
        # enclosing query's scope for correlated subqueries: unresolved
        # columns become RexOuterRef and are eliminated by decorrelation
        self.outer_scope = outer_scope
        # SELECT-list correlated scalar subqueries decorrelated ahead of
        # expression binding: AST node id -> replacement rex
        self._select_sq_rex: Dict[int, RexNode] = {}

    def error(self, msg: str, node: Optional[A.Node] = None):
        pos = getattr(node, "pos", (0, 0)) if node is not None else (0, 0)
        line, col = pos if pos != (0, 0) else (None, None)
        raise ValidationException(self.sql, msg, line, col)

    # ------------------------------------------------------------ entry point
    def bind(self, query: A.SelectLike) -> RelNode:
        return self.bind_query(query)

    def bind_query(self, q: A.SelectLike) -> RelNode:
        if isinstance(q, A.Select):
            return self.bind_select(q)
        if isinstance(q, A.SetOp):
            return self.bind_setop(q)
        if isinstance(q, A.ValuesQuery):
            return self.bind_values(q)
        self.error(f"Unsupported query node {type(q).__name__}", q)

    # ---------------------------------------------------------------- values
    def bind_values(self, q: A.ValuesQuery) -> RelNode:
        rows = []
        ncols = len(q.rows[0])
        col_types: List[SqlType] = [NULLTYPE] * ncols
        for row in q.rows:
            if len(row) != ncols:
                self.error("VALUES rows must have equal arity", q)
            bound_row = []
            for j, e in enumerate(row):
                rex = self.bind_expr(e, Scope([]))
                if not isinstance(rex, RexLiteral):
                    rex = _fold_to_literal(rex)
                    if rex is None:
                        self.error("VALUES must contain literals", e)
                bound_row.append(rex)
                col_types[j] = promote(col_types[j], rex.stype) if col_types[j].name != "NULL" or rex.stype.name != "NULL" else NULLTYPE
            rows.append(bound_row)
        fields = [Field(f"EXPR${j}", col_types[j] if col_types[j].name != "NULL" else INTEGER)
                  for j in range(ncols)]
        return LogicalValues(rows=rows, schema=fields)

    # ---------------------------------------------------------------- set ops
    def bind_setop(self, q: A.SetOp) -> RelNode:
        left = self.bind_query(q.left)
        right = self.bind_query(q.right)
        if len(left.schema) != len(right.schema):
            self.error(f"{q.op} inputs must have the same number of columns", q)
        fields = []
        for lf, rf in zip(left.schema, right.schema):
            fields.append(Field(lf.name, promote(lf.stype, rf.stype)))
        cls = {"UNION": LogicalUnion, "INTERSECT": LogicalIntersect,
               "EXCEPT": LogicalExcept}[q.op]
        plan: RelNode = cls(inputs_=[left, right], all=q.all, schema=fields)
        if q.order_by or q.limit is not None or q.offset is not None:
            scope = Scope.from_fields(fields, None)
            plan = self._apply_order_limit(plan, scope, q.order_by, q.limit,
                                           q.offset, output_fields=fields)
        return plan

    # ---------------------------------------------------------------- select
    def bind_select(self, q: A.Select) -> RelNode:
        # CTEs: later CTEs may reference earlier ones (frame mutated in order)
        if q.ctes:
            frame = dict(self.cte_stack[-1])
            self.cte_stack.append(frame)
            for name, cte_q in q.ctes:
                frame[name.lower()] = self.bind_query(cte_q)
        try:
            return self._bind_select_body(q)
        finally:
            if q.ctes:
                self.cte_stack.pop()

    def _bind_select_body(self, q: A.Select) -> RelNode:
        # ---- FROM
        if q.from_ is not None:
            plan, scope = self.bind_relation(q.from_)
        else:
            plan = LogicalValues(rows=[[RexLiteral(0, INTEGER)]],
                                 schema=[Field("__dummy__", INTEGER)])
            scope = Scope([ScopeEntry(None, "__dummy__", INTEGER, hidden=True)])

        # ---- WHERE (with subquery conjunct handling)
        if q.where is not None:
            plan, scope = self._apply_filter_with_subqueries(plan, scope, q.where)

        # ---- expand stars
        proj_items: List[Tuple[A.Expr, Optional[str]]] = []
        for e, alias in q.projections:
            if isinstance(e, A.Star):
                for i, entry in enumerate(scope.entries):
                    if entry.hidden:
                        continue
                    if e.table is not None and (entry.qualifier or "").lower() != e.table.lower():
                        continue
                    proj_items.append((A.ColumnRef(parts=_entry_parts(entry)), entry.name))
                if not proj_items and e.table is not None:
                    self.error(f"Unknown table alias '{e.table}' in star", e)
            else:
                proj_items.append((e, alias))

        # ---- aggregate or plain
        has_agg = q.group_by is not None or any(
            _ast_contains_agg(e, self.catalog) for e, _ in proj_items
        ) or (q.having is not None and _ast_contains_agg(q.having, self.catalog))

        if has_agg:
            plan, out_fields, hidden_sort = self._bind_aggregate_query(plan, scope, q, proj_items)
        else:
            plan, out_fields, hidden_sort = self._bind_plain_query(plan, scope, q, proj_items)

        # ---- DISTINCT
        if q.distinct:
            n = len(out_fields)
            if hidden_sort:
                # distinct over visible columns only; hidden sort cols would
                # change semantics -> rebind without hidden (rare: DISTINCT +
                # ORDER BY non-output expr is invalid SQL anyway)
                self.error("SELECT DISTINCT with ORDER BY on non-output expression")
            plan = LogicalAggregate(input=plan, group_keys=list(range(n)), aggs=[],
                                    schema=list(plan.schema))

        # ---- ORDER BY / LIMIT / OFFSET
        plan = self._apply_order_limit(plan, Scope.from_fields(plan.schema, None),
                                       q.order_by, q.limit, q.offset,
                                       output_fields=out_fields,
                                       hidden_sort=hidden_sort,
                                       proj_items=proj_items)
        return plan

    # ------------------------------------------------------------- relations
    def bind_relation(self, rel: A.Relation) -> Tuple[RelNode, Scope]:
        if isinstance(rel, A.TableRef):
            return self._bind_table_ref(rel)
        if isinstance(rel, A.SubqueryRelation):
            plan = self.bind_query(rel.query)
            names = rel.column_aliases or [f.name for f in plan.schema]
            if rel.column_aliases:
                if len(names) != len(plan.schema):
                    self.error("Column alias count mismatch", rel)
                plan = LogicalProject(
                    input=plan,
                    exprs=[RexInputRef(i, f.stype) for i, f in enumerate(plan.schema)],
                    schema=[Field(n, f.stype) for n, f in zip(names, plan.schema)],
                )
            scope = Scope([ScopeEntry(rel.alias, n, f.stype)
                           for n, f in zip(names, plan.schema)])
            return plan, scope
        if isinstance(rel, A.JoinRelation):
            return self._bind_join(rel)
        if isinstance(rel, A.PredictRelation):
            return self._bind_predict(rel)
        self.error(f"Unsupported relation {type(rel).__name__}", rel)

    def _bind_table_ref(self, rel: A.TableRef) -> Tuple[RelNode, Scope]:
        # CTE?
        if len(rel.parts) == 1:
            cte = self.cte_stack[-1].get(rel.parts[0].lower())
            if cte is not None:
                alias = rel.alias or rel.parts[0]
                plan = cte
                scope = Scope.from_fields(plan.schema, alias)
                if rel.sample:
                    plan, scope = self._apply_sample(plan, scope, rel.sample)
                return plan, scope
        resolved = self.catalog.resolve_table(rel.parts)
        if resolved is None:
            self.error(f"Table '{'.'.join(rel.parts)}' not found", rel)
        schema_name, table_name, fields, view_plan = resolved
        if view_plan is not None:
            plan: RelNode = view_plan
        else:
            plan = LogicalTableScan(schema_name=schema_name, table_name=table_name,
                                    schema=fields)
        alias = rel.alias or rel.parts[-1]
        names = rel.column_aliases or [f.name for f in fields]
        scope = Scope([ScopeEntry(alias, n, f.stype) for n, f in zip(names, fields)])
        if rel.sample:
            plan, scope = self._apply_sample(plan, scope, rel.sample)
        return plan, scope

    def _apply_sample(self, plan, scope, sample):
        method, pct, seed = sample
        plan = LogicalSample(input=plan, method=method, percentage=pct, seed=seed,
                             schema=list(plan.schema))
        return plan, scope

    def _bind_predict(self, rel: A.PredictRelation) -> Tuple[RelNode, Scope]:
        from .nodes import RelNode as _R  # local import for type only
        inner = self.bind_query(rel.query)
        model_info = self.catalog.resolve_model(rel.model)
        if model_info is None:
            self.error(f"Model '{'.'.join(rel.model)}' not found", rel)
        # schema = inner schema + "target" prediction column
        from .predict import LogicalPredict  # deferred to avoid cycle
        fields = list(inner.schema) + [Field("target", DOUBLE)]
        plan = LogicalPredict(input=inner, model_name=rel.model, schema=fields)
        alias = rel.alias or "PREDICT"
        return plan, Scope.from_fields(fields, alias)

    def _bind_join(self, rel: A.JoinRelation) -> Tuple[RelNode, Scope]:
        left_plan, left_scope = self.bind_relation(rel.left)
        right_plan, right_scope = self.bind_relation(rel.right)
        combined = left_scope.concat(right_scope)
        nl = len(left_scope.entries)

        using_cols: Optional[List[str]] = None
        if rel.using == "NATURAL":
            lnames = [e.name for e in left_scope.entries if not e.hidden]
            rnames = {e.name for e in right_scope.entries if not e.hidden}
            using_cols = [n for n in lnames if n in rnames]
        elif rel.using:
            using_cols = list(rel.using)

        condition: Optional[RexNode] = None
        if using_cols is not None:
            conds = []
            for c in using_cols:
                li = left_scope.resolve([c])
                ri = right_scope.resolve([c])
                if li is None or ri is None:
                    self.error(f"USING column '{c}' missing from join input", rel)
                lt = left_scope.entries[li].stype
                rt = right_scope.entries[ri].stype
                conds.append(RexCall("=", [RexInputRef(li, lt),
                                           RexInputRef(nl + ri, rt)], BOOLEAN))
                # hide the right-side duplicate from star expansion
                right_scope.entries[ri].hidden = True
            condition = _and_all(conds)
        elif rel.condition is not None:
            condition = self.bind_expr(rel.condition, combined)
            if _contains_placeholder(condition, RexAggPlaceholder):
                self.error("Aggregate functions not allowed in JOIN condition", rel)

        fields = [Field(e.name, e.stype) for e in combined.entries]
        # outer joins make the other side nullable
        jt = rel.join_type
        schema_fields = []
        for i, f in enumerate(fields):
            nullable = f.stype.nullable
            if jt in ("LEFT", "FULL") and i >= nl:
                nullable = True
            if jt in ("RIGHT", "FULL") and i < nl:
                nullable = True
            schema_fields.append(Field(f.name, f.stype.with_nullable(nullable)))
        plan = LogicalJoin(left=left_plan, right=right_plan, join_type=jt,
                           condition=condition, schema=schema_fields)
        return plan, combined

    # ------------------------------------------------------- filter/subquery
    def _apply_filter_with_subqueries(self, plan: RelNode, scope: Scope,
                                      where: A.Expr) -> Tuple[RelNode, Scope]:
        conjuncts = _split_conjuncts(where)
        plain: List[A.Expr] = []
        for c in conjuncts:
            handled, plan = self._try_bind_subquery_conjunct(plan, scope, c)
            if not handled:
                plain.append(c)
        if plain:
            cond = self.bind_expr(_and_ast(plain), scope)
            if _contains_placeholder(cond, RexAggPlaceholder):
                self.error("Aggregate functions not allowed in WHERE", where)
            plan = LogicalFilter(input=plan, condition=cond, schema=list(plan.schema))
        return plan, scope

    # --------------------------------------------------- correlated scalar
    def _bind_correlated_scalar_cmp(self, plan: RelNode, scope: Scope,
                                    op: str, other_ast: A.Expr,
                                    sq: A.Subquery) -> Tuple[bool, RelNode]:
        """Decorrelate ``expr <op> (SELECT agg(..) FROM .. WHERE k = outer.k)``
        into an INNER join against the subquery aggregated BY the correlation
        keys, plus a comparison filter (the classic rewrite; the reference
        gets it from Calcite's SubQueryRemoveRule). Empty groups vanish from
        the grouped aggregate, which matches NULL-compares-false semantics
        for a WHERE conjunct."""
        sub = Binder(self.catalog, self.sql, outer_scope=scope,
                             params=self.params)
        sub.cte_stack = self.cte_stack[:]
        sub_plan = sub.bind_query(sq.query)
        if len(sub_plan.schema) != 1:
            self.error("Scalar subquery must return one column", sq)
        if not _plan_has_outer(sub_plan):
            # uncorrelated: reuse this bind instead of discarding it (the
            # generic path would re-bind the whole subquery from scratch)
            lhs = self.bind_expr(other_ast, scope)
            t = sub_plan.schema[0].stype.with_nullable(True)
            cmp = RexCall(op, [lhs, RexScalarSubquery(sub_plan, t)], BOOLEAN)
            return True, LogicalFilter(input=plan, condition=cmp,
                                       schema=list(plan.schema))

        sub2, pairs, needed, count_like = self._decorrelate_scalar_agg(
            sub_plan, sq)
        nk = len(needed)

        nl = len(plan.schema)
        inner_of = {ii: pos for pos, ii in enumerate(needed)}
        cond: Optional[RexNode] = None
        for oi, ii, styp in pairs:
            eq = RexCall("=", [
                RexInputRef(oi, scope.entries[oi].stype),
                RexInputRef(nl + inner_of[ii], styp)], BOOLEAN)
            cond = eq if cond is None else RexCall("AND", [cond, eq], BOOLEAN)
        joined = LogicalJoin(left=plan, right=sub2,
                             join_type="LEFT" if count_like else "INNER",
                             condition=cond,
                             schema=list(plan.schema) + list(sub2.schema))
        lhs = self.bind_expr(other_ast, scope)  # left columns keep positions
        val: RexNode = RexInputRef(nl + nk, sub2.schema[-1].stype)
        if count_like:
            val = RexCall("COALESCE", [val, RexLiteral(0, val.stype)],
                          val.stype)
        cmp = RexCall(op, [lhs, val], BOOLEAN)
        filt = LogicalFilter(input=joined, condition=cmp,
                             schema=list(joined.schema))
        out = LogicalProject(
            input=filt,
            exprs=[RexInputRef(i, f.stype) for i, f in enumerate(plan.schema)],
            schema=list(plan.schema))
        return True, out

    def _decorrelate_scalar_agg(self, sub_plan: RelNode, sq: A.Subquery):
        """Shared core of the correlated scalar-aggregate rewrite: turn a
        whole-table-aggregate subquery correlated by equality predicates
        into a grouped aggregate keyed by the correlation columns.
        Returns ``(sub2, pairs, needed, count_like)``: the grouped subplan
        (schema = correlation keys + original outputs), the (outer idx,
        inner idx, type) equality pairs, the distinct inner key ordinals,
        and whether the aggregate is COUNT-shaped (0, not NULL, over an
        empty group — callers must LEFT-join + COALESCE)."""
        # peel output projections above the aggregate (e.g. 0.2 * AVG(x))
        projects: List[LogicalProject] = []
        core = sub_plan
        while isinstance(core, LogicalProject):
            if any(_rex_has_outer(e) for e in core.exprs):
                self.error("Unsupported correlated subquery "
                           "(correlation outside WHERE)", sq)
            projects.append(core)
            core = core.input
        if not isinstance(core, LogicalAggregate) or core.group_keys:
            self.error("Unsupported correlated scalar subquery "
                       "(expected a whole-table aggregate)", sq)

        # walk through the agg-argument projection chain to the filter
        chain: List[LogicalProject] = []
        node = core.input
        while isinstance(node, LogicalProject):
            if any(_rex_has_outer(e) for e in node.exprs):
                self.error("Unsupported correlated subquery "
                           "(correlation outside WHERE)", sq)
            chain.append(node)
            node = node.input
        node2, corr = _extract_correlated(node, self, sq)

        pairs: List[Tuple[int, int, SqlType]] = []  # (outer idx, inner idx)
        for cj in corr:
            o = i = None
            if (isinstance(cj, RexCall) and cj.op == "="
                    and len(cj.operands) == 2):
                a, b = cj.operands
                if isinstance(a, RexInputRef) and isinstance(b, RexOuterRef):
                    o, i = b, a
                elif isinstance(a, RexOuterRef) and isinstance(b, RexInputRef):
                    o, i = a, b
            if o is None:
                self.error("Unsupported correlated subquery predicate "
                           "(only equality correlation)", sq)
            pairs.append((o.index, i.index, i.stype))
        if not pairs:
            self.error("Unsupported correlated subquery", sq)
        needed: List[int] = []
        for _, ii, _t in pairs:
            if ii not in needed:
                needed.append(ii)

        # thread the correlation keys up through the projection chain
        cur: RelNode = node2
        key_pos = list(needed)
        for P in reversed(chain):
            exprs = list(P.exprs) + [
                RexInputRef(k, cur.schema[k].stype) for k in key_pos]
            fields = list(P.schema) + [
                Field(cur.schema[k].name, cur.schema[k].stype)
                for k in key_pos]
            base = len(P.exprs)
            cur = LogicalProject(input=cur, exprs=exprs, schema=fields)
            key_pos = [base + j for j in range(len(needed))]

        key_fields = [Field(cur.schema[k].name, cur.schema[k].stype)
                      for k in key_pos]
        agg2 = LogicalAggregate(input=cur, group_keys=list(key_pos),
                                aggs=core.aggs,
                                schema=key_fields + list(core.schema))
        sub2: RelNode = agg2
        nk = len(key_pos)
        for P in reversed(projects):
            exprs = ([RexInputRef(j, f.stype)
                      for j, f in enumerate(key_fields)]
                     + [shift_rex(e, nk) for e in P.exprs])
            sub2 = LogicalProject(input=sub2, exprs=exprs,
                                  schema=key_fields + list(P.schema))

        # COUNT-style aggregates are 0 over an empty set, not NULL: the
        # INNER-join rewrite would silently drop the no-match groups, so
        # those use a LEFT join + COALESCE(count, 0) — only sound when the
        # count is the subquery's direct output
        count_like = any(a.op in ("COUNT", "REGR_COUNT", "$SUM0")
                         for a in core.aggs)
        trivial_projects = all(
            len(P.exprs) == 1 and isinstance(P.exprs[0], RexInputRef)
            for P in projects)
        if count_like and (not trivial_projects or len(core.aggs) != 1):
            self.error("Unsupported correlated COUNT subquery shape", sq)
        return sub2, pairs, needed, count_like

    def _decorrelate_select_subqueries(self, plan: RelNode, scope: Scope,
                                       proj_items) -> RelNode:
        """Correlated scalar-aggregate subqueries in the SELECT list:
        LEFT-join the grouped subplan on the correlation keys and remember
        the value column for bind_expr (postgres-class parity; the
        reference gets this from Calcite's SubQueryRemoveRule).  A missing
        group yields NULL (or 0 for COUNT via COALESCE) — exactly the
        scalar subquery's empty-result semantics."""
        for e, _alias in proj_items:
            for sq in _walk_scalar_subqueries(e):
                sub = Binder(self.catalog, self.sql, outer_scope=scope,
                             params=self.params)
                sub.cte_stack = self.cte_stack[:]
                sub_plan = sub.bind_query(sq.query)
                if not _plan_has_outer(sub_plan):
                    continue  # uncorrelated: the ordinary rex path handles it
                if len(sub_plan.schema) != 1:
                    self.error("Scalar subquery must return one column", sq)
                sub2, pairs, needed, count_like = \
                    self._decorrelate_scalar_agg(sub_plan, sq)
                nl = len(plan.schema)
                inner_of = {ii: pos for pos, ii in enumerate(needed)}
                cond: Optional[RexNode] = None
                for oi, ii, styp in pairs:
                    eq = RexCall("=", [
                        RexInputRef(oi, scope.entries[oi].stype),
                        RexInputRef(nl + inner_of[ii], styp)], BOOLEAN)
                    cond = (eq if cond is None
                            else RexCall("AND", [cond, eq], BOOLEAN))
                plan = LogicalJoin(
                    left=plan, right=sub2, join_type="LEFT", condition=cond,
                    schema=list(plan.schema) + list(sub2.schema))
                t = sub2.schema[-1].stype.with_nullable(True)
                val: RexNode = RexInputRef(nl + len(needed), t)
                if count_like:
                    val = RexCall("COALESCE",
                                  [val, RexLiteral(0, val.stype)], val.stype)
                self._select_sq_rex[id(sq)] = val
        return plan

    def _try_bind_subquery_conjunct(self, plan: RelNode, scope: Scope,
                                    c: A.Expr) -> Tuple[bool, RelNode]:
        negated = False
        inner = c
        if isinstance(inner, A.Call) and inner.op == "NOT" and len(inner.args) == 1:
            if isinstance(inner.args[0], A.Subquery):
                negated = True
                inner = inner.args[0]
        if not isinstance(inner, A.Subquery):
            # comparison against a correlated scalar-aggregate subquery:
            # expr <op> (SELECT agg(...) WHERE inner_col = outer_col ...)
            if (isinstance(inner, A.Call)
                    and inner.op in ("=", "<", ">", "<=", ">=", "<>")
                    and len(inner.args) == 2):
                for side, other in ((0, 1), (1, 0)):
                    sq = inner.args[side]
                    if isinstance(sq, A.Subquery) and sq.kind == "scalar":
                        handled, out = self._bind_correlated_scalar_cmp(
                            plan, scope, inner.op if side == 1 else
                            _flip_cmp(inner.op), inner.args[other], sq)
                        if handled:
                            return True, out
            return False, plan
        kind = inner.kind
        neg = negated != inner.negated
        if kind == "exists":
            sub = Binder(self.catalog, self.sql, outer_scope=scope,
                             params=self.params)
            sub.cte_stack = self.cte_stack[:]
            sub_plan = sub.bind_query(inner.query)
            jt = "ANTI" if neg else "SEMI"
            if _plan_has_outer(sub_plan):
                # correlated EXISTS: the correlated conjuncts of the
                # subquery's top filter become the SEMI/ANTI join condition
                core, corr = _extract_correlated(sub_plan, self, inner)
                nl = len(plan.schema)
                cond = _corr_join_condition(corr, nl)
                out = LogicalJoin(left=plan, right=core, join_type=jt,
                                  condition=cond, schema=list(plan.schema))
                return True, out
            out = LogicalJoin(left=plan, right=sub_plan, join_type=jt,
                              condition=RexLiteral(True, BOOLEAN),
                              schema=list(plan.schema))
            return True, out
        if kind in ("in", "any", "all"):
            sub = Binder(self.catalog, self.sql, params=self.params)
            sub.cte_stack = self.cte_stack[:]
            sub_plan = sub.bind_query(inner.query)
            if len(sub_plan.schema) != 1:
                self.error("Subquery in IN must return one column", inner)
            key = self.bind_expr(inner.outer, scope)
            if kind == "all":
                # x <op> ALL(sub) === NOT (x <inv-op> ANY(sub)) — rewrite via
                # min/max for orderable ops
                return self._bind_quantified_all(plan, scope, key, inner, sub_plan)
            if kind == "any" and inner.op not in ("=", None):
                return self._bind_quantified_any(plan, scope, key, inner, sub_plan)
            # IN / = ANY: semi/anti join on key equality
            nl = len(plan.schema)
            # key must be a column: append as hidden projection if not
            plan2, key_idx = self._ensure_column(plan, key)
            sub_t = sub_plan.schema[0].stype
            cond = RexCall("=", [RexInputRef(key_idx, key.stype),
                                 RexInputRef(len(plan2.schema), sub_t)], BOOLEAN)
            jt = "ANTI" if neg else "SEMI"
            out = LogicalJoin(left=plan2, right=sub_plan, join_type=jt,
                              condition=cond, schema=list(plan2.schema))
            # NOT IN null semantics are handled by the ANTI-join kernel
            # (null-aware flag lives on the plan node)
            out.null_aware = neg  # type: ignore[attr-defined]
            if len(plan2.schema) != len(plan.schema):
                out = LogicalProject(
                    input=out,
                    exprs=[RexInputRef(i, f.stype) for i, f in enumerate(plan.schema)],
                    schema=list(plan.schema),
                )
            return True, out
        return False, plan

    def _bind_quantified_all(self, plan, scope, key, inner, sub_plan):
        # x < ALL(sub) -> x < MIN(sub); x > ALL(sub) -> x > MAX(sub);
        # x <> ALL(sub) -> NOT IN
        op = inner.op
        if op == "<>":
            new = A.Subquery(query=inner.query, kind="in", outer=inner.outer, negated=True)
            return self._try_bind_subquery_conjunct(plan, scope, new)
        agg = {"<": "MIN", "<=": "MIN", ">": "MAX", ">=": "MAX", "=": None}.get(op)
        if agg is None:
            self.error(f"Unsupported ALL comparison {op}", inner)
        sub_t = sub_plan.schema[0].stype
        agg_plan = LogicalAggregate(
            input=sub_plan, group_keys=[],
            aggs=[AggCall(agg, [0], False, sub_t, "m")],
            schema=[Field("m", sub_t)],
        )
        rex = RexCall(op, [key, RexScalarSubquery(agg_plan, sub_t)], BOOLEAN)
        out = LogicalFilter(input=plan, condition=rex, schema=list(plan.schema))
        return True, out

    def _bind_quantified_any(self, plan, scope, key, inner, sub_plan):
        op = inner.op
        agg = {"<": "MAX", "<=": "MAX", ">": "MIN", ">=": "MIN"}.get(op)
        if agg is None:
            self.error(f"Unsupported ANY comparison {op}", inner)
        sub_t = sub_plan.schema[0].stype
        agg_plan = LogicalAggregate(
            input=sub_plan, group_keys=[],
            aggs=[AggCall(agg, [0], False, sub_t, "m")],
            schema=[Field("m", sub_t)],
        )
        rex = RexCall(op, [key, RexScalarSubquery(agg_plan, sub_t)], BOOLEAN)
        out = LogicalFilter(input=plan, condition=rex, schema=list(plan.schema))
        return True, out

    def _ensure_column(self, plan: RelNode, rex: RexNode) -> Tuple[RelNode, int]:
        if isinstance(rex, RexInputRef):
            return plan, rex.index
        exprs = [RexInputRef(i, f.stype) for i, f in enumerate(plan.schema)] + [rex]
        fields = list(plan.schema) + [Field("__key__", rex.stype)]
        return LogicalProject(input=plan, exprs=exprs, schema=fields), len(fields) - 1

    # ----------------------------------------------------------- plain select
    def _bind_plain_query(self, plan: RelNode, scope: Scope, q: A.Select,
                          proj_items) -> Tuple[RelNode, List[Field], int]:
        # correlated scalar subqueries in the SELECT list join their
        # grouped subplans onto `plan` first (scope positions are left-side
        # and stay valid; the final project drops the joined columns)
        plan = self._decorrelate_select_subqueries(plan, scope, proj_items)
        bound = []
        names = []
        for e, alias in proj_items:
            rex = self.bind_expr(e, scope)
            bound.append(rex)
            names.append(alias or _default_name(e, len(names)))
        # ORDER BY exprs that aren't plain outputs -> hidden extra projections
        hidden_exprs, hidden_names = self._hidden_sort_exprs(q.order_by, proj_items,
                                                            names, scope)
        all_exprs = bound + hidden_exprs
        # window extraction
        if any(_contains_placeholder(r, RexWindowPlaceholder) for r in all_exprs):
            plan, all_exprs = self._lower_windows(plan, all_exprs)
        fields = [Field(n, r.stype) for n, r in zip(names + hidden_names, all_exprs)]
        out = LogicalProject(input=plan, exprs=all_exprs, schema=fields)
        visible = [Field(n, r.stype) for n, r in zip(names, all_exprs[: len(names)])]
        return out, visible, len(hidden_exprs)

    def _hidden_sort_exprs(self, order_by, proj_items, out_names, scope):
        hidden_exprs, hidden_names = [], []
        for k in order_by:
            resolved = self._resolve_orderby_item(k.expr, proj_items, out_names)
            if resolved is not None:
                continue
            rex = self.bind_expr(k.expr, scope)
            hidden_exprs.append(rex)
            hidden_names.append(f"__sort_{len(hidden_names)}")
        return hidden_exprs, hidden_names

    def _resolve_orderby_item(self, e: A.Expr, proj_items, out_names) -> Optional[int]:
        """Ordinal into output fields if the ORDER BY item is an output column."""
        if isinstance(e, A.Literal) and isinstance(e.value, int):
            idx = e.value - 1
            if not (0 <= idx < len(out_names)):
                self.error(f"ORDER BY position {e.value} out of range", e)
            return idx
        if isinstance(e, A.ColumnRef) and len(e.parts) == 1:
            name = e.parts[0]
            if name in out_names:
                return out_names.index(name)
            low = [n.lower() for n in out_names]
            if name.lower() in low:
                return low.index(name.lower())
        # structural match with a projection expr
        for i, (pe, _) in enumerate(proj_items):
            if _ast_equal(e, pe):
                return i
        return None

    # ------------------------------------------------------------- aggregate
    def _bind_aggregate_query(self, plan: RelNode, scope: Scope, q: A.Select,
                              proj_items) -> Tuple[RelNode, List[Field], int]:
        out_names = [alias or _default_name(e, i) for i, (e, alias) in enumerate(proj_items)]

        # resolve GROUP BY items (ordinals, output aliases, expressions)
        group_ast: List[A.Expr] = []
        for g in (q.group_by or []):
            if isinstance(g, A.Literal) and isinstance(g.value, int):
                idx = g.value - 1
                if not (0 <= idx < len(proj_items)):
                    self.error(f"GROUP BY position {g.value} out of range", g)
                group_ast.append(proj_items[idx][0])
                continue
            if isinstance(g, A.ColumnRef) and len(g.parts) == 1 and scope.resolve(g.parts) is None:
                name = g.parts[0]
                cand = [i for i, n in enumerate(out_names) if n == name or n.lower() == name.lower()]
                if cand:
                    group_ast.append(proj_items[cand[0]][0])
                    continue
            group_ast.append(g)

        group_rex = [self.bind_expr(g, scope) for g in group_ast]

        # bind projections/having/order with agg placeholders
        bound_proj = [self.bind_expr(e, scope) for e, _ in proj_items]
        bound_having = self.bind_expr(q.having, scope) if q.having is not None else None
        hidden_rex: List[RexNode] = []
        for k in q.order_by:
            if self._resolve_orderby_item(k.expr, proj_items, out_names) is None:
                hidden_rex.append(self.bind_expr(k.expr, scope))

        # collect agg placeholders
        collector = _AggCollector(group_rex)
        post_proj = [collector.rewrite(r) for r in bound_proj]
        post_having = collector.rewrite(bound_having) if bound_having is not None else None
        post_hidden = [collector.rewrite(r) for r in hidden_rex]

        # validate: post exprs only reference agg-output ordinals
        # build pre-projection
        pre_exprs = collector.pre_exprs
        if not pre_exprs and plan.schema:
            # COUNT(*) with no group keys references no columns at all; keep
            # one input ref so the pre-projection still carries the row count
            # (a zero-column table has no length)
            pre_exprs = [RexInputRef(0, plan.schema[0].stype)]
        pre_fields = [Field(f"$f{i}", r.stype) for i, r in enumerate(pre_exprs)]
        pre = LogicalProject(input=plan, exprs=pre_exprs, schema=pre_fields)

        n_groups = len(collector.group_slots)
        agg_fields = [Field(f"$g{i}", pre_exprs[s].stype)
                      for i, s in enumerate(collector.group_slots)]
        agg_calls: List[AggCall] = []
        for i, ph in enumerate(collector.agg_calls):
            agg_calls.append(AggCall(
                op=ph.op, args=ph.arg_slots, distinct=ph.distinct, stype=ph.stype,
                name=f"$a{i}", filter_arg=ph.filter_slot, udaf=ph.udaf,
            ))
            agg_fields.append(Field(f"$a{i}", ph.stype))
        agg = LogicalAggregate(input=pre, group_keys=list(collector.group_slots),
                               aggs=agg_calls, schema=agg_fields)

        plan2: RelNode = agg
        if post_having is not None:
            plan2 = LogicalFilter(input=plan2, condition=post_having,
                                  schema=list(plan2.schema))

        all_post = post_proj + post_hidden
        if any(_contains_placeholder(r, RexWindowPlaceholder) for r in all_post):
            plan2, all_post = self._lower_windows(plan2, all_post)
        hidden_names = [f"__sort_{i}" for i in range(len(post_hidden))]
        fields = [Field(n, r.stype) for n, r in zip(out_names + hidden_names, all_post)]
        out = LogicalProject(input=plan2, exprs=all_post, schema=fields)
        visible = fields[: len(out_names)]
        return out, visible, len(post_hidden)

    # --------------------------------------------------------------- windows
    def _lower_windows(self, plan: RelNode, exprs: List[RexNode]):
        """Extract RexWindowPlaceholders: plan -> LogicalWindow, rewrite refs."""
        calls: List[WindowCall] = []
        extra_exprs: List[RexNode] = []   # computed inputs the window needs
        base_n = len(plan.schema)

        def slot_for(rex: RexNode) -> int:
            if isinstance(rex, RexInputRef):
                return rex.index
            for i, e in enumerate(extra_exprs):
                if _rex_equal(e, rex):
                    return base_n + i
            extra_exprs.append(rex)
            return base_n + len(extra_exprs) - 1

        win_slot_of: List[int] = []
        placeholders: List[RexWindowPlaceholder] = []

        def collect(r: RexNode):
            if isinstance(r, RexWindowPlaceholder):
                for o in r.operands:
                    collect(o)
                for p in r.partition:
                    collect(p)
                for o, _, _ in r.order:
                    collect(o)
                placeholders.append(r)
                return
            if isinstance(r, (RexCall, RexUdf)):
                for o in r.operands:
                    collect(o)

        for r in exprs:
            collect(r)

        # build input projection with extra computed columns
        for ph in placeholders:
            pass
        # ensure slots for everything (operands/partitions/orders)
        for ph in placeholders:
            arg_slots = [slot_for(o) for o in ph.operands]
            part_slots = [slot_for(p) for p in ph.partition]
            order_cols = [SortCollation(slot_for(o), asc, nf) for o, asc, nf in ph.order]
            calls.append(WindowCall(op=ph.op, args=arg_slots, partition=part_slots,
                                    order=order_cols, frame=ph.frame, stype=ph.stype,
                                    name=f"$w{len(calls)}"))
            win_slot_of.append(base_n + len(extra_exprs) + len(win_slot_of))

        if extra_exprs:
            proj_exprs = [RexInputRef(i, f.stype) for i, f in enumerate(plan.schema)] + extra_exprs
            proj_fields = list(plan.schema) + [Field(f"$we{i}", e.stype)
                                               for i, e in enumerate(extra_exprs)]
            plan = LogicalProject(input=plan, exprs=proj_exprs, schema=proj_fields)

        win_fields = list(plan.schema) + [Field(c.name, c.stype) for c in calls]
        plan = LogicalWindow(input=plan, calls=calls, schema=win_fields)

        # rewrite placeholders to refs
        ph_map = {}
        for i, ph in enumerate(placeholders):
            ph_map[id(ph)] = RexInputRef(len(plan.schema) - len(calls) + i, ph.stype)

        def rewrite(r: RexNode) -> RexNode:
            if isinstance(r, RexWindowPlaceholder):
                return ph_map[id(r)]
            if isinstance(r, RexCall):
                return RexCall(r.op, [rewrite(o) for o in r.operands], r.stype, r.info)
            if isinstance(r, RexUdf):
                return RexUdf(r.name, r.func, [rewrite(o) for o in r.operands],
                              r.stype, r.row_udf)
            return r

        return plan, [rewrite(r) for r in exprs]

    # ---------------------------------------------------------- order / limit
    def _apply_order_limit(self, plan: RelNode, scope: Scope, order_by,
                           limit_e, offset_e, output_fields: List[Field],
                           hidden_sort: int = 0, proj_items=None) -> RelNode:
        collation: List[SortCollation] = []
        n_visible = len(output_fields)
        hidden_used = 0
        out_names = [f.name for f in output_fields]
        for k in order_by:
            # MUST mirror the resolution the binder used when deciding which
            # keys get hidden sort columns (_hidden_sort_exprs), or the
            # hidden-column accounting below goes out of sync
            idx = self._resolve_orderby_item(k.expr, proj_items or [],
                                             out_names)
            if idx is None:
                # hidden sort columns were appended in order of unresolved keys
                idx = n_visible + hidden_used
                hidden_used += 1
                if idx >= len(plan.schema):
                    self.error("Cannot resolve ORDER BY expression", k.expr)
            collation.append(SortCollation(idx, k.ascending, k.nulls_first))

        limit = _const_int(limit_e) if limit_e is not None else None
        offset = _const_int(offset_e) if offset_e is not None else None

        if collation or limit is not None or offset is not None:
            plan = LogicalSort(input=plan, collation=collation, limit=limit,
                               offset=offset, schema=list(plan.schema))
        if hidden_sort:
            exprs = [RexInputRef(i, f.stype) for i, f in enumerate(plan.schema[:n_visible])]
            plan = LogicalProject(input=plan, exprs=exprs, schema=list(output_fields))
        return plan

    # ============================================================ expressions
    def bind_expr(self, e: A.Expr, scope: Scope) -> RexNode:
        if isinstance(e, A.Literal):
            return self._bind_literal(e)
        if isinstance(e, A.IntervalLiteral):
            return self._bind_interval(e)
        if isinstance(e, A.ColumnRef):
            idx = scope.resolve(e.parts)
            if idx is None:
                if self.outer_scope is not None:
                    oidx = self.outer_scope.resolve(e.parts)
                    if oidx is not None:
                        return RexOuterRef(oidx,
                                           self.outer_scope.entries[oidx].stype)
                self.error(f"Column '{'.'.join(e.parts)}' not found", e)
            return RexInputRef(idx, scope.entries[idx].stype)
        if isinstance(e, A.Star):
            self.error("* not allowed here", e)
        if isinstance(e, A.Call):
            return self._bind_call(e, scope)
        if isinstance(e, A.Case):
            return self._bind_case(e, scope)
        if isinstance(e, A.Cast):
            inner = self.bind_expr(e.expr, scope)
            target = parse_type_name(e.type_name, e.precision, e.scale)
            return RexCall("CAST", [inner], target, info=target)
        if isinstance(e, A.InList):
            expr = self.bind_expr(e.expr, scope)
            vals = [self.bind_expr(v, scope) for v in e.values]
            rex = RexCall("IN_LIST", [expr] + vals, BOOLEAN)
            if e.negated:
                return RexCall("NOT", [rex], BOOLEAN)
            return rex
        if isinstance(e, A.Between):
            x = self.bind_expr(e.expr, scope)
            lo = self.bind_expr(e.low, scope)
            hi = self.bind_expr(e.high, scope)
            if e.symmetric:
                cond = RexCall("OR", [
                    RexCall("AND", [RexCall(">=", [x, lo], BOOLEAN),
                                    RexCall("<=", [x, hi], BOOLEAN)], BOOLEAN),
                    RexCall("AND", [RexCall(">=", [x, hi], BOOLEAN),
                                    RexCall("<=", [x, lo], BOOLEAN)], BOOLEAN),
                ], BOOLEAN)
            else:
                cond = RexCall("AND", [RexCall(">=", [x, lo], BOOLEAN),
                                       RexCall("<=", [x, hi], BOOLEAN)], BOOLEAN)
            if e.negated:
                return RexCall("NOT", [cond], BOOLEAN)
            return cond
        if isinstance(e, A.Like):
            x = self.bind_expr(e.expr, scope)
            pat = self.bind_expr(e.pattern, scope)
            esc = self.bind_expr(e.escape, scope) if e.escape else None
            op = {"LIKE": "LIKE", "ILIKE": "ILIKE", "SIMILAR": "SIMILAR"}[e.kind]
            operands = [x, pat] + ([esc] if esc else [])
            rex = RexCall(op, operands, BOOLEAN)
            if e.negated:
                return RexCall("NOT", [rex], BOOLEAN)
            return rex
        if isinstance(e, A.IsNull):
            x = self.bind_expr(e.expr, scope)
            return RexCall("IS_NOT_NULL" if e.negated else "IS_NULL", [x],
                           SqlType("BOOLEAN", nullable=False))
        if isinstance(e, A.IsBool):
            x = self.bind_expr(e.expr, scope)
            base = "IS_TRUE" if e.value else "IS_FALSE"
            op = f"IS_NOT_{'TRUE' if e.value else 'FALSE'}" if e.negated else base
            return RexCall(op, [x], SqlType("BOOLEAN", nullable=False))
        if isinstance(e, A.IsDistinctFrom):
            l = self.bind_expr(e.left, scope)
            r = self.bind_expr(e.right, scope)
            op = "IS_NOT_DISTINCT_FROM" if e.negated else "IS_DISTINCT_FROM"
            return RexCall(op, [l, r], SqlType("BOOLEAN", nullable=False))
        if isinstance(e, A.Subquery):
            if e.kind == "scalar":
                pre = self._select_sq_rex.get(id(e))
                if pre is not None:
                    # decorrelated ahead of binding (SELECT-list position)
                    return pre
                # bind with the outer scope visible so a correlated subquery
                # in an unsupported position fails with a clear message, not
                # a phantom "column not found"
                sub = Binder(self.catalog, self.sql, outer_scope=scope,
                             params=self.params)
                sub.cte_stack = self.cte_stack[:]
                sub_plan = sub.bind_query(e.query)
                if _plan_has_outer(sub_plan):
                    self.error(
                        "Correlated scalar subqueries are only supported as "
                        "top-level WHERE comparison conjuncts", e)
                if len(sub_plan.schema) != 1:
                    self.error("Scalar subquery must return one column", e)
                t = sub_plan.schema[0].stype.with_nullable(True)
                return RexScalarSubquery(sub_plan, t)
            if e.kind == "exists":
                sub = Binder(self.catalog, self.sql, outer_scope=scope,
                             params=self.params)
                sub.cte_stack = self.cte_stack[:]
                sub_plan = sub.bind_query(e.query)
                if _plan_has_outer(sub_plan):
                    self.error(
                        "Correlated EXISTS is only supported as a top-level "
                        "WHERE conjunct", e)
                cnt = LogicalAggregate(
                    input=sub_plan, group_keys=[],
                    aggs=[AggCall("COUNT", [], False, BIGINT, "c")],
                    schema=[Field("c", BIGINT)],
                )
                rex = RexCall(">", [RexScalarSubquery(cnt, BIGINT),
                                    RexLiteral(0, BIGINT)], BOOLEAN)
                if e.negated:
                    return RexCall("NOT", [rex], BOOLEAN)
                return rex
            # IN in general expression position: build boolean via semi join is
            # not expressible -> only supported at top-level WHERE conjuncts
            self.error("IN/ANY subquery only supported in WHERE conjuncts", e)
        if isinstance(e, A.Param):
            if self.params is None:
                self.error("Positional parameters not supported without "
                           "bound values (pass params=[...] or use EXECUTE)", e)
            if not (0 <= e.index < len(self.params)):
                self.error(f"Parameter ${e.index + 1} has no bound value "
                           f"({len(self.params)} supplied)", e)
            return self._bind_param_value(self.params[e.index], e)
        self.error(f"Unsupported expression {type(e).__name__}", e)

    def _bind_param_value(self, v, node) -> RexLiteral:
        """A bound parameter value becomes an inline literal with the same
        python-type inference ``_bind_literal`` applies to parsed literals;
        the parameterization pass (plan/parameterize.py) then re-hoists
        eligible ones, so distinct values still share one compiled shape."""
        import datetime

        if v is None:
            return RexLiteral(None, NULLTYPE)
        if isinstance(v, bool):          # before int: bool is an int subclass
            return RexLiteral(v, SqlType("BOOLEAN", nullable=False))
        if isinstance(v, int):
            t = INTEGER if -(2**31) <= v < 2**31 else BIGINT
            return RexLiteral(v, t.with_nullable(False))
        if isinstance(v, float):
            return RexLiteral(v, SqlType("DOUBLE", nullable=False))
        if isinstance(v, str):
            return RexLiteral(v, SqlType("VARCHAR", nullable=False))
        if isinstance(v, datetime.datetime):
            return RexLiteral(python_value_to_physical(v, TIMESTAMP),
                              SqlType("TIMESTAMP", nullable=False))
        if isinstance(v, datetime.date):
            return RexLiteral(python_value_to_physical(v, DATE),
                              SqlType("DATE", nullable=False))
        if isinstance(v, datetime.time):
            return RexLiteral(python_value_to_physical(v, TIME),
                              SqlType("TIME", nullable=False))
        self.error(f"Unsupported parameter type {type(v).__name__}", node)

    def _bind_literal(self, e: A.Literal) -> RexLiteral:
        tn = e.type_name
        if tn == "BIGINT":
            v = e.value
            t = INTEGER if -(2**31) <= v < 2**31 else BIGINT
            return RexLiteral(v, t.with_nullable(False))
        if tn == "DOUBLE":
            return RexLiteral(float(e.value), SqlType("DOUBLE", nullable=False))
        if tn == "VARCHAR":
            return RexLiteral(e.value, SqlType("VARCHAR", nullable=False))
        if tn == "BOOLEAN":
            return RexLiteral(bool(e.value), SqlType("BOOLEAN", nullable=False))
        if tn == "NULL":
            return RexLiteral(None, NULLTYPE)
        if tn == "DATE":
            return RexLiteral(python_value_to_physical(e.value, DATE),
                              SqlType("DATE", nullable=False))
        if tn == "TIMESTAMP":
            return RexLiteral(python_value_to_physical(e.value, TIMESTAMP),
                              SqlType("TIMESTAMP", nullable=False))
        if tn == "TIME":
            return RexLiteral(python_value_to_physical(e.value, TIME),
                              SqlType("TIME", nullable=False))
        if tn == "SYMBOL":
            return RexLiteral(e.value, SqlType("SYMBOL", nullable=False))
        self.error(f"Unknown literal type {tn}", e)

    def _bind_interval(self, e: A.IntervalLiteral) -> RexLiteral:
        unit = e.unit
        if unit in ("YEAR", "MONTH", "QUARTER") or (e.to_unit in ("MONTH",)):
            months = 0
            if isinstance(e.value, str):
                # '1-2' YEAR TO MONTH
                y, m = e.value.split("-")
                months = int(y) * 12 + int(m)
            else:
                mult = {"YEAR": 12, "QUARTER": 3, "MONTH": 1}[unit]
                months = int(e.value * mult)
            return RexLiteral(months, SqlType("INTERVAL_YEAR_MONTH", nullable=False))
        if isinstance(e.value, str):
            # 'D HH:MM:SS' style compound — parse pieces
            ms = _parse_daytime_interval(e.value, unit, e.to_unit)
            return RexLiteral(ms, SqlType("INTERVAL_DAY_TIME", nullable=False))
        mult = _INTERVAL_UNIT_MS.get(unit)
        if mult is None:
            self.error(f"Unsupported interval unit {unit}", e)
        return RexLiteral(int(e.value * mult), SqlType("INTERVAL_DAY_TIME", nullable=False))

    def _bind_case(self, e: A.Case, scope: Scope) -> RexNode:
        operands: List[RexNode] = []
        if e.operand is not None:
            base = self.bind_expr(e.operand, scope)
            for cond, val in e.whens:
                c = RexCall("=", [base, self.bind_expr(cond, scope)], BOOLEAN)
                operands += [c, self.bind_expr(val, scope)]
        else:
            for cond, val in e.whens:
                operands += [self.bind_expr(cond, scope), self.bind_expr(val, scope)]
        if e.else_ is not None:
            operands.append(self.bind_expr(e.else_, scope))
        else:
            operands.append(RexLiteral(None, NULLTYPE))
        value_types = [operands[i].stype for i in range(1, len(operands), 2)]
        value_types.append(operands[-1].stype)
        out_t = F.infer_call_type("CASE", value_types)
        return RexCall("CASE", operands, out_t)

    def _bind_call(self, e: A.Call, scope: Scope) -> RexNode:
        op = e.op

        # window function?
        if e.over is not None:
            args = [self.bind_expr(a, scope) for a in e.args
                    if not isinstance(a, A.Star)]
            part = [self.bind_expr(p, scope) for p in e.over.partition_by]
            order = [(self.bind_expr(k.expr, scope), k.ascending, k.nulls_first)
                     for k in e.over.order_by]
            if F.is_window_only(op):
                stype = F.infer_agg_type(op, [a.stype for a in args] or [BIGINT])
            elif F.is_aggregate(op):
                stype = F.infer_agg_type(op, [a.stype for a in args] or [BIGINT])
            else:
                self.error(f"Function {op} cannot be used with OVER", e)
            return RexWindowPlaceholder(op=op, operands=args, partition=part,
                                        order=order, frame=e.over.frame, stype=stype)

        if F.is_window_only(op):
            self.error(f"Window function {op} requires OVER", e)

        # aggregate?
        if F.is_aggregate(op):
            if op == "COUNT" and len(e.args) == 1 and isinstance(e.args[0], A.Star):
                args: List[RexNode] = []
            else:
                args = [self.bind_expr(a, scope) for a in e.args]
            filt = self.bind_expr(e.filter, scope) if e.filter is not None else None
            stype = F.infer_agg_type(op, [a.stype for a in args] or [BIGINT])
            return RexAggPlaceholder(op=op, operands=args, distinct=e.distinct,
                                     filter=filt, stype=stype)

        # registered UDF / UDAF?
        fd = self.catalog.get_function(getattr(e, "original_name", op))
        if fd is not None:
            args = [self.bind_expr(a, scope) for a in e.args]
            if fd.aggregation:
                filt = self.bind_expr(e.filter, scope) if e.filter is not None else None
                return RexAggPlaceholder(op=fd.name, operands=args,
                                         distinct=e.distinct, filter=filt,
                                         stype=fd.return_type, udaf=fd)
            return RexUdf(fd.name, fd.func, args, fd.return_type, fd.row_udf)

        # scalar builtin
        args = [self.bind_expr(a, scope) for a in e.args]
        try:
            stype = F.infer_call_type(op, [a.stype for a in args])
        except KeyError:
            self.error(f"Unknown function or operator '{op}'", e)
        return RexCall(op, args, stype)


# ---------------------------------------------------------------------------
# aggregate collector
# ---------------------------------------------------------------------------

class _AggCollectedCall:
    def __init__(self, op, arg_slots, distinct, filter_slot, stype, udaf):
        self.op = op
        self.arg_slots = arg_slots
        self.distinct = distinct
        self.filter_slot = filter_slot
        self.stype = stype
        self.udaf = udaf


class _AggCollector:
    """Builds the pre-projection and rewrites post-agg expressions.

    Output ordinal layout after LogicalAggregate: group keys first (in the
    order of the GROUP BY clause), then one column per aggregate call.
    """

    def __init__(self, group_rex: List[RexNode]):
        self.pre_exprs: List[RexNode] = []
        self.group_slots: List[int] = []
        self.group_rex = group_rex
        self.agg_calls: List[_AggCollectedCall] = []
        for g in group_rex:
            self.group_slots.append(self._slot(g))

    def _slot(self, rex: RexNode) -> int:
        for i, e in enumerate(self.pre_exprs):
            if _rex_equal(e, rex):
                return i
        self.pre_exprs.append(rex)
        return len(self.pre_exprs) - 1

    def _agg_output(self, ph: RexAggPlaceholder) -> int:
        arg_slots = [self._slot(a) for a in ph.operands]
        filter_slot = self._slot(ph.filter) if ph.filter is not None else None
        for i, c in enumerate(self.agg_calls):
            if (c.op == ph.op and c.arg_slots == arg_slots and c.distinct == ph.distinct
                    and c.filter_slot == filter_slot and c.udaf is ph.udaf):
                return len(self.group_rex) + i
        self.agg_calls.append(_AggCollectedCall(ph.op, arg_slots, ph.distinct,
                                                filter_slot, ph.stype, ph.udaf))
        return len(self.group_rex) + len(self.agg_calls) - 1

    def rewrite(self, rex: RexNode) -> RexNode:
        # exact match with a group expression?
        for gi, g in enumerate(self.group_rex):
            if _rex_equal(rex, g):
                return RexInputRef(gi, g.stype)
        if isinstance(rex, RexAggPlaceholder):
            idx = self._agg_output(rex)
            return RexInputRef(idx, rex.stype)
        if isinstance(rex, RexWindowPlaceholder):
            return RexWindowPlaceholder(
                op=rex.op,
                operands=[self.rewrite(o) for o in rex.operands],
                partition=[self.rewrite(p) for p in rex.partition],
                order=[(self.rewrite(o), a, nf) for o, a, nf in rex.order],
                frame=rex.frame, stype=rex.stype,
            )
        if isinstance(rex, RexCall):
            return RexCall(rex.op, [self.rewrite(o) for o in rex.operands],
                           rex.stype, rex.info)
        if isinstance(rex, RexUdf):
            return RexUdf(rex.name, rex.func, [self.rewrite(o) for o in rex.operands],
                          rex.stype, rex.row_udf)
        if isinstance(rex, RexInputRef):
            raise ValidationException(
                "", f"Column ${rex.index} is neither grouped nor aggregated")
        return rex


# ---------------------------------------------------------------------------
# misc helpers
# ---------------------------------------------------------------------------

def _entry_parts(entry: ScopeEntry) -> List[str]:
    if entry.qualifier:
        return [entry.qualifier, entry.name]
    return [entry.name]


def _split_conjuncts(e: A.Expr) -> List[A.Expr]:
    if isinstance(e, A.Call) and e.op == "AND":
        return _split_conjuncts(e.args[0]) + _split_conjuncts(e.args[1])
    return [e]


def _and_ast(conjuncts: List[A.Expr]) -> A.Expr:
    out = conjuncts[0]
    for c in conjuncts[1:]:
        out = A.Call(op="AND", args=[out, c])
    return out


def _and_all(rexes: List[RexNode]) -> RexNode:
    out = rexes[0]
    for r in rexes[1:]:
        out = RexCall("AND", [out, r], BOOLEAN)
    return out


def _default_name(e: A.Expr, i: int) -> str:
    if isinstance(e, A.ColumnRef):
        return e.parts[-1]
    if isinstance(e, A.Cast) and isinstance(e.expr, A.ColumnRef):
        return e.expr.parts[-1]
    return f"EXPR${i}"


def _ast_equal(a: A.Expr, b: A.Expr) -> bool:
    if type(a) is not type(b):
        return False
    if isinstance(a, A.ColumnRef):
        return [p.lower() for p in a.parts] == [p.lower() for p in b.parts] or a.parts[-1].lower() == b.parts[-1].lower()
    if isinstance(a, A.Literal):
        return a.value == b.value
    if isinstance(a, A.Call):
        return a.op == b.op and len(a.args) == len(b.args) and all(
            _ast_equal(x, y) for x, y in zip(a.args, b.args))
    if isinstance(a, A.Cast):
        return a.type_name == b.type_name and _ast_equal(a.expr, b.expr)
    return False


def _const_int(e: A.Expr) -> int:
    if isinstance(e, A.Literal) and isinstance(e.value, int):
        return e.value
    if isinstance(e, A.Call) and e.op == "NEGATE":
        return -_const_int(e.args[0])
    raise ValidationException("", "LIMIT/OFFSET must be integer literals")


def _fold_to_literal(rex: RexNode) -> Optional[RexLiteral]:
    """Tiny constant folder for VALUES rows (e.g. -3, 1+1)."""
    if isinstance(rex, RexLiteral):
        return rex
    if isinstance(rex, RexCall) and all(isinstance(o, RexLiteral) for o in rex.operands):
        vals = [o.value for o in rex.operands]
        try:
            if rex.op == "NEGATE":
                return RexLiteral(-vals[0], rex.stype)
            if rex.op == "+":
                return RexLiteral(vals[0] + vals[1], rex.stype)
            if rex.op == "-":
                return RexLiteral(vals[0] - vals[1], rex.stype)
            if rex.op == "*":
                return RexLiteral(vals[0] * vals[1], rex.stype)
            if rex.op == "/":
                if rex.stype.is_integer:
                    return RexLiteral(int(vals[0] / vals[1]), rex.stype)
                return RexLiteral(vals[0] / vals[1], rex.stype)
            if rex.op == "CAST":
                return RexLiteral(vals[0], rex.stype)
        except Exception:
            return None
    return None


def _parse_daytime_interval(value: str, unit: str, to_unit: Optional[str]) -> int:
    """Parse compound day-time interval strings like '1 2:03:04.5'."""
    value = value.strip()
    sign = 1
    if value.startswith("-"):
        sign = -1
        value = value[1:]
    days = hours = minutes = 0
    seconds = 0.0
    if " " in value:
        d, rest = value.split(" ", 1)
        days = int(d)
        value = rest
    if ":" in value:
        parts = value.split(":")
        if unit == "HOUR" or (unit == "DAY" and days):
            pass
        nums = [float(p) for p in parts]
        if len(nums) == 3:
            hours, minutes, seconds = int(nums[0]), int(nums[1]), nums[2]
        elif len(nums) == 2:
            if unit in ("MINUTE",):
                minutes, seconds = int(nums[0]), nums[1]
            else:
                hours, minutes = int(nums[0]), int(nums[1])
    else:
        v = float(value)
        if unit == "DAY":
            days = int(v)
        elif unit == "HOUR":
            hours = int(v)
        elif unit == "MINUTE":
            minutes = int(v)
        else:
            seconds = v
    ms = (((days * 24 + hours) * 60 + minutes) * 60 + seconds) * 1000
    return sign * int(ms)


# ---------------------------------------------------------------------------
# correlated-subquery plan surgery (used by Binder decorrelation above)
# ---------------------------------------------------------------------------

def _rex_has_outer(rex: RexNode) -> bool:
    if isinstance(rex, RexOuterRef):
        return True
    if isinstance(rex, (RexCall, RexUdf)):
        return any(_rex_has_outer(o) for o in rex.operands)
    return False


def _node_rexes(node: RelNode) -> List[RexNode]:
    if isinstance(node, LogicalFilter):
        return [node.condition]
    if isinstance(node, LogicalProject):
        return list(node.exprs)
    if isinstance(node, LogicalJoin):
        return [node.condition] if node.condition is not None else []
    return []


def _plan_has_outer(plan: RelNode) -> bool:
    if any(_rex_has_outer(r) for r in _node_rexes(plan)):
        return True
    return any(_plan_has_outer(i) for i in plan.inputs)


def _walk_scalar_subqueries(e):
    """Yield scalar A.Subquery nodes inside an expression AST, without
    descending into subquery bodies (each body is bound by its own
    Binder; nested correlation resolves there)."""
    import dataclasses

    if isinstance(e, A.Subquery):
        if e.kind == "scalar":
            yield e
        return
    if not dataclasses.is_dataclass(e):
        return
    for f in dataclasses.fields(e):
        v = getattr(e, f.name, None)
        if isinstance(v, A.Node):
            yield from _walk_scalar_subqueries(v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, A.Node):
                    yield from _walk_scalar_subqueries(item)


def _extract_correlated(plan: RelNode, binder: "Binder", node: A.Node):
    """Split the correlated conjuncts out of the plan's top filter(s).

    Returns (plan without the correlated conjuncts, [corr conjunct rex]).
    Correlation anywhere deeper than the top filter stack (join conditions,
    nested subplans, projections) is rejected — those shapes need general
    unnesting, which this engine does not implement (reference: Calcite
    handles them via CorrelationId plans)."""
    from .optimizer import _and_all, _split_conjuncts as _split_rex

    corr: List[RexNode] = []
    core = plan
    while isinstance(core, LogicalProject) and not any(
            _rex_has_outer(e) for e in core.exprs):
        # projections above the filter are irrelevant for EXISTS
        core = core.input
    while isinstance(core, LogicalFilter):
        conjs = _split_rex(core.condition)
        pure = [c for c in conjs if not _rex_has_outer(c)]
        corr.extend(c for c in conjs if _rex_has_outer(c))
        inp = core.input
        if pure:
            cond = _and_all(pure)
            core = LogicalFilter(input=inp, condition=cond,
                                 schema=list(inp.schema))
            break
        core = inp
    if _plan_has_outer(core):
        binder.error("Unsupported correlated subquery "
                     "(correlation below the top-level WHERE)", node)
    return core, corr


def _corr_join_condition(corr: List[RexNode], nl: int) -> RexNode:
    """Correlated conjuncts -> join condition: outer refs address the left
    side verbatim, inner refs shift past it."""
    def rewrite(r: RexNode) -> RexNode:
        if isinstance(r, RexOuterRef):
            return RexInputRef(r.index, r.stype)
        if isinstance(r, RexInputRef):
            return RexInputRef(r.index + nl, r.stype)
        if isinstance(r, RexCall):
            return RexCall(r.op, [rewrite(o) for o in r.operands],
                           r.stype, r.info)
        if isinstance(r, RexUdf):
            return RexUdf(r.name, r.func, [rewrite(o) for o in r.operands],
                          r.stype, r.row_udf)
        return r

    if not corr:
        return RexLiteral(True, BOOLEAN)
    out = rewrite(corr[0])
    for c in corr[1:]:
        out = RexCall("AND", [out, rewrite(c)], BOOLEAN)
    return out


_CMP_FLIP = {"=": "=", "<>": "<>", "<": ">", ">": "<", "<=": ">=", ">=": "<="}


def _flip_cmp(op: str) -> str:
    return _CMP_FLIP[op]
