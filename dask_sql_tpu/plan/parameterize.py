"""Parameterized plan identity: hoist literals into runtime arguments.

The dominant production traffic pattern — the same query shape with
different constants — used to defeat every cache the engine has: each
literal minted a fresh canonical-plan fingerprint, so repeat arrivals
missed the program cache, the result cache AND the cross-process program
store, and paid the full XLA compile wall every time.  "Fine-Tuning Data
Structures" (PAPERS.md) frames the right split: specialize on STRUCTURE,
parameterize on VALUE.

This pass walks the OPTIMIZED plan and replaces eligible ``RexLiteral``
nodes with ``RexParam`` nodes.  A param fingerprints by position and type
only (``compiled._fp_rex`` emits ``P{i}:{TYPE}``), so every literal
variant of a shape shares one compiled program; the value rides as a
dtype-stable scalar jit argument appended after the table arrays.

Eligibility is deliberately narrow (v1):

- the literal is a DIRECT operand of a binary comparison
  (``= <> != < <= > >=``) whose other operand subtree contains at least
  one column reference — this guarantees the comparison broadcasts
  against a Column and never hits the both-scalar host branch
  (``ops.comparison``'s ``bool(fn(da, db))``), which would concretize a
  traced value;
- the literal's physical representation is numeric and non-NULL
  (integers, floats, DATE/TIMESTAMP/TIME micros/days).  Strings stay
  specialized: dictionary codes are resolved against the scan dictionary
  at trace time, so the code a string literal maps to is baked into the
  program.  Booleans and NULLs stay baked too (they steer trace-time
  simplifications).

Structure-changing literals are never touched: IN-list arity, LIMIT /
OFFSET counts (plain ints on LogicalSort, not rex), VALUES rows, scalar
subquery bodies, anything under a volatile call (RAND,
CURRENT_TIMESTAMP, ...) or a UDF.  The pass is idempotent — ``RexParam``
nodes pass through untouched — because the compiled path's degradation
ladder re-enters ``try_execute_compiled`` with an already-parameterized
plan.

``DSQL_PARAM_PLANS=0`` is the kill switch: the pass becomes the identity
and every fingerprint/cache key is bit-for-bit what it was before this
subsystem existed.
"""
from __future__ import annotations

import copy
import os
from typing import List, Tuple

from . import nodes as N

# binary comparisons whose literal operands are value-stable to hoist:
# the traced comparison is shape-generic in the scalar operand
PARAM_OPS = frozenset({"=", "<>", "!=", "<", "<=", ">", ">="})

# SqlType names whose physical representation is a plain numeric scalar
# (types.py): safe to pass as a 0-d jit argument with a stable dtype
PARAM_TYPE_NAMES = frozenset({
    "TINYINT", "SMALLINT", "INTEGER", "BIGINT",
    "FLOAT", "REAL", "DOUBLE", "DECIMAL",
    "DATE", "TIMESTAMP", "TIME",
})

# mirrors result_cache.VOLATILE_OPS (no import: plan/ must not depend on
# runtime/) — a literal adjacent to one of these stays specialized, so a
# volatile expression can never be partially hoisted into a shared shape
_VOLATILE_OPS = frozenset({
    "RAND", "RANDOM", "RAND_INTEGER",
    "CURRENT_DATE", "CURRENT_TIMESTAMP", "NOW", "LOCALTIMESTAMP",
    "CURRENT_TIME", "LOCALTIME",
})


def param_plans_enabled() -> bool:
    """DSQL_PARAM_PLANS kill switch; default ON."""
    return os.environ.get("DSQL_PARAM_PLANS", "1") != "0"


def _eligible_literal(rex: N.RexNode) -> bool:
    return (isinstance(rex, N.RexLiteral)
            and rex.value is not None
            and not isinstance(rex.value, (bool, str))
            and isinstance(rex.value, (int, float))
            and rex.stype is not None
            and rex.stype.name in PARAM_TYPE_NAMES)


def _has_column_ref(rex: N.RexNode) -> bool:
    if isinstance(rex, N.RexInputRef):
        return True
    if isinstance(rex, (N.RexCall, N.RexUdf)):
        return any(_has_column_ref(o) for o in rex.operands)
    return False


def _contains_volatile(rex: N.RexNode) -> bool:
    if isinstance(rex, N.RexUdf):
        return True
    if isinstance(rex, N.RexCall):
        if rex.op in _VOLATILE_OPS:
            return True
        return any(_contains_volatile(o) for o in rex.operands)
    return False


class _Hoist:
    __slots__ = ("next_slot", "hoisted")

    def __init__(self):
        self.next_slot = 0
        self.hoisted = 0

    def param(self, lit: N.RexLiteral) -> N.RexParam:
        p = N.RexParam(self.next_slot, lit.value, lit.stype)
        self.next_slot += 1
        self.hoisted += 1
        return p


def _walk_rex(rex: N.RexNode, acc: _Hoist) -> N.RexNode:
    """Rewrite eligible literals under this expression; returns ``rex``
    itself when nothing below changed."""
    if not isinstance(rex, N.RexCall):
        # literals NOT in an eligible comparison position stay baked;
        # scalar-subquery plans and UDFs stay specialized wholesale
        return rex
    if rex.op in _VOLATILE_OPS:
        return rex
    if (rex.op in PARAM_OPS and len(rex.operands) == 2
            and not any(_contains_volatile(o) for o in rex.operands)):
        a, b = rex.operands
        new_a, new_b = a, b
        if _eligible_literal(a) and _has_column_ref(b):
            new_a = acc.param(a)
        else:
            new_a = _walk_rex(a, acc)
        if _eligible_literal(b) and _has_column_ref(a):
            new_b = acc.param(b)
        else:
            new_b = _walk_rex(b, acc)
        if new_a is a and new_b is b:
            return rex
        return N.RexCall(rex.op, [new_a, new_b], rex.stype, rex.info)
    new_ops = [_walk_rex(o, acc) for o in rex.operands]
    if all(n is o for n, o in zip(new_ops, rex.operands)):
        return rex
    return N.RexCall(rex.op, new_ops, rex.stype, rex.info)


def _walk_rel(rel: N.RelNode, acc: _Hoist) -> N.RelNode:
    kids = rel.inputs
    new_kids = [_walk_rel(k, acc) for k in kids]
    changed = any(n is not o for n, o in zip(new_kids, kids))

    # only these three node kinds carry hoistable expressions; everything
    # else (Aggregate args, Sort limits, Values rows, Window frames) is
    # structure and stays specialized
    if isinstance(rel, N.LogicalFilter):
        cond = _walk_rex(rel.condition, acc)
        if cond is not rel.condition or changed:
            out = copy.copy(rel)
            out.input = new_kids[0]
            out.condition = cond
            return out
        return rel
    if isinstance(rel, N.LogicalProject):
        exprs = [_walk_rex(e, acc) for e in rel.exprs]
        if changed or any(n is not o for n, o in zip(exprs, rel.exprs)):
            out = copy.copy(rel)
            out.input = new_kids[0]
            out.exprs = exprs
            return out
        return rel
    if isinstance(rel, N.LogicalJoin):
        cond = (None if rel.condition is None
                else _walk_rex(rel.condition, acc))
        if cond is not rel.condition or changed:
            # copy.copy keeps dynamically-attached verdicts (null_aware)
            out = copy.copy(rel)
            out.left, out.right = new_kids
            out.condition = cond
            return out
        return rel
    if changed:
        return rel.with_inputs(new_kids)
    return rel


def parameterize_plan(plan: N.RelNode) -> Tuple[N.RelNode, int]:
    """(rewritten plan, number of literals hoisted THIS call).

    Idempotent: a second pass over the result hoists nothing (RexParam is
    not RexLiteral), so re-entrant callers (the whole→stages degradation
    rung) never double-count or renumber."""
    acc = _Hoist()
    new = _walk_rel(plan, acc)
    return new, acc.hoisted


def collect_params(plan: N.RelNode) -> List[N.RexParam]:
    """Every RexParam in this (sub)plan, ordered by slot.

    Diagnostic/introspection helper — the compiled path orders its
    bound-argument vector by FINGERPRINT traversal instead
    (``compiled._fp_plan`` collects params as it serializes), so the arg
    order and the ``P{i}`` positions in the key can never disagree."""
    out: List[N.RexParam] = []
    seen: set = set()

    def rex(r: N.RexNode):
        if isinstance(r, N.RexParam):
            if id(r) not in seen:
                seen.add(id(r))
                out.append(r)
        elif isinstance(r, (N.RexCall, N.RexUdf)):
            for o in r.operands:
                rex(o)
        elif isinstance(r, N.RexScalarSubquery):
            rel(r.plan)

    def rel(node: N.RelNode):
        if isinstance(node, N.LogicalProject):
            for e in node.exprs:
                rex(e)
        elif isinstance(node, N.LogicalFilter):
            rex(node.condition)
        elif isinstance(node, N.LogicalJoin) and node.condition is not None:
            rex(node.condition)
        for k in node.inputs:
            rel(k)

    rel(plan)
    out.sort(key=lambda p: p.slot)
    return out
