"""Rule-based heuristic optimizer.

Reproduces the load-bearing effects of the reference's 17-rule HepPlanner
program (/root/reference/planner/.../RelationalAlgebraGenerator.java:198-224):
FILTER_INTO_JOIN / JOIN_CONDITION_PUSH (filter pushdown through projects and
into join sides), PROJECT_MERGE / FILTER_MERGE, and projection pruning down to
table scans (the effect of ProjectableFilterableTable + PROJECT rules).
AVG/DISTINCT decompositions are unnecessary here — the segment-reduction
kernels implement those aggregates directly.

Passes are applied to fixpoint in a bounded loop; every pass is a pure
RelNode -> RelNode function, so user rules can be appended to ``PASSES``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..types import BOOLEAN
from .nodes import (
    AggCall, Field, LogicalAggregate, LogicalExcept, LogicalFilter,
    LogicalIntersect, LogicalJoin, LogicalProject, LogicalSample, LogicalSort,
    LogicalTableScan, LogicalUnion, LogicalValues, LogicalWindow, RelNode,
    RexCall, RexInputRef, RexLiteral, RexNode, RexScalarSubquery, RexUdf,
    SortCollation, WindowCall, remap_rex, rex_inputs,
)


# ---------------------------------------------------------------------------
# generic helpers
# ---------------------------------------------------------------------------

def _split_conjuncts(rex: RexNode) -> List[RexNode]:
    if isinstance(rex, RexCall) and rex.op == "AND":
        return _split_conjuncts(rex.operands[0]) + _split_conjuncts(rex.operands[1])
    return [rex]


def _and_all(rexes: List[RexNode]) -> Optional[RexNode]:
    if not rexes:
        return None
    out = rexes[0]
    for r in rexes[1:]:
        out = RexCall("AND", [out, r], BOOLEAN)
    return out


def _is_pure(rex: RexNode) -> bool:
    """True if the expression is deterministic & side-effect free (safe to
    push/duplicate)."""
    if isinstance(rex, (RexInputRef, RexLiteral)):
        return True
    if isinstance(rex, RexScalarSubquery):
        return False
    if isinstance(rex, RexUdf):
        return False
    if isinstance(rex, RexCall):
        if rex.op in ("RAND", "RANDOM", "RAND_INTEGER"):
            return False
        return all(_is_pure(o) for o in rex.operands)
    return False


# ---------------------------------------------------------------------------
# pass: merge adjacent filters, drop TRUE filters
# ---------------------------------------------------------------------------

def merge_filters(rel: RelNode) -> RelNode:
    rel = rel.with_inputs([merge_filters(i) for i in rel.inputs]) if rel.inputs else rel
    if isinstance(rel, LogicalFilter):
        if isinstance(rel.condition, RexLiteral) and rel.condition.value is True:
            return rel.input
        if isinstance(rel.input, LogicalFilter):
            cond = RexCall("AND", [rel.input.condition, rel.condition], BOOLEAN)
            return LogicalFilter(input=rel.input.input, condition=cond,
                                 schema=rel.schema)
    return rel


# ---------------------------------------------------------------------------
# pass: merge Project(Project) — PROJECT_MERGE
# ---------------------------------------------------------------------------

def _inline_rex(rex: RexNode, exprs: List[RexNode]) -> RexNode:
    if isinstance(rex, RexInputRef):
        return exprs[rex.index]
    if isinstance(rex, RexCall):
        return RexCall(rex.op, [_inline_rex(o, exprs) for o in rex.operands],
                       rex.stype, rex.info)
    if isinstance(rex, RexUdf):
        return RexUdf(rex.name, rex.func, [_inline_rex(o, exprs) for o in rex.operands],
                      rex.stype, rex.row_udf)
    return rex


def _rex_size(rex: RexNode) -> int:
    if isinstance(rex, (RexCall, RexUdf)):
        return 1 + sum(_rex_size(o) for o in rex.operands)
    return 1


def merge_projects(rel: RelNode) -> RelNode:
    rel = rel.with_inputs([merge_projects(i) for i in rel.inputs]) if rel.inputs else rel
    if isinstance(rel, LogicalProject) and isinstance(rel.input, LogicalProject):
        inner = rel.input
        if all(_is_pure(e) for e in inner.exprs):
            new_exprs = [_inline_rex(e, inner.exprs) for e in rel.exprs]
            # avoid exponential blowup from duplicating huge exprs
            if sum(map(_rex_size, new_exprs)) <= 4 * (
                sum(map(_rex_size, rel.exprs)) + sum(map(_rex_size, inner.exprs))
            ):
                return LogicalProject(input=inner.input, exprs=new_exprs,
                                      schema=rel.schema)
    return rel


# ---------------------------------------------------------------------------
# pass: push filters down — FILTER_INTO_JOIN / FILTER_PROJECT_TRANSPOSE /
# FILTER_AGGREGATE_TRANSPOSE
# ---------------------------------------------------------------------------

def push_filters(rel: RelNode) -> RelNode:
    if rel.inputs:
        rel = rel.with_inputs([push_filters(i) for i in rel.inputs])
    if not isinstance(rel, LogicalFilter):
        return rel
    child = rel.input
    conjuncts = _split_conjuncts(rel.condition)

    # -- through Project: rewrite refs via inlining (only pure exprs)
    if isinstance(child, LogicalProject) and all(_is_pure(e) for e in child.exprs):
        pushable = [c for c in conjuncts if _is_pure(c)]
        stay = [c for c in conjuncts if not _is_pure(c)]
        if pushable:
            inner_cond = _and_all([_inline_rex(c, child.exprs) for c in pushable])
            new_input = push_filters(LogicalFilter(
                input=child.input, condition=inner_cond, schema=child.input.schema))
            new_child = LogicalProject(input=new_input, exprs=child.exprs,
                                       schema=child.schema)
            if stay:
                return LogicalFilter(input=new_child, condition=_and_all(stay),
                                     schema=rel.schema)
            return new_child

    # -- into Join sides
    if isinstance(child, LogicalJoin) and child.join_type in ("INNER", "LEFT", "RIGHT", "CROSS"):
        nl = len(child.left.schema)
        left_side, right_side, into_join, stay = [], [], [], []
        for c in conjuncts:
            refs = rex_inputs(c)
            if not _is_pure(c):
                stay.append(c)
            elif all(r < nl for r in refs) and child.join_type in ("INNER", "LEFT", "CROSS"):
                left_side.append(c)
            elif all(r >= nl for r in refs) and child.join_type in ("INNER", "RIGHT", "CROSS"):
                right_side.append(c)
            elif child.join_type in ("INNER", "CROSS"):
                # both-side conjunct becomes part of the join condition so the
                # executor can extract equi keys (FILTER_INTO_JOIN,
                # RelationalAlgebraGenerator.java:207-208)
                into_join.append(c)
            else:
                stay.append(c)
        if left_side or right_side or into_join:
            new_left, new_right = child.left, child.right
            if left_side:
                new_left = push_filters(LogicalFilter(
                    input=child.left, condition=_and_all(left_side),
                    schema=child.left.schema))
            if right_side:
                shifted = [remap_rex(c, {i: i - nl for i in rex_inputs(c)})
                           for c in right_side]
                new_right = push_filters(LogicalFilter(
                    input=child.right, condition=_and_all(shifted),
                    schema=child.right.schema))
            cond = child.condition
            jt = child.join_type
            if into_join:
                pieces = ([] if cond is None or (
                    isinstance(cond, RexLiteral) and cond.value is True) else [cond])
                cond = _and_all(pieces + into_join)
                jt = "INNER"
            new_join = LogicalJoin(left=new_left, right=new_right,
                                   join_type=jt, condition=cond,
                                   schema=child.schema)
            if stay:
                return LogicalFilter(input=new_join, condition=_and_all(stay),
                                     schema=rel.schema)
            return new_join

    # -- through SEMI/ANTI joins: their output IS the left input, so pure
    # conjuncts always push into the left side (without this, a WHERE above
    # a decorrelated IN/EXISTS keeps whole cross products unfiltered)
    if isinstance(child, LogicalJoin) and child.join_type in ("SEMI", "ANTI"):
        pushable = [c for c in conjuncts if _is_pure(c)]
        stay = [c for c in conjuncts if not _is_pure(c)]
        if pushable:
            new_left = push_filters(LogicalFilter(
                input=child.left, condition=_and_all(pushable),
                schema=child.left.schema))
            new_join = LogicalJoin(left=new_left, right=child.right,
                                   join_type=child.join_type,
                                   condition=child.condition,
                                   schema=child.schema)
            if hasattr(child, "null_aware"):
                new_join.null_aware = child.null_aware  # type: ignore
            if stay:
                return LogicalFilter(input=new_join, condition=_and_all(stay),
                                     schema=rel.schema)
            return new_join

    # -- through Aggregate: conjuncts that only touch group keys
    if isinstance(child, LogicalAggregate):
        n_keys = len(child.group_keys)
        pushable, stay = [], []
        for c in conjuncts:
            refs = rex_inputs(c)
            if _is_pure(c) and all(r < n_keys for r in refs):
                pushable.append(c)
            else:
                stay.append(c)
        if pushable:
            mapping = {i: child.group_keys[i] for i in range(n_keys)}
            inner = _and_all([remap_rex(c, mapping) for c in pushable])
            new_input = push_filters(LogicalFilter(
                input=child.input, condition=inner, schema=child.input.schema))
            new_agg = LogicalAggregate(input=new_input, group_keys=child.group_keys,
                                       aggs=child.aggs, schema=child.schema)
            if stay:
                return LogicalFilter(input=new_agg, condition=_and_all(stay),
                                     schema=rel.schema)
            return new_agg

    return rel


# ---------------------------------------------------------------------------
# pass: connectivity-based join reordering
# ---------------------------------------------------------------------------

def reorder_joins(rel: RelNode, context=None) -> RelNode:
    """Reorder INNER/CROSS join chains so every step has a join predicate.

    The binder lowers a comma FROM list to a left-deep cross-product chain
    and relies on filter pushdown to recover equi joins — which fails when
    two FROM neighbours only connect through a later table (TPC-H Q9:
    ``part, supplier, lineitem, ...`` — part and supplier both join
    lineitem, not each other), leaving a true cross product. Calcite's
    planner has the same weakness in the reference's rule list (no
    JoinCommute/LoptOptimize there either), but its users write ANSI JOINs;
    our oracle suite uses comma syntax heavily.

    Only chains where the given order actually strands a step without a
    connecting predicate are rewritten (greedy: next leaf in FROM order
    connected to the joined prefix, equi predicates preferred); otherwise
    the plan is left exactly as written.
    """
    # match Filter(chain) / bare chain BEFORE the generic recursion: the
    # rewrite must see the filter's conjunct pool together with the intact
    # chain (recursing first would rebuild the chain under a Project and
    # hide it from the filter-level match); leaf subtrees are recursed
    # through the rewritten node's inputs afterwards
    out = None
    if isinstance(rel, LogicalFilter) and isinstance(rel.input, LogicalJoin):
        out = _reorder_chain(rel.input, _split_conjuncts(rel.condition),
                             context)
    elif isinstance(rel, LogicalJoin):
        out = _reorder_chain(rel, [], context)
    if out is not None:
        chain, leftover = out
        new: RelNode = chain
        if leftover:
            new = LogicalFilter(input=chain, condition=_and_all(leftover),
                                schema=chain.schema)
        return new.with_inputs([reorder_joins(i, context)
                                for i in new.inputs])
    if rel.inputs:
        rel = rel.with_inputs([reorder_joins(i, context)
                               for i in rel.inputs])
    return rel


def reorder_joins_stats(rel: RelNode, context) -> RelNode:
    """Statistics-driven join ordering (runtime/statistics.py): rank join
    orders by estimated output cardinality — NDV-based equi-join
    selectivity over ingest stats — instead of the stranded-conjunct count
    alone.  Runs as a POST-pass after the native/Python pipeline (both
    leave semantics-preserving INNER/CROSS chains), rewrites only on a
    clear estimated-cost win that never increases stranded steps, and is
    a no-op without stats or with DSQL_ADAPTIVE=0."""
    from ..runtime import statistics as _stats

    if context is None or not _stats.adaptive_enabled():
        return rel
    try:
        return reorder_joins(rel, context)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        logger.debug("stats join reorder failed; keeping plan",
                     exc_info=True)
        return rel


def _reorder_chain(root: LogicalJoin, filt_conjuncts: List[RexNode],
                   context=None):
    """Returns (new_rel, leftover_filter_conjuncts) or None to keep as-is.

    With ``context`` (stats mode) the greedy order minimizes ESTIMATED
    intermediate cardinality instead of just chasing connectivity, and
    the rewrite guard becomes "clearly cheaper and never more stranded"
    instead of "strictly fewer stranded steps"."""
    if root.join_type not in ("INNER", "CROSS"):
        return None
    leaves: List[Tuple[int, RelNode]] = []   # (global offset, leaf)
    pool: List[RexNode] = []                 # conjuncts in global ordinals

    def flat(j: RelNode, base: int) -> int:
        if isinstance(j, LogicalJoin) and j.join_type in ("INNER", "CROSS"):
            lw = flat(j.left, base)
            rw = flat(j.right, base + lw)
            if j.condition is not None and not (
                    isinstance(j.condition, RexLiteral)
                    and j.condition.value is True):
                for cj in _split_conjuncts(j.condition):
                    pool.append(remap_rex(
                        cj, {i: base + i for i in rex_inputs(cj)}))
            return lw + rw
        leaves.append((base, j))
        return len(j.schema)

    total = flat(root, 0)
    if len(leaves) < 3:
        return None

    leaf_of: Dict[int, int] = {}
    for li, (off, leaf) in enumerate(leaves):
        for o in range(off, off + len(leaf.schema)):
            leaf_of[o] = li

    def leafset(c: RexNode) -> Set[int]:
        return {leaf_of[r] for r in rex_inputs(c)}

    def is_equi(c: RexNode) -> bool:
        return isinstance(c, RexCall) and c.op == "="

    # connectors: pure multi-leaf conjuncts from join conditions AND the
    # filter above; single-leaf/impure filter conjuncts stay behind for
    # push_filters
    cand = pool + [c for c in filt_conjuncts if _is_pure(c)]
    connectors = [(c, leafset(c)) for c in cand if len(leafset(c)) >= 2]
    if not connectors:
        return None

    def count_stranded(seq: List[int]) -> int:
        joined: Set[int] = {seq[0]}
        bad = 0
        for li in seq[1:]:
            if not any(li in ls and (ls - {li}) <= joined
                       for _, ls in connectors):
                bad += 1
            joined.add(li)
        return bad

    # Stranded steps in the ORIGINAL plan are counted against its actual
    # (possibly bushy) tree — a join node is a cross step only if no
    # connector within its subtree spans its two children. Linearizing the
    # original into a left-deep sequence would falsely count connected bushy
    # joins as stranded and rewrite plans that need no help (ADVICE r1).
    leaf_iter = iter(range(len(leaves)))

    def tree_stranded(j: RelNode) -> Tuple[Set[int], int]:
        if isinstance(j, LogicalJoin) and j.join_type in ("INNER", "CROSS"):
            lset, lbad = tree_stranded(j.left)
            rset, rbad = tree_stranded(j.right)
            here = lset | rset
            connected = any(ls & lset and ls & rset and ls <= here
                            for _, ls in connectors)
            return here, lbad + rbad + (0 if connected else 1)
        return {next(leaf_iter)}, 0

    orig_stranded = tree_stranded(root)[1]
    if orig_stranded == 0 and context is None:
        return None

    if context is not None:
        order = _stats_order(leaves, leaf_of, connectors, is_equi, context)
        # never trade estimated cost for MORE stranded (cross) steps, and
        # only rewrite when the order actually changed — an equal order
        # would re-trigger on its own output every optimize() call
        if (order is None or order == list(range(len(leaves)))
                or count_stranded(order) > orig_stranded):
            return None
    else:
        # greedy order: prefer an equi-connected leaf (FROM order), then
        # any connected leaf, then fall back to a genuine cross step
        order = [0]
        joined = {0}
        remaining = list(range(1, len(leaves)))
        while remaining:
            pick = None
            for want_equi in (True, False):
                for li in remaining:
                    for c, ls in connectors:
                        if (li in ls and (ls - {li}) <= joined
                                and (is_equi(c) or not want_equi)):
                            pick = li
                            break
                    if pick is not None:
                        break
                if pick is not None:
                    break
            if pick is None:
                pick = remaining[0]
            order.append(pick)
            joined.add(pick)
            remaining.remove(pick)

        # rewrite only on STRICT improvement: an equally-stranded reorder
        # would re-trigger on its own output forever (a genuinely
        # unconnected pair stays a cross join no matter the order)
        if count_stranded(order) >= orig_stranded:
            return None

    # ordinal mapping old-global -> new-global
    old_to_new: Dict[int, int] = {}
    new_off = 0
    for li in order:
        off, leaf = leaves[li]
        for k in range(len(leaf.schema)):
            old_to_new[off + k] = new_off + k
        new_off += len(leaf.schema)

    # build the left-deep tree, attaching each connector at the first step
    # where all its leaves are available
    placed = [False] * len(connectors)
    single = [c for c in pool if len(leafset(c)) < 2]
    acc = leaves[order[0]][1]
    covered = {order[0]}
    for li in order[1:]:
        covered.add(li)
        conds = []
        for ci, (c, ls) in enumerate(connectors):
            if not placed[ci] and ls <= covered:
                placed[ci] = True
                conds.append(remap_rex(c, {o: old_to_new[o]
                                           for o in rex_inputs(c)}))
        leaf = leaves[li][1]
        schema = list(acc.schema) + list(leaf.schema)
        acc = LogicalJoin(left=acc, right=leaf,
                          join_type="INNER" if conds else "CROSS",
                          condition=_and_all(conds), schema=schema)

    # restore the original column order for the parent
    orig_fields: List[Field] = []
    for off, leaf in leaves:
        orig_fields.extend(leaf.schema)
    exprs = [RexInputRef(old_to_new[o], orig_fields[o].stype)
             for o in range(total)]
    proj = LogicalProject(input=acc, exprs=exprs, schema=orig_fields)

    # leftovers: consumed filter connectors disappear from the filter;
    # single-leaf join-condition conjuncts rejoin the filter pool (they
    # were inside join conditions, now remapped to the original ordinals
    # the filter namespace uses — which ARE the original global ordinals)
    used_filter = {id(c) for (c, ls), p in zip(connectors, placed)
                   if p and any(c is fc for fc in filt_conjuncts)}
    leftover = [c for c in filt_conjuncts
                if id(c) not in used_filter]
    leftover.extend(single)
    return proj, leftover


def _stats_order(leaves, leaf_of, connectors, is_equi, context):
    """Greedy minimum-estimated-cardinality join order (System-R style,
    left-deep, no DP — chains are short).  Returns the leaf order or None
    when any leaf is inestimable or no order clearly beats the written
    one (10% hysteresis so borderline estimates don't flap plans)."""
    from ..runtime import statistics as _stats

    leaf_rows = []
    for _, leaf in leaves:
        r = _stats.estimate_rows(leaf, context)
        if r is None:
            return None
        leaf_rows.append(max(float(r), 1.0))

    def ordinal_ndv(o):
        li = leaf_of[o]
        cs = _stats.column_stats_for(
            leaves[li][1], o - leaves[li][0], context)
        return cs.ndv if cs is not None and cs.ndv else None

    def step(cur, joined, li):
        """Estimated rows after joining leaf ``li`` onto the prefix."""
        est = cur * leaf_rows[li]
        connected = False
        for c, ls in connectors:
            if li in ls and (ls - {li}) <= joined:
                connected = True
                if is_equi(c):
                    ndvs = [v for v in (ordinal_ndv(o)
                                        for o in rex_inputs(c)) if v]
                    est /= max(max(ndvs) if ndvs else 10.0, 10.0)
                else:
                    est *= 0.5
        return max(est, 1.0), connected

    # The compiled equi join builds a hash table on its smaller side and
    # requires a UNIQUE build key (physical/compiled.py flags a duplicate
    # build at runtime and drops the whole plan to eager).  An attach step
    # is "risky" when NEITHER side of its equi key can be proven unique
    # from stats; the greedy avoids risky steps and an order that is
    # riskier than the written one is rejected outright — a cardinality
    # win is worthless if it costs the compiled path.
    unique_cache: Dict[int, Set[int]] = {}

    def leaf_unique_ords(li):
        got = unique_cache.get(li)
        if got is None:
            off, leaf = leaves[li]
            got = set()
            for k in range(len(leaf.schema)):
                cs = _stats.column_stats_for(leaf, k, context)
                if (cs is not None and cs.ndv
                        and cs.ndv >= 0.95 * leaf_rows[li]):
                    got.add(off + k)
            unique_cache[li] = got
        return got

    def attach(uniq, joined, li):
        """(risky, new_uniq) for attaching ``li`` to the prefix.  ``uniq``
        is the set of ordinals the prefix is provably unique on; it
        survives a step only through the side whose key IS unique (the
        other side's rows may fan out)."""
        leaf_ords, int_ords = set(), set()
        for c, ls in connectors:
            if li in ls and (ls - {li}) <= joined and is_equi(c):
                for o in rex_inputs(c):
                    (leaf_ords if leaf_of[o] == li else int_ords).add(o)
        if not leaf_ords:  # cross or pure non-equi step: no hash build
            return False, set()
        leaf_u = leaf_unique_ords(li)
        leaf_ok = bool(leaf_ords & leaf_u)
        int_ok = bool(int_ords & uniq)
        new: Set[int] = set()
        if leaf_ok:
            new |= uniq
        if int_ok:
            new |= leaf_u
        return not (leaf_ok or int_ok), new

    def seq_cost(seq):
        cur = leaf_rows[seq[0]]
        joined = {seq[0]}
        cost = 0.0
        for li in seq[1:]:
            cur, _ = step(cur, joined, li)
            joined.add(li)
            cost += cur
        return cost

    def seq_risk(seq):
        joined = {seq[0]}
        uniq = leaf_unique_ords(seq[0])
        risk = 0
        for li in seq[1:]:
            risky, uniq = attach(uniq, joined, li)
            risk += risky
            joined.add(li)
        return risk

    def greedy(start):
        order = [start]
        joined = {start}
        uniq = leaf_unique_ords(start)
        cur = leaf_rows[start]
        cost = 0.0
        risk = 0
        remaining = [i for i in range(len(leaves)) if i != start]
        while remaining:
            best = None
            for li in remaining:
                est, connected = step(cur, joined, li)
                risky, _ = attach(uniq, joined, li)
                key = (0 if connected else 1, 1 if risky else 0, est, li)
                if best is None or key < best[0]:
                    best = (key, li, est)
            _, li, est = best
            risky, uniq = attach(uniq, joined, li)
            risk += risky
            order.append(li)
            joined.add(li)
            remaining.remove(li)
            cur = est
            cost += est
        return order, cost, risk

    best_order, best_cost, best_risk = None, None, 0
    for start in range(len(leaves)):
        order, cost, risk = greedy(start)
        if best_cost is None or (risk, cost) < (best_risk, best_cost):
            best_order, best_cost, best_risk = order, cost, risk

    base_cost = seq_cost(list(range(len(leaves))))
    if (best_order == list(range(len(leaves)))
            or best_cost >= 0.9 * base_cost
            or best_risk > seq_risk(list(range(len(leaves))))):
        return None
    _stats.record_choice("join_order", "stats", leaves=len(leaves),
                         est=int(best_cost), base=int(base_cost))
    return best_order


# ---------------------------------------------------------------------------
# pass: extract equi conditions from join residuals into the condition
# (JOIN_CONDITION_PUSH is implicit: our executor splits equi pairs itself)
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# pass: column pruning down to TableScan
# ---------------------------------------------------------------------------

def prune_columns(rel: RelNode) -> RelNode:
    new_rel, _ = _prune(rel, set(range(len(rel.schema))))
    return new_rel


def _identity_map(n: int) -> Dict[int, int]:
    return {i: i for i in range(n)}


def _prune(rel: RelNode, needed: Set[int]) -> Tuple[RelNode, Dict[int, int]]:
    """Returns (new_rel, mapping old_ordinal -> new_ordinal).

    ``needed`` are the output ordinals the parent requires; a node may keep
    more.  Mapping covers at least ``needed``.
    """
    if isinstance(rel, LogicalTableScan):
        keep = sorted(needed) if needed else list(range(min(1, len(rel.schema))))
        if not keep:
            keep = [0] if rel.schema else []
        new_schema = [rel.schema[i] for i in keep]
        mapping = {o: i for i, o in enumerate(keep)}
        return LogicalTableScan(rel.schema_name, rel.table_name, new_schema), mapping

    if isinstance(rel, LogicalProject):
        keep = sorted(needed) if needed else ([0] if rel.exprs else [])
        child_needed: Set[int] = set()
        for i in keep:
            child_needed.update(rex_inputs(rel.exprs[i]))
        new_child, cmap = _prune(rel.input, child_needed)
        new_exprs = [remap_rex(rel.exprs[i], cmap) for i in keep]
        new_schema = [rel.schema[i] for i in keep]
        mapping = {o: i for i, o in enumerate(keep)}
        return LogicalProject(new_child, new_exprs, new_schema), mapping

    if isinstance(rel, LogicalFilter):
        child_needed = set(needed) | set(rex_inputs(rel.condition))
        new_child, cmap = _prune(rel.input, child_needed)
        cond = remap_rex(rel.condition, cmap)
        keep = sorted(needed) if needed else sorted(cmap.keys())
        exprs = [RexInputRef(cmap[i], rel.schema[i].stype) for i in keep]
        new_schema = [rel.schema[i] for i in keep]
        if sorted(cmap.keys()) == keep and all(cmap[k] == j for j, k in enumerate(keep)):
            return LogicalFilter(new_child, cond, new_schema), {o: i for i, o in enumerate(keep)}
        filt = LogicalFilter(new_child, cond, list(new_child.schema))
        proj = LogicalProject(filt, exprs, new_schema)
        return proj, {o: i for i, o in enumerate(keep)}

    if isinstance(rel, LogicalAggregate):
        n_keys = len(rel.group_keys)
        used_aggs = sorted(i - n_keys for i in needed if i >= n_keys)
        child_needed = set(rel.group_keys)
        for ai in used_aggs:
            child_needed.update(rel.aggs[ai].args)
            if rel.aggs[ai].filter_arg is not None:
                child_needed.add(rel.aggs[ai].filter_arg)
        new_child, cmap = _prune(rel.input, child_needed)
        new_keys = [cmap[k] for k in rel.group_keys]
        new_aggs = []
        for ai in used_aggs:
            a = rel.aggs[ai]
            new_aggs.append(AggCall(a.op, [cmap[x] for x in a.args], a.distinct,
                                    a.stype, a.name,
                                    cmap[a.filter_arg] if a.filter_arg is not None else None,
                                    a.udaf))
        new_schema = rel.schema[:n_keys] + [rel.schema[n_keys + ai] for ai in used_aggs]
        mapping = {i: i for i in range(n_keys)}
        for j, ai in enumerate(used_aggs):
            mapping[n_keys + ai] = n_keys + j
        return LogicalAggregate(new_child, new_keys, new_aggs, new_schema), mapping

    if isinstance(rel, LogicalJoin):
        nl = len(rel.left.schema)
        cond_refs = set(rex_inputs(rel.condition)) if rel.condition is not None else set()
        all_needed = set(needed) | cond_refs
        left_needed = {i for i in all_needed if i < nl}
        right_needed = {i - nl for i in all_needed if i >= nl}
        new_left, lmap = _prune(rel.left, left_needed)
        new_right, rmap = _prune(rel.right, right_needed)
        new_nl = len(new_left.schema)
        mapping = {}
        for o, n in lmap.items():
            mapping[o] = n
        for o, n in rmap.items():
            mapping[nl + o] = new_nl + n
        cond = remap_rex(rel.condition, mapping) if rel.condition is not None else None
        if rel.join_type in ("SEMI", "ANTI"):
            new_schema = [rel.schema[i] for i in sorted(lmap.keys())]
            # the right side is not part of the output: returning its
            # phantom ordinals would corrupt the parent's schema accounting
            out_mapping = dict(lmap)
        else:
            new_schema = ([rel.schema[i] for i in sorted(lmap.keys())] +
                          [rel.schema[nl + i] for i in sorted(rmap.keys())])
            out_mapping = mapping
        out = LogicalJoin(new_left, new_right, rel.join_type, cond, new_schema)
        if hasattr(rel, "null_aware"):
            out.null_aware = rel.null_aware  # type: ignore[attr-defined]
        return out, out_mapping

    if isinstance(rel, LogicalSort):
        child_needed = set(needed) | {c.index for c in rel.collation}
        new_child, cmap = _prune(rel.input, child_needed)
        coll = [SortCollation(cmap[c.index], c.ascending, c.nulls_first)
                for c in rel.collation]
        new_schema = [rel.schema[i] for i in sorted(cmap.keys())]
        # schema must mirror child schema ordering
        inv = sorted(cmap.keys())
        new_schema = [rel.schema[i] for i in inv]
        return LogicalSort(new_child, coll, rel.limit, rel.offset, new_schema), cmap

    if isinstance(rel, LogicalWindow):
        n_in = len(rel.input.schema)
        used_calls = sorted(i - n_in for i in needed if i >= n_in)
        child_needed = {i for i in needed if i < n_in}
        for ci in used_calls:
            c = rel.calls[ci]
            child_needed.update(c.args)
            child_needed.update(c.partition)
            child_needed.update(k.index for k in c.order)
        new_child, cmap = _prune(rel.input, child_needed)
        new_calls = []
        for ci in used_calls:
            c = rel.calls[ci]
            new_calls.append(WindowCall(
                c.op, [cmap[a] for a in c.args], [cmap[p] for p in c.partition],
                [SortCollation(cmap[k.index], k.ascending, k.nulls_first)
                 for k in c.order], c.frame, c.stype, c.name))
        new_schema = list(new_child.schema) + [rel.schema[n_in + ci] for ci in used_calls]
        mapping = dict(cmap)
        for j, ci in enumerate(used_calls):
            mapping[n_in + ci] = len(new_child.schema) + j
        return LogicalWindow(new_child, new_calls, new_schema), mapping

    if isinstance(rel, (LogicalUnion, LogicalIntersect, LogicalExcept)):
        # set ops need all columns (row identity)
        new_inputs = []
        for i in rel.inputs_:
            ni, _ = _prune(i, set(range(len(i.schema))))
            new_inputs.append(ni)
        out = rel.with_inputs(new_inputs)
        return out, _identity_map(len(rel.schema))

    if isinstance(rel, LogicalSample):
        new_child, cmap = _prune(rel.input, needed)
        new_schema = [f for f in new_child.schema]
        return LogicalSample(new_child, rel.method, rel.percentage, rel.seed,
                             new_schema), cmap

    # default: require everything below, identity above
    if rel.inputs:
        new_inputs = []
        for i in rel.inputs:
            ni, imap = _prune(i, set(range(len(i.schema))))
            new_inputs.append(ni)
        rel = rel.with_inputs(new_inputs)
    return rel, _identity_map(len(rel.schema))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _factor_or(rex: RexNode) -> RexNode:
    """Pull conjuncts common to every OR branch out of the OR:
    (a AND x) OR (a AND y) -> a AND (x OR y).

    Equivalent under SQL three-valued logic for predicate positions (both
    forms are non-true in exactly the same cases). Without it, TPC-H Q19's
    OR-of-conjuncts hides its shared equi-join key and the executor falls
    back to a full cross product.
    """
    if not isinstance(rex, RexCall):
        return rex
    rex = RexCall(rex.op, [_factor_or(o) for o in rex.operands],
                  rex.stype, rex.info)
    if rex.op != "OR":
        return rex

    def branches(r: RexNode) -> List[RexNode]:
        if isinstance(r, RexCall) and r.op == "OR":
            return branches(r.operands[0]) + branches(r.operands[1])
        return [r]

    brs = [(_split_conjuncts(b)) for b in branches(rex)]
    common = [c for c in brs[0]
              if _is_pure(c) and all(any(c == d for d in b) for b in brs[1:])]
    if not common:
        return rex
    rest_branches = []
    for b in brs:
        rest = [c for c in b if not any(c == d for d in common)]
        rest_branches.append(_and_all(rest) or RexLiteral(True, BOOLEAN))
    rest_or = rest_branches[0]
    for rb in rest_branches[1:]:
        rest_or = RexCall("OR", [rest_or, rb], BOOLEAN)
    return _and_all(common + [rest_or])


def factor_or_predicates(rel: RelNode) -> RelNode:
    if rel.inputs:
        rel = rel.with_inputs([factor_or_predicates(i) for i in rel.inputs])
    if isinstance(rel, LogicalFilter):
        return LogicalFilter(input=rel.input,
                             condition=_factor_or(rel.condition),
                             schema=rel.schema)
    if isinstance(rel, LogicalJoin) and rel.condition is not None:
        out = LogicalJoin(left=rel.left, right=rel.right,
                          join_type=rel.join_type,
                          condition=_factor_or(rel.condition),
                          schema=rel.schema)
        if hasattr(rel, "null_aware"):
            out.null_aware = rel.null_aware  # type: ignore[attr-defined]
        return out
    return rel


# push_filters runs BEFORE reorder_joins: sinking filter equalities into
# join conditions first both repairs chains that need no reordering (TPC-H
# Q17: the equi predicate lives two filters above the non-equi join) and
# feeds the reorder pass a complete connector pool via the join conditions
# it flattens; a second push sinks the reorder's leftover conjuncts


def push_join_side_conditions(rel: RelNode) -> RelNode:
    """Move single-side ON-clause conjuncts into the side they reference.

    For LEFT joins a build-side-only conjunct filters the build input before
    the join (identical semantics: it can only knock out matches, never probe
    rows); probe-side-only conjuncts must STAY in the ON clause (they void
    matches without dropping probe rows). INNER joins push both directions.
    """
    if rel.inputs:
        rel = rel.with_inputs([push_join_side_conditions(i)
                               for i in rel.inputs])
    if not (isinstance(rel, LogicalJoin)
            and rel.join_type in ("INNER", "LEFT", "RIGHT")
            and rel.condition is not None):
        return rel
    nl = len(rel.left.schema)
    left_ok = rel.join_type in ("INNER", "RIGHT")
    right_ok = rel.join_type in ("INNER", "LEFT")
    stay, to_left, to_right = [], [], []
    for cj in _split_conjuncts(rel.condition):
        refs = rex_inputs(cj)
        if not _is_pure(cj) or not refs:
            stay.append(cj)
        elif all(r < nl for r in refs) and left_ok:
            to_left.append(cj)
        elif all(r >= nl for r in refs) and right_ok:
            to_right.append(cj)
        else:
            stay.append(cj)
    if not to_left and not to_right:
        return rel
    new_left, new_right = rel.left, rel.right
    if to_left:
        new_left = LogicalFilter(input=rel.left,
                                 condition=_and_all(to_left),
                                 schema=rel.left.schema)
    if to_right:
        shifted = [remap_rex(cj, {i: i - nl for i in rex_inputs(cj)})
                   for cj in to_right]
        new_right = LogicalFilter(input=rel.right,
                                  condition=_and_all(shifted),
                                  schema=rel.right.schema)
    cond = _and_all(stay) if stay else None
    out = LogicalJoin(left=new_left, right=new_right,
                      join_type=rel.join_type, condition=cond,
                      schema=rel.schema)
    if hasattr(rel, "null_aware"):
        out.null_aware = rel.null_aware  # type: ignore[attr-defined]
    return out


def split_join_condition(rel: LogicalJoin):
    """Split a join condition into equi-key pairs + residual rex
    (reference: _split_join_condition join.py:245-284).  Shared by the
    physical executors AND the optimizer's exist-test rewrite — one
    decomposition, so heuristics and lowerings cannot drift."""
    nl = len(rel.left.schema)
    equi: List[tuple] = []
    residual: List = []

    def visit(rex):
        if isinstance(rex, RexCall) and rex.op == "AND":
            visit(rex.operands[0])
            visit(rex.operands[1])
            return
        if isinstance(rex, RexCall) and rex.op == "=" and len(rex.operands) == 2:
            a, b = rex.operands
            if isinstance(a, RexInputRef) and isinstance(b, RexInputRef):
                if a.index < nl <= b.index:
                    equi.append((a.index, b.index - nl))
                    return
                if b.index < nl <= a.index:
                    equi.append((b.index, a.index - nl))
                    return
        if isinstance(rex, RexLiteral) and rex.value is True:
            return
        residual.append(rex)

    if rel.condition is not None:
        visit(rel.condition)
    return equi, residual


def peel_root_epilogue(plan: RelNode):
    """Split ``plan`` into (core, epilogue): the epilogue is the root
    Project/Sort chain down to and including its DEEPEST Sort, returned in
    application order (deepest first); Projects below that Sort stay in the
    core.  No terminal Sort means no epilogue.

    The SPMD backend (parallel/spmd.py) executes the core sharded and
    applies the epilogue on the host over the compacted result — a global
    ORDER BY inside a shard_map program would be a full repartition for
    rows the host materializes anyway (the same reasoning as the compiled
    executor's off-TPU host_sort peel)."""
    chain: List[RelNode] = []
    node = plan
    while isinstance(node, (LogicalProject, LogicalSort)):
        chain.append(node)
        node = node.inputs[0]
    last_sort = None
    for i, nd in enumerate(chain):
        if isinstance(nd, LogicalSort):
            last_sort = i
    if last_sort is None:
        return plan, []
    peeled = chain[:last_sort + 1]
    return peeled[-1].inputs[0], list(reversed(peeled))


_EXIST_TEST_OPS = {"<>", "<", "<=", ">", ">="}
_EXIST_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "<>": "<>"}


def rewrite_exist_test_joins(rel: RelNode) -> RelNode:
    """SEMI/ANTI with equi keys plus ONE build-vs-probe comparison residual
    (TPC-H Q21's ``EXISTS(l2.orderkey = l1.orderkey AND l2.suppkey <>
    l1.suppkey)``) — the compiled executor's in-join exist-test payload
    formulation for this shape produces XLA:TPU programs so large the
    remote compile helper is OOM-killed.  Algebraic equivalent: group the
    build by the equi keys with COUNT(x)/MIN(x)/MAX(x), then
        exists b.x <> y  <=>  cnt >= 1 AND (mn <> y OR mx <> y)
        exists b.x <  y  <=>  cnt >= 1 AND mn < y       (etc. via min/max)
    so the SEMI becomes a plain INNER equi join + filter, and the ANTI a
    LEFT join + null-aware filter — both compile like ordinary joins.
    Floats are excluded (NaN comparison semantics the min/max reduction
    cannot reproduce), matching the exist-test path's own restriction."""
    new_inputs = [rewrite_exist_test_joins(i) for i in rel.inputs]
    if any(a is not b for a, b in zip(new_inputs, rel.inputs)):
        rel = rel.with_inputs(new_inputs)
    if not isinstance(rel, LogicalJoin) \
            or rel.join_type not in ("SEMI", "ANTI") \
            or getattr(rel, "null_aware", False) \
            or rel.condition is None:
        return rel
    equi, residual = split_join_condition(rel)
    if not equi or len(residual) != 1:
        return rel
    r = residual[0]
    nl = len(rel.left.schema)
    if not (isinstance(r, RexCall) and r.op in _EXIST_TEST_OPS
            and len(r.operands) == 2
            and all(isinstance(o, RexInputRef) for o in r.operands)):
        return rel
    a, b = r.operands
    if a.index < nl <= b.index:
        y_idx, x_idx, op = a.index, b.index - nl, _EXIST_FLIP[r.op]
    elif b.index < nl <= a.index:
        y_idx, x_idx, op = b.index, a.index - nl, r.op
    else:
        return rel
    from ..types import BIGINT

    right = rel.right
    x_f = right.schema[x_idx]
    y_f = rel.left.schema[y_idx]
    if x_f.stype.is_floating or y_f.stype.is_floating:
        return rel
    gks = []
    for _, bi in equi:
        if bi not in gks:
            gks.append(bi)
    key_fields = [Field(right.schema[bi].name, right.schema[bi].stype)
                  for bi in gks]
    agg = LogicalAggregate(
        input=right, group_keys=list(gks),
        aggs=[AggCall("COUNT", [x_idx], False, BIGINT, "cnt$"),
              AggCall("MIN", [x_idx], False, x_f.stype, "mn$"),
              AggCall("MAX", [x_idx], False, x_f.stype, "mx$")],
        schema=key_fields + [Field("cnt$", BIGINT),
                             Field("mn$", x_f.stype),
                             Field("mx$", x_f.stype)])
    pos_of = {bi: i for i, bi in enumerate(gks)}
    cond = None
    for pi, bi in equi:
        eq = RexCall("=", [RexInputRef(pi, rel.left.schema[pi].stype),
                           RexInputRef(nl + pos_of[bi],
                                       right.schema[bi].stype)], BOOLEAN)
        cond = eq if cond is None else RexCall("AND", [cond, eq], BOOLEAN)
    nk = len(gks)
    joined = LogicalJoin(
        left=rel.left, right=agg,
        join_type="INNER" if rel.join_type == "SEMI" else "LEFT",
        condition=cond, schema=list(rel.left.schema) + list(agg.schema))
    y = RexInputRef(y_idx, y_f.stype)
    cnt = RexInputRef(nl + nk, BIGINT)
    mn = RexInputRef(nl + nk + 1, x_f.stype)
    mx = RexInputRef(nl + nk + 2, x_f.stype)
    if op == "<>":
        pred = RexCall("OR", [RexCall("<>", [mn, y], BOOLEAN),
                              RexCall("<>", [mx, y], BOOLEAN)], BOOLEAN)
    elif op in ("<", "<="):
        pred = RexCall(op, [mn, y], BOOLEAN)
    else:
        pred = RexCall(op, [mx, y], BOOLEAN)
    cnt_pos = RexCall(">=", [RexCall("COALESCE",
                                     [cnt, RexLiteral(0, BIGINT)], BIGINT),
                             RexLiteral(1, BIGINT)], BOOLEAN)
    exists_pred = RexCall("AND", [cnt_pos, pred], BOOLEAN)
    if rel.join_type == "SEMI":
        keep: RexNode = exists_pred
    else:
        # NOT EXISTS keeps the row when the group is absent, when the
        # probe value is NULL (no comparison can succeed), or when no
        # build value satisfies the comparison — 3VL-safe by construction
        keep = RexCall("OR", [
            RexCall("IS_NULL", [y], BOOLEAN),
            RexCall("NOT", [exists_pred], BOOLEAN)], BOOLEAN)
    filt = LogicalFilter(input=joined, condition=keep,
                         schema=list(joined.schema))
    return LogicalProject(
        input=filt,
        exprs=[RexInputRef(i, f.stype)
               for i, f in enumerate(rel.left.schema)],
        schema=list(rel.schema))


_AGG_THROUGH_JOIN_OPS = {"COUNT", "SUM", "$SUM0", "MIN", "MAX"}


def aggregate_through_join(rel: RelNode) -> RelNode:
    """Pre-aggregate a join's right side when the aggregate only groups by
    left-side columns and only aggregates right-side columns.

    Turns the 1:N expansion of e.g. TPC-H Q13 (customer LEFT JOIN orders,
    COUNT per customer) into a groupby on the N side + an N:1 join — which
    the compiled executor's unique-build join handles, and which is
    strictly less work everywhere (the join output never materializes the
    multiplicity). Calcite ships the same family as
    AggregateJoinTransposeRule; the reference's rule list only has the
    REMOVE variant (RelationalAlgebraGenerator.java:206).
    """
    if rel.inputs:
        rel = rel.with_inputs([aggregate_through_join(i) for i in rel.inputs])
    if not isinstance(rel, LogicalAggregate):
        return rel
    join = rel.input
    # look through a bare-ref projection (the binder's pre-projection)
    remap: Optional[List[int]] = None
    if (isinstance(join, LogicalProject)
            and all(isinstance(e, RexInputRef) for e in join.exprs)):
        remap = [e.index for e in join.exprs]
        join = join.input
    if not (isinstance(join, LogicalJoin)
            and join.join_type in ("INNER", "LEFT")
            and join.condition is not None):
        return rel

    def m(i: int) -> int:
        return remap[i] if remap is not None else i

    group_keys = [m(g) for g in rel.group_keys]
    agg_args = [[m(a) for a in agg.args] for agg in rel.aggs]
    nl = len(join.left.schema)
    # equi keys must be bare column refs (they become the pre-agg group keys)
    lkeys: List[int] = []
    rkeys: List[int] = []
    for cj in _split_conjuncts(join.condition):
        if not (isinstance(cj, RexCall) and cj.op == "="
                and len(cj.operands) == 2
                and all(isinstance(o, RexInputRef) for o in cj.operands)):
            return rel
        a, b = cj.operands[0].index, cj.operands[1].index
        if a < nl <= b:
            lkeys.append(a); rkeys.append(b - nl)
        elif b < nl <= a:
            lkeys.append(b); rkeys.append(a - nl)
        else:
            return rel
    if not lkeys:
        return rel
    if not all(g < nl for g in group_keys):
        return rel
    for agg, args in zip(rel.aggs, agg_args):
        if (agg.op not in _AGG_THROUGH_JOIN_OPS or agg.distinct
                or agg.udaf is not None or agg.filter_arg is not None
                or not args or any(a < nl for a in args)):
            return rel

    # right pre-aggregate: group by the right join keys
    pre_fields = [Field(f"$jk{i}", join.right.schema[k].stype)
                  for i, k in enumerate(rkeys)]
    pre_aggs: List[AggCall] = []
    for i, (agg, args) in enumerate(zip(rel.aggs, agg_args)):
        pre_aggs.append(AggCall(op=agg.op, args=[a - nl for a in args],
                                distinct=False, stype=agg.stype,
                                name=f"$pa{i}", filter_arg=None, udaf=None))
        pre_fields.append(Field(f"$pa{i}", agg.stype))
    pre = LogicalAggregate(input=join.right, group_keys=list(rkeys),
                           aggs=pre_aggs, schema=pre_fields)

    # rejoin: left columns keep their ordinals; right side is now the
    # pre-aggregate (keys first, then one column per aggregate)
    cond = None
    for i, lk in enumerate(lkeys):
        eq = RexCall("=", [RexInputRef(lk, join.left.schema[lk].stype),
                           RexInputRef(nl + i, pre_fields[i].stype)],
                     BOOLEAN)
        cond = eq if cond is None else RexCall("AND", [cond, eq], BOOLEAN)
    j_schema = list(join.left.schema) + pre_fields
    j2 = LogicalJoin(left=join.left, right=pre, join_type=join.join_type,
                     condition=cond, schema=j_schema)

    # outer combine: COUNT -> $SUM0 of the (0-coalesced) partial counts,
    # SUM/MIN/MAX recombine with themselves over the partials
    out_aggs: List[AggCall] = []
    for i, agg in enumerate(rel.aggs):
        slot = nl + len(rkeys) + i
        outer_op = "$SUM0" if agg.op == "COUNT" else agg.op
        out_aggs.append(AggCall(op=outer_op, args=[slot], distinct=False,
                                stype=agg.stype, name=agg.name,
                                filter_arg=None, udaf=None))
    agg2 = LogicalAggregate(input=j2, group_keys=list(group_keys),
                            aggs=out_aggs, schema=rel.schema)
    return agg2


PASSES = [merge_filters, factor_or_predicates, push_filters, merge_filters,
          reorder_joins, push_filters, merge_filters,
          push_join_side_conditions, push_filters, merge_filters,
          rewrite_exist_test_joins,
          aggregate_through_join, merge_projects]


def optimize_subplans(rel: RelNode) -> RelNode:
    """Recursively optimize plans embedded in scalar-subquery expressions —
    the tree passes only walk ``rel.inputs``, so a HAVING/WHERE subquery's
    own join chain would otherwise reach the executor unoptimized (TPC-H
    Q11: a 3-table comma list inside HAVING stays a cross product)."""

    def walk_rex(r: RexNode) -> None:
        if isinstance(r, RexScalarSubquery):
            r.plan = optimize(r.plan)
        elif isinstance(r, RexCall):
            for o in r.operands:
                walk_rex(o)

    if rel.inputs:
        rel = rel.with_inputs([optimize_subplans(i) for i in rel.inputs])
    if isinstance(rel, LogicalProject):
        for e in rel.exprs:
            walk_rex(e)
    elif isinstance(rel, LogicalFilter):
        walk_rex(rel.condition)
    elif isinstance(rel, LogicalJoin) and rel.condition is not None:
        walk_rex(rel.condition)
    return rel


def optimize(plan: RelNode, enable_pruning: bool = True,
             context=None) -> RelNode:
    """Rule pipeline; prefers the native (C++) optimizer when available.

    native/optimizer.cpp is a lockstep port of every pass in this module
    (the reference's planner runs its HepPlanner natively too,
    RelationalAlgebraGenerator.java:97-224); this Python pipeline is the
    fallback for plans carrying Python-only payloads (UDFs, custom
    aggregations, PREDICT nodes) and the semantics reference the native
    port is tested against (tests/unit/test_native_optimizer.py)."""
    # the DSQL_NATIVE=0 opt-out lives in native.load() — one gate, not two
    from .native_planner import optimize_native
    native = optimize_native(plan, enable_pruning)
    if native is not None:
        # stats reorder runs as a POST-pass so the native early-return
        # cannot skip it — both pipelines emit the INNER/CROSS chains it
        # rewrites, and it no-ops without a context or with DSQL_ADAPTIVE=0
        return reorder_joins_stats(native, context)
    for p in PASSES:
        plan = p(plan)
    plan = optimize_subplans(plan)
    if enable_pruning:
        plan = prune_columns(plan)
        plan = merge_projects(plan)
    return reorder_joins_stats(plan, context)
