"""Cross-cutting utilities: plugin machinery and SQL error pretty-printing.

TPU-native re-implementation of the reference's utils
(/root/reference/dask_sql/utils.py): ``Pluggable`` (utils.py:54-81) is the
single extension mechanism shared by the REL converter, REX converter and
input plugins; ``ParsingException`` (utils.py:84-174) renders a caret marker
under the offending SQL fragment.
"""
from __future__ import annotations

import uuid
from typing import Any, Dict


class Pluggable:
    """Base class providing a per-subclass plugin registry.

    Mirrors the semantics of the reference's Pluggable (utils.py:54-81): each
    direct subclass gets its own registry dict keyed by plugin name; plugins
    are singletons; ``replace=False`` keeps the first registration.
    """

    __plugins: Dict[type, Dict[str, Any]] = {}

    @classmethod
    def add_plugin(cls, name: str, plugin: Any, replace: bool = True) -> None:
        registry = Pluggable.__plugins.setdefault(cls, {})
        if name in registry and not replace:
            return
        registry[name] = plugin

    @classmethod
    def get_plugin(cls, name: str) -> Any:
        return Pluggable.__plugins.setdefault(cls, {})[name]

    @classmethod
    def get_plugins(cls) -> list:
        return list(Pluggable.__plugins.setdefault(cls, {}).values())

    @classmethod
    def has_plugin(cls, name: str) -> bool:
        return name in Pluggable.__plugins.setdefault(cls, {})


class ParsingException(Exception):
    """Parse/validation error with a ``^``-marked SQL excerpt.

    Reference behavior: utils.py:84-174 turns Calcite's "From line X, column Y
    to line X2, column Y2" messages into a caret-underlined SQL snippet.  Our
    native parser reports (line, col, length) directly.
    """

    def __init__(self, sql: str, message: str, line: int = None, col: int = None,
                 length: int = 1):
        self.sql = sql
        self.raw_message = message
        # 1-based position, consumed by the Presto server's errorLocation
        # (the reference exposes from_line/from_col the same way)
        self.line = line
        self.col = col
        if line is not None and sql:
            lines = sql.splitlines()
            if 0 < line <= len(lines):
                bad = lines[line - 1]
                marker = " " * (col - 1) + "^" * max(1, min(length, len(bad) - col + 1))
                message = (
                    f"{message}\n\n"
                    f"\tline {line}, column {col}\n\n"
                    f"\t{bad}\n"
                    f"\t{marker}"
                )
        super().__init__(message)


class ValidationException(ParsingException):
    """Binder/validator error (unknown column, type mismatch...)."""


class OptimizationException(Exception):
    pass


def new_temporary_column(existing) -> str:
    """A column name guaranteed unique (reference: utils.py:248-256)."""
    while True:
        name = f"__tmp_{uuid.uuid4().hex[:12]}"
        if name not in existing:
            return name


def convert_sql_kwargs(kwargs) -> dict:
    """Normalize a parsed kwargs dict (values are python literals already).

    The reference converts a Java SqlKwargs HashMap (utils.py:198-235); our
    native parser produces python values directly, including nested dicts
    (MAP/MULTISET) and lists (ARRAY), so this just passes through while
    lower-casing string 'True'/'False' style values is NOT done — parser
    already typed them.
    """
    return dict(kwargs)
