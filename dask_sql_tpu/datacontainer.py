"""Catalog containers: schemas, tables, functions, models.

Mirrors the reference's datacontainer.py (SchemaContainer,
/root/reference/dask_sql/datacontainer.py:184-191, FunctionDescription :9) —
but tables are device-columnar ``Table`` objects (see table.py for why no
frontend/backend column mapping is needed here).
"""
from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .table import Table
from .types import SqlType


@dataclass
class FunctionDescription:
    name: str
    parameters: List[Tuple[str, SqlType]]
    return_type: SqlType
    aggregation: bool
    func: Callable = None
    row_udf: bool = False


@dataclass
class TableEntry:
    """A registered table: materialized device table or a lazy view plan."""
    table: Optional[Table] = None
    plan: Any = None               # bound RelNode for CREATE VIEW ... AS
    statistics: Optional[dict] = None
    # ingest-time TableStats (runtime/statistics.py): row count, per-column
    # NDV/min-max/null-fraction/dense-int detection — drives adaptive
    # operator dispatch, join ordering, and the scheduler's working-set
    # estimate.  Separate from ``statistics`` (the user-supplied dict kept
    # for reference parity).
    stats: Any = None
    filepath: Optional[str] = None
    gpu: bool = False              # parity flag only
    # mesh mode: columns are padded to device-count divisibility and
    # row-sharded; row_valid (same sharding) marks the real rows
    row_valid: Any = None
    # out-of-HBM mode: host-resident ChunkedSource (io/chunked.py);
    # ``table`` is then a 1-row binding stub, and execution must go through
    # physical/streaming.py (context routes it)
    chunked: Any = None


class SchemaContainer:
    def __init__(self, name: str):
        self.name = name
        self.tables: Dict[str, TableEntry] = {}
        self.models: Dict[str, Tuple[Any, List[str]]] = {}
        self.experiments: Dict[str, Table] = {}
        self.functions: Dict[str, FunctionDescription] = {}
        self.function_lists: List[FunctionDescription] = []

    def add_table(self, name: str, entry: TableEntry):
        self.tables[name] = entry
