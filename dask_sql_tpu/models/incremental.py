"""Out-of-core (incremental) training and batched prediction.

Reference parity: ``CREATE MODEL (wrap_fit = True, ...)`` wraps the estimator
in dask-ml ``Incremental`` so training streams partition-by-partition via
``partial_fit`` (/root/reference/dask_sql/physical/rel/custom/
create_model.py:141-155); ``wrap_predict`` wraps it in ``ParallelPostFit``
for partitioned prediction (:147-155).

The TPU-first analogue: the training SELECT's row-local plan (projections /
filters / resident-side joins above ONE chunked scan) executes per host
batch through the same compile-once streaming machinery queries use
(physical/streaming.py — every batch is padded to identical shapes, so one
XLA program serves all batches), and each batch's host frame feeds
``partial_fit``.  No more than one batch is device- or host-materialized at
a time.
"""
from __future__ import annotations

import logging
from typing import Iterator, List

import numpy as np

logger = logging.getLogger(__name__)


def iter_query_batches(context, plan) -> Iterator:
    """Yield the query result as per-batch ``Table``s (result row-stream).

    Requires the plan to be a row-stream over exactly one chunked scan: no
    blocking operator (aggregate / sort / window) on the scan's path, so the
    concatenation of per-batch results IS the query result.  Off-path
    subtrees (e.g. resident join sides) are materialized once by the
    streaming rewriter.
    """
    from ..physical import streaming as S
    from ..plan.nodes import (LogicalAggregate, LogicalExcept,
                              LogicalIntersect, LogicalSort, LogicalUnion,
                              LogicalWindow)

    scans = S._chunked_scans(plan, context)
    if len(scans) != 1:
        raise S.StreamingUnsupported(
            f"incremental training needs exactly one chunked table in the "
            f"training query (found {len(scans)})")
    scan = scans[0]
    path = S._path_to(plan, scan)
    if path is None:
        raise S.StreamingUnsupported(
            "chunked table referenced inside a scalar subquery cannot "
            "stream training batches")
    for node in path[:-1]:
        # blocking operators make the result not-a-row-stream; set
        # operators would replay their resident branch into EVERY batch
        # (and dedup semantics don't distribute over batches)
        if isinstance(node, (LogicalAggregate, LogicalSort, LogicalWindow,
                             LogicalUnion, LogicalIntersect, LogicalExcept)):
            raise S.StreamingUnsupported(
                f"{type(node).__name__} above the chunked scan makes the "
                "training query a blocking computation, not a row-stream; "
                "materialize it into a resident table first or drop "
                "wrap_fit")
    entry = context.schema[scan.schema_name].tables[scan.table_name]
    source = entry.chunked
    names = [f.name for f in plan.schema]
    try:
        # inside the try: the rewriter materializes off-path resident
        # subtrees into __stream__ temps as it goes, and a failure partway
        # (e.g. a disallowed join shape deeper in) must not leak them
        partial = S._stream_partial_plans(plan, scan, path, context)
        for bi in range(source.n_batches):
            table, row_valid = source.batch_table(bi)
            S._set_batch_entry(context, table, row_valid)
            result = S._run_resident(partial, context)
            yield result.with_names(names)
    finally:
        S._cleanup(context)


def incremental_fit(model, context, plan, target_column: str,
                    fit_kwargs: dict) -> List[str]:
    """Stream the training query batch-by-batch through ``partial_fit``.

    Returns the feature column names.  Classifiers need the full label set
    on the FIRST ``partial_fit`` call; when the caller did not provide
    ``classes`` in fit_kwargs, a cheap label-only prescan collects it
    (mirrors dask-ml's requirement that Incremental classifiers get
    ``classes`` up front).
    """
    fit_kwargs = dict(fit_kwargs)
    try:
        from sklearn.base import is_classifier as _is_clf
        clf = _is_clf(model)
    except ImportError:
        # non-sklearn estimators: the legacy marker is the only signal
        clf = getattr(model, "_estimator_type", "") == "classifier"
    if clf and target_column and "classes" not in fit_kwargs:
        # prescan a LABEL-ONLY projection of the plan, re-optimized so
        # column pruning actually strips the unused feature columns and
        # subtrees — otherwise the full training query's device compute
        # would run twice
        from ..plan.nodes import Field, LogicalProject, RexInputRef
        from ..plan.optimizer import optimize
        tgt = next((i for i, f in enumerate(plan.schema)
                    if f.name == target_column), None)
        if tgt is None:
            raise KeyError(
                f"target_column {target_column!r} is not a column of the "
                f"training query (have: "
                f"{[f.name for f in plan.schema]})")
        label_plan = optimize(LogicalProject(
            input=plan, exprs=[RexInputRef(tgt, plan.schema[tgt].stype)],
            schema=[Field(target_column, plan.schema[tgt].stype)]))
        seen = set()
        for t in iter_query_batches(context, label_plan):
            col = t.column(target_column)
            seen.update(np.unique(col.to_numpy()).tolist())
        fit_kwargs["classes"] = np.sort(np.asarray(sorted(seen)))
        logger.info("incremental fit: prescanned %d classes",
                    len(fit_kwargs["classes"]))

    from .training import _all_numeric
    feature_names: List[str] = []
    n_batches = 0
    for t in iter_query_batches(context, plan):
        df = t.to_pandas()
        if target_column:
            y = df[target_column].to_numpy()
            X = df.drop(columns=[target_column])
        else:
            y = None
            X = df
        feature_names = X.columns.tolist()
        Xn = (X.to_numpy(dtype=np.float64, na_value=np.nan)
              if _all_numeric(X) else X)
        if y is not None:
            model.partial_fit(Xn, y, **fit_kwargs)
        else:
            model.partial_fit(Xn, **fit_kwargs)
        # classes only feeds the first call on sklearn classifiers, but
        # passing it again is accepted; transformers (no y) take none
        n_batches += 1
    if n_batches == 0:
        # match the gathered path, where sklearn's fit raises on empty
        # input at CREATE MODEL time — never register an unfit estimator
        raise ValueError(
            "incremental training source produced no batches (empty "
            "chunked table?); refusing to register an unfit model")
    logger.info("incremental fit: %d partial_fit batches", n_batches)
    return feature_names


class BatchedPredictor:
    """``wrap_predict`` analogue of dask-ml ParallelPostFit (reference
    create_model.py:147-155): prediction runs in bounded host batches so a
    table-sized feature matrix is never scored in one call.  Delegates
    everything else to the wrapped estimator; picklable for EXPORT MODEL."""

    #: rows per predict slice — bounds peak memory of model.predict
    batch_rows = 1 << 20

    def __init__(self, model, batch_rows: int = None):
        self.model = model
        if batch_rows is not None:
            self.batch_rows = int(batch_rows)

    def _batched(self, method: str, X):
        fn = getattr(self.model, method)
        n = len(X)
        if n <= self.batch_rows:
            return fn(X)
        parts = []
        for s in range(0, n, self.batch_rows):
            part = X[s:s + self.batch_rows] if not hasattr(X, "iloc") \
                else X.iloc[s:s + self.batch_rows]
            parts.append(np.asarray(fn(part)))
        return np.concatenate(parts)

    # every scoring surface ParallelPostFit wraps is batched, not just
    # predict — the memory bound must hold for probabilities too
    def predict(self, X):
        return self._batched("predict", X)

    def predict_proba(self, X):
        return self._batched("predict_proba", X)

    def predict_log_proba(self, X):
        return self._batched("predict_log_proba", X)

    def decision_function(self, X):
        return self._batched("decision_function", X)

    def transform(self, X):
        return self._batched("transform", X)

    def __getattr__(self, name):
        # delegation target; __getattr__ only fires for attributes not on
        # the wrapper itself, so the batched methods above stay ours
        return getattr(self.model, name)

    def __getstate__(self):
        return {"model": self.model, "batch_rows": self.batch_rows}

    def __setstate__(self, state):
        self.model = state["model"]
        self.batch_rows = state["batch_rows"]
