"""Small bridge: run a bound query AST through plan+execute (used by ML
statements, which hold the inner SELECT as AST instead of re-stringifying it
the way the reference must, create_model.py:157-158)."""
from __future__ import annotations

from ..table import Table


def run_query(context, query_ast, sql: str) -> Table:
    plan = context._get_plan(query_ast, sql)
    # the full execution route, NOT a direct RelExecutor call: a chunked
    # (out-of-HBM) source must go through the streaming executor — the eager
    # executor would silently compute on its 1-row binding stub — and
    # resident plans get the whole-plan compiled path
    return context._execute_query_plan(plan)
