"""SQL-driven ML: CREATE MODEL / CREATE EXPERIMENT / EXPORT MODEL.

Re-implements the reference's ML statements
(/root/reference/dask_sql/physical/rel/custom/create_model.py:11-171,
create_experiment.py:14-224, export_model.py:10-89): train an sklearn-style
estimator on the result of a SELECT, run hyperparameter search, serialize
models.  Training data is gathered from device to host numpy — model fitting
is a host-side affair in the reference too (dask-ml collects partitions).
"""
from __future__ import annotations

import importlib
import logging
import pickle
from typing import Any, Optional

import numpy as np

from ..sql import ast as A
from ..table import Table

logger = logging.getLogger(__name__)


def import_class(name: str) -> type:
    """Dynamic import of 'package.module.Class' (reference utils.py:238-245)."""
    module_path, _, class_name = name.rpartition(".")
    module = importlib.import_module(module_path)
    return getattr(module, class_name)


def _gather_xy(table: Table, target_column: Optional[str]):
    df = table.to_pandas()
    if target_column:
        y = df[target_column].to_numpy()
        X = df.drop(columns=[target_column])
    else:
        y = None
        X = df
    return X, y


def create_model(stmt: A.CreateModel, context, sql: str):
    schema_name, name = context.fqn(stmt.name)
    if name in context.schema[schema_name].models:
        if stmt.if_not_exists:
            return None
        if not stmt.or_replace:
            raise RuntimeError(f"A model with the name {name} is already present.")

    kwargs = dict(stmt.kwargs)
    try:
        model_class = kwargs.pop("model_class")
    except KeyError:
        raise AttributeError("Parameters must include a 'model_class' parameter.")
    target_column = kwargs.pop("target_column", "")
    wrap_predict = bool(kwargs.pop("wrap_predict", False))
    wrap_fit = bool(kwargs.pop("wrap_fit", False))
    fit_kwargs = kwargs.pop("fit_kwargs", {})

    ModelClass = import_class(model_class)
    model = ModelClass(**kwargs)

    # wrap_fit over an out-of-HBM source: stream partial_fit batch-by-batch
    # (reference wraps in dask-ml Incremental, create_model.py:141-155).
    # Over a resident table the whole training set already fits on device,
    # so plain fit IS the single-partition Incremental semantics.
    plan = context._get_plan(stmt.query, sql)
    from ..physical.streaming import plan_references_chunked
    if wrap_fit and plan_references_chunked(plan, context):
        if not hasattr(model, "partial_fit"):
            raise AttributeError(
                f"wrap_fit=True over a chunked table needs an estimator "
                f"with partial_fit; {model_class} has none")
        from .incremental import incremental_fit
        feature_names = incremental_fit(model, context, plan,
                                        target_column, fit_kwargs)
        if wrap_predict:
            from .incremental import BatchedPredictor
            model = BatchedPredictor(model)
        context.register_model(name, model, feature_names,
                               schema_name=schema_name)
        return None

    training_table = context._execute_query_plan(plan)
    X, y = _gather_xy(training_table, target_column)
    if y is not None:
        model.fit(X.to_numpy(dtype=np.float64, na_value=np.nan)
                  if _all_numeric(X) else X, y, **fit_kwargs)
    else:
        model.fit(X.to_numpy(dtype=np.float64, na_value=np.nan)
                  if _all_numeric(X) else X, **fit_kwargs)
    if wrap_predict:
        from .incremental import BatchedPredictor
        model = BatchedPredictor(model)
    context.register_model(name, model, X.columns.tolist(), schema_name=schema_name)
    return None


def _all_numeric(df) -> bool:
    return all(k.kind in "ifb" for k in df.dtypes)


def create_experiment(stmt: A.CreateExperiment, context, sql: str):
    schema_name, name = context.fqn(stmt.name)
    if name in context.schema[schema_name].models and not (stmt.if_not_exists or stmt.or_replace):
        raise RuntimeError(f"An experiment with the name {name} is already present.")
    if name in context.schema[schema_name].models and stmt.if_not_exists:
        return None

    kwargs = dict(stmt.kwargs)
    model_class = kwargs.pop("model_class", None)
    experiment_class = kwargs.pop("experiment_class", None)
    automl_class = kwargs.pop("automl_class", None)
    target_column = kwargs.pop("target_column", "")
    tune_params = kwargs.pop("tune_parameters", {})
    experiment_kwargs = kwargs.pop("experiment_kwargs", {})
    automl_kwargs = kwargs.pop("automl_kwargs", {})

    from .executor_bridge import run_query
    training_table = run_query(context, stmt.query, sql)
    X, y = _gather_xy(training_table, target_column)
    Xn = X.to_numpy(dtype=np.float64, na_value=np.nan) if _all_numeric(X) else X

    if automl_class:
        AutoML = import_class(automl_class)
        automl = AutoML(**automl_kwargs)
        automl.fit(Xn, y)
        best = getattr(automl, "fitted_pipeline_", automl)
        context.register_model(name, best, X.columns.tolist(), schema_name=schema_name)
        return None

    if not model_class:
        raise AttributeError("Parameters must include a 'model_class' or 'automl_class'.")
    if not experiment_class:
        raise AttributeError(
            f"Parameters must include a 'experiment_class' parameter for tuning {model_class}.")
    ModelClass = import_class(model_class)
    ExperimentClass = import_class(experiment_class)
    model = ModelClass(**kwargs)
    search = ExperimentClass(model, dict(tune_params), **experiment_kwargs)
    search.fit(Xn, y)

    import pandas as pd
    results = pd.DataFrame(search.cv_results_)
    # stringify param objects for device storage
    for c in results.columns:
        if results[c].dtype == object:
            results[c] = results[c].map(str)
    experiment_table = Table.from_pandas(results)
    context.schema[schema_name].experiments[name] = experiment_table
    context.register_model(name, search.best_estimator_, X.columns.tolist(),
                           schema_name=schema_name)
    return experiment_table


def export_model(stmt: A.ExportModel, context, sql: str):
    info = context.resolve_model(stmt.name)
    if info is None:
        raise RuntimeError(f"A model with the name {'.'.join(stmt.name)} is not present.")
    model, training_columns = info
    kwargs = dict(stmt.kwargs)
    fmt = str(kwargs.pop("format", "pickle")).lower()
    try:
        location = kwargs.pop("location")
    except KeyError:
        raise AttributeError("Parameters must include a 'location' parameter.")

    if fmt in ("pickle", "pkl"):
        with open(location, "wb") as f:
            pickle.dump(model, f, **kwargs)
    elif fmt == "joblib":
        import joblib
        joblib.dump(model, location, **kwargs)
    elif fmt == "mlflow":
        try:
            import mlflow
        except ImportError:
            raise NotImplementedError("mlflow is not installed in this environment")
        mlflow.sklearn.save_model(model, location, **kwargs)
    elif fmt == "onnx":
        raise NotImplementedError("ONNX export is not implemented (parity with reference)")
    else:
        raise NotImplementedError(f"Unknown format {fmt}")
    return None
